"""The bench-trajectory regression gate (tools/bench_compare.py): headline
key comparison semantics, the allowlist (pinned and unpinned), truncated-
tail salvage, and the acceptance pin — r04 -> r05 on the checked-in files
reproduces the known deltas and passes."""

import json
import sys
from pathlib import Path

import pytest

REPO_ROOT = Path(__file__).resolve().parent.parent
if str(REPO_ROOT) not in sys.path:
    sys.path.insert(0, str(REPO_ROOT))

from tools.bench_compare import (  # noqa: E402
    compare,
    extract_record,
    find_bench_files,
    main,
    run,
)


def _bench(tmp_path, name, record):
    p = tmp_path / name
    p.write_text(json.dumps({"parsed": record}))
    return p


class TestCompare:
    def test_direction_aware_verdicts(self):
        rows = compare(
            {"pipelined_pods_per_sec": 100.0, "device_p99_s": 0.1},
            {"pipelined_pods_per_sec": 80.0, "device_p99_s": 0.2},
        )
        by_key = {r["key"]: r for r in rows}
        # throughput down 20% = regressed; p99 UP 100% = regressed too
        assert by_key["pipelined_pods_per_sec"]["verdict"] == "regressed"
        assert by_key["device_p99_s"]["verdict"] == "regressed"

    def test_improvement_and_tolerance(self):
        rows = compare(
            {"pipelined_pods_per_sec": 100.0, "device_p99_s": 0.2},
            {"pipelined_pods_per_sec": 109.0, "device_p99_s": 0.1},
        )
        by_key = {r["key"]: r for r in rows}
        assert by_key["pipelined_pods_per_sec"]["verdict"] == "ok"  # +9% < 10%
        assert by_key["device_p99_s"]["verdict"] == "improved"  # halved

    def test_missing_keys_reported_not_failed(self, tmp_path):
        old = _bench(tmp_path, "BENCH_r01.json", {"pipelined_pods_per_sec": 10.0})
        new = _bench(tmp_path, "BENCH_r02.json", {"device_p99_s": 0.1})
        report = run(old, new)
        assert report["failed"] == []  # budgeted legs drop keys legitimately
        verdicts = {r["key"]: r["verdict"] for r in report["rows"]}
        assert verdicts["pipelined_pods_per_sec"] == "missing_new"
        assert verdicts["device_p99_s"] == "missing_old"


class TestGate:
    def test_regression_fails_and_allowlist_excuses(self, tmp_path):
        old = _bench(tmp_path, "BENCH_r01.json", {"pipelined_pods_per_sec": 100.0})
        new = _bench(tmp_path, "BENCH_r02.json", {"pipelined_pods_per_sec": 50.0})
        report = run(old, new)
        assert report["failed"] == ["pipelined_pods_per_sec"]

        allow = tmp_path / "allow.json"
        allow.write_text(json.dumps([
            {"key": "pipelined_pods_per_sec", "reason": "traded for p99"},
        ]))
        excused = run(old, new, allowlist_path=allow)
        assert excused["failed"] == []
        row = next(r for r in excused["rows"]
                   if r["key"] == "pipelined_pods_per_sec")
        assert row["verdict"] == "allowlisted" and row["reason"]

    def test_pinned_waiver_dies_with_its_run(self, tmp_path):
        old = _bench(tmp_path, "BENCH_r01.json", {"pipelined_pods_per_sec": 100.0})
        new = _bench(tmp_path, "BENCH_r02.json", {"pipelined_pods_per_sec": 50.0})
        allow = tmp_path / "allow.json"
        allow.write_text(json.dumps([
            {"key": "pipelined_pods_per_sec", "reason": "r01 only",
             "new": "BENCH_r01.json"},  # pinned to a DIFFERENT run
        ]))
        assert run(old, new, allowlist_path=allow)["failed"] == [
            "pipelined_pods_per_sec"
        ]

    def test_cli_exit_codes(self, tmp_path):
        old = _bench(tmp_path, "BENCH_r01.json", {"pipelined_pods_per_sec": 100.0})
        new = _bench(tmp_path, "BENCH_r02.json", {"pipelined_pods_per_sec": 50.0})
        args = [str(old), str(new), "--allowlist", ""]
        assert main(args) == 1
        assert main(args + ["--report"]) == 0  # the make-benchmark mode
        assert main(["--dir", str(tmp_path / "empty")]) == 2

    def test_newest_two_selected_by_round_number(self, tmp_path):
        for i in (3, 1, 10, 2):
            _bench(tmp_path, f"BENCH_r{i:02d}.json", {"value": float(i)})
        files = find_bench_files(tmp_path)
        assert [f.name for f in files[-2:]] == [
            "BENCH_r03.json", "BENCH_r10.json",
        ]


class TestTailSalvage:
    def test_front_truncated_tail_recovers_suffix(self, tmp_path):
        # the harness stored only the tail of a long record line: the head
        # (and the opening brace) are gone, possibly mid-nested-object
        full = {"noise": {"a": 1}, "pipelined_pods_per_sec": 240612.8,
                "device_p99_s": 0.1605}
        line = json.dumps(full)
        p = tmp_path / "BENCH_r09.json"
        p.write_text(json.dumps({"tail": line[len('{"noise": {"a"'):]}))
        record, truncated = extract_record(p)
        assert truncated is True
        assert record["pipelined_pods_per_sec"] == 240612.8
        assert record["device_p99_s"] == 0.1605


@pytest.mark.skipif(
    len(find_bench_files(REPO_ROOT)) < 2,
    reason="checked-in bench trajectory not present",
)
class TestCheckedInTrajectory:
    def test_r04_to_r05_reproduces_known_deltas_and_passes(self):
        """The acceptance pin: the r05 round DOUBLED pipelined throughput
        (the first TPU>CPU round); the gate must see that as improvement,
        fail nothing, and salvage r05's truncated record line."""
        report = run(
            REPO_ROOT / "BENCH_r04.json",
            REPO_ROOT / "BENCH_r05.json",
            allowlist_path=REPO_ROOT / "tools" / "bench_allowlist.json",
        )
        assert report["failed"] == []
        row = next(r for r in report["rows"]
                   if r["key"] == "pipelined_pods_per_sec")
        assert row["verdict"] == "improved"
        assert row["delta_pct"] == pytest.approx(104.7, abs=0.5)

    def test_make_bench_compare_equivalent_passes(self):
        # exactly what CI runs: newest two checked-in rounds, default gate
        assert main(["--dir", str(REPO_ROOT),
                     "--allowlist",
                     str(REPO_ROOT / "tools" / "bench_allowlist.json")]) == 0
