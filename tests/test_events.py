"""Operator-visible Events (kube/events.py): launch/terminate/consolidate
actions are recorded as core/v1 Events with client-go-style aggregation —
additive capability (the reference snapshot emits none, SURVEY §5.5)."""

import time

from karpenter_tpu.api import labels as lbl
from karpenter_tpu.cloudprovider.fake import FakeCloudProvider, instance_types
from karpenter_tpu.cloudprovider.requirements import catalog_requirements
from karpenter_tpu.kube.client import Cluster
from karpenter_tpu.kube.events import EventRecorder, recorder_for
from tests.factories import make_node, make_pod, make_provisioner


class TestRecorder:
    def test_repeat_aggregates_into_count(self):
        now = [100.0]
        cluster = Cluster(clock=lambda: now[0])
        rec = EventRecorder(cluster)
        e1 = rec.event("Node", "n1", "Launched", "msg")
        now[0] += 5
        e2 = rec.event("Node", "n1", "Launched", "msg")
        assert e2 is e1 and e1.count == 2
        assert len(cluster.list("events", None)) == 1
        # a different message is a fresh event
        rec.event("Node", "n1", "Launched", "other")
        assert len(cluster.list("events", None)) == 2

    def test_recorder_shared_per_cluster(self):
        cluster = Cluster()
        assert recorder_for(cluster) is recorder_for(cluster)

    def test_emit_failure_never_raises(self):
        class Broken(Cluster):
            def create(self, kind, obj):
                if kind == "events":
                    raise RuntimeError("boom")
                return super().create(kind, obj)

        rec = EventRecorder(Broken())
        assert rec.event("Node", "n1", "Launched", "msg") is None


class TestControllerEvents:
    def test_launch_and_consolidate_emit(self):
        from karpenter_tpu.controllers.consolidation import ConsolidationController
        from karpenter_tpu.controllers.provisioning import ProvisioningController

        cluster = Cluster()
        provider = FakeCloudProvider(instance_types(20))
        provisioner = make_provisioner(solver="ffd")
        c = provisioner.spec.constraints
        c.requirements = c.requirements.merge(
            catalog_requirements(provider.get_instance_types())
        )
        cluster.create("provisioners", provisioner)
        controller = ProvisioningController(cluster, provider, start_workers=False)
        controller.reconcile(provisioner.metadata.name)
        worker = controller.workers[provisioner.metadata.name]
        pod = make_pod(requests={"cpu": "0.5"})
        cluster.create("pods", pod)
        worker.add(pod)
        worker.batcher.idle_duration = 0.05
        worker.provision_once()
        controller.stop()
        reasons = {e.reason for e in cluster.list("events", None)}
        assert "Launched" in reasons
        launched = [e for e in cluster.list("events", None) if e.reason == "Launched"]
        assert launched[0].involved_kind == "Node"
        assert "bound 1 pod(s)" in launched[0].message

    def test_termination_emits(self):
        from karpenter_tpu.controllers.termination import TerminationController

        cluster = Cluster()
        provider = FakeCloudProvider(instance_types(5))
        controller = TerminationController(cluster, provider, start_queue=False)
        node = make_node(
            name="doomed", provisioner_name="default",
            finalizers=[lbl.TERMINATION_FINALIZER],
        )
        cluster.create("nodes", node)
        cluster.delete("nodes", "doomed", namespace="")
        controller.reconcile("doomed")
        reasons = {e.reason for e in cluster.list("events", None)}
        assert "Terminated" in reasons
