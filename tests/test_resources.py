"""Resource parsing/arithmetic and dense-vector encoding tests."""

import numpy as np
import pytest

from karpenter_tpu.utils import resources as res
from tests.factories import make_pod


class TestParsing:
    def test_plain(self):
        assert res.parse_quantity("4") == 4.0
        assert res.parse_quantity(2.5) == 2.5

    def test_milli(self):
        assert res.parse_quantity("100m") == pytest.approx(0.1)
        assert res.parse_quantity("1500m") == pytest.approx(1.5)

    def test_binary_suffixes(self):
        assert res.parse_quantity("1Ki") == 1024
        assert res.parse_quantity("2Gi") == 2 * 2**30
        assert res.parse_quantity("1.5Gi") == pytest.approx(1.5 * 2**30)

    def test_decimal_suffixes(self):
        assert res.parse_quantity("1k") == 1000
        assert res.parse_quantity("2G") == 2e9

    def test_invalid(self):
        with pytest.raises(ValueError):
            res.parse_quantity("abc")


class TestArithmetic:
    def test_merge(self):
        out = res.merge({"cpu": 1.0}, {"cpu": 2.0, "memory": 5.0})
        assert out == {"cpu": 3.0, "memory": 5.0}

    def test_fits(self):
        assert res.fits({"cpu": 1.0}, {"cpu": 1.0, "memory": 5.0})
        assert not res.fits({"cpu": 2.0}, {"cpu": 1.0})
        # missing key in total counts as zero
        assert not res.fits({"gpu": 1.0}, {"cpu": 1.0})

    def test_requests_for_pods_adds_pod_count(self):
        p1 = make_pod(requests={"cpu": "1"})
        p2 = make_pod(requests={"cpu": "2"})
        out = res.requests_for_pods(p1, p2)
        assert out[res.CPU] == 3.0
        assert out[res.PODS] == 2.0


class TestVectorEncoding:
    def test_known_axes(self):
        v = res.to_vector({res.CPU: 2.0, res.MEMORY: 1024.0})
        assert v[res.AXIS_INDEX[res.CPU]] == 2.0
        assert v[res.AXIS_INDEX[res.MEMORY]] == 1024.0
        assert v.dtype == np.float32

    def test_extra_axes(self):
        v = res.to_vector({"example.com/foo": 3.0}, extra_axes=["example.com/foo"])
        assert v[res.NUM_RESOURCE_AXES] == 3.0

    def test_unknown_axis_raises(self):
        with pytest.raises(KeyError):
            res.to_vector({"example.com/foo": 3.0})

    def test_collect_extra_axes(self):
        extras = res.collect_extra_axes([{"z.com/a": 1.0}, {res.CPU: 1.0, "a.com/b": 2.0}])
        assert extras == ["a.com/b", "z.com/a"]
