"""Control-plane partition tolerance (docs/partition.md): the resilient
kube transport (per-verb retries, 429/Retry-After, mutation-priority flow
control, the apiserver breaker + degraded cache reads), the watch-loop
backoff hot-fix, the eviction Retry-After satellite, the events
zero-retry policy, bind-409 disposition, and REJECTED-vs-UNREACHABLE
lease-loss fencing through the shard manager and the launch/GC guards."""

import threading
import time

import pytest

from karpenter_tpu import metrics as m
from karpenter_tpu.kube.apiserver import ApiCluster, ApiError
from karpenter_tpu.kube.client import Cluster, Conflict, NotFound
from karpenter_tpu.kube.leader import (
    FENCE_MARGIN_FRACTION,
    FenceStatus,
    KubeLease,
    KubeLeaseSet,
)
from karpenter_tpu.kube.testserver import TestApiServer
from karpenter_tpu.kube.transport import (
    VERB_CREATE,
    VERB_EVENTS,
    VERB_MUTATE,
    VERB_READ,
    ApiUnavailable,
    FlowLimiter,
    KubeThrottled,
    KubeTransport,
    is_unreachable,
)
from karpenter_tpu.resilience import CircuitBreaker
from karpenter_tpu.testing.chaos import ApiServerChaos, ChaosWindow
from tests.factories import make_pdb, make_pod, make_provisioner


def _counter(name, labels=None):
    return m.REGISTRY.get_sample_value(name, labels or {}) or 0.0


class _FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _transport(clock=None, sleeps=None, **kw):
    """A KubeTransport with an injected clock and a sleep recorder (sleeps
    advance the fake clock, so deadlines behave)."""
    clock = clock or _FakeClock()
    sleeps = sleeps if sleeps is not None else []

    def sleep(s):
        sleeps.append(s)
        clock.advance(s)

    kw.setdefault("qps", 1000.0)
    kw.setdefault("burst", 1000)
    return KubeTransport(clock=clock, sleep=sleep, **kw), clock, sleeps


# ---------------------------------------------------------------------------
# flow control
# ---------------------------------------------------------------------------


class TestFlowLimiter:
    def test_mutation_priority_reserve(self):
        """Reads cannot drain the bucket below the mutation reserve; a
        mutation still gets a token after reads start refusing."""
        clock = _FakeClock()
        limiter = FlowLimiter(qps=0.000001, burst=10, clock=clock, sleep=lambda s: None)
        reads = 0
        while limiter.try_take(False):
            reads += 1
            assert reads < 100
        assert reads < 10  # the reserve held some tokens back
        assert limiter.try_take(True)  # a mutation spends the reserve
        # and once truly empty, mutations refuse too
        while limiter.try_take(True):
            pass
        assert not limiter.try_take(True)

    def test_take_reports_waits(self):
        clock = _FakeClock()

        def sleep(s):
            clock.advance(s)

        limiter = FlowLimiter(qps=100.0, burst=1, clock=clock, sleep=sleep)
        ok, waited = limiter.take(True, timeout=1.0)
        assert ok and not waited
        ok, waited = limiter.take(True, timeout=1.0)
        assert ok and waited  # had to wait for the refill
        limiter2 = FlowLimiter(qps=0.000001, burst=1, clock=clock, sleep=sleep)
        assert limiter2.try_take(True)
        ok, waited = limiter2.take(True, timeout=0.05)
        assert not ok and waited  # bounded: gives up at the timeout


# ---------------------------------------------------------------------------
# the transport policy ladder (unit, fake attempts)
# ---------------------------------------------------------------------------


class TestKubeTransport:
    def test_read_retries_5xx_then_succeeds(self):
        transport, clock, sleeps = _transport()
        answers = [(503, {}, None), (503, {}, None), (200, {"ok": 1}, None)]
        calls = []

        def attempt():
            calls.append(1)
            return answers[len(calls) - 1]

        before = _counter("karpenter_kube_request_retries_total", {"verb_class": "read"})
        status, doc, _ = transport.request(VERB_READ, "GET", "pods", attempt)
        assert status == 200 and doc == {"ok": 1}
        assert len(calls) == 3
        assert len(sleeps) == 2  # two jittered backoffs
        assert _counter(
            "karpenter_kube_request_retries_total", {"verb_class": "read"}
        ) == before + 2

    def test_connection_errors_retry_then_raise(self):
        transport, clock, sleeps = _transport()
        calls = []

        def attempt():
            calls.append(1)
            raise ConnectionRefusedError("down")

        with pytest.raises(ConnectionRefusedError):
            transport.request(VERB_MUTATE, "PATCH", "nodes", attempt)
        assert len(calls) == 3  # max_attempts for the mutate class

    def test_create_is_never_retried(self):
        transport, clock, sleeps = _transport()
        calls = []

        def attempt():
            calls.append(1)
            return 503, {"kind": "Status"}, None

        status, doc, _ = transport.request(VERB_CREATE, "POST", "nodes", attempt)
        assert status == 503
        assert len(calls) == 1
        assert sleeps == []

    def test_429_retry_after_is_honored(self):
        """The server's own hint paces the retry — not the jitter ladder."""
        transport, clock, sleeps = _transport()
        answers = [(429, {}, 0.07), (200, {}, None)]
        calls = []

        def attempt():
            calls.append(1)
            return answers[len(calls) - 1]

        status, _, _ = transport.request(VERB_MUTATE, "PATCH", "pods", attempt)
        assert status == 200
        assert sleeps == [0.07]

    def test_429_on_create_surfaces_the_hint(self):
        transport, clock, sleeps = _transport()
        with pytest.raises(KubeThrottled) as ei:
            transport.request(
                VERB_CREATE, "POST", "pods", lambda: (429, {}, 0.35)
            )
        assert ei.value.retry_after == pytest.approx(0.35)
        assert sleeps == []

    def test_429_counts_as_breaker_success(self):
        """A throttling apiserver is ALIVE: a 429 storm must never open
        the breaker (that would turn backpressure into an outage)."""
        transport, clock, sleeps = _transport()
        for _ in range(20):
            with pytest.raises(KubeThrottled):
                transport.request(
                    VERB_CREATE, "POST", "pods", lambda: (429, {}, 0.01)
                )
        assert not transport.degraded()

    def test_breaker_opens_then_half_open_recovers(self):
        clock = _FakeClock()
        transport, clock, sleeps = _transport(clock=clock)
        calls = []

        def failing():
            calls.append(1)
            return 503, {}, None

        # sustained 5xx: the windowed failure rate opens the breaker (each
        # logical read pays up to 3 attempts; the breaker can open MID-
        # ladder, failing the remaining attempts fast)
        for _ in range(4):
            try:
                transport.request(VERB_READ, "GET", "pods", failing)
            except ApiUnavailable:
                break
        assert transport.degraded()
        n = len(calls)
        with pytest.raises(ApiUnavailable):
            transport.request(VERB_READ, "GET", "pods", failing)
        assert len(calls) == n  # fast-fail: no attempt was paid
        # cool-off elapses: one half-open probe is admitted and closes it
        clock.advance(transport.breaker.open_seconds + 0.1)
        status, _, _ = transport.request(
            VERB_READ, "GET", "pods", lambda: (200, {}, None)
        )
        assert status == 200
        assert not transport.degraded()

    def test_lease_class_bypasses_an_open_breaker(self):
        """Lease traffic IS the fencing signal: a breaker opened by OTHER
        traffic must not fast-fail renewals — a 1s blip would otherwise
        read as a 5s outage to the lease layer (spurious fencing)."""
        from karpenter_tpu.kube.transport import VERB_LEASE

        transport, clock, sleeps = _transport()
        transport.breaker.trip()
        assert transport.degraded()
        with pytest.raises(ApiUnavailable):
            transport.request(VERB_READ, "GET", "pods", lambda: (200, {}, None))
        status, _, _ = transport.request(
            VERB_LEASE, "PUT", "leases", lambda: (200, {}, None)
        )
        assert status == 200  # the real attempt was paid, breaker or not

    def test_round_budget_caps_retries(self):
        """An exhausted reconcile-round Budget degrades to retry-free."""
        from karpenter_tpu.resilience import Budget

        transport, clock, sleeps = _transport()
        calls = []

        def attempt():
            calls.append(1)
            return 503, {}, None

        budget = Budget(0.01, clock=clock)
        with budget.activate():
            status, _, _ = transport.request(VERB_READ, "GET", "pods", attempt)
        assert status == 503
        assert len(calls) == 1

    def test_events_drop_counter(self):
        transport, clock, sleeps = _transport()
        before = _counter("karpenter_kube_events_dropped_total")

        def attempt():
            raise ConnectionResetError("slow apiserver")

        with pytest.raises(ConnectionResetError):
            transport.request(VERB_EVENTS, "POST", "events", attempt)
        assert _counter("karpenter_kube_events_dropped_total") == before + 1
        assert sleeps == []  # zero retries for the events class

    def test_events_5xx_also_counts_as_dropped(self):
        """A 503 brownout answer is RETURNED (the recorder swallows the
        ApiError): that write is just as lost as a timeout — the triage
        counter must see it."""
        transport, clock, sleeps = _transport()
        before = _counter("karpenter_kube_events_dropped_total")
        status, _, _ = transport.request(
            VERB_EVENTS, "POST", "events", lambda: (503, {}, None)
        )
        assert status == 503
        assert _counter("karpenter_kube_events_dropped_total") == before + 1

    def test_unreachable_classification(self):
        assert is_unreachable(ApiUnavailable("open"))
        assert is_unreachable(KubeThrottled("429", 1.0))
        assert is_unreachable(ConnectionRefusedError())
        assert is_unreachable(TimeoutError())
        assert is_unreachable(ApiError(503, "storm"))
        assert is_unreachable(ApiError(429, "brownout"))
        assert not is_unreachable(ApiError(403, "rbac"))
        assert not is_unreachable(Conflict("409"))
        assert not is_unreachable(NotFound("404"))
        assert not is_unreachable(ValueError("bug"))


# ---------------------------------------------------------------------------
# end-to-end against the protocol double (+ ApiServerChaos)
# ---------------------------------------------------------------------------


@pytest.fixture()
def env():
    server = TestApiServer()
    server.start()
    clients = []

    def connect(**kw):
        kw.setdefault("kinds", ())
        c = ApiCluster(server.url, **kw)
        # CI-speed retry pacing; the ladder shape is what's under test
        c.transport._backoff_base = 0.01
        c.transport._backoff_cap = 0.05
        clients.append(c)
        return c

    server.connect = connect
    yield server
    for c in clients:
        c.stop()
    server.stop()


class TestTransportE2E:
    def test_patch_rides_through_transient_5xx(self, env):
        """The satellite's conflict/transient coverage: a PATCH that eats
        two injected 503s still lands (idempotent verb class retries)."""
        cluster = env.connect()
        cluster.create("provisioners", make_provisioner(name="p1"))
        chaos = ApiServerChaos(seed=7)
        env.chaos = chaos
        chaos.fail_next("PATCH", 2)
        before = _counter(
            "karpenter_kube_request_retries_total", {"verb_class": "mutate"}
        )
        fresh = cluster.patch_status(
            "provisioners", "p1", {"lastScaleTime": "2026-08-03T00:00:00Z"},
            namespace="",
        )
        assert fresh is not None
        assert chaos.counts(chaos.injected) == 2
        assert _counter(
            "karpenter_kube_request_retries_total", {"verb_class": "mutate"}
        ) == before + 2

    def test_conflicts_stay_loud_under_chaos(self, env):
        """A 409 is a POSITIVE answer: even with chaos injecting transient
        errors around it, create/update conflicts surface as Conflict, and
        are never retried into silent success."""
        cluster = env.connect()
        prov = make_provisioner(name="dup")
        cluster.create("provisioners", prov)
        env.chaos = ApiServerChaos(seed=3)
        env.chaos.fail_next("PUT", 1)
        live = cluster.get_live("provisioners", "dup", namespace="")
        live.metadata.resource_version = 999999  # stale: a racer's write won
        with pytest.raises(Conflict):
            cluster.update("provisioners", live)
        with pytest.raises(Conflict):
            cluster.create("provisioners", make_provisioner(name="dup"))

    def test_server_429_retry_after_paces_the_mutate_ladder(self, env):
        cluster = env.connect()
        cluster.create("provisioners", make_provisioner(name="throttled"))
        chaos = ApiServerChaos(throttle_rate=1.0, retry_after=0.05, seed=1)
        env.chaos = chaos
        before = _counter("karpenter_kube_throttled_total", {"source": "server"})
        t0 = time.perf_counter()
        with pytest.raises(KubeThrottled) as ei:
            cluster.patch_status(
                "provisioners", "throttled", {"lastScaleTime": "x"}, namespace=""
            )
        # all three attempts throttled: two Retry-After sleeps were paid
        assert time.perf_counter() - t0 >= 0.1
        assert ei.value.retry_after == pytest.approx(0.05)
        assert _counter(
            "karpenter_kube_throttled_total", {"source": "server"}
        ) >= before + 3

    def test_degraded_reads_serve_the_cache(self, env):
        """Breaker OPEN -> get_live answers from the informer view for
        watched kinds, raises ApiUnavailable for un-watched ones (leases:
        nothing cached there but our own write echoes)."""
        cluster = env.connect(kinds=("pods",))  # pods ARE informer-watched
        cluster.transport.breaker = CircuitBreaker(
            dependency="kube-apiserver", min_volume=2, failure_rate=0.5,
            open_seconds=60.0,
        )
        node_pod = make_pod(name="cached-pod")
        cluster.create("pods", node_pod)  # populates the local cache
        env.chaos = ApiServerChaos()
        env.chaos.blackout(120.0)
        for _ in range(3):  # feed the breaker its failures
            try:
                cluster.get_live("provisioners", "nope", namespace="")
            except Exception:
                pass
        assert cluster.degraded()
        before = _counter("karpenter_kube_degraded_reads_total")
        got = cluster.get_live("pods", "cached-pod")
        assert got.metadata.name == "cached-pod"
        assert _counter("karpenter_kube_degraded_reads_total") == before + 1
        # lease traffic bypasses the breaker (it IS the fencing signal) —
        # it pays the real attempt and fails UNREACHABLE, never from cache
        with pytest.raises(Exception) as ei:
            cluster.get_live("leases", "some-lease", namespace="kube-system")
        assert is_unreachable(ei.value)
        with pytest.raises(Exception) as ei:
            cluster.list_live("leases", namespace="kube-system")
        assert is_unreachable(ei.value)

    def test_bind_409_same_node_is_idempotent(self, env):
        cluster = env.connect()
        pod = make_pod(name="bound-once")
        cluster.create("pods", pod)
        cluster.bind(pod, "node-a")
        # a lost response replayed: the server answers 409, the live pod
        # already points at the SAME node — the goal was achieved
        replay = make_pod(name="bound-once")
        replay.spec.node_name = ""
        cluster.bind(replay, "node-a")
        assert replay.spec.node_name == "node-a"

    def test_bind_409_different_node_raises(self, env):
        """The non-idempotent arm (satellite coverage): the live pod is
        bound ELSEWHERE — rebinding would double-place it, so it raises."""
        cluster = env.connect()
        pod = make_pod(name="contested")
        cluster.create("pods", pod)
        cluster.bind(pod, "node-a")
        rival = make_pod(name="contested")
        rival.spec.node_name = ""
        with pytest.raises(Conflict):
            cluster.bind(rival, "node-b")
        assert rival.spec.node_name == ""

    def test_bind_409_pod_gone_raises(self, env):
        cluster = env.connect()
        pod = make_pod(name="vanishing")
        cluster.create("pods", pod)
        cluster.bind(pod, "node-a")
        env.cluster.delete("pods", "vanishing")
        ghost = make_pod(name="vanishing")
        ghost.spec.node_name = ""
        with pytest.raises((Conflict, NotFound)):
            cluster.bind(ghost, "node-b")

    def test_evict_surfaces_retry_after(self, env):
        """The satellite: a PDB-blocked eviction's 429 Retry-After header
        rides back to the caller instead of being discarded."""
        env.eviction_retry_after = 0.35
        cluster = env.connect()
        pod = make_pod(name="protected", labels={"app": "guarded"})
        pod.spec.node_name = "node-a"
        env.cluster.seed("pods", pod)
        env.cluster.create("pdbs", make_pdb(
            name="guard", labels={"app": "guarded"}, min_available=1,
        ))
        ok, hint = cluster.evict_with_hint(pod)
        assert not ok
        assert hint == pytest.approx(0.35)
        # the boolean surface still answers plain False
        assert cluster.evict(pod) is False

    def test_eviction_queue_honors_the_hint(self, env):
        """Termination's rate-limited requeue uses the server's schedule,
        not the blind exponential interval."""
        from karpenter_tpu.controllers.termination import EvictionQueue

        env.eviction_retry_after = 0.3
        cluster = env.connect()
        pod = make_pod(name="queued", labels={"app": "guarded"})
        pod.spec.node_name = "node-a"
        env.cluster.seed("pods", pod)
        # the queue's pre-check reads the CLIENT's informer view: seed the
        # cache too (this client runs no watches)
        cluster.seed("pods", pod)
        env.cluster.create("pdbs", make_pdb(
            name="guard", labels={"app": "guarded"}, min_available=1,
        ))
        q = EvictionQueue(cluster, start=False)
        q.add([pod])
        key = q.queue.get(timeout=1.0)
        assert not q.process_one(key)
        with q.queue._lock:
            assert len(q.queue._delayed) == 1
            ready_at, _, requeued = q.queue._delayed[0]
        assert requeued == key
        delay = ready_at - time.monotonic()
        # ~the server's 0.3s hint — NOT the 0.1s blind base interval
        assert 0.15 < delay <= 0.31

    def test_event_write_never_blocks_a_reconcile(self, env):
        """Events ride the zero-retry/short-deadline class: with the
        apiserver injecting 1s latency, the recorder returns fast and the
        drop is counted."""
        from karpenter_tpu.api.objects import Event, ObjectMeta
        from karpenter_tpu.kube.events import recorder_for

        cluster = env.connect()
        cluster.events_timeout = 0.2
        env.chaos = ApiServerChaos(latency_floor=1.0)
        before = _counter("karpenter_kube_events_dropped_total")
        t0 = time.perf_counter()
        out = recorder_for(cluster).event(
            "Node", "slow-node", "Launched", "latency chaos", type="Normal"
        )
        elapsed = time.perf_counter() - t0
        assert elapsed < 0.9, f"event write blocked {elapsed:.2f}s"
        assert out is None  # fire-and-forget: dropped, not raised
        assert _counter("karpenter_kube_events_dropped_total") == before + 1

    def test_watch_relist_backs_off_under_blackout(self, env):
        """The hot-loop satellite: a down apiserver drives bounded,
        exponentially-spaced re-list attempts, and recovery re-syncs."""
        cluster = ApiCluster(env.url, kinds=("pods",))
        cluster.transport._backoff_base = 0.01
        cluster.transport._backoff_cap = 0.02
        cluster.watch_backoff_base = 0.05
        cluster.watch_backoff_cap = 0.4
        env.chaos = ApiServerChaos()
        env.chaos.blackout(1.0)
        try:
            cluster.start()
            time.sleep(1.0)
            attempts = cluster.relist_attempts.get("pods", 0)
            # 0.05+0.1+0.2+0.4... exponential: a handful, not dozens (the
            # old fixed delay would log ~20 at this base; a hot loop 100s)
            assert 1 <= attempts <= 8, f"{attempts} relists in 1s"
            # blackout over: the next paced attempt succeeds and syncs
            assert cluster.wait_for_sync(10.0)
        finally:
            cluster.stop()


# ---------------------------------------------------------------------------
# lease-loss fencing: REJECTED vs UNREACHABLE
# ---------------------------------------------------------------------------


class _PartitionedCluster:
    """In-memory Cluster proxy whose lease surface can be partitioned:
    while ``down``, every read/write raises a connection error — exactly
    what the transport surfaces when the apiserver is gone."""

    _CUT = frozenset({
        "try_get", "get", "list", "create", "update", "delete", "bind",
    })

    def __init__(self, cluster):
        self._cluster = cluster
        self.down = False

    def __getattr__(self, name):
        attr = getattr(self._cluster, name)
        if not callable(attr) or name not in self._CUT:
            return attr

        def guarded(*args, **kwargs):
            if self.down:
                raise ConnectionRefusedError("chaos: apiserver partitioned")
            return attr(*args, **kwargs)

        return guarded


class TestLeaseFencing:
    def _lease(self, duration=10.0):
        clock = _FakeClock()
        backing = Cluster(clock=clock)
        cluster = _PartitionedCluster(backing)
        lease = KubeLease(cluster, name="shard-a", identity="r1", duration=duration)
        return lease, cluster, clock

    def test_sub_expiry_blip_keeps_the_hold(self):
        lease, cluster, clock = self._lease()
        assert lease.try_acquire()
        cluster.down = True  # blip begins
        clock.advance(3.0)
        assert lease.renew(), "a sub-expiry blip must not read as lease loss"
        assert not lease.status.fenced
        cluster.down = False  # blip ends: a real renew re-anchors expiry
        assert lease.renew()
        clock.advance(9.0)  # would be past the ORIGINAL expiry
        cluster.down = True
        assert not lease.status.fenced

    def test_fences_past_expiry_margin(self):
        lease, cluster, clock = self._lease(duration=10.0)
        assert lease.try_acquire()
        cluster.down = True
        margin = FENCE_MARGIN_FRACTION * lease.duration
        clock.advance(10.0 - margin - 0.5)
        assert lease.renew()  # still inside the grace window
        clock.advance(1.0)  # now past expiry - margin
        assert not lease.renew()
        assert lease.status.fenced

    def test_recovery_lifts_the_fence(self):
        lease, cluster, clock = self._lease()
        assert lease.try_acquire()
        cluster.down = True
        clock.advance(50.0)
        assert not lease.renew()
        assert lease.status.fenced
        cluster.down = False
        assert lease.try_acquire()  # expired server-side: re-acquirable
        assert not lease.status.fenced

    def test_rejected_is_still_instant_loss(self):
        """A peer's takeover must behave exactly as before fencing existed:
        renewal answers False NOW, and nothing fences."""
        lease, cluster, clock = self._lease(duration=10.0)
        assert lease.try_acquire()
        clock.advance(11.0)  # expired; a peer claims it
        rival = KubeLease(cluster, name="shard-a", identity="r2", duration=10.0)
        assert rival.try_acquire()
        assert not lease.renew()
        assert not lease.status.fenced

    def test_shard_manager_fences_end_to_end(self):
        """KubeLeaseSet + ShardManager: blip -> zero churn; blackout past
        expiry -> on_lost + fenced() True + gauge; recovery -> re-owned."""
        from karpenter_tpu.fleet import ShardManager

        clock = _FakeClock()
        backing = Cluster(clock=clock)
        cluster = _PartitionedCluster(backing)
        leases = KubeLeaseSet(cluster, identity="r1", duration=10.0)
        gained, lost = [], []
        mgr = ShardManager(
            leases, keys_fn=lambda: {"p1"},
            on_acquired=gained.append, on_lost=lost.append,
            include_default_shard=False,
        )
        mgr.tick()
        assert mgr.owns("p1") and gained == ["p1"]
        # sub-expiry blip: renewed optimistically, zero churn
        cluster.down = True
        clock.advance(3.0)
        mgr.tick()
        assert mgr.owns("p1") and lost == [] and not mgr.fenced()
        # blackout outlives the lease: fence + synchronous loss
        clock.advance(20.0)
        mgr.tick()
        assert lost == ["p1"]
        assert not mgr.owns("p1")
        assert mgr.fenced()
        assert _counter("karpenter_fleet_fenced") == 1.0
        # partition heals: the next ticks re-own and un-fence
        cluster.down = False
        mgr.tick()
        mgr.tick()
        assert mgr.owns("p1")
        assert not mgr.fenced()
        assert _counter("karpenter_fleet_fenced") == 0.0

    def test_acquire_hold_is_timestamped_before_the_round_trip(self):
        """A slow-but-answering acquire must not inflate the client-side
        hold by its own RTT — that would eat the fence safety margin and
        reopen the split-brain window the margin exists to cover."""
        lease, cluster, clock = self._lease(duration=10.0)
        t0 = clock()
        orig = KubeLease._try_acquire

        def slow_acquire(self):
            out = orig(self)
            clock.advance(5.0)  # the round trip took 5s
            return out

        KubeLease._try_acquire = slow_acquire
        try:
            assert lease.try_acquire()
        finally:
            KubeLease._try_acquire = orig
        assert lease._held_until == pytest.approx(t0 + 10.0)

    def test_file_lease_backends_never_fence(self, tmp_path):
        from karpenter_tpu.fleet import ShardManager, build_lease_set

        leases = build_lease_set(str(tmp_path / "shards"), identity="r1")
        mgr = ShardManager(leases, keys_fn=lambda: set())
        assert mgr.fenced() is False


class TestFencedGuards:
    def _worker(self, fenced):
        from karpenter_tpu.cloudprovider.fake import FakeCloudProvider, instance_types
        from karpenter_tpu.controllers.provisioning import ProvisionerWorker

        cluster = Cluster()
        worker = ProvisionerWorker(
            make_provisioner(), cluster, FakeCloudProvider(instance_types(5)),
            owned=lambda: True, fenced=fenced,
        )
        worker.batcher.idle_duration = 0.01
        return cluster, worker

    def test_fenced_worker_refuses_the_launch(self):
        fenced = {"v": False}
        cluster, worker = self._worker(lambda: fenced["v"])
        pod = make_pod(name="fenced-pod", requests={"cpu": "0.5"})
        cluster.create("pods", pod)
        worker.add(pod)
        fenced["v"] = True  # the blackout outlived the lease mid-flight
        before = _counter(
            "karpenter_fleet_duplicate_launch_guard_total", {"reason": "fenced"}
        )
        worker.provision_once()
        assert not pod.spec.node_name
        assert cluster.nodes() == []
        assert _counter(
            "karpenter_fleet_duplicate_launch_guard_total", {"reason": "fenced"}
        ) == before + 1

    def test_unfenced_worker_launches(self):
        cluster, worker = self._worker(lambda: False)
        pod = make_pod(name="free-pod", requests={"cpu": "0.5"})
        cluster.create("pods", pod)
        worker.add(pod)
        worker.provision_once()
        assert pod.spec.node_name

    def test_fenced_termination_defers_the_cloud_delete(self):
        """Finalizer-driven teardown acts on the informer view, which is
        stale while fenced — the cloud delete waits for the control plane
        (cloud-NOTIFIED interruption terminates stay un-gated)."""
        from karpenter_tpu.api import labels as lbl
        from karpenter_tpu.controllers.termination import TerminationController
        from karpenter_tpu.testing.factories import make_node

        class _Provider:
            deletes = 0

            def delete(self, node):
                self.deletes += 1

        fenced = {"v": True}
        cluster = Cluster()
        provider = _Provider()
        tc = TerminationController(
            cluster, provider, start_queue=False, fenced=lambda: fenced["v"]
        )
        node = make_node(name="draining")
        node.metadata.deletion_timestamp = cluster.clock()
        node.metadata.finalizers = [lbl.TERMINATION_FINALIZER]
        cluster.seed("nodes", node)
        before = _counter(
            "karpenter_fleet_duplicate_launch_guard_total", {"reason": "fenced"}
        )
        assert tc.reconcile("draining") == tc.DRAIN_REQUEUE
        assert provider.deletes == 0
        assert _counter(
            "karpenter_fleet_duplicate_launch_guard_total", {"reason": "fenced"}
        ) == before + 1
        fenced["v"] = False  # the control plane answered: teardown resumes
        assert tc.reconcile("draining") is None
        assert provider.deletes == 1

    def test_gc_sweep_skips_while_fenced(self):
        from karpenter_tpu.controllers.garbage_collection import (
            GC_POLL_KEY,
            GarbageCollectionController,
        )

        class _Fenced:
            def owns(self, key):
                return True

            def fenced(self):
                return True

        class _Provider:
            calls = 0

            def list_instances(self):
                self.calls += 1
                return []

        provider = _Provider()
        gc = GarbageCollectionController(
            Cluster(), provider, ownership=_Fenced(), gc_interval=0.1
        )
        before = _counter(
            "karpenter_fleet_duplicate_launch_guard_total", {"reason": "fenced"}
        )
        gc.reconcile(GC_POLL_KEY)
        assert provider.calls == 0, "fenced sweep must not touch the cloud"
        assert _counter(
            "karpenter_fleet_duplicate_launch_guard_total", {"reason": "fenced"}
        ) == before + 1


# ---------------------------------------------------------------------------
# the chaos harness itself
# ---------------------------------------------------------------------------


class TestApiServerChaos:
    def test_seeded_injection_is_deterministic(self, env):
        cluster = env.connect()
        for i in range(6):
            cluster.create("pods", make_pod(name=f"seeded-{i}"))

        def run(seed):
            chaos = ApiServerChaos(per_verb={"GET": 0.5}, seed=seed)
            env.chaos = chaos
            outcomes = []
            for i in range(6):
                try:
                    status, _, _ = cluster.transport.request(
                        VERB_CREATE, "GET", "pods",
                        lambda i=i: cluster._attempt(
                            "GET", f"/api/v1/namespaces/default/pods/seeded-{i}",
                            None, "application/json", None,
                        ),
                    )
                    outcomes.append("ok" if status == 200 else "err")
                except Exception:
                    outcomes.append("err")
            env.chaos = None
            return outcomes

        a, b = run(42), run(42)
        assert a == b
        assert "err" in a and "ok" in a

    def test_blackout_window_drops_connections(self, env):
        cluster = env.connect()
        chaos = ApiServerChaos(blackouts=[ChaosWindow(0.0, 30.0)])
        env.chaos = chaos
        with pytest.raises(Exception) as ei:
            cluster.get_live("provisioners", "anything", namespace="")
        assert is_unreachable(ei.value)
        assert chaos.counts(chaos.dropped) >= 1

    def test_fail_next_is_exact(self, env):
        cluster = env.connect()
        cluster.create("pods", make_pod(name="exact"))
        chaos = ApiServerChaos(seed=0)
        env.chaos = chaos
        chaos.fail_next("GET", 2)
        got = cluster.get_live("pods", "exact")  # 2 failures, then clean
        assert got.metadata.name == "exact"
        assert chaos.counts(chaos.injected) == 2
