"""Crash-consistent launch path (docs/launch-journal.md): the write-ahead
launch journal, token-idempotent creates on all four providers and both
HTTP wires, the recovery adopt/confirm ladder, the orphan-instance GC
controller, the cross-process requeue endpoints, and the crash-mid-create
chaos scenarios."""

import threading
import time

import pytest

from karpenter_tpu.api import labels as lbl
from karpenter_tpu.api.objects import NodeSelectorRequirement
from karpenter_tpu.api.provisioner import Constraints
from karpenter_tpu.cloudprovider.requirements import catalog_requirements
from karpenter_tpu.cloudprovider.types import LiveInstance, NodeRequest
from karpenter_tpu.kube.client import Cluster
from karpenter_tpu.launch import (
    STATE_CREATED,
    STATE_INTENT,
    FileLaunchJournal,
    KubeLaunchJournal,
    MemoryLaunchJournal,
    build_journal,
)
from karpenter_tpu.launch import recovery
from tests.factories import make_pod, make_provisioner


def constraints_for(provider, provider_cfg=None):
    from karpenter_tpu.api.requirements import Requirements

    c = Constraints(requirements=Requirements.new(), provider=provider_cfg)
    provider.default(c)
    catalog = provider.get_instance_types(provider_cfg)
    c.requirements = c.requirements.merge(catalog_requirements(catalog))
    return c, catalog


# ---------------------------------------------------------------------------
# journal backends
# ---------------------------------------------------------------------------


@pytest.fixture(params=["memory", "file", "kube"])
def journal(request, tmp_path):
    if request.param == "memory":
        yield MemoryLaunchJournal()
    elif request.param == "file":
        yield FileLaunchJournal(str(tmp_path / "journal.json"))
    else:
        yield KubeLaunchJournal(Cluster(), namespace="kube-system")


class TestJournalBackends:
    def test_intent_created_resolve_lifecycle(self, journal):
        journal.record_intent("tok-1", "prov-a", trace="00-aa-bb-01")
        entry = journal.get("tok-1")
        assert entry is not None
        assert entry.state == STATE_INTENT
        assert entry.provisioner == "prov-a"
        assert entry.trace == "00-aa-bb-01"

        journal.mark_created("tok-1", "node-1")
        entry = journal.get("tok-1")
        assert entry.state == STATE_CREATED
        assert entry.node_name == "node-1"

        journal.resolve("tok-1")
        assert journal.get("tok-1") is None
        assert journal.unresolved() == []

    def test_resolve_unknown_token_is_noop(self, journal):
        journal.resolve("never-recorded")  # must not raise
        journal.mark_created("never-recorded", "node-x")
        assert journal.get("never-recorded") is None

    def test_unresolved_lists_all_open_entries(self, journal):
        journal.record_intent("a", "p1")
        journal.record_intent("b", "p2")
        journal.mark_created("b", "node-b")
        tokens = {e.token for e in journal.unresolved()}
        assert tokens == {"a", "b"}

    def test_file_journal_survives_process_death(self, tmp_path):
        """The entire point: a NEW journal instance over the same path (a
        restarted / replacement process) sees the dead writer's entries."""
        path = str(tmp_path / "journal.json")
        dying = FileLaunchJournal(path)
        dying.record_intent("orphan-tok", "prov-a", trace="t")
        dying.mark_created("orphan-tok", "node-1")
        del dying  # no resolve: the process died

        survivor = FileLaunchJournal(path)
        entries = survivor.unresolved()
        assert len(entries) == 1
        assert entries[0].token == "orphan-tok"
        assert entries[0].state == STATE_CREATED

    def test_file_journal_concurrent_writers_do_not_lose_entries(self, tmp_path):
        path = str(tmp_path / "journal.json")

        def write(start):
            j = FileLaunchJournal(path)
            for i in range(start, start + 20):
                j.record_intent(f"tok-{i}", "p")

        threads = [threading.Thread(target=write, args=(s,)) for s in (0, 20, 40)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(FileLaunchJournal(path).unresolved()) == 60

    def test_kube_journal_peer_visibility_and_lease_cleanup(self):
        """Two journal instances over one cluster (two replicas): entries a
        dead peer wrote are visible, and resolution DELETES the Lease
        object (token in the name — a blanked object would be garbage)."""
        cluster = Cluster()
        writer = KubeLaunchJournal(cluster)
        reader = KubeLaunchJournal(cluster)
        writer.record_intent("tok-1", "prov-a")
        assert [e.token for e in reader.unresolved()] == ["tok-1"]
        reader.resolve("tok-1")
        assert writer.unresolved() == []
        assert cluster.list("leases", namespace="kube-system") == []

    def test_build_journal_spec_grammar(self, tmp_path):
        assert build_journal("") is None
        assert isinstance(build_journal("memory:"), MemoryLaunchJournal)
        fj = build_journal(str(tmp_path / "j.json"))
        assert isinstance(fj, FileLaunchJournal)
        kj = build_journal("kube:karpenter/launch", cluster=Cluster())
        assert isinstance(kj, KubeLaunchJournal)
        assert kj.namespace == "karpenter" and kj.prefix == "launch"


# ---------------------------------------------------------------------------
# token-idempotent creates: all four providers, both wires
# ---------------------------------------------------------------------------


class TestIdempotentCreateFake:
    def test_same_token_same_node_single_instance(self):
        from karpenter_tpu.cloudprovider.fake import FakeCloudProvider

        p = FakeCloudProvider()
        c, catalog = constraints_for(p)
        req = NodeRequest(template=c, instance_type_options=catalog,
                          launch_token="tok-x")
        n1 = p.create(req)
        n2 = p.create(req)
        assert n1.metadata.name == n2.metadata.name
        assert len(p.list_instances()) == 1
        assert p.list_instances()[0].launch_token == "tok-x"
        assert n1.metadata.annotations[lbl.LAUNCH_TOKEN_ANNOTATION] == "tok-x"

    def test_tokenless_creates_still_distinct(self):
        from karpenter_tpu.cloudprovider.fake import FakeCloudProvider

        p = FakeCloudProvider()
        c, catalog = constraints_for(p)
        req = NodeRequest(template=c, instance_type_options=catalog)
        assert p.create(req).metadata.name != p.create(req).metadata.name

    def test_delete_releases_token(self):
        from karpenter_tpu.cloudprovider.fake import FakeCloudProvider

        p = FakeCloudProvider()
        c, catalog = constraints_for(p)
        req = NodeRequest(template=c, instance_type_options=catalog,
                          launch_token="tok-x")
        n1 = p.create(req)
        p.delete(n1)
        assert p.list_instances() == []
        n2 = p.create(req)  # a dead instance must not be replayed
        assert n2.metadata.name != n1.metadata.name


@pytest.fixture(params=["inproc", "http"])
def sim_env(request):
    from karpenter_tpu.cloudprovider.simulated import SimCloudAPI, SimulatedCloudProvider

    api = SimCloudAPI()
    if request.param == "http":
        from karpenter_tpu.cloudprovider.httpapi import CloudAPIServer, HttpCloudAPI

        server = CloudAPIServer(api, page_size=10_000).start()
        provider = SimulatedCloudProvider(HttpCloudAPI(server.url, backoff_base=0.01))
        yield api, provider
        server.stop()
    else:
        provider = SimulatedCloudProvider(api)
        yield api, provider


class TestIdempotentCreateSimulated:
    def test_same_token_replays_same_instance(self, sim_env):
        api, provider = sim_env
        c, catalog = constraints_for(provider)
        req = NodeRequest(template=c, instance_type_options=catalog,
                          launch_token="tok-sim")
        n1 = provider.create(req)
        n2 = provider.create(req)
        assert n1.metadata.name == n2.metadata.name
        assert len(api.instances) == 1
        live = provider.list_instances()
        assert len(live) == 1 and live[0].launch_token == "tok-sim"
        assert n1.metadata.annotations[lbl.LAUNCH_TOKEN_ANNOTATION] == "tok-sim"

    def test_list_instances_crosses_the_wire_with_tokens(self, sim_env):
        api, provider = sim_env
        c, catalog = constraints_for(provider)
        provider.create(NodeRequest(template=c, instance_type_options=catalog,
                                    launch_token="tok-a"))
        provider.create(NodeRequest(template=c, instance_type_options=catalog,
                                    launch_token="tok-b"))
        live = provider.list_instances()
        assert {i.launch_token for i in live} == {"tok-a", "tok-b"}
        assert all(isinstance(i, LiveInstance) for i in live)
        assert all(i.created_at > 0 for i in live)

    def test_terminate_releases_token_no_dead_instance_replay(self, sim_env):
        """A token replay must never resurrect a TERMINATED instance as a
        live create result: terminate drops the ledger entry (like
        Fake/GKE delete), so a late retry after a delete launches fresh."""
        api, provider = sim_env
        c, catalog = constraints_for(provider)
        req = NodeRequest(template=c, instance_type_options=catalog,
                          launch_token="tok-dead")
        n1 = provider.create(req)
        api.terminate_instances([n1.metadata.name])
        n2 = provider.create(req)
        assert n2.metadata.name != n1.metadata.name
        assert api.instances[n2.metadata.name].state != "terminated"


@pytest.fixture(params=["inproc", "http"])
def gke_env(request):
    from karpenter_tpu.cloudprovider.gke import GkeCloudProvider, SimGkeAPI

    api = SimGkeAPI()
    if request.param == "http":
        from karpenter_tpu.cloudprovider.httpapi import GkeAPIServer, HttpGkeAPI

        server = GkeAPIServer(api).start()
        provider = GkeCloudProvider(api=HttpGkeAPI(server.url, backoff_base=0.01))
        yield api, provider
        server.stop()
    else:
        provider = GkeCloudProvider(api=api)
        yield api, provider


class TestIdempotentCreateGke:
    def test_same_token_same_host_no_second_pool(self, gke_env):
        api, provider = gke_env
        c, catalog = constraints_for(provider)
        req = NodeRequest(template=c, instance_type_options=catalog,
                          launch_token="tok-gke")
        n1 = provider.create(req)
        n2 = provider.create(req)
        assert n1.metadata.name == n2.metadata.name
        assert len(api.node_pools) == 1
        assert n1.metadata.annotations[lbl.LAUNCH_TOKEN_ANNOTATION] == "tok-gke"

    def test_multi_host_sibling_claims_stamp_their_own_tokens(self, gke_env):
        """Each host of a slice carries the token of the create() that
        returned it, so recovery can re-find ANY host by its journal
        entry — including hosts claimed from the pending pool (no API
        call happens for those)."""
        api, provider = gke_env
        from karpenter_tpu.cloudprovider.gke import slice_hosts

        c, catalog = constraints_for(provider)
        multi = [it for it in catalog if slice_hosts(it.name) > 1]
        assert multi, "gke catalog always carries multi-host slice types"
        it = min(multi, key=lambda t: slice_hosts(t.name))
        hosts = slice_hosts(it.name)
        reqs = [
            NodeRequest(template=c, instance_type_options=[it],
                        launch_token=f"tok-h{i}")
            for i in range(hosts)
        ]
        nodes = [provider.create(r) for r in reqs]
        assert len(api.node_pools) == 1  # ONE slice serves all hosts
        live = {i.id: i for i in provider.list_instances()}
        for i, node in enumerate(nodes):
            assert live[node.metadata.name].launch_token == f"tok-h{i}"

    def test_wire_create_is_idempotent_only_when_tokened(self, gke_env):
        api, provider = gke_env
        if not hasattr(provider.api, "_request"):
            pytest.skip("wire-only behavior")
        # tokened POST marks itself idempotent for the transport retry
        # policy; token-less keeps the conservative no-retry contract —
        # asserted indirectly: a tokened retry cannot double-launch
        pool1 = provider.api.create_node_pool(
            "n2-standard-8", "us-central1-a", False, 1, launch_token="t-1"
        )
        pool2 = provider.api.create_node_pool(
            "n2-standard-8", "us-central1-a", False, 1, launch_token="t-1"
        )
        assert pool1.name == pool2.name
        assert len(api.node_pools) == 1


class TestIdempotentCreateMetered:
    def _provider(self):
        from karpenter_tpu.cloudprovider import metrics as cpm
        from karpenter_tpu.cloudprovider.simulated import (
            SimCloudAPI,
            SimulatedCloudProvider,
        )

        api = SimCloudAPI()
        inner = SimulatedCloudProvider(api)
        return api, inner, cpm.decorate(inner)

    def test_retried_create_after_committed_failure_yields_one_instance(self):
        """THE acceptance scenario: the first attempt commits the launch
        but the response is lost (an exception after the vendor call);
        the metered retry replays the token and exactly one instance
        exists."""
        api, inner, metered = self._provider()
        c, catalog = constraints_for(inner)
        real_create = inner.create
        fail_once = {"armed": True}

        def create_commit_then_die(request):
            node = real_create(request)
            if fail_once.pop("armed", None):
                raise ConnectionError("response lost after commit")
            return node

        inner.create = create_commit_then_die
        node = metered.create(
            NodeRequest(template=c, instance_type_options=catalog,
                        launch_token="tok-retry")
        )
        assert len(api.instances) == 1  # committed once, replayed once
        assert node.metadata.annotations[lbl.LAUNCH_TOKEN_ANNOTATION] == "tok-retry"

    def test_metered_stamps_token_for_direct_callers(self):
        api, inner, metered = self._provider()
        c, catalog = constraints_for(inner)
        node = metered.create(NodeRequest(template=c, instance_type_options=catalog))
        assert node.metadata.annotations.get(lbl.LAUNCH_TOKEN_ANNOTATION)
        assert list(api.instances.values())[0].launch_token

    def test_list_instances_passes_through(self):
        api, inner, metered = self._provider()
        c, catalog = constraints_for(inner)
        metered.create(NodeRequest(template=c, instance_type_options=catalog,
                                   launch_token="t"))
        assert [i.launch_token for i in metered.list_instances()] == ["t"]


# ---------------------------------------------------------------------------
# recovery: the adopt/confirm ladder
# ---------------------------------------------------------------------------


class TestRecoveryLadder:
    def _env(self):
        from karpenter_tpu.cloudprovider.simulated import (
            SimCloudAPI,
            SimulatedCloudProvider,
        )

        cluster = Cluster()
        api = SimCloudAPI()
        provider = SimulatedCloudProvider(api)
        journal = MemoryLaunchJournal()
        cluster.create("provisioners", make_provisioner(name="prov-a"))
        return cluster, api, provider, journal

    def _launch_instance(self, provider, token):
        c, catalog = constraints_for(provider)
        return provider.create(
            NodeRequest(template=c, instance_type_options=catalog,
                        launch_token=token)
        )

    def _by_token(self, provider):
        return {i.launch_token: i for i in provider.list_instances()
                if i.launch_token}

    def test_never_launched_resolves(self):
        cluster, api, provider, journal = self._env()
        journal.record_intent("ghost", "prov-a")
        outcome = recovery.replay_entry(
            journal, cluster, provider, journal.get("ghost"),
            self._by_token(provider), now=time.time() + 120, replay_after=60,
        )
        assert outcome == recovery.NEVER_LAUNCHED
        assert journal.get("ghost") is None

    def test_young_entry_is_pending_untouched(self):
        cluster, api, provider, journal = self._env()
        journal.record_intent("young", "prov-a")
        outcome = recovery.replay_entry(
            journal, cluster, provider, journal.get("young"),
            self._by_token(provider), now=time.time(), replay_after=60,
        )
        assert outcome == recovery.PENDING
        assert journal.get("young") is not None

    def test_orphan_instance_is_adopted(self):
        cluster, api, provider, journal = self._env()
        journal.record_intent("tok-orphan", "prov-a", trace="")
        node = self._launch_instance(provider, "tok-orphan")
        # the crash: the Node object was never written
        outcome = recovery.replay_entry(
            journal, cluster, provider, journal.get("tok-orphan"),
            self._by_token(provider), now=time.time() + 120, replay_after=60,
        )
        assert outcome == recovery.ADOPTED
        adopted = cluster.try_get("nodes", node.metadata.name, namespace="")
        assert adopted is not None
        assert adopted.metadata.annotations[lbl.LAUNCH_TOKEN_ANNOTATION] == "tok-orphan"
        assert adopted.metadata.annotations["karpenter.sh/adopted"] == "true"
        # the adopted node must be deletable THROUGH the terminator: the
        # finalizer is what routes its deletion to the cloud delete
        assert lbl.TERMINATION_FINALIZER in adopted.metadata.finalizers
        # template labels + cloud labels both landed
        assert adopted.metadata.labels[lbl.PROVISIONER_NAME_LABEL] == "prov-a"
        assert adopted.metadata.labels[lbl.INSTANCE_TYPE]
        assert adopted.status.capacity  # catalog capacity attached
        assert journal.get("tok-orphan") is None

    def test_node_exists_resolves_without_second_node(self):
        cluster, api, provider, journal = self._env()
        journal.record_intent("tok-mid", "prov-a")
        node = self._launch_instance(provider, "tok-mid")
        cluster.create("nodes", node)  # crash landed AFTER the Node write
        journal.mark_created("tok-mid", node.metadata.name)
        outcome = recovery.replay_entry(
            journal, cluster, provider, journal.get("tok-mid"),
            self._by_token(provider), now=time.time() + 120, replay_after=60,
        )
        assert outcome == recovery.NODE_EXISTS
        assert len(cluster.nodes()) == 1
        assert journal.get("tok-mid") is None

    def test_adoption_without_provisioner_still_tracks_capacity(self):
        """The provisioner was deleted between the crash and the sweep:
        adoption still writes a Node (capacity must be tracked; emptiness
        or the operator reaps it later)."""
        cluster, api, provider, journal = self._env()
        journal.record_intent("tok-x", "deleted-prov")
        node = self._launch_instance(provider, "tok-x")
        outcome = recovery.replay_entry(
            journal, cluster, provider, journal.get("tok-x"),
            self._by_token(provider), now=time.time() + 120, replay_after=60,
        )
        assert outcome == recovery.ADOPTED
        adopted = cluster.try_get("nodes", node.metadata.name, namespace="")
        assert adopted is not None
        assert lbl.TERMINATION_FINALIZER in adopted.metadata.finalizers


# ---------------------------------------------------------------------------
# the GC controller
# ---------------------------------------------------------------------------


class TestGarbageCollectionController:
    def _env(self, journal=None, ownership=None, grace=60.0, replay_after=0.0):
        from karpenter_tpu.cloudprovider.simulated import (
            SimCloudAPI,
            SimulatedCloudProvider,
        )
        from karpenter_tpu.controllers.garbage_collection import (
            GC_POLL_KEY,
            GarbageCollectionController,
        )
        from karpenter_tpu.controllers.termination import TerminationController

        cluster = Cluster()
        api = SimCloudAPI()
        provider = SimulatedCloudProvider(api)
        termination = TerminationController(cluster, provider, start_queue=False)
        gc = GarbageCollectionController(
            cluster, provider, journal=journal, termination=termination,
            ownership=ownership, gc_interval=5.0, grace_period=grace,
            replay_after=replay_after,
        )
        cluster.create("provisioners", make_provisioner(name="prov-a"))
        return cluster, api, provider, gc, GC_POLL_KEY

    def _launch(self, provider, token=""):
        c, catalog = constraints_for(provider)
        return provider.create(
            NodeRequest(template=c, instance_type_options=catalog,
                        launch_token=token)
        )

    def test_sweep_adopts_journaled_orphan(self):
        journal = MemoryLaunchJournal(clock=lambda: 0.0)
        cluster, api, provider, gc, key = self._env(journal=journal)
        journal.record_intent("tok-1", "prov-a")
        node = self._launch(provider, "tok-1")
        assert gc.reconcile(key) == 5.0  # self-rescheduling poll
        assert gc.adopted == 1
        assert cluster.try_get("nodes", node.metadata.name, namespace="") is not None
        assert journal.unresolved() == []

    def test_sweep_reaps_unjournaled_leak_past_grace(self):
        cluster, api, provider, gc, key = self._env(grace=0.0)
        self._launch(provider)  # token-less, no journal, no Node
        gc.reconcile(key)
        assert gc.leaks_terminated == 1
        live = [i for i in api.instances.values() if i.state != "terminated"]
        assert live == []
        # and the reap is idempotent: a second sweep finds nothing
        gc.reconcile(key)
        assert gc.leaks_terminated == 1

    def test_young_instance_spared_by_grace(self):
        cluster, api, provider, gc, key = self._env(grace=3600.0)
        self._launch(provider)
        gc.reconcile(key)
        assert gc.leaks_terminated == 0
        assert any(i.state != "terminated" for i in api.instances.values())

    def test_tracked_instance_never_touched(self):
        cluster, api, provider, gc, key = self._env(grace=0.0)
        node = self._launch(provider, "tok-live")
        cluster.create("nodes", node)
        gc.reconcile(key)
        assert gc.leaks_terminated == 0 and gc.adopted == 0

    def test_journaled_instance_not_reaped_while_entry_pending(self):
        """An instance whose journal entry is still inside the replay
        grace must not be reaped as a leak — the adoption ladder owns it."""
        journal = MemoryLaunchJournal()
        cluster, api, provider, gc, key = self._env(
            journal=journal, grace=0.0, replay_after=3600.0,
        )
        journal.record_intent("tok-wait", "prov-a")
        self._launch(provider, "tok-wait")
        gc.reconcile(key)
        assert gc.leaks_terminated == 0
        assert journal.get("tok-wait") is not None

    def test_shard_routing_skips_foreign_entries(self):
        class OwnNothing:
            def owns(self, key):
                return False

        journal = MemoryLaunchJournal(clock=lambda: 0.0)
        cluster, api, provider, gc, key = self._env(
            journal=journal, ownership=OwnNothing(), grace=0.0,
        )
        journal.record_intent("tok-1", "prov-a")
        self._launch(provider, "tok-1")
        self._launch(provider)  # unjournaled leak on the default shard
        gc.reconcile(key)
        assert gc.adopted == 0 and gc.leaks_terminated == 0

    def test_provider_without_list_surface_opts_out(self):
        from karpenter_tpu.controllers.garbage_collection import (
            GC_POLL_KEY,
            GarbageCollectionController,
        )

        class NoList:
            def list_instances(self):
                return NotImplemented

            def name(self):
                return "nolist"

        gc = GarbageCollectionController(Cluster(), NoList())
        assert gc.reconcile(GC_POLL_KEY) == gc.gc_interval
        assert gc.sweeps == 1

    def test_replay_counters_by_outcome(self):
        journal = MemoryLaunchJournal(clock=lambda: 0.0)
        cluster, api, provider, gc, key = self._env(journal=journal)
        journal.record_intent("ghost", "prov-a")  # never launched
        journal.record_intent("tok-live", "prov-a")
        node = self._launch(provider, "tok-live")
        cluster.create("nodes", node)  # node exists
        gc.reconcile(key)
        assert gc.replays == 2
        assert journal.unresolved() == []


# ---------------------------------------------------------------------------
# wire re-offer endpoint (fleet satellite)
# ---------------------------------------------------------------------------


class TestWireRequeue:
    def test_sim_wire_requeues_notice_across_processes(self):
        from karpenter_tpu.cloudprovider.httpapi import CloudAPIServer, HttpCloudAPI
        from karpenter_tpu.cloudprovider.simulated import SimCloudAPI, SimulatedCloudProvider
        from karpenter_tpu.interruption.types import PREEMPTION, DisruptionNotice

        api = SimCloudAPI()
        server = CloudAPIServer(api).start()
        try:
            provider = SimulatedCloudProvider(HttpCloudAPI(server.url, backoff_base=0.01))
            notice = DisruptionNotice(kind=PREEMPTION, node_name="i-123",
                                      grace_period_seconds=30)
            assert provider.requeue_disruption(notice) is True
            polled = provider.poll_disruptions()
            assert [n.node_name for n in polled] == ["i-123"]
            assert polled[0].kind == PREEMPTION
        finally:
            server.stop()

    def test_gke_wire_requeues_notice_across_processes(self):
        from karpenter_tpu.cloudprovider.gke import GkeCloudProvider, SimGkeAPI
        from karpenter_tpu.cloudprovider.httpapi import GkeAPIServer, HttpGkeAPI
        from karpenter_tpu.interruption.types import MAINTENANCE, DisruptionNotice

        api = SimGkeAPI()
        server = GkeAPIServer(api).start()
        try:
            provider = GkeCloudProvider(api=HttpGkeAPI(server.url, backoff_base=0.01))
            notice = DisruptionNotice(kind=MAINTENANCE, node_name="gke-n-1",
                                      grace_period_seconds=60)
            assert provider.requeue_disruption(notice) is True
            assert [n.node_name for n in provider.poll_disruptions()] == ["gke-n-1"]
        finally:
            server.stop()


# ---------------------------------------------------------------------------
# fleet satellite: informer-watched shard keys + immediate tick
# ---------------------------------------------------------------------------


class TestWatchedShardKeys:
    def test_seeds_and_tracks_watch_events(self):
        from karpenter_tpu.fleet import WatchedShardKeys

        cluster = Cluster()
        cluster.create("provisioners", make_provisioner(name="pre-existing"))
        keys = WatchedShardKeys(cluster)
        assert keys.keys() == {"pre-existing"}

        changes = []
        keys.on_change = lambda: changes.append(1)
        cluster.create("provisioners", make_provisioner(name="added"))
        assert keys.keys() == {"pre-existing", "added"}
        assert changes  # membership change notified immediately
        cluster.delete("provisioners", "added", namespace="")
        assert keys.keys() == {"pre-existing"}
        assert len(changes) == 2

    def test_request_tick_wakes_the_manager_loop(self):
        from karpenter_tpu.fleet import ShardManager, WatchedShardKeys, build_lease_set
        import tempfile

        cluster = Cluster()
        path = tempfile.mktemp(prefix="karpenter-shard-")
        leases = build_lease_set(path, identity="r1", duration=30.0)
        keys = WatchedShardKeys(cluster)
        mgr = ShardManager(leases, keys_fn=keys.keys, renew_interval=3600.0)
        keys.on_change = mgr.request_tick
        mgr.start()
        try:
            # renew interval is an hour: only the watch-driven wake can
            # claim the new shard inside the assertion window
            cluster.create("provisioners", make_provisioner(name="hot-add"))
            deadline = time.time() + 5
            while time.time() < deadline and not mgr.owns("hot-add"):
                time.sleep(0.02)
            assert mgr.owns("hot-add")
        finally:
            mgr.stop()

    def test_build_runtime_uses_watched_keys(self, tmp_path):
        from karpenter_tpu.main import build_runtime
        from karpenter_tpu.options import Options

        rt = build_runtime(
            Options(shard_lease=str(tmp_path / "leases")),
            cluster=Cluster(),
            start_workers=False,
        )
        try:
            rt.cluster.create("provisioners", make_provisioner(name="p1"))
            rt.ownership.tick()
            assert rt.ownership.owns("p1")
        finally:
            rt.stop()


# ---------------------------------------------------------------------------
# crash chaos: the launch path dies between its writes
# ---------------------------------------------------------------------------


class TestLaunchCrashChaos:
    def test_crash_proxy_is_one_shot_and_observable(self):
        from karpenter_tpu.api.objects import Node, ObjectMeta
        from karpenter_tpu.testing.chaos import LaunchCrash, LaunchCrashCluster

        cluster = Cluster()
        proxy = LaunchCrashCluster(cluster)
        proxy.arm("before_node_write")
        with pytest.raises(LaunchCrash):
            proxy.create("nodes", Node(metadata=ObjectMeta(name="n-1", namespace="")))
        assert proxy.crashed.is_set()
        assert proxy.crashes == {"before_node_write": 1}
        # one-shot: the node was NOT written, and the next create passes
        assert cluster.try_get("nodes", "n-1", namespace="") is None
        proxy.create("nodes", Node(metadata=ObjectMeta(name="n-2", namespace="")))
        assert cluster.try_get("nodes", "n-2", namespace="") is not None

    def test_arm_unknown_point_rejected(self):
        from karpenter_tpu.testing.chaos import LaunchCrashCluster

        with pytest.raises(ValueError):
            LaunchCrashCluster(Cluster()).arm("mid_nowhere")

    def _runtime(self, cluster, api, journal_path, gc_interval=0.2,
                 replay_after=0.2):
        from karpenter_tpu.cloudprovider.simulated import SimulatedCloudProvider
        from karpenter_tpu.main import build_runtime
        from karpenter_tpu.options import Options

        rt = build_runtime(
            Options(
                launch_journal=journal_path,
                gc_interval=gc_interval,
                gc_grace_period=3600.0,
            ),
            cluster=cluster,
            cloud_provider=SimulatedCloudProvider(api=api),
        )
        rt.garbage_collection.replay_after = replay_after
        return rt

    def test_crash_before_node_write_adopted_by_successor(self):
        """END-TO-END: replica 1 dies between the cloud create and the
        Node write; replica 2 (same cluster, same journal file, same
        cloud) adopts the orphan within its GC cadence and the pods
        eventually bind — zero leaks, zero duplicate instances per
        token."""
        import tempfile

        from karpenter_tpu.cloudprovider.simulated import SimCloudAPI
        from karpenter_tpu.testing.chaos import LaunchCrashCluster

        cluster = Cluster()
        api = SimCloudAPI()
        journal_path = tempfile.mktemp(prefix="karpenter-journal-")
        proxy = LaunchCrashCluster(cluster)
        # the victim's OWN GC must never run the replay ladder (the
        # process is "dead" the moment the crash fires, but stop() takes
        # real time under load) — recovery is the SUCCESSOR's job here
        rt1 = self._runtime(proxy, api, journal_path, replay_after=3600.0)
        rt1.manager.start()
        try:
            cluster.create("provisioners", make_provisioner(name="prov-a"))
            deadline = time.time() + 10
            while time.time() < deadline and "prov-a" not in rt1.provisioning.workers:
                time.sleep(0.02)
            rt1.provisioning.workers["prov-a"].batcher.idle_duration = 0.05
            proxy.arm("before_node_write")
            cluster.create("pods", make_pod(name="victim", requests={"cpu": "0.5"}))
            assert proxy.crashed.wait(timeout=30), "crash never fired"
        finally:
            rt1.stop()

        # the wreck: an instance exists, journaled, with no Node
        assert len(api.instances) == 1
        assert cluster.nodes() == []
        from karpenter_tpu.launch import FileLaunchJournal

        assert len(FileLaunchJournal(journal_path).unresolved()) == 1

        rt2 = self._runtime(cluster, api, journal_path)
        rt2.manager.start()
        try:
            # the pod predates rt2's watches (a real apiserver's informer
            # relist would deliver it); nudge selection the way the relist
            # would so the successor's launch path picks it up
            rt2.manager.enqueue("selection", ("victim", "default"))
            instance_id = next(iter(api.instances))
            # the Node write, the counter bump, and the journal resolve land
            # a few ms apart inside one replay — poll for all three, not
            # just the first
            deadline = time.time() + 30
            while time.time() < deadline:
                if (
                    cluster.try_get("nodes", instance_id, namespace="") is not None
                    and rt2.garbage_collection.adopted >= 1
                    and FileLaunchJournal(journal_path).unresolved() == []
                ):
                    break
                time.sleep(0.05)
            adopted = cluster.try_get("nodes", instance_id, namespace="")
            assert adopted is not None, "orphan never adopted"
            assert adopted.metadata.annotations["karpenter.sh/adopted"] == "true"
            assert rt2.garbage_collection.adopted == 1
            assert FileLaunchJournal(journal_path).unresolved() == []
            # and the pod still gets capacity (replica 2's own launch path)
            deadline = time.time() + 30
            while time.time() < deadline:
                pod = cluster.try_get("pods", "victim", namespace="default")
                if pod is not None and pod.spec.node_name:
                    break
                time.sleep(0.05)
            pod = cluster.try_get("pods", "victim", namespace="default")
            assert pod is not None and pod.spec.node_name
            # no token launched twice
            tokens = [i.launch_token for i in api.instances.values() if i.launch_token]
            assert len(tokens) == len(set(tokens))
        finally:
            rt2.stop()

    def test_crash_after_node_write_resolves_without_duplicate(self):
        """Replica 1 dies between the Node write and the bind: the Node
        already tracks the instance, so recovery must RESOLVE (not adopt a
        second node, not reap the instance)."""
        import tempfile

        from karpenter_tpu.cloudprovider.simulated import SimCloudAPI
        from karpenter_tpu.testing.chaos import LaunchCrashCluster

        cluster = Cluster()
        api = SimCloudAPI()
        journal_path = tempfile.mktemp(prefix="karpenter-journal-")
        proxy = LaunchCrashCluster(cluster)
        # victim GC disabled from the ladder (see the sibling test): the
        # resolve under test must come from replica 2's recovery
        rt1 = self._runtime(proxy, api, journal_path, replay_after=3600.0)
        rt1.manager.start()
        try:
            cluster.create("provisioners", make_provisioner(name="prov-a"))
            deadline = time.time() + 10
            while time.time() < deadline and "prov-a" not in rt1.provisioning.workers:
                time.sleep(0.02)
            rt1.provisioning.workers["prov-a"].batcher.idle_duration = 0.05
            proxy.arm("after_node_write")
            cluster.create("pods", make_pod(name="victim-2", requests={"cpu": "0.5"}))
            assert proxy.crashed.wait(timeout=30), "crash never fired"
        finally:
            rt1.stop()

        assert len(cluster.nodes()) == 1  # the Node write landed
        from karpenter_tpu.launch import FileLaunchJournal

        assert len(FileLaunchJournal(journal_path).unresolved()) == 1

        rt2 = self._runtime(cluster, api, journal_path)
        rt2.manager.start()
        try:
            deadline = time.time() + 30
            while time.time() < deadline and FileLaunchJournal(journal_path).unresolved():
                time.sleep(0.05)
            assert FileLaunchJournal(journal_path).unresolved() == []
            assert rt2.garbage_collection.adopted == 0
            assert rt2.garbage_collection.leaks_terminated == 0
            assert len(cluster.nodes()) >= 1  # original node untouched
        finally:
            rt2.stop()
