"""Shim: factories are a first-class package deliverable (the reference ships
pkg/test); tests import them from here for brevity."""
from karpenter_tpu.testing.factories import *  # noqa: F401,F403
from karpenter_tpu.testing.factories import (  # noqa: F401
    hostname_spread,
    make_daemonset,
    make_node,
    make_pdb,
    make_pod,
    make_provisioner,
    make_pv,
    make_pvc,
    make_storage_class,
    zone_spread,
)
