"""Wire-level behavior of the cloud-API HTTP double: pagination, throttling
with Retry-After, 5xx retry/backoff, error-code classification, and the
fleet error body (reference: the provider drives a real SDK over HTTP
against behavior-programmable fakes — aws/fake/ec2api.go:35-137)."""

import pytest

from karpenter_tpu.cloudprovider.httpapi import (
    CloudAPIServer,
    HttpCloudAPI,
    ThrottlingError,
)
from karpenter_tpu.cloudprovider.simulated import (
    CloudAPIError,
    InsufficientCapacityError,
    SimCloudAPI,
    SimulatedCloudProvider,
)


@pytest.fixture()
def wire():
    api = SimCloudAPI()
    server = CloudAPIServer(api).start()  # default page size: 3 (paginates)
    client = HttpCloudAPI(server.url, backoff_base=0.01)
    yield api, server, client
    server.stop()


class TestPagination:
    def test_instance_types_span_pages(self, wire):
        api, server, client = wire
        got = client.describe_instance_types()
        assert [i.name for i in got] == [i.name for i in api.catalog]
        # 11 catalog entries at page size 3 → 4 paged GETs, one logical call
        assert api.calls["describe_instance_types"] == 4

    def test_explicit_page_size(self, wire):
        api, server, client = wire
        client.page_size = 100
        got = client.describe_instance_types()
        assert len(got) == len(api.catalog)
        assert api.calls["describe_instance_types"] == 1


class TestRetries:
    def test_throttle_retried_honoring_retry_after(self, wire):
        api, server, client = wire
        api.inject_error("describe_subnets", ThrottlingError(retry_after=0.01))
        subnets = client.describe_subnets({"purpose": "nodes"})
        assert len(subnets) == 3
        assert client.retries == 1

    def test_injected_5xx_retried_with_backoff(self, wire):
        api, server, client = wire
        api.inject_error("describe_security_groups", CloudAPIError("control plane down"))
        groups = client.describe_security_groups({"purpose": "nodes"})
        assert [g.id for g in groups] == ["sg-nodes"]
        assert client.retries == 1

    def test_retries_exhausted_raises_typed_error(self, wire):
        api, server, client = wire
        for _ in range(10):
            api.inject_error("describe_subnets", CloudAPIError("still down"))
        with pytest.raises(CloudAPIError):
            client.describe_subnets({})
        assert client.retries == client.max_attempts - 1

    def test_ice_not_retried_maps_to_typed_error(self, wire):
        api, server, client = wire
        api.inject_error("create_fleet", InsufficientCapacityError("no pool"))
        with pytest.raises(InsufficientCapacityError):
            client.create_fleet("on-demand", [("lt", "sim.gp-4x", "sim-zone-1a")])
        assert client.retries == 0

    def test_unknown_route_is_client_error(self, wire):
        api, server, client = wire
        with pytest.raises(CloudAPIError):
            client._request("GET", "/v1/no-such-thing")
        assert client.retries == 0


class TestFleetWire:
    def test_retried_fleet_post_does_not_double_launch(self, wire):
        """A lost response to the non-idempotent fleet POST must not leak
        an untracked instance: the client token replays the recorded
        answer on retry (the CreateFleet ClientToken contract)."""
        import json as _json
        import urllib.request

        api, server, client = wire
        body = _json.dumps({
            "capacityType": "on-demand",
            "overrides": [{"launchTemplate": "lt", "instanceType": "sim.gp-4x",
                           "zone": "sim-zone-1a"}],
            "clientToken": "tok-1",
        }).encode()

        def post():
            req = urllib.request.Request(
                server.url + "/v1/fleet", data=body, method="POST",
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req) as r:
                return _json.loads(r.read())

        first = post()
        second = post()  # the "retry after a lost response"
        assert first == second
        assert len(api.instances) == 1

    def test_blank_tag_value_is_exists_wildcard_over_the_wire(self, wire):
        """selector value "" means key-exists; parse_qs must not drop the
        blank pair or the wire filter silently loosens to match-all."""
        api, server, client = wire
        named = client.describe_subnets({"Name": ""})
        assert {s.id for s in named} == {"subnet-1", "subnet-2", "subnet-3"}
        none = client.describe_security_groups({"Name": ""})
        assert none == []  # no security group carries a Name tag

    def test_missing_field_is_400_not_retried(self, wire):
        api, server, client = wire
        with pytest.raises(CloudAPIError):
            client._request("POST", "/v1/fleet", {"overrides": []})
        assert client.retries == 0
        assert api.calls.get("create_fleet") is None

    def test_per_override_ice_errors_cross_the_wire(self, wire):
        api, server, client = wire
        api.insufficient_capacity_pools.add(("on-demand", "sim.gp-4x", "sim-zone-1a"))
        instances, errors = client.create_fleet(
            "on-demand",
            [("lt", "sim.gp-4x", "sim-zone-1a"), ("lt", "sim.gp-8x", "sim-zone-1b")],
        )
        assert errors == [("on-demand", "sim.gp-4x", "sim-zone-1a")]
        assert len(instances) == 1 and instances[0].instance_type == "sim.gp-8x"
        # the launch is real server-side state, visible to later describes
        assert [i.id for i in client.describe_instances([instances[0].id])] == [
            instances[0].id
        ]

    def test_terminate_round_trip(self, wire):
        api, server, client = wire
        instances, _ = client.create_fleet("on-demand", [("lt", "sim.gp-2x", "sim-zone-1b")])
        client.terminate_instances([instances[0].id])
        assert api.instances[instances[0].id].state == "terminated"

    def test_launch_template_name_quoting(self, wire):
        api, server, client = wire
        name = "karpenter/lt: weird name+chars"
        assert client.ensure_launch_template(name, {"k": "v"}) == name
        assert name in api.launch_templates
        client.delete_launch_template(name)
        assert name not in api.launch_templates


class TestGkeWire:
    @pytest.fixture()
    def gke_wire(self):
        from karpenter_tpu.cloudprovider.gke import SimGkeAPI
        from karpenter_tpu.cloudprovider.httpapi import GkeAPIServer, HttpGkeAPI

        api = SimGkeAPI()
        server = GkeAPIServer(api).start()
        client = HttpGkeAPI(server.url, backoff_base=0.01)
        yield api, server, client
        server.stop()

    def test_node_pool_round_trip(self, gke_wire):
        api, server, client = gke_wire
        pool = client.create_node_pool("ct5lp-hightpu-4t", "us-central1-a", False, 2)
        assert len(pool.instances) == 2
        assert pool.name in api.node_pools
        client.delete_instance(pool.instances[0].name)
        assert len(api.node_pools[pool.name].instances) == 1
        client.delete_node_pool(pool.name)
        assert pool.name not in api.node_pools

    def test_stockout_crosses_as_409_and_classifies(self, gke_wire):
        from karpenter_tpu.cloudprovider.gke import GkeStockoutError

        api, server, client = gke_wire
        api.set_stockout("ct5lp-hightpu-4t", "us-central1-a")
        with pytest.raises(GkeStockoutError):
            client.create_node_pool("ct5lp-hightpu-4t", "us-central1-a", False, 4)
        assert client.retries == 0  # a stockout is not transport — never retried

    def test_bad_request_crosses_as_400(self, gke_wire):
        from karpenter_tpu.cloudprovider.gke import GkeApiError

        api, server, client = gke_wire
        with pytest.raises(GkeApiError):
            client.create_node_pool("ct5lp-hightpu-4t", "us-central1-a", False, 0)
        assert client.retries == 0

    def test_multi_host_podslice_atomic_over_the_wire(self, gke_wire):
        """A count=N pool crosses the wire as one atomic creation: N
        instances in the response, all server-side; a stocked-out slice
        yields zero instances, never a partial pool."""
        from karpenter_tpu.cloudprovider.gke import GkeStockoutError

        api, server, client = gke_wire
        pool = client.create_node_pool(
            "ct5lp-hightpu-4t", "us-central1-a", False, 4, tpu_topology="4x4"
        )
        assert len(pool.instances) == 4
        assert pool.tpu_topology == "4x4"
        assert all(i.node_pool == pool.name for i in pool.instances)
        assert len(api.node_pools[pool.name].instances) == 4
        api.set_stockout("ct5lp-hightpu-4t", "us-central1-b")
        with pytest.raises(GkeStockoutError):
            client.create_node_pool("ct5lp-hightpu-4t", "us-central1-b", False, 4)
        assert len(api.node_pools) == 1  # no partial second pool

    def test_provider_over_wire_stockout_marks_ice(self, gke_wire):
        """End-to-end: GkeCloudProvider over the HTTP client — a stockout
        crossing the wire still drives the ICE/unavailable-offerings path."""
        from karpenter_tpu.api.provisioner import Constraints
        from karpenter_tpu.api.requirements import Requirements
        from karpenter_tpu.cloudprovider.gke import GkeCloudProvider
        from karpenter_tpu.cloudprovider.requirements import catalog_requirements
        from karpenter_tpu.cloudprovider.types import NodeRequest

        api, server, client = gke_wire
        provider = GkeCloudProvider(api=client)
        c = Constraints(requirements=Requirements.new())
        provider.default(c)
        catalog = provider.get_instance_types()
        c.requirements = c.requirements.merge(catalog_requirements(catalog))
        node = provider.create(NodeRequest(template=c, instance_type_options=catalog))
        assert node.metadata.name.startswith("gke-")


class TestRegistryWiring:
    def test_http_backed_providers_constructible_by_name(self, wire, monkeypatch):
        from karpenter_tpu.cloudprovider.registry import new_cloud_provider

        api, server, client = wire
        provider = new_cloud_provider("simulated-http", url=server.url)
        assert provider.name() == "simulated"
        assert len(provider.get_instance_types()) == len(api.catalog) - 1  # metal filtered
        monkeypatch.delenv("KARPENTER_CLOUD_API_URL", raising=False)
        with pytest.raises(ValueError):
            new_cloud_provider("simulated-http")  # no URL anywhere


class TestRuntimeOverWire:
    def test_full_control_plane_provisions_over_the_wire(self, wire, monkeypatch):
        """The whole runtime — selection → batcher → solve → launch → bind —
        with every cloud control-plane call crossing HTTP: the provider is
        constructed by registry NAME from the env URL, exactly as
        ``--cloud-provider=simulated-http`` would in production."""
        import time

        from karpenter_tpu.kube.client import Cluster
        from karpenter_tpu.main import build_runtime
        from karpenter_tpu.options import Options
        from tests.factories import make_pod, make_provisioner

        api, server, client = wire
        monkeypatch.setenv("KARPENTER_CLOUD_API_URL", server.url)
        cluster = Cluster()
        rt = build_runtime(
            Options(cloud_provider="simulated-http", default_solver="ffd"),
            cluster=cluster,
        )
        rt.manager.start()
        try:
            cluster.create("provisioners", make_provisioner(solver="ffd"))
            deadline = time.time() + 10
            while time.time() < deadline and not rt.provisioning.workers:
                time.sleep(0.02)
            assert rt.provisioning.workers, "no provisioner worker after 10s"
            for w in rt.provisioning.workers.values():
                w.batcher.idle_duration = 0.1
            for i in range(4):
                cluster.create("pods", make_pod(name=f"wire-{i}", requests={"cpu": "1"}))
            deadline = time.time() + 30
            while time.time() < deadline:
                pods = [cluster.get("pods", f"wire-{i}") for i in range(4)]
                if all(p.spec.node_name for p in pods):
                    break
                time.sleep(0.1)
            pods = [cluster.get("pods", f"wire-{i}") for i in range(4)]
            assert all(p.spec.node_name for p in pods), [
                p.spec.node_name for p in pods
            ]
            # the launched capacity exists server-side, reached over HTTP
            assert api.calls.get("create_fleet", 0) >= 1
            assert any(i.state == "running" for i in api.instances.values())
        finally:
            rt.stop()


class TestProviderOverWire:
    def test_provider_survives_transient_throttle_during_launch(self, wire):
        """End-to-end: a provider whose control plane throttles mid-launch
        still creates the node — the wire client absorbs the 429."""
        from karpenter_tpu.api.provisioner import Constraints
        from karpenter_tpu.api.requirements import Requirements
        from karpenter_tpu.cloudprovider.requirements import catalog_requirements
        from karpenter_tpu.cloudprovider.types import NodeRequest

        api, server, client = wire
        provider = SimulatedCloudProvider(client)
        c = Constraints(requirements=Requirements.new())
        provider.default(c)
        catalog = provider.get_instance_types()
        c.requirements = c.requirements.merge(catalog_requirements(catalog))
        api.inject_error("create_fleet", ThrottlingError(retry_after=0.01))
        node = provider.create(
            NodeRequest(template=c, instance_type_options=catalog)
        )
        assert node.metadata.name.startswith("i-")
        assert client.retries >= 1
