"""The reference ENFORCES its scheduler benchmark floor — batches >100 pods
must clear 250 pods/sec or the benchmark fails
(scheduling_benchmark_test.go:47,151-155). Same contract here, enforced in
the CPU test suite via the native packer path (generous margin so a loaded
CI box doesn't flake; the real numbers are 2-3 orders above the floor)."""

import random
import time

from karpenter_tpu.cloudprovider.fake import instance_types
from karpenter_tpu.cloudprovider.requirements import catalog_requirements
from karpenter_tpu.kube.client import Cluster
from karpenter_tpu.scheduling.scheduler import Scheduler
from karpenter_tpu.testing import diverse_pods, make_provisioner

FLOOR_PODS_PER_SEC = 250.0


def test_scheduler_clears_the_reference_floor():
    catalog = instance_types(400)
    provisioner = make_provisioner(solver="tpu")
    c = provisioner.spec.constraints
    c.requirements = c.requirements.merge(catalog_requirements(catalog))
    pods = diverse_pods(500, random.Random(42))
    scheduler = Scheduler(Cluster(), rng=random.Random(1))
    scheduler.solve(provisioner, catalog, pods)  # warmup/compile
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        nodes = scheduler.solve(provisioner, catalog, pods)
        best = min(best, time.perf_counter() - t0)
    scheduled = sum(len(n.pods) for n in nodes)
    assert scheduled > 100
    rate = scheduled / best
    assert rate >= FLOOR_PODS_PER_SEC, (
        f"{rate:.0f} pods/sec is below the reference's enforced "
        f"{FLOOR_PODS_PER_SEC} pods/sec floor"
    )


def test_bench_legs_emit_oracle_certification():
    """Every published bench figure must carry oracle certification
    (VERDICT r4 #7): no 'Failed to schedule N pods' line ships without an
    unschedulable_expected/unexplained verdict beside it."""
    import bench  # repo root is on sys.path via conftest

    p = bench.bench_pipelined(200, streams=2, iters=1)
    assert p["unexplained"] == 0
    assert "unschedulable_expected" in p
    r = bench.bench_config(1, 1)
    assert r["unexplained"] == 0
    assert "unschedulable_expected" in r
