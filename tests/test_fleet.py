"""Fleet-scale HA tests (docs/fleet.md): the keyed lease set, the shard
manager's claim/renew/handback/takeover protocol, the provisioning and
interruption ownership guards, the duplicate-launch/bind guards, and the
replica-kill chaos e2e — three live replicas over one cluster, the owner of
a mid-storm shard crashed, every pod still binds exactly once."""

import os
import tempfile
import threading
import time

import pytest

from karpenter_tpu.fleet import DEFAULT_SHARD, ShardManager, build_lease_set, rendezvous_owner
from karpenter_tpu.kube.client import Cluster
from karpenter_tpu.utils.lease import FileLease, FileLeaseSet, LeaderElector
from tests.factories import make_pod, make_provisioner

pytestmark = pytest.mark.fleet


def _lease_path(tmp_path):
    return str(tmp_path / "shards.lease")


class TestFileLeaseSatellites:
    def test_holder_reads_under_the_flock(self, tmp_path, monkeypatch):
        """holder() must serialize against writers — regression for the
        torn-read satellite: it now enters the same flock as acquire/renew."""
        path = str(tmp_path / "lease")
        lease = FileLease(path, identity="a", duration=10)
        assert lease.try_acquire()
        entered = []
        orig = FileLease._locked

        def spying_locked(self):
            entered.append(True)
            return orig(self)

        monkeypatch.setattr(FileLease, "_locked", spying_locked)
        assert lease.holder() == "a"
        assert entered, "holder() bypassed the flock"

    def test_stale_tmp_files_swept_on_acquire(self, tmp_path):
        path = str(tmp_path / "lease")
        stale = f"{path}.dead-writer.tmp"
        with open(stale, "w") as f:
            f.write("{")
        # age it past the sweep horizon
        old = time.time() - 3600
        os.utime(stale, (old, old))
        fresh = f"{path}.live-writer.tmp"
        with open(fresh, "w") as f:
            f.write("{")
        FileLease(path, identity="a", duration=10).try_acquire()
        assert not os.path.exists(stale), "stale tmp survived the sweep"
        assert os.path.exists(fresh), "a fresh (possibly mid-RMW) tmp was removed"


class TestLeaderElectorAtMostOnce:
    def test_on_lost_fires_once_per_epoch(self, tmp_path):
        calls = []
        elector = LeaderElector(
            FileLease(str(tmp_path / "l"), identity="x"),
            on_lost=lambda: calls.append(1),
        )
        elector._acquired()
        # the failed-renew branch and the raising-backend branch race: both
        # call _fire_lost for the same epoch — only one may fire
        elector._fire_lost()
        elector._fire_lost()
        assert calls == [1]
        # a fresh epoch fires again
        elector._acquired()
        elector._fire_lost()
        assert calls == [1, 1]

    def test_clean_stop_consumes_the_epoch_without_firing(self, tmp_path):
        calls = []
        lease = FileLease(str(tmp_path / "l"), identity="x")
        elector = LeaderElector(lease, on_lost=lambda: calls.append(1))
        assert lease.try_acquire()
        elector._acquired()
        elector.stop()
        # a straggling elector-thread branch observing the loss afterwards
        elector._fire_lost()
        assert calls == []

    def test_raising_backend_fires_once_and_thread_survives(self, tmp_path):
        calls = []

        class RaisingLease:
            def __init__(self):
                self.acquired = threading.Event()
                self.raising = False

            def try_acquire(self):
                # while the backend is down nothing re-acquires: a fresh
                # acquisition would legitimately start a NEW epoch
                return not self.raising

            def renew(self):
                if self.raising:
                    raise RuntimeError("backend down")
                return True

            def release(self):
                pass

        lease = RaisingLease()
        elector = LeaderElector(
            lease, renew_interval=0.02, on_lost=lambda: calls.append(1)
        )
        elector.start()
        deadline = time.time() + 5
        while time.time() < deadline and not elector.is_leader:
            time.sleep(0.01)
        assert elector.is_leader
        lease.raising = True
        deadline = time.time() + 5
        while time.time() < deadline and not calls:
            time.sleep(0.01)
        time.sleep(0.1)  # more raising renew ticks pass
        assert calls == [1], "on_lost fired more than once for one epoch"
        elector.stop()


class TestFileLeaseSet:
    def test_acquire_renew_release_roundtrip(self, tmp_path):
        now = [0.0]
        a = FileLeaseSet(_lease_path(tmp_path), identity="a", duration=10, clock=lambda: now[0])
        b = FileLeaseSet(_lease_path(tmp_path), identity="b", duration=10, clock=lambda: now[0])
        assert a.try_acquire("p0")
        assert not b.try_acquire("p0")
        assert a.holder("p0") == "a"
        assert a.renew_many(["p0"]) == {"p0"}
        a.release("p0")
        assert b.try_acquire("p0")

    def test_expired_hold_is_taken_over(self, tmp_path):
        now = [0.0]
        a = FileLeaseSet(_lease_path(tmp_path), identity="a", duration=10, clock=lambda: now[0])
        b = FileLeaseSet(_lease_path(tmp_path), identity="b", duration=10, clock=lambda: now[0])
        assert a.try_acquire("p0")
        now[0] = 11.0
        assert a.holder("p0") is None
        assert b.try_acquire("p0")
        # the old holder's renew must now fail — takeover won
        assert a.renew_many(["p0"]) == set()

    def test_membership_heartbeat_and_expiry(self, tmp_path):
        now = [0.0]
        a = FileLeaseSet(_lease_path(tmp_path), identity="a", duration=10, clock=lambda: now[0])
        b = FileLeaseSet(_lease_path(tmp_path), identity="b", duration=10, clock=lambda: now[0])
        assert a.heartbeat() == {"a"}
        assert b.heartbeat() == {"a", "b"}
        now[0] = 11.0
        assert b.heartbeat() == {"b"}  # a stopped heartbeating and expired
        b.resign()
        now[0] = 12.0
        assert a.heartbeat() == {"a"}

    def test_renew_many_is_one_critical_section(self, tmp_path):
        a = FileLeaseSet(_lease_path(tmp_path), identity="a", duration=10)
        keys = [f"p{i}" for i in range(20)]
        for k in keys:
            assert a.try_acquire(k)
        assert a.renew_many(keys) == set(keys)
        assert set(a.snapshot()) == set(keys)
        a.release_all()
        assert a.snapshot() == {}


class TestShardManager:
    def _manager(self, path, ident, keys, now, **kw):
        return ShardManager(
            FileLeaseSet(path, identity=ident, duration=10, clock=lambda: now[0]),
            keys_fn=lambda: keys,
            **kw,
        )

    def test_single_replica_owns_everything(self, tmp_path):
        now = [0.0]
        m = self._manager(_lease_path(tmp_path), "a", ["p0", "p1"], now)
        m.tick()
        assert m.owned() == {"p0", "p1", DEFAULT_SHARD}

    def test_fleet_partitions_disjoint_and_complete(self, tmp_path):
        now = [0.0]
        keys = [f"p{i}" for i in range(16)]
        managers = [
            self._manager(_lease_path(tmp_path), ident, keys, now)
            for ident in ("a", "b", "c")
        ]
        for _ in range(4):
            for m in managers:
                m.tick()
        owned = [m.owned() for m in managers]
        union = set().union(*owned)
        assert union == set(keys) | {DEFAULT_SHARD}
        for i in range(3):
            for j in range(i + 1, 3):
                assert not (owned[i] & owned[j]), "two replicas own one shard"
        assert all(o for o in owned), "a live replica ended up with zero shards"

    def test_rendezvous_is_deterministic_and_minimal(self):
        members = ["a", "b", "c"]
        keys = [f"p{i}" for i in range(64)]
        before = {k: rendezvous_owner(k, members) for k in keys}
        # removing b re-homes ONLY b's keys
        after = {k: rendezvous_owner(k, ["a", "c"]) for k in keys}
        for k in keys:
            if before[k] != "b":
                assert after[k] == before[k]

    def test_crash_takeover_within_two_lease_durations(self, tmp_path):
        now = [0.0]
        keys = [f"p{i}" for i in range(8)]
        ma = self._manager(_lease_path(tmp_path), "a", keys, now)
        mb = self._manager(_lease_path(tmp_path), "b", keys, now)
        for _ in range(3):
            ma.tick()
            mb.tick()
        dead_shards = mb.owned()
        assert dead_shards
        mb.crash()  # no release: holds must EXPIRE
        # within one lease duration the survivor cannot steal (holds live)
        now[0] += 5.0
        ma.tick()
        assert not (ma.owned() & dead_shards)
        # past expiry (< 2 durations total) the survivor takes everything
        now[0] += 6.0
        ma.tick()
        ma.tick()
        assert ma.owned() == set(keys) | {DEFAULT_SHARD}

    def test_on_lost_fires_when_renewal_fails(self, tmp_path):
        now = [0.0]
        lost = []
        ma = self._manager(_lease_path(tmp_path), "a", ["p0"], now, on_lost=lost.append)
        ma.tick()
        assert ma.owns("p0")
        # simulate a long stall: everything expired, b took the shard over
        now[0] = 11.0
        b = FileLeaseSet(_lease_path(tmp_path), identity="b", duration=10, clock=lambda: now[0])
        assert b.try_acquire("p0")
        ma.tick()
        assert "p0" in lost
        assert not ma.owns("p0")

    def test_handback_to_joining_replica(self, tmp_path):
        now = [0.0]
        keys = [f"p{i}" for i in range(12)]
        ma = self._manager(_lease_path(tmp_path), "a", keys, now)
        ma.tick()
        assert len(ma.owned()) == 13  # everything, while alone
        mb = self._manager(_lease_path(tmp_path), "b", keys, now)
        for _ in range(3):
            mb.tick()
            ma.tick()
        assert mb.owned(), "joining replica never received a share"
        assert not (ma.owned() & mb.owned())

    def test_renew_interval_derives_from_duration(self, tmp_path):
        """A lease duration shorter than the default renew cadence must
        pull the cadence down with it — renewing 3s leases every 5s would
        expire every hold between ticks (perpetual churn)."""
        now = [0.0]
        short = ShardManager(
            FileLeaseSet(_lease_path(tmp_path), identity="a", duration=3, clock=lambda: now[0]),
            keys_fn=lambda: ["p0"],
        )
        assert short.renew_interval == pytest.approx(1.0)
        long = ShardManager(
            FileLeaseSet(_lease_path(tmp_path), identity="b", duration=60, clock=lambda: now[0]),
            keys_fn=lambda: ["p0"],
        )
        assert long.renew_interval == pytest.approx(5.0)

    def test_steal_from_wedged_winner_does_not_oscillate(self, tmp_path):
        """A winner that heartbeats but never claims (wedged watch) loses
        its keys to a loser after one tick of grace — and the loser KEEPS
        them: handing back to the same wedged winner would re-orphan the
        shard every other tick."""
        now = [0.0]
        keys = [f"p{i}" for i in range(8)]
        ma = self._manager(_lease_path(tmp_path), "a", keys, now)
        # "b" is wedged: it heartbeats membership but never runs a claim
        # tick, so rendezvous assigns it keys nobody ever takes
        b_leases = FileLeaseSet(_lease_path(tmp_path), identity="b", duration=10, clock=lambda: now[0])
        for _ in range(4):
            b_leases.heartbeat()
            ma.tick()
        # a owns EVERYTHING despite b being a live member
        assert ma.owned() == set(keys) | {DEFAULT_SHARD}
        stolen = {
            k for k in ma.owned() if rendezvous_owner(k, {"a", "b"}) == "b"
        }
        assert stolen, "rendezvous never assigned b anything (test vacuous)"
        # stability: further ticks with b still wedged change nothing
        for _ in range(4):
            b_leases.heartbeat()
            ma.tick()
            assert ma.owned() == set(keys) | {DEFAULT_SHARD}
        # b dies entirely → nothing to hand back to; a keeps serving
        now[0] += 11.0
        ma.tick()
        assert ma.owned() == set(keys) | {DEFAULT_SHARD}

    def test_handback_gives_the_winner_two_full_ticks(self, tmp_path):
        """A handed-back key must not enter the releasing replica's OWN
        steal-pending set in the same tick — a merely-slow winner would
        lose it right back and _stolen_from would pin the misplacement."""
        now = [0.0]
        keys = [f"p{i}" for i in range(12)]
        ma = self._manager(_lease_path(tmp_path), "a", keys, now)
        ma.tick()  # alone: owns everything
        # b joins (heartbeat only); a hands b's rendezvous share back
        b_leases = FileLeaseSet(_lease_path(tmp_path), identity="b", duration=10, clock=lambda: now[0])
        b_leases.heartbeat()
        ma.tick()
        b_share = {
            k for k in keys + [DEFAULT_SHARD]
            if rendezvous_owner(k, {"a", "b"}) == "b"
        }
        assert b_share and not (ma.owned() & b_share)
        # ONE more a-tick while b is slow: a may mark pending but must not
        # have re-stolen yet (the winner gets two full ticks)
        ma.tick()
        assert not (ma.owned() & b_share), (
            "releasing replica re-stole a handed-back key after one tick"
        )
        # b finally claims on its first real tick
        mb = ShardManager(b_leases, keys_fn=lambda: keys)
        mb.tick()
        assert mb.owned() == b_share
        # and a's _stolen_from never pinned anything
        for _ in range(3):
            ma.tick()
            mb.tick()
        assert mb.owned() == b_share

    def test_stop_fires_on_lost_before_releasing_the_lease(self, tmp_path):
        """Shutdown ordering is the split-brain guard: the worker must be
        stopped (on_lost) BEFORE the lease releases, or a survivor could
        claim the shard while this replica's launch is still in flight."""
        now = [0.0]
        events = []
        leases = FileLeaseSet(_lease_path(tmp_path), identity="a", duration=10, clock=lambda: now[0])
        orig_release = leases.release
        leases.release = lambda key: (events.append(("release", key)), orig_release(key))
        m = ShardManager(
            leases, keys_fn=lambda: ["p0"],
            on_lost=lambda key: events.append(("on_lost", key)),
            include_default_shard=False,
        )
        m.tick()
        assert m.owns("p0")
        m.stop()
        assert events == [("on_lost", "p0"), ("release", "p0")]

    def test_deleted_key_released(self, tmp_path):
        now = [0.0]
        keys = ["p0", "p1"]
        ma = self._manager(_lease_path(tmp_path), "a", keys, now)
        ma.tick()
        assert ma.owns("p1")
        keys.remove("p1")
        ma.tick()
        assert not ma.owns("p1")
        assert ma.leases.holder("p1") is None

    def test_clean_stop_releases_and_fires_on_lost(self, tmp_path):
        now = [0.0]
        lost = []
        ma = self._manager(_lease_path(tmp_path), "a", ["p0"], now, on_lost=lost.append)
        ma.tick()
        ma.stop()
        assert "p0" in lost
        assert ma.leases.holder("p0") is None
        assert ma.owned() == set()


class TestBuildLeaseSet:
    def test_file_spec(self, tmp_path):
        ls = build_lease_set(_lease_path(tmp_path), identity="x", duration=7)
        assert isinstance(ls, FileLeaseSet)
        assert ls.identity == "x" and ls.duration == 7

    def test_kube_spec(self):
        from karpenter_tpu.kube.leader import KubeLeaseSet

        ls = build_lease_set("kube:karpenter/shards", cluster=Cluster(), identity="x")
        assert isinstance(ls, KubeLeaseSet)
        assert ls.namespace == "karpenter" and ls.prefix == "shards"

    def test_kube_member_lease_deleted_on_resign_and_stale_gc(self):
        """Member Lease names embed the per-process identity, so a
        kept-but-blanked object is permanent garbage: resign() must DELETE
        it, and a peer's tick must GC long-expired member leases from
        crashed replicas."""
        now = [0.0]
        cluster = Cluster(clock=lambda: now[0])
        a = build_lease_set("kube:shards", cluster=cluster, identity="a", duration=10)
        b = build_lease_set("kube:shards", cluster=cluster, identity="b", duration=10)
        a.heartbeat()
        b.heartbeat()
        assert len(cluster.list("leases", namespace="kube-system")) == 2
        a.resign()
        assert len(cluster.list("leases", namespace="kube-system")) == 1
        # b crashes (never resigns); once unambiguously stale a peer GCs it
        now[0] += 10 * 4 + 11
        assert a.heartbeat() == {"a"}
        names = [
            lease.metadata.name
            for lease in cluster.list("leases", namespace="kube-system")
        ]
        assert not any("member-b" in n for n in names), names

    def test_kube_snapshot_resolves_untouched_keys_via_one_list(self):
        """A fresh replica must see peers' shard holders (its lazy lease
        table knows nothing) — snapshot(keys) resolves through one LIST."""
        cluster = Cluster()
        a = build_lease_set("kube:shards", cluster=cluster, identity="a", duration=10)
        b = build_lease_set("kube:shards", cluster=cluster, identity="b", duration=10)
        assert a.try_acquire("p0") and a.try_acquire("p1")
        # b never touched p0/p1; the keys hint resolves them
        assert b.snapshot(["p0", "p1", "p2"]) == {"p0": "a", "p1": "a"}

    def test_kube_lease_set_prefers_uncached_list_live(self):
        """Against a real apiserver the informer plane does NOT watch
        leases, so the cached list() only shows this process's own writes
        — members()/snapshot() must go through list_live or every replica
        believes it is alone and claims every shard."""
        calls = {"live": 0, "cached": 0}
        now = [0.0]

        class SpyCluster(Cluster):
            def list_live(self, kind, namespace=None):
                calls["live"] += 1
                return Cluster.list(self, kind, namespace)

            def list(self, kind, namespace=None):
                calls["cached"] += 1
                return Cluster.list(self, kind, namespace)

        cluster = SpyCluster(clock=lambda: now[0])
        a = build_lease_set("kube:shards", cluster=cluster, identity="a", duration=10)
        b = build_lease_set("kube:shards", cluster=cluster, identity="b", duration=10)
        a.heartbeat()
        assert b.heartbeat() == {"a", "b"}
        assert a.try_acquire("p0")
        now[0] += 2.0  # past the one-tick listing-reuse window
        assert b.snapshot(["p0"]) == {"p0": "a"}
        assert calls["live"] >= 3
        assert calls["cached"] == 0, "shard discovery read the informer cache"

    def test_kube_one_list_serves_heartbeat_and_snapshot(self):
        """snapshot() right after heartbeat() must reuse the same listing
        — two full namespace LISTs per replica per tick doubles apiserver
        load for identical bytes."""
        calls = {"live": 0}

        class SpyCluster(Cluster):
            def list_live(self, kind, namespace=None):
                calls["live"] += 1
                return Cluster.list(self, kind, namespace)

        a = build_lease_set("kube:shards", cluster=SpyCluster(), identity="a", duration=10)
        a.try_acquire("p0")
        a.heartbeat()
        before = calls["live"]
        assert a.snapshot(["p0"]) == {"p0": "a"}
        assert calls["live"] == before  # reused the heartbeat's listing

    def test_kube_lease_set_coordinates(self):
        cluster = Cluster()
        a = build_lease_set("kube:shards", cluster=cluster, identity="a", duration=10)
        b = build_lease_set("kube:shards", cluster=cluster, identity="b", duration=10)
        assert a.heartbeat() == {"a"}
        assert b.heartbeat() == {"a", "b"}
        assert a.try_acquire("p0")
        assert not b.try_acquire("p0")
        assert a.renew_many(["p0"]) == {"p0"}
        assert b.holder("p0") == "a"
        a.release("p0")
        assert b.try_acquire("p0")


class _FixedOwnership:
    """Test double for fleet.ShardManager: a fixed owned-set."""

    def __init__(self, owned=()):
        self.owned_set = set(owned)

    def owns(self, key):
        return key in self.owned_set


class TestProvisioningOwnership:
    def _controller(self, ownership):
        from karpenter_tpu.cloudprovider.fake import FakeCloudProvider, instance_types
        from karpenter_tpu.controllers.provisioning import ProvisioningController

        cluster = Cluster()
        pc = ProvisioningController(
            cluster, FakeCloudProvider(instance_types(5)),
            start_workers=False, ownership=ownership,
        )
        return cluster, pc

    def test_unowned_provisioner_runs_no_worker(self):
        ownership = _FixedOwnership()
        cluster, pc = self._controller(ownership)
        cluster.create("provisioners", make_provisioner())
        requeue = pc.reconcile("default")
        assert pc.workers == {}
        assert requeue is not None  # re-checks on the lease cadence

    def test_owned_provisioner_runs_worker_and_loss_tears_down(self):
        ownership = _FixedOwnership({"default"})
        cluster, pc = self._controller(ownership)
        cluster.create("provisioners", make_provisioner())
        pc.reconcile("default")
        assert "default" in pc.workers
        # the shard manager's on_lost hook
        ownership.owned_set.clear()
        pc.release_shard("default")
        assert "default" not in pc.workers
        # and the next reconcile stays worker-less
        pc.reconcile("default")
        assert pc.workers == {}

    def test_launch_guard_blocks_after_ownership_loss(self):
        ownership = _FixedOwnership({"default"})
        cluster, pc = self._controller(ownership)
        cluster.create("provisioners", make_provisioner())
        pc.reconcile("default")
        worker = pc.workers["default"]
        worker.batcher.idle_duration = 0.01
        pod = make_pod(name="guarded", requests={"cpu": "0.5"})
        cluster.create("pods", pod)
        worker.add(pod)
        ownership.owned_set.clear()  # lease lost mid-flight
        worker.provision_once()
        assert not pod.spec.node_name, "launched without the shard lease"
        assert cluster.nodes() == []

    def test_bind_recheck_never_duplicates(self):
        from karpenter_tpu import metrics as m

        ownership = _FixedOwnership({"default"})
        cluster, pc = self._controller(ownership)
        cluster.create("provisioners", make_provisioner())
        pc.reconcile("default")
        worker = pc.workers["default"]

        def guard_hits():
            return m.REGISTRY.get_sample_value(
                "karpenter_fleet_duplicate_launch_guard_total",
                {"reason": "already_bound"},
            ) or 0.0

        pod = make_pod(name="dup-bind", requests={"cpu": "0.5"})
        cluster.create("pods", pod)
        # another replica bound it between this replica's solve and bind
        cluster.bind(pod, "other-replicas-node")
        before = guard_hits()
        worker._bind([pod], "my-node")
        assert pod.spec.node_name == "other-replicas-node"
        assert guard_hits() == before + 1


class TestInterruptionOwnership:
    def _runtime_bits(self, ownership):
        from karpenter_tpu.cloudprovider.simulated import SimCloudAPI, SimulatedCloudProvider
        from karpenter_tpu.controllers.interruption import InterruptionController

        api = SimCloudAPI()
        provider = SimulatedCloudProvider(api=api)
        cluster = Cluster()
        controller = InterruptionController(
            cluster, provider, ownership=ownership,
        )
        return api, provider, cluster, controller

    def _node(self, cluster, name="n-1", provisioner="default"):
        from karpenter_tpu.api import labels as lbl
        from karpenter_tpu.api.objects import Node, NodeSpec, ObjectMeta

        node = Node(
            metadata=ObjectMeta(
                name=name, namespace="",
                labels={lbl.PROVISIONER_NAME_LABEL: provisioner},
            ),
            spec=NodeSpec(provider_id=f"sim:///z/{name}"),
        )
        cluster.create("nodes", node)
        return node

    def test_foreign_notice_requeued_for_the_owner(self):
        from karpenter_tpu.interruption.types import PREEMPTION, DisruptionNotice

        ownership = _FixedOwnership()  # owns nothing
        api, provider, cluster, controller = self._runtime_bits(ownership)
        self._node(cluster)
        notice = DisruptionNotice(kind=PREEMPTION, node_name="n-1")
        controller.handle_notice(notice)
        assert controller.foreign_notices == 1
        # back on the provider stream for the owner's next poll
        assert provider.poll_disruptions() == [notice]
        assert controller.notices_handled == 0

    def test_owned_notice_handled_locally(self):
        from karpenter_tpu.interruption.types import PREEMPTION, DisruptionNotice

        ownership = _FixedOwnership({"default"})
        api, provider, cluster, controller = self._runtime_bits(ownership)
        cluster.create("provisioners", make_provisioner())  # the label is live
        self._node(cluster)
        controller.handle_notice(
            DisruptionNotice(kind=PREEMPTION, node_name="n-1")
        )
        assert controller.foreign_notices == 0
        assert provider.poll_disruptions() == []

    def test_deleted_provisioner_label_routes_to_default_shard(self):
        """A node whose provisioner was DELETED must route to the default
        shard — its own key left every replica's universe, so routing to
        it would requeue the notice forever with no owner appearing."""
        from karpenter_tpu.interruption.types import PREEMPTION, DisruptionNotice

        ownership = _FixedOwnership({DEFAULT_SHARD})
        api, provider, cluster, controller = self._runtime_bits(ownership)
        self._node(cluster, provisioner="long-gone")  # no such provisioner
        controller.handle_notice(
            DisruptionNotice(kind=PREEMPTION, node_name="n-1")
        )
        # the default-shard owner handled it locally, no requeue ping-pong
        assert controller.foreign_notices == 0
        assert provider.poll_disruptions() == []

    def test_unlabeled_node_routes_to_default_shard(self):
        from karpenter_tpu.interruption.types import PREEMPTION, DisruptionNotice

        ownership = _FixedOwnership({DEFAULT_SHARD})
        api, provider, cluster, controller = self._runtime_bits(ownership)
        from karpenter_tpu.api.objects import Node, NodeSpec, ObjectMeta

        cluster.create("nodes", Node(
            metadata=ObjectMeta(name="bare", namespace=""),
            spec=NodeSpec(provider_id="sim:///z/bare"),
        ))
        controller.handle_notice(
            DisruptionNotice(kind=PREEMPTION, node_name="bare")
        )
        assert controller.foreign_notices == 0


class TestSelectionOwnership:
    def test_foreign_pod_requeues_quietly_without_relaxing(self):
        """A pod admitted only by another replica's shard must NOT raise
        NoProvisionerMatched here — the manager's retry loop would relax a
        preference per retry on a SHARED pod object the owner never asked
        to degrade."""
        from karpenter_tpu.api.objects import (
            NodeSelectorRequirement,
            NodeSelectorTerm,
            PreferredSchedulingTerm,
        )
        from karpenter_tpu.cloudprovider.fake import FakeCloudProvider, instance_types
        from karpenter_tpu.controllers.provisioning import ProvisioningController
        from karpenter_tpu.controllers.selection import SelectionController

        cluster = Cluster()
        ownership = _FixedOwnership()  # this replica owns nothing
        pc = ProvisioningController(
            cluster, FakeCloudProvider(instance_types(5)),
            start_workers=False, ownership=ownership,
        )
        selection = SelectionController(cluster, pc, wait=False)
        cluster.create("provisioners", make_provisioner())
        pod = make_pod(
            name="foreign", requests={"cpu": "0.5"},
            node_preferences=[PreferredSchedulingTerm(
                weight=1,
                preference=NodeSelectorTerm(match_expressions=[
                    NodeSelectorRequirement(
                        key="zone-pref", operator="In", values=["a"],
                    ),
                ]),
            )],
        )
        cluster.create("pods", pod)
        prefs_before = len(
            pod.spec.affinity.node_affinity.preferred
        )
        # no raise, no relax: the owner replica's selection serves it
        assert selection.reconcile("foreign", "default") is not None
        assert len(pod.spec.affinity.node_affinity.preferred) == prefs_before
        # once THIS replica owns the shard, selection proceeds normally
        ownership.owned_set.add("default")
        pc.reconcile("default")
        selection.reconcile("foreign", "default")
        assert pc.workers["default"].is_pending(pod.key)

    def test_overlapping_shards_resolve_by_priority_exactly_once(self):
        """A pod BOTH an owned and a foreign shard admit is served by
        exactly ONE replica: the owner of the FIRST admitting provisioner
        in sorted-name order (single-replica selection priority). Serving
        it on every admitting replica would double-launch capacity;
        serving it on none would livelock."""
        from karpenter_tpu.cloudprovider.fake import FakeCloudProvider, instance_types
        from karpenter_tpu.controllers.provisioning import ProvisioningController
        from karpenter_tpu.controllers.selection import SelectionController

        def replica(cluster, owned):
            pc = ProvisioningController(
                cluster, FakeCloudProvider(instance_types(5)),
                start_workers=False, ownership=_FixedOwnership(owned),
            )
            return pc, SelectionController(cluster, pc, wait=False)

        # "aa" sorts before "zz": the aa-owner wins the overlapping pod
        cluster = Cluster()
        cluster.create("provisioners", make_provisioner(name="aa"))
        cluster.create("provisioners", make_provisioner(name="zz"))
        pc_a, sel_a = replica(cluster, {"aa"})
        pc_z, sel_z = replica(cluster, {"zz"})
        pc_a.reconcile("aa")
        pc_z.reconcile("zz")
        pod = make_pod(name="both", requests={"cpu": "0.5"})
        cluster.create("pods", pod)
        sel_z.reconcile("both", "default")  # zz's replica defers...
        assert not pc_z.workers["zz"].is_pending(pod.key)
        sel_a.reconcile("both", "default")  # ...aa's replica serves
        assert pc_a.workers["aa"].is_pending(pod.key)


class TestConsolidationOwnership:
    def test_unowned_shard_plans_no_wave(self):
        """Consolidation disrupts a provisioner's nodes: only the shard
        owner may plan/execute, or N replicas each retire wave_size nodes
        concurrently (N x the configured pacing)."""
        from karpenter_tpu.cloudprovider.fake import FakeCloudProvider, instance_types
        from karpenter_tpu.controllers.consolidation import ConsolidationController

        cluster = Cluster()
        cluster.create("provisioners", make_provisioner())
        controller = ConsolidationController(
            cluster, FakeCloudProvider(instance_types(5)),
            enabled=True, ownership=_FixedOwnership(),
        )
        planned = []
        controller.plan = lambda p: planned.append(p)  # must never be called
        requeue = controller.reconcile("default")
        assert planned == []
        assert requeue is not None  # re-checks on the lease cadence

    def test_owned_shard_consolidates_normally(self):
        from karpenter_tpu.cloudprovider.fake import FakeCloudProvider, instance_types
        from karpenter_tpu.controllers.consolidation import ConsolidationController

        cluster = Cluster()
        cluster.create("provisioners", make_provisioner())
        controller = ConsolidationController(
            cluster, FakeCloudProvider(instance_types(5)),
            enabled=True, ownership=_FixedOwnership({"default"}),
        )
        assert controller.reconcile("default") is not None  # normal requeue


class TestReplicaKillEndToEnd:
    def test_three_replicas_survive_owner_crash_no_duplicate_binds(self, tmp_path):
        """The acceptance e2e (fast lane): 3 controller replicas share one
        cluster + lease file; mid-storm the owner of a shard is CRASHED
        (leases expire, no release). Every pod still binds, no pod is ever
        re-bound (zero duplicate launches), and the orphaned shards re-home
        within 2x the lease duration."""
        from karpenter_tpu.api.objects import NodeSelectorRequirement
        from karpenter_tpu.cloudprovider.simulated import SimCloudAPI, SimulatedCloudProvider
        from karpenter_tpu.main import build_runtime
        from karpenter_tpu.options import Options
        from karpenter_tpu.testing.chaos import ReplicaChaos

        lease_path = _lease_path(tmp_path)
        lease_duration = 1.5
        cluster = Cluster()
        api = SimCloudAPI()
        fleet = ReplicaChaos()
        rebinds = []
        last_node = {}
        mu = threading.Lock()

        def on_pod(event, pod):
            if event == "DELETED" or not pod.spec.node_name:
                return
            with mu:
                prev = last_node.get(pod.metadata.name)
                if prev and prev != pod.spec.node_name:
                    rebinds.append((pod.metadata.name, prev, pod.spec.node_name))
                last_node[pod.metadata.name] = pod.spec.node_name

        cluster.watch("pods", on_pod)
        n_prov, n_pods = 6, 36
        try:
            for i in range(3):
                rt = build_runtime(
                    Options(shard_lease=lease_path, shard_lease_duration=lease_duration),
                    cluster=cluster,
                    cloud_provider=SimulatedCloudProvider(api=api),
                    shard_identity=f"replica-{i}",
                )
                rt.ownership.renew_interval = 0.15
                rt.ownership.start()
                rt.manager.start()
                fleet.add(f"replica-{i}", rt)
            names = [f"fleet-{i}" for i in range(n_prov)]
            for name in names:
                cluster.create("provisioners", make_provisioner(
                    name=name, solver="ffd",
                    requirements=[NodeSelectorRequirement(
                        key="fleet", operator="In", values=[name],
                    )],
                ))
            deadline = time.time() + 20
            while time.time() < deadline:
                owners = {n: fleet.owner_named(n) for n in names}
                if all(
                    rt is not None and n in rt.provisioning.workers
                    for n, (_, rt) in owners.items()
                ):
                    break
                time.sleep(0.05)
            assert all(fleet.owner_named(n)[1] for n in names), "shards never owned"
            for rt in fleet.replicas.values():
                for w in rt.provisioning.workers.values():
                    w.batcher.idle_duration = 0.05
            pods = [
                make_pod(
                    name=f"ha-{i}", requests={"cpu": "0.25"},
                    node_selector={"fleet": names[i % n_prov]},
                )
                for i in range(n_pods)
            ]
            for p in pods:
                cluster.create("pods", p)
            time.sleep(0.2)  # storm engages
            victim, victim_rt = fleet.owner_named(names[0])
            victim_shards = frozenset(victim_rt.ownership.owned())
            t_kill = time.perf_counter()
            fleet.kill(victim)
            # rebalance: every orphaned shard re-owned within 2x duration
            rebalanced_at = None
            deadline = time.time() + lease_duration * 6
            while time.time() < deadline:
                survivors_own = set()
                for rt in fleet.replicas.values():
                    survivors_own |= rt.ownership.owned()
                if victim_shards <= survivors_own:
                    rebalanced_at = time.perf_counter() - t_kill
                    break
                time.sleep(0.05)
            assert rebalanced_at is not None, "orphaned shards never re-owned"
            # the bar is 2x the lease duration; the +2s margin absorbs
            # in-process noise (all three "replicas" are threads of one
            # pytest process sharing the GIL with the provisioning storm)
            assert rebalanced_at <= 2 * lease_duration + 2.0, (
                f"rebalance took {rebalanced_at:.2f}s "
                f"(bar: {2 * lease_duration:.2f}s + scheduling margin)"
            )
            deadline = time.time() + 60
            while time.time() < deadline and not all(p.spec.node_name for p in pods):
                time.sleep(0.05)
            bound = [p for p in pods if p.spec.node_name]
            assert len(bound) == n_pods, (
                f"chaos_provision_success_rate={len(bound) / n_pods:.3f} != 1.0"
            )
            assert rebinds == [], f"duplicate launches/binds: {rebinds}"
        finally:
            fleet.stop_all()


@pytest.mark.slow
class TestFleetStormSoak:
    def test_storm_acceptance_bars(self):
        """The slow-lane storm soak (the bench leg at acceptance scale):
        8 provisioners x 3 replicas x a 2-member sidecar pool, replica
        crash + session-bearing sidecar kill mid-storm. Bars: success rate
        1.0, zero duplicate launches, rebalance within 2x lease duration,
        and at least one attributed pool failover."""
        import bench

        # lease_duration 4s (not the bench default 2s): the soak's replicas
        # are THREADS of one process, and 8 provisioners' XLA compiles
        # GIL-starve the survivors' tick cadence — real replicas are
        # separate processes. The 2x bar is still enforced, just against a
        # duration that dwarfs in-process scheduling noise.
        r = bench.bench_fleet_storm(
            n_pods=120, n_provisioners=8, n_replicas=3, pool_size=2,
            solver="tpu", lease_duration=4.0,
        )
        assert r["chaos_provision_success_rate"] == 1.0
        assert r["duplicate_launches"] == 0
        assert r["rebalance_within_bar"], r
        assert r["pool_failovers_total"] >= 1
        assert r["p99_time_to_bind_s"] is not None
        assert r["aggregate_pods_per_sec"] and r["aggregate_pods_per_sec"] > 0
