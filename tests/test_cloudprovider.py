"""Cloud-provider abstraction tests (mirrors pkg/cloudprovider behaviors)."""

from karpenter_tpu.api import labels as lbl
from karpenter_tpu.api.objects import NodeSelectorRequirement as R
from karpenter_tpu.api.requirements import Requirements
from karpenter_tpu.cloudprovider import (
    catalog_requirements,
    compatible,
    filter_instance_types,
)
from karpenter_tpu.cloudprovider.fake import (
    FakeCloudProvider,
    default_catalog,
    instance_types,
    instance_types_assorted,
    new_instance_type,
)
from karpenter_tpu.cloudprovider.types import NodeRequest, Offering
from karpenter_tpu.api.provisioner import Constraints
from karpenter_tpu.utils import resources as res


class TestCatalogRequirements:
    def test_union_of_supported_values(self):
        reqs = catalog_requirements(default_catalog())
        assert "default-instance-type" in reqs.instance_types()
        assert "arm-instance-type" in reqs.instance_types()
        assert reqs.architectures() == {"amd64", "arm64"}
        assert "test-zone-1" in reqs.zones()
        assert reqs.capacity_types() == {"spot", "on-demand"}

    def test_generators(self):
        assert len(instance_types(400)) == 400
        assert len(instance_types_assorted()) == 7 * 8 * 3 * 2 * 2 * 2


class TestCompatible:
    def test_arch_mismatch(self):
        it = new_instance_type("t", architecture="arm64")
        reqs = catalog_requirements([it]).add(
            R(key=lbl.ARCH, operator="In", values=["amd64"])
        )
        assert not compatible(it, reqs)

    def test_zone_and_capacity_must_pair(self):
        it = new_instance_type(
            "t", offerings=[Offering("spot", "z-1"), Offering("on-demand", "z-2")]
        )
        base = catalog_requirements([it])
        # spot only offered in z-1; restricting to z-2 + spot must fail
        reqs = base.add(
            R(key=lbl.TOPOLOGY_ZONE, operator="In", values=["z-2"]),
            R(key=lbl.CAPACITY_TYPE, operator="In", values=["spot"]),
        )
        assert not compatible(it, reqs)
        reqs = base.add(
            R(key=lbl.TOPOLOGY_ZONE, operator="In", values=["z-1"]),
            R(key=lbl.CAPACITY_TYPE, operator="In", values=["spot"]),
        )
        assert compatible(it, reqs)


class TestFilter:
    def test_resource_fit_includes_overhead(self):
        small = new_instance_type(
            "small", resources={res.CPU: 1.0, res.MEMORY: res.parse_quantity("1Gi")}
        )
        big = new_instance_type(
            "big", resources={res.CPU: 16.0, res.MEMORY: res.parse_quantity("64Gi")}
        )
        reqs = catalog_requirements([small, big])
        # 1 cpu request + 100m overhead exceeds the small type's 1 cpu
        out = filter_instance_types([small, big], reqs, {res.CPU: 1.0, res.PODS: 1.0})
        assert [it.name for it in out] == ["big"]


class TestFakeProvider:
    def test_create_records_and_labels(self):
        provider = FakeCloudProvider()
        catalog = provider.get_instance_types()
        constraints = Constraints(requirements=catalog_requirements(catalog))
        node = provider.create(NodeRequest(template=constraints, instance_type_options=catalog))
        assert len(provider.create_calls) == 1
        assert node.metadata.labels[lbl.INSTANCE_TYPE] == "default-instance-type"
        assert node.metadata.labels[lbl.TOPOLOGY_ZONE] in constraints.requirements.zones()
        assert node.status.allocatable[res.CPU] == 4.0
