"""Pod affinity / anti-affinity scheduling tests (BASELINE config 3 —
capability beyond the reference; semantics guided by the reference's skipped
contexts, scheduling/suite_test.go:1014-1080)."""

import random

import pytest

from karpenter_tpu.api import labels as lbl
from karpenter_tpu.api.objects import LabelSelector, PodAffinityTerm
from karpenter_tpu.cloudprovider.fake import FakeCloudProvider, instance_types
from karpenter_tpu.cloudprovider.requirements import catalog_requirements
from karpenter_tpu.kube.client import Cluster
from karpenter_tpu.scheduling.ffd import FFDScheduler
from karpenter_tpu.scheduling.scheduler import Scheduler
from karpenter_tpu.testing import diverse_pods
from tests.factories import make_node, make_pod, make_provisioner


def affinity(labels, key=lbl.TOPOLOGY_ZONE):
    return PodAffinityTerm(label_selector=LabelSelector(match_labels=labels), topology_key=key)


def solve(pods, cluster=None, solver="ffd", catalog=None):
    cluster = cluster or Cluster()
    catalog = catalog or instance_types(10)
    provisioner = make_provisioner(solver=solver)
    c = provisioner.spec.constraints
    c.requirements = c.requirements.merge(catalog_requirements(catalog))
    return Scheduler(cluster, rng=random.Random(0)).solve(provisioner, catalog, pods)


def zone_of(vnode):
    zones = vnode.constraints.requirements.zones()
    assert len(zones) == 1, f"expected one zone, got {zones}"
    return next(iter(zones))


class TestZoneAffinity:
    def test_self_affinity_colocates_in_one_zone(self):
        sel = {"app": "web"}
        pods = [
            make_pod(labels=sel, requests={"cpu": "1"}, pod_requirements=[affinity(sel)])
            for _ in range(4)
        ]
        vnodes = solve(pods)
        assert sum(len(v.pods) for v in vnodes) == 4
        zones = {zone_of(v) for v in vnodes}
        assert len(zones) == 1  # all nodes in the same zone

    def test_affinity_follows_existing_cluster_pods(self):
        cluster = Cluster()
        node = make_node(
            name="existing", labels={lbl.TOPOLOGY_ZONE: "test-zone-2"},
        )
        cluster.create("nodes", node)
        cluster.create(
            "pods",
            make_pod(labels={"app": "db"}, node_name="existing", unschedulable=False),
        )
        pod = make_pod(requests={"cpu": "1"}, pod_requirements=[affinity({"app": "db"})])
        vnodes = solve([pod], cluster=cluster)
        assert len(vnodes) == 1
        assert zone_of(vnodes[0]) == "test-zone-2"

    def test_affinity_without_any_provider_unschedulable(self):
        pod = make_pod(requests={"cpu": "1"}, pod_requirements=[affinity({"app": "ghost"})])
        vnodes = solve([pod])
        assert sum(len(v.pods) for v in vnodes) == 0

    def test_batch_provider_satisfies_affinity(self):
        """A pod with affinity to ANOTHER batch pod's labels co-locates with
        it even though neither exists in the cluster yet."""
        provider = make_pod(labels={"app": "cache"}, requests={"cpu": "1"})
        follower = make_pod(requests={"cpu": "1"}, pod_requirements=[affinity({"app": "cache"})])
        vnodes = solve([provider, follower])
        assert sum(len(v.pods) for v in vnodes) == 2
        zones = {zone_of(v) for v in vnodes}
        assert len(zones) == 1


class TestHostnameAffinity:
    def test_self_affinity_single_node(self):
        sel = {"group": "tight"}
        pods = [
            make_pod(labels=sel, requests={"cpu": "0.5"},
                     pod_requirements=[affinity(sel, key=lbl.HOSTNAME)])
            for _ in range(3)
        ]
        vnodes = solve(pods)
        assert len(vnodes) == 1  # one shared hostname = one node
        assert len(vnodes[0].pods) == 3

    def test_unsatisfiable_hostname_affinity_drops_pod(self):
        pod = make_pod(requests={"cpu": "1"},
                       pod_requirements=[affinity({"app": "ghost"}, key=lbl.HOSTNAME)])
        vnodes = solve([pod])
        assert sum(len(v.pods) for v in vnodes) == 0


class TestZoneAntiAffinity:
    def test_self_anti_affinity_spreads_zones(self):
        sel = {"app": "ha"}
        pods = [
            make_pod(labels=sel, requests={"cpu": "1"}, pod_anti_requirements=[affinity(sel)])
            for _ in range(3)
        ]
        vnodes = solve(pods)  # fake catalog offers 3 zones
        assert sum(len(v.pods) for v in vnodes) == 3
        zones = [zone_of(v) for v in vnodes]
        assert len(set(zones)) == 3

    def test_excess_anti_affinity_pods_unschedulable(self):
        sel = {"app": "ha"}
        pods = [
            make_pod(labels=sel, requests={"cpu": "1"}, pod_anti_requirements=[affinity(sel)])
            for _ in range(5)
        ]
        vnodes = solve(pods)  # only 3 zones exist
        assert sum(len(v.pods) for v in vnodes) == 3

    def test_clean_zone_reserved_for_non_matching_members(self):
        """4 matchers + 6 non-matchers, 3 zones: placing a matcher in every
        zone would strand all 6 non-matchers. The injection reserves one
        clean zone, so only 2 matchers drop and all non-matchers schedule."""
        sel = {"app": "ha"}
        matchers = [
            make_pod(labels=sel, requests={"cpu": "1"}, pod_anti_requirements=[affinity(sel)])
            for _ in range(4)
        ]
        others = [
            make_pod(labels={"app": "other"}, requests={"cpu": "1"},
                     pod_anti_requirements=[affinity(sel)])
            for _ in range(6)
        ]
        vnodes = solve(matchers + others)
        placed = [p for v in vnodes for p in v.pods]
        assert len(placed) == 8  # 2 matchers + all 6 non-matchers
        placed_others = [p for p in placed if p.metadata.labels.get("app") == "other"]
        assert len(placed_others) == 6
        # non-matchers all share the reserved (matcher-free) zone
        by_pod_zone = {}
        for v in vnodes:
            for p in v.pods:
                by_pod_zone[p.key] = zone_of(v)
        matcher_zones = {by_pod_zone[p.key] for p in matchers if p.key in by_pod_zone}
        other_zones = {by_pod_zone[p.key] for p in others if p.key in by_pod_zone}
        assert len(other_zones) == 1
        assert other_zones.isdisjoint(matcher_zones)

    def test_avoids_zone_with_existing_match(self):
        cluster = Cluster()
        for zone in ("test-zone-1", "test-zone-2"):
            node = make_node(name=f"n-{zone}", labels={lbl.TOPOLOGY_ZONE: zone})
            cluster.create("nodes", node)
            cluster.create(
                "pods",
                make_pod(labels={"app": "db"}, node_name=node.metadata.name, unschedulable=False),
            )
        pod = make_pod(requests={"cpu": "1"}, pod_anti_requirements=[affinity({"app": "db"})])
        vnodes = solve([pod], cluster=cluster)
        assert len(vnodes) == 1
        assert zone_of(vnodes[0]) == "test-zone-3"  # the only match-free zone


class TestHostnameAntiAffinity:
    def test_self_anti_affinity_one_pod_per_node(self):
        sel = {"app": "solo"}
        pods = [
            make_pod(labels=sel, requests={"cpu": "0.5"},
                     pod_anti_requirements=[affinity(sel, key=lbl.HOSTNAME)])
            for _ in range(4)
        ]
        vnodes = solve(pods)
        assert len(vnodes) == 4
        assert all(len(v.pods) == 1 for v in vnodes)

    def test_non_matching_anti_pods_share_a_node(self):
        """Anti-affinity against a selector the pods don't match lets them
        co-locate with each other."""
        pods = [
            make_pod(labels={"app": "other"}, requests={"cpu": "0.5"},
                     pod_anti_requirements=[affinity({"app": "loner"}, key=lbl.HOSTNAME)])
            for _ in range(3)
        ]
        vnodes = solve(pods)
        assert sum(len(v.pods) for v in vnodes) == 3
        assert len(vnodes) == 1


class TestMixedAffinityAntiAffinity:
    def test_anti_processed_first_so_affinity_adopts_free_zone(self):
        """A pod with both affinity and anti-affinity must not be seeded into
        the zone its anti rule forbids; its affinity partners follow it."""
        cluster = Cluster()
        node = make_node(name="n1", labels={lbl.TOPOLOGY_ZONE: "test-zone-1"})
        cluster.create("nodes", node)
        cluster.create(
            "pods", make_pod(labels={"app": "y"}, node_name="n1", unschedulable=False)
        )
        p1 = make_pod(
            labels={"app": "x"}, requests={"cpu": "1"},
            pod_requirements=[affinity({"app": "x"})],
            pod_anti_requirements=[affinity({"app": "y"})],
        )
        p2 = make_pod(requests={"cpu": "1"}, pod_requirements=[affinity({"app": "x"})])
        vnodes = solve([p1, p2], cluster=cluster)
        assert sum(len(v.pods) for v in vnodes) == 2
        zones = {zone_of(v) for v in vnodes}
        assert zones and "test-zone-1" not in zones  # avoided the app=y zone
        assert len(zones) == 1  # and stayed together

    def test_affinity_adopts_pinned_provider_domain(self):
        """A provider already pinned by its own anti rule is adopted, not
        skipped: the follower joins the provider's zone."""
        cluster = Cluster()
        node = make_node(name="n1", labels={lbl.TOPOLOGY_ZONE: "test-zone-1"})
        cluster.create("nodes", node)
        cluster.create(
            "pods", make_pod(labels={"app": "y"}, node_name="n1", unschedulable=False)
        )
        provider_pod = make_pod(
            labels={"app": "x"}, requests={"cpu": "1"},
            pod_anti_requirements=[affinity({"app": "y"})],
        )
        follower = make_pod(requests={"cpu": "1"}, pod_requirements=[affinity({"app": "x"})])
        vnodes = solve([provider_pod, follower], cluster=cluster)
        assert sum(len(v.pods) for v in vnodes) == 2
        zones = {zone_of(v) for v in vnodes}
        assert len(zones) == 1 and "test-zone-1" not in zones


class TestProviderConstraintsRespected:
    def test_provider_not_pinned_outside_its_own_node_affinity(self):
        """Seeding a zone for an affinity group must respect the provider's
        own zone constraints — the joint intersection wins."""
        from karpenter_tpu.api.objects import NodeSelectorRequirement

        provider_pod = make_pod(
            labels={"app": "web"}, requests={"cpu": "1"},
            node_requirements=[
                NodeSelectorRequirement(
                    key=lbl.TOPOLOGY_ZONE, operator="In", values=["test-zone-3"]
                )
            ],
        )
        follower = make_pod(requests={"cpu": "1"}, pod_requirements=[affinity({"app": "web"})])
        vnodes = solve([provider_pod, follower])
        assert sum(len(v.pods) for v in vnodes) == 2  # both schedule
        assert {zone_of(v) for v in vnodes} == {"test-zone-3"}


class TestSolverParityOnAffinity:
    @pytest.mark.parametrize("n", [35, 70])
    def test_diverse_mix_schedules_on_both_backends(self, n):
        """The benchmark's full diverse mix — incl. both affinity flavors —
        schedules the same pod count through FFD and the TPU solver."""
        catalog = instance_types(50)
        results = {}
        for solver in ("ffd", "tpu"):
            pods = diverse_pods(n, random.Random(7))
            vnodes = solve(pods, solver=solver, catalog=catalog)
            results[solver] = sum(len(v.pods) for v in vnodes)
        assert results["ffd"] == results["tpu"]
        # the mix is satisfiable apart from (at most) affinity pods whose
        # random selector has no provider in the batch
        assert results["ffd"] >= int(n * 0.7)

    def test_affinity_pods_actually_constrained(self):
        """Regression: before affinity support, diverse_pods' affinity pods
        were silently scheduled without their constraints."""
        sel = {"my-label": "q"}  # no batch pod carries this label
        pod = make_pod(requests={"cpu": "1"}, pod_requirements=[affinity(sel)])
        assert sum(len(v.pods) for v in solve([pod])) == 0


class TestSelectionAcceptsAffinity:
    def test_affinity_pod_routed_and_scheduled_end_to_end(self):
        from karpenter_tpu.controllers.provisioning import ProvisioningController
        from karpenter_tpu.controllers.selection import SelectionController

        cluster = Cluster()
        provider = FakeCloudProvider(instance_types(10))
        provisioning = ProvisioningController(cluster, provider, start_workers=False)
        selection = SelectionController(cluster, provisioning, wait=False)
        provisioning.apply(make_provisioner())
        sel = {"app": "web"}
        pods = [
            make_pod(labels=sel, requests={"cpu": "1"}, pod_requirements=[affinity(sel)])
            for _ in range(2)
        ]
        for p in pods:
            cluster.create("pods", p)
            assert selection.reconcile(p.metadata.name) == 5.0
        worker = provisioning.list_workers()[0]
        worker.batcher.idle_duration = 0.01
        worker.provision_once()
        provisioning.stop()
        assert all(p.spec.node_name for p in pods)
        zones = {
            cluster.get("nodes", p.spec.node_name, namespace="").metadata.labels[lbl.TOPOLOGY_ZONE]
            for p in pods
        }
        assert len(zones) == 1


class TestUnschedulabilityOracle:
    """scheduling/oracle.py: every drop must be provably inherent to the
    constraint structure (VERDICT r1 weak #4), never a greedy artifact."""

    def _classify(self, pods, cluster=None, catalog=None, solver="ffd"):
        from karpenter_tpu.scheduling.oracle import classify_drops

        cluster = cluster or Cluster()
        catalog = catalog or instance_types(10)
        provisioner = make_provisioner(solver=solver)
        c = provisioner.spec.constraints
        c.requirements = c.requirements.merge(catalog_requirements(catalog))
        vnodes = Scheduler(cluster, rng=random.Random(0)).solve(provisioner, catalog, pods)
        return classify_drops(
            cluster, c, catalog, pods, [p for v in vnodes for p in v.pods]
        )

    def test_excess_matchers_certified_exhausted(self):
        from karpenter_tpu.scheduling import oracle

        sel = {"app": "ha"}
        pods = [
            make_pod(labels=sel, requests={"cpu": "1"}, pod_anti_requirements=[affinity(sel)])
            for _ in range(5)
        ]
        verdict = self._classify(pods)
        assert verdict["dropped"] == 2  # 3 zones, no non-matchers to reserve for
        assert verdict["expected"] == {oracle.ANTI_ZONE_EXHAUSTED: 2}
        assert verdict["unexplained"] == []
        assert verdict["missed"] == []

    def test_reservation_drop_certified(self):
        from karpenter_tpu.scheduling import oracle

        sel = {"app": "ha"}
        pods = [
            make_pod(labels=sel, requests={"cpu": "1"}, pod_anti_requirements=[affinity(sel)])
            for _ in range(3)
        ] + [
            make_pod(labels={"app": "x"}, requests={"cpu": "1"},
                     pod_anti_requirements=[affinity(sel)])
        ]
        verdict = self._classify(pods)
        # capacity = 3 clean zones - 1 reserved = 2 → exactly 1 matcher drops
        assert verdict["dropped"] == 1
        assert verdict["expected"] == {oracle.ANTI_ZONE_EXHAUSTED: 1}
        assert verdict["unexplained"] == []

    def test_all_zones_dirty_certified(self):
        from karpenter_tpu.scheduling import oracle

        cluster = Cluster()
        for zone in ("test-zone-1", "test-zone-2", "test-zone-3"):
            node = make_node(name=f"n-{zone}", labels={lbl.TOPOLOGY_ZONE: zone})
            cluster.create("nodes", node)
            cluster.create(
                "pods",
                make_pod(labels={"app": "db"}, node_name=node.metadata.name,
                         unschedulable=False),
            )
        pod = make_pod(requests={"cpu": "1"}, pod_anti_requirements=[affinity({"app": "db"})])
        verdict = self._classify([pod], cluster=cluster)
        assert verdict["dropped"] == 1
        assert verdict["expected"] == {oracle.ANTI_NO_CLEAN_ZONE: 1}
        assert verdict["unexplained"] == []

    def test_no_provider_certified(self):
        from karpenter_tpu.scheduling import oracle

        pod = make_pod(requests={"cpu": "1"}, pod_requirements=[affinity({"app": "ghost"})])
        verdict = self._classify([pod])
        assert verdict["expected"] == {oracle.AFFINITY_NO_PROVIDER: 1}
        assert verdict["unexplained"] == []

    def test_oversized_pod_certified(self):
        from karpenter_tpu.scheduling import oracle

        pod = make_pod(requests={"cpu": "100000"})
        verdict = self._classify([pod])
        assert verdict["expected"] == {oracle.NO_CAPACITY: 1}
        assert verdict["unexplained"] == []

    @pytest.mark.parametrize("solver", ["ffd", "tpu"])
    def test_benchmark_mix_fully_explained(self, solver):
        """The headline bench scenario: every drop oracle-certified, zero
        unexplained, on both backends (round 1 dropped 127 with no proof;
        the reservation repair cuts that to the provable minimum)."""
        pods = diverse_pods(700, random.Random(42))
        verdict = self._classify(pods, catalog=instance_types(50), solver=solver)
        assert verdict["unexplained"] == []
        assert verdict["missed"] == []
        assert verdict["dropped"] < 700 * 0.03  # drops are the rare case

    def test_pinned_matcher_not_stranded_by_reservation(self):
        """A matcher pinned to one zone must not lose it to the reservation
        when another clean zone serves the non-matchers equally well."""
        sel = {"app": "ha"}
        pinned = make_pod(
            labels=sel, requests={"cpu": "1"},
            node_selector={lbl.TOPOLOGY_ZONE: "test-zone-1"},
            pod_anti_requirements=[affinity(sel)],
        )
        other = make_pod(labels={"app": "x"}, requests={"cpu": "1"},
                         pod_anti_requirements=[affinity(sel)])
        verdict = self._classify([pinned, other])
        assert verdict["dropped"] == 0
        assert verdict["unexplained"] == []

    def test_unreservable_nonmatcher_no_false_alarm(self):
        """A non-matcher pinned to a non-viable zone can't use any clean
        zone, so no reservation happens: all 3 matchers place, the pinned
        pod drops with its own exact reason, and the oracle raises no
        under-budget alarm."""
        from karpenter_tpu.scheduling import oracle

        sel = {"app": "ha"}
        matchers = [
            make_pod(labels=sel, requests={"cpu": "1"}, pod_anti_requirements=[affinity(sel)])
            for _ in range(3)
        ]
        pinned = make_pod(
            labels={"app": "x"}, requests={"cpu": "1"},
            node_selector={lbl.TOPOLOGY_ZONE: "test-zone-9"},
            pod_anti_requirements=[affinity(sel)],
        )
        verdict = self._classify(matchers + [pinned])
        assert verdict["dropped"] == 1
        assert verdict["expected"] == {oracle.PIN_NO_VIABLE_ZONE: 1}
        assert verdict["unexplained"] == []
        assert verdict["missed"] == []

    def test_hostname_affinity_cluster_pod_is_not_a_provider(self):
        """Hostname affinity targets a fresh node, so a scheduled cluster
        pod can't provide the match — oracle and solver must agree the pod
        is unschedulable."""
        from karpenter_tpu.scheduling import oracle

        cluster = Cluster()
        node = make_node(name="n1", labels={lbl.TOPOLOGY_ZONE: "test-zone-1"})
        cluster.create("nodes", node)
        cluster.create(
            "pods",
            make_pod(labels={"app": "db"}, node_name="n1", unschedulable=False),
        )
        pod = make_pod(requests={"cpu": "1"},
                       pod_requirements=[affinity({"app": "db"}, key=lbl.HOSTNAME)])
        verdict = self._classify([pod], cluster=cluster)
        assert verdict["dropped"] == 1
        assert verdict["expected"] == {oracle.AFFINITY_NO_PROVIDER: 1}
        assert verdict["unexplained"] == []

    def test_extended_resource_catalog_does_not_crash(self):
        """Extended resources (e.g. accelerators) flow through the oracle's
        axis discovery like the encoder's."""
        from karpenter_tpu.cloudprovider.fake import new_instance_type
        from karpenter_tpu.scheduling import oracle

        catalog = instance_types(4) + [
            new_instance_type("tpu-it", resources={"cpu": 8.0, "memory": 32e9,
                                                   "pods": 100.0, "google.com/tpu": 4.0})
        ]
        ok = make_pod(requests={"cpu": "1", "google.com/tpu": "2"})
        too_big = make_pod(requests={"cpu": "1", "google.com/tpu": "8"})
        verdict = self._classify([ok, too_big], catalog=catalog)
        assert verdict["dropped"] == 1
        assert verdict["expected"] == {oracle.NO_CAPACITY: 1}
        assert verdict["unexplained"] == []


class TestBulkPathEdges:
    """The bulk fast paths in zonal (anti-)affinity assignment and the
    token-merge slow paths they defer to (DomainPlan stores decisions as
    interned tuples; pods crossing multiple groups exercise the merge)."""

    def test_pod_in_zone_affinity_and_hostname_anti_groups(self):
        # one pod carries BOTH a zone-affinity term and a hostname
        # anti-affinity term: the hostname decision must not disturb the
        # zone token, and both constraints must hold in the result
        sel = {"app": "both"}
        pods = [
            make_pod(
                labels=sel,
                requests={"cpu": "0.5"},
                pod_requirements=[affinity(sel, key=lbl.TOPOLOGY_ZONE)],
                pod_anti_requirements=[affinity(sel, key=lbl.HOSTNAME)],
            )
            for _ in range(4)
        ]
        for solver in ("ffd", "tpu"):
            nodes = solve(list(pods), solver=solver)
            placed = [n for n in nodes if n.pods]
            # anti-host: pairwise separation -> one matching pod per node
            assert all(len(n.pods) == 1 for n in placed)
            assert sum(len(n.pods) for n in placed) == 4
            # zone affinity: all in ONE zone
            zones = {zone_of(n) for n in placed}
            assert len(zones) == 1, zones

    def test_narrowed_member_takes_general_path_others_bulk(self):
        # 10 unrestricted members + 1 member whose own selector narrows it
        # to a different zone than the group majority would pick: the
        # narrowed pod must land in ITS zone (general path), the rest
        # colocate (bulk path); the narrowed pod is the group's first
        # member so its choice seeds the populated domain
        sel = {"app": "mixed"}
        narrow = make_pod(
            labels=sel, requests={"cpu": "0.5"},
            node_selector={lbl.TOPOLOGY_ZONE: "test-zone-2"},
            pod_requirements=[affinity(sel)],
        )
        rest = [
            make_pod(labels=sel, requests={"cpu": "0.5"},
                     pod_requirements=[affinity(sel)])
            for _ in range(10)
        ]
        for solver in ("ffd", "tpu"):
            nodes = solve([narrow] + list(rest), solver=solver)
            by_zone = {}
            for n in nodes:
                for p in n.pods:
                    by_zone.setdefault(zone_of(n), []).append(p)
            # self-affinity: everyone in one zone, and it must be the
            # narrowed member's only allowed zone
            assert set(by_zone) == {"test-zone-2"}
            assert sum(len(v) for v in by_zone.values()) == 11

    def test_zone_decision_merges_with_prior_zone_decision(self):
        # a pod in TWO zone-affinity groups: the second group's assignment
        # must see the first group's pin (live read) and adopt it rather
        # than splitting the pod across zones
        sel_a, sel_b = {"app": "a"}, {"app": "b"}
        both = make_pod(
            labels={**sel_a, **sel_b}, requests={"cpu": "0.5"},
            pod_requirements=[affinity(sel_a), affinity(sel_b)],
        )
        friends_a = [make_pod(labels=sel_a, requests={"cpu": "0.5"},
                              pod_requirements=[affinity(sel_a)]) for _ in range(3)]
        friends_b = [make_pod(labels=sel_b, requests={"cpu": "0.5"},
                              pod_requirements=[affinity(sel_b)]) for _ in range(3)]
        for solver in ("ffd", "tpu"):
            nodes = solve([both] + friends_a + friends_b, solver=solver)
            zones = {zone_of(n) for n in nodes if n.pods}
            # everyone must collapse into one zone: the shared member pins
            # both groups together
            assert len(zones) == 1, zones
            assert sum(len(n.pods) for n in nodes) == 7


class TestDiscoverOverflowOrder:
    """Registry-overflow pods (topo_code == -1) must keep batch-interleaved
    member and group-creation order in the bucketed (>=512) discovery path
    (ADVICE r4: overflow members used to gather after every coded class,
    so zone/hostname assignment order diverged from the per-pod path once
    the class registry filled)."""

    def test_overflow_members_interleave_in_batch_order(self):
        from karpenter_tpu.api.objects import (
            LabelSelector,
            TopologySpreadConstraint,
        )
        from karpenter_tpu.scheduling import statics as statics_mod
        from karpenter_tpu.scheduling.topology import Topology

        import uuid

        # unique selector per invocation: the statics class registry is a
        # process global, and a re-run must re-create (not re-find) class A
        # so class B still overflows
        sel = {"app": f"ovf-{uuid.uuid4().hex[:8]}"}
        k1, k2 = lbl.TOPOLOGY_ZONE, "test.overflow/k2"

        def spreads(*keys):
            return [
                TopologySpreadConstraint(
                    max_skew=1, topology_key=k,
                    label_selector=LabelSelector(match_labels=sel),
                )
                for k in keys
            ]

        # class A = spread on k1 only; class B = spread on k1 AND k2 — a
        # DIFFERENT topology class sharing group k1, so the k1 group mixes
        # coded and overflow members when class B overflows
        pods = [
            make_pod(name=f"ovf-{i:04d}", requests={"cpu": "0.1"})
            if i % 3 == 2 else make_pod(
                name=f"ovf-{i:04d}", labels=sel, requests={"cpu": "0.1"},
                topology=spreads(k1) if i % 3 == 0 else spreads(k1, k2),
            )
            for i in range(540)
        ]
        # allow exactly ONE new class: class A interns, class B gets -1
        saved = statics_mod._TOPO_CLASS_MAX
        statics_mod._TOPO_CLASS_MAX = len(statics_mod._topo_classes) + 1
        try:
            sts = [statics_mod.statics(p) for p in pods]
        finally:
            statics_mod._TOPO_CLASS_MAX = saved
        codes = {s.topo_code for s in sts if s.topo_any}
        assert -1 in codes, codes
        assert any(c > 0 for c in codes), codes

        aff_groups, spread_groups, port_members = {}, {}, []
        Topology._discover(pods, sts, aff_groups, spread_groups, port_members)

        expected = {}
        for i, p in enumerate(pods):
            if i % 3 != 2:
                expected.setdefault(k1, []).append(p.metadata.name)
            if i % 3 == 1:
                expected.setdefault(k2, []).append(p.metadata.name)
        assert len(spread_groups) == 2
        # group creation order = first appearance of each key in the batch,
        # independent of which classes overflowed
        assert [g.constraint.topology_key for g in spread_groups.values()] == [k1, k2]
        for g in spread_groups.values():
            # member order = batch order, overflow members interleaved
            # exactly like the per-pod path
            assert [p.metadata.name for p in g.pods] == expected[g.constraint.topology_key]
            assert all(s is statics_mod.statics(p) for p, s in zip(g.pods, g.sts))


class TestAffinityDenseScenario:
    """The r5 #1b bench scenario (docs/affinity-regime.md): half the batch
    in required (anti-)affinity groups must solve cleanly and certify."""

    def test_generator_mix_and_clean_solve(self):
        from karpenter_tpu.scheduling.oracle import classify_drops
        from karpenter_tpu.testing import affinity_dense_pods

        pods = affinity_dense_pods(400, random.Random(5), frac=0.5)
        assert len(pods) == 400
        aff = [p for p in pods if p.spec.affinity is not None]
        assert abs(len(aff) - 200) <= 1
        anti = [
            p for p in aff
            if p.spec.affinity.pod_anti_affinity is not None
        ]
        assert anti and len(anti) < len(aff)  # both rule kinds present
        catalog = instance_types(50)
        provisioner = make_provisioner(solver="tpu")
        c = provisioner.spec.constraints
        c.requirements = c.requirements.merge(catalog_requirements(catalog))
        cluster = Cluster()
        nodes = Scheduler(cluster, rng=random.Random(1)).solve(
            provisioner, catalog, pods
        )
        placed = [p for n in nodes for p in n.pods]
        verdict = classify_drops(cluster, c, catalog, pods, placed)
        assert verdict["unexplained"] == [], verdict["unexplained"][:3]
        # zone-affinity groups actually co-located (plain pods land on
        # unpinned multi-zone nodes — only group members' nodes are pinned)
        by_zone = {}
        for n in nodes:
            zones = n.constraints.requirements.zones()
            for p in n.pods:
                g = p.metadata.labels.get("aff-group")
                if (
                    g is not None
                    and p.spec.affinity is not None
                    and p.spec.affinity.pod_affinity is not None
                ):
                    assert len(zones) == 1, (g, zones)
                    by_zone.setdefault(g, set()).add(next(iter(zones)))
        assert by_zone and all(len(zs) == 1 for zs in by_zone.values())
