"""Chaos-harness tests: the seeded failure regimes (testing/chaos.py), the
robustness satellites (describe-miss liveness, the typed all-ICE fleet
error, warmup retry), and the chaos-seeded e2e — provision → interrupt →
replace through the FULL runtime under a 10% API error rate + 50ms p95
injected latency, finishing with zero lost pods and no breaker left open."""

import time

import pytest

from karpenter_tpu.api import labels as lbl
from karpenter_tpu.cloudprovider.simulated import (
    CloudAPIError,
    InsufficientCapacityError,
    LIVENESS_MISS_THRESHOLD,
    SimCloudAPI,
    SimulatedCloudProvider,
)
from karpenter_tpu.kube.client import Cluster
from karpenter_tpu.testing.chaos import ChaosPolicy, ChaosWindow, chaos_wrap
from tests.factories import make_pod, make_provisioner

pytestmark = pytest.mark.chaos


class TestChaosProxy:
    def test_seeded_runs_are_reproducible(self):
        def run():
            api = SimCloudAPI()
            chaos = chaos_wrap(api, ChaosPolicy(error_rate=0.3, seed=11))
            outcomes = []
            for _ in range(50):
                try:
                    chaos.describe_instance_types()
                    outcomes.append("ok")
                except Exception as e:
                    outcomes.append(type(e).__name__)
            return outcomes

        assert run() == run()
        assert "CloudAPIError" in run() or "ThrottlingError" in run()

    def test_zero_rate_injects_nothing(self):
        api = SimCloudAPI()
        chaos = chaos_wrap(api, ChaosPolicy(error_rate=0.0, seed=1))
        for _ in range(100):
            chaos.describe_subnets({"purpose": "nodes"})
        assert chaos.injected_total() == 0

    def test_programming_surface_passes_through(self):
        """Chaos applies to control-plane calls, never to the test's
        ability to program the double."""
        from karpenter_tpu.interruption.types import PREEMPTION, DisruptionNotice

        api = SimCloudAPI()
        chaos = chaos_wrap(api, ChaosPolicy(error_rate=1.0, seed=2))
        chaos.inject_error("create_fleet", CloudAPIError("staged"))  # no raise
        chaos.send_disruption_notice(
            DisruptionNotice(kind=PREEMPTION, node_name="i-1")
        )
        assert len(api.disruptions) == 1
        assert api._errors["create_fleet"]

    def test_blackout_window_fails_everything(self):
        clock = [0.0]
        api = SimCloudAPI()
        chaos = chaos_wrap(
            api,
            ChaosPolicy(blackouts=(ChaosWindow(1.0, 2.0),), seed=3),
            clock=lambda: clock[0],
        )
        chaos.describe_instance_types()  # before the window
        clock[0] = 1.5
        with pytest.raises(CloudAPIError, match="blackout"):
            chaos.describe_instance_types()
        clock[0] = 2.5
        chaos.describe_instance_types()  # the window ended

    def test_ice_storm_raises_typed_all_ice_with_overrides(self):
        clock = [0.5]
        api = SimCloudAPI()
        chaos = chaos_wrap(
            api,
            ChaosPolicy(ice_storms=(ChaosWindow(0.0, 10.0),), seed=4),
            clock=lambda: clock[0],
        )
        overrides = [("lt", "sim.gp-4x", "sim-zone-1a"), ("lt", "sim.gp-8x", "sim-zone-1b")]
        with pytest.raises(InsufficientCapacityError) as ei:
            chaos.create_fleet("on-demand", overrides)
        assert ei.value.overrides == [
            ("on-demand", "sim.gp-4x", "sim-zone-1a"),
            ("on-demand", "sim.gp-8x", "sim-zone-1b"),
        ]
        assert not api.instances  # nothing launched during the storm

    def test_injected_latency_observed(self):
        api = SimCloudAPI()
        chaos = chaos_wrap(api, ChaosPolicy(latency_p95=0.005, seed=5))
        for _ in range(20):
            chaos.describe_subnets({"purpose": "nodes"})
        assert chaos.delayed.get("describe_subnets", 0) > 0

    def test_chaos_crosses_the_http_wire_as_5xx_and_is_retried(self):
        """A chaos-wrapped double behind the HTTP server turns injections
        into wire errors; the transport's retry policy absorbs a low rate."""
        from karpenter_tpu.cloudprovider.httpapi import CloudAPIServer, HttpCloudAPI

        api = SimCloudAPI()
        chaos = chaos_wrap(api, ChaosPolicy(error_rate=0.2, seed=6))
        with CloudAPIServer(chaos) as server:
            client = HttpCloudAPI(server.url, backoff_base=0.005)
            for _ in range(20):
                assert len(client.describe_subnets({"purpose": "nodes"})) == 3
            assert chaos.injected_total() > 0
            assert client.retries >= 1


class TestAllIceTypedError:
    """Satellite: ([], errors) with every override ICE'd is now a typed
    InsufficientCapacityError carrying the overrides, on both paths."""

    def test_in_process_all_ice_raises_typed(self):
        api = SimCloudAPI()
        api.insufficient_capacity_pools.add(("on-demand", "sim.gp-4x", "sim-zone-1a"))
        with pytest.raises(InsufficientCapacityError) as ei:
            api.create_fleet("on-demand", [("lt", "sim.gp-4x", "sim-zone-1a")])
        assert ei.value.overrides == [("on-demand", "sim.gp-4x", "sim-zone-1a")]

    def test_partial_ice_still_returns_instances(self):
        api = SimCloudAPI()
        api.insufficient_capacity_pools.add(("on-demand", "sim.gp-4x", "sim-zone-1a"))
        instances, errors = api.create_fleet(
            "on-demand",
            [("lt", "sim.gp-4x", "sim-zone-1a"), ("lt", "sim.gp-8x", "sim-zone-1b")],
        )
        assert len(instances) == 1
        assert errors == [("on-demand", "sim.gp-4x", "sim-zone-1a")]

    def test_provider_marks_ice_cache_from_typed_error(self):
        """The launch path caches out exactly the pools the typed error
        names, so the next catalog read routes around them."""
        api = SimCloudAPI()
        provider = SimulatedCloudProvider(api=api)
        catalog = provider.get_instance_types()
        target = catalog[0]
        for o in target.offerings:
            api.insufficient_capacity_pools.add((o.capacity_type, target.name, o.zone))
        unavailable = provider.instance_type_provider.unavailable
        assert not unavailable.is_unavailable(
            "on-demand", target.name, target.offerings[0].zone
        )
        with pytest.raises(InsufficientCapacityError):
            api.create_fleet(
                "on-demand",
                [("lt", target.name, o.zone) for o in target.offerings
                 if o.capacity_type == "on-demand"],
            )
        # drive the same through the instance provider to hit the handler
        from karpenter_tpu.cloudprovider.simulated import SimProviderConfig

        try:
            provider.instance_provider.api.create_fleet(
                "on-demand",
                [("lt", target.name, o.zone) for o in target.offerings
                 if o.capacity_type == "on-demand"],
            )
        except InsufficientCapacityError as e:
            for ct, it, zone in e.overrides:
                provider.instance_type_provider.unavailable.mark_unavailable(ct, it, zone)
        assert unavailable.is_unavailable(
            "on-demand", target.name,
            next(o.zone for o in target.offerings if o.capacity_type == "on-demand"),
        )

    def test_all_ice_crosses_the_wire_typed_with_overrides(self):
        from karpenter_tpu.cloudprovider.httpapi import CloudAPIServer, HttpCloudAPI

        api = SimCloudAPI()
        api.insufficient_capacity_pools.add(("spot", "sim.gp-2x", "sim-zone-1b"))
        with CloudAPIServer(api) as server:
            client = HttpCloudAPI(server.url, backoff_base=0.005)
            with pytest.raises(InsufficientCapacityError) as ei:
                client.create_fleet("spot", [("lt", "sim.gp-2x", "sim-zone-1b")])
            assert ei.value.overrides == [("spot", "sim.gp-2x", "sim-zone-1b")]


class TestDescribeMissLiveness:
    """Satellite: one id missing from one flaky describe must not orphan a
    healthy node — N consecutive misses (or a terminated state) are needed
    before the liveness consumer declares it gone."""

    def _node_for(self, api):
        provider = SimulatedCloudProvider(api=api)
        instances, _ = api.create_fleet("on-demand", [("lt", "sim.gp-4x", "sim-zone-1a")])
        from karpenter_tpu.api.objects import Node, NodeSpec, ObjectMeta

        node = Node(
            metadata=ObjectMeta(name=instances[0].id, namespace=""),
            spec=NodeSpec(provider_id=f"sim:///sim-zone-1a/{instances[0].id}"),
        )
        return provider, node, instances[0]

    def test_single_miss_is_not_gone(self):
        api = SimCloudAPI()
        provider, node, inst = self._node_for(api)
        del api.instances[inst.id]  # the cloud forgot it (or the response was flaky)
        assert provider.instance_gone(node) is False  # miss 1 of 3
        assert provider.instance_gone(node) is False  # miss 2 of 3
        assert provider.instance_gone(node) is True   # threshold reached

    def test_sighting_resets_the_streak(self):
        api = SimCloudAPI()
        provider, node, inst = self._node_for(api)
        record = api.instances.pop(inst.id)
        for _ in range(LIVENESS_MISS_THRESHOLD - 1):
            assert provider.instance_gone(node) is False
        api.instances[inst.id] = record  # it was a flake: the instance lives
        assert provider.instance_gone(node) is False
        del api.instances[inst.id]
        assert provider.instance_gone(node) is False  # the streak restarted

    def test_terminated_state_is_immediately_gone(self):
        api = SimCloudAPI()
        provider, node, inst = self._node_for(api)
        api.terminate_instances([inst.id])
        assert provider.instance_gone(node) is True

    def test_typed_not_found_is_immediately_gone(self):
        """A positive "no such record" answer (the wire's 404 → typed
        InstanceNotFoundError) skips the consecutive-miss threshold."""
        from karpenter_tpu.cloudprovider.simulated import InstanceNotFoundError

        api = SimCloudAPI()
        provider, node, inst = self._node_for(api)
        api.inject_error(
            "describe_instances", InstanceNotFoundError(f"no record of {inst.id}")
        )
        assert provider.instance_gone(node) is True

    def test_typed_not_found_crosses_the_wire_as_404(self):
        """Server-side InstanceNotFoundError must cross as a typed 404
        (never a retryable 500) so the wire provider's liveness consumer
        gets the same fast path as the in-process one."""
        from karpenter_tpu.cloudprovider.httpapi import CloudAPIServer, HttpCloudAPI
        from karpenter_tpu.cloudprovider.simulated import InstanceNotFoundError

        api = SimCloudAPI()
        with CloudAPIServer(api) as server:
            provider = SimulatedCloudProvider(
                api=HttpCloudAPI(server.url, backoff_base=0.005)
            )
            instances, _ = api.create_fleet(
                "on-demand", [("lt", "sim.gp-4x", "sim-zone-1a")]
            )
            from karpenter_tpu.api.objects import Node, NodeSpec, ObjectMeta

            node = Node(
                metadata=ObjectMeta(name=instances[0].id, namespace=""),
                spec=NodeSpec(provider_id=f"sim:///sim-zone-1a/{instances[0].id}"),
            )
            api.inject_error("describe_instances", InstanceNotFoundError("no record"))
            assert provider.instance_gone(node) is True

    def test_errored_describe_is_unknown_not_a_miss(self):
        api = SimCloudAPI()
        provider, node, inst = self._node_for(api)
        del api.instances[inst.id]
        for _ in range(LIVENESS_MISS_THRESHOLD * 2):
            api.inject_error("describe_instances", CloudAPIError("chaos"))
            assert provider.instance_gone(node) is None
        # the error streak advanced nothing: still need all N real misses
        for _ in range(LIVENESS_MISS_THRESHOLD - 1):
            assert provider.instance_gone(node) is False

    def test_node_controller_deletes_only_after_threshold(self):
        from karpenter_tpu.controllers.node import NodeController

        now = [1000.0]
        cluster = Cluster(clock=lambda: now[0])
        api = SimCloudAPI()
        provider, _, inst = self._node_for(api)
        controller = NodeController(cluster, cloud_provider=provider)
        cluster.create("provisioners", make_provisioner())
        from karpenter_tpu.api.objects import Node, NodeSpec, NodeStatus, ObjectMeta, PodCondition

        node = Node(
            metadata=ObjectMeta(
                name=inst.id, namespace="",
                labels={lbl.PROVISIONER_NAME_LABEL: "default"},
            ),
            spec=NodeSpec(provider_id=f"sim:///sim-zone-1a/{inst.id}"),
            status=NodeStatus(conditions=[PodCondition(type="Ready", status="True")]),
        )
        node.metadata.creation_timestamp = now[0]
        cluster.create("nodes", node)
        del api.instances[inst.id]
        for probe in range(LIVENESS_MISS_THRESHOLD - 1):
            controller.reconcile(inst.id)
            assert cluster.try_get("nodes", inst.id, namespace="") is not None, (
                f"node deleted after only {probe + 1} miss(es)"
            )
            now[0] += 31.0  # past the per-node probe interval
        controller.reconcile(inst.id)
        # the threshold-reaching miss hands the node to termination (the
        # finalizer keeps the object around until the drain completes)
        live = cluster.try_get("nodes", inst.id, namespace="")
        assert live is not None and live.metadata.deletion_timestamp is not None
        from karpenter_tpu.controllers.termination import TerminationController

        termination = TerminationController(cluster, provider, start_queue=False)
        assert termination.reconcile(inst.id) is None
        assert cluster.try_get("nodes", inst.id, namespace="") is None

    def test_probe_rate_limited_per_node(self):
        from karpenter_tpu.controllers.node import CloudLiveness

        now = [0.0]
        cluster = Cluster(clock=lambda: now[0])
        api = SimCloudAPI()
        provider, node, inst = self._node_for(api)
        liveness = CloudLiveness(cluster, provider)
        base = api.calls.get("describe_instances", 0)
        liveness.reconcile(None, node)
        liveness.reconcile(None, node)  # same probe window: no second call
        assert api.calls.get("describe_instances", 0) == base + 1
        now[0] += 31.0
        liveness.reconcile(None, node)
        assert api.calls.get("describe_instances", 0) == base + 2


class TestLaunchFastRequeue:
    """A transient launch failure re-enters the batch's pods into the
    batcher for the next round — without dropping their pending state, so
    selection's verify requeue cannot spuriously relax preferences."""

    def test_failed_launch_requeues_and_stays_pending(self):
        from karpenter_tpu.cloudprovider.fake import FakeCloudProvider, instance_types
        from karpenter_tpu.controllers.provisioning import ProvisioningController

        provider = FakeCloudProvider(instance_types(5))
        fails = [1]
        original = provider.create

        def flaky(request):
            if fails[0]:
                fails[0] -= 1
                raise ConnectionError("launch blip")
            return original(request)

        provider.create = flaky
        cluster = Cluster()
        pc = ProvisioningController(cluster, provider, start_workers=False)
        cluster.create("provisioners", make_provisioner())
        pc.reconcile("default")
        worker = pc.list_workers()[0]
        worker.batcher.idle_duration = 0.01
        pods = [make_pod(name=f"fr-{i}", requests={"cpu": "0.5"}) for i in range(2)]
        for p in pods:
            cluster.create("pods", p)
            worker.add(p)
        worker.provision_once()  # launch fails; pods re-enter the batcher
        assert all(not p.spec.node_name for p in pods)
        # still pending: the selection verify path must short-circuit
        assert all(worker.is_pending(p.key) for p in pods)
        worker.provision_once()  # the requeued round succeeds
        assert all(p.spec.node_name for p in pods)
        assert not any(worker.is_pending(p.key) for p in pods)


class TestWarmupRetry:
    """Satellite: a transient first-compile/catalog failure retries once in
    the background and lands on the warmup-failure counter."""

    def _worker(self, provider):
        from karpenter_tpu.controllers.provisioning import ProvisionerWorker

        prov = make_provisioner(solver="tpu")
        worker = ProvisionerWorker(prov, Cluster(), provider)
        worker._stop.wait = lambda t: None  # no real sleep between attempts
        return worker

    def _warmup_failures(self):
        from karpenter_tpu import metrics

        return metrics.REGISTRY.get_sample_value(
            "karpenter_solver_warmup_failures_total"
        ) or 0.0

    def test_transient_failure_retried_once_and_counted(self):
        from karpenter_tpu.cloudprovider.fake import FakeCloudProvider, instance_types

        provider = FakeCloudProvider(instance_types(4))
        original = provider.get_instance_types
        fail = [1]

        def flaky(p=None):
            if fail[0]:
                fail[0] -= 1
                raise ConnectionError("catalog not up yet")
            return original(p)

        provider.get_instance_types = flaky
        worker = self._worker(provider)
        before = self._warmup_failures()
        worker._warmup()
        assert worker.warmed.is_set()
        assert self._warmup_failures() == before + 1  # one failed attempt
        assert fail[0] == 0  # the background retry actually ran the solve

    def test_double_failure_gives_up_counted_twice(self):
        from karpenter_tpu.cloudprovider.fake import FakeCloudProvider

        provider = FakeCloudProvider()

        def dead(p=None):
            raise ConnectionError("never up")

        provider.get_instance_types = dead
        worker = self._worker(provider)
        before = self._warmup_failures()
        worker._warmup()
        assert worker.warmed.is_set()  # first real batch will compile
        assert self._warmup_failures() == before + 2


class TestChaosEndToEnd:
    def test_provision_interrupt_replace_under_chaos(self):
        """The acceptance e2e: the full runtime against the simulated
        provider under ChaosPolicy(error_rate=0.1, latency_p95=0.05,
        seed=…) provisions and binds every pending pod, survives a
        preemption mid-chaos with zero pods evicted without replacement,
        and ends with no breaker open."""
        from karpenter_tpu.interruption.types import PREEMPTION, DisruptionNotice
        from karpenter_tpu.main import build_runtime
        from karpenter_tpu.options import Options

        api = SimCloudAPI()
        chaos = chaos_wrap(api, ChaosPolicy(error_rate=0.1, latency_p95=0.05, seed=77))
        provider = SimulatedCloudProvider(api=chaos)
        cluster = Cluster()
        rt = build_runtime(Options(), cluster=cluster, cloud_provider=provider)
        rt.interruption.poll_interval = 0.1
        rt.manager.start()
        try:
            cluster.create("provisioners", make_provisioner(solver="ffd"))
            deadline = time.time() + 10
            while time.time() < deadline and not rt.provisioning.workers:
                time.sleep(0.02)
            assert rt.provisioning.workers
            for w in rt.provisioning.workers.values():
                w.batcher.idle_duration = 0.05
            pods = [
                make_pod(name=f"chaos-e2e-{i}", requests={"cpu": "0.25"})
                for i in range(24)
            ]
            for p in pods:
                cluster.create("pods", p)

            def all_bound():
                return all(p.spec.node_name for p in pods)

            deadline = time.time() + 60
            while time.time() < deadline and not all_bound():
                time.sleep(0.05)
            assert all_bound(), "pods never bound under chaos"

            # interrupt → replace, still under chaos
            victim = next(p.spec.node_name for p in pods)
            api.send_disruption_notice(DisruptionNotice(
                kind=PREEMPTION, node_name=victim, grace_period_seconds=60.0,
            ))
            deadline = time.time() + 60
            while time.time() < deadline:
                if (
                    cluster.try_get("nodes", victim, namespace="") is None
                    and all(p.spec.node_name not in ("", victim) for p in pods)
                ):
                    break
                time.sleep(0.05)
            assert cluster.try_get("nodes", victim, namespace="") is None, (
                "preempted node never terminated under chaos"
            )
            assert all_bound(), "pods lost across the chaotic replacement"
            assert all(p.spec.node_name != victim for p in pods)
            assert rt.interruption.evicted_unready == 0
            # every bound pod sits on a LIVE node (liveness never orphaned one)
            live = {n.metadata.name for n in cluster.nodes()}
            for p in pods:
                assert p.spec.node_name in live
            # the chaos actually fired, and no breaker is left open
            assert chaos.injected_total() > 0
            assert rt.cloud_provider.breakers.open_dependencies() == []
        finally:
            rt.stop()


class TestArrivalPattern:
    """The seeded diurnal + flash-crowd generator behind the
    forecast-storm bench leg."""

    def _pattern(self, **kwargs):
        from karpenter_tpu.testing.chaos import ArrivalPattern

        kwargs.setdefault("base_pods_per_tick", 4.0)
        kwargs.setdefault("period_s", 60.0)
        kwargs.setdefault("tick_s", 5.0)
        kwargs.setdefault("seed", 7)
        return ArrivalPattern(**kwargs)

    def test_schedule_is_deterministic_from_seed(self):
        a = self._pattern(flash_at=(20.0,))
        b = self._pattern(flash_at=(20.0,))
        assert a.schedule(120.0) == b.schedule(120.0)
        c = self._pattern(flash_at=(20.0,), seed=8)
        assert a.schedule(120.0) != c.schedule(120.0)

    def test_schedule_covers_duration_in_tick_order(self):
        p = self._pattern()
        sched = p.schedule(60.0)
        times = [t for t, _ in sched]
        assert times == sorted(times)
        assert times[0] == 0.0
        assert times[-1] < 60.0
        assert all(n >= 0 for _, n in sched)

    def test_diurnal_rate_bounds(self):
        p = self._pattern(amplitude=0.75)
        rates = [p.rate_at(t) for t in range(0, 60)]
        assert max(rates) == pytest.approx(4.0 * 1.75, rel=0.01)
        assert min(rates) == pytest.approx(4.0 * 0.25, rel=0.05)
        assert all(r >= 0 for r in rates)

    def test_flash_crowd_folds_extra_pods_in(self):
        calm = self._pattern()
        stormy = self._pattern(flash_at=(20.0,), flash_pods=40,
                               flash_len_s=10.0)
        assert stormy.total_pods(60.0) >= calm.total_pods(60.0) + 40

    def test_in_flash_window_boundaries(self):
        p = self._pattern(flash_at=(20.0, 40.0), flash_len_s=10.0)
        assert not p.in_flash(19.9)
        assert p.in_flash(20.0)
        assert p.in_flash(29.9)
        assert not p.in_flash(30.0)
        assert p.in_flash(45.0)
        assert not p.in_flash(55.0)

    def test_flash_past_duration_ignored(self):
        p = self._pattern(flash_at=(999.0,), flash_pods=40)
        with_f = p.total_pods(60.0)
        without = self._pattern().total_pods(60.0)
        assert with_f == without
