"""Simulated vendor provider tests (mirrors aws/suite_test.go driven against
fake EC2/SSM): capacity types, ICE cache behavior, launch templates, subnets,
security groups, GPU preference, overhead model, defaulting/validation."""

import pytest

from karpenter_tpu.api import labels as lbl
from karpenter_tpu.api.objects import NodeSelectorRequirement
from karpenter_tpu.api.provisioner import Constraints
from karpenter_tpu.api.requirements import Requirements
from karpenter_tpu.cloudprovider.requirements import catalog_requirements
from karpenter_tpu.cloudprovider.simulated import (
    CloudAPIError,
    InsufficientCapacityError,
    SimCloudAPI,
    SimInstanceTypeInfo,
    SimProviderConfig,
    SimSubnet,
    SimulatedCloudProvider,
    compute_overhead,
    network_limited_pods,
)
from karpenter_tpu.cloudprovider.types import NodeRequest
from karpenter_tpu.controllers.provisioning import ProvisioningController
from karpenter_tpu.kube.client import Cluster
from karpenter_tpu.utils import resources as res
from tests.factories import make_pod, make_provisioner


@pytest.fixture(params=["inproc", "http"])
def env(request):
    """The whole suite runs twice: once with the in-process double, once
    with every control-plane call crossing a real HTTP wire against the
    same double (VERDICT r3 ask #7 — a client and double written by the
    same hand can share a protocol misunderstanding; serde + status-code
    mapping must survive a real boundary). Error injection and call
    counting still program the underlying SimCloudAPI."""
    now = [1000.0]
    api = SimCloudAPI()
    if request.param == "http":
        from karpenter_tpu.cloudprovider.httpapi import CloudAPIServer, HttpCloudAPI

        server = CloudAPIServer(api, page_size=10_000).start()
        provider = SimulatedCloudProvider(
            HttpCloudAPI(server.url, backoff_base=0.01), clock=lambda: now[0]
        )
        yield api, provider, now
        server.stop()
    else:
        provider = SimulatedCloudProvider(api, clock=lambda: now[0])
        yield api, provider, now


def constraints_for(provider, requirements=None, provider_cfg=None):
    c = Constraints(
        requirements=Requirements.new(*(requirements or [])), provider=provider_cfg
    )
    provider.default(c)
    catalog = provider.get_instance_types(provider_cfg)
    c.requirements = c.requirements.merge(catalog_requirements(catalog))
    return c, catalog


class TestCatalog:
    def test_metal_filtered_offering_zones_from_subnets(self, env):
        api, provider, _ = env
        catalog = provider.get_instance_types()
        names = {it.name for it in catalog}
        assert "sim.metal-96x" not in names
        assert "sim.gp-4x" in names
        for it in catalog:
            assert {o.zone for o in it.offerings} <= {"sim-zone-1a", "sim-zone-1b", "sim-zone-1c"}

    def test_catalog_cached_five_minutes(self, env):
        api, provider, now = env
        provider.get_instance_types()
        provider.get_instance_types()
        assert api.calls["describe_instance_types"] == 1
        now[0] += 301
        provider.get_instance_types()
        assert api.calls["describe_instance_types"] == 2

    def test_subnet_selector_restricts_zones(self, env):
        api, provider, _ = env
        cfg = {"subnetSelector": {"Name": "private-a"}}
        catalog = provider.get_instance_types(cfg)
        for it in catalog:
            assert {o.zone for o in it.offerings} == {"sim-zone-1a"}

    def test_no_matching_subnets_raises(self, env):
        api, provider, _ = env
        with pytest.raises(CloudAPIError):
            provider.get_instance_types({"subnetSelector": {"Name": "nope"}})


class TestOverheadModel:
    def test_cpu_ladder(self):
        info = SimInstanceTypeInfo(name="t", vcpus=4, memory_gib=8)
        # 100m system + 60m (first core) + 10m (second) + 10m (cores 3-4)
        assert compute_overhead(info)[res.CPU] == pytest.approx(0.18)

    def test_memory_formula(self):
        info = SimInstanceTypeInfo(name="t", vcpus=2, memory_gib=4,
                                   max_network_interfaces=3, ips_per_interface=10)
        pods = network_limited_pods(info)
        assert pods == 3 * 9 + 2
        assert compute_overhead(info)[res.MEMORY] == (11 * pods + 455) * 1024**2


class TestLaunch:
    def test_launch_creates_node_with_labels_and_allocatable(self, env):
        api, provider, _ = env
        c, catalog = constraints_for(provider)
        cheapest = sorted(catalog, key=lambda it: it.effective_price())
        node = provider.create(NodeRequest(template=c, instance_type_options=cheapest))
        assert node.metadata.labels[lbl.INSTANCE_TYPE] == cheapest[0].name
        assert node.metadata.labels[lbl.CAPACITY_TYPE] == lbl.CAPACITY_TYPE_ON_DEMAND
        assert node.metadata.labels[lbl.TOPOLOGY_ZONE].startswith("sim-zone-")
        assert node.status.allocatable[res.CPU] < node.status.capacity[res.CPU]
        assert api.instances  # really launched

    def test_spot_used_when_requested(self, env):
        api, provider, _ = env
        c, catalog = constraints_for(
            provider,
            requirements=[
                NodeSelectorRequirement(
                    key=lbl.CAPACITY_TYPE, operator="In",
                    values=[lbl.CAPACITY_TYPE_SPOT, lbl.CAPACITY_TYPE_ON_DEMAND],
                )
            ],
        )
        node = provider.create(NodeRequest(template=c, instance_type_options=catalog))
        assert node.metadata.labels[lbl.CAPACITY_TYPE] == lbl.CAPACITY_TYPE_SPOT

    def test_on_demand_default_without_spot(self, env):
        api, provider, _ = env
        c, catalog = constraints_for(provider)
        node = provider.create(NodeRequest(template=c, instance_type_options=catalog))
        assert node.metadata.labels[lbl.CAPACITY_TYPE] == lbl.CAPACITY_TYPE_ON_DEMAND

    def test_gpu_types_dropped_when_generic_available(self, env):
        api, provider, _ = env
        c, catalog = constraints_for(provider)
        node = provider.create(NodeRequest(template=c, instance_type_options=catalog))
        it = next(i for i in catalog if i.name == node.metadata.labels[lbl.INSTANCE_TYPE])
        assert not it.resources.get(res.NVIDIA_GPU)

    def test_gpu_only_options_still_launch(self, env):
        api, provider, _ = env
        c, catalog = constraints_for(provider)
        gpu_only = [it for it in catalog if it.resources.get(res.NVIDIA_GPU)]
        node = provider.create(NodeRequest(template=c, instance_type_options=gpu_only))
        assert "gpu" in node.metadata.labels[lbl.INSTANCE_TYPE]

    def test_delete_terminates_instance(self, env):
        api, provider, _ = env
        c, catalog = constraints_for(provider)
        node = provider.create(NodeRequest(template=c, instance_type_options=catalog))
        provider.delete(node)
        instance_id = node.spec.provider_id.rsplit("/", 1)[-1]
        assert api.instances[instance_id].state == "terminated"


class TestICE:
    def test_ice_marks_offering_unavailable_and_skips_it(self, env):
        api, provider, now = env
        c, catalog = constraints_for(provider)
        cheapest = sorted(catalog, key=lambda it: it.effective_price())[0]
        # exhaust the cheapest type in every zone
        for z in ("sim-zone-1a", "sim-zone-1b", "sim-zone-1c"):
            api.insufficient_capacity_pools.add((lbl.CAPACITY_TYPE_ON_DEMAND, cheapest.name, z))
        node = provider.create(NodeRequest(template=c, instance_type_options=list(catalog)))
        # fleet fell through to a non-exhausted type
        assert node.metadata.labels[lbl.INSTANCE_TYPE] != cheapest.name
        # next catalog read excludes the ICE'd offerings entirely
        refreshed = provider.get_instance_types()
        it = next(i for i in refreshed if i.name == cheapest.name)
        assert lbl.CAPACITY_TYPE_ON_DEMAND not in {o.capacity_type for o in it.offerings}

    def test_ice_cache_expires_after_45s(self, env):
        api, provider, now = env
        provider.instance_type_provider.unavailable.mark_unavailable(
            lbl.CAPACITY_TYPE_ON_DEMAND, "sim.gp-1x", "sim-zone-1a"
        )
        assert provider.instance_type_provider.unavailable.is_unavailable(
            lbl.CAPACITY_TYPE_ON_DEMAND, "sim.gp-1x", "sim-zone-1a"
        )
        now[0] += 46
        assert not provider.instance_type_provider.unavailable.is_unavailable(
            lbl.CAPACITY_TYPE_ON_DEMAND, "sim.gp-1x", "sim-zone-1a"
        )

    def test_all_pools_exhausted_raises(self, env):
        api, provider, _ = env
        c, catalog = constraints_for(provider)
        one = [sorted(catalog, key=lambda it: it.effective_price())[0]]
        for z in ("sim-zone-1a", "sim-zone-1b", "sim-zone-1c"):
            api.insufficient_capacity_pools.add((lbl.CAPACITY_TYPE_ON_DEMAND, one[0].name, z))
        with pytest.raises(InsufficientCapacityError):
            provider.create(NodeRequest(template=c, instance_type_options=one))


class TestLaunchTemplates:
    def test_identical_configs_share_one_template(self, env):
        api, provider, _ = env
        c, catalog = constraints_for(provider)
        provider.create(NodeRequest(template=c, instance_type_options=catalog))
        provider.create(NodeRequest(template=c, instance_type_options=catalog))
        assert len(api.launch_templates) == 1

    def test_different_labels_get_different_templates(self, env):
        api, provider, _ = env
        c1, catalog = constraints_for(provider)
        c2, _ = constraints_for(provider)
        c2.labels = {"team": "a"}
        provider.create(NodeRequest(template=c1, instance_type_options=catalog))
        provider.create(NodeRequest(template=c2, instance_type_options=catalog))
        assert len(api.launch_templates) == 2

    def test_gpu_nodes_get_gpu_image(self, env):
        api, provider, _ = env
        c, catalog = constraints_for(provider)
        gpu_only = [it for it in catalog if it.resources.get(res.NVIDIA_GPU)]
        provider.create(NodeRequest(template=c, instance_type_options=gpu_only))
        data = next(iter(api.launch_templates.values()))
        assert "gpu" in data["image"]

    def test_byo_launch_template_respected(self, env):
        api, provider, _ = env
        cfg = {"launchTemplate": "my-custom-lt"}
        c, catalog = constraints_for(provider, provider_cfg=cfg)
        c.provider = cfg
        node = provider.create(NodeRequest(template=c, instance_type_options=catalog))
        instance_id = node.spec.provider_id.rsplit("/", 1)[-1]
        assert api.instances[instance_id].launch_template == "my-custom-lt"
        assert api.launch_templates == {}  # nothing created


class TestLaunchTemplateContents:
    def test_user_data_carries_labels_taints_dns(self, env):
        from karpenter_tpu.api.objects import Taint
        from karpenter_tpu.api.provisioner import KubeletConfiguration

        api, provider, _ = env
        c, catalog = constraints_for(provider)
        c.labels = {"team": "infra"}
        c.taints = [Taint(key="dedicated", value="gpu", effect="NoSchedule")]
        c.kubelet_configuration = KubeletConfiguration(cluster_dns=["10.0.0.10"])
        provider.create(NodeRequest(template=c, instance_type_options=catalog))
        data = next(iter(api.launch_templates.values()))
        ud = data["user_data"]
        assert "--node-labels=team=infra" in ud
        assert "--register-with-taints=dedicated=gpu:NoSchedule" in ud
        assert "--cluster-dns=10.0.0.10" in ud

    def test_minimal_family_renders_toml(self, env):
        api, provider, _ = env
        cfg = {"imageFamily": "minimal"}
        c, catalog = constraints_for(provider, provider_cfg=cfg)
        c.provider = cfg
        c.labels = {"team": "infra"}
        provider.create(NodeRequest(template=c, instance_type_options=catalog))
        data = next(iter(api.launch_templates.values()))
        assert data["user_data"].startswith("[settings.kubernetes]")
        assert 'node-labels = "team=infra"' in data["user_data"]

    def test_block_device_mappings_and_metadata_options(self, env):
        api, provider, _ = env
        cfg = {
            "blockDeviceMappings": [
                {"deviceName": "/dev/xvdb", "volumeSize": 100, "volumeType": "gp3"}
            ],
            "metadataOptions": {"httpTokens": "optional"},
        }
        c, catalog = constraints_for(provider, provider_cfg=cfg)
        c.provider = cfg
        provider.create(NodeRequest(template=c, instance_type_options=catalog))
        data = next(iter(api.launch_templates.values()))
        assert data["block_device_mappings"][0]["volume_size_gib"] == 100
        assert data["metadata_options"]["http_tokens"] == "optional"

    def test_bad_bdm_and_metadata_rejected(self, env):
        _, provider, _ = env
        from karpenter_tpu.api.provisioner import Constraints

        errs = provider.validate(
            Constraints(provider={"blockDeviceMappings": [{"volumeSize": -1}]})
        )
        assert any("volumeSize" in e for e in errs)
        errs = provider.validate(
            Constraints(provider={"metadataOptions": {"httpTokens": "never"}})
        )
        assert any("httpTokens" in e for e in errs)

    def test_malformed_provider_yields_errors_not_crash(self, env):
        _, provider, _ = env
        from karpenter_tpu.api.provisioner import Constraints

        errs = provider.validate(
            Constraints(provider={"blockDeviceMappings": [{"volumeSize": "100Gi"}]})
        )
        assert any("volumeSize" in e for e in errs)
        # YAML 'metadataOptions:' with no body deserializes to None
        errs = provider.validate(Constraints(provider={"metadataOptions": None}))
        assert errs == []  # empty object = defaults, no crash
        errs = provider.validate(Constraints(provider={"blockDeviceMappings": "nope"}))
        assert any("must be a list" in e for e in errs)

    def test_encrypted_false_string_respected(self, env):
        from karpenter_tpu.cloudprovider.simulated import SimProviderConfig

        cfg = SimProviderConfig.deserialize(
            {"blockDeviceMappings": [{"encrypted": "false"}]}
        )
        assert cfg.block_device_mappings[0].encrypted is False

    def test_byo_lt_conflicts_with_metadata_options(self, env):
        _, provider, _ = env
        from karpenter_tpu.api.provisioner import Constraints

        errs = provider.validate(
            Constraints(
                provider={"launchTemplate": "mine", "metadataOptions": {"httpTokens": "optional"}}
            )
        )
        assert any("metadataOptions" in e for e in errs)

    def test_byo_lt_conflicts_with_bdms(self, env):
        _, provider, _ = env
        from karpenter_tpu.api.provisioner import Constraints

        errs = provider.validate(
            Constraints(
                provider={
                    "launchTemplate": "mine",
                    "blockDeviceMappings": [{"deviceName": "/dev/xvda"}],
                }
            )
        )
        assert any("blockDeviceMappings" in e for e in errs)


class TestValidationDefaults:
    def test_defaults_applied(self, env):
        _, provider, _ = env
        c = Constraints()
        provider.default(c)
        assert c.requirements.capacity_types() == {lbl.CAPACITY_TYPE_ON_DEMAND}
        assert c.requirements.architectures() == {lbl.ARCH_AMD64}

    def test_defaults_idempotent(self, env):
        _, provider, _ = env
        c = Constraints(
            requirements=Requirements.new(
                NodeSelectorRequirement(
                    key=lbl.CAPACITY_TYPE, operator="In", values=[lbl.CAPACITY_TYPE_SPOT]
                )
            )
        )
        provider.default(c)
        assert c.requirements.capacity_types() == {lbl.CAPACITY_TYPE_SPOT}

    def test_bad_image_family_rejected(self, env):
        _, provider, _ = env
        errs = provider.validate(Constraints(provider={"imageFamily": "nope"}))
        assert errs

    def test_restricted_tags_rejected(self, env):
        _, provider, _ = env
        errs = provider.validate(
            Constraints(provider={"tags": {"karpenter.sh/provisioner-name": "x"}})
        )
        assert errs

    def test_empty_selector_rejected(self, env):
        _, provider, _ = env
        errs = provider.validate(Constraints(provider={"subnetSelector": {}}))
        assert errs


class TestEndToEnd:
    def test_provisioning_through_simulated_provider(self, env):
        """The full slice — pending pods → solve → fleet launch → bind —
        against the simulated vendor instead of the plain fake."""
        api, provider, _ = env
        cluster = Cluster()
        controller = ProvisioningController(cluster, provider, start_workers=False)
        provisioner = make_provisioner()
        cluster.create("provisioners", provisioner)
        pods = [make_pod(requests={"cpu": "1"}) for _ in range(5)]
        for p in pods:
            cluster.create("pods", p)
        controller.apply(provisioner)
        worker = controller.workers[provisioner.name]
        for p in pods:
            worker.batcher.add(p)
        worker.batcher.idle_duration = 0.01
        vnodes = worker.provision_once()
        controller.stop()
        assert vnodes
        assert all(p.spec.node_name for p in cluster.pods())
        node = cluster.nodes()[0]
        assert node.metadata.labels[lbl.PROVISIONER_NAME_LABEL] == "default"
        assert node.spec.provider_id.startswith("sim:///")


class TestSecurityGroups:
    """reference: aws/suite_test.go Context("Security Groups") — the
    selector restricts which groups land in the launch template; matching
    nothing is a loud failure."""

    def test_selector_restricts_groups_in_template(self, env):
        api, provider, _ = env
        c, catalog = constraints_for(
            provider, provider_cfg={"securityGroupSelector": {"purpose": "extra"}}
        )
        cheapest = sorted(catalog, key=lambda it: it.effective_price())
        provider.create(NodeRequest(template=c, instance_type_options=cheapest))
        lts = list(api.launch_templates.values())
        assert lts, "launch expected to create a template"
        assert lts[-1]["security_groups"] == ["sg-extra"]

    def test_default_selector_picks_node_groups(self, env):
        api, provider, _ = env
        c, catalog = constraints_for(provider)
        cheapest = sorted(catalog, key=lambda it: it.effective_price())
        provider.create(NodeRequest(template=c, instance_type_options=cheapest))
        lts = list(api.launch_templates.values())
        assert lts[-1]["security_groups"] == ["sg-nodes"]

    def test_no_matching_groups_is_loud(self, env):
        api, provider, _ = env
        c, catalog = constraints_for(
            provider, provider_cfg={"securityGroupSelector": {"purpose": "nope"}}
        )
        cheapest = sorted(catalog, key=lambda it: it.effective_price())
        with pytest.raises(Exception, match="security groups"):
            provider.create(NodeRequest(template=c, instance_type_options=cheapest))


class TestEphemeralStorage:
    """reference: aws/suite_test.go Context("Ephemeral Storage") — pods
    requesting ephemeral-storage schedule against the types' usable
    storage; over-sized requests are certified unsatisfiable."""

    def test_pod_with_ephemeral_storage_schedules(self, env):
        import random

        from karpenter_tpu.kube.client import Cluster
        from karpenter_tpu.scheduling.scheduler import Scheduler
        from tests.factories import make_pod, make_provisioner

        api, provider, _ = env
        prov = make_provisioner(solver="ffd")
        c = prov.spec.constraints
        provider.default(c)
        catalog = provider.get_instance_types()
        c.requirements = c.requirements.merge(catalog_requirements(catalog))
        pods = [
            make_pod(requests={"cpu": "0.5", "ephemeral-storage": "1Gi"})
            for _ in range(4)
        ]
        nodes = Scheduler(Cluster(), rng=random.Random(1)).solve(prov, catalog, pods)
        assert sum(len(n.pods) for n in nodes) == 4

    def test_oversized_ephemeral_storage_certified_unsatisfiable(self, env):
        import random

        from karpenter_tpu.kube.client import Cluster
        from karpenter_tpu.scheduling.oracle import classify_drops
        from karpenter_tpu.scheduling.scheduler import Scheduler
        from tests.factories import make_pod, make_provisioner

        api, provider, _ = env
        prov = make_provisioner(solver="ffd")
        c = prov.spec.constraints
        provider.default(c)
        catalog = provider.get_instance_types()
        c.requirements = c.requirements.merge(catalog_requirements(catalog))
        pods = [make_pod(requests={"cpu": "0.5", "ephemeral-storage": "1Pi"})]
        cluster = Cluster()
        nodes = Scheduler(cluster, rng=random.Random(1)).solve(prov, catalog, pods)
        assert sum(len(n.pods) for n in nodes) == 0
        verdict = classify_drops(
            cluster, c, catalog, pods, [p for n in nodes for p in n.pods]
        )
        assert verdict["dropped"] == 1 and not verdict["unexplained"]


class TestInstanceProfile:
    """reference: aws/suite_test.go Context("Instance Profile") — the
    provider-config profile flows into the launch template; absent means
    the (empty/cluster-default) profile."""

    def test_profile_from_provider_config(self, env):
        api, provider, _ = env
        c, catalog = constraints_for(
            provider, provider_cfg={"instanceProfile": "overridden-profile"}
        )
        cheapest = sorted(catalog, key=lambda it: it.effective_price())
        provider.create(NodeRequest(template=c, instance_type_options=cheapest))
        lts = list(api.launch_templates.values())
        assert lts[-1]["instance_profile"] == "overridden-profile"

    def test_default_profile_when_unspecified(self, env):
        api, provider, _ = env
        c, catalog = constraints_for(provider)
        cheapest = sorted(catalog, key=lambda it: it.effective_price())
        provider.create(NodeRequest(template=c, instance_type_options=cheapest))
        lts = list(api.launch_templates.values())
        assert lts[-1]["instance_profile"] == ""
