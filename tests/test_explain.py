"""Elimination attribution vs ground truth (docs/decisions.md).

The core contract: for an unschedulable pod, the attributed elimination
dimension is the one whose REMOVAL lets the pod place — verified by
brute-force single-constraint ablation re-solves on the native packer
across 100+ randomized scenarios (5 planted dimensions x 21 seeds), plus
route-parity (the verdicts are a pure function of the encoded batch and
the bit-exact assignment, so the native and device kernels must explain
identically) and the message/rollup semantics.
"""

from __future__ import annotations

import random

import numpy as np
import pytest

from karpenter_tpu.api import labels as lbl
from karpenter_tpu.api.objects import NodeSelectorRequirement
from karpenter_tpu.cloudprovider.fake import new_instance_type
from karpenter_tpu.cloudprovider.requirements import catalog_requirements
from karpenter_tpu.cloudprovider.types import Offering
from karpenter_tpu.scheduling.ffd import sort_pods_ffd
from karpenter_tpu.solver import encode as enc
from karpenter_tpu.solver import explain as expl
from karpenter_tpu.solver.native import native_available, pack_native
from tests.factories import make_pod, make_provisioner

pytestmark = pytest.mark.skipif(
    not native_available(wait=240.0), reason="native packer unavailable"
)

TARGET = "target-pod"


def uniform_catalog(n, cpu=4.0, zones=None):
    offerings = (
        [Offering("on-demand", z) for z in zones] if zones else None
    )
    return [
        new_instance_type(
            f"it-{i}", resources={"cpu": float(cpu), "pods": 100.0},
            offerings=offerings,
        )
        for i in range(n)
    ]


def solve_scenario(catalog, pods, daemon=None, requirements=None):
    """Encode exactly like the production facade (catalog requirements
    layered in), solve on the native packer, return (batch, assignment)."""
    prov = make_provisioner(requirements=requirements or [])
    constraints = prov.spec.constraints.clone()
    constraints.requirements = constraints.requirements.merge(
        catalog_requirements(catalog)
    )
    catalog = sorted(catalog, key=lambda it: it.effective_price())
    pods = sort_pods_ffd(pods)
    batch = enc.encode(constraints, catalog, pods, daemon or {})
    n_max = len(batch.pod_valid)
    result = pack_native(*batch.pack_args(), n_max=n_max)
    return batch, np.asarray(result.assignment)[: batch.n_pods]


def target_verdict(batch, assignment):
    for i, p in enumerate(batch.pods[: batch.n_pods]):
        if p.metadata.name == TARGET:
            placed = bool(assignment[i] >= 0)
            return placed, expl.explain_pod(batch, i)
    raise AssertionError("target pod not in batch")


class Scenario:
    """One planted-dimension scenario plus its ablation operators. Each
    operator removes exactly one constraint dimension; the attribution is
    correct iff removing the ATTRIBUTED dimension places the pod and
    removing the others does not (operators in ``skip`` logically subsume
    the planted dimension and are exempt from the negative check)."""

    def __init__(self, rng: random.Random):
        self.rng = rng

    def build(self):  # -> (catalog, pods, daemon, requirements)
        raise NotImplementedError

    expected: str
    fixes: frozenset
    skip: frozenset = frozenset()

    def ablate(self, op, catalog, pods, daemon, requirements):
        catalog = list(catalog)
        pods = [p for p in pods]
        daemon = dict(daemon)
        requirements = list(requirements)
        target = next(p for p in pods if p.metadata.name == TARGET)
        if op == "capacity":
            # remove the resource-fit dimension: every type grows huge
            catalog = [
                new_instance_type(
                    it.name,
                    resources={"cpu": 10_000.0, "pods": 10_000.0},
                    offerings=list(it.offerings),
                )
                for it in catalog
            ]
        elif op == "daemon":
            daemon = {}
        elif op == "selector":
            # drop the pod's non-topology selector keys
            target.spec.node_selector = {
                k: v for k, v in target.spec.node_selector.items()
                if k in (lbl.TOPOLOGY_ZONE, lbl.HOSTNAME)
            }
        elif op == "zone":
            target.spec.node_selector = {
                k: v for k, v in target.spec.node_selector.items()
                if k != lbl.TOPOLOGY_ZONE
            }
        elif op == "hostname":
            target.spec.node_selector = {
                k: v for k, v in target.spec.node_selector.items()
                if k != lbl.HOSTNAME
            }
        else:
            raise AssertionError(op)
        return catalog, pods, daemon, requirements


class ResourceScenario(Scenario):
    expected = expl.REASON_RESOURCE
    fixes = frozenset({"capacity"})
    # zeroing a zero daemon is a no-op, but it is NOT exempt: it must fail

    def build(self):
        n = self.rng.randint(3, 8)
        cpu = self.rng.uniform(2.0, 6.0)
        catalog = uniform_catalog(n, cpu=cpu)
        pods = [
            make_pod(requests={"cpu": "0.2"}) for _ in range(self.rng.randint(1, 4))
        ]
        # requests more cpu than ANY type's usable capacity
        pods.append(
            make_pod(name=TARGET, requests={"cpu": str(cpu + self.rng.uniform(1.0, 50.0))})
        )
        return catalog, pods, {}, []


class DaemonScenario(Scenario):
    expected = expl.REASON_DAEMON
    fixes = frozenset({"daemon"})
    skip = frozenset({"capacity"})  # more capacity also absorbs the overhead

    def build(self):
        n = self.rng.randint(2, 6)
        cpu = 4.0
        catalog = uniform_catalog(n, cpu=cpu)
        # usable = cpu - 0.1 overhead; target fits alone, not plus daemon
        daemon = {"cpu": self.rng.uniform(0.5, 1.0)}
        target_req = cpu - 0.1 - self.rng.uniform(0.05, 0.3)
        pods = [make_pod(requests={"cpu": "0.2"})]
        pods.append(make_pod(name=TARGET, requests={"cpu": str(target_req)}))
        return catalog, pods, daemon, []


class RequirementScenario(Scenario):
    expected = expl.REASON_REQUIREMENT
    fixes = frozenset({"selector"})

    def build(self):
        n = self.rng.randint(3, 8)
        catalog = uniform_catalog(n)
        pods = [make_pod(requests={"cpu": "0.2"})]
        pods.append(make_pod(
            name=TARGET,
            requests={"cpu": "0.5"},
            node_selector={lbl.INSTANCE_TYPE: "no-such-type"},
        ))
        return catalog, pods, {}, []


class ZoneScenario(Scenario):
    expected = expl.REASON_ZONE
    fixes = frozenset({"zone"})

    def build(self):
        n = self.rng.randint(3, 8)
        catalog = uniform_catalog(n, zones=["zone-a", "zone-b"])
        pods = [make_pod(requests={"cpu": "0.2"})]
        pods.append(make_pod(
            name=TARGET,
            requests={"cpu": "0.5"},
            node_selector={lbl.TOPOLOGY_ZONE: "zone-missing"},
        ))
        return catalog, pods, {}, []


class FrontierScenario(Scenario):
    """Mixed resource elimination: some compatible types fail the pod
    even alone, the rest only once the daemon overhead lands — the
    pod-level verdict is the kernel's own gate (no frontier row admits
    it). BOTH resource-family ablations fix it: more capacity, or no
    daemon (the big type then fits)."""

    expected = expl.REASON_FRONTIER
    fixes = frozenset({"capacity", "daemon"})

    def build(self):
        small = self.rng.uniform(1.0, 2.0)
        big = 4.0
        catalog = [
            new_instance_type(
                "small", resources={"cpu": small, "pods": 100.0}
            ),
            new_instance_type("big", resources={"cpu": big, "pods": 100.0}),
        ]
        daemon = {"cpu": self.rng.uniform(0.6, 1.0)}
        # fits big alone (usable 3.9) but not + daemon; never fits small
        target_req = big - 0.1 - self.rng.uniform(0.05, 0.4)
        pods = [make_pod(name=TARGET, requests={"cpu": str(target_req)})]
        return catalog, pods, daemon, []


SCENARIOS = [
    ResourceScenario, DaemonScenario, RequirementScenario,
    ZoneScenario, FrontierScenario,
]
SEEDS = list(range(21))
ABLATIONS = ("capacity", "daemon", "selector", "zone", "hostname")


@pytest.mark.parametrize("seed", SEEDS)
@pytest.mark.parametrize("scenario_cls", SCENARIOS)
def test_attribution_matches_single_constraint_ablation(scenario_cls, seed):
    """The 100+ scenario sweep (5 dims x 21 seeds): the attributed top
    reason must be exactly the dimension whose removal places the pod —
    both directions of the iff, on brute-force native re-solves."""
    def fresh():
        # deterministic rebuild: every ablation starts from an identical
        # scenario (the rng must not advance across builds)
        return scenario_cls(random.Random((hash(scenario_cls.__name__) ^ seed) & 0xFFFF))

    sc = fresh()
    catalog, pods, daemon, requirements = sc.build()
    batch, assignment = solve_scenario(catalog, pods, daemon, requirements)
    placed, verdict = target_verdict(batch, assignment)
    assert not placed, "scenario must leave the target unplaced"
    assert verdict["top_reason"] == sc.expected, verdict
    assert verdict["viable_types"] == 0
    for op in ABLATIONS:
        if op in sc.skip:
            continue
        sc2 = fresh()
        a_catalog, a_pods, a_daemon, a_reqs = sc2.ablate(op, *sc2.build())
        a_batch, a_assignment = solve_scenario(
            a_catalog, a_pods, a_daemon, a_reqs
        )
        a_placed, _ = target_verdict(a_batch, a_assignment)
        should_place = op in sc.fixes
        assert a_placed == should_place, (
            f"{scenario_cls.__name__}: ablating `{op}` -> placed="
            f"{a_placed}, expected {should_place} (attributed "
            f"{verdict['top_reason']})"
        )


def test_verdicts_identical_across_native_and_device_routes():
    """Attribution is a pure function of (encoded batch, assignment); the
    kernel routes are assignment-bit-exact, so the verdict dicts must be
    identical whichever backend produced the result."""
    import jax  # noqa: F401  (skip cleanly if jax is broken)

    from karpenter_tpu.solver import kernel

    sc = ResourceScenario(random.Random(7))
    catalog, pods, daemon, requirements = sc.build()
    batch, native_assignment = solve_scenario(
        catalog, pods, daemon, requirements
    )
    n_max = len(batch.pod_valid)
    device = kernel.pack(*batch.pack_args(), n_max=n_max)
    device_assignment = np.asarray(device.assignment)[: batch.n_pods]
    assert np.array_equal(native_assignment, device_assignment)
    v_native = expl.explain_batch(batch, native_assignment)
    v_device = expl.explain_batch(batch, device_assignment)
    assert v_native == v_device
    assert v_native, "scenario must produce at least one verdict"


def test_compound_rollup_message_joins_dimensions():
    """A pod killed by accelerator-style requirement on some types AND
    zone topology on the rest rolls both up ('... requirement ∧
    zone_topology' or the reverse, dominant first)."""
    catalog = (
        # zone-b offerings: excluded by the pod's zone-a requirement
        [new_instance_type(
            f"zoned-{i}", resources={"cpu": 4.0, "pods": 100.0},
            offerings=[Offering("on-demand", "zone-b")],
        ) for i in range(2)]
        # zone-a offerings but the wrong architecture
        + [new_instance_type(
            f"arch-{i}", architecture="arm64",
            resources={"cpu": 4.0, "pods": 100.0},
            offerings=[Offering("on-demand", "zone-a")],
        ) for i in range(3)]
    )
    pods = [make_pod(
        name=TARGET, requests={"cpu": "0.5"},
        node_selector={lbl.TOPOLOGY_ZONE: "zone-a", lbl.ARCH: "amd64"},
    )]
    batch, assignment = solve_scenario(catalog, pods)
    placed, verdict = target_verdict(batch, assignment)
    assert not placed
    assert set(verdict["reasons"]) == {
        expl.REASON_REQUIREMENT, expl.REASON_ZONE,
    }
    assert "∧" in verdict["message"]
    assert verdict["top_reason"] == expl.REASON_REQUIREMENT  # 3 vs 2 types
    # the detail keys name the offending dimensions
    assert lbl.ARCH in verdict["reason_details"][expl.REASON_REQUIREMENT]


def test_frontier_rollup_for_mixed_resource_elimination():
    """Some compatible types fail the pod alone, others only once the
    daemon overhead lands: the pod-level verdict is the kernel's own
    formulation — no frontier row admits it (capacity_frontier)."""
    catalog = [
        new_instance_type("small", resources={"cpu": 2.0, "pods": 100.0}),
        new_instance_type("big", resources={"cpu": 4.0, "pods": 100.0}),
    ]
    # fits big alone (3.5 <= 3.9) but not + daemon (4.4 > 3.9); small
    # fails even alone
    pods = [make_pod(name=TARGET, requests={"cpu": "3.5"})]
    batch, assignment = solve_scenario(catalog, pods, daemon={"cpu": 0.9})
    placed, verdict = target_verdict(batch, assignment)
    assert not placed
    assert verdict["top_reason"] == expl.REASON_FRONTIER
    assert verdict["reasons"] == {
        expl.REASON_RESOURCE: 1, expl.REASON_DAEMON: 1,
    }
    assert verdict["frontier_admits"] is False


def test_hostname_poison_is_annotation_not_eliminator():
    """A pod pinning a hostname outside the base domains still places on
    a fresh node (the reference skips compatibility for a node's first
    pod) — the verdict annotates the poisoned pin instead of inventing an
    elimination."""
    catalog = uniform_catalog(3)
    pods = [make_pod(
        name=TARGET, requests={"cpu": "0.5"},
        node_selector={lbl.HOSTNAME: "pinned-host"},
    )]
    requirements = [NodeSelectorRequirement(
        key=lbl.HOSTNAME, operator="In", values=["other-host"],
    )]
    batch, assignment = solve_scenario(
        catalog, pods, requirements=requirements
    )
    placed, verdict = target_verdict(batch, assignment)
    assert placed
    assert verdict["hostname_poisoned"] == "pinned-host"
    assert verdict["top_reason"] == ""


def test_schedulable_pod_reports_viable_types():
    catalog = uniform_catalog(3)
    pods = [make_pod(name=TARGET, requests={"cpu": "0.5"})]
    batch, assignment = solve_scenario(catalog, pods)
    placed, verdict = target_verdict(batch, assignment)
    assert placed
    assert verdict["viable_types"] == 3
    assert verdict["top_reason"] == ""
    assert verdict["message"] == "schedulable on a fresh node"


def test_explain_batch_filters_to_unschedulable():
    catalog = uniform_catalog(3, cpu=4.0)
    pods = [
        make_pod(requests={"cpu": "0.5"}),
        make_pod(name=TARGET, requests={"cpu": "100"}),
    ]
    batch, assignment = solve_scenario(catalog, pods)
    verdicts = expl.explain_batch(batch, assignment)
    assert len(verdicts) == 1
    assert verdicts[0]["pod"].endswith(TARGET)
    assert verdicts[0]["placed"] is False
    everyone = expl.explain_batch(batch, assignment, only_unschedulable=False)
    assert len(everyone) == batch.n_pods


def test_verdict_memo_never_collides_across_batches_on_a_shared_table():
    """encode re-indexes signature ids densely PER BATCH while the
    verdict memo lives on the shared SignatureTable: two batches whose
    different signatures land on the same local id must not serve each
    other's verdicts (the memo keys the signature OBJECT)."""
    from karpenter_tpu.solver.encode import EncodeCache

    catalog = uniform_catalog(4, zones=["zone-a"])
    prov = make_provisioner()
    constraints = prov.spec.constraints.clone()
    constraints.requirements = constraints.requirements.merge(
        catalog_requirements(catalog)
    )
    cat = sorted(catalog, key=lambda it: it.effective_price())
    cache = EncodeCache()

    def explain_target(pods):
        pods = sort_pods_ffd(pods)
        batch = enc.encode(constraints, cat, pods, {}, cache=cache)
        result = pack_native(
            *batch.pack_args(), n_max=len(batch.pod_valid)
        )
        assignment = np.asarray(result.assignment)[: batch.n_pods]
        return target_verdict(batch, assignment)

    # batch A: requirement-family elimination (bogus instance type);
    # batch B (same table via the shared EncodeCache, same request bytes,
    # colliding local sig id): zone-family elimination
    _, v_a = explain_target([make_pod(
        name=TARGET, requests={"cpu": "0.5"},
        node_selector={lbl.INSTANCE_TYPE: "no-such-type"},
    )])
    _, v_b = explain_target([make_pod(
        name=TARGET, requests={"cpu": "0.5"},
        node_selector={lbl.TOPOLOGY_ZONE: "zone-missing"},
    )])
    assert v_a["top_reason"] == expl.REASON_REQUIREMENT
    assert v_b["top_reason"] == expl.REASON_ZONE


def test_candidate_listing_capped_counts_complete():
    catalog = uniform_catalog(30, cpu=2.0)
    pods = [make_pod(name=TARGET, requests={"cpu": "50"})]
    batch, assignment = solve_scenario(catalog, pods)
    _, verdict = target_verdict(batch, assignment)
    assert verdict["reasons"][expl.REASON_RESOURCE] == 30  # complete
    assert len(verdict["candidates"]) == expl.DEFAULT_MAX_CANDIDATES
