"""karplint: golden-fixture corpus, suppression/baseline mechanics, and
the clean-tree + runtime acceptance gates.

The per-rule fire/near-miss behavior lives in tests/karplint_fixtures/
(one firing fixture and one near-miss per rule, self-describing headers);
the selftest walks it. These tests drive that corpus plus the mechanics a
fixture can't express: baselines, fingerprints, P0 non-baselineability,
and the analyzer's performance envelope.
"""

import json
import sys
import time
from pathlib import Path

import pytest

REPO_ROOT = Path(__file__).resolve().parent.parent
if str(REPO_ROOT) not in sys.path:
    sys.path.insert(0, str(REPO_ROOT))

from tools.karplint import Analyzer, Baseline  # noqa: E402
from tools.karplint.__main__ import main  # noqa: E402

CORPUS = REPO_ROOT / "tests" / "karplint_fixtures"


# --- acceptance gates -------------------------------------------------------


def test_selftest_every_rule_fires_and_near_misses_stay_clean():
    assert main(["--selftest", str(CORPUS)]) == 0


def test_corpus_run_exits_nonzero():
    # the seeded fixture corpus must fail a plain analyze run
    assert main(["--root", str(CORPUS), "--no-baseline", "."]) == 1


def test_repo_tree_is_clean_with_checked_in_baseline():
    assert main(["--root", str(REPO_ROOT), "karpenter_tpu"]) == 0


def test_full_repo_analyze_under_10s():
    t0 = time.perf_counter()
    analyzer = Analyzer(REPO_ROOT, ["karpenter_tpu", "tests", "tools"])
    analyzer.run(baseline=None)
    assert time.perf_counter() - t0 < 10.0


def test_all_nineteen_rules_registered():
    from tools.karplint import rule_names

    assert rule_names() == [
        "bounded-wait",
        "debug-endpoint",
        "drift-chart",
        "drift-flag",
        "drift-status",
        "event-decision-id",
        "kube-transport",
        "lock-blocking",
        "lock-guard",
        "lock-order",
        "metric-name",
        "mutation-guard",
        "patch-literal-list",
        "reconcile-io",
        "retry-idempotent",
        "span-closed",
        "tracer-branch",
        "tracer-dtype",
        "tracer-host-sync",
    ]


def test_callgraph_is_built_once_per_fileset():
    # every interprocedural rule (tracer pair, lock pair, mutation-guard)
    # shares the memoized graph: a full run constructs at most two — the
    # whole-tree graph plus the solver/-scoped one — no matter how many
    # rules consume them
    from tools.karplint import callgraph

    before = callgraph.BUILD_COUNT
    Analyzer(REPO_ROOT, ["karpenter_tpu"]).run(baseline=None)
    assert callgraph.BUILD_COUNT - before <= 2


# --- CLI surfaces: drift subcommand + SARIF ---------------------------------


def test_drift_subcommand_runs_only_drift_rules(capsys):
    # the drift_bad fixture tree carries flag/chart/status drift on purpose
    rc = main([
        "--root", str(CORPUS / "drift_bad"), "--no-baseline",
        "--format", "json", "drift", ".",
    ])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 1
    rules_fired = {f["rule"] for f in payload["findings"]}
    assert rules_fired  # the seeded drift must be caught
    assert all(r.startswith("drift-") for r in rules_fired)


def test_drift_subcommand_clean_on_repo_tree():
    assert main(["--root", str(REPO_ROOT), "--no-baseline",
                 "drift", "karpenter_tpu"]) == 0


def test_drift_subcommand_rejects_rules_without_drift(capsys):
    rc = main([
        "--root", str(REPO_ROOT), "--rules", "metric-name", "drift", ".",
    ])
    assert rc == 2


def test_sarif_output_is_valid_and_levels_map_severity(tmp_path, capsys):
    (tmp_path / "mod.py").write_text(LOCK_VIOLATION.format(suffix=""))
    rc = main([
        "--root", str(tmp_path), "--no-baseline", "--rules", "lock-guard",
        "--format", "sarif", ".",
    ])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "karplint"
    # the driver catalogs the active rules with default levels
    catalog = {r["id"]: r for r in run["tool"]["driver"]["rules"]}
    assert catalog["lock-guard"]["defaultConfiguration"]["level"] == "error"
    (result,) = run["results"]
    assert result["ruleId"] == "lock-guard"
    assert result["level"] == "error"  # P0 -> error
    loc = result["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"] == "mod.py"
    assert loc["region"]["startLine"] >= 1
    assert run["invocations"][0]["executionSuccessful"] is True


# --- suppression ------------------------------------------------------------

LOCK_VIOLATION = """import threading

class T:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = set()  # guarded-by: self._lock

    def add(self, item):
        self._items.add(item){suffix}
"""


def _run_on(tmp_path, source, rules=None):
    (tmp_path / "mod.py").write_text(source)
    analyzer = Analyzer(tmp_path, ["."], rules=rules)
    active, baselined = analyzer.run(baseline=None)
    return active


def test_unsuppressed_violation_fires(tmp_path):
    active = _run_on(tmp_path, LOCK_VIOLATION.format(suffix=""), rules=["lock-guard"])
    assert [f.rule for f in active] == ["lock-guard"]
    assert active[0].severity == "P0"


def test_same_line_suppression_comment(tmp_path):
    active = _run_on(
        tmp_path,
        LOCK_VIOLATION.format(suffix="  # karplint: disable=lock-guard"),
        rules=["lock-guard"],
    )
    assert active == []


def test_bare_disable_suppresses_all_rules(tmp_path):
    active = _run_on(
        tmp_path,
        LOCK_VIOLATION.format(suffix="  # karplint: disable"),
        rules=["lock-guard"],
    )
    assert active == []


def test_suppressing_a_different_rule_does_not_hide(tmp_path):
    active = _run_on(
        tmp_path,
        LOCK_VIOLATION.format(suffix="  # karplint: disable=metric-name"),
        rules=["lock-guard"],
    )
    assert len(active) == 1


# --- baseline ---------------------------------------------------------------

P1_METRIC = """from prometheus_client import Counter

LAUNCHES = Counter("launches", "No _total suffix.", namespace="karpenter")
"""


def _docs(tmp_path):
    (tmp_path / "docs").mkdir(exist_ok=True)
    (tmp_path / "docs" / "metrics.md").write_text("karpenter_launches\n")


def test_baseline_grandfathers_p1(tmp_path):
    _docs(tmp_path)
    (tmp_path / "metrics.py").write_text(P1_METRIC)
    analyzer = Analyzer(tmp_path, ["."], rules=["metric-name"])
    active, _ = analyzer.run(baseline=None)
    assert len(active) == 1 and active[0].severity == "P1"

    baseline = Baseline.from_findings(analyzer.fingerprints())
    active, baselined = analyzer.run(baseline=baseline)
    assert active == []
    assert len(baselined) == 1


def test_baseline_survives_unrelated_line_drift(tmp_path):
    _docs(tmp_path)
    (tmp_path / "metrics.py").write_text(P1_METRIC)
    analyzer = Analyzer(tmp_path, ["."], rules=["metric-name"])
    baseline = Baseline.from_findings(analyzer.fingerprints())

    # edits ABOVE the grandfathered line move its lineno, not its fingerprint
    (tmp_path / "metrics.py").write_text("# a comment\n# another\n" + P1_METRIC)
    active, baselined = Analyzer(tmp_path, ["."], rules=["metric-name"]).run(
        baseline=baseline
    )
    assert active == []
    assert len(baselined) == 1


def test_baseline_never_hides_p0(tmp_path):
    (tmp_path / "mod.py").write_text(LOCK_VIOLATION.format(suffix=""))
    analyzer = Analyzer(tmp_path, ["."], rules=["lock-guard"])
    baseline = Baseline.from_findings(analyzer.fingerprints())  # P0 entry forced in
    active, baselined = analyzer.run(baseline=baseline)
    assert [f.severity for f in active] == ["P0"]
    assert baselined == []


def test_write_baseline_cli_refuses_p0(tmp_path, monkeypatch):
    (tmp_path / "mod.py").write_text(LOCK_VIOLATION.format(suffix=""))
    out = tmp_path / "baseline.json"
    rc = main([
        "--root", str(tmp_path), "--rules", "lock-guard",
        "--write-baseline", "--baseline", str(out), ".",
    ])
    assert rc == 1  # P0s were skipped and reported
    assert Baseline.load(out).entries == []


# --- rule internals the fixtures can't express ------------------------------


def test_dtype_contract_parsed_from_signature_file():
    analyzer = Analyzer(CORPUS, ["solver"], rules=["tracer-dtype"])
    active, _ = analyzer.run(baseline=None)
    messages = "\n".join(f.message for f in active)
    assert "declares f32" in messages  # frontier contract came from signature.py
    assert "declares bool" in messages  # type_mask
    assert "declares i32" in messages  # join_table builtin


def test_lock_rule_scopes_annotations_per_class(tmp_path):
    src = """import threading

class Annotated:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = set()  # guarded-by: self._lock

    def add(self, x):
        self._items.add(x)

class Unannotated:
    def __init__(self):
        self._items = set()

    def add(self, x):
        self._items.add(x)
"""
    active = _run_on(tmp_path, src, rules=["lock-guard"])
    assert len(active) == 1
    assert "Annotated" not in active[0].message or True
    assert active[0].line == 9  # only the annotated class's mutation


def test_metric_rule_sees_through_local_helper(tmp_path):
    _docs(tmp_path)
    (tmp_path / "metrics.py").write_text(
        """from prometheus_client import Gauge

def _node_gauge(name, doc):
    return Gauge(name, doc, ["node"], namespace="karpenter")

ALLOC = _node_gauge("ghost_gauge", "Not documented.")
"""
    )
    active, _ = Analyzer(tmp_path, ["."], rules=["metric-name"]).run(baseline=None)
    assert any("karpenter_ghost_gauge" in f.message for f in active)


def test_reconcile_io_ignores_helper_methods(tmp_path):
    (tmp_path / "controllers").mkdir()
    (tmp_path / "controllers" / "c.py").write_text(
        """import time

class C:
    def worker(self):
        time.sleep(1)
"""
    )
    active, _ = Analyzer(tmp_path, ["."], rules=["reconcile-io"]).run(baseline=None)
    assert active == []


# --- the runtime halves of the annotations ----------------------------------


def test_idempotent_marker_is_metadata_only():
    from karpenter_tpu.resilience import idempotent, is_idempotent

    def f(x):
        return x * 2

    assert not is_idempotent(f)
    g = idempotent(f)
    assert g is f
    assert is_idempotent(f)
    assert f(3) == 6


def test_providers_carry_idempotent_markers():
    from karpenter_tpu.cloudprovider.fake import FakeCloudProvider
    from karpenter_tpu.resilience import is_idempotent

    p = FakeCloudProvider()
    assert is_idempotent(p.delete)
    assert is_idempotent(p.get_instance_types)
    assert is_idempotent(p.poll_disruptions)
    # create became token-idempotent with the launch-token work: a retried
    # create replays the committed token instead of double-launching
    assert is_idempotent(p.create)


def test_upsert_keyed_replaces_and_appends():
    from karpenter_tpu.kube.patch import upsert_condition, upsert_taint, without_keyed

    base = [
        {"type": "Ready", "status": "True"},
        {"type": "Active", "status": "False"},
    ]
    out = upsert_condition(base, {"type": "Active", "status": "True"})
    assert out == [
        {"type": "Ready", "status": "True"},
        {"type": "Active", "status": "True"},
    ]
    # pure: inputs untouched
    assert base[1]["status"] == "False"
    # append when absent
    out2 = upsert_condition(base, {"type": "New", "status": "True"})
    assert [c["type"] for c in out2] == ["Ready", "Active", "New"]

    taints = [{"key": "a", "effect": "NoSchedule"}]
    out3 = upsert_taint(taints, {"key": "b", "effect": "NoExecute"})
    assert [t["key"] for t in out3] == ["a", "b"]
    assert without_keyed(out3, "a", key="key") == [{"key": "b", "effect": "NoExecute"}]


def test_default_router_lazy_init_is_locked():
    # regression lock-in for the P0 the analyzer found: concurrent first
    # calls must converge on ONE router instance
    import threading

    from karpenter_tpu.solver import router as r

    r.reset_default()
    seen = []
    barrier = threading.Barrier(8)

    def grab():
        barrier.wait()
        seen.append(r.default_router())

    threads = [threading.Thread(target=grab) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len({id(x) for x in seen}) == 1
    r.reset_default()
