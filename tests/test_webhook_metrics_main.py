"""Webhook admission, metrics controllers, options, registry, and the full
runtime wiring (mirrors cmd/webhook, metrics node/pod suites, and
cmd/controller/main.go)."""

import time

import pytest
from prometheus_client import generate_latest

from karpenter_tpu import metrics
from karpenter_tpu.api import labels as lbl
from karpenter_tpu.api.objects import NodeSelectorRequirement, OwnerReference
from karpenter_tpu.cloudprovider import registry
from karpenter_tpu.cloudprovider.fake import FakeCloudProvider, instance_types
from karpenter_tpu.cloudprovider.simulated import SimulatedCloudProvider
from karpenter_tpu.controllers.metrics_node import NodeMetricsController
from karpenter_tpu.controllers.metrics_pod import PodMetricsController
from karpenter_tpu.kube.client import Cluster
from karpenter_tpu.main import build_runtime
from karpenter_tpu.options import Options, parse_args
from karpenter_tpu.webhook import AdmissionError, Webhook
from tests.factories import make_node, make_pod, make_provisioner


def scrape() -> str:
    return generate_latest(metrics.REGISTRY).decode()


class TestCloudProviderMetricsDecorator:
    """All provider methods observed, not just create
    (reference: pkg/cloudprovider/metrics/cloudprovider.go:37-93)."""

    def test_all_methods_observed(self):
        from karpenter_tpu.cloudprovider import metrics as cpmetrics

        provider = cpmetrics.decorate(FakeCloudProvider(instance_types(3)))
        assert cpmetrics.decorate(provider) is provider  # idempotent
        cpmetrics.reconciling_controller.set("provisioning")
        types = provider.get_instance_types(None)
        from karpenter_tpu.cloudprovider.types import NodeRequest

        prov = make_provisioner()
        node = provider.create(
            NodeRequest(template=prov.spec.constraints, instance_type_options=types)
        )
        provider.delete(node)
        out = scrape()
        for method in ("create", "delete", "get_instance_types"):
            assert (
                f'karpenter_cloudprovider_duration_seconds_count{{controller="provisioning",'
                f'method="{method}",provider="fake"}}' in out
            ), f"{method} not observed: {out}"

    def test_manager_sets_controller_label(self):
        from karpenter_tpu.cloudprovider import metrics as cpmetrics
        from karpenter_tpu.controllers.manager import Manager

        seen = []
        manager = Manager(Cluster())
        manager.register("termination", lambda key: seen.append(
            cpmetrics.reconciling_controller.get()
        ))
        manager.reconcile_now("termination", "some-node")
        assert seen == ["termination"]


class TestWebhook:
    def test_defaulting_applies_vendor_hook(self):
        webhook = Webhook(SimulatedCloudProvider())
        prov = make_provisioner()
        webhook.default(prov)
        c = prov.spec.constraints
        assert c.requirements.capacity_types() == {lbl.CAPACITY_TYPE_ON_DEMAND}
        assert c.requirements.architectures() == {lbl.ARCH_AMD64}

    def test_validation_rejects_bad_spec(self):
        webhook = Webhook(FakeCloudProvider(instance_types(2)))
        prov = make_provisioner(ttl_after_empty=-1)
        with pytest.raises(AdmissionError):
            webhook.validate(prov)

    def test_validation_rejects_vendor_errors(self):
        webhook = Webhook(SimulatedCloudProvider())
        prov = make_provisioner(provider={"imageFamily": "bogus"})
        with pytest.raises(AdmissionError) as e:
            webhook.admit(prov)
        assert any("imageFamily" in err for err in e.value.errors)

    def test_admit_passes_good_spec(self):
        webhook = Webhook(SimulatedCloudProvider())
        prov = make_provisioner()
        assert webhook.admit(prov) is prov

    def test_default_solver_flows_to_unset_provisioners(self):
        from karpenter_tpu.api.provisioner import Provisioner

        webhook = Webhook(FakeCloudProvider(instance_types(2)), default_solver="tpu")
        prov = Provisioner()  # solver left unset ("")
        assert prov.spec.solver == ""
        webhook.default(prov)
        assert prov.spec.solver == "tpu"
        # explicit choice wins over the process default
        prov2 = make_provisioner(solver="ffd")
        webhook.default(prov2)
        assert prov2.spec.solver == "ffd"

    def test_restricted_requirement_op_rejected(self):
        webhook = Webhook(FakeCloudProvider(instance_types(2)))
        prov = make_provisioner(
            requirements=[
                NodeSelectorRequirement(key=lbl.TOPOLOGY_ZONE, operator="DoesNotExist")
            ]
        )
        with pytest.raises(AdmissionError):
            webhook.validate(prov)


class TestNodeMetrics:
    def test_gauges_published_and_removed(self):
        cluster = Cluster()
        controller = NodeMetricsController(cluster)
        node = make_node(
            name="metrics-node-1",
            capacity={"cpu": "4", "memory": "8Gi"},
            allocatable={"cpu": "3.8", "memory": "7Gi"},
            provisioner_name="default",
            labels={lbl.TOPOLOGY_ZONE: "z1", lbl.INSTANCE_TYPE: "t3", lbl.ARCH: "amd64",
                    lbl.CAPACITY_TYPE: "on-demand"},
        )
        cluster.create("nodes", node)
        pod = make_pod(node_name="metrics-node-1", unschedulable=False, requests={"cpu": "1"})
        cluster.create("pods", pod)
        ds_pod = make_pod(node_name="metrics-node-1", unschedulable=False, requests={"cpu": "0.2"})
        ds_pod.metadata.owner_references.append(
            OwnerReference(api_version="apps/v1", kind="DaemonSet", name="ds")
        )
        cluster.create("pods", ds_pod)
        controller.reconcile("metrics-node-1")
        out = scrape()
        assert 'karpenter_nodes_allocatable{arch="amd64"' in out
        assert "karpenter_nodes_total_pod_requests" in out
        assert "karpenter_nodes_total_daemon_requests" in out
        assert "karpenter_nodes_system_overhead" in out
        cluster.delete("nodes", "metrics-node-1", namespace="")
        controller.reconcile("metrics-node-1")
        assert 'node_name="metrics-node-1"' not in scrape()


class TestPodMetrics:
    def test_pod_state_gauge_lifecycle(self):
        cluster = Cluster()
        controller = PodMetricsController(cluster)
        node = make_node(name="pm-node", provisioner_name="default",
                         labels={lbl.TOPOLOGY_ZONE: "z9"})
        cluster.create("nodes", node)
        pod = make_pod(name="pm-pod", node_name="pm-node", unschedulable=False)
        cluster.create("pods", pod)
        controller.reconcile("pm-pod")
        out = scrape()
        assert 'karpenter_pods_state{' in out
        assert 'name="pm-pod"' in out and 'zone="z9"' in out
        cluster.delete("pods", "pm-pod")
        controller.reconcile("pm-pod")
        assert 'name="pm-pod"' not in scrape()


class TestOptionsRegistry:
    def test_options_defaults_valid(self):
        assert Options().validate() == []

    def test_parse_args_overrides(self):
        opts = parse_args(["--cloud-provider", "simulated", "--default-solver", "tpu"])
        assert opts.cloud_provider == "simulated"
        assert opts.default_solver == "tpu"

    def test_bad_solver_rejected(self):
        with pytest.raises(SystemExit):
            parse_args(["--default-solver", "quantum"])

    def test_registry_builds_providers(self):
        assert registry.new_cloud_provider("fake").name() == "fake"
        assert registry.new_cloud_provider("simulated").name() == "simulated"
        with pytest.raises(ValueError):
            registry.new_cloud_provider("gcp")


class TestLoggingConfig:
    def test_setup_and_validate(self):
        from karpenter_tpu.logging_config import (
            apply_log_level,
            setup_logging,
            validate_log_config,
        )
        import logging

        setup_logging("info")
        assert logging.getLogger("karpenter").level == logging.INFO
        assert apply_log_level("debug")
        assert logging.getLogger("karpenter").level == logging.DEBUG
        assert not apply_log_level("loud")
        assert validate_log_config("warning") is None
        assert validate_log_config("loud")
        apply_log_level("info")

    def test_watcher_reloads_live(self, tmp_path):
        import logging
        import time as _t

        from karpenter_tpu.logging_config import LogLevelWatcher, setup_logging

        setup_logging("info")
        path = tmp_path / "loglevel"
        path.write_text("warning")
        watcher = LogLevelWatcher(str(path), interval=0.05)
        watcher.start()
        try:
            deadline = _t.monotonic() + 5
            while _t.monotonic() < deadline and logging.getLogger("karpenter").level != logging.WARNING:
                _t.sleep(0.02)
            assert logging.getLogger("karpenter").level == logging.WARNING
            path.write_text("debug")
            deadline = _t.monotonic() + 5
            while _t.monotonic() < deadline and logging.getLogger("karpenter").level != logging.DEBUG:
                _t.sleep(0.02)
            assert logging.getLogger("karpenter").level == logging.DEBUG
        finally:
            watcher.stop()
            logging.getLogger("karpenter").setLevel(logging.INFO)

    def test_bad_log_level_rejected_at_startup(self):
        with pytest.raises(SystemExit):
            parse_args(["--log-level", "loud"])


class TestServedEndpoints:
    def test_metrics_and_healthz_served(self):
        import socket
        import urllib.request

        from karpenter_tpu.main import run_controller_process

        def free_port():
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
            s.close()
            return port

        opts = Options(metrics_port=free_port(), health_probe_port=free_port())
        runtime = run_controller_process(opts)
        try:
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{opts.metrics_port}/metrics", timeout=5
            ).read().decode()
            assert "karpenter" in body
            health = urllib.request.urlopen(
                f"http://127.0.0.1:{opts.health_probe_port}/healthz", timeout=5
            )
            assert health.status == 200
            # the controller health server also judges: run_controller_process
            # installs the online SLO engine, so /debug/slo serves the
            # default objectives (no data yet — ok stays null, not failing)
            import json

            with urllib.request.urlopen(
                f"http://127.0.0.1:{opts.health_probe_port}/debug/slo", timeout=5
            ) as resp:
                slo = json.loads(resp.read())["slo"]
            assert "solve_p99" in slo["objectives"]
            assert slo["objectives"]["solve_p99"]["ok"] is None
            # and /debug/traces carries the exporter stats + query filters
            with urllib.request.urlopen(
                f"http://127.0.0.1:{opts.health_probe_port}/debug/traces?limit=1",
                timeout=5,
            ) as resp:
                traces = json.loads(resp.read())
            assert "stats" in traces and len(traces["traces"]) <= 1
        finally:
            runtime.stop()


class TestRuntime:
    def test_full_runtime_end_to_end(self):
        """cmd/controller/main.go analog: start everything, create a
        provisioner + pods, watch them get scheduled; then delete the node
        and watch termination drain it."""
        runtime = build_runtime(
            cloud_provider=FakeCloudProvider(instance_types(10)), start_workers=True
        )
        runtime.manager.start()
        try:
            cluster = runtime.cluster
            prov = runtime.webhook.admit(make_provisioner())
            cluster.create("provisioners", prov)
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and not runtime.provisioning.list_workers():
                time.sleep(0.02)
            for w in runtime.provisioning.list_workers():
                w.batcher.idle_duration = 0.05
            pods = [make_pod(requests={"cpu": "1"}) for _ in range(3)]
            for p in pods:
                cluster.create("pods", p)
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline and any(
                p.spec.node_name == "" for p in cluster.pods()
            ):
                time.sleep(0.05)
            assert all(p.spec.node_name for p in cluster.pods())
            assert cluster.nodes()
            # usage accounting flowed into status
            deadline = time.monotonic() + 5
            prov_live = cluster.get("provisioners", "default", namespace="")
            while time.monotonic() < deadline and not prov_live.status.resources:
                time.sleep(0.05)
            assert prov_live.status.resources
            # now delete the node: termination should drain + remove it
            node = cluster.nodes()[0]
            cluster.delete("nodes", node.metadata.name, namespace="")
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline and cluster.try_get(
                "nodes", node.metadata.name, namespace=""
            ) is not None:
                time.sleep(0.05)
            assert cluster.try_get("nodes", node.metadata.name, namespace="") is None
        finally:
            runtime.stop()
