"""Consolidation re-pack tests (BASELINE config 5 — capability beyond the
reference): batched re-solve of live nodes, price accounting, safety gates,
and end-to-end migration."""

import pytest

from karpenter_tpu.api import labels as lbl
from karpenter_tpu.cloudprovider.fake import FakeCloudProvider, instance_types, new_instance_type
from karpenter_tpu.cloudprovider.requirements import catalog_requirements
from karpenter_tpu.controllers.consolidation import ConsolidationController
from karpenter_tpu.kube.client import Cluster
from karpenter_tpu.utils import resources as res
from tests.factories import make_node, make_pod, make_provisioner


def build_env(catalog=None, solver="ffd"):
    cluster = Cluster()
    provider = FakeCloudProvider(catalog if catalog is not None else instance_types(20))
    provisioner = make_provisioner(solver=solver)
    c = provisioner.spec.constraints
    c.requirements = c.requirements.merge(
        catalog_requirements(provider.get_instance_types())
    )
    cluster.create("provisioners", provisioner)
    controller = ConsolidationController(cluster, provider)
    return cluster, provider, provisioner, controller


def fragmented_cluster(cluster, n_nodes=4, pods_per_node=1, instance_type="fake-it-19"):
    """N big nodes each nearly empty — the classic consolidation target."""
    for i in range(n_nodes):
        node = make_node(
            name=f"big-{i}",
            capacity={"cpu": "20", "memory": "40Gi", "pods": "200"},
            provisioner_name="default",
            labels={lbl.INSTANCE_TYPE: instance_type, lbl.TOPOLOGY_ZONE: "test-zone-1",
                    lbl.CAPACITY_TYPE: "on-demand"},
            finalizers=[lbl.TERMINATION_FINALIZER],
        )
        cluster.create("nodes", node)
        for j in range(pods_per_node):
            cluster.create(
                "pods",
                make_pod(
                    name=f"pod-{i}-{j}",
                    requests={"cpu": "0.5"},
                    node_name=node.metadata.name,
                    unschedulable=False,
                ),
            )


class TestPlanning:
    def test_plan_finds_cheaper_packing(self):
        cluster, provider, provisioner, controller = build_env()
        fragmented_cluster(cluster)
        plan = controller.plan(provisioner)
        assert len(plan.nodes) == 4
        assert len(plan.pods) == 4
        assert plan.proposed  # everything fits on far fewer/cheaper nodes
        assert plan.proposed_price < plan.current_price
        assert plan.worthwhile

    def test_empty_cluster_no_plan(self):
        cluster, provider, provisioner, controller = build_env()
        plan = controller.plan(provisioner)
        assert not plan.worthwhile

    def test_do_not_evict_node_excluded(self):
        cluster, provider, provisioner, controller = build_env()
        fragmented_cluster(cluster, n_nodes=2)
        pod = cluster.get("pods", "pod-0-0")
        pod.metadata.annotations[lbl.DO_NOT_EVICT_ANNOTATION] = "true"
        plan = controller.plan(provisioner)
        assert {n.metadata.name for n in plan.nodes} == {"big-1"}

    def test_deleting_and_cordoned_nodes_excluded(self):
        cluster, provider, provisioner, controller = build_env()
        fragmented_cluster(cluster, n_nodes=3)
        cluster.get("nodes", "big-0", namespace="").spec.unschedulable = True
        cluster.delete("nodes", "big-1", namespace="")
        plan = controller.plan(provisioner)
        assert {n.metadata.name for n in plan.nodes} == {"big-2"}

    def test_unplaceable_pods_block_consolidation(self):
        """If the re-pack cannot seat every pod, the plan must not execute."""
        catalog = [new_instance_type("tiny", resources={res.CPU: 1.0, res.PODS: 2.0})]
        cluster, provider, provisioner, controller = build_env(catalog=catalog)
        node = make_node(
            name="old", capacity={"cpu": "64"}, provisioner_name="default",
            labels={lbl.INSTANCE_TYPE: "huge-legacy"},
        )
        cluster.create("nodes", node)
        cluster.create(
            "pods",
            make_pod(requests={"cpu": "32"}, node_name="old", unschedulable=False),
        )
        plan = controller.plan(provisioner)
        assert sum(len(v.pods) for v in plan.proposed) == 0
        assert not plan.worthwhile

    def test_marginal_savings_not_worthwhile(self):
        """Savings under the 5% churn threshold are rejected."""
        cluster, provider, provisioner, controller = build_env()
        # one pod on the node it would choose anyway → zero savings
        node = make_node(
            name="right-sized",
            capacity={"cpu": "1", "memory": "2Gi", "pods": "10"},
            provisioner_name="default",
            labels={lbl.INSTANCE_TYPE: "fake-it-0", lbl.TOPOLOGY_ZONE: "test-zone-1",
                    lbl.CAPACITY_TYPE: "on-demand"},
        )
        cluster.create("nodes", node)
        cluster.create(
            "pods",
            make_pod(requests={"cpu": "0.5"}, node_name="right-sized", unschedulable=False),
        )
        plan = controller.plan(provisioner)
        assert not plan.worthwhile


class TestExecution:
    def test_execute_migrates_pods_and_retires_nodes(self):
        cluster, provider, provisioner, controller = build_env()
        fragmented_cluster(cluster)
        plan = controller.plan(provisioner)
        launched = controller.execute(plan)
        assert len(launched) < 4  # consolidated
        live_nodes = {
            n.metadata.name
            for n in cluster.nodes()
            if n.metadata.deletion_timestamp is None
        }
        assert live_nodes == {n.metadata.name for n in launched}
        for pod in cluster.pods():
            assert pod.spec.node_name in live_nodes
        # old nodes are terminating (finalizer-bearing), awaiting drain
        for i in range(4):
            old = cluster.try_get("nodes", f"big-{i}", namespace="")
            assert old is None or old.metadata.deletion_timestamp is not None

    def test_reconcile_runs_plan_and_requeues(self):
        cluster, provider, provisioner, controller = build_env()
        fragmented_cluster(cluster)
        assert controller.reconcile("default") == 300.0
        live = [n for n in cluster.nodes() if n.metadata.deletion_timestamp is None]
        assert len(live) < 4

    def test_disabled_controller_noop(self):
        cluster, provider, provisioner, controller = build_env()
        controller.enabled = False
        fragmented_cluster(cluster)
        assert controller.reconcile("default") is None
        assert len(cluster.nodes()) == 4

    def test_anti_affinity_workload_can_consolidate(self):
        """The candidates' own live pods must not block their re-pack: two
        anti-affinity pods on two huge nodes consolidate onto two cheap nodes
        (their old seats don't count as occupied zones)."""
        from karpenter_tpu.api.objects import LabelSelector, PodAffinityTerm

        cluster, provider, provisioner, controller = build_env()
        sel = {"app": "ha"}
        for i, zone in enumerate(["test-zone-1", "test-zone-2"]):
            node = make_node(
                name=f"huge-{i}",
                capacity={"cpu": "20", "memory": "40Gi", "pods": "200"},
                provisioner_name="default",
                labels={lbl.INSTANCE_TYPE: "fake-it-19", lbl.TOPOLOGY_ZONE: zone,
                        lbl.CAPACITY_TYPE: "on-demand"},
                finalizers=[lbl.TERMINATION_FINALIZER],
            )
            cluster.create("nodes", node)
            pod = make_pod(
                name=f"ha-{i}", labels=sel, requests={"cpu": "0.5"},
                node_name=node.metadata.name, unschedulable=False,
                pod_anti_requirements=[
                    PodAffinityTerm(
                        label_selector=LabelSelector(match_labels=sel),
                        topology_key=lbl.TOPOLOGY_ZONE,
                    )
                ],
            )
            cluster.create("pods", pod)
        plan = controller.plan(provisioner)
        assert sum(len(v.pods) for v in plan.proposed) == 2  # both re-seated
        assert plan.worthwhile

    def test_consolidation_under_live_manager(self):
        """The full async loop: consolidation reconciles via the manager,
        migrates pods to cheaper capacity, and termination drains the old
        nodes to completion."""
        import time

        from karpenter_tpu.cloudprovider.fake import FakeCloudProvider
        from karpenter_tpu.main import build_runtime

        runtime = build_runtime(
            cloud_provider=FakeCloudProvider(instance_types(20)),
            start_workers=True,
            consolidation_enabled=True,
        )
        cluster = runtime.cluster
        cluster.create("provisioners", make_provisioner())
        fragmented_cluster(cluster)
        runtime.manager.start()
        try:
            runtime.manager.enqueue("consolidation", "default")
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline:
                live = [
                    n for n in cluster.nodes() if n.metadata.deletion_timestamp is None
                ]
                old_gone = all(
                    cluster.try_get("nodes", f"big-{i}", namespace="") is None
                    for i in range(4)
                )
                if len(live) < 4 and old_gone:
                    break
                time.sleep(0.05)
            live = [n for n in cluster.nodes() if n.metadata.deletion_timestamp is None]
            assert len(live) < 4  # consolidated
            # termination finished draining every retired node
            for i in range(4):
                assert cluster.try_get("nodes", f"big-{i}", namespace="") is None
            # every pod survived the migration, seated on a live node
            live_names = {n.metadata.name for n in live}
            pods = cluster.pods()
            assert len(pods) == 4
            assert all(p.spec.node_name in live_names for p in pods)
        finally:
            runtime.stop()

    def test_tpu_solver_consolidation(self):
        cluster, provider, provisioner, controller = build_env(solver="tpu")
        fragmented_cluster(cluster)
        plan = controller.plan(provisioner)
        assert plan.worthwhile
        launched = controller.execute(plan)
        assert 1 <= len(launched) < 4


class TestEvictWavePacing:
    """Evict-mode retirement is paced (ADVICE r2 / VERDICT r2 weak #5):
    at most EVICT_WAVE_SIZE nodes per reconcile, and the next wave is gated
    on the prior wave's nodes being gone AND the recreated pods having
    re-seated — a large worthwhile plan must never be a cluster-wide
    disruption storm."""

    def _evict_env(self, n_nodes):
        from karpenter_tpu.api.objects import OwnerReference

        cluster = Cluster()
        provider = FakeCloudProvider(instance_types(20))
        provisioner = make_provisioner(solver="ffd")
        c = provisioner.spec.constraints
        c.requirements = c.requirements.merge(
            catalog_requirements(provider.get_instance_types())
        )
        cluster.create("provisioners", provisioner)
        controller = ConsolidationController(cluster, provider, migration="evict")
        owner = OwnerReference(api_version="apps/v1", kind="ReplicaSet", name="rs")
        for i in range(n_nodes):
            node = make_node(
                name=f"big-{i}",
                capacity={"cpu": "20", "memory": "40Gi", "pods": "200"},
                provisioner_name="default",
                labels={lbl.INSTANCE_TYPE: "fake-it-19",
                        lbl.TOPOLOGY_ZONE: "test-zone-1",
                        lbl.CAPACITY_TYPE: "on-demand"},
            )
            cluster.create("nodes", node)
            cluster.create(
                "pods",
                make_pod(name=f"pod-{i}", requests={"cpu": "0.5"},
                         node_name=node.metadata.name, unschedulable=False,
                         owner=owner),
            )
        return cluster, controller, provisioner

    def test_waves_bound_concurrent_disruption(self):
        from karpenter_tpu.controllers.consolidation import (
            EVICT_WAVE_SIZE,
            WAVE_CHECK_INTERVAL,
        )

        n = 40
        cluster, controller, provisioner = self._evict_env(n)
        before = {x.metadata.name for x in cluster.nodes()}
        requeue = controller.reconcile(provisioner.metadata.name)
        after = {x.metadata.name for x in cluster.nodes()}
        # exactly one wave retired, not the whole worthwhile plan
        assert len(before - after) == EVICT_WAVE_SIZE
        assert requeue == WAVE_CHECK_INTERVAL

    def test_next_wave_gated_on_reseating(self):
        from karpenter_tpu.controllers.consolidation import EVICT_WAVE_SIZE

        cluster, controller, provisioner = self._evict_env(20)
        controller.reconcile(provisioner.metadata.name)
        n_after_first = len(cluster.nodes())
        # the recreated workload is still pending — wave NOT settled
        pending = make_pod(name="recreated-0", requests={"cpu": "0.5"})
        cluster.create("pods", pending)
        assert controller.wave_settled(provisioner.metadata.name) is False
        controller.reconcile(provisioner.metadata.name)
        assert len(cluster.nodes()) == n_after_first  # no new disruption
        # the pod re-seats -> the gate opens -> the next wave proceeds
        survivors = cluster.nodes()
        cluster.bind(pending, survivors[0].metadata.name)
        assert controller.wave_settled(provisioner.metadata.name) is True
        controller.reconcile(provisioner.metadata.name)
        assert len(cluster.nodes()) < n_after_first
        assert n_after_first - len(cluster.nodes()) <= EVICT_WAVE_SIZE

    def test_thousand_node_plan_is_paced(self):
        """The BASELINE 1k-node config as an OPERATION: the first reconcile
        of a 1000-node worthwhile plan disrupts at most one wave."""
        from karpenter_tpu.controllers.consolidation import EVICT_WAVE_SIZE

        cluster, controller, provisioner = self._evict_env(1000)
        controller.reconcile(provisioner.metadata.name)
        assert 1000 - len(cluster.nodes()) == EVICT_WAVE_SIZE

    def test_preexisting_pending_pod_does_not_gate_waves(self):
        """A pod that was ALREADY unschedulable before the wave launched
        (e.g. permanently unsatisfiable) must not deadlock consolidation."""
        cluster, controller, provisioner = self._evict_env(20)
        cluster.create("pods", make_pod(name="stuck-forever", requests={"cpu": "999"}))
        n0 = len(cluster.nodes())
        controller.reconcile(provisioner.metadata.name)
        n1 = len(cluster.nodes())
        assert n0 - n1 > 0  # first wave ran despite the stuck pod
        # the stuck pod is in the wave's baseline: the gate opens
        assert controller.wave_settled(provisioner.metadata.name) is True
        controller.reconcile(provisioner.metadata.name)
        assert len(cluster.nodes()) < n1  # second wave proceeded

    def test_wave_settle_timeout_releases_the_gate(self):
        from karpenter_tpu.controllers.consolidation import WAVE_SETTLE_TIMEOUT

        now = [1000.0]
        cluster = Cluster(clock=lambda: now[0])
        provider = FakeCloudProvider(instance_types(20))
        provisioner = make_provisioner(solver="ffd")
        c = provisioner.spec.constraints
        c.requirements = c.requirements.merge(
            catalog_requirements(provider.get_instance_types())
        )
        cluster.create("provisioners", provisioner)
        controller = ConsolidationController(cluster, provider, migration="evict")
        from karpenter_tpu.api.objects import OwnerReference

        owner = OwnerReference(api_version="apps/v1", kind="ReplicaSet", name="rs")
        for i in range(12):
            node = make_node(
                name=f"big-{i}", capacity={"cpu": "20", "memory": "40Gi", "pods": "200"},
                provisioner_name="default",
                labels={lbl.INSTANCE_TYPE: "fake-it-19", lbl.TOPOLOGY_ZONE: "test-zone-1",
                        lbl.CAPACITY_TYPE: "on-demand"},
            )
            cluster.create("nodes", node)
            cluster.create("pods", make_pod(name=f"pod-{i}", requests={"cpu": "0.5"},
                                            node_name=node.metadata.name,
                                            unschedulable=False, owner=owner))
        controller.reconcile(provisioner.metadata.name)
        # a NEW stuck pod appears after the wave: the gate holds...
        cluster.create("pods", make_pod(name="new-stuck", requests={"cpu": "999"}))
        assert controller.wave_settled(provisioner.metadata.name) is False
        # ...until the settle deadline passes — then it releases (logged)
        now[0] += WAVE_SETTLE_TIMEOUT + 1
        assert controller.wave_settled(provisioner.metadata.name) is True


# ---------------------------------------------------------------------------
# Minimal-move matching + disruption-cost ordering (solver/repack.py)
# ---------------------------------------------------------------------------


class TestMinimalMove:
    def _node(self, name, itype, capacity_type="on-demand", zone="test-zone-1"):
        return make_node(
            name=name, provisioner_name="default",
            labels={lbl.INSTANCE_TYPE: itype, lbl.CAPACITY_TYPE: capacity_type,
                    lbl.TOPOLOGY_ZONE: zone},
        )

    def _vnode(self, itype_name, pods):
        from karpenter_tpu.scheduling.ffd import VirtualNode

        return VirtualNode(
            constraints=None,
            instance_type_options=[new_instance_type(itype_name)],
            pods=pods,
        )

    def test_exact_match_is_kept_not_moved(self):
        from karpenter_tpu.solver.repack import minimal_move_match

        p1, p2, p3 = (make_pod(name=f"p{i}") for i in (1, 2, 3))
        a = self._node("a", "it-big")
        b = self._node("b", "it-big")
        node_pods = {"a": [p1, p2], "b": [p3]}
        # the proposal re-creates a's packing verbatim and re-seats p3
        # elsewhere: a is its own replacement; only b churns
        proposed = [self._vnode("it-big", [p1, p2]), self._vnode("it-small", [p3])]
        match = minimal_move_match([a, b], node_pods, proposed)
        assert [n.metadata.name for n in match.keep] == ["a"]
        assert [n.metadata.name for n in match.retire] == ["b"]
        assert len(match.launch) == 1
        assert [p.metadata.name for p in match.moves] == ["p3"]

    def test_same_pods_different_instance_type_is_not_a_match(self):
        from karpenter_tpu.solver.repack import minimal_move_match

        p1 = make_pod(name="p1")
        a = self._node("a", "it-big")
        # the proposal wants the same pod set on a CHEAPER type — the
        # signature must not pair them, or the downsize would never happen
        proposed = [self._vnode("it-small", [p1])]
        match = minimal_move_match([a], {"a": [p1]}, proposed)
        assert match.keep == []
        assert [n.metadata.name for n in match.retire] == ["a"]
        assert len(match.launch) == 1

    def test_duplicate_signatures_pair_one_to_one(self):
        from karpenter_tpu.solver.repack import minimal_move_match

        p1, p2 = make_pod(name="p1"), make_pod(name="p2")
        a = self._node("a", "it-big")
        b = self._node("b", "it-big")
        # two empty-identical worlds, but the proposal needs only one of
        # the signature — the pool must not double-spend the match
        proposed = [self._vnode("it-big", [p1]), self._vnode("it-big", [p2])]
        match = minimal_move_match(
            [a, b], {"a": [p1], "b": [p1]}, proposed
        )
        # only a (name-ordered) holds [p1]; the [p2] vnode has no twin
        assert [n.metadata.name for n in match.keep] == ["a"]
        assert [n.metadata.name for n in match.retire] == ["b"]

    def test_retirement_orders_cheapest_disruption_first(self):
        from karpenter_tpu.solver.repack import order_retirement

        cheap = self._node("cheap", "it-small")
        pricey = self._node("pricey", "it-big")
        out = order_retirement(
            [pricey, cheap], {},
            {"it-small": 0.1, "it-big": 2.0},
            lambda ct, z: 0.0,
        )
        assert [n.metadata.name for n in out] == ["cheap", "pricey"]

    def test_interruption_risk_discounts_doomed_capacity(self):
        from karpenter_tpu.solver.repack import order_retirement

        stable = self._node("stable", "it-big", capacity_type="on-demand")
        doomed = self._node("doomed", "it-big", capacity_type="spot")
        # same price, but the cloud keeps reclaiming spot in this zone:
        # the voluntary wave should spend its budget there first
        out = order_retirement(
            [stable, doomed], {},
            {"it-big": 1.0},
            lambda ct, z: 0.9 if ct == "spot" else 0.0,
        )
        assert [n.metadata.name for n in out] == ["doomed", "stable"]

    def test_move_charge_prefers_emptier_nodes(self):
        from karpenter_tpu.solver.repack import order_retirement

        empty = self._node("empty", "it-big")
        crowded = self._node("crowded", "it-big")
        out = order_retirement(
            [crowded, empty],
            {"crowded": [make_pod(name=f"c{i}") for i in range(5)], "empty": []},
            {"it-big": 1.0},
            lambda ct, z: 0.0,
        )
        assert [n.metadata.name for n in out] == ["empty", "crowded"]

    def test_disruption_cost_clamps_risk(self):
        from karpenter_tpu.solver.repack import MOVE_COST, disruption_cost

        node = self._node("n", "it")
        # risk over 1 must not turn the cost negative
        assert disruption_cost(node, [], 2.0, 5.0) == 0.0
        assert disruption_cost(node, [make_pod()], 2.0, 5.0) == MOVE_COST
        assert disruption_cost(node, [], 2.0, -1.0) == 2.0


# ---------------------------------------------------------------------------
# Plan-time PDB victim screening (controllers/disruption.py)
# ---------------------------------------------------------------------------


class TestPDBScreening:
    def _evict_env(self):
        from karpenter_tpu.api.objects import OwnerReference

        cluster = Cluster()
        provider = FakeCloudProvider(instance_types(20))
        provisioner = make_provisioner(solver="ffd")
        c = provisioner.spec.constraints
        c.requirements = c.requirements.merge(
            catalog_requirements(provider.get_instance_types())
        )
        cluster.create("provisioners", provisioner)
        controller = ConsolidationController(cluster, provider, migration="evict")
        owner = OwnerReference(api_version="apps/v1", kind="ReplicaSet", name="rs")
        for i in range(2):
            node = make_node(
                name=f"big-{i}",
                capacity={"cpu": "20", "memory": "40Gi", "pods": "200"},
                provisioner_name="default",
                labels={lbl.INSTANCE_TYPE: "fake-it-19",
                        lbl.TOPOLOGY_ZONE: "test-zone-1",
                        lbl.CAPACITY_TYPE: "on-demand"},
            )
            cluster.create("nodes", node)
            cluster.create(
                "pods",
                make_pod(name=f"db-{i}", labels={"app": "db"},
                         requests={"cpu": "0.5"}, node_name=node.metadata.name,
                         unschedulable=False, owner=owner),
            )
        return cluster, controller, provisioner

    def test_frozen_pdb_excludes_nodes_at_plan_time(self):
        from tests.factories import make_pdb

        cluster, controller, provisioner = self._evict_env()
        # minAvailable == replica count: zero disruptions allowed RIGHT NOW
        cluster.create("pdbs", make_pdb(labels={"app": "db"}, min_available=2))
        plan = controller.plan(provisioner)
        assert plan.nodes == []  # both nodes screened out before any cordon

    def test_pdb_with_headroom_does_not_freeze(self):
        from tests.factories import make_pdb

        cluster, controller, provisioner = self._evict_env()
        cluster.create("pdbs", make_pdb(labels={"app": "db"}, min_available=1))
        plan = controller.plan(provisioner)
        assert len(plan.nodes) == 2

    def test_max_unavailable_zero_freezes(self):
        from karpenter_tpu.controllers.disruption import pdb_frozen_pod_keys
        from tests.factories import make_pdb

        cluster, controller, provisioner = self._evict_env()
        cluster.create("pdbs", make_pdb(labels={"app": "db"}, max_unavailable=0))
        frozen = pdb_frozen_pod_keys(cluster)
        assert len(frozen) == 2
        assert controller.plan(provisioner).nodes == []

    def test_unrelated_pdb_does_not_freeze(self):
        from karpenter_tpu.controllers.disruption import pdb_frozen_pod_keys
        from tests.factories import make_pdb

        cluster, controller, provisioner = self._evict_env()
        cluster.create("pdbs", make_pdb(labels={"app": "other"}, min_available=5))
        assert pdb_frozen_pod_keys(cluster) == set()


# ---------------------------------------------------------------------------
# The journaled, orchestrated wave + crash replay (launch/recovery.py)
# ---------------------------------------------------------------------------


def orchestrated_env(n_nodes, clock=None, journal=None):
    """Evict-mode controller wired the way main.py wires it: the
    taint→replace→drain orchestrator plus a crash journal."""
    from karpenter_tpu.api.objects import OwnerReference
    from karpenter_tpu.interruption.orchestrator import Orchestrator
    from karpenter_tpu.launch.journal import MemoryLaunchJournal

    cluster = Cluster(clock=clock) if clock else Cluster()
    provider = FakeCloudProvider(instance_types(20))
    provisioner = make_provisioner(solver="ffd")
    c = provisioner.spec.constraints
    c.requirements = c.requirements.merge(
        catalog_requirements(provider.get_instance_types())
    )
    cluster.create("provisioners", provisioner)
    journal = journal if journal is not None else MemoryLaunchJournal()
    controller = ConsolidationController(
        cluster, provider, migration="evict",
        orchestrator=Orchestrator(cluster, provider, None, None),
        journal=journal,
    )
    owner = OwnerReference(api_version="apps/v1", kind="ReplicaSet", name="rs")
    for i in range(n_nodes):
        node = make_node(
            name=f"big-{i}",
            capacity={"cpu": "20", "memory": "40Gi", "pods": "200"},
            provisioner_name="default",
            labels={lbl.INSTANCE_TYPE: "fake-it-19",
                    lbl.TOPOLOGY_ZONE: "test-zone-1",
                    lbl.CAPACITY_TYPE: "on-demand"},
        )
        cluster.create("nodes", node)
        cluster.create(
            "pods",
            make_pod(name=f"pod-{i}", requests={"cpu": "0.5"},
                     node_name=node.metadata.name, unschedulable=False,
                     owner=owner),
        )
    return cluster, controller, provisioner, journal


class TestJournaledWave:
    def test_wave_journaled_before_first_victim_is_touched(self):
        from karpenter_tpu.launch.journal import MemoryLaunchJournal

        cordoned_at_record = []

        class SpyJournal(MemoryLaunchJournal):
            def record_intent(self, *args, **kwargs):
                cordoned_at_record.append(
                    sum(1 for n in spy_cluster.nodes() if n.spec.unschedulable)
                )
                return super().record_intent(*args, **kwargs)

        cluster, controller, provisioner, journal = orchestrated_env(
            12, journal=SpyJournal()
        )
        spy_cluster = cluster
        controller.reconcile("default")
        # the intent was written while ZERO victims were cordoned — the
        # entry is the complete blast radius for a crash at ANY point
        assert cordoned_at_record == [0]
        (entry,) = journal.unresolved()
        assert entry.marker == "consolidation"
        assert entry.decision_id  # tied to the audit record
        assert len(entry.victims) == controller.wave_size
        # every journaled victim is now draining (orchestrator handoff)
        for name in entry.victims:
            node = cluster.try_get("nodes", name, namespace="")
            assert node is not None and node.metadata.deletion_timestamp is not None

    def test_settled_wave_resolves_journal_and_counts_reclaimed(self):
        cluster, controller, provisioner, journal = orchestrated_env(12)
        controller.reconcile("default")
        (entry,) = journal.unresolved()
        # finish the drains (the termination controller's job) and re-seat
        # the displaced pods
        for name in entry.victims:
            node = cluster.try_get("nodes", name, namespace="")
            cluster.remove_finalizer("nodes", node, lbl.TERMINATION_FINALIZER)
        survivor = next(
            n for n in cluster.nodes() if n.metadata.name not in entry.victims
        )
        for p in cluster.pods():
            if not p.spec.node_name:
                cluster.bind(p, survivor.metadata.name)
        assert controller.wave_settled("default") is True
        assert journal.unresolved() == []
        assert controller.nodes_reclaimed == len(entry.victims)
        assert controller.ledger.in_flight("default") == 0

    def test_events_carry_the_decision_id(self):
        from karpenter_tpu.kube.events import DECISION_ID_ANNOTATION

        cluster, controller, provisioner, journal = orchestrated_env(12)
        controller.reconcile("default")
        (entry,) = journal.unresolved()
        stamped = [
            e for e in cluster.list("events")
            if e.metadata.annotations.get(DECISION_ID_ANNOTATION)
            == entry.decision_id
        ]
        # the wave summary (Consolidated) and every per-victim drain
        # warning rejoin the same audit record
        reasons = {e.reason for e in stamped}
        assert "Consolidated" in reasons
        assert "ConsolidationDrain" in reasons


class TestCrashedWaveReplay:
    def _crashed_wave(self):
        """The post-crash world: intent journaled, some victims cordoned,
        the owning replica dead before any drain handoff."""
        from karpenter_tpu.api.objects import Taint
        from karpenter_tpu.launch.journal import MemoryLaunchJournal

        cluster = Cluster()
        journal = MemoryLaunchJournal(clock=lambda: 0.0)
        for i in range(3):
            node = make_node(name=f"victim-{i}", provisioner_name="default")
            cluster.create("nodes", node)
        for i in range(2):  # the crash hit after cordoning two of three
            node = cluster.get("nodes", f"victim-{i}", namespace="")
            node.spec.unschedulable = True
            node.spec.taints.append(
                Taint(key=lbl.INTERRUPTION_TAINT_KEY, value="consolidation",
                      effect="NoSchedule")
            )
        journal.record_intent(
            "consolidation-deadbeef", "default", marker="consolidation",
            victims=["victim-0", "victim-1", "victim-2"],
            decision_id="d-123",
        )
        (entry,) = journal.unresolved()
        return cluster, journal, entry

    def test_replay_uncordons_survivors_and_resolves(self):
        from karpenter_tpu.launch.recovery import (
            CONSOLIDATION_REPLAYED,
            replay_entry,
        )

        cluster, journal, entry = self._crashed_wave()
        out = replay_entry(
            journal, cluster, None, entry, {}, now=100.0, replay_after=10.0
        )
        assert out == CONSOLIDATION_REPLAYED
        assert journal.unresolved() == []
        for i in range(3):
            node = cluster.get("nodes", f"victim-{i}", namespace="")
            assert node.spec.unschedulable is False
            assert not any(
                t.key == lbl.INTERRUPTION_TAINT_KEY for t in node.spec.taints
            )

    def test_replay_preserves_unrelated_taints(self):
        from karpenter_tpu.api.objects import Taint
        from karpenter_tpu.launch.recovery import replay_entry

        cluster, journal, entry = self._crashed_wave()
        node = cluster.get("nodes", "victim-0", namespace="")
        node.spec.taints.append(
            Taint(key="dedicated", value="gpu", effect="NoSchedule")
        )
        replay_entry(journal, cluster, None, entry, {}, now=100.0,
                     replay_after=10.0)
        node = cluster.get("nodes", "victim-0", namespace="")
        assert [t.key for t in node.spec.taints] == ["dedicated"]

    def test_replay_skips_already_deleted_victims(self):
        from karpenter_tpu.launch.recovery import (
            CONSOLIDATION_REPLAYED,
            replay_entry,
        )

        cluster, journal, entry = self._crashed_wave()
        cluster.delete("nodes", "victim-2", namespace="")
        out = replay_entry(
            journal, cluster, None, entry, {}, now=100.0, replay_after=10.0
        )
        assert out == CONSOLIDATION_REPLAYED
        assert journal.unresolved() == []

    def test_young_entry_is_left_for_the_live_wave(self):
        from karpenter_tpu.launch.recovery import PENDING, replay_entry

        cluster, journal, entry = self._crashed_wave()
        # younger than the replay grace: the owning replica may still be
        # alive mid-wave — replay must not race it
        out = replay_entry(
            journal, cluster, None, entry, {}, now=5.0, replay_after=10.0
        )
        assert out == PENDING
        assert len(journal.unresolved()) == 1
        assert cluster.get("nodes", "victim-0", namespace="").spec.unschedulable

    def test_uncordon_failure_retries_next_sweep(self):
        from karpenter_tpu.launch.recovery import PENDING, replay_entry

        cluster, journal, entry = self._crashed_wave()

        def failing_patch(*args, **kwargs):
            raise RuntimeError("apiserver blip")

        cluster.merge_patch = failing_patch
        out = replay_entry(
            journal, cluster, None, entry, {}, now=100.0, replay_after=10.0
        )
        assert out == PENDING
        # the entry survives for the next sweep — resolving on a failed
        # un-cordon would strand the victims cordoned forever
        assert len(journal.unresolved()) == 1

    def test_wave_entry_never_reads_as_never_launched(self):
        from karpenter_tpu.launch.recovery import (
            CONSOLIDATION_REPLAYED,
            NEVER_LAUNCHED,
            replay_entry,
        )

        cluster, journal, entry = self._crashed_wave()
        # a wave entry carries no launch token, so the generic ladder
        # would misread it as NEVER_LAUNCHED and resolve without
        # un-cordoning anything — the marker branch must win
        out = replay_entry(
            journal, cluster, None, entry, {}, now=100.0, replay_after=10.0
        )
        assert out == CONSOLIDATION_REPLAYED
        assert out != NEVER_LAUNCHED


class TestWaveSettleHardening:
    def test_out_of_band_victim_delete_settles_cleanly(self):
        """A victim force-deleted by an operator mid-wave must settle the
        wave, resolve its journal entry, and release the budget."""
        cluster, controller, provisioner, journal = orchestrated_env(12)
        controller.reconcile("default")
        (entry,) = journal.unresolved()
        for name in entry.victims:
            node = cluster.try_get("nodes", name, namespace="")
            node.metadata.finalizers = []
            cluster.remove_finalizer("nodes", node, lbl.TERMINATION_FINALIZER)
        survivor = next(
            n for n in cluster.nodes() if n.metadata.name not in entry.victims
        )
        for p in cluster.pods():
            if not p.spec.node_name:
                cluster.bind(p, survivor.metadata.name)
        assert controller.wave_settled("default") is True
        assert journal.unresolved() == []
        assert controller.ledger.in_flight("default") == 0

    def test_timeout_uncordons_stranded_victims(self):
        """A victim whose drain handoff died (cordoned, NOT deleting — the
        terminally-failed-replacement shape) must be un-cordoned when the
        settle timeout finishes the wave: a cordoned survivor is pure
        capacity loss."""
        from karpenter_tpu.controllers.consolidation import WAVE_SETTLE_TIMEOUT
        from karpenter_tpu.interruption.types import DisruptionNotice

        now = [1000.0]
        cluster, controller, provisioner, journal = orchestrated_env(
            12, clock=lambda: now[0]
        )
        real = controller.orchestrator

        class CordonOnly:
            """Taints+cordons the victim, then dies before the drain —
            the mid-wave failure the timeout path must clean up."""

            def consolidate(self, node, decision_id="", on_release=None):
                real._taint_and_cordon(
                    node,
                    DisruptionNotice(
                        kind="consolidation", node_name=node.metadata.name,
                        grace_period_seconds=0.0,
                    ),
                )
                return None

        controller.orchestrator = CordonOnly()
        controller.reconcile("default")
        (entry,) = journal.unresolved()
        stranded = entry.victims
        for name in stranded:
            assert cluster.get("nodes", name, namespace="").spec.unschedulable
        # cordoned victims still standing: the gate holds...
        assert controller.wave_settled("default") is False
        now[0] += WAVE_SETTLE_TIMEOUT + 1
        # ...until the deadline — then the wave is FINISHED, not abandoned
        assert controller.wave_settled("default") is True
        for name in stranded:
            node = cluster.get("nodes", name, namespace="")
            assert node.spec.unschedulable is False
            assert not any(
                t.key == lbl.INTERRUPTION_TAINT_KEY for t in node.spec.taints
            )
        assert journal.unresolved() == []
        assert controller.ledger.in_flight("default") == 0
