"""Consolidation re-pack tests (BASELINE config 5 — capability beyond the
reference): batched re-solve of live nodes, price accounting, safety gates,
and end-to-end migration."""

import pytest

from karpenter_tpu.api import labels as lbl
from karpenter_tpu.cloudprovider.fake import FakeCloudProvider, instance_types, new_instance_type
from karpenter_tpu.cloudprovider.requirements import catalog_requirements
from karpenter_tpu.controllers.consolidation import ConsolidationController
from karpenter_tpu.kube.client import Cluster
from karpenter_tpu.utils import resources as res
from tests.factories import make_node, make_pod, make_provisioner


def build_env(catalog=None, solver="ffd"):
    cluster = Cluster()
    provider = FakeCloudProvider(catalog if catalog is not None else instance_types(20))
    provisioner = make_provisioner(solver=solver)
    c = provisioner.spec.constraints
    c.requirements = c.requirements.merge(
        catalog_requirements(provider.get_instance_types())
    )
    cluster.create("provisioners", provisioner)
    controller = ConsolidationController(cluster, provider)
    return cluster, provider, provisioner, controller


def fragmented_cluster(cluster, n_nodes=4, pods_per_node=1, instance_type="fake-it-19"):
    """N big nodes each nearly empty — the classic consolidation target."""
    for i in range(n_nodes):
        node = make_node(
            name=f"big-{i}",
            capacity={"cpu": "20", "memory": "40Gi", "pods": "200"},
            provisioner_name="default",
            labels={lbl.INSTANCE_TYPE: instance_type, lbl.TOPOLOGY_ZONE: "test-zone-1",
                    lbl.CAPACITY_TYPE: "on-demand"},
            finalizers=[lbl.TERMINATION_FINALIZER],
        )
        cluster.create("nodes", node)
        for j in range(pods_per_node):
            cluster.create(
                "pods",
                make_pod(
                    name=f"pod-{i}-{j}",
                    requests={"cpu": "0.5"},
                    node_name=node.metadata.name,
                    unschedulable=False,
                ),
            )


class TestPlanning:
    def test_plan_finds_cheaper_packing(self):
        cluster, provider, provisioner, controller = build_env()
        fragmented_cluster(cluster)
        plan = controller.plan(provisioner)
        assert len(plan.nodes) == 4
        assert len(plan.pods) == 4
        assert plan.proposed  # everything fits on far fewer/cheaper nodes
        assert plan.proposed_price < plan.current_price
        assert plan.worthwhile

    def test_empty_cluster_no_plan(self):
        cluster, provider, provisioner, controller = build_env()
        plan = controller.plan(provisioner)
        assert not plan.worthwhile

    def test_do_not_evict_node_excluded(self):
        cluster, provider, provisioner, controller = build_env()
        fragmented_cluster(cluster, n_nodes=2)
        pod = cluster.get("pods", "pod-0-0")
        pod.metadata.annotations[lbl.DO_NOT_EVICT_ANNOTATION] = "true"
        plan = controller.plan(provisioner)
        assert {n.metadata.name for n in plan.nodes} == {"big-1"}

    def test_deleting_and_cordoned_nodes_excluded(self):
        cluster, provider, provisioner, controller = build_env()
        fragmented_cluster(cluster, n_nodes=3)
        cluster.get("nodes", "big-0", namespace="").spec.unschedulable = True
        cluster.delete("nodes", "big-1", namespace="")
        plan = controller.plan(provisioner)
        assert {n.metadata.name for n in plan.nodes} == {"big-2"}

    def test_unplaceable_pods_block_consolidation(self):
        """If the re-pack cannot seat every pod, the plan must not execute."""
        catalog = [new_instance_type("tiny", resources={res.CPU: 1.0, res.PODS: 2.0})]
        cluster, provider, provisioner, controller = build_env(catalog=catalog)
        node = make_node(
            name="old", capacity={"cpu": "64"}, provisioner_name="default",
            labels={lbl.INSTANCE_TYPE: "huge-legacy"},
        )
        cluster.create("nodes", node)
        cluster.create(
            "pods",
            make_pod(requests={"cpu": "32"}, node_name="old", unschedulable=False),
        )
        plan = controller.plan(provisioner)
        assert sum(len(v.pods) for v in plan.proposed) == 0
        assert not plan.worthwhile

    def test_marginal_savings_not_worthwhile(self):
        """Savings under the 5% churn threshold are rejected."""
        cluster, provider, provisioner, controller = build_env()
        # one pod on the node it would choose anyway → zero savings
        node = make_node(
            name="right-sized",
            capacity={"cpu": "1", "memory": "2Gi", "pods": "10"},
            provisioner_name="default",
            labels={lbl.INSTANCE_TYPE: "fake-it-0", lbl.TOPOLOGY_ZONE: "test-zone-1",
                    lbl.CAPACITY_TYPE: "on-demand"},
        )
        cluster.create("nodes", node)
        cluster.create(
            "pods",
            make_pod(requests={"cpu": "0.5"}, node_name="right-sized", unschedulable=False),
        )
        plan = controller.plan(provisioner)
        assert not plan.worthwhile


class TestExecution:
    def test_execute_migrates_pods_and_retires_nodes(self):
        cluster, provider, provisioner, controller = build_env()
        fragmented_cluster(cluster)
        plan = controller.plan(provisioner)
        launched = controller.execute(plan)
        assert len(launched) < 4  # consolidated
        live_nodes = {
            n.metadata.name
            for n in cluster.nodes()
            if n.metadata.deletion_timestamp is None
        }
        assert live_nodes == {n.metadata.name for n in launched}
        for pod in cluster.pods():
            assert pod.spec.node_name in live_nodes
        # old nodes are terminating (finalizer-bearing), awaiting drain
        for i in range(4):
            old = cluster.try_get("nodes", f"big-{i}", namespace="")
            assert old is None or old.metadata.deletion_timestamp is not None

    def test_reconcile_runs_plan_and_requeues(self):
        cluster, provider, provisioner, controller = build_env()
        fragmented_cluster(cluster)
        assert controller.reconcile("default") == 300.0
        live = [n for n in cluster.nodes() if n.metadata.deletion_timestamp is None]
        assert len(live) < 4

    def test_disabled_controller_noop(self):
        cluster, provider, provisioner, controller = build_env()
        controller.enabled = False
        fragmented_cluster(cluster)
        assert controller.reconcile("default") is None
        assert len(cluster.nodes()) == 4

    def test_anti_affinity_workload_can_consolidate(self):
        """The candidates' own live pods must not block their re-pack: two
        anti-affinity pods on two huge nodes consolidate onto two cheap nodes
        (their old seats don't count as occupied zones)."""
        from karpenter_tpu.api.objects import LabelSelector, PodAffinityTerm

        cluster, provider, provisioner, controller = build_env()
        sel = {"app": "ha"}
        for i, zone in enumerate(["test-zone-1", "test-zone-2"]):
            node = make_node(
                name=f"huge-{i}",
                capacity={"cpu": "20", "memory": "40Gi", "pods": "200"},
                provisioner_name="default",
                labels={lbl.INSTANCE_TYPE: "fake-it-19", lbl.TOPOLOGY_ZONE: zone,
                        lbl.CAPACITY_TYPE: "on-demand"},
                finalizers=[lbl.TERMINATION_FINALIZER],
            )
            cluster.create("nodes", node)
            pod = make_pod(
                name=f"ha-{i}", labels=sel, requests={"cpu": "0.5"},
                node_name=node.metadata.name, unschedulable=False,
                pod_anti_requirements=[
                    PodAffinityTerm(
                        label_selector=LabelSelector(match_labels=sel),
                        topology_key=lbl.TOPOLOGY_ZONE,
                    )
                ],
            )
            cluster.create("pods", pod)
        plan = controller.plan(provisioner)
        assert sum(len(v.pods) for v in plan.proposed) == 2  # both re-seated
        assert plan.worthwhile

    def test_consolidation_under_live_manager(self):
        """The full async loop: consolidation reconciles via the manager,
        migrates pods to cheaper capacity, and termination drains the old
        nodes to completion."""
        import time

        from karpenter_tpu.cloudprovider.fake import FakeCloudProvider
        from karpenter_tpu.main import build_runtime

        runtime = build_runtime(
            cloud_provider=FakeCloudProvider(instance_types(20)),
            start_workers=True,
            consolidation_enabled=True,
        )
        cluster = runtime.cluster
        cluster.create("provisioners", make_provisioner())
        fragmented_cluster(cluster)
        runtime.manager.start()
        try:
            runtime.manager.enqueue("consolidation", "default")
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline:
                live = [
                    n for n in cluster.nodes() if n.metadata.deletion_timestamp is None
                ]
                old_gone = all(
                    cluster.try_get("nodes", f"big-{i}", namespace="") is None
                    for i in range(4)
                )
                if len(live) < 4 and old_gone:
                    break
                time.sleep(0.05)
            live = [n for n in cluster.nodes() if n.metadata.deletion_timestamp is None]
            assert len(live) < 4  # consolidated
            # termination finished draining every retired node
            for i in range(4):
                assert cluster.try_get("nodes", f"big-{i}", namespace="") is None
            # every pod survived the migration, seated on a live node
            live_names = {n.metadata.name for n in live}
            pods = cluster.pods()
            assert len(pods) == 4
            assert all(p.spec.node_name in live_names for p in pods)
        finally:
            runtime.stop()

    def test_tpu_solver_consolidation(self):
        cluster, provider, provisioner, controller = build_env(solver="tpu")
        fragmented_cluster(cluster)
        plan = controller.plan(provisioner)
        assert plan.worthwhile
        launched = controller.execute(plan)
        assert 1 <= len(launched) < 4


class TestEvictWavePacing:
    """Evict-mode retirement is paced (ADVICE r2 / VERDICT r2 weak #5):
    at most EVICT_WAVE_SIZE nodes per reconcile, and the next wave is gated
    on the prior wave's nodes being gone AND the recreated pods having
    re-seated — a large worthwhile plan must never be a cluster-wide
    disruption storm."""

    def _evict_env(self, n_nodes):
        from karpenter_tpu.api.objects import OwnerReference

        cluster = Cluster()
        provider = FakeCloudProvider(instance_types(20))
        provisioner = make_provisioner(solver="ffd")
        c = provisioner.spec.constraints
        c.requirements = c.requirements.merge(
            catalog_requirements(provider.get_instance_types())
        )
        cluster.create("provisioners", provisioner)
        controller = ConsolidationController(cluster, provider, migration="evict")
        owner = OwnerReference(api_version="apps/v1", kind="ReplicaSet", name="rs")
        for i in range(n_nodes):
            node = make_node(
                name=f"big-{i}",
                capacity={"cpu": "20", "memory": "40Gi", "pods": "200"},
                provisioner_name="default",
                labels={lbl.INSTANCE_TYPE: "fake-it-19",
                        lbl.TOPOLOGY_ZONE: "test-zone-1",
                        lbl.CAPACITY_TYPE: "on-demand"},
            )
            cluster.create("nodes", node)
            cluster.create(
                "pods",
                make_pod(name=f"pod-{i}", requests={"cpu": "0.5"},
                         node_name=node.metadata.name, unschedulable=False,
                         owner=owner),
            )
        return cluster, controller, provisioner

    def test_waves_bound_concurrent_disruption(self):
        from karpenter_tpu.controllers.consolidation import (
            EVICT_WAVE_SIZE,
            WAVE_CHECK_INTERVAL,
        )

        n = 40
        cluster, controller, provisioner = self._evict_env(n)
        before = {x.metadata.name for x in cluster.nodes()}
        requeue = controller.reconcile(provisioner.metadata.name)
        after = {x.metadata.name for x in cluster.nodes()}
        # exactly one wave retired, not the whole worthwhile plan
        assert len(before - after) == EVICT_WAVE_SIZE
        assert requeue == WAVE_CHECK_INTERVAL

    def test_next_wave_gated_on_reseating(self):
        from karpenter_tpu.controllers.consolidation import EVICT_WAVE_SIZE

        cluster, controller, provisioner = self._evict_env(20)
        controller.reconcile(provisioner.metadata.name)
        n_after_first = len(cluster.nodes())
        # the recreated workload is still pending — wave NOT settled
        pending = make_pod(name="recreated-0", requests={"cpu": "0.5"})
        cluster.create("pods", pending)
        assert controller.wave_settled(provisioner.metadata.name) is False
        controller.reconcile(provisioner.metadata.name)
        assert len(cluster.nodes()) == n_after_first  # no new disruption
        # the pod re-seats -> the gate opens -> the next wave proceeds
        survivors = cluster.nodes()
        cluster.bind(pending, survivors[0].metadata.name)
        assert controller.wave_settled(provisioner.metadata.name) is True
        controller.reconcile(provisioner.metadata.name)
        assert len(cluster.nodes()) < n_after_first
        assert n_after_first - len(cluster.nodes()) <= EVICT_WAVE_SIZE

    def test_thousand_node_plan_is_paced(self):
        """The BASELINE 1k-node config as an OPERATION: the first reconcile
        of a 1000-node worthwhile plan disrupts at most one wave."""
        from karpenter_tpu.controllers.consolidation import EVICT_WAVE_SIZE

        cluster, controller, provisioner = self._evict_env(1000)
        controller.reconcile(provisioner.metadata.name)
        assert 1000 - len(cluster.nodes()) == EVICT_WAVE_SIZE

    def test_preexisting_pending_pod_does_not_gate_waves(self):
        """A pod that was ALREADY unschedulable before the wave launched
        (e.g. permanently unsatisfiable) must not deadlock consolidation."""
        cluster, controller, provisioner = self._evict_env(20)
        cluster.create("pods", make_pod(name="stuck-forever", requests={"cpu": "999"}))
        n0 = len(cluster.nodes())
        controller.reconcile(provisioner.metadata.name)
        n1 = len(cluster.nodes())
        assert n0 - n1 > 0  # first wave ran despite the stuck pod
        # the stuck pod is in the wave's baseline: the gate opens
        assert controller.wave_settled(provisioner.metadata.name) is True
        controller.reconcile(provisioner.metadata.name)
        assert len(cluster.nodes()) < n1  # second wave proceeded

    def test_wave_settle_timeout_releases_the_gate(self):
        from karpenter_tpu.controllers.consolidation import WAVE_SETTLE_TIMEOUT

        now = [1000.0]
        cluster = Cluster(clock=lambda: now[0])
        provider = FakeCloudProvider(instance_types(20))
        provisioner = make_provisioner(solver="ffd")
        c = provisioner.spec.constraints
        c.requirements = c.requirements.merge(
            catalog_requirements(provider.get_instance_types())
        )
        cluster.create("provisioners", provisioner)
        controller = ConsolidationController(cluster, provider, migration="evict")
        from karpenter_tpu.api.objects import OwnerReference

        owner = OwnerReference(api_version="apps/v1", kind="ReplicaSet", name="rs")
        for i in range(12):
            node = make_node(
                name=f"big-{i}", capacity={"cpu": "20", "memory": "40Gi", "pods": "200"},
                provisioner_name="default",
                labels={lbl.INSTANCE_TYPE: "fake-it-19", lbl.TOPOLOGY_ZONE: "test-zone-1",
                        lbl.CAPACITY_TYPE: "on-demand"},
            )
            cluster.create("nodes", node)
            cluster.create("pods", make_pod(name=f"pod-{i}", requests={"cpu": "0.5"},
                                            node_name=node.metadata.name,
                                            unschedulable=False, owner=owner))
        controller.reconcile(provisioner.metadata.name)
        # a NEW stuck pod appears after the wave: the gate holds...
        cluster.create("pods", make_pod(name="new-stuck", requests={"cpu": "999"}))
        assert controller.wave_settled(provisioner.metadata.name) is False
        # ...until the settle deadline passes — then it releases (logged)
        now[0] += WAVE_SETTLE_TIMEOUT + 1
        assert controller.wave_settled(provisioner.metadata.name) is True
