"""Interruption subsystem tests: notice → taint/cordon → proactive
replacement → drain → terminate, grace-deadline enforcement, the
replacement-capacity-unavailable fallback, multi-notice bursts, and the
DisruptionSource plumbing of every provider (in-process and over HTTP)."""

import time

import pytest

from karpenter_tpu.api import labels as lbl
from karpenter_tpu.api.objects import OwnerReference
from karpenter_tpu.cloudprovider.fake import FakeCloudProvider, instance_types
from karpenter_tpu.controllers.interruption import POLL_KEY, InterruptionController
from karpenter_tpu.controllers.provisioning import ProvisioningController
from karpenter_tpu.controllers.termination import TerminationController
from karpenter_tpu.interruption import (
    MAINTENANCE,
    PREEMPTION,
    DisruptionNotice,
    NoticeQueue,
)
from karpenter_tpu.kube.client import Cluster
from karpenter_tpu.utils import pod as podutil
from tests.factories import make_pod, make_provisioner


@pytest.fixture()
def env():
    now = [1000.0]
    cluster = Cluster(clock=lambda: now[0])
    provider = FakeCloudProvider(instance_types(5))
    provisioning = ProvisioningController(cluster, provider, start_workers=False)
    termination = TerminationController(cluster, provider, start_queue=False)
    controller = InterruptionController(
        cluster, provider, provisioning=provisioning, termination=termination
    )
    return cluster, provider, provisioning, termination, controller, now


def start_worker(cluster, provisioning):
    cluster.create("provisioners", make_provisioner())
    provisioning.reconcile("default")
    worker = provisioning.list_workers()[0]
    worker.batcher.idle_duration = 0.01
    return worker


def launch_workload(cluster, worker, n_pods=4, requests=None):
    """Create n pending pods and drive one solve; returns (node_name, pods)."""
    pods = [
        make_pod(name=f"w-{time.monotonic_ns()}-{i}", requests=requests or {"cpu": "0.5"})
        for i in range(n_pods)
    ]
    for p in pods:
        cluster.create("pods", p)
        worker.add(p)
    worker.provision_once()
    names = {p.spec.node_name for p in pods}
    assert len(names) == 1 and "" not in names, f"workload not co-located: {names}"
    return names.pop(), pods


class TestNoticeResponse:
    def test_taint_cordon_and_event(self, env):
        cluster, provider, provisioning, termination, ic, now = env
        worker = start_worker(cluster, provisioning)
        node_name, _ = launch_workload(cluster, worker)
        provider.preempt(node_name, grace_period_seconds=120.0)
        assert ic.reconcile(POLL_KEY) == ic.poll_interval
        node = cluster.try_get("nodes", node_name, namespace="")
        assert node.spec.unschedulable
        taints = {t.key: t.value for t in node.spec.taints}
        assert taints.get(lbl.INTERRUPTION_TAINT_KEY) == PREEMPTION
        # handed to termination (finalizer-bearing delete)
        assert node.metadata.deletion_timestamp is not None
        reasons = {e.reason for e in cluster.list("events")}
        assert "InterruptionNotice" in reasons

    def test_unknown_node_ignored(self, env):
        cluster, provider, provisioning, termination, ic, now = env
        provider.preempt("no-such-node")
        assert ic.reconcile(POLL_KEY) == ic.poll_interval
        assert ic.notices_handled == 0
        assert ic.evicted_unready == 0

    def test_reannounced_notice_deduped(self, env):
        cluster, provider, provisioning, termination, ic, now = env
        worker = start_worker(cluster, provisioning)
        node_name, _ = launch_workload(cluster, worker)
        # the cloud re-announces every metadata poll; the queue dedupes
        assert provider.preempt(node_name) is not None
        assert not provider.disruptions.push(
            DisruptionNotice(kind=PREEMPTION, node_name=node_name)
        )
        ic.reconcile(POLL_KEY)
        assert ic.notices_handled == 1
        # a second notice AFTER handling finds the node terminating → no-op
        provider.preempt(node_name)
        ic.reconcile(POLL_KEY)
        assert ic.notices_handled == 1


class TestProactiveReplacement:
    def test_replacement_launches_before_any_eviction(self, env):
        """The acceptance flow: 120s grace → replacement node launched
        before the first eviction, full drain, termination before the
        deadline, zero pods unscheduled once replacement is ready."""
        cluster, provider, provisioning, termination, ic, now = env
        worker = start_worker(cluster, provisioning)
        node_name, pods = launch_workload(cluster, worker)
        provider.preempt(node_name, grace_period_seconds=120.0)
        deadline = now[0] + 120.0
        ic.reconcile(POLL_KEY)
        # pods were released and injected — nothing was evicted or deleted
        assert all(p.spec.node_name == "" for p in pods)
        assert all(cluster.try_get("pods", p.metadata.name) is not None for p in pods)
        assert provider.delete_calls == []
        # the replacement solve runs while the old node still exists
        assert cluster.try_get("nodes", node_name, namespace="") is not None
        worker.provision_once()
        assert len(provider.create_calls) == 2  # original + replacement
        assert provider.delete_calls == []  # replacement BEFORE any teardown
        replacement = {p.spec.node_name for p in pods}
        assert len(replacement) == 1 and node_name not in replacement and "" not in replacement
        # full drain + termination inside the grace period
        assert termination.reconcile(node_name) is None
        assert now[0] < deadline
        assert cluster.try_get("nodes", node_name, namespace="") is None
        assert provider.delete_calls == [node_name]
        # zero pods unscheduled once replacement capacity is ready
        assert not any(podutil.is_provisionable(p) for p in cluster.pods())
        assert ic.evicted_unready == 0
        # deadline record closes out as a completed drain
        assert ic.reconcile(node_name) is None
        assert len(ic.lead_times) == len(pods)

    def test_replacement_respects_volume_topology(self, env):
        """submit() bypasses selection, but a replacement pod with a
        zone-bound PV must still carry the volume's node-affinity into the
        solve — otherwise the replacement lands where the volume cannot
        attach."""
        from karpenter_tpu.api.objects import Volume
        from tests.factories import make_pv, make_pvc

        cluster, provider, provisioning, termination, ic, now = env
        worker = start_worker(cluster, provisioning)
        cluster.create("pvs", make_pv(name="pv-a", zones=["test-zone-2"]))
        cluster.create("pvcs", make_pvc(name="claim-a", volume_name="pv-a"))
        pod = make_pod(name="stateful", requests={"cpu": "0.5"})
        pod.spec.volumes.append(Volume(name="data", persistent_volume_claim="claim-a"))
        cluster.create("pods", pod)
        worker.add(pod)
        worker.provision_once()
        node_name = pod.spec.node_name
        assert node_name
        provider.preempt(node_name)
        ic.reconcile(POLL_KEY)
        worker.provision_once()
        replacement = cluster.try_get("nodes", pod.spec.node_name, namespace="")
        assert replacement is not None and replacement.metadata.name != node_name
        assert replacement.metadata.labels[lbl.TOPOLOGY_ZONE] == "test-zone-2"

    def test_daemonset_and_static_pods_stay(self, env):
        cluster, provider, provisioning, termination, ic, now = env
        worker = start_worker(cluster, provisioning)
        node_name, pods = launch_workload(cluster, worker, n_pods=2)
        ds_pod = make_pod(
            node_name=node_name, unschedulable=False,
            owner=OwnerReference(api_version="apps/v1", kind="DaemonSet", name="ds"),
        )
        static_pod = make_pod(
            node_name=node_name, unschedulable=False,
            owner=OwnerReference(api_version="v1", kind="Node", name=node_name),
        )
        cluster.create("pods", ds_pod)
        cluster.create("pods", static_pod)
        provider.preempt(node_name)
        ic.reconcile(POLL_KEY)
        # per-node workloads are not re-routed through provisioning
        assert ds_pod.spec.node_name == node_name
        assert static_pod.spec.node_name == node_name
        assert all(p.spec.node_name == "" for p in pods)


class TestDeadlineEnforcement:
    def test_do_not_evict_holdout_forced_at_deadline(self, env):
        cluster, provider, provisioning, termination, ic, now = env
        worker = start_worker(cluster, provisioning)
        node_name, pods = launch_workload(cluster, worker, n_pods=2)
        holdout = make_pod(node_name=node_name, unschedulable=False)
        holdout.metadata.annotations[lbl.DO_NOT_EVICT_ANNOTATION] = "true"
        cluster.create("pods", holdout)
        provider.preempt(node_name, grace_period_seconds=60.0)
        ic.reconcile(POLL_KEY)
        # the holdout keeps its bind; the drain is blocked
        assert holdout.spec.node_name == node_name
        assert termination.reconcile(node_name) == termination.DRAIN_REQUEUE
        # before the deadline: the controller just keeps watching
        requeue = ic.reconcile(node_name)
        assert requeue is not None and requeue <= 1.0
        assert cluster.try_get("nodes", node_name, namespace="") is not None
        # past the deadline: forced termination, loss accounted
        now[0] += 61.0
        assert ic.reconcile(node_name) is None
        assert cluster.try_get("nodes", node_name, namespace="") is None
        assert node_name in provider.delete_calls
        assert ic.evicted_unready == 1
        reasons = {e.reason for e in cluster.list("events")}
        assert "InterruptionDeadlineReached" in reasons

    def test_grace_deadline_tracks_notice(self, env):
        cluster, provider, provisioning, termination, ic, now = env
        worker = start_worker(cluster, provisioning)
        node_name, _ = launch_workload(cluster, worker, n_pods=1)
        provider.preempt(node_name, grace_period_seconds=300.0, kind=MAINTENANCE)
        ic.reconcile(POLL_KEY)
        now[0] += 299.0
        assert ic.reconcile(node_name) == 1.0  # still inside the window
        now[0] += 2.0
        assert ic.reconcile(node_name) is None  # enforced


class TestReplacementUnavailable:
    def test_no_admitting_provisioner_leaves_pods_pending(self, env):
        """Fallback: with no worker to inject into, released pods survive
        as pending (selection retries them later) instead of dying with
        the node."""
        cluster, provider, provisioning, termination, ic, now = env
        # a node that exists outside any provisioner worker
        from tests.factories import make_node

        node = make_node(
            provisioner_name="default", finalizers=[lbl.TERMINATION_FINALIZER]
        )
        cluster.create("nodes", node)
        pod = make_pod(node_name=node.metadata.name, unschedulable=False)
        cluster.create("pods", pod)
        provider.preempt(node.metadata.name)
        ic.reconcile(POLL_KEY)
        assert pod.spec.node_name == ""
        assert podutil.is_provisionable(pod)
        assert termination.reconcile(node.metadata.name) is None  # drains clean
        assert cluster.try_get("pods", pod.metadata.name) is not None

    def test_launch_failure_does_not_lose_pods(self, env):
        cluster, provider, provisioning, termination, ic, now = env

        fail = [1]
        original_create = provider.create

        def flaky_create(request):
            if fail[0]:
                fail[0] -= 1
                raise RuntimeError("insufficient capacity")
            return original_create(request)

        worker = start_worker(cluster, provisioning)
        node_name, pods = launch_workload(cluster, worker, n_pods=2)
        provider.create = flaky_create
        provider.preempt(node_name)
        ic.reconcile(POLL_KEY)
        worker.provision_once()  # launch fails; pods stay pending
        assert all(podutil.is_provisionable(p) for p in pods)
        # the selection requeue path re-routes them; emulate one round
        for p in pods:
            assert provisioning.submit(p) is not None
        worker.provision_once()
        assert all(p.spec.node_name not in ("", node_name) for p in pods)
        assert ic.evicted_unready == 0


class TestMultiNoticeBurst:
    def test_burst_replaces_every_node(self, env):
        cluster, provider, provisioning, termination, ic, now = env
        worker = start_worker(cluster, provisioning)
        victims = []
        all_pods = []
        for _ in range(3):
            node_name, pods = launch_workload(cluster, worker, n_pods=2)
            victims.append(node_name)
            all_pods.extend(pods)
        for name in victims:
            provider.preempt(name, grace_period_seconds=120.0)
        ic.reconcile(POLL_KEY)
        assert ic.notices_handled == 3
        worker.provision_once()  # one batched replacement solve
        for p in all_pods:
            assert p.spec.node_name and p.spec.node_name not in victims
        for name in victims:
            assert termination.reconcile(name) is None
            assert cluster.try_get("nodes", name, namespace="") is None
        assert ic.evicted_unready == 0
        assert sorted(provider.delete_calls) == sorted(victims)
        assert len(ic.lead_times) == len(all_pods)


class TestDisruptionSources:
    def test_fake_poll_drains(self):
        provider = FakeCloudProvider()
        provider.preempt("n1", grace_period_seconds=30.0)
        notices = provider.poll_disruptions()
        assert [n.node_name for n in notices] == ["n1"]
        assert notices[0].grace_period_seconds == 30.0
        assert provider.poll_disruptions() == []

    def test_simulated_provider_poll(self):
        from karpenter_tpu.cloudprovider.simulated import SimCloudAPI, SimulatedCloudProvider

        api = SimCloudAPI()
        provider = SimulatedCloudProvider(api=api)
        api.send_disruption_notice(
            DisruptionNotice(kind=PREEMPTION, node_name="i-0001", grace_period_seconds=90.0)
        )
        notices = provider.poll_disruptions()
        assert [(n.kind, n.node_name) for n in notices] == [(PREEMPTION, "i-0001")]
        assert provider.poll_disruptions() == []

    def test_gke_provider_poll(self):
        from karpenter_tpu.cloudprovider.gke import GkeCloudProvider, SimGkeAPI

        api = SimGkeAPI()
        provider = GkeCloudProvider(api=api)
        api.send_disruption_notice(
            DisruptionNotice(kind=MAINTENANCE, node_name="gke-np-1-0")
        )
        assert [n.kind for n in provider.poll_disruptions()] == [MAINTENANCE]

    def test_metered_provider_passthrough(self):
        from karpenter_tpu.cloudprovider.metrics import decorate

        provider = FakeCloudProvider()
        metered = decorate(provider)
        provider.preempt("n1")
        assert [n.node_name for n in metered.poll_disruptions()] == ["n1"]

    def test_http_cloud_events_route(self):
        from karpenter_tpu.cloudprovider.httpapi import CloudAPIServer, HttpCloudAPI
        from karpenter_tpu.cloudprovider.simulated import SimCloudAPI

        api = SimCloudAPI()
        with CloudAPIServer(api) as server:
            client = HttpCloudAPI(server.url)
            api.send_disruption_notice(
                DisruptionNotice(
                    kind=PREEMPTION, node_name="i-00000001",
                    grace_period_seconds=45.0, reason="spot reclaim",
                )
            )
            notices = client.poll_disruptions()
            assert len(notices) == 1
            n = notices[0]
            assert (n.kind, n.node_name, n.grace_period_seconds, n.reason) == (
                PREEMPTION, "i-00000001", 45.0, "spot reclaim",
            )
            assert client.poll_disruptions() == []

    def test_http_gke_events_route(self):
        from karpenter_tpu.cloudprovider.gke import GkeCloudProvider, SimGkeAPI
        from karpenter_tpu.cloudprovider.httpapi import GkeAPIServer, HttpGkeAPI

        api = SimGkeAPI()
        with GkeAPIServer(api) as server:
            provider = GkeCloudProvider(api=HttpGkeAPI(server.url))
            api.send_disruption_notice(
                DisruptionNotice(kind=PREEMPTION, node_name="gke-x")
            )
            assert [n.node_name for n in provider.poll_disruptions()] == ["gke-x"]

    def test_notice_queue_dedup_and_wire_roundtrip(self):
        q = NoticeQueue()
        n = DisruptionNotice(kind=PREEMPTION, node_name="a", grace_period_seconds=15.0)
        assert q.push(n)
        assert not q.push(DisruptionNotice(kind=PREEMPTION, node_name="a"))
        assert q.push(DisruptionNotice(kind=MAINTENANCE, node_name="a"))
        assert len(q) == 2
        assert [x.node_name for x in q.drain()] == ["a", "a"]
        assert len(q) == 0
        assert DisruptionNotice.from_wire(n.to_wire()) == n


class TestFullRuntime:
    def test_preemption_through_running_manager(self):
        """The subsystem end-to-end under the real manager: watch-driven
        selection, a polling interruption controller, threaded workers."""
        from karpenter_tpu.main import build_runtime
        from karpenter_tpu.options import Options

        provider = FakeCloudProvider(instance_types(10))
        cluster = Cluster()
        rt = build_runtime(Options(), cluster=cluster, cloud_provider=provider)
        rt.interruption.poll_interval = 0.1
        rt.manager.start()
        try:
            cluster.create("provisioners", make_provisioner())
            deadline = time.time() + 10
            while time.time() < deadline and not rt.provisioning.workers:
                time.sleep(0.02)
            for w in rt.provisioning.workers.values():
                w.batcher.idle_duration = 0.05
            pods = [make_pod(name=f"rt-{i}", requests={"cpu": "0.25"}) for i in range(8)]
            for p in pods:
                cluster.create("pods", p)

            def all_bound():
                return all(p.spec.node_name for p in pods)

            deadline = time.time() + 20
            while time.time() < deadline and not all_bound():
                time.sleep(0.05)
            assert all_bound(), "initial workload never bound"
            victim = next(p.spec.node_name for p in pods)
            provider.preempt(victim, grace_period_seconds=120.0)
            deadline = time.time() + 20
            while time.time() < deadline:
                if (
                    cluster.try_get("nodes", victim, namespace="") is None
                    and all(p.spec.node_name not in ("", victim) for p in pods)
                ):
                    break
                time.sleep(0.05)
            assert cluster.try_get("nodes", victim, namespace="") is None, (
                "preempted node never terminated"
            )
            assert all_bound(), "pods left unbound after replacement"
            assert all(p.spec.node_name != victim for p in pods)
            assert rt.interruption.evicted_unready == 0
            assert victim in provider.delete_calls
        finally:
            rt.stop()
