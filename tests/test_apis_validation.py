"""CRD validation/defaulting semantics (mirrors
pkg/apis/provisioning/v1alpha5/suite_test.go): TTLs, restricted labels and
domains, taint shapes, requirement operators, limits arithmetic."""

import pytest

from karpenter_tpu.api import labels as lbl
from karpenter_tpu.api.objects import NodeSelectorRequirement, Taint
from karpenter_tpu.api.provisioner import (
    Limits,
    default_provisioner,
    validate_provisioner,
)
from tests.factories import make_provisioner


def errs_of(provisioner):
    return validate_provisioner(provisioner)


class TestTTLValidation:
    def test_negative_ttls_rejected(self):
        assert errs_of(make_provisioner(ttl_after_empty=-1))
        assert errs_of(make_provisioner(ttl_until_expired=-1))

    def test_zero_and_positive_ttls_allowed(self):
        assert not errs_of(make_provisioner(ttl_after_empty=0, ttl_until_expired=600))

    def test_unset_ttls_allowed(self):
        assert not errs_of(make_provisioner())


class TestLabelValidation:
    def test_well_known_labels_allowed(self):
        assert not errs_of(make_provisioner(labels={lbl.TOPOLOGY_ZONE: "z1"}))

    def test_restricted_domain_rejected(self):
        assert errs_of(make_provisioner(labels={"kubernetes.io/hostname": "x"}))
        assert errs_of(make_provisioner(labels={"karpenter.sh/custom": "x"}))
        assert errs_of(make_provisioner(labels={"node.k8s.io/foo": "x"}))

    def test_domain_exception_allowed(self):
        assert not errs_of(make_provisioner(labels={"kops.k8s.io/instancegroup": "x"}))

    def test_custom_domain_allowed(self):
        assert not errs_of(make_provisioner(labels={"example.com/team": "infra"}))

    def test_empty_label_value_rejected(self):
        assert errs_of(make_provisioner(labels={"example.com/team": ""}))


class TestTaintValidation:
    def test_valid_taint(self):
        assert not errs_of(
            make_provisioner(taints=[Taint(key="dedicated", value="gpu", effect="NoSchedule")])
        )

    def test_empty_key_rejected(self):
        assert errs_of(make_provisioner(taints=[Taint(key="", effect="NoSchedule")]))

    def test_bad_effect_rejected(self):
        assert errs_of(make_provisioner(taints=[Taint(key="k", effect="Sometimes")]))


class TestRequirementValidation:
    def test_provisioner_ops_limited(self):
        # provisioners may use In/NotIn/Exists; DoesNotExist is pod-only
        # (reference: provisioner_validation.go:30-31)
        ok = make_provisioner(
            requirements=[NodeSelectorRequirement(key=lbl.TOPOLOGY_ZONE, operator="In", values=["z"])]
        )
        assert not errs_of(ok)
        bad = make_provisioner(
            requirements=[NodeSelectorRequirement(key=lbl.TOPOLOGY_ZONE, operator="DoesNotExist")]
        )
        assert errs_of(bad)

    def test_unknown_operator_rejected(self):
        assert errs_of(
            make_provisioner(
                requirements=[NodeSelectorRequirement(key=lbl.TOPOLOGY_ZONE, operator="Gt", values=["3"])]
            )
        )

    def test_restricted_requirement_key_rejected(self):
        assert errs_of(
            make_provisioner(
                requirements=[
                    NodeSelectorRequirement(key=lbl.HOSTNAME, operator="In", values=["n1"])
                ]
            )
        )

    def test_infeasible_intersection_rejected(self):
        assert errs_of(
            make_provisioner(
                requirements=[
                    NodeSelectorRequirement(key=lbl.TOPOLOGY_ZONE, operator="In", values=["a"]),
                    NodeSelectorRequirement(key=lbl.TOPOLOGY_ZONE, operator="In", values=["b"]),
                ]
            )
        )

    def test_bad_solver_rejected(self):
        assert errs_of(make_provisioner(solver="quantum"))


class TestDefaults:
    def test_solver_default_applied_once(self):
        p = make_provisioner()
        p.spec.solver = ""
        default_provisioner(p, "tpu")
        assert p.spec.solver == "tpu"
        default_provisioner(p, "ffd")  # idempotent: explicit value wins
        assert p.spec.solver == "tpu"


class TestLimits:
    def test_exceeded_by(self):
        limits = Limits(resources={"cpu": 10.0})
        assert limits.exceeded_by({"cpu": 10.0}) is not None  # at the limit
        assert limits.exceeded_by({"cpu": 9.9}) is None
        assert limits.exceeded_by({"memory": 1e12}) is None  # unlimited resource


class TestLabelKeyEdges:
    """reference: v1alpha5 suite 'should fail for invalid label keys' /
    'should allow labels kOps require'."""

    def test_malformed_label_key_rejected(self):
        p = make_provisioner(labels={"not a valid key!": "v"})
        assert validate_provisioner(p)

    def test_kops_domain_exception_allowed(self):
        p = make_provisioner(labels={"kops.k8s.io/instancegroup": "nodes"})
        assert not validate_provisioner(p)

    def test_invalid_taint_value_rejected(self):
        from karpenter_tpu.api.objects import Taint

        p = make_provisioner(
            taints=[Taint(key="ok", value="bad value!", effect="NoSchedule")]
        )
        assert validate_provisioner(p)

    def test_malformed_label_value_rejected(self):
        assert validate_provisioner(make_provisioner(labels={"example.com/team": "bad value!"}))
        assert validate_provisioner(make_provisioner(labels={"example.com/team": "-leading"}))
        assert validate_provisioner(make_provisioner(labels={"example.com/team": "x" * 64}))

    def test_valid_label_value_allowed(self):
        assert not validate_provisioner(make_provisioner(labels={"example.com/team": "a-b_c.d"}))
        assert not validate_provisioner(make_provisioner(labels={"example.com/team": "x" * 63}))

    def test_label_key_length_and_prefix_syntax(self):
        # name part > 63 chars
        assert validate_provisioner(make_provisioner(labels={"p" * 64: "v"}))
        # prefix not a DNS-1123 subdomain
        assert validate_provisioner(make_provisioner(labels={"Bad_Domain!/name": "v"}))
        # multiple slashes
        assert validate_provisioner(make_provisioner(labels={"a/b/c": "v"}))
        # prefix > 253 chars
        assert validate_provisioner(make_provisioner(labels={("a" * 254) + "/name": "v"}))

    def test_malformed_taint_key_rejected(self):
        p = make_provisioner(taints=[Taint(key="not a key!", effect="NoSchedule")])
        assert validate_provisioner(p)

    def test_malformed_requirement_key_rejected(self):
        p = make_provisioner(
            requirements=[NodeSelectorRequirement(key="spaced key", operator="Exists")]
        )
        assert validate_provisioner(p)
