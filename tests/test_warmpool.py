"""Speculative warm-pool provisioning tests: the wave controller
(controllers/warmpool.py), the worker's warm-hit steal
(controllers/provisioning.py), the speculative rungs of the journal
replay ladder (launch/recovery.py), and the brownout interaction."""

import time

import pytest

from karpenter_tpu import metrics, obs
from karpenter_tpu.api import labels as lbl
from karpenter_tpu.cloudprovider.simulated import (
    SimCloudAPI,
    SimulatedCloudProvider,
)
from karpenter_tpu.controllers.provisioning import ProvisioningController
from karpenter_tpu.controllers.warmpool import (
    WARM_POOL_KEY,
    WarmPoolController,
)
from karpenter_tpu.kube.client import Cluster
from karpenter_tpu.launch import recovery
from karpenter_tpu.launch.journal import MemoryLaunchJournal
from karpenter_tpu.obs.trace import Span
from karpenter_tpu.resilience.brownout import BrownoutController
from tests.factories import make_pod, make_provisioner


def _span(name, **attrs):
    return Span(name=name, trace_id="t" * 32, span_id="s" * 16,
                parent_id=None, parent=None, attrs=attrs)


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


@pytest.fixture(autouse=True)
def _fresh_obs():
    obs.configure_decisions("")  # memory-only decision ring per test
    yield
    obs.shutdown_forecast()
    obs.configure_decisions("")


class _Env:
    """One provisioner ('wp'), a simulated cloud, a memory journal, the
    provisioning controller (warm_pool=True so the steal runs), the wave
    controller, and a forecaster on a fake clock."""

    def __init__(self, max_nodes=10, ttl=600.0, ownership=None,
                 provisioner=None, horizon_s=5.0):
        self.cluster = Cluster()
        self.api = SimCloudAPI()
        self.provider = SimulatedCloudProvider(self.api)
        self.journal = MemoryLaunchJournal()
        self.prov = provisioner or make_provisioner(name="wp")
        self.cluster.create("provisioners", self.prov)
        self.controller = ProvisioningController(
            self.cluster, self.provider, start_workers=False,
            journal=self.journal, warm_pool=True,
        )
        self.controller.apply(self.prov)
        self.worker = self.controller.workers[self.prov.metadata.name]
        self.worker.batcher.idle_duration = 0.01
        self.wp = WarmPoolController(
            self.cluster, self.provider, self.controller,
            journal=self.journal, ownership=ownership,
            warm_pool_ttl=ttl, max_nodes=max_nodes,
        )
        self.clock = FakeClock()
        self.eng = obs.configure_forecast(
            bucket_s=1.0, alpha=1.0, default_horizon_s=horizon_s,
            clock=self.clock,
        )

    def forecast_demand(self, pods_per_s, pods_per_node=1.0):
        """Prime the forecaster: one closed 1s bucket of ``pods_per_s``
        arrivals packing at ``pods_per_node``. With alpha=1 and a single
        observation the upper band equals the point rate."""
        self.eng(_span(
            "provision.round", provisioner=self.prov.metadata.name,
            batch=pods_per_s, nodes=pods_per_s / pods_per_node,
        ))
        self.clock.t += 1.0  # close the bucket

    def warm_nodes(self):
        return [
            n for n in self.cluster.nodes()
            if lbl.WARM_POOL_ANNOTATION in n.metadata.annotations
        ]

    def stop(self):
        self.controller.stop()


class TestWarmPoolWave:
    def test_wave_launches_forecast_deficit(self):
        env = _Env(horizon_s=5.0)
        try:
            env.forecast_demand(pods_per_s=2, pods_per_node=2.0)
            # want = ceil(2 pods/s * 5s / 2 pods-per-node) = 5 nodes
            assert env.wp.reconcile(WARM_POOL_KEY) == env.wp.interval
            warm = env.warm_nodes()
            assert len(warm) == 5
            assert env.wp.speculative_launches == 5
            for n in warm:
                assert n.metadata.labels[lbl.PROVISIONER_NAME_LABEL] == "wp"
                assert n.metadata.annotations[lbl.WARM_POOL_ANNOTATION] == "true"
                assert n.metadata.annotations[lbl.LAUNCH_TOKEN_ANNOTATION]
            # every speculative entry is journaled, marked, and OPEN
            open_entries = env.journal.unresolved()
            assert len(open_entries) == 5
            assert all(e.speculative for e in open_entries)
            assert all(e.node_name for e in open_entries)
            # the wave landed in the decision ring for whatif replay
            waves = [r for r in obs.decision_log().recent(limit=32)
                     if r.get("state", {}).get("warm_pool_wave")]
            assert len(waves) == 1
            assert waves[0]["state"]["deficit"] == 5
        finally:
            env.stop()

    def test_standing_capacity_counts_against_want(self):
        env = _Env()
        try:
            env.forecast_demand(pods_per_s=2, pods_per_node=2.0)
            env.wp.reconcile(WARM_POOL_KEY)
            first = env.wp.speculative_launches
            env.wp.reconcile(WARM_POOL_KEY)  # same forecast, pool standing
            assert env.wp.speculative_launches == first
            assert len(env.warm_nodes()) == first
        finally:
            env.stop()

    def test_max_nodes_caps_speculation(self):
        env = _Env(max_nodes=3)
        try:
            env.forecast_demand(pods_per_s=40)  # wants 200 nodes
            env.wp.reconcile(WARM_POOL_KEY)
            assert len(env.warm_nodes()) == 3
        finally:
            env.stop()

    def test_no_forecaster_no_speculation(self):
        env = _Env()
        try:
            obs.shutdown_forecast(env.eng)
            assert env.wp.reconcile(WARM_POOL_KEY) == env.wp.interval
            assert env.warm_nodes() == []
        finally:
            env.stop()

    def test_zero_forecast_no_speculation(self):
        env = _Env()
        try:
            env.wp.reconcile(WARM_POOL_KEY)  # no rounds observed at all
            assert env.warm_nodes() == []
            assert env.journal.unresolved() == []
        finally:
            env.stop()

    def test_paused_wave_skips(self):
        env = _Env()
        try:
            env.forecast_demand(pods_per_s=4)
            env.wp.set_paused(True)
            env.wp.reconcile(WARM_POOL_KEY)
            assert env.warm_nodes() == []
            env.wp.set_paused(False)
            env.wp.reconcile(WARM_POOL_KEY)
            assert env.warm_nodes() != []
        finally:
            env.stop()

    def test_limits_block_speculation(self):
        from karpenter_tpu.utils import resources as res

        prov = make_provisioner(name="wp", limits={"cpu": "4"})
        prov.status.resources = {res.CPU: 4.0}
        env = _Env(provisioner=prov)
        try:
            env.forecast_demand(pods_per_s=4)
            env.wp.reconcile(WARM_POOL_KEY)
            assert env.warm_nodes() == []
        finally:
            env.stop()


class TestFencing:
    def test_fenced_replica_never_speculates(self):
        class Fenced:
            def fenced(self):
                return True

            def owns(self, name):
                return True

        env = _Env(ownership=Fenced())
        try:
            env.forecast_demand(pods_per_s=4)
            before = metrics.FLEET_DUPLICATE_LAUNCH_GUARD.labels(
                reason="fenced"
            )._value.get()
            env.wp.reconcile(WARM_POOL_KEY)
            assert env.warm_nodes() == []
            assert env.journal.unresolved() == []
            assert len(env.api.instances) == 0
            assert metrics.FLEET_DUPLICATE_LAUNCH_GUARD.labels(
                reason="fenced"
            )._value.get() == before + 1
        finally:
            env.stop()

    def test_worker_fence_rechecked_per_create(self):
        """A fence that lands after the wave's top-of-loop check still
        stops every create (the per-launch re-check)."""
        env = _Env()
        try:
            env.forecast_demand(pods_per_s=4)
            env.worker.fenced = lambda: True
            before = metrics.FLEET_DUPLICATE_LAUNCH_GUARD.labels(
                reason="fenced"
            )._value.get()
            env.wp.reconcile(WARM_POOL_KEY)
            assert env.warm_nodes() == []
            assert len(env.api.instances) == 0
            assert metrics.FLEET_DUPLICATE_LAUNCH_GUARD.labels(
                reason="fenced"
            )._value.get() > before
        finally:
            env.stop()

    def test_lost_ownership_rechecked_per_create(self):
        env = _Env()
        try:
            env.forecast_demand(pods_per_s=4)
            env.worker.owned = lambda: False
            env.wp.reconcile(WARM_POOL_KEY)
            assert env.warm_nodes() == []
            assert len(env.api.instances) == 0
        finally:
            env.stop()


class TestBrownoutInteraction:
    def test_rung1_freezes_wave_midflight_and_resumes(self):
        """Brownout rung 1 arriving DURING a wave freezes the not-yet-
        started launches; dropping back to rung 0 lets the next wave top
        the pool back up. Deterministic because every create pauses the
        pool before returning: any launch task started after the first
        completion sees paused() and freezes."""
        env = _Env(max_nodes=12)
        try:
            env.forecast_demand(pods_per_s=12, pods_per_node=1.0)
            ctl = BrownoutController(
                burning_fn=lambda: True, warmpool=env.wp, escalate_after=1,
            )
            orig_create = env.provider.create

            def create_then_brownout(request):
                node = orig_create(request)
                ctl.tick()  # rung 0 → 1: set_paused(True) mid-wave
                return node

            env.provider.create = create_then_brownout
            env.wp.reconcile(WARM_POOL_KEY)
            frozen_at = env.wp.speculative_launches
            # the wave wanted 12 (capped); the executor admits at most 8
            # concurrently, so the freeze provably cut the wave short
            assert 1 <= frozen_at <= 8
            assert len(env.warm_nodes()) == frozen_at
            assert env.wp.paused()
            # stop() fully reverses: speculation resumes on the next wave
            env.provider.create = orig_create
            ctl.stop()
            assert not env.wp.paused()
            env.wp.reconcile(WARM_POOL_KEY)
            assert len(env.warm_nodes()) == 12
        finally:
            env.stop()

    def test_standing_nodes_survive_brownout_and_stay_claimable(self):
        env = _Env()
        try:
            env.forecast_demand(pods_per_s=2, pods_per_node=2.0)
            env.wp.reconcile(WARM_POOL_KEY)
            standing = len(env.warm_nodes())
            assert standing > 0
            ctl = BrownoutController(
                burning_fn=lambda: True, warmpool=env.wp, escalate_after=1,
            )
            ctl.tick()
            assert env.wp.paused()
            assert len(env.warm_nodes()) == standing  # nothing torn down
            # demand still claims warm capacity while speculation is paused
            pod = make_pod(requests={"cpu": "0.25"})
            env.cluster.create("pods", pod)
            env.worker.batcher.add(pod)
            env.worker.provision_once()
            bound = env.cluster.get("pods", pod.metadata.name, pod.metadata.namespace)
            assert bound.spec.node_name in {
                n.metadata.name for n in env.cluster.nodes()
            }
            assert len(env.warm_nodes()) == standing - 1
            ctl.stop()
        finally:
            env.stop()


class TestWarmSteal:
    def _standing_pool(self, env, pods_per_s=2, pods_per_node=2.0):
        env.forecast_demand(pods_per_s=pods_per_s, pods_per_node=pods_per_node)
        env.wp.reconcile(WARM_POOL_KEY)
        warm = env.warm_nodes()
        assert warm
        return warm

    def test_hit_binds_claims_and_resolves(self):
        env = _Env()
        try:
            warm = self._standing_pool(env)
            instances_before = len(env.api.instances)
            hits_before = metrics.WARMPOOL_HITS.labels(
                provisioner="wp"
            )._value.get()
            # sized to fit the cheapest sim type the speculation launched
            pods = [make_pod(requests={"cpu": "0.25"}) for _ in range(2)]
            for p in pods:
                env.cluster.create("pods", p)
                env.worker.batcher.add(p)
            env.worker.provision_once()
            warm_names = {n.metadata.name for n in warm}
            for p in pods:
                bound = env.cluster.get("pods", p.metadata.name, p.metadata.namespace)
                assert bound.spec.node_name in warm_names
            # the claim removed the marker and resolved the entry
            claimed = [
                n for n in env.cluster.nodes()
                if n.metadata.name in warm_names
                and lbl.WARM_POOL_ANNOTATION not in n.metadata.annotations
            ]
            assert len(claimed) >= 1
            open_tokens = {e.node_name for e in env.journal.unresolved()}
            for n in claimed:
                assert n.metadata.name not in open_tokens
            # a hit pays no launch
            assert len(env.api.instances) == instances_before
            assert metrics.WARMPOOL_HITS.labels(
                provisioner="wp"
            )._value.get() == hits_before + 2
        finally:
            env.stop()

    def test_stolen_round_still_records_a_decision(self):
        """A round fully absorbed by the steal must land in the decision
        ring (state.warm_claim) — whatif replays the ring as the demand
        record, and a missing round under-counts arrivals by exactly the
        hit rate."""
        env = _Env()
        try:
            self._standing_pool(env)
            pod = make_pod(requests={"cpu": "0.25"})
            env.cluster.create("pods", pod)
            env.worker.batcher.add(pod)
            env.worker.provision_once()
            claims = [r for r in obs.decision_log().recent(limit=32)
                      if r.get("state", {}).get("warm_claim")]
            assert len(claims) == 1
            rec = claims[0]
            assert rec["provisioner"] == "wp"
            assert rec["pods_considered"] == 1
            assert rec["unschedulable_count"] == 0
            assert rec["state"]["warm_nodes"]
        finally:
            env.stop()

    def test_selector_mismatch_misses(self):
        env = _Env()
        try:
            self._standing_pool(env)
            misses_before = metrics.WARMPOOL_MISSES.labels(
                provisioner="wp"
            )._value.get()
            pod = make_pod(requests={"cpu": "1"},
                           node_selector={"disk": "nvme"})
            env.cluster.create("pods", pod)
            env.worker.batcher.add(pod)
            env.worker.provision_once()
            assert metrics.WARMPOOL_MISSES.labels(
                provisioner="wp"
            )._value.get() > misses_before
            # warm pool untouched — the selector can't match the template
            assert all(
                lbl.WARM_POOL_ANNOTATION in n.metadata.annotations
                for n in env.warm_nodes()
            )
        finally:
            env.stop()

    def test_lost_claim_falls_back_to_solver(self):
        env = _Env()
        try:
            self._standing_pool(env)
            orig = env.cluster.merge_patch

            def failing_patch(kind, name, patch, namespace=""):
                if kind == "nodes":
                    raise RuntimeError("node raced away")
                return orig(kind, name, patch, namespace=namespace)

            env.cluster.merge_patch = failing_patch
            pod = make_pod(requests={"cpu": "0.25"})
            env.cluster.create("pods", pod)
            env.worker.batcher.add(pod)
            env.worker.provision_once()
            env.cluster.merge_patch = orig
            bound = env.cluster.get("pods", pod.metadata.name, pod.metadata.namespace)
            assert bound.spec.node_name  # solver provided after the lost claim
            # the un-claimed warm nodes keep their marker (TTL will reap)
            assert env.warm_nodes()
        finally:
            env.stop()


class TestSpeculativeReplayLadder:
    """The GC replay rungs for speculative entries — including the
    regression this PR fixes: an entry past the TTL is GC-eligible EVEN
    THOUGH its instance is alive and tracked."""

    def _standing(self, env):
        # one pod per horizon at 5 pods-per-node → exactly one warm node
        env.forecast_demand(pods_per_s=1, pods_per_node=5.0)
        env.wp.reconcile(WARM_POOL_KEY)
        entries = env.journal.unresolved()
        assert len(entries) == 1
        return entries[0]

    @staticmethod
    def _forget_node(env, name):
        """Simulate the crash that ate the Node write: drop the object
        (finalizers cleared so the fake apiserver really deletes)."""
        node = env.cluster.get("nodes", name, "")
        node.metadata.finalizers = []
        env.cluster.delete("nodes", name, namespace="")

    def _by_token(self, env):
        return {i.launch_token: i for i in env.provider.list_instances()
                if i.launch_token}

    def _replay(self, env, entry, now):
        return recovery.replay_entry(
            env.journal, env.cluster, env.provider, entry,
            self._by_token(env), now=now, replay_after=0.0,
            warm_pool_ttl=env.wp.warm_pool_ttl,
        )

    def test_standing_within_ttl_stays_open(self):
        env = _Env(ttl=600.0)
        try:
            entry = self._standing(env)
            out = self._replay(env, entry, now=entry.created_at + 10)
            assert out == recovery.PENDING
            assert env.journal.get(entry.token) is not None
            assert env.warm_nodes()  # untouched
        finally:
            env.stop()

    def test_claimed_entry_resolves(self):
        env = _Env()
        try:
            entry = self._standing(env)
            env.cluster.merge_patch(
                "nodes", entry.node_name,
                {"metadata": {"annotations": {lbl.WARM_POOL_ANNOTATION: None}}},
                namespace="",
            )
            out = self._replay(env, entry, now=entry.created_at + 10)
            assert out == recovery.NODE_EXISTS
            assert env.journal.get(entry.token) is None
            # claimed node is NOT reaped
            assert env.cluster.try_get(
                "nodes", entry.node_name, namespace=""
            ) is not None
        finally:
            env.stop()

    def test_expired_standing_entry_reaped_despite_live_instance(self):
        """THE regression: live instance + tracked Node + open speculative
        entry past TTL → reclaim instance AND node AND entry. Without the
        TTL rung the open entry protects the instance forever."""
        env = _Env(ttl=60.0)
        try:
            entry = self._standing(env)
            assert self._by_token(env)  # instance is alive
            out = self._replay(env, entry, now=entry.created_at + 61)
            assert out == recovery.SPECULATION_EXPIRED
            assert env.journal.get(entry.token) is None
            assert entry.token not in self._by_token(env)  # terminated
            assert env.cluster.try_get(
                "nodes", entry.node_name, namespace=""
            ) is None
            # nothing leaked: every live instance maps to a node
            assert env.provider.list_instances() == []
        finally:
            env.stop()

    def test_expired_untracked_instance_reaped(self):
        env = _Env(ttl=60.0)
        try:
            entry = self._standing(env)
            self._forget_node(env, entry.node_name)
            out = self._replay(env, entry, now=entry.created_at + 61)
            assert out == recovery.SPECULATION_EXPIRED
            assert env.journal.get(entry.token) is None
            assert entry.token not in self._by_token(env)
        finally:
            env.stop()

    def test_untracked_within_ttl_adopted_back_into_pool(self):
        env = _Env(ttl=600.0)
        try:
            entry = self._standing(env)
            self._forget_node(env, entry.node_name)
            out = self._replay(env, entry, now=entry.created_at + 10)
            assert out == recovery.ADOPTED
            # entry stays open (the TTL breadcrumb) and the node carries
            # the warm marker again — claimable standing capacity
            assert env.journal.get(entry.token) is not None
            adopted = env.warm_nodes()
            assert len(adopted) == 1
            assert adopted[0].metadata.annotations[
                lbl.LAUNCH_TOKEN_ANNOTATION
            ] == entry.token
        finally:
            env.stop()

    def test_gc_controller_reaps_expired_speculation(self):
        """End-to-end through the GC sweep: short TTL, clock advanced past
        it → the sweep reclaims the warm node and closes the journal."""
        from karpenter_tpu.controllers.garbage_collection import (
            GarbageCollectionController,
        )

        env = _Env(ttl=0.05)
        try:
            entry = self._standing(env)
            gc = GarbageCollectionController(
                env.cluster, env.provider, journal=env.journal,
                gc_interval=0.01, replay_after=0.0, warm_pool_ttl=0.05,
            )
            deadline = time.time() + 5.0
            while time.time() < deadline and env.journal.unresolved():
                gc.reconcile("__gc__")
                time.sleep(0.02)
            assert env.journal.unresolved() == []
            assert env.warm_nodes() == []
            assert entry.token not in self._by_token(env)
        finally:
            env.stop()
