"""Test configuration.

Forces JAX onto a virtual 8-device CPU mesh BEFORE jax is imported anywhere,
so sharding/multi-chip tests run without TPU hardware (the driver separately
dry-runs the multi-chip path via __graft_entry__.dryrun_multichip).
"""

import os

# Force CPU even under the axon TPU tunnel (its sitecustomize registers the
# TPU backend whenever PALLAS_AXON_POOL_IPS is set). Set KARPENTER_TEST_TPU=1
# to run against the real chip instead (enables the pallas parity tests).
if os.environ.get("KARPENTER_TEST_TPU") != "1":
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ["JAX_PLATFORMS"] = "cpu"
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
    # The env var alone is NOT enough: the axon plugin's sitecustomize runs
    # at interpreter startup (before conftest) and registers the TPU backend
    # regardless; jax.config still wins if no backend was initialized yet.
    import jax

    jax.config.update("jax_platforms", "cpu")

import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _fresh_cost_router():
    """The packer cost router is process-shared (worker hot-swap and the
    consolidation shadow scheduler must inherit learning); tests need each
    test's routing decisions independent of what earlier tests measured."""
    from karpenter_tpu.solver import router

    router.reset_default()
    yield
    router.reset_default()
