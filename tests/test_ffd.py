"""FFD reference-scheduler behavior tests (mirrors contexts from
pkg/controllers/provisioning/scheduling/suite_test.go and
instance_selection_test.go)."""

import random

import pytest

from karpenter_tpu.api import labels as lbl
from karpenter_tpu.api.objects import NodeSelectorRequirement as R, Taint, Toleration
from karpenter_tpu.cloudprovider.fake import (
    default_catalog,
    instance_types,
    instance_types_assorted,
)
from karpenter_tpu.cloudprovider.requirements import catalog_requirements
from karpenter_tpu.kube.client import Cluster
from karpenter_tpu.scheduling.ffd import FFDScheduler
from karpenter_tpu.utils import resources as res
from tests.factories import hostname_spread, make_daemonset, make_pod, make_provisioner, zone_spread


def solve(pods, catalog=None, provisioner=None, cluster=None):
    catalog = catalog if catalog is not None else default_catalog()
    cluster = cluster or Cluster()
    provisioner = provisioner or make_provisioner()
    constraints = provisioner.spec.constraints
    constraints.requirements = constraints.requirements.merge(catalog_requirements(catalog))
    sched = FFDScheduler(cluster, rng=random.Random(42))
    return sched.solve(constraints, catalog, pods)


class TestBasicPacking:
    def test_one_pod_one_node(self):
        nodes = solve([make_pod(requests={"cpu": "1"})])
        assert len(nodes) == 1
        assert len(nodes[0].pods) == 1

    def test_packs_multiple_pods_on_one_node(self):
        pods = [make_pod(requests={"cpu": "1"}) for _ in range(3)]
        nodes = solve(pods)
        assert len(nodes) == 1
        assert len(nodes[0].pods) == 3

    def test_opens_new_node_when_full(self):
        # catalog of one 4-cpu type with 100m overhead: two 3-cpu pods can't share
        catalog = instance_types(4)  # 1..4 cpu types
        pods = [make_pod(requests={"cpu": "3"}) for _ in range(2)]
        nodes = solve(pods, catalog=catalog)
        assert len(nodes) == 2

    def test_unschedulable_pod_dropped(self):
        nodes = solve([make_pod(requests={"cpu": "1000"})])
        assert nodes == []

    def test_pod_count_limit(self):
        # default-instance-type allows 5 pods; 100m cpu each fits cpu-wise
        pods = [make_pod(requests={"cpu": "0.1"}) for _ in range(7)]
        nodes = solve(pods)
        assert len(nodes) == 2
        assert sum(len(n.pods) for n in nodes) == 7


class TestInstanceSelection:
    def test_lands_on_cheapest_feasible(self):
        catalog = instance_types_assorted()
        random.Random(0).shuffle(catalog)
        nodes = solve([make_pod(requests={"cpu": "0.9"})], catalog=catalog)
        assert len(nodes) == 1
        # cheapest surviving option should be first and minimal-cpu
        cheapest = min(nodes[0].instance_type_options, key=lambda it: it.effective_price())
        assert nodes[0].instance_type_options[0].effective_price() == cheapest.effective_price()
        assert nodes[0].instance_type_options[0].resources[res.CPU] == 1.0

    def test_arch_constraint_respected(self):
        catalog = instance_types_assorted()
        nodes = solve(
            [
                make_pod(
                    requests={"cpu": "0.5"},
                    node_requirements=[R(key=lbl.ARCH, operator="In", values=["arm64"])],
                )
            ],
            catalog=catalog,
        )
        assert len(nodes) == 1
        assert all(it.architecture == "arm64" for it in nodes[0].instance_type_options)


class TestConstraints:
    def test_node_selector_zone(self):
        pods = [
            make_pod(requests={"cpu": "1"}, node_selector={lbl.TOPOLOGY_ZONE: "test-zone-1"}),
            make_pod(requests={"cpu": "1"}, node_selector={lbl.TOPOLOGY_ZONE: "test-zone-2"}),
        ]
        nodes = solve(pods)
        assert len(nodes) == 2

    def test_incompatible_selector_unschedulable(self):
        nodes = solve([make_pod(node_selector={lbl.TOPOLOGY_ZONE: "unknown-zone"})])
        assert nodes == []

    def test_taints_block_intolerant_pods(self):
        provisioner = make_provisioner(taints=[Taint(key="dedicated", value="gpu", effect="NoSchedule")])
        # FFD itself doesn't gate on taints (selection does), but the
        # provisioner-level validate_pod must reject
        pod = make_pod()
        assert provisioner.spec.constraints.validate_pod(pod)
        tolerant = make_pod(
            tolerations=[Toleration(key="dedicated", operator="Equal", value="gpu")]
        )
        assert provisioner.spec.constraints.validate_pod(tolerant) == []

    def test_provisioner_requirement_narrows_zones(self):
        provisioner = make_provisioner(
            requirements=[R(key=lbl.TOPOLOGY_ZONE, operator="In", values=["test-zone-2"])]
        )
        nodes = solve([make_pod(requests={"cpu": "1"})], provisioner=provisioner)
        assert len(nodes) == 1
        assert nodes[0].constraints.requirements.zones() == {"test-zone-2"}


class TestTopology:
    def test_zone_spread(self):
        pods = [
            make_pod(requests={"cpu": "0.5"}, labels={"app": "web"}, topology=[zone_spread(labels={"app": "web"})])
            for _ in range(3)
        ]
        nodes = solve(pods)
        zones = set()
        for n in nodes:
            zones.update(n.constraints.requirements.zones())
        # 3 pods with maxSkew 1 over 3 zones → one pod per zone
        assert len(nodes) == 3
        assert len(zones) == 3

    def test_hostname_spread(self):
        pods = [
            make_pod(
                requests={"cpu": "0.5"},
                labels={"app": "web"},
                topology=[hostname_spread(labels={"app": "web"})],
            )
            for _ in range(3)
        ]
        nodes = solve(pods)
        # maxSkew=1 over generated hostnames → one pod per hostname/node
        assert len(nodes) == 3

    def test_zone_spread_counts_existing_cluster_pods(self):
        from karpenter_tpu.api.objects import Node, ObjectMeta

        cluster = Cluster()
        # an existing node in test-zone-1 running 2 matching pods
        cluster.create(
            "nodes",
            Node(metadata=ObjectMeta(name="existing", namespace="", labels={lbl.TOPOLOGY_ZONE: "test-zone-1"})),
        )
        for i in range(2):
            p = make_pod(labels={"app": "web"}, node_name="existing", unschedulable=False)
            cluster.create("pods", p)
        pods = [
            make_pod(requests={"cpu": "0.5"}, labels={"app": "web"}, topology=[zone_spread(labels={"app": "web"})])
            for _ in range(2)
        ]
        nodes = solve(pods, cluster=cluster)
        zones = set()
        for n in nodes:
            zones.update(n.constraints.requirements.zones())
        # skew counts make zone-2/zone-3 preferred over loaded zone-1
        assert "test-zone-1" not in zones


class TestDaemonOverhead:
    def test_daemon_resources_reserved(self):
        cluster = Cluster()
        cluster.create("daemonsets", make_daemonset(requests={"cpu": "1"}))
        # 4-cpu nodes, 100m type overhead + 1cpu daemon → 2.5cpu pod fits
        # alone but two don't
        pods = [make_pod(requests={"cpu": "1.5"}) for _ in range(2)]
        nodes = solve(pods, catalog=instance_types(4), cluster=cluster)
        assert len(nodes) == 2

    def test_incompatible_daemonset_ignored(self):
        cluster = Cluster()
        cluster.create(
            "daemonsets",
            make_daemonset(requests={"cpu": "4"}, node_selector={"nope": "nope"}),
        )
        nodes = solve([make_pod(requests={"cpu": "1"})], cluster=cluster)
        assert len(nodes) == 1

    def test_daemonset_without_matching_toleration_ignored(self):
        # reference: 'should ignore daemonsets without matching tolerations'
        # — a tainted provisioner's nodes never run an intolerant daemonset,
        # so its requests must not inflate the overhead
        from karpenter_tpu.scheduling.ffd import daemon_overhead
        from tests.factories import make_provisioner

        cluster = Cluster()
        cluster.create("daemonsets", make_daemonset(requests={"cpu": "4"}))
        prov = make_provisioner(
            taints=[Taint(key="dedicated", value="team", effect="NoSchedule")]
        )
        overhead = daemon_overhead(cluster, prov.spec.constraints)
        assert overhead.get(res.CPU, 0.0) == 0.0
        # the same daemonset WITH the toleration counts
        cluster.create(
            "daemonsets",
            make_daemonset(
                requests={"cpu": "2"},
                tolerations=[Toleration(key="dedicated", value="team")],
            ),
        )
        overhead = daemon_overhead(cluster, prov.spec.constraints)
        assert overhead.get(res.CPU, 0.0) == 2.0


class TestAccelerators:
    def test_gpu_pod_gets_gpu_node(self):
        nodes = solve([make_pod(requests={res.NVIDIA_GPU: "1"})])
        assert len(nodes) == 1
        assert all(
            it.resources.get(res.NVIDIA_GPU, 0) >= 1 for it in nodes[0].instance_type_options
        )

    def test_benchmark_catalog_packs(self):
        catalog = instance_types(50)
        pods = [make_pod(requests={"cpu": "1", "memory": "1Gi"}) for _ in range(20)]
        nodes = solve(pods, catalog=catalog)
        assert sum(len(n.pods) for n in nodes) == 20


class TestScheduleAnyway:
    """whenUnsatisfiable semantics (reference: 'should violate max-skew
    when unsat = schedule anyway' / '... not ... do not schedule'): when a
    pod's own narrowing excludes every registered spread domain,
    ScheduleAnyway drops the constraint (no domain pinned — the pod remains
    schedulable on its own merits), DoNotSchedule pins an unprovidable
    domain (pod visibly unschedulable)."""

    def _inject(self, when: str):
        from karpenter_tpu.api.objects import LabelSelector, TopologySpreadConstraint
        from karpenter_tpu.scheduling.topology import Topology
        from tests.factories import make_provisioner

        sel = {"app": "s"}
        spread = TopologySpreadConstraint(
            max_skew=1,
            topology_key=lbl.TOPOLOGY_ZONE,
            when_unsatisfiable=when,
            label_selector=LabelSelector(match_labels=sel),
        )
        # the pod's own affinity excludes every zone the constraints
        # register (NotIn all viable) -> allowed domains are empty
        pod = make_pod(
            labels=sel,
            requests={"cpu": "0.5"},
            node_requirements=[
                R(key=lbl.TOPOLOGY_ZONE, operator="NotIn",
                  values=["test-zone-1", "test-zone-2", "test-zone-3"])
            ],
            topology=[spread],
        )
        catalog = instance_types(10)
        prov = make_provisioner()
        c = prov.spec.constraints
        c.requirements = c.requirements.merge(catalog_requirements(catalog))
        plan = Topology(Cluster(), rng=random.Random(1)).inject_plan(c, [pod])
        return pod, plan

    def test_schedule_anyway_leaves_pod_unpinned(self):
        pod, plan = self._inject("ScheduleAnyway")
        # soft: the spread stays out of the pod's way entirely
        assert plan.decision(pod, lbl.TOPOLOGY_ZONE) is None

    def test_do_not_schedule_pins_unprovidable_domain(self):
        pod, plan = self._inject("DoNotSchedule")
        pinned = plan.decision(pod, lbl.TOPOLOGY_ZONE)
        # hard: a domain is pinned, and it is one no offering provides
        assert pinned is not None
        assert pinned not in ("test-zone-1", "test-zone-2", "test-zone-3")

    @pytest.mark.parametrize("solver", ["ffd", "tpu"])
    def test_soft_spread_never_blocks_scheduling(self, solver):
        from karpenter_tpu.api.objects import LabelSelector, TopologySpreadConstraint
        from karpenter_tpu.scheduling.scheduler import Scheduler
        from tests.factories import make_provisioner

        sel = {"app": "s"}
        spread = TopologySpreadConstraint(
            max_skew=1, topology_key=lbl.TOPOLOGY_ZONE,
            when_unsatisfiable="ScheduleAnyway",
            label_selector=LabelSelector(match_labels=sel),
        )
        catalog = instance_types(10)
        prov = make_provisioner(solver=solver)
        c = prov.spec.constraints
        c.requirements = c.requirements.merge(catalog_requirements(catalog))
        pods = [
            make_pod(labels=sel, requests={"cpu": "0.5"}, topology=[spread])
            for _ in range(6)
        ]
        nodes = Scheduler(Cluster(), rng=random.Random(1)).solve(prov, catalog, pods)
        assert sum(len(n.pods) for n in nodes) == 6
