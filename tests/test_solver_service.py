"""Solver service tests: the flat-buffer codec, a live in-process gRPC
round trip of the packing kernel (SURVEY §5.8 — the reconcile-loop → JAX
sidecar transport), and the v3 session lifecycle (fingerprint miss →
NEEDS_CATALOG → transparent re-open, restart recovery, LRU/TTL eviction,
loud version-skew failure)."""

import random
import socket
import struct

import numpy as np
import pytest

from karpenter_tpu.solver.service import (
    N_POD_ARRAYS,
    SESSION_MAX,
    STATUS_NEEDS_CATALOG,
    STATUS_OK,
    RemoteSolver,
    SolverService,
    catalog_session_key,
    pack_arrays,
    serve,
    unpack_arrays,
)


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def encoded_args(n_types: int = 8, n_pods: int = 6, seed: int = 3):
    """A real encoded batch's ``pack_args`` tuple + its n_max."""
    from karpenter_tpu.cloudprovider.fake import instance_types
    from karpenter_tpu.cloudprovider.requirements import catalog_requirements
    from karpenter_tpu.kube.client import Cluster
    from karpenter_tpu.scheduling.ffd import daemon_overhead, sort_pods_ffd
    from karpenter_tpu.scheduling.topology import Topology
    from karpenter_tpu.solver import encode as enc
    from karpenter_tpu.testing import diverse_pods, make_provisioner

    catalog = sorted(instance_types(n_types), key=lambda it: it.effective_price())
    constraints = make_provisioner(solver="tpu").spec.constraints
    constraints.requirements = constraints.requirements.merge(
        catalog_requirements(catalog)
    )
    pods = sort_pods_ffd(diverse_pods(n_pods, random.Random(seed)))
    cluster = Cluster()
    Topology(cluster, rng=random.Random(1)).inject(constraints, pods)
    batch = enc.encode(
        constraints, catalog, pods, daemon_overhead(cluster, constraints)
    )
    return batch.pack_args(), len(batch.pod_valid)


class TestCodec:
    def test_round_trip_preserves_arrays(self):
        arrays = [
            np.array([True, False, True]),
            np.arange(12, dtype=np.int32).reshape(3, 4),
            np.random.default_rng(0).random((2, 3, 4)).astype(np.float32),
            np.array(7, dtype=np.int32),  # scalar
            np.zeros((0,), dtype=np.float32),  # empty
        ]
        out = unpack_arrays(pack_arrays(arrays))
        assert len(out) == len(arrays)
        for a, b in zip(arrays, out):
            assert a.dtype == b.dtype
            assert a.shape == b.shape
            np.testing.assert_array_equal(a, b)

    def test_off_spec_dtypes_normalized(self):
        out = unpack_arrays(pack_arrays([np.array([1, 2], dtype=np.int64),
                                         np.array([1.5], dtype=np.float64)]))
        assert out[0].dtype == np.int32
        assert out[1].dtype == np.float32

    def test_bad_magic_rejected(self):
        with pytest.raises(ValueError):
            unpack_arrays(b"NOPE" + b"\x00" * 16)


class TestRemoteSolve:
    def test_grpc_round_trip_matches_local_kernel(self):
        """Serve the kernel over gRPC in-process and verify the remote
        PackResult is identical to the local one on a real encoded batch."""
        import jax

        from karpenter_tpu.cloudprovider.fake import instance_types
        from karpenter_tpu.cloudprovider.requirements import catalog_requirements
        from karpenter_tpu.kube.client import Cluster
        from karpenter_tpu.scheduling.ffd import daemon_overhead, sort_pods_ffd
        from karpenter_tpu.scheduling.topology import Topology
        from karpenter_tpu.solver import encode as enc
        from karpenter_tpu.solver import kernel
        from karpenter_tpu.testing import diverse_pods, make_provisioner

        catalog = sorted(instance_types(16), key=lambda it: it.effective_price())
        provisioner = make_provisioner(solver="tpu")
        constraints = provisioner.spec.constraints
        constraints.requirements = constraints.requirements.merge(
            catalog_requirements(catalog)
        )
        pods = sort_pods_ffd(diverse_pods(24, random.Random(3)))
        cluster = Cluster()
        Topology(cluster, rng=random.Random(1)).inject(constraints, pods)
        daemon = daemon_overhead(cluster, constraints)
        batch = enc.encode(constraints, catalog, pods, daemon)
        args = (
            batch.pod_valid, batch.pod_open_sig, batch.pod_core, batch.pod_host,
            batch.pod_host_in_base, batch.pod_open_host, batch.pod_req,
            batch.join_table, batch.frontiers, batch.daemon,
        )
        n_max = len(batch.pod_valid)
        local = jax.device_get(tuple(kernel.pack(*args, n_max=n_max)))

        address = f"127.0.0.1:{free_port()}"
        server = serve(address)
        try:
            client = RemoteSolver(address, timeout=30)
            remote = client.pack(*args, n_max=n_max)
            for l, r in zip(local, tuple(remote)):
                np.testing.assert_array_equal(np.asarray(l), np.asarray(r))
            client.close()
        finally:
            server.stop(grace=1)

    def test_scheduler_uses_service_and_falls_back(self):
        """TpuScheduler with a service address produces the same virtual
        nodes; with a dead address it falls back to the in-process kernel."""
        from karpenter_tpu.cloudprovider.fake import instance_types
        from karpenter_tpu.cloudprovider.requirements import catalog_requirements
        from karpenter_tpu.kube.client import Cluster
        from karpenter_tpu.solver.backend import TpuScheduler
        from karpenter_tpu.testing import make_pod, make_provisioner

        catalog = instance_types(8)
        provisioner = make_provisioner(solver="tpu")
        constraints = provisioner.spec.constraints
        constraints.requirements = constraints.requirements.merge(
            catalog_requirements(catalog)
        )
        pods = [make_pod(requests={"cpu": "1"}) for _ in range(4)]

        address = f"127.0.0.1:{free_port()}"
        server = serve(address)
        try:
            remote_sched = TpuScheduler(
                Cluster(), rng=random.Random(0), service_address=address
            )
            vnodes = remote_sched.solve(constraints, catalog, pods)
            assert sum(len(v.pods) for v in vnodes) == 4
        finally:
            server.stop(grace=1)

        dead = TpuScheduler(
            Cluster(), rng=random.Random(0),
            service_address=f"127.0.0.1:{free_port()}",
        )
        dead._remote = None
        vnodes = dead.solve(constraints, catalog, pods)
        assert sum(len(v.pods) for v in vnodes) == 4  # fallback worked


class TestSessions:
    """The v3 session lifecycle: catalog tensors cross the wire once per
    fingerprint; everything else is delta solves + recovery paths."""

    def test_steady_state_pack_excludes_catalog_bytes(self):
        """Two solves, one OpenSession: the second Pack ships only the
        pod-side arrays, and the wire stage timings land in the profile."""
        args, n_max = encoded_args()
        address = f"127.0.0.1:{free_port()}"
        server = serve(address)
        try:
            client = RemoteSolver(address, timeout=30)
            prof = {}
            first = client.pack_begin(*args, n_max=n_max, prof=prof)()
            second = client.pack_begin(*args, n_max=n_max, prof=prof)()
            assert client.session_uploads == 1
            assert "wire_ser_s" in prof and "wire_deser_s" in prof
            for a, b in zip(tuple(first), tuple(second)):
                np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
            # and the delta frame really is smaller than the v2-equivalent
            # full frame by at least the catalog bytes
            from karpenter_tpu.solver.service import _key_array

            key = catalog_session_key(*args[N_POD_ARRAYS:])
            delta = pack_arrays(
                [_key_array(key), np.asarray([n_max], np.int32)]
                + [np.asarray(a) for a in args[:N_POD_ARRAYS]]
            )
            full = pack_arrays([np.asarray(a) for a in args])
            catalog_bytes = sum(
                np.asarray(a).nbytes for a in args[N_POD_ARRAYS:]
            )
            assert len(full) - len(delta) >= catalog_bytes - 64
            client.close()
        finally:
            server.stop(grace=1)

    def test_fingerprint_miss_needs_catalog_then_transparent_reopen(self):
        """Server-side eviction (or any fingerprint miss) answers
        NEEDS_CATALOG; the client re-opens and the solve still succeeds."""
        args, n_max = encoded_args()
        address = f"127.0.0.1:{free_port()}"
        server = serve(address)
        try:
            client = RemoteSolver(address, timeout=30)
            first = client.pack(*args, n_max=n_max)
            assert client.session_uploads == 1
            # evict everything server-side; the client still believes its
            # session is open — exactly the LRU/TTL-eviction shape
            svc = server.solver_service
            with svc._sessions_lock:
                svc._sessions.clear()
            second = client.pack(*args, n_max=n_max)
            assert client.session_uploads == 2  # transparent re-open happened
            for a, b in zip(tuple(first), tuple(second)):
                np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
            client.close()
        finally:
            server.stop(grace=1)

    def test_sidecar_restart_recovery(self):
        """A restarted sidecar has an empty session store; the same client
        object recovers through NEEDS_CATALOG without caller involvement."""
        args, n_max = encoded_args()
        address = f"127.0.0.1:{free_port()}"
        server = serve(address)
        client = RemoteSolver(address, timeout=30)
        try:
            first = client.pack(*args, n_max=n_max)
        finally:
            server.stop(grace=1)
        server2 = serve(address)  # fresh process-equivalent: no sessions
        try:
            second = client.pack(*args, n_max=n_max)
            assert client.session_uploads == 2
            for a, b in zip(tuple(first), tuple(second)):
                np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
            client.close()
        finally:
            server2.stop(grace=1)

    def test_session_lru_eviction_under_many_catalogs(self):
        """More live catalog generations than session_max: the LRU holds the
        cap and evictions are counted."""
        from prometheus_client import generate_latest

        from karpenter_tpu import metrics
        from karpenter_tpu.solver.service import _key_array

        svc = SolverService(session_max=2)
        rng = np.random.default_rng(0)
        keys = []
        for i in range(4):
            join = rng.integers(-1, 5, (3, 2)).astype(np.int32)
            front = rng.random((3, 1, 2)).astype(np.float32)
            daemon = np.zeros(2, np.float32)
            key = catalog_session_key(join, front, daemon)
            keys.append(key)
            svc.open_session_bytes(
                pack_arrays([_key_array(key), join, front, daemon])
            )
        assert svc.session_count() == 2
        with svc._sessions_lock:
            assert set(svc._sessions) == set(keys[-2:])  # LRU order kept
        out = generate_latest(metrics.REGISTRY).decode()
        assert "karpenter_solver_session_evictions_total" in out

    def test_session_ttl_eviction(self):
        """Catalog generations nobody touched within the TTL release their
        device memory on the next store maintenance."""
        from karpenter_tpu.solver.service import _key_array

        now = [0.0]
        svc = SolverService(session_ttl=10.0, clock=lambda: now[0])
        join = np.zeros((2, 2), np.int32)
        front = np.zeros((2, 1, 1), np.float32)
        daemon = np.zeros(1, np.float32)
        key = catalog_session_key(join, front, daemon)
        svc.open_session_bytes(pack_arrays([_key_array(key), join, front, daemon]))
        assert svc.session_count() == 1
        now[0] = 11.0
        join2 = np.ones((2, 2), np.int32)
        key2 = catalog_session_key(join2, front, daemon)
        svc.open_session_bytes(pack_arrays([_key_array(key2), join2, front, daemon]))
        with svc._sessions_lock:
            assert key not in svc._sessions and key2 in svc._sessions
        # store maintenance also rides the SOLVE path: in steady state no
        # further OpenSession arrives, yet stale generations must still
        # release their pinned tensors
        now[0] = 30.0
        response = svc.solve_bytes(
            pack_arrays([_key_array(key), np.asarray([4], np.int32)])
        )
        assert int(unpack_arrays(response)[0].reshape(-1)[0]) == STATUS_NEEDS_CATALOG
        assert svc.session_count() == 0  # key2 TTL-swept by the solve path

    def test_thrashing_store_reports_low_hit_rate(self):
        """More live catalogs than session_max: every solve re-pays the
        upload, and the hit rate must say ~0 — the NEEDS_CATALOG retry
        must not double-count as one miss plus one hit."""
        from karpenter_tpu.solver import session_stats

        args_a, n_max_a = encoded_args(n_types=8)
        args_b, n_max_b = encoded_args(n_types=12)
        key_a = catalog_session_key(*args_a[N_POD_ARRAYS:])
        key_b = catalog_session_key(*args_b[N_POD_ARRAYS:])
        assert key_a != key_b, "test needs two distinct catalog generations"
        address = f"127.0.0.1:{free_port()}"
        server = serve(address, service=SolverService(session_max=1))
        try:
            client = RemoteSolver(address, timeout=60)
            session_stats.reset()
            for _ in range(3):
                client.pack(*args_a, n_max=n_max_a)
                client.pack(*args_b, n_max=n_max_b)
            snap = session_stats.snapshot()
            # every round evicted the other generation: all misses after
            # the store's one slot flips, no phantom hits from retries
            assert snap["misses"] >= 5, snap
            assert snap["hit_rate"] < 0.2, snap
            client.close()
        finally:
            server.stop(grace=1)

    def test_reopen_of_resident_key_is_idempotent(self):
        """A client whose opened-LRU forgot a live key (or a second client
        of the same sidecar) re-opens it: no re-upload to HBM, no spurious
        miss, fresh state untouched."""
        from karpenter_tpu.solver import session_stats
        from karpenter_tpu.solver.service import _key_array

        svc = SolverService()
        join = np.arange(4, dtype=np.int32).reshape(2, 2)
        front = np.ones((2, 1, 1), np.float32)
        daemon = np.zeros(1, np.float32)
        key = catalog_session_key(join, front, daemon)
        frame = pack_arrays([_key_array(key), join, front, daemon])
        session_stats.reset()
        svc.open_session_bytes(frame)
        first = session_stats.snapshot()
        svc.open_session_bytes(frame)
        assert session_stats.snapshot() == first  # nothing re-counted
        assert svc.session_count() == 1
        with svc._sessions_lock:
            assert svc._sessions[key][2] is True  # still fresh

    def test_unknown_key_answers_needs_catalog(self):
        args, n_max = encoded_args()
        from karpenter_tpu.solver.service import _key_array

        svc = SolverService()
        key = catalog_session_key(*args[N_POD_ARRAYS:])
        response = svc.solve_bytes(
            pack_arrays(
                [_key_array(key), np.asarray([n_max], np.int32)]
                + [np.asarray(a) for a in args[:N_POD_ARRAYS]]
            )
        )
        status = int(unpack_arrays(response)[0].reshape(-1)[0])
        assert status == STATUS_NEEDS_CATALOG

    def test_v2_client_v3_server_skew_fails_loudly(self):
        """A v2 frame (version word 2) must be REJECTED with the version in
        the error — never mis-parsed as a session frame."""
        args, n_max = encoded_args()
        frame = bytearray(
            pack_arrays([np.asarray(a) for a in args]
                        + [np.asarray([n_max], np.int32)])
        )
        struct.pack_into("<H", frame, 4, 2)  # the v2 client's version word
        svc = SolverService()
        with pytest.raises(ValueError, match="unsupported version 2"):
            svc.solve_bytes(bytes(frame))
        # and symmetrically: a v3 client unpacking a v2-framed response
        with pytest.raises(ValueError, match="unsupported version 2"):
            unpack_arrays(bytes(frame))

    def test_default_session_bounds_sane(self):
        svc = SolverService()
        assert svc.session_max == SESSION_MAX > 0
        assert svc.session_ttl > 0
        assert STATUS_OK != STATUS_NEEDS_CATALOG


class TestOverloadControl:
    """Wire status words STATUS_OVERLOADED / STATUS_DEADLINE_EXCEEDED
    (docs/overload.md): the bounded admission gate, the propagated-deadline
    pre-dispatch shed, HBM-pressure gating of new uploads, typed client
    verdicts, and loud unknown-status failure."""

    def _opened(self, svc):
        """Open a real session on ``svc``; returns (key, pod-side arrays,
        n_max)."""
        from karpenter_tpu.solver.service import _key_array

        args, n_max = encoded_args()
        args = [np.asarray(a) for a in args]
        key = catalog_session_key(*args[N_POD_ARRAYS:])
        svc.open_session_bytes(
            pack_arrays([_key_array(key)] + args[N_POD_ARRAYS:])
        )
        return key, args[:N_POD_ARRAYS], n_max

    def _solve_frame(self, key, pod_arrays, n_max, deadline_s=None):
        from karpenter_tpu.solver.service import _key_array

        arrays = [_key_array(key), np.asarray([n_max, 1], np.int32)] + pod_arrays
        if deadline_s is not None:
            arrays.append(np.asarray([deadline_s], np.float32))
        return pack_arrays(arrays)

    def test_full_admission_queue_answers_overloaded_with_hint(self):
        from karpenter_tpu.solver.service import STATUS_OVERLOADED

        svc = SolverService(
            max_inflight=1, queue_depth=0, overload_retry_after=0.7,
        )
        key, pods, n_max = self._opened(svc)
        assert svc.admission.enter() == "admitted"  # occupy the one slot
        try:
            response = svc.solve_bytes(self._solve_frame(key, pods, n_max))
            status_arr, *payload = unpack_arrays(response)
            assert int(status_arr.reshape(-1)[0]) == STATUS_OVERLOADED
            # the retry-after hint rides the payload
            assert float(payload[0].reshape(-1)[0]) == pytest.approx(0.7)
            assert svc.shed["queue_full"] == 1
            assert svc.dispatches == 0
        finally:
            svc.admission.leave()
        # slot freed: the same frame now solves
        response = svc.solve_bytes(self._solve_frame(key, pods, n_max))
        assert int(unpack_arrays(response)[0].reshape(-1)[0]) == STATUS_OK
        assert svc.dispatches == 1

    def test_expired_deadline_sheds_before_device_dispatch(self):
        from karpenter_tpu.solver.service import STATUS_DEADLINE_EXCEEDED

        svc = SolverService()
        key, pods, n_max = self._opened(svc)
        # junk pod arrays prove the shed happens pre-dispatch: they would
        # crash the solve if it ever reached the kernel
        junk = [np.zeros(3, np.float32)] * N_POD_ARRAYS
        response = svc.solve_bytes(
            self._solve_frame(key, junk, n_max, deadline_s=0.0)
        )
        assert (
            int(unpack_arrays(response)[0].reshape(-1)[0])
            == STATUS_DEADLINE_EXCEEDED
        )
        assert svc.shed["deadline"] == 1
        assert svc.dispatches == 0

    def test_live_deadline_solves_normally(self):
        svc = SolverService()
        key, pods, n_max = self._opened(svc)
        response = svc.solve_bytes(
            self._solve_frame(key, pods, n_max, deadline_s=30.0)
        )
        assert int(unpack_arrays(response)[0].reshape(-1)[0]) == STATUS_OK
        assert svc.dispatches == 1
        assert svc.shed["deadline"] == 0

    def test_hbm_floor_refuses_new_uploads_resident_solves_flow(self, monkeypatch):
        from karpenter_tpu.solver import service as svcmod
        from karpenter_tpu.solver.service import STATUS_OVERLOADED, _key_array

        svc = SolverService(hbm_floor_bytes=1024)
        key, pods, n_max = self._opened(svc)  # resident BEFORE the pressure
        monkeypatch.setattr(svcmod, "publish_device_headroom", lambda: 0)
        # a NEW catalog generation is refused...
        args2, _ = encoded_args(n_types=5, n_pods=4, seed=9)
        args2 = [np.asarray(a) for a in args2]
        key2 = catalog_session_key(*args2[N_POD_ARRAYS:])
        assert key2 != key
        response = svc.open_session_bytes(
            pack_arrays([_key_array(key2)] + args2[N_POD_ARRAYS:])
        )
        assert int(unpack_arrays(response)[0].reshape(-1)[0]) == STATUS_OVERLOADED
        assert svc.shed["hbm_pressure"] == 1
        assert svc.session_count() == 1
        # ...while the RESIDENT session's solves keep flowing
        response = svc.solve_bytes(self._solve_frame(key, pods, n_max))
        assert int(unpack_arrays(response)[0].reshape(-1)[0]) == STATUS_OK
        # and re-opening the resident key is still a cheap touch, not a shed
        response = svc.open_session_bytes(
            pack_arrays(
                [_key_array(key)]
                + [np.asarray(a) for a in encoded_args()[0][N_POD_ARRAYS:]]
            )
        )
        assert int(unpack_arrays(response)[0].reshape(-1)[0]) == STATUS_OK

    def test_client_raises_typed_verdicts_and_unknown_fails_loud(self):
        from karpenter_tpu.resilience.overload import (
            DeadlineExceededError,
            OverloadedError,
        )
        from karpenter_tpu.solver.service import (
            STATUS_DEADLINE_EXCEEDED,
            STATUS_OVERLOADED,
        )

        rs = RemoteSolver.__new__(RemoteSolver)  # no channel needed
        rs.address = "test:1"
        with pytest.raises(OverloadedError) as ei:
            rs._check_status(
                STATUS_OVERLOADED, [np.asarray([2.5], np.float32)]
            )
        assert ei.value.retry_after == 2.5
        with pytest.raises(DeadlineExceededError):
            rs._check_status(STATUS_DEADLINE_EXCEEDED, [])
        with pytest.raises(RuntimeError, match="unknown solver status word 99"):
            rs._check_status(99, [])
        rs._check_status(STATUS_OK, [])  # no-op
        # a hint-less OVERLOADED payload still carries a sane default
        with pytest.raises(OverloadedError) as ei:
            rs._check_status(STATUS_OVERLOADED, [])
        assert ei.value.retry_after == 1.0

    def test_overloaded_over_live_grpc_and_old_frames_interop(self):
        """End to end over the wire: a full sidecar admission queue raises
        the typed OverloadedError client-side; an old-style frame (no
        trailers at all) still solves on the new server."""
        from karpenter_tpu.resilience.overload import OverloadedError

        address = f"127.0.0.1:{free_port()}"
        svc = SolverService(
            max_inflight=1, queue_depth=0, overload_retry_after=0.3,
        )
        server = serve(address, service=svc)
        try:
            args, n_max = encoded_args()
            client = RemoteSolver(address, timeout=10)
            client.pack(*args, n_max=n_max)  # old-client-shaped happy path
            assert svc.admission.enter() == "admitted"
            try:
                with pytest.raises(OverloadedError) as ei:
                    client.pack(*args, n_max=n_max)
                assert ei.value.retry_after == pytest.approx(0.3)
            finally:
                svc.admission.leave()
            client.pack(*args, n_max=n_max)  # recovered
            client.close()
        finally:
            server.stop(grace=0)

    def test_deadline_propagates_over_live_grpc(self):
        """The round Budget rides the wire: a request that outlives its
        budget in the sidecar's admission queue sheds pre-dispatch and the
        client surfaces the non-retryable verdict. With the capability bit
        stripped (an old server), the same frame carries no deadline and
        the solve goes through once the queue frees — rolling-upgrade
        interop."""
        import threading

        from karpenter_tpu.resilience import Budget
        from karpenter_tpu.resilience.overload import DeadlineExceededError
        from karpenter_tpu.solver.service import PROTO_TRACE_TRAILER

        address = f"127.0.0.1:{free_port()}"
        svc = SolverService(max_inflight=1, queue_depth=2)
        server = serve(address, service=svc)
        try:
            args, n_max = encoded_args()
            client = RemoteSolver(address, timeout=10)
            client.pack(*args, n_max=n_max)  # open the session, learn features
            assert svc.admission.enter() == "admitted"  # wedge the executor
            try:
                with Budget(0.3).activate():  # expires while queued
                    with pytest.raises(DeadlineExceededError):
                        client.pack(*args, n_max=n_max)
                assert svc.shed["deadline"] == 1
                dispatches = svc.dispatches
                # an "old server" never advertised PROTO_DEADLINE: the
                # client must not append the trailer, so the same doomed
                # budget just queues until the executor frees, then solves
                with client._lock:
                    client._server_features = PROTO_TRACE_TRAILER
                release = threading.Timer(0.5, svc.admission.leave)
                release.start()
                with Budget(0.3).activate():
                    client.pack(*args, n_max=n_max)
                release.join()
                assert svc.dispatches == dispatches + 1
                assert svc.shed["deadline"] == 1  # no further shed
            finally:
                pass  # the timer already released the wedge slot
            client.close()
        finally:
            server.stop(grace=0)


class TestHealth:
    def test_grpc_and_http_health_flip_on_readiness(self):
        """Readiness is gated on the warmup solve; a not-yet-warm sidecar
        reports NOT_SERVING / 503, a warmed one SERVING / 200."""
        import urllib.request

        address = f"127.0.0.1:{free_port()}"
        hport = free_port()
        server = serve(address, health_port=hport, warmup=True)
        try:
            client = RemoteSolver(address, timeout=5)
            # liveness is up immediately
            assert (
                urllib.request.urlopen(f"http://127.0.0.1:{hport}/healthz").status == 200
            )
            server.solver_service.ready.wait(timeout=120)
            assert server.solver_service.ready.is_set(), "warmup never finished"
            assert client.health() is True
            assert (
                urllib.request.urlopen(f"http://127.0.0.1:{hport}/readyz").status == 200
            )
            # the session store's metrics are scrapeable from the SIDECAR
            # process (the controller's registry never sees them)
            scrape = urllib.request.urlopen(
                f"http://127.0.0.1:{hport}/metrics"
            ).read().decode()
            assert "karpenter_solver_session_catalog_uploads_total" in scrape
            client.close()
        finally:
            server.health_server.shutdown()
            server.stop(grace=1)

    def test_unready_sidecar_reports_not_serving(self):
        import urllib.error
        import urllib.request

        address = f"127.0.0.1:{free_port()}"
        hport = free_port()
        server = serve(address, health_port=hport)
        server.solver_service.ready.clear()  # simulate still-warming
        try:
            client = RemoteSolver(address, timeout=5)
            assert client.health() is False
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(f"http://127.0.0.1:{hport}/readyz")
            assert ei.value.code == 503
            client.close()
        finally:
            server.health_server.shutdown()
            server.stop(grace=1)

    def test_dead_sidecar_health_false_and_breaker_metric(self):
        """A dead sidecar flips client health to False, and the breaker
        trip is scrapeable (VERDICT r1 weak #7)."""
        from prometheus_client import generate_latest

        from karpenter_tpu import metrics
        from karpenter_tpu.cloudprovider.fake import instance_types
        from karpenter_tpu.cloudprovider.requirements import catalog_requirements
        from karpenter_tpu.kube.client import Cluster
        from karpenter_tpu.solver.backend import TpuScheduler
        from karpenter_tpu.testing import make_pod, make_provisioner

        address = f"127.0.0.1:{free_port()}"
        client = RemoteSolver(address, timeout=2)
        assert client.health() is False
        client.close()

        catalog = instance_types(4)
        constraints = make_provisioner(solver="tpu").spec.constraints
        constraints.requirements = constraints.requirements.merge(
            catalog_requirements(catalog)
        )
        sched = TpuScheduler(Cluster(), rng=random.Random(0), service_address=address)
        sched.solve(constraints, catalog, [make_pod(requests={"cpu": "1"})])
        out = generate_latest(metrics.REGISTRY).decode()
        assert f'karpenter_solver_breaker_open{{address="{address}"}} 1.0' in out
        assert f'karpenter_solver_breaker_trips_total{{address="{address}"}} 1.0' in out


class TestHbmTelemetry:
    """Device-memory telemetry (docs/metrics.md): the per-session HBM
    gauge must track the session store exactly — labels appear on open,
    carry the pinned byte count, and vanish on LRU/TTL eviction."""

    @staticmethod
    def _hbm_labels():
        from karpenter_tpu import metrics

        return {
            s.labels["session"]: s.value
            for m in metrics.SOLVER_SESSION_HBM.collect()
            for s in m.samples
        }

    @staticmethod
    def _open(svc, seed):
        from karpenter_tpu.solver.service import _key_array

        rng = np.random.default_rng(seed)
        join = rng.integers(-1, 5, (3, 2)).astype(np.int32)
        front = rng.random((3, 1, 2)).astype(np.float32)
        daemon = np.zeros(2, np.float32)
        key = catalog_session_key(join, front, daemon)
        svc.open_session_bytes(pack_arrays([_key_array(key), join, front, daemon]))
        nbytes = join.nbytes + front.nbytes + daemon.nbytes
        return key.hex()[:12], nbytes

    def test_gauge_set_on_open_and_removed_on_lru_eviction(self):
        svc = SolverService(session_max=2)
        first, nbytes = self._open(svc, seed=10)
        labels = self._hbm_labels()
        assert labels.get(first) == nbytes  # catalog tensors, byte-exact
        second, _ = self._open(svc, seed=11)
        third, _ = self._open(svc, seed=12)  # LRU evicts `first`
        labels = self._hbm_labels()
        assert first not in labels
        assert second in labels and third in labels
        # the SUM over labels is what the store pins right now
        assert svc.session_count() == 2 == len(
            {k for k in labels if k in (second, third)}
        )

    def test_gauge_removed_on_ttl_eviction(self):
        now = [0.0]
        svc = SolverService(session_ttl=10.0, clock=lambda: now[0])
        first, _ = self._open(svc, seed=20)
        assert first in self._hbm_labels()
        now[0] = 11.0
        second, _ = self._open(svc, seed=21)  # open sweeps the stale entry
        labels = self._hbm_labels()
        assert first not in labels and second in labels

    def test_headroom_gauge_never_lies_on_cpu(self):
        """The CPU test rig reports no memory_stats: the headroom child
        must stay ABSENT (None return), never publish a fake zero."""
        from karpenter_tpu import metrics
        from karpenter_tpu.solver.service import publish_device_headroom

        got = publish_device_headroom()
        samples = [
            s for m in metrics.SOLVER_HBM_HEADROOM.collect() for s in m.samples
        ]
        if got is None:
            assert samples == []  # no child = no lie
        else:  # a real accelerator backend: the child carries the headroom
            assert got >= 0 and samples[0].value == got
