"""Solver service tests: the flat-buffer codec and a live in-process gRPC
round trip of the packing kernel (SURVEY §5.8 — the reconcile-loop → JAX
sidecar transport)."""

import random
import socket

import numpy as np
import pytest

from karpenter_tpu.solver.service import (
    RemoteSolver,
    pack_arrays,
    serve,
    unpack_arrays,
)


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class TestCodec:
    def test_round_trip_preserves_arrays(self):
        arrays = [
            np.array([True, False, True]),
            np.arange(12, dtype=np.int32).reshape(3, 4),
            np.random.default_rng(0).random((2, 3, 4)).astype(np.float32),
            np.array(7, dtype=np.int32),  # scalar
            np.zeros((0,), dtype=np.float32),  # empty
        ]
        out = unpack_arrays(pack_arrays(arrays))
        assert len(out) == len(arrays)
        for a, b in zip(arrays, out):
            assert a.dtype == b.dtype
            assert a.shape == b.shape
            np.testing.assert_array_equal(a, b)

    def test_off_spec_dtypes_normalized(self):
        out = unpack_arrays(pack_arrays([np.array([1, 2], dtype=np.int64),
                                         np.array([1.5], dtype=np.float64)]))
        assert out[0].dtype == np.int32
        assert out[1].dtype == np.float32

    def test_bad_magic_rejected(self):
        with pytest.raises(ValueError):
            unpack_arrays(b"NOPE" + b"\x00" * 16)


class TestRemoteSolve:
    def test_grpc_round_trip_matches_local_kernel(self):
        """Serve the kernel over gRPC in-process and verify the remote
        PackResult is identical to the local one on a real encoded batch."""
        import jax

        from karpenter_tpu.cloudprovider.fake import instance_types
        from karpenter_tpu.cloudprovider.requirements import catalog_requirements
        from karpenter_tpu.kube.client import Cluster
        from karpenter_tpu.scheduling.ffd import daemon_overhead, sort_pods_ffd
        from karpenter_tpu.scheduling.topology import Topology
        from karpenter_tpu.solver import encode as enc
        from karpenter_tpu.solver import kernel
        from karpenter_tpu.testing import diverse_pods, make_provisioner

        catalog = sorted(instance_types(16), key=lambda it: it.effective_price())
        provisioner = make_provisioner(solver="tpu")
        constraints = provisioner.spec.constraints
        constraints.requirements = constraints.requirements.merge(
            catalog_requirements(catalog)
        )
        pods = sort_pods_ffd(diverse_pods(24, random.Random(3)))
        cluster = Cluster()
        Topology(cluster, rng=random.Random(1)).inject(constraints, pods)
        daemon = daemon_overhead(cluster, constraints)
        batch = enc.encode(constraints, catalog, pods, daemon)
        args = (
            batch.pod_valid, batch.pod_open_sig, batch.pod_core, batch.pod_host,
            batch.pod_host_in_base, batch.pod_open_host, batch.pod_req,
            batch.join_table, batch.frontiers, batch.daemon,
        )
        n_max = len(batch.pod_valid)
        local = jax.device_get(tuple(kernel.pack(*args, n_max=n_max)))

        address = f"127.0.0.1:{free_port()}"
        server = serve(address)
        try:
            client = RemoteSolver(address, timeout=30)
            remote = client.pack(*args, n_max=n_max)
            for l, r in zip(local, tuple(remote)):
                np.testing.assert_array_equal(np.asarray(l), np.asarray(r))
            client.close()
        finally:
            server.stop(grace=1)

    def test_scheduler_uses_service_and_falls_back(self):
        """TpuScheduler with a service address produces the same virtual
        nodes; with a dead address it falls back to the in-process kernel."""
        from karpenter_tpu.cloudprovider.fake import instance_types
        from karpenter_tpu.cloudprovider.requirements import catalog_requirements
        from karpenter_tpu.kube.client import Cluster
        from karpenter_tpu.solver.backend import TpuScheduler
        from karpenter_tpu.testing import make_pod, make_provisioner

        catalog = instance_types(8)
        provisioner = make_provisioner(solver="tpu")
        constraints = provisioner.spec.constraints
        constraints.requirements = constraints.requirements.merge(
            catalog_requirements(catalog)
        )
        pods = [make_pod(requests={"cpu": "1"}) for _ in range(4)]

        address = f"127.0.0.1:{free_port()}"
        server = serve(address)
        try:
            remote_sched = TpuScheduler(
                Cluster(), rng=random.Random(0), service_address=address
            )
            vnodes = remote_sched.solve(constraints, catalog, pods)
            assert sum(len(v.pods) for v in vnodes) == 4
        finally:
            server.stop(grace=1)

        dead = TpuScheduler(
            Cluster(), rng=random.Random(0),
            service_address=f"127.0.0.1:{free_port()}",
        )
        dead._remote = None
        vnodes = dead.solve(constraints, catalog, pods)
        assert sum(len(v.pods) for v in vnodes) == 4  # fallback worked


class TestHealth:
    def test_grpc_and_http_health_flip_on_readiness(self):
        """Readiness is gated on the warmup solve; a not-yet-warm sidecar
        reports NOT_SERVING / 503, a warmed one SERVING / 200."""
        import urllib.request

        address = f"127.0.0.1:{free_port()}"
        hport = free_port()
        server = serve(address, health_port=hport, warmup=True)
        try:
            client = RemoteSolver(address, timeout=5)
            # liveness is up immediately
            assert (
                urllib.request.urlopen(f"http://127.0.0.1:{hport}/healthz").status == 200
            )
            server.solver_service.ready.wait(timeout=120)
            assert server.solver_service.ready.is_set(), "warmup never finished"
            assert client.health() is True
            assert (
                urllib.request.urlopen(f"http://127.0.0.1:{hport}/readyz").status == 200
            )
            client.close()
        finally:
            server.health_server.shutdown()
            server.stop(grace=1)

    def test_unready_sidecar_reports_not_serving(self):
        import urllib.error
        import urllib.request

        address = f"127.0.0.1:{free_port()}"
        hport = free_port()
        server = serve(address, health_port=hport)
        server.solver_service.ready.clear()  # simulate still-warming
        try:
            client = RemoteSolver(address, timeout=5)
            assert client.health() is False
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(f"http://127.0.0.1:{hport}/readyz")
            assert ei.value.code == 503
            client.close()
        finally:
            server.health_server.shutdown()
            server.stop(grace=1)

    def test_dead_sidecar_health_false_and_breaker_metric(self):
        """A dead sidecar flips client health to False, and the breaker
        trip is scrapeable (VERDICT r1 weak #7)."""
        from prometheus_client import generate_latest

        from karpenter_tpu import metrics
        from karpenter_tpu.cloudprovider.fake import instance_types
        from karpenter_tpu.cloudprovider.requirements import catalog_requirements
        from karpenter_tpu.kube.client import Cluster
        from karpenter_tpu.solver.backend import TpuScheduler
        from karpenter_tpu.testing import make_pod, make_provisioner

        address = f"127.0.0.1:{free_port()}"
        client = RemoteSolver(address, timeout=2)
        assert client.health() is False
        client.close()

        catalog = instance_types(4)
        constraints = make_provisioner(solver="tpu").spec.constraints
        constraints.requirements = constraints.requirements.merge(
            catalog_requirements(catalog)
        )
        sched = TpuScheduler(Cluster(), rng=random.Random(0), service_address=address)
        sched.solve(constraints, catalog, [make_pod(requests={"cpu": "1"})])
        out = generate_latest(metrics.REGISTRY).decode()
        assert f'karpenter_solver_breaker_open{{address="{address}"}} 1.0' in out
        assert f'karpenter_solver_breaker_trips_total{{address="{address}"}} 1.0' in out
