"""Streaming solver transport tests (docs/solver-transport.md § Streaming).

Covers the stream lifecycle satellites end to end: envelope codec
loudness, out-of-order completion under injected latency, mid-stream
sidecar restart (NEEDS_CATALOG re-open OVER the stream), credit
exhaustion → soft backoff → re-admit, corrupt streamed frames →
STATUS_INTEGRITY/quarantine, PROTO_STREAM interop in both rolling-upgrade
orders, the zero-copy shm arena, cross-stream dispatch coalescing
bit-exactness, and the TTL-sweep/HBM-gate parity the stream path must
keep with the unary path."""

import random
import socket
import struct
import threading
import time

import numpy as np
import pytest

from karpenter_tpu.resilience.overload import OverloadedError
from karpenter_tpu.solver import stream as st
from karpenter_tpu.solver.service import (
    N_POD_ARRAYS,
    PROTO_FEATURES,
    PROTO_STREAM,
    STATUS_INTEGRITY,
    STATUS_OK,
    STATUS_OVERLOADED,
    RemoteSolver,
    SolverService,
    append_checksum,
    catalog_session_key,
    pack_arrays,
    serve,
    unpack_arrays,
    _key_array,
)


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def encoded_args(n_types: int = 8, n_pods: int = 6, seed: int = 3):
    """A real encoded batch's ``pack_args`` tuple + its pod count."""
    from karpenter_tpu.cloudprovider.fake import instance_types
    from karpenter_tpu.cloudprovider.requirements import catalog_requirements
    from karpenter_tpu.kube.client import Cluster
    from karpenter_tpu.scheduling.ffd import daemon_overhead, sort_pods_ffd
    from karpenter_tpu.scheduling.topology import Topology
    from karpenter_tpu.solver import encode as enc
    from karpenter_tpu.testing import diverse_pods, make_provisioner

    catalog = sorted(instance_types(n_types), key=lambda it: it.effective_price())
    constraints = make_provisioner(solver="tpu").spec.constraints
    constraints.requirements = constraints.requirements.merge(
        catalog_requirements(catalog)
    )
    pods = sort_pods_ffd(diverse_pods(n_pods, random.Random(seed)))
    cluster = Cluster()
    Topology(cluster, rng=random.Random(1)).inject(constraints, pods)
    batch = enc.encode(
        constraints, catalog, pods, daemon_overhead(cluster, constraints)
    )
    return [np.asarray(a) for a in batch.pack_args()], len(batch.pod_valid)


def wait_until(predicate, timeout=8.0, interval=0.02) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()


def assert_results_equal(a, b):
    for f in a._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(a, f)), np.asarray(getattr(b, f)), err_msg=f
        )


# ---------------------------------------------------------------------------
# envelope codec
# ---------------------------------------------------------------------------


class TestEnvelope:
    def test_round_trip(self):
        payload = b"\x01\x02\x03" * 100
        msg = st.pack_stream_msg(st.MSG_SOLVE, 1234567890123, payload)
        mt, corr, out = st.unpack_stream_msg(msg)
        assert (mt, corr, out) == (st.MSG_SOLVE, 1234567890123, payload)

    def test_bad_magic_loud(self):
        msg = bytearray(st.pack_stream_msg(st.MSG_SOLVE, 1, b"x"))
        msg[0] ^= 0xFF
        with pytest.raises(ValueError, match="magic"):
            st.unpack_stream_msg(bytes(msg))

    @pytest.mark.parametrize("version", [0, 2, 255])
    def test_version_skew_loud(self, version):
        msg = bytearray(st.pack_stream_msg(st.MSG_SOLVE, 1, b"x"))
        struct.pack_into("<H", msg, 4, version)
        with pytest.raises(ValueError, match=f"stream version {version}"):
            st.unpack_stream_msg(bytes(msg))

    def test_corr_id_flip_detected(self):
        """A flipped correlation id must NEVER route: it would complete
        the wrong future with another solve's checksum-valid result —
        the one silent-corruption hole multiplexing opens."""
        msg = bytearray(st.pack_stream_msg(st.MSG_RESULT, 7, b"payload"))
        msg[8] ^= 0x01  # first corr-id byte
        with pytest.raises(st.EnvelopeCorrupt):
            st.unpack_stream_msg(bytes(msg))

    def test_truncated_envelope_loud(self):
        msg = st.pack_stream_msg(st.MSG_SOLVE, 1, b"")
        with pytest.raises(ValueError, match="truncated"):
            st.unpack_stream_msg(msg[:10])


# ---------------------------------------------------------------------------
# shm arena
# ---------------------------------------------------------------------------


class TestShmArena:
    def _arrays(self):
        rng = np.random.default_rng(5)
        return [
            np.array([True, False, True, True]),
            rng.integers(0, 100, (4, 3)).astype(np.int32),
            rng.random((2, 5)).astype(np.float32),
            np.array(3, np.int32),  # scalar
        ]

    def test_write_read_round_trip(self, tmp_path):
        arena = st.ShmArena(str(tmp_path), size=1 << 20)
        reader = st.ShmArenaReader(arena.path)
        arrays = self._arrays()
        token, desc = arena.write(arrays)
        out = reader.read(desc)
        assert len(out) == len(arrays)
        for a, b in zip(arrays, out):
            assert a.dtype == b.dtype and a.shape == b.shape
            np.testing.assert_array_equal(a, b)
        arena.free(token)
        assert arena.live_blocks() == 0
        reader.close()
        arena.close()

    def test_header_corruption_rejected(self, tmp_path):
        arena = st.ShmArena(str(tmp_path), size=1 << 20)
        reader = st.ShmArenaReader(arena.path)
        token, desc = arena.write(self._arrays())
        bad = desc.copy()
        bad[0] += 1  # token mismatch vs the in-arena header
        with pytest.raises(ValueError):
            reader.read(bad)
        # clobber the in-arena header itself: CRC catches it
        base = int(desc[1]) | (int(desc[2]) << 31)
        arena._map[base + 4:base + 8] = b"\xff\xff\xff\xff"
        with pytest.raises(ValueError):
            reader.read(desc)
        reader.close()
        arena.close()

    def test_full_arena_returns_none(self, tmp_path):
        arena = st.ShmArena(str(tmp_path), size=4096)
        big = [np.zeros(8192, np.float32)]
        assert arena.write(big) is None  # larger than the arena
        small = [np.zeros(256, np.float32)]
        tokens = []
        while True:
            wrote = arena.write(small)
            if wrote is None:
                break
            tokens.append(wrote[0])
        assert tokens, "at least one small block must fit"
        # freeing makes room again (the wraparound path)
        arena.free(tokens[0])
        assert arena.write(small) is not None
        arena.close()

    def test_out_of_bounds_descriptor_rejected(self, tmp_path):
        arena = st.ShmArena(str(tmp_path), size=1 << 16)
        reader = st.ShmArenaReader(arena.path)
        desc = np.asarray([1, 1 << 20, 0, 1, 2, 1, 4], np.int32)
        with pytest.raises(ValueError):
            reader.read(desc)
        reader.close()
        arena.close()


# ---------------------------------------------------------------------------
# live stream lifecycle
# ---------------------------------------------------------------------------


class _Harness:
    """One live sidecar + unary reference client; streamed clients are
    created per test and closed by :meth:`stop`."""

    def __init__(self, service=None, shm_dir="", coalesce_window_s=None,
                 checksum=True):
        self.address = f"127.0.0.1:{free_port()}"
        self.server = serve(
            self.address, service=service, shm_dir=shm_dir,
            coalesce_window_s=coalesce_window_s,
        )
        self.checksum = checksum
        self.clients = []

    def client(self, stream=True, shm_dir="", checksum=None) -> RemoteSolver:
        c = RemoteSolver(
            self.address, timeout=10.0, cold_timeout=60.0,
            checksum=self.checksum if checksum is None else checksum,
            stream=stream, shm_dir=shm_dir,
        )
        self.clients.append(c)
        return c

    def restart(self, service=None, **kw):
        self.server.stop(grace=0)
        self.server = serve(self.address, service=service, **kw)

    def stop(self):
        for c in self.clients:
            try:
                c.close()
            except Exception:
                pass
        self.server.stop(grace=0)


@pytest.fixture
def args16():
    args, p = encoded_args()
    return args, p


class TestStreamLifecycle:
    def test_streamed_solve_matches_unary(self, args16):
        args, _ = args16
        h = _Harness()
        try:
            ref = h.client(stream=False).pack(*args, n_max=16)
            rs = h.client(stream=True)
            rs.pack(*args, n_max=16)  # opens session, establishes stream
            assert wait_until(lambda: rs._stream is not None and rs._stream.up)
            prof = {}
            out = rs.pack_begin(*args, n_max=16, prof=prof)()
            assert_results_equal(out, ref)
            assert prof["solver_transport"] == "stream"
            assert h.server.solver_service.stream_stats["stream_solves"] >= 1
        finally:
            h.stop()

    def test_out_of_order_completion_under_latency(self, args16):
        """A slow solve dispatched FIRST must not head-of-line-block a
        fast one dispatched after it: responses complete out of order
        into their own futures (the multiplexing contract)."""
        args, _ = args16
        sleeps = {24: 1.0, 16: 0.0}

        class Laggy(SolverService):
            def solve_stream_group(self, entries):
                time.sleep(sleeps.get(entries[0].n_max, 0.0))
                super().solve_stream_group(entries)

        h = _Harness(service=Laggy())
        try:
            rs = h.client(stream=True)
            ref16 = h.client(stream=False).pack(*args, n_max=16)
            ref24 = h.client(stream=False).pack(*args, n_max=24)
            rs.pack(*args, n_max=16)  # warm + establish
            assert wait_until(lambda: rs._stream is not None and rs._stream.up)
            prof_a, prof_b = {}, {}
            t0 = time.perf_counter()
            wait_slow = rs.pack_begin(*args, n_max=24, prof=prof_a)
            wait_fast = rs.pack_begin(*args, n_max=16, prof=prof_b)
            out_fast = wait_fast()
            fast_done = time.perf_counter() - t0
            out_slow = wait_slow()
            assert prof_a["solver_transport"] == "stream"
            assert prof_b["solver_transport"] == "stream"
            # the fast solve completed while the slow one was still
            # sleeping server-side — out-of-order completion for real
            assert fast_done < 0.9, fast_done
            assert_results_equal(out_fast, ref16)
            assert_results_equal(out_slow, ref24)
        finally:
            h.stop()

    def test_midstream_restart_reopens_over_stream(self, args16):
        """Sidecar restart: the stream breaks, re-establishes in the
        background against the fresh (empty-store) service, and the
        NEEDS_CATALOG recovery — re-open AND retry — rides the NEW
        stream, not a unary detour."""
        args, _ = args16
        h = _Harness()
        try:
            rs = h.client(stream=True)
            ref = h.client(stream=False).pack(*args, n_max=16)
            rs.pack(*args, n_max=16)
            assert wait_until(lambda: rs._stream is not None and rs._stream.up)
            uploads_before = rs.session_uploads
            established_before = rs._stream.established_count
            h.restart()  # fresh service: empty session store, same address
            # wait for the RE-establishment, not the stale pre-break "up"
            # (the client may not have noticed the kill yet)
            assert wait_until(
                lambda: rs._stream.established_count > established_before
                and rs._stream.up,
                timeout=20.0,
            )
            out = rs.pack(*args, n_max=16)
            assert_results_equal(out, ref)
            # the re-open happened (fresh store answered NEEDS_CATALOG)...
            assert rs.session_uploads > uploads_before
            # ...and it rode the stream: the NEW server's stream handler
            # saw an MSG_OPEN
            box = h.server.stream_server_box[0]
            assert box is not None and box.snapshot()["stream_opens"] >= 1
        finally:
            h.stop()

    def test_credit_exhaustion_typed_and_readmits(self, args16):
        """Window empty → OverloadedError(kind='credits') at the SENDER,
        with the server's hint; once a result returns the credit, the
        next solve is admitted again."""
        args, _ = args16
        gate = threading.Event()

        class Gated(SolverService):
            def solve_stream_group(self, entries):
                gate.wait(timeout=20.0)
                super().solve_stream_group(entries)

        h = _Harness(
            service=Gated(max_inflight=1, queue_depth=0,
                          overload_retry_after=0.05),
        )
        try:
            rs = h.client(stream=True)
            gate.set()
            rs.pack(*args, n_max=16)  # warm + establish (window = 1)
            assert wait_until(lambda: rs._stream is not None and rs._stream.up)
            gate.clear()
            blocked = rs.pack_begin(*args, n_max=16)  # holds the 1 credit
            with pytest.raises(OverloadedError) as ei:
                rs.pack_begin(*args, n_max=16)
            assert ei.value.kind == "credits"
            assert ei.value.retry_after == pytest.approx(0.05)
            assert rs._stream.credit_stalls >= 1
            gate.set()
            blocked()  # completes; credit returns
            assert wait_until(lambda: rs._stream.credits_available() >= 1)
            rs.pack(*args, n_max=16)  # re-admitted
        finally:
            gate.set()
            h.stop()

    def test_credit_exhaustion_soft_backoff_in_pool(self, args16):
        """The pool consumes a credit stall exactly like an admission
        refusal: soft backoff (typed OverloadedError upward), ZERO
        breaker state touched, member re-admitted after the hint."""
        from karpenter_tpu.solver.pool import SolverPool

        args, _ = args16
        gate = threading.Event()

        class Gated(SolverService):
            def solve_stream_group(self, entries):
                gate.wait(timeout=20.0)
                super().solve_stream_group(entries)

        h = _Harness(
            service=Gated(max_inflight=1, queue_depth=0,
                          overload_retry_after=0.05),
        )
        pool = SolverPool(
            [h.address], timeout=10.0,
            client_factory=lambda addr: h.client(stream=True),
        )
        try:
            gate.set()
            pool.pack(*args, n_max=16)  # warm
            member = h.clients[-1]
            assert wait_until(lambda: member._stream is not None and member._stream.up)
            gate.clear()
            blocked = pool.pack_begin(*args, n_max=16)
            with pytest.raises(OverloadedError):
                pool.pack_begin(*args, n_max=16)
            # backpressure, not failure: the real breaker never moved
            assert pool._breaker(h.address).available()
            assert pool.failovers == 0
            assert pool.overload_skips >= 1
            gate.set()
            blocked()
            assert wait_until(
                lambda: member._stream.credits_available() >= 1
            )
            # sit out the hint window, then the member re-admits
            time.sleep(0.06)
            pool.pack(*args, n_max=16)
        finally:
            gate.set()
            pool.close()
            h.stop()

    def test_corrupt_streamed_response_quarantines(self, args16):
        """A corrupted streamed response is a typed IntegrityError at the
        client (frame checksum), and the pool QUARANTINES the member —
        trip, not a windowed failure."""
        from karpenter_tpu.resilience.integrity import IntegrityError
        from karpenter_tpu.solver.pool import PoolExhausted, SolverPool

        args, _ = args16
        corrupt = {"on": False}

        class Corrupting(SolverService):
            def solve_stream_group(self, entries):
                if corrupt["on"]:
                    for e in entries:
                        orig = e.respond

                        def bad(b, _o=orig):
                            flipped = bytearray(b)
                            flipped[len(flipped) // 2] ^= 0x10
                            _o(bytes(flipped))

                        e.respond = bad
                super().solve_stream_group(entries)

        h = _Harness(service=Corrupting())
        pool = SolverPool(
            [h.address], timeout=10.0,
            client_factory=lambda addr: h.client(stream=True),
        )
        try:
            pool.pack(*args, n_max=16)  # warm + establish + negotiate
            member = h.clients[-1]
            assert wait_until(lambda: member._stream is not None and member._stream.up)
            corrupt["on"] = True
            with pytest.raises((PoolExhausted, IntegrityError)):
                pool.pack(*args, n_max=16)
            # quarantined: the member's breaker is OPEN right now
            assert not pool._breaker(h.address).available()
        finally:
            pool.close()
            h.stop()

    def test_corrupt_streamed_request_answers_integrity(self, args16):
        """Server side of the same contract: a streamed solve frame whose
        checksum disagrees answers STATUS_INTEGRITY — never a solve
        against garbage."""
        args, _ = args16
        h = _Harness()
        try:
            rs = h.client(stream=True)
            rs.pack(*args, n_max=16)
            assert wait_until(lambda: rs._stream is not None and rs._stream.up)
            key = catalog_session_key(*args[N_POD_ARRAYS:])
            frame = append_checksum(pack_arrays(
                [_key_array(key), np.asarray([16, 1], np.int32)]
                + list(args[:N_POD_ARRAYS])
            ))
            bad = bytearray(frame)
            bad[len(bad) // 2] ^= 0x04
            fut = rs._stream.solve(bytes(bad))
            response = fut.result(timeout=10.0)
            status = int(unpack_arrays(response)[0].reshape(-1)[0])
            assert status == STATUS_INTEGRITY
        finally:
            h.stop()


class TestInterop:
    def test_new_client_old_server_stays_unary(self, args16):
        """A server that never advertises PROTO_STREAM (an old build)
        keeps a stream-enabled client on the unary path — no stream is
        ever attempted, solves keep working."""
        args, _ = args16
        h = _Harness(
            service=SolverService(features=PROTO_FEATURES & ~PROTO_STREAM)
        )
        try:
            ref = h.client(stream=False).pack(*args, n_max=16)
            rs = h.client(stream=True)
            out = rs.pack(*args, n_max=16)
            out2 = rs.pack(*args, n_max=16)
            assert_results_equal(out, ref)
            assert_results_equal(out2, ref)
            assert rs._stream is None  # never even constructed
        finally:
            h.stop()

    def test_old_client_new_server_unary_untouched(self, args16):
        """An old client (stream disabled — the pre-stream build) against
        a new server: pure unary, byte-identical protocol, and the
        server's stream machinery is never built."""
        args, _ = args16
        h = _Harness()
        try:
            rs = h.client(stream=False)
            out = rs.pack(*args, n_max=16)
            assert out is not None
            assert h.server.stream_server_box[0] is None
        finally:
            h.stop()


class TestShmFastPath:
    def test_shm_solves_and_frees(self, args16, tmp_path):
        args, _ = args16
        shm = str(tmp_path)
        h = _Harness(shm_dir=shm)
        try:
            ref = h.client(stream=False).pack(*args, n_max=16)
            rs = h.client(stream=True, shm_dir=shm)
            rs.pack(*args, n_max=16)
            assert wait_until(
                lambda: rs._stream is not None and rs._stream.shm_active
            )
            prof = {}
            out = rs.pack_begin(*args, n_max=16, prof=prof)()
            assert prof["solver_transport"] == "stream_shm"
            assert_results_equal(out, ref)
            # the arena block was freed on completion
            assert rs._stream._arena.live_blocks() == 0
            box = h.server.stream_server_box[0]
            assert box.snapshot()["shm_solves"] >= 1
        finally:
            h.stop()

    def test_server_without_shm_declines_arena(self, args16, tmp_path):
        args, _ = args16
        h = _Harness()  # no shm_dir server-side
        try:
            rs = h.client(stream=True, shm_dir=str(tmp_path))
            rs.pack(*args, n_max=16)
            assert wait_until(lambda: rs._stream is not None and rs._stream.up)
            prof = {}
            rs.pack_begin(*args, n_max=16, prof=prof)()
            # declined arena → inline stream frames, still streamed
            assert prof["solver_transport"] == "stream"
            assert not rs._stream.shm_active
        finally:
            h.stop()


class TestDecisionParity:
    def test_coalesced_entries_yield_identical_decision_records(
        self, monkeypatch
    ):
        """Decision-observability parity (docs/decisions.md): a coalesced
        multi-solve dispatch must yield per-entry decision records — and
        per-pod elimination attribution — BIT-IDENTICAL to solo solves.
        Attribution is a pure function of (encoded batch, assignment), so
        this holds exactly as long as the coalesced assignment stays
        bit-exact; the test pins both links of that chain."""
        from karpenter_tpu.cloudprovider.fake import instance_types
        from karpenter_tpu.cloudprovider.requirements import catalog_requirements
        from karpenter_tpu.kube.client import Cluster
        from karpenter_tpu.obs import decisions as dec
        from karpenter_tpu.scheduling.ffd import daemon_overhead, sort_pods_ffd
        from karpenter_tpu.scheduling.topology import Topology
        from karpenter_tpu.solver import encode as enc
        from karpenter_tpu.solver import explain as expl
        from karpenter_tpu.solver import kernel
        from karpenter_tpu.testing import diverse_pods, make_provisioner
        from karpenter_tpu.testing.factories import make_pod

        monkeypatch.setenv("KARPENTER_PACKER", "scan")
        dec.set_enabled(True)
        catalog = sorted(
            instance_types(8), key=lambda it: it.effective_price()
        )
        constraints = make_provisioner(solver="tpu").spec.constraints
        constraints.requirements = constraints.requirements.merge(
            catalog_requirements(catalog)
        )
        pods = diverse_pods(5, random.Random(3))
        pods.append(make_pod(name="stuck-x", requests={"cpu": "100000"}))
        pods = sort_pods_ffd(pods)
        cluster = Cluster()
        Topology(cluster, rng=random.Random(1)).inject(constraints, pods)
        batch = enc.encode(
            constraints, catalog, pods, daemon_overhead(cluster, constraints)
        )
        args = [np.asarray(a) for a in batch.pack_args()]
        p = len(batch.pod_valid)
        r = batch.pod_req.shape[1]

        service = SolverService()
        key = catalog_session_key(*args[N_POD_ARRAYS:])
        resp = service.open_session_bytes(
            pack_arrays([_key_array(key)] + list(args[N_POD_ARRAYS:]))
        )
        assert int(unpack_arrays(resp)[0].reshape(-1)[0]) == STATUS_OK
        solo_frame = service.solve_bytes(
            pack_arrays(
                [_key_array(key), np.asarray([16, 1], np.int32)]
                + list(args[:N_POD_ARRAYS])
            )
        )
        solo_buf = unpack_arrays(solo_frame)[1]
        solo = kernel.split_result(np.asarray(solo_buf), p, 16, r)
        solo_assignment = np.asarray(solo.assignment)[: batch.n_pods].copy()
        assert (solo_assignment < 0).any(), "scenario needs a stuck pod"

        responses = []
        entries = [
            service.stream_parse_solve(
                pack_arrays(
                    [_key_array(key), np.asarray([16, 1], np.int32)]
                    + list(args[:N_POD_ARRAYS])
                ),
                respond=responses.append,
            )
            for _ in range(3)
        ]
        service.solve_stream_group(entries)
        assert len(responses) == 3

        def record_of(assignment):
            # a fixed clock and no packing nodes: everything left in the
            # record is a pure function of (batch, assignment)
            log = dec.DecisionLog(clock=lambda: 0.0)
            rec = log.record_round(
                "parity", batch.pods[: batch.n_pods], [],
                context={
                    "batch": batch,
                    "assignment": assignment,
                    "n_max": 16,
                    "route": "device",
                },
                trace_id="t",
            )
            return {
                k: rec[k]
                for k in (
                    "pods_considered", "unschedulable_count",
                    "unschedulable", "route",
                )
            }

        solo_record = record_of(solo_assignment)
        solo_verdicts = expl.explain_batch(batch, solo_assignment)
        assert solo_verdicts, "attribution must cover the stuck pod"
        for resp_frame in responses:
            arrays = unpack_arrays(resp_frame)
            assert int(arrays[0].reshape(-1)[0]) == STATUS_OK
            coal = kernel.split_result(np.asarray(arrays[1]), p, 16, r)
            coal_assignment = np.asarray(coal.assignment)[: batch.n_pods].copy()
            np.testing.assert_array_equal(solo_assignment, coal_assignment)
            assert record_of(coal_assignment) == solo_record
            assert expl.explain_batch(batch, coal_assignment) == solo_verdicts


class TestCoalescing:
    def test_coalesced_group_dispatch_bit_exact(self, args16, monkeypatch):
        """Deterministic unit-level proof: a multi-entry group through
        ``solve_stream_group`` takes ONE coalesced (vmapped) dispatch and
        every demuxed response is bit-exact with the unary solve."""
        monkeypatch.setenv("KARPENTER_PACKER", "scan")
        args, _ = args16
        service = SolverService()
        key = catalog_session_key(*args[N_POD_ARRAYS:])
        resp = service.open_session_bytes(
            pack_arrays([_key_array(key)] + list(args[N_POD_ARRAYS:]))
        )
        assert int(unpack_arrays(resp)[0].reshape(-1)[0]) == STATUS_OK
        ref_frame = service.solve_bytes(
            pack_arrays(
                [_key_array(key), np.asarray([16, 1], np.int32)]
                + list(args[:N_POD_ARRAYS])
            )
        )
        ref_buf = unpack_arrays(ref_frame)[1]
        responses = []
        entries = [
            service.stream_parse_solve(
                pack_arrays(
                    [_key_array(key), np.asarray([16, 1], np.int32)]
                    + list(args[:N_POD_ARRAYS])
                ),
                respond=responses.append,
            )
            for _ in range(3)
        ]
        before = dict(service.stream_stats)
        service.solve_stream_group(entries)
        assert len(responses) == 3
        for r in responses:
            arrays = unpack_arrays(r)
            assert int(arrays[0].reshape(-1)[0]) == STATUS_OK
            np.testing.assert_array_equal(arrays[1], ref_buf)
        assert (
            service.stream_stats["coalesced_dispatches"]
            == before["coalesced_dispatches"] + 1
        )
        assert (
            service.stream_stats["coalesced_solves"]
            == before["coalesced_solves"] + 3
        )

    def test_concurrent_same_shape_solves_coalesce_bit_exact(
        self, args16, monkeypatch
    ):
        # pin the scan kernel: coalescing only engages on a DEVICE route
        # (on the CPU rig pack_best would route native, where a vmapped
        # dispatch amortizes nothing), and scan is the same kernel family
        # the real device runs — the bit-exactness claim under test
        monkeypatch.setenv("KARPENTER_PACKER", "scan")
        args, _ = args16
        h = _Harness(coalesce_window_s=0.25)
        try:
            ref = h.client(stream=False).pack(*args, n_max=16)
            clients = [h.client(stream=True) for _ in range(2)]
            for c in clients:
                c.pack(*args, n_max=16)  # warm + establish both streams
                assert wait_until(lambda c=c: c._stream is not None and c._stream.up)
            svc = h.server.solver_service
            before = dict(svc.stream_stats)

            # group formation is timing-dependent (entries must land
            # inside one collection window); fire salvos until one
            # coalesces — bounded, and every result must stay bit-exact
            for _ in range(10):
                waits, errs = [], []

                def fire(c):
                    try:
                        waits.append(c.pack_begin(*args, n_max=16))
                    except Exception as e:  # pragma: no cover - diagnostic
                        errs.append(e)

                threads = [
                    threading.Thread(target=fire, args=(clients[i % 2],))
                    for i in range(4)
                ]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join(timeout=20.0)
                assert not errs and len(waits) == 4
                for w in waits:
                    assert_results_equal(w(), ref)  # coalesced stays bit-exact
                if (
                    svc.stream_stats["coalesced_dispatches"]
                    > before["coalesced_dispatches"]
                ):
                    break
            after = svc.stream_stats
            assert after["coalesced_dispatches"] > before["coalesced_dispatches"]
            assert after["coalesced_solves"] - before["coalesced_solves"] >= 2
        finally:
            h.stop()


class TestStreamPathParity:
    """The PR-4 store-maintenance contracts the stream path must keep:
    steady-state streams send no unary traffic, so the TTL sweep and the
    HBM-pressure OpenSession gate must ride the stream too."""

    def test_ttl_sweep_rides_streamed_solves(self):
        clock = [0.0]
        service = SolverService(session_ttl=5.0, clock=lambda: clock[0])
        args_a, _ = encoded_args(n_types=8, seed=3)
        args_b, _ = encoded_args(n_types=6, seed=9)
        key_a = catalog_session_key(*args_a[N_POD_ARRAYS:])
        key_b = catalog_session_key(*args_b[N_POD_ARRAYS:])
        assert key_a != key_b
        for args, key in ((args_a, key_a), (args_b, key_b)):
            resp = service.open_session_bytes(
                pack_arrays([_key_array(key)] + list(args[N_POD_ARRAYS:]))
            )
            assert int(unpack_arrays(resp)[0].reshape(-1)[0]) == STATUS_OK
        assert service.session_count() == 2
        clock[0] = 10.0  # past session A and B's TTL
        responses = []
        entry = service.stream_parse_solve(
            pack_arrays(
                [_key_array(key_b), np.asarray([16, 1], np.int32)]
                + list(args_b[:N_POD_ARRAYS])
            ),
            respond=responses.append,
        )
        assert not isinstance(entry, bytes)
        service.solve_stream_group([entry])
        assert responses
        assert int(unpack_arrays(responses[0])[0].reshape(-1)[0]) == STATUS_OK
        # B was touched by its own solve; stale A's HBM was released by
        # the sweep riding the STREAM path
        assert service.session_count() == 1

    def test_hbm_gate_refuses_streamed_open(self, args16, monkeypatch):
        args, _ = args16
        from karpenter_tpu.solver import service as svc_mod

        monkeypatch.setattr(
            svc_mod, "publish_device_headroom", lambda: 1024
        )
        h = _Harness(
            service=SolverService(hbm_floor_bytes=1 << 30),
        )
        try:
            rs = h.client(stream=True, checksum=False)
            # force the stream up without an open: drive the raw client
            assert rs._stream_for(PROTO_FEATURES) is not None
            key = catalog_session_key(*args[N_POD_ARRAYS:])
            frame = pack_arrays(
                [_key_array(key)] + list(args[N_POD_ARRAYS:])
            )
            response = rs._stream.open(frame).result(timeout=10.0)
            status = int(unpack_arrays(response)[0].reshape(-1)[0])
            assert status == STATUS_OVERLOADED
        finally:
            h.stop()


# ---------------------------------------------------------------------------
# flight-recorder wire-dominance watch rule
# ---------------------------------------------------------------------------


class TestWireDominanceWatchRule:
    def _solve_tree(self, wire_s: float, sidecar_s: float):
        from karpenter_tpu import obs

        tracer = obs.tracer()
        with tracer.span("solver.solve") as root:
            with tracer.span("solver.wire") as w:
                time.sleep(wire_s)
                w.add_child_record("sidecar.solve", sidecar_s)
                w.add_child_record("sidecar.fetch", sidecar_s / 2)
        return root

    def test_wire_dominated_solve_self_reports(self, tmp_path):
        from karpenter_tpu.obs.flight import FlightRecorder

        rec = FlightRecorder(str(tmp_path), budget_s=10.0)  # never on budget
        root = self._solve_tree(wire_s=0.03, sidecar_s=0.001)
        rec(root)
        records = rec.recent()
        assert records, "wire-dominated solve must flight-record"
        assert records[0]["wire_dominated"] is True
        assert records[0]["wire_self_s"] > records[0]["solve_share_s"]

    def test_solve_dominated_solve_stays_quiet(self, tmp_path):
        from karpenter_tpu.obs.flight import FlightRecorder

        rec = FlightRecorder(str(tmp_path), budget_s=10.0)
        root = self._solve_tree(wire_s=0.006, sidecar_s=0.2)
        rec(root)
        assert rec.recent() == []

    def test_in_process_solve_never_fires(self, tmp_path):
        from karpenter_tpu import obs
        from karpenter_tpu.obs.flight import FlightRecorder

        rec = FlightRecorder(str(tmp_path), budget_s=10.0)
        tracer = obs.tracer()
        with tracer.span("solver.solve") as root:
            with tracer.span("solve.pack_fetch"):
                time.sleep(0.01)
        rec(root)
        assert rec.recent() == []
