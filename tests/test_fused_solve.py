"""Fused single-dispatch solve parity (solver/fused.py): the compact i16
upload + device gather + bit-packed typemask must reproduce exactly what the
unfused path computes — the lax.scan PackResult plus decode's host-side
surviving-type matrix. Runs on CPU (kernel="scan"); the chip runs the same
wrapper with kernel="pallas"."""

import random

import numpy as np
import pytest


def encoded_batch(n_pods, seed=42, n_types=50):
    from karpenter_tpu.cloudprovider.fake import instance_types
    from karpenter_tpu.cloudprovider.requirements import catalog_requirements
    from karpenter_tpu.kube.client import Cluster
    from karpenter_tpu.scheduling.ffd import daemon_overhead, sort_pods_ffd
    from karpenter_tpu.scheduling.topology import Topology
    from karpenter_tpu.solver import encode as enc
    from karpenter_tpu.testing import diverse_pods, make_provisioner

    catalog = sorted(instance_types(n_types), key=lambda it: it.effective_price())
    provisioner = make_provisioner(solver="tpu")
    c = provisioner.spec.constraints
    c.requirements = c.requirements.merge(catalog_requirements(catalog))
    pods = sort_pods_ffd(diverse_pods(n_pods, random.Random(seed)))
    cc = c.clone()
    topo = Topology(Cluster(), rng=random.Random(1))
    plan = topo.inject_plan(cc, pods)
    daemon = daemon_overhead(Cluster(), cc)
    return enc.encode(cc, catalog, pods, daemon, plan=plan)


@pytest.mark.parametrize("n_pods,n_max,seed", [(60, 64, 1), (300, 128, 2), (900, 256, 3)])
def test_fused_matches_unfused(n_pods, n_max, seed):
    import jax

    from karpenter_tpu.solver import fused
    from karpenter_tpu.solver import kernel as K

    batch = encoded_batch(n_pods, seed=seed)
    assert fused.ids_fit(batch)

    # unfused reference: scan kernel + host typemask
    ref = K.pack(*batch.pack_args(), n_max=n_max)
    ref = K.PackResult(*(np.asarray(a) for a in ref))
    mask_arr = batch.type_mask_matrix()
    fits = np.all(
        ref.node_req[:, None, :] <= batch.usable[None, :, :], axis=-1
    )
    ref_mask = (
        mask_arr[np.clip(ref.node_sig, 0, None)]
        & fits
        & (ref.node_sig >= 0)[:, None]
    )

    # fused: compact upload, one dispatch, one buffer
    pod_tab, open_by_core, bhh = fused.pack_pod_table(batch)
    assert pod_tab.dtype == np.int16 and pod_tab.shape[0] == 4
    uniq = batch.uniq_req
    # the compact upload must be materially smaller than what the unfused
    # path ships per solve (the seven per-pod arrays)
    per_pod_bytes = sum(np.asarray(a).nbytes for a in batch.pack_args()[:7])
    assert pod_tab.nbytes + open_by_core.nbytes + uniq.nbytes < per_pod_bytes
    buf = jax.device_get(
        fused.fused_solve(
            pod_tab, open_by_core, bhh, uniq,
            batch.join_table.astype(np.int32),
            batch.frontiers.astype(np.float32),
            batch.daemon.astype(np.float32),
            mask_arr.astype(bool),
            batch.usable.astype(np.float32),
            n_max=n_max, kernel="scan",
        )
    )
    got, got_mask = fused.split_fused(
        buf, len(batch.pod_valid), n_max, batch.usable.shape[1], batch.usable.shape[0]
    )

    np.testing.assert_array_equal(np.asarray(got.assignment), ref.assignment)
    np.testing.assert_array_equal(np.asarray(got.node_sig), ref.node_sig)
    np.testing.assert_array_equal(np.asarray(got.node_host), ref.node_host)
    np.testing.assert_array_equal(np.asarray(got.node_req), ref.node_req)
    assert int(got.n_nodes) == int(ref.n_nodes)
    np.testing.assert_array_equal(got_mask, ref_mask)


def test_device_invariants_cache_hits_by_content():
    from karpenter_tpu.solver import fused

    b1 = encoded_batch(60, seed=1)
    b2 = encoded_batch(60, seed=1)
    cache = fused.DeviceInvariants()
    a = cache.get(b1)
    b = cache.get(b2)  # same content, different objects -> same device arrays
    assert all(x is y for x, y in zip(a, b))
    assert len(cache._cache) == 1


def test_backend_solve_uses_fused_typemask_on_scan(monkeypatch):
    """Drive TpuScheduler.solve end-to-end with the fused path forced on
    (scan kernel, CPU) and assert assignment parity with the FFD oracle."""
    from karpenter_tpu.cloudprovider.fake import instance_types
    from karpenter_tpu.cloudprovider.requirements import catalog_requirements
    from karpenter_tpu.kube.client import Cluster
    from karpenter_tpu.scheduling.ffd import FFDScheduler
    from karpenter_tpu.solver import backend as bk
    from karpenter_tpu.testing import diverse_pods, make_provisioner

    monkeypatch.setattr(
        bk.TpuScheduler, "_fused_route", lambda self, batch, n_max: "v1"
    )
    catalog = instance_types(50)
    provisioner = make_provisioner(solver="tpu")
    c = provisioner.spec.constraints
    c.requirements = c.requirements.merge(catalog_requirements(catalog))
    pods = diverse_pods(300, random.Random(7))

    tpu_nodes = bk.TpuScheduler(Cluster(), rng=random.Random(1)).solve(
        c.clone(), catalog, list(pods)
    )
    ffd_nodes = FFDScheduler(Cluster(), rng=random.Random(1)).solve(
        c.clone(), catalog, list(pods)
    )
    assert len(tpu_nodes) == len(ffd_nodes)
    tpu_sets = sorted(sorted(p.key for p in n.pods) for n in tpu_nodes)
    ffd_sets = sorted(sorted(p.key for p in n.pods) for n in ffd_nodes)
    assert tpu_sets == ffd_sets
    # surviving-type options agree too (fused typemask vs FFD narrowing)
    tpu_opts = {
        tuple(sorted(p.key for p in n.pods)): sorted(t.name for t in n.instance_type_options)
        for n in tpu_nodes
    }
    ffd_opts = {
        tuple(sorted(p.key for p in n.pods)): sorted(t.name for t in n.instance_type_options)
        for n in ffd_nodes
    }
    assert tpu_opts == ffd_opts


class TestFusedRoute:
    """Shape routing of the fused single-dispatch path: v1 within the
    unroll budget, v2 for diverse F>1 batches whose tables fit VMEM, None
    otherwise (unfused ladder)."""

    def _batch_and_sched(self, n_types, k_labels, n_pods=512):
        from karpenter_tpu.cloudprovider.fake import instance_types_tradeoff
        from karpenter_tpu.cloudprovider.requirements import catalog_requirements
        from karpenter_tpu.kube.client import Cluster
        from karpenter_tpu.scheduling.ffd import daemon_overhead, sort_pods_ffd
        from karpenter_tpu.scheduling.topology import Topology
        from karpenter_tpu.solver import backend as bk
        from karpenter_tpu.solver import encode as enc
        from karpenter_tpu.testing import make_pod, make_provisioner

        catalog = sorted(
            instance_types_tradeoff(n_types), key=lambda it: it.effective_price()
        )
        prov = make_provisioner(solver="tpu")
        c = prov.spec.constraints
        c.requirements = c.requirements.merge(catalog_requirements(catalog))
        pods = sort_pods_ffd([
            make_pod(requests={"cpu": "0.5"}, node_selector={"team": f"t{i % k_labels}"})
            for i in range(n_pods)
        ])
        cc = c.clone()
        plan = Topology(Cluster(), rng=random.Random(1)).inject_plan(cc, pods)
        batch = enc.encode(cc, catalog, pods, daemon_overhead(Cluster(), cc), plan=plan)
        return batch, bk.TpuScheduler(Cluster())

    def test_diverse_f_gt1_routes_v2_when_pallas_available(self, monkeypatch):
        from karpenter_tpu.solver import backend as bk

        batch, sched = self._batch_and_sched(n_types=16, k_labels=64)
        S, F = batch.frontiers.shape[0], batch.frontiers.shape[1]
        assert S * F > 1024  # past the v1 unroll budget
        import karpenter_tpu.solver.pallas_kernel as pk

        monkeypatch.setattr(pk, "pallas_available", lambda: True)
        assert sched._fused_route(batch, 256) == "v2"
        # and on a CPU-only backend the same shape takes the unfused ladder
        monkeypatch.setattr(pk, "pallas_available", lambda: False)
        assert sched._fused_route(batch, 256) is None

    def test_vmem_overflow_falls_off_the_v2_route(self, monkeypatch):
        import karpenter_tpu.solver.pallas_kernel as pk

        batch, sched = self._batch_and_sched(n_types=16, k_labels=64)
        monkeypatch.setattr(pk, "pallas_available", lambda: True)
        assert sched._fused_route(batch, 256) == "v2"
        # a node table too large for the VMEM budget disables ONLY this
        # n_max, without memoizing a failure
        assert sched._fused_route(batch, 1 << 14) is None
        assert sched._fused_route(batch, 256) == "v2"
