"""Fleet telemetry plane (karpenter_tpu/obs/collector.py + profiler.py):
mergeable histogram aggregation, cross-process trace stitching with clock
rebase, the file/HTTP collection backends, the stdlib sampling profiler
with span attribution, the /debug/profile + /debug/fleet endpoints, and
the satellite wiring (?trace_id= exact lookup, flight-panel containment
metric, bench_compare gating of the new keys)."""

import json
import math
import os
import random
import socket
import subprocess
import sys
import threading
import time
import urllib.request

import pytest

from karpenter_tpu import metrics, obs
from karpenter_tpu.obs import collector as tc
from karpenter_tpu.obs.slo import (
    FAST_SLICES,
    GROWTH,
    Histogram,
    SlidingWindow,
    SloEngine,
)
from karpenter_tpu.obs.trace import Span


@pytest.fixture(autouse=True)
def _fresh_obs():
    obs.reset_for_tests()
    yield
    obs.reset_for_tests()


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _span_dict(
    name,
    trace_id,
    span_id,
    parent_id=None,
    t0=0.0,
    dur_ms=10.0,
    wall=1754300000.0,
    attrs=None,
    children=None,
):
    return {
        "name": name,
        "trace_id": trace_id,
        "span_id": span_id,
        "parent_id": parent_id,
        "t0": t0,
        "t1": t0 + dur_ms / 1e3,
        "duration_ms": dur_ms,
        "wall_start": wall,
        "attrs": attrs or {},
        "error": None,
        "children": children or [],
    }


# ---------------------------------------------------------------------------
# histogram merge: the property the fleet aggregation rests on
# ---------------------------------------------------------------------------


class TestHistogramMerge:
    def test_merge_equals_combined_stream_sketch(self):
        """merge(snap_a, snap_b) must agree with the sketch built over the
        CONCATENATED stream exactly — same fixed bucket geometry, merge is
        per-bucket addition, nothing is re-binned."""
        rng = random.Random(7)
        a_vals = [rng.lognormvariate(-3.0, 1.0) for _ in range(2000)]
        b_vals = [rng.lognormvariate(-2.0, 0.7) for _ in range(3000)]
        ha, hb, hc = Histogram(), Histogram(), Histogram()
        for v in a_vals:
            ha.observe(v)
            hc.observe(v)
        for v in b_vals:
            hb.observe(v)
            hc.observe(v)
        merged = Histogram().merge(ha.snapshot()).merge(hb.snapshot())
        assert merged.counts == hc.counts
        assert merged.total() == len(a_vals) + len(b_vals)
        for q in (0.5, 0.9, 0.95, 0.99):
            assert merged.quantile(q) == hc.quantile(q)
        assert merged.mean() == hc.mean()

    def test_merged_quantiles_track_exact_within_growth_error(self):
        """Against the exact sort of the combined stream the merged sketch
        is bounded by the bucket scheme: a value sits within sqrt(GROWTH)
        of its bucket's geometric midpoint (~2.5%); allow rank-edge slack
        on top."""
        rng = random.Random(11)
        a_vals = [rng.lognormvariate(-3.0, 0.8) for _ in range(4000)]
        b_vals = [rng.lognormvariate(-2.5, 0.8) for _ in range(4000)]
        ha, hb = Histogram(), Histogram()
        for v in a_vals:
            ha.observe(v)
        for v in b_vals:
            hb.observe(v)
        merged = Histogram().merge(ha).merge(hb)
        exact = sorted(a_vals + b_vals)
        bucket_err = math.sqrt(GROWTH) - 1  # ~2.47%
        for q in (0.5, 0.9, 0.99):
            truth = exact[min(int(q * len(exact)), len(exact) - 1)]
            got = merged.quantile(q)
            assert abs(got - truth) / truth < bucket_err + 0.02, (q, got, truth)

    def test_merge_accepts_json_string_keys(self):
        h = Histogram()
        h.observe(0.05)
        snap = json.loads(json.dumps(h.snapshot()))
        assert all(isinstance(k, str) for k in snap["counts"])
        merged = Histogram().merge(snap)
        assert merged.counts == h.counts

    def test_window_expiry_by_index_interacts_with_merge(self):
        """Member windows age by INDEX against their clocks: events recorded
        before the window horizon must be absent from the snapshot a merge
        consumes — a silent member's stale load can't haunt fleet p99."""
        clock = {"now": 0.0}
        sw = SlidingWindow(
            slice_s=1.0, fast_slices=FAST_SLICES, total_slices=60,
            clock=lambda: clock["now"],
        )
        for _ in range(50):
            sw.record(10.0, None, bad=True)  # ancient, terrible latencies
        # silence ages the 5-slice fast window out entirely while the
        # 60-slice slow window still reaches back to the old load
        clock["now"] = 30.0
        for _ in range(20):
            sw.record(0.01, None, bad=False)
        fast = Histogram.from_window(sw.merged(fast=True))
        assert fast.events() == 20
        assert fast.bad == 0
        merged = Histogram().merge(fast.snapshot())
        assert merged.quantile(0.99) < 0.05  # the 10s horrors expired
        # the slow window still remembers them (60 slices deep)
        slow = Histogram.from_window(sw.merged(fast=False))
        assert slow.events() == 70


# ---------------------------------------------------------------------------
# the stitcher
# ---------------------------------------------------------------------------


def _controller_tree(trace_id="ab" * 16, wall=1754300000.0):
    graft = [
        _span_dict("sidecar.solve", trace_id, "g1" + "0" * 14,
                   "wire" + "0" * 12, t0=100.05, dur_ms=50.0, wall=wall + 0.05),
        _span_dict("sidecar.fetch", trace_id, "g2" + "0" * 14,
                   "wire" + "0" * 12, t0=100.10, dur_ms=20.0, wall=wall + 0.10),
    ]
    return _span_dict(
        "solver.solve", trace_id, "root" + "0" * 12, None,
        t0=100.0, dur_ms=200.0, wall=wall,
        children=[
            _span_dict("solve.pack_begin", trace_id, "pb" + "0" * 14,
                       "root" + "0" * 12, t0=100.0, dur_ms=10.0, wall=wall),
            _span_dict("solver.wire", trace_id, "wire" + "0" * 12,
                       "root" + "0" * 12, t0=100.01, dur_ms=180.0,
                       wall=wall + 0.01, children=graft),
        ],
    )


def _sidecar_tree(trace_id="ab" * 16, wall=1754300000.0, base=5000.0):
    # a DIFFERENT perf_counter base: cross-process clocks never agree
    return _span_dict(
        "sidecar.pack", trace_id, "sc" + "0" * 14, "pb" + "0" * 14,
        t0=base, dur_ms=100.0, wall=wall + 0.04,
        attrs={"session": "abc", "admission_wait_s": 0.012},
        children=[
            _span_dict("sidecar.solve", trace_id, "ss" + "0" * 14,
                       "sc" + "0" * 14, t0=base, dur_ms=50.0, wall=wall + 0.04),
            _span_dict("sidecar.fetch", trace_id, "sf" + "0" * 14,
                       "sc" + "0" * 14, t0=base + 0.05, dur_ms=20.0,
                       wall=wall + 0.09),
        ],
    )


class TestStitcher:
    def test_sidecar_pack_joins_under_overlapping_wire(self):
        roots, joins = tc.stitch([_controller_tree(), _sidecar_tree()])
        assert joins == 1 and len(roots) == 1
        wire = roots[0]["children"][1]
        assert wire["name"] == "solver.wire"
        kids = [c["name"] for c in wire["children"]]
        # the grafted childless stage records are REPLACED by the real
        # subtree — nothing double-counts in critical_path
        assert kids == ["sidecar.pack"]
        pack = wire["children"][0]
        assert pack["stitched"] is True
        assert pack["trace_id"] == roots[0]["trace_id"]

    def test_rebase_is_monotonic_consistent(self):
        roots, _ = tc.stitch([_controller_tree(), _sidecar_tree()])
        wire = roots[0]["children"][1]
        pack = wire["children"][0]
        assert wire["t0"] <= pack["t0"] <= pack["t1"] <= wire["t1"]
        for child in pack["children"]:
            assert wire["t0"] <= child["t0"] <= child["t1"] <= wire["t1"]
        # measured duration survives the rebase
        assert pack["duration_ms"] == 100.0

    def test_missing_anchor_stays_standalone_root(self):
        lonely = _sidecar_tree(trace_id="cd" * 16)
        roots, joins = tc.stitch([_controller_tree(), lonely])
        assert joins == 0
        assert len(roots) == 2
        names = sorted(r["name"] for r in roots)
        assert names == ["sidecar.pack", "solver.solve"]

    def test_anchor_fallback_without_wall_overlap(self):
        # the sidecar work wall-lands an hour away from any wire span:
        # attach at the ANCHOR (dispatch-time span), never a wrong wire
        side = _sidecar_tree(wall=1754303600.0)
        roots, joins = tc.stitch([_controller_tree(), side])
        assert joins == 1
        pb = roots[0]["children"][0]
        assert pb["name"] == "solve.pack_begin"
        assert [c["name"] for c in pb["children"]] == ["sidecar.pack"]

    def test_inputs_not_mutated(self):
        ctree, stree = _controller_tree(), _sidecar_tree()
        before = json.dumps([ctree, stree], sort_keys=True)
        tc.stitch([ctree, stree])
        assert json.dumps([ctree, stree], sort_keys=True) == before

    def test_wire_attribution_splits_wire_queue_device(self):
        roots, _ = tc.stitch([_controller_tree(), _sidecar_tree()])
        attr = tc.wire_attribution(roots[0])
        assert attr["stitched"] is True
        assert attr["device_ms"] == pytest.approx(70.0)
        assert attr["sidecar_queue_ms"] == pytest.approx(12.0)
        # wire envelope minus sidecar share, all positive, shares add up
        assert attr["wire_ms"] > 0
        assert 0 < attr["wire_share_pct"] < 100

    def test_wire_attribution_none_without_wire(self):
        t = _span_dict("solver.solve", "ef" * 16, "r" * 16)
        assert tc.wire_attribution(t) is None


# ---------------------------------------------------------------------------
# fleet SLO aggregation
# ---------------------------------------------------------------------------


def _feed_engine(engine: SloEngine, name: str, values, threshold: float):
    for i, v in enumerate(values):
        sp = Span(name, "ab" * 16, f"{i:016d}"[:16], None, None)
        sp.start = 0.0
        sp.end = v
        engine(sp)


class TestFleetSloAggregation:
    def test_fleet_merged_p99_within_5pct_of_exact(self):
        rng = random.Random(3)
        a_vals = [abs(rng.gauss(0.03, 0.01)) + 1e-4 for _ in range(600)]
        b_vals = [abs(rng.gauss(0.06, 0.02)) + 1e-4 for _ in range(400)]
        eng_a = SloEngine(objectives=("solve.p99 < 100ms",), window_s=300)
        eng_b = SloEngine(objectives=("solve.p99 < 100ms",), window_s=300)
        _feed_engine(eng_a, "solver.solve", a_vals, 0.1)
        _feed_engine(eng_b, "solver.solve", b_vals, 0.1)
        merged = tc.merge_objective_snapshots({
            "replica-a": eng_a.histogram_snapshot(),
            "replica-b": eng_b.histogram_snapshot(),
        })
        got = merged["solve_p99"]["value"]
        exact = sorted(a_vals + b_vals)
        truth = exact[min(int(0.99 * len(exact)), len(exact) - 1)]
        assert abs(got - truth) / truth < 0.05, (got, truth)
        assert merged["solve_p99"]["members"] == ["replica-a", "replica-b"]
        assert merged["solve_p99"]["events"]["fast"] == 1000

    def test_disjoint_objective_sets_merge_by_name(self):
        # controller and sidecar report DIFFERENT objective sets; each
        # merges over whoever carries it
        ctrl = SloEngine(objectives=("solve.p99 < 100ms",), window_s=300)
        side = SloEngine(objectives=("sidecar.pack.p99 < 100ms",), window_s=300)
        _feed_engine(ctrl, "solver.solve", [0.01] * 20, 0.1)
        _feed_engine(side, "sidecar.pack", [0.02] * 20, 0.1)
        merged = tc.merge_objective_snapshots({
            "c": ctrl.histogram_snapshot(), "s": side.histogram_snapshot(),
        })
        assert merged["solve_p99"]["members"] == ["c"]
        assert merged["sidecar_pack_p99"]["members"] == ["s"]
        assert merged["solve_p99"]["ok"] is True

    def test_fleet_burn_rate_over_threshold_events(self):
        eng = SloEngine(objectives=("solve.p99 < 100ms",), window_s=300)
        # half the events breach a p99 objective: burn rate far above 1
        _feed_engine(eng, "solver.solve", [0.01] * 25 + [0.5] * 25, 0.1)
        merged = tc.merge_objective_snapshots({"m": eng.histogram_snapshot()})
        assert merged["solve_p99"]["burn_rate"]["fast"] > 1.0
        assert merged["solve_p99"]["ok"] is False


# ---------------------------------------------------------------------------
# backends + collector
# ---------------------------------------------------------------------------


def _member(identity, role="controller", flushed_at=None, trees=(), slo=None):
    return {
        "version": tc.PAYLOAD_VERSION,
        "identity": identity,
        "role": role,
        "flushed_at": time.time() if flushed_at is None else flushed_at,
        "traces": list(trees),
        "slo": slo or {},
        "profile": {},
    }


class TestFileBackend:
    def test_publish_then_poll_round_trip(self, tmp_path):
        a = tc.FileTelemetryBackend(str(tmp_path), identity="a")
        b = tc.FileTelemetryBackend(str(tmp_path), identity="b")
        a.publish(_member("a", trees=[_controller_tree()]))
        b.publish(_member("b", role="sidecar", trees=[_sidecar_tree()]))
        docs = {d["identity"]: d for d in a.poll()}
        assert set(docs) == {"a", "b"}
        assert docs["b"]["role"] == "sidecar"
        # republish replaces the member file whole, no accumulation
        a.publish(_member("a", trees=[]))
        docs = {d["identity"]: d for d in b.poll()}
        assert docs["a"]["traces"] == []
        assert len(list(tmp_path.glob("member-*.json"))) == 2

    def test_flush_ships_the_newest_ring_trees(self):
        # a full ring must flush the LATEST solves, not traffic from 192
        # solves ago — the limit slices from the newest end
        for i in range(tc.FLUSH_TREE_LIMIT + 10):
            with obs.tracer().span("solver.solve") as sp:
                last = sp.trace_id
                if i == 0:
                    first = sp.trace_id
        payload = tc.member_payload("me", "controller")
        ids = {t["trace_id"] for t in payload["traces"]}
        assert len(payload["traces"]) == tc.FLUSH_TREE_LIMIT
        assert last in ids
        assert first not in ids

    def test_corrupt_member_file_skipped(self, tmp_path):
        backend = tc.FileTelemetryBackend(str(tmp_path), identity="a")
        backend.publish(_member("a"))
        (tmp_path / "member-zzz.json").write_text("{torn")
        assert [d["identity"] for d in backend.poll()] == ["a"]


class TestCollector:
    def test_member_inventory_with_staleness(self, tmp_path):
        clock = {"now": 1000.0}
        backend = tc.FileTelemetryBackend(str(tmp_path), identity="x")
        backend.publish(_member("fresh", flushed_at=995.0))
        backend.publish(_member("quiet", flushed_at=900.0))
        coll = tc.TelemetryCollector(
            [backend], flush_interval=10.0, clock=lambda: clock["now"],
        )
        coll.refresh()
        members = {m["identity"]: m for m in coll.members()}
        assert members["fresh"]["stale"] is False
        assert members["quiet"]["stale"] is True  # > 3x flush interval
        assert members["quiet"]["age_s"] == pytest.approx(100.0)

    def test_fleet_payload_stitches_and_counts_new_joins_once(self, tmp_path):
        backend = tc.FileTelemetryBackend(str(tmp_path), identity="x")
        backend.publish(_member("ctrl", trees=[_controller_tree()]))
        backend.publish(
            _member("side", role="sidecar", trees=[_sidecar_tree()])
        )
        coll = tc.TelemetryCollector([backend], flush_interval=10.0)
        coll.refresh()
        before = metrics.TELEMETRY_STITCHED._value.get()
        payload = coll.fleet_payload()
        assert metrics.TELEMETRY_STITCHED._value.get() == before + 1
        # the same flushed tree re-polled is NOT a new stitch
        coll.refresh()
        coll.fleet_payload()
        assert metrics.TELEMETRY_STITCHED._value.get() == before + 1
        assert payload["traces"]["stitched"] == 1
        idx = payload["traces"]["index"][0]
        assert idx["stitched"] is True
        assert idx["members"] == ["ctrl", "side"]
        worst = payload["worst_stitched"]
        assert worst["wire"]["stitched"] is True
        legs = [leg["name"] for leg in worst["critical_path"]]
        assert "sidecar.pack" in legs

    def test_http_pull_mode_scrapes_debug_endpoints(self):
        """The pull backend assembles a member payload from a live health
        server's EXISTING /debug endpoints — the no-shared-volume mode."""
        pytest.importorskip("grpc")
        from karpenter_tpu.solver.service import SolverService, _serve_health

        obs.configure_slo(objectives=("solve.p99 < 100ms",))
        obs.configure_profiler(hz=50)
        with obs.tracer().span("solver.solve"):
            pass
        port = free_port()
        httpd = _serve_health(SolverService(), port)
        try:
            backend = tc.HttpTelemetryBackend(
                [f"peer-1=http://127.0.0.1:{port}"]
            )
            docs = backend.poll()
            assert len(docs) == 1
            doc = docs[0]
            assert doc["identity"] == "peer-1"
            assert any(
                t["name"] == "solver.solve" for t in doc["traces"]
            )
            assert "objectives" in doc["slo"]
            # an unreachable peer contributes nothing, poll survives
            dead = tc.HttpTelemetryBackend(
                [f"http://127.0.0.1:{free_port()}"], timeout=0.2
            )
            assert dead.poll() == []
        finally:
            httpd.shutdown()


# ---------------------------------------------------------------------------
# the sampling profiler
# ---------------------------------------------------------------------------


class TestProfiler:
    def _parked_thread(self):
        release = threading.Event()
        parked = threading.Event()

        def parked_here_for_profiler():
            parked.set()
            release.wait(5.0)

        t = threading.Thread(target=parked_here_for_profiler, daemon=True)
        t.start()
        parked.wait(5.0)
        return t, release

    def test_sample_once_folds_parked_stack(self):
        prof = obs.SamplingProfiler(hz=50, tracer=obs.tracer())
        t, release = self._parked_thread()
        try:
            n = prof.sample_once()
            assert n >= 1
            assert any(
                "parked_here_for_profiler" in stack for stack in prof._folds
            )
            collapsed = prof.collapsed()
            for line in collapsed.splitlines():
                stack, _, count = line.rpartition(" ")
                assert stack and int(count) >= 1
        finally:
            release.set()
            t.join()

    def test_samples_attributed_to_active_span(self):
        prof = obs.SamplingProfiler(hz=50, tracer=obs.tracer())
        entered = threading.Event()
        release = threading.Event()

        def in_span():
            with obs.tracer().span("prof.target"):
                entered.set()
                release.wait(5.0)

        t = threading.Thread(target=in_span, daemon=True)
        t.start()
        entered.wait(5.0)
        try:
            prof.sample_once()
            prof.sample_once()
            assert prof.snapshot()["span_samples"].get("prof.target", 0) >= 2
        finally:
            release.set()
            t.join()

    def test_top_reports_leaf_self_time(self):
        prof = obs.SamplingProfiler(hz=50, tracer=obs.tracer())
        t, release = self._parked_thread()
        try:
            prof.sample_once()
            frames = [row["frame"] for row in prof.top(50)]
            # the leaf is the wait, not our helper — self time, not
            # containment
            assert any("wait" in f for f in frames)
        finally:
            release.set()
            t.join()

    def test_fold_storage_bounded(self):
        prof = obs.SamplingProfiler(hz=50, max_folds=2)
        prof._bump_locked(prof._folds, "a")
        prof._bump_locked(prof._folds, "b")
        prof._bump_locked(prof._folds, "c")
        prof._bump_locked(prof._folds, "d")
        assert set(prof._folds) == {"a", "b", "<other>"}
        assert prof._folds["<other>"] == 2

    def test_flight_record_carries_profile_panel(self, tmp_path):
        rec = obs.configure_flight(str(tmp_path), budget_s=0.0)
        obs.configure_profiler(hz=50)
        t, release = TestProfiler._parked_thread(self)
        try:
            obs.profiler().sample_once()
        finally:
            release.set()
            t.join()
        with obs.tracer().span("solver.solve"):
            pass
        panel = rec.recent()[0]["state"]["profile"]
        assert panel["window_samples"] >= 1
        assert panel["top_folds"]

    def test_debug_profile_payload_shapes(self):
        ctype, body = obs.debug_profile_payload("")
        assert ctype == "application/json"
        assert json.loads(body)["profile"]["enabled"] is False
        prof = obs.configure_profiler(hz=50)
        t, release = self._parked_thread()
        try:
            prof.sample_once()
        finally:
            release.set()
            t.join()
        ctype, body = obs.debug_profile_payload("")
        doc = json.loads(body)["profile"]
        assert doc["enabled"] is True and doc["samples"] >= 1
        ctype, body = obs.debug_profile_payload("format=collapsed")
        assert ctype == "text/plain"
        assert b"parked_here_for_profiler" in body

    def test_daemon_loop_overhead_self_accounted(self):
        prof = obs.configure_profiler(hz=97)
        time.sleep(0.3)
        snap = prof.snapshot()
        assert snap["samples"] > 0
        # generous CI bound; the bench gate pins the real <1% bar
        assert snap["overhead_ratio"] < 0.10
        assert metrics.TELEMETRY_PROFILE_OVERHEAD._value.get() == pytest.approx(
            prof.overhead_ratio(), abs=0.05
        )


# ---------------------------------------------------------------------------
# satellites: flight panel containment metric, ?trace_id= lookup
# ---------------------------------------------------------------------------


class TestFlightPanelErrors:
    def test_raising_panel_counts_and_never_loses_tree(self, tmp_path):
        rec = obs.configure_flight(str(tmp_path), budget_s=0.0)
        obs.register_state("broken", lambda: 1 / 0)
        obs.register_state("fine", lambda: {"ok": 1})
        before = metrics.FLIGHT_PANEL_ERRORS.labels(panel="broken")._value.get()
        with obs.tracer().span("solver.solve"):
            pass
        record = rec.recent()[0]
        # containment: the span tree AND the healthy panel both landed
        assert record["trace"]["name"] == "solver.solve"
        assert record["state"]["fine"] == {"ok": 1}
        assert "state provider failed" in record["state"]["broken"]
        after = metrics.FLIGHT_PANEL_ERRORS.labels(panel="broken")._value.get()
        assert after == before + 1


class TestTraceIdLookup:
    def test_exact_lookup_via_shared_helper(self):
        with obs.tracer().span("solver.solve") as sp:
            wanted = sp.trace_id
        with obs.tracer().span("solver.solve"):
            pass
        payload = obs.debug_traces_payload(f"trace_id={wanted}")
        assert len(payload["traces"]) == 1
        assert payload["traces"][0]["trace_id"] == wanted
        assert obs.debug_traces_payload("trace_id=" + "0" * 32)["traces"] == []

    def test_lookup_over_sidecar_health_http(self):
        pytest.importorskip("grpc")
        from karpenter_tpu.solver.service import SolverService, _serve_health

        with obs.tracer().span("sidecar.pack") as sp:
            wanted = sp.trace_id
        with obs.tracer().span("sidecar.pack"):
            pass
        port = free_port()
        httpd = _serve_health(SolverService(), port)
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/traces?trace_id={wanted}",
                timeout=5,
            ) as resp:
                assert resp.headers.get("Content-Type") == "application/json"
                doc = json.loads(resp.read())
            assert [t["trace_id"] for t in doc["traces"]] == [wanted]
            # the new endpoints answer on the same server
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/fleet", timeout=5
            ) as resp:
                assert json.loads(resp.read()) == {"fleet": {}}
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/profile", timeout=5
            ) as resp:
                assert resp.headers.get("Content-Type") == "application/json"
                assert json.loads(resp.read())["profile"]["enabled"] is False
            # the dual-typed endpoint's header follows the helper — the
            # controller/sidecar parity holds for content type too
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/profile?format=collapsed",
                timeout=5,
            ) as resp:
                assert resp.headers.get("Content-Type") == "text/plain"
        finally:
            httpd.shutdown()


# ---------------------------------------------------------------------------
# the acceptance path: live gRPC solve -> stitched tree -> /debug/fleet
# ---------------------------------------------------------------------------


def encoded_args(n_types: int = 8, n_pods: int = 6, seed: int = 3):
    """A real encoded batch's ``pack_args`` tuple + its n_max (the
    test_solver_service harness)."""
    from karpenter_tpu.cloudprovider.fake import instance_types
    from karpenter_tpu.cloudprovider.requirements import catalog_requirements
    from karpenter_tpu.kube.client import Cluster
    from karpenter_tpu.scheduling.ffd import daemon_overhead, sort_pods_ffd
    from karpenter_tpu.scheduling.topology import Topology
    from karpenter_tpu.solver import encode as enc
    from karpenter_tpu.testing import diverse_pods, make_provisioner

    catalog = sorted(instance_types(n_types), key=lambda it: it.effective_price())
    constraints = make_provisioner(solver="tpu").spec.constraints
    constraints.requirements = constraints.requirements.merge(
        catalog_requirements(catalog)
    )
    pods = sort_pods_ffd(diverse_pods(n_pods, random.Random(seed)))
    cluster = Cluster()
    Topology(cluster, rng=random.Random(1)).inject(constraints, pods)
    batch = enc.encode(
        constraints, catalog, pods, daemon_overhead(cluster, constraints)
    )
    return batch.pack_args(), len(batch.pod_valid)


class TestLiveStitchAcceptance:
    def test_live_grpc_solve_stitches_pack_under_wire(self):
        """The acceptance bar: a live controller+sidecar solve (real gRPC,
        the test_solver_service harness) must stitch the sidecar's REAL
        sidecar.pack tree in as a child of the controller's solver.wire —
        same trace id, monotonic-consistent bounds — replacing the
        wire-trailer grafts."""
        pytest.importorskip("grpc")
        from karpenter_tpu.solver.service import RemoteSolver, serve

        address = f"127.0.0.1:{free_port()}"
        server = serve(address)
        try:
            client = RemoteSolver(address, timeout=30)
            args, _p = encoded_args()
            with obs.tracer().span("solver.solve") as root_sp:
                result = client.pack(*args, n_max=8)
            assert int(result.n_nodes) >= 1
            roots, joins = tc.stitch(obs.exporter().trees())
            assert joins >= 1
            solve = next(r for r in roots if r["name"] == "solver.solve")
            assert solve["trace_id"] == root_sp.trace_id
            wires = [
                s for s in tc._walk(solve) if s["name"] == "solver.wire"
            ]
            assert wires
            packs = [
                c for c in wires[0]["children"] if c["name"] == "sidecar.pack"
            ]
            assert packs, [c["name"] for c in wires[0]["children"]]
            pack = packs[0]
            assert pack["stitched"] is True
            assert pack["trace_id"] == solve["trace_id"]
            w = wires[0]
            assert w["t0"] <= pack["t0"] <= pack["t1"] <= w["t1"]
            # the admission-queue attribute rode the wire
            assert "admission_wait_s" in pack["attrs"]
            # real children, not trailer grafts
            kid_names = {c["name"] for c in pack["children"]}
            assert {"sidecar.solve", "sidecar.fetch"} <= kid_names
            attr = tc.wire_attribution(solve)
            assert attr["stitched"] is True
            assert attr["wire_share_pct"] is not None
        finally:
            server.stop(grace=0)

    def test_fleet_endpoint_merges_members_p99_within_5pct(self, tmp_path):
        """/debug/fleet over a shared dir: this process's engine flushes
        through the plane, a second member publishes its own snapshot, and
        the fleet-merged solve.p99 tracks the offline exact quantile of
        the COMBINED stream within the 5% bar."""
        rng = random.Random(9)
        mine = [abs(rng.gauss(0.02, 0.008)) + 1e-4 for _ in range(500)]
        theirs = [abs(rng.gauss(0.05, 0.02)) + 1e-4 for _ in range(500)]
        eng = obs.configure_slo(objectives=("solve.p99 < 100ms",))
        _feed_engine(eng, "solver.solve", mine, 0.1)
        plane = obs.configure_telemetry(
            identity="replica-self", role="controller",
            directory=str(tmp_path), flush_interval=60.0,
        )
        plane.flush()
        other_eng = SloEngine(objectives=("solve.p99 < 100ms",))
        _feed_engine(other_eng, "solver.solve", theirs, 0.1)
        tc.FileTelemetryBackend(str(tmp_path), identity="replica-b").publish(
            _member("replica-b", slo=other_eng.histogram_snapshot())
        )
        payload = obs.debug_fleet_payload()["fleet"]
        members = {m["identity"] for m in payload["members"]}
        assert {"replica-self", "replica-b"} <= members
        got = payload["slo"]["solve_p99"]["value"]
        exact = sorted(mine + theirs)
        truth = exact[min(int(0.99 * len(exact)), len(exact) - 1)]
        assert abs(got - truth) / truth < 0.05, (got, truth)
        assert metrics.TELEMETRY_FLUSHES._value.get() >= 1

    def test_two_process_stitch_over_file_backend(self, tmp_path):
        """A REAL second process: the sidecar runs `python -m
        karpenter_tpu.solver.service --telemetry-dir ...`, flushes its own
        ring, and the collector stitches its sidecar.pack (a genuinely
        foreign perf_counter base) into this process's solver.wire."""
        grpc = pytest.importorskip("grpc")
        from karpenter_tpu.solver.service import RemoteSolver

        address = f"127.0.0.1:{free_port()}"
        proc = subprocess.Popen(
            [
                sys.executable, "-m", "karpenter_tpu.solver.service",
                "--address", address, "--health-port", "0",
                "--telemetry-dir", str(tmp_path),
                "--telemetry-flush-interval", "1",
                "--profile-hz", "7",
            ],
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )
        try:
            # wait for the LISTENING state first: hammering a not-yet-bound
            # port walks the channel into reconnect backoff and the pack
            # then fails fast for minutes
            grpc.channel_ready_future(
                grpc.insecure_channel(address)
            ).result(timeout=120)
            client = RemoteSolver(address, timeout=180, cold_timeout=300)
            args, _p = encoded_args()
            with obs.tracer().span("solver.solve"):
                result = client.pack(*args, n_max=8)
            assert int(result.n_nodes) >= 1
            backend = tc.FileTelemetryBackend(str(tmp_path), identity="ctrl")
            coll = tc.TelemetryCollector(
                [backend], flush_interval=1.0,
                extra_trees=lambda: obs.exporter().snapshot(
                    limit=None, newest_first=False
                ),
            )
            packs = []
            deadline = time.time() + 30
            while time.time() < deadline and not packs:
                coll.refresh()
                roots, _ = coll.stitched()
                for root in roots:
                    if root["name"] != "solver.solve":
                        continue
                    for s in tc._walk(root):
                        if s["name"] == "sidecar.pack" and s.get("stitched"):
                            packs.append((root, s))
                time.sleep(1.0)
            assert packs, "sidecar flush never stitched"
            root, pack = packs[0]
            wire = next(
                s for s in tc._walk(root)
                if s["name"] == "solver.wire"
                and any(c is pack for c in s["children"])
            )
            assert wire["t0"] <= pack["t0"] <= pack["t1"] <= wire["t1"]
        finally:
            proc.terminate()
            proc.wait(timeout=20)


# ---------------------------------------------------------------------------
# packaging: bench gate keys, chart flags, CI wiring
# ---------------------------------------------------------------------------


class TestPackaging:
    def test_bench_compare_gates_new_keys(self):
        from tools.bench_compare import HEADLINE_KEYS, compare

        for key in ("fleet_critical_path_ms", "wire_share_pct",
                    "profiler_overhead_pct"):
            assert HEADLINE_KEYS[key] == -1
        rows = {
            r["key"]: r
            for r in compare(
                {"fleet_critical_path_ms": 100.0, "profiler_overhead_pct": 0.2},
                {"fleet_critical_path_ms": 150.0, "profiler_overhead_pct": 0.1},
            )
        }
        assert rows["fleet_critical_path_ms"]["verdict"] == "regressed"
        assert rows["profiler_overhead_pct"]["verdict"] == "improved"
        # pre-telemetry rounds lack the keys: reported, never fatal
        assert rows["wire_share_pct"]["verdict"] == "missing_new"

    def test_chart_renders_profiler_and_telemetry_flags(self):
        out = subprocess.run(
            [sys.executable, "hack/render_chart.py", "charts/karpenter-tpu"],
            capture_output=True, text=True, check=True,
        ).stdout
        assert "--profile-hz=19" in out
        assert "--telemetry-peers=solver-0=" in out

    def test_ci_and_make_carry_the_overhead_gate(self):
        with open("Makefile") as f:
            assert "profile-smoke" in f.read()
        with open(".github/workflows/ci.yaml") as f:
            assert "--profile-overhead-check" in f.read()
