"""Termination tests (mirrors termination/suite_test.go): cordon/drain/evict
ordering, do-not-evict, PDB 429 handling, static pods."""

import pytest

from karpenter_tpu.api import labels as lbl
from karpenter_tpu.api.objects import OwnerReference, Toleration
from karpenter_tpu.cloudprovider.fake import FakeCloudProvider, instance_types
from karpenter_tpu.controllers.termination import TerminationController, is_stuck_terminating
from karpenter_tpu.kube.client import Cluster
from tests.factories import make_node, make_pdb, make_pod, make_provisioner


@pytest.fixture()
def env():
    now = [1000.0]
    cluster = Cluster(clock=lambda: now[0])
    provider = FakeCloudProvider(instance_types(5))
    controller = TerminationController(cluster, provider, start_queue=False)
    return cluster, provider, controller, now


def deleting_node(cluster, **kw):
    kw.setdefault("provisioner_name", "default")
    kw.setdefault("finalizers", [lbl.TERMINATION_FINALIZER])
    node = make_node(**kw)
    cluster.create("nodes", node)
    cluster.delete("nodes", node.metadata.name, namespace="")
    return node


def drain_queue(controller):
    """Run queued evictions synchronously (queue thread not started)."""
    q = controller.eviction_queue
    while len(q.queue):
        key = q.queue.get(timeout=0.1)
        if key is None:
            break
        if not q.process_one(key):
            return False
    return True


class TestTermination:
    def test_empty_node_terminated_and_instance_deleted(self, env):
        cluster, provider, controller, _ = env
        node = deleting_node(cluster)
        assert controller.reconcile(node.metadata.name) is None
        assert cluster.try_get("nodes", node.metadata.name, namespace="") is None
        assert node.metadata.name in provider.delete_calls

    def test_node_cordoned_before_drain(self, env):
        cluster, provider, controller, _ = env
        node = deleting_node(cluster)
        cluster.create("pods", make_pod(node_name=node.metadata.name, unschedulable=False))
        requeue = controller.reconcile(node.metadata.name)
        assert node.spec.unschedulable
        assert requeue == controller.DRAIN_REQUEUE  # not drained yet

    def test_drain_evicts_then_terminates(self, env):
        cluster, provider, controller, _ = env
        node = deleting_node(cluster)
        pod = make_pod(node_name=node.metadata.name, unschedulable=False)
        cluster.create("pods", pod)
        controller.reconcile(node.metadata.name)
        assert drain_queue(controller)  # eviction deletes the pod
        assert cluster.try_get("pods", pod.metadata.name) is None
        assert controller.reconcile(node.metadata.name) is None
        assert cluster.try_get("nodes", node.metadata.name, namespace="") is None

    def test_do_not_evict_blocks_drain(self, env):
        cluster, provider, controller, _ = env
        node = deleting_node(cluster)
        pod = make_pod(node_name=node.metadata.name, unschedulable=False)
        pod.metadata.annotations[lbl.DO_NOT_EVICT_ANNOTATION] = "true"
        cluster.create("pods", pod)
        assert controller.reconcile(node.metadata.name) == controller.DRAIN_REQUEUE
        assert cluster.try_get("pods", pod.metadata.name) is not None
        assert cluster.try_get("nodes", node.metadata.name, namespace="") is not None

    def test_critical_pods_evicted_last(self, env):
        cluster, provider, controller, _ = env
        node = deleting_node(cluster)
        normal = make_pod(node_name=node.metadata.name, unschedulable=False)
        critical = make_pod(node_name=node.metadata.name, unschedulable=False)
        critical.spec.priority_class_name = "system-node-critical"
        cluster.create("pods", normal)
        cluster.create("pods", critical)
        controller.reconcile(node.metadata.name)
        drain_queue(controller)
        # first round only evicts the non-critical pod
        assert cluster.try_get("pods", normal.metadata.name) is None
        assert cluster.try_get("pods", critical.metadata.name) is not None
        controller.reconcile(node.metadata.name)
        drain_queue(controller)
        assert cluster.try_get("pods", critical.metadata.name) is None

    def test_static_pods_ignored(self, env):
        cluster, provider, controller, _ = env
        node = deleting_node(cluster)
        static = make_pod(node_name=node.metadata.name, unschedulable=False)
        static.metadata.owner_references.append(OwnerReference(api_version="v1", kind="Node", name=node.metadata.name))
        cluster.create("pods", static)
        assert controller.reconcile(node.metadata.name) is None  # drained despite static pod
        assert cluster.try_get("nodes", node.metadata.name, namespace="") is None

    def test_tolerating_unschedulable_pods_ignored(self, env):
        cluster, provider, controller, _ = env
        node = deleting_node(cluster)
        ds_like = make_pod(
            node_name=node.metadata.name,
            unschedulable=False,
            tolerations=[Toleration(operator="Exists")],
        )
        cluster.create("pods", ds_like)
        assert controller.reconcile(node.metadata.name) is None

    def test_pdb_blocks_eviction_with_429(self, env):
        cluster, provider, controller, _ = env
        node = deleting_node(cluster)
        pod = make_pod(node_name=node.metadata.name, unschedulable=False, labels={"app": "db"})
        cluster.create("pods", pod)
        cluster.create("pdbs", make_pdb(labels={"app": "db"}, min_available=1))
        controller.reconcile(node.metadata.name)
        assert not drain_queue(controller)  # blocked → 429 retry path
        assert cluster.try_get("pods", pod.metadata.name) is not None

    def test_node_without_finalizer_ignored(self, env):
        cluster, provider, controller, _ = env
        node = make_node(provisioner_name="default")
        cluster.create("nodes", node)
        cluster.delete("nodes", node.metadata.name, namespace="")
        assert controller.reconcile(node.metadata.name) is None
        assert provider.delete_calls == []

    def test_live_node_ignored(self, env):
        cluster, provider, controller, _ = env
        node = make_node(provisioner_name="default", finalizers=[lbl.TERMINATION_FINALIZER])
        cluster.create("nodes", node)
        assert controller.reconcile(node.metadata.name) is None
        assert not node.spec.unschedulable


class TestStuckTerminating:
    def test_past_grace_window(self):
        pod = make_pod(unschedulable=False)
        assert not is_stuck_terminating(pod, 1000.0)
        pod.metadata.deletion_timestamp = 900.0
        assert not is_stuck_terminating(pod, 920.0)  # within 30s grace
        assert is_stuck_terminating(pod, 931.0)
