"""Measured-cost packer routing (VERDICT r4 weak #3 / r5 ask #1a): `auto`
must route by per-shape measured cost — native as a first-class contender —
never by platform."""

import os
import random

import pytest

from karpenter_tpu.cloudprovider.fake import instance_types
from karpenter_tpu.cloudprovider.requirements import catalog_requirements
from karpenter_tpu.kube.client import Cluster
from karpenter_tpu.scheduling.scheduler import Scheduler
from karpenter_tpu.solver.router import CostRouter
from karpenter_tpu.testing import diverse_pods, make_provisioner


class TestCostRouter:
    def test_cold_start_tries_every_candidate_in_order(self):
        r = CostRouter()
        key = (1024, 5, 1)
        assert r.choose(key, ["device", "native"]) == "device"
        r.record(key, "device", 0.100)
        assert r.choose(key, ["device", "native"]) == "native"
        r.record(key, "native", 0.001)

    def test_exploits_cheapest_after_cold_start(self):
        r = CostRouter()
        key = (1024, 5, 1)
        r.record(key, "device", 0.100)
        r.record(key, "native", 0.001)
        r._solves[key] = 2
        assert all(
            r.choose(key, ["device", "native"]) == "native" for _ in range(10)
        )

    def test_choose_never_sacrifices_a_solve_to_exploration(self):
        # probing is signalled out-of-band (should_probe) and executed off
        # the critical path; choose() itself always exploits
        r = CostRouter(probe_every=4)
        key = (1024, 5, 1)
        r.record(key, "device", 0.100)
        r.record(key, "native", 0.001)
        picks = [r.choose(key, ["device", "native"]) for _ in range(16)]
        assert picks.count("native") == 16

    def test_should_probe_fires_on_cadence(self):
        r = CostRouter(probe_every=4)
        key = (1024, 5, 1)
        r.record(key, "device", 0.100)
        r.record(key, "native", 0.001)
        fires = []
        for _ in range(16):
            r.choose(key, ["device", "native"])
            fires.append(r.should_probe(key))
        assert fires.count(True) == 4  # every 4th solve triggers a probe

    def test_environment_drift_re_wins_the_route(self):
        # the chip gets fast (or the tunnel clears): shadow probes keep the
        # loser's EMA fresh and the route flips back
        r = CostRouter(probe_every=2, alpha=0.5)
        key = (2048, 9, 1)
        r.record(key, "device", 0.500)  # compile-poisoned first sample
        r.record(key, "native", 0.010)
        for _ in range(8):  # probes keep measuring a now-fast device
            r.record(key, "device", 0.001)
        assert r.choose(key, ["device", "native"]) == "device"

    def test_single_candidate_short_circuits(self):
        r = CostRouter()
        assert r.choose((1, 1, 1), ["device"]) == "device"
        assert r.report() == {}  # no bookkeeping spent

    def test_shape_classes_are_independent(self):
        r = CostRouter()
        small, large = (256, 3, 1), (10240, 40, 1)
        r.record(small, "device", 0.001)
        r.record(small, "native", 0.010)
        r.record(large, "device", 0.200)
        r.record(large, "native", 0.002)
        r._solves[small] = r._solves[large] = 2
        assert r.choose(small, ["device", "native"]) == "device"
        assert r.choose(large, ["device", "native"]) == "native"


class TestRoutedScheduler:
    def _solve_n(self, n_solves, n_pods=512):
        catalog = instance_types(50)
        provisioner = make_provisioner(solver="tpu")
        c = provisioner.spec.constraints
        c.requirements = c.requirements.merge(catalog_requirements(catalog))
        pods = diverse_pods(n_pods, random.Random(7))
        scheduler = Scheduler(Cluster(), rng=random.Random(1))
        outs = []
        for _ in range(n_solves):
            nodes = scheduler.solve(provisioner, catalog, pods)
            outs.append(
                (
                    scheduler._tpu.last_profile.get("packer_backend"),
                    sorted(
                        tuple(sorted(p.metadata.name for p in n.pods))
                        for n in nodes
                    ),
                )
            )
        return scheduler, outs

    @pytest.mark.skipif(
        os.environ.get("KARPENTER_PACKER", "auto").lower() != "auto",
        reason="router only runs under auto",
    )
    def test_auto_converges_to_cheaper_backend_with_identical_assignments(self):
        from karpenter_tpu.solver.native import native_available

        if not native_available(wait=180):
            pytest.skip("native packer unavailable")
        scheduler, outs = self._solve_n(4)
        backends = [b for b, _ in outs]
        # packer_backend reports what actually SERVED (on a no-TPU host the
        # routed device ladder itself lands on the native branch), so only
        # the exploitation outcome is asserted on labels; the router report
        # below proves the cold start measured both routes
        assert backends[2] == backends[3] == "native", backends
        # routing is a performance decision only: identical assignments
        assert len({str(a) for _, a in outs}) == 1
        # the router carries a measurement for both backends
        report = scheduler._tpu.router.report()
        assert any(k.startswith("device@") for k in report)
        assert any(k.startswith("native@") for k in report)

    @pytest.mark.skipif(
        os.environ.get("KARPENTER_PACKER", "auto").lower() != "auto",
        reason="router only runs under auto",
    )
    def test_shadow_probe_refreshes_loser_off_critical_path(self):
        from karpenter_tpu.solver.native import native_available

        if not native_available(wait=180):
            pytest.skip("native packer unavailable")
        catalog = instance_types(50)
        provisioner = make_provisioner(solver="tpu")
        c = provisioner.spec.constraints
        c.requirements = c.requirements.merge(catalog_requirements(catalog))
        pods = diverse_pods(512, random.Random(7))
        scheduler = Scheduler(Cluster(), rng=random.Random(1))
        scheduler.solve(provisioner, catalog, pods)  # builds _tpu
        scheduler._tpu.router.probe_every = 2
        first_device = None
        for _ in range(5):
            scheduler.solve(provisioner, catalog, pods)
            report = scheduler._tpu.router.report()
            dev = [v for k, v in report.items() if k.startswith("device@")]
            if first_device is None and dev:
                first_device = dev[0]
        t = scheduler._tpu._probe_thread
        assert t is not None, "device shadow probe never started"
        t.join(timeout=60)
        dev = [
            v for k, v in scheduler._tpu.router.report().items()
            if k.startswith("device@")
        ]
        # the probe recorded: EMA moved off the compile-poisoned cold sample
        assert dev and dev[0] != first_device
        # and the winning path stayed native throughout
        assert scheduler._tpu.last_profile["packer_backend"] == "native"

    @pytest.mark.skipif(
        os.environ.get("KARPENTER_PACKER", "auto").lower() != "auto",
        reason="router only runs under auto",
    )
    def test_broken_native_degrades_to_device_and_loses_route(self, monkeypatch):
        # containment parity with the old pack_best ladder: a broken native
        # lib must degrade to the device path, never crash the reconcile —
        # and must record a PENALTY, not its microsecond failure time
        from karpenter_tpu.solver import native
        from karpenter_tpu.solver.router import FAILURE_PENALTY_S

        if not native.native_available(wait=180):
            pytest.skip("native packer unavailable")
        catalog = instance_types(50)
        provisioner = make_provisioner(solver="tpu")
        c = provisioner.spec.constraints
        c.requirements = c.requirements.merge(catalog_requirements(catalog))
        pods = diverse_pods(512, random.Random(7))
        scheduler = Scheduler(Cluster(), rng=random.Random(1))
        baseline = scheduler.solve(provisioner, catalog, pods)  # device cold

        def broken(*a, **kw):
            raise RuntimeError("libffd_pack.so corrupt")

        monkeypatch.setattr(native, "pack_native", broken)
        nodes = scheduler.solve(provisioner, catalog, pods)  # native cold: fails
        assert sum(len(n.pods) for n in nodes) == sum(len(n.pods) for n in baseline)
        router = scheduler._tpu.router
        key = next(k for (b, k) in router._ema if b == "native")
        assert router.ema(key, "native") == FAILURE_PENALTY_S
        scheduler.solve(provisioner, catalog, pods)
        assert scheduler._tpu.last_profile["packer_backend"] == "device"


class TestNearTie:
    """A close race must not let the runner-up's EMA go stale — but the
    freshness comes from a RAISED SHADOW-PROBE cadence, never from
    sacrificing a production solve (choose() stays exploit-only)."""

    def test_near_tie_raises_probe_cadence_not_route(self):
        r = CostRouter(probe_every=64)
        key = (2048, 9, 1)
        r.record(key, "device", 0.0105)
        r.record(key, "native", 0.0100)  # within the 1.25x near-tie band
        picks, fires = [], 0
        for _ in range(32):
            picks.append(r.choose(key, ["device", "native"]))
            fires += r.should_probe(key)
        assert picks.count("native") == 32  # every solve exploits
        assert fires == 4  # probes every 8th instead of every 64th

    def test_clear_winner_probes_at_base_cadence(self):
        r = CostRouter(probe_every=64)
        key = (2048, 9, 1)
        r.record(key, "device", 0.100)  # 100x apart: not a tie
        r.record(key, "native", 0.001)
        fires = 0
        for _ in range(64):
            r.choose(key, ["device", "native"])
            fires += r.should_probe(key)
        assert fires == 1  # only the base 64-solve cadence

    def test_near_tie_probes_recover_a_stale_winner(self):
        # the drift failure mode: the nominal winner goes stale while the
        # world shifts; the raised probe cadence refreshes the runner-up
        # off the critical path and the route flips
        r = CostRouter(probe_every=64)
        key = (1024, 5, 1)
        r.record(key, "device", 0.010)
        r.record(key, "native", 0.011)  # near-tie, device nominally ahead
        for _ in range(40):
            pick = r.choose(key, ["device", "native"])
            # the world changed: device now takes 3x, native got faster
            r.record(key, pick, 0.030 if pick == "device" else 0.008)
            if r.should_probe(key):
                # the shadow probe measures the loser's CURRENT cost
                loser = "native" if pick == "device" else "device"
                r.record(key, loser, 0.008 if loser == "native" else 0.030)
        assert r.choose(key, ["device", "native"]) == "native"
