"""Serde fuzz round-trips, validated against the shipped CRD schema.

Randomized Provisioner objects (requirements algebra, taints, limits,
kubelet config, provider blocks) must (a) survive to_wire → from_wire →
to_wire byte-identically, and (b) produce wire documents the CRD's
openAPIV3Schema accepts — the same contract a real apiserver enforces at
admission (VERDICT r2 #5: conformance beyond the self-authored double).
The validator is a small structural interpreter of deploy/crd.yaml, so a
schema/serde drift fails here before it fails against a cluster.
"""

import json
import os
import random

import pytest

from karpenter_tpu.api.objects import NodeSelectorRequirement, Taint
from karpenter_tpu.api.provisioner import (
    Constraints,
    KubeletConfiguration,
    Limits,
    Provisioner,
    ProvisionerSpec,
)
from karpenter_tpu.api.requirements import Requirements
from karpenter_tpu.kube import serde

CRD_PATH = os.path.join(os.path.dirname(__file__), "..", "deploy", "crd.yaml")


# -- minimal openAPIV3Schema interpreter ------------------------------------

def _load_crd_schema():
    import yaml

    with open(CRD_PATH) as f:
        doc = yaml.safe_load(f)
    return doc["spec"]["versions"][0]["schema"]["openAPIV3Schema"]


def validate(doc, schema, path="$"):
    """Structural check against the subset of openAPIV3Schema the CRD uses:
    type, properties, items, additionalProperties, enum, minimum, anyOf,
    x-kubernetes-preserve-unknown-fields."""
    errs = []
    if "anyOf" in schema:
        subs = [validate(doc, s, path) for s in schema["anyOf"]]
        if all(subs):
            errs.append(f"{path}: matches no anyOf branch ({subs[0][0]})")
        return errs
    t = schema.get("type")
    if t == "object":
        if not isinstance(doc, dict):
            return [f"{path}: expected object, got {type(doc).__name__}"]
        props = schema.get("properties", {})
        addl = schema.get("additionalProperties")
        preserve = schema.get("x-kubernetes-preserve-unknown-fields")
        for k, v in doc.items():
            if k in props:
                errs += validate(v, props[k], f"{path}.{k}")
            elif addl is not None and isinstance(addl, dict):
                errs += validate(v, addl, f"{path}.{k}")
            elif preserve or addl is True:
                continue
            elif props:
                errs.append(f"{path}.{k}: unknown field")
        for k in schema.get("required", []):
            if k not in doc:
                errs.append(f"{path}.{k}: required")
    elif t == "array":
        if not isinstance(doc, list):
            return [f"{path}: expected array, got {type(doc).__name__}"]
        for i, v in enumerate(doc):
            errs += validate(v, schema.get("items", {}), f"{path}[{i}]")
    elif t == "string":
        if not isinstance(doc, str):
            return [f"{path}: expected string, got {type(doc).__name__}"]
        if "enum" in schema and doc not in schema["enum"]:
            errs.append(f"{path}: {doc!r} not in enum {schema['enum']}")
    elif t == "integer":
        if not isinstance(doc, int) or isinstance(doc, bool):
            return [f"{path}: expected integer, got {type(doc).__name__}"]
        if "minimum" in schema and doc < schema["minimum"]:
            errs.append(f"{path}: {doc} < minimum {schema['minimum']}")
    return errs


# -- fuzz generator ---------------------------------------------------------

KEYS = ["kubernetes.io/arch", "kubernetes.io/os", "topology.kubernetes.io/zone",
        "node.kubernetes.io/instance-type", "karpenter.sh/capacity-type", "team"]
VALUES = ["a", "b", "zone-1", "zone-2", "amd64", "arm64", "linux", "spot", "on-demand"]


def random_provisioner(rng: random.Random) -> Provisioner:
    reqs = [
        NodeSelectorRequirement(
            key=rng.choice(KEYS),
            operator=rng.choice(["In", "NotIn", "Exists"]),
            values=(
                sorted(rng.sample(VALUES, rng.randint(1, 3)))
                if rng.random() < 0.8 else []
            ),
        )
        for _ in range(rng.randint(0, 4))
    ]
    for r in reqs:
        if r.operator == "Exists":
            r.values = []
        elif not r.values:
            r.values = [rng.choice(VALUES)]
    taints = [
        Taint(
            key=f"taint-{rng.randint(0, 3)}",
            value=rng.choice(["", "x", "y"]),
            effect=rng.choice(["NoSchedule", "PreferNoSchedule", "NoExecute"]),
        )
        for _ in range(rng.randint(0, 2))
    ]
    limits = None
    if rng.random() < 0.5:
        limits = Limits(resources={
            "cpu": float(rng.randint(1, 1000)),
            "memory": float(rng.randint(1, 64) * 2**30),
        })
    spec = ProvisionerSpec(
        constraints=Constraints(
            labels={f"l{i}": rng.choice(VALUES) for i in range(rng.randint(0, 2))},
            taints=taints,
            requirements=Requirements.new(*reqs),
            kubelet_configuration=(
                KubeletConfiguration(cluster_dns=["10.0.0.10"])
                if rng.random() < 0.3 else None
            ),
            provider=(
                {"instanceProfile": "x", "tags": {"a": "b"}}
                if rng.random() < 0.4 else None
            ),
        ),
        ttl_seconds_after_empty=rng.choice([None, 0, 30, 600]),
        ttl_seconds_until_expired=rng.choice([None, 60, 2592000]),
        solver=rng.choice(["", "ffd", "tpu"]),
        limits=limits,
    )
    from karpenter_tpu.api.objects import ObjectMeta
    from karpenter_tpu.api.provisioner import Condition, ProvisionerStatus

    status = ProvisionerStatus()
    if rng.random() < 0.5:
        # the Active condition rides the status wire (VERDICT r4 ask #5)
        status.conditions.append(
            Condition(
                type="Active",
                status=rng.choice(["True", "False", "Unknown"]),
                reason=rng.choice(["", "ValidationFailed", "ApplyFailed"]),
                message=rng.choice(["", "bad spec"]),
                last_transition_time=rng.choice([None, 1700000000.0]),
            )
        )
    return Provisioner(
        metadata=ObjectMeta(name=f"fuzz-{rng.randint(0, 10**6)}"),
        spec=spec,
        status=status,
    )


SCHEMA = _load_crd_schema()


def test_crd_schema_loaded_sanely():
    spec_schema = SCHEMA["properties"]["spec"]
    assert spec_schema["type"] == "object"
    assert "requirements" in spec_schema["properties"]
    ops = spec_schema["properties"]["requirements"]["items"]["properties"]["operator"]["enum"]
    assert ops == ["In", "NotIn", "Exists"]


@pytest.mark.parametrize("seed", range(40))
def test_fuzzed_provisioner_round_trip_and_schema(seed):
    rng = random.Random(seed)
    p = random_provisioner(rng)
    wire1 = serde.to_wire("provisioners", p)
    errs = validate(wire1, SCHEMA, "$")
    # apiVersion/kind/metadata are validated apiserver-side (TypeMeta +
    # ObjectMeta), outside the CRD's structural schema
    errs = [
        e for e in errs
        if not e.startswith(("$.metadata", "$.apiVersion", "$.kind"))
    ]
    assert not errs, errs
    back = serde.from_wire("provisioners", wire1)
    wire2 = serde.to_wire("provisioners", back)
    assert wire1 == wire2, "to_wire → from_wire → to_wire must be a fixed point"


# -- v3 solver wire framing --------------------------------------------------
#
# The session transport (solver/service.py) bumped the flat-buffer framing
# to v3: fuzzed arrays must survive pack → unpack bit-identically, session
# frames (key + delta arrays) must round-trip, and EVERY other version word
# must fail loudly — a v2 client against a v3 server (or vice versa) gets
# "unsupported version", never a silent mis-parse.


def _random_arrays(rng: random.Random):
    import numpy as np

    nprng = np.random.default_rng(rng.randrange(2**31))
    arrays = []
    for _ in range(rng.randint(1, 6)):
        ndim = rng.randint(0, 3)
        shape = tuple(rng.randint(0, 5) for _ in range(ndim))
        kind = rng.choice(["bool", "i32", "f32"])
        if kind == "bool":
            arrays.append(nprng.random(shape) < 0.5)
        elif kind == "i32":
            arrays.append(
                nprng.integers(-(2**31), 2**31 - 1, shape).astype(np.int32)
            )
        else:
            arrays.append(nprng.standard_normal(shape).astype(np.float32))
    return arrays


@pytest.mark.parametrize("seed", range(15))
def test_v3_framing_fuzzed_arrays_round_trip(seed):
    import numpy as np

    from karpenter_tpu.solver import service

    arrays = _random_arrays(random.Random(seed))
    out = service.unpack_arrays(service.pack_arrays(arrays))
    assert len(out) == len(arrays)
    for a, b in zip(arrays, out):
        assert np.asarray(a).dtype == b.dtype and np.asarray(a).shape == b.shape
        np.testing.assert_array_equal(a, b)


@pytest.mark.parametrize("seed", range(5))
def test_v3_session_frame_round_trip(seed):
    """A session frame — 16-byte key as i32[4] + delta arrays — survives
    the codec with the key bytes intact."""
    import numpy as np

    from karpenter_tpu.solver import service

    rng = random.Random(seed)
    arrays = _random_arrays(rng)
    key = bytes(rng.randrange(256) for _ in range(16))
    frame = service.pack_arrays([np.frombuffer(key, np.int32)] + arrays)
    key_arr, *rest = service.unpack_arrays(frame)
    assert key_arr.tobytes() == key
    assert len(rest) == len(arrays)


@pytest.mark.parametrize("version", [0, 1, 2, 4, 255, 65535])
def test_v3_version_skew_fails_loudly(version):
    import struct

    import numpy as np

    from karpenter_tpu.solver import service

    frame = bytearray(service.pack_arrays([np.arange(4, dtype=np.int32)]))
    struct.pack_into("<H", frame, 4, version)
    with pytest.raises(ValueError, match=f"unsupported version {version}"):
        service.unpack_arrays(bytes(frame))


def test_v3_catalog_key_content_addressed():
    """Same content → same key; any tensor perturbation → new key (a stale
    session can never serve a drifted catalog)."""
    import numpy as np

    from karpenter_tpu.solver import service

    join = np.arange(6, dtype=np.int32).reshape(2, 3)
    front = np.ones((2, 1, 2), np.float32)
    daemon = np.zeros(2, np.float32)
    k1 = service.catalog_session_key(join, front, daemon)
    k2 = service.catalog_session_key(join.copy(), front.copy(), daemon.copy())
    assert k1 == k2 and len(k1) == 16
    join2 = join.copy()
    join2[0, 0] = 99
    assert service.catalog_session_key(join2, front, daemon) != k1
    # shape perturbation with identical bytes must also miss
    assert service.catalog_session_key(join.reshape(3, 2), front, daemon) != k1


# -- v3 status words (overload control) ---------------------------------------
#
# PR-9 grew the response status vocabulary: OVERLOADED (bounded admission /
# HBM pressure refused the work, payload leads with an f32 retry-after hint)
# and DEADLINE_EXCEEDED (the propagated round budget died before device
# dispatch — non-retryable). The words must survive the codec exactly, a
# status word NEITHER side knows must fail loud (the version-skew contract
# extended to in-band status), and frames without the new trailers must
# parse identically on a new server — old client × new server interop.


@pytest.mark.parametrize("seed", range(10))
def test_v3_status_word_round_trip(seed):
    import numpy as np

    from karpenter_tpu.solver import service

    rng = random.Random(seed)
    status = rng.choice([
        service.STATUS_OK,
        service.STATUS_NEEDS_CATALOG,
        service.STATUS_DEADLINE_EXCEEDED,
        service.STATUS_OVERLOADED,
    ])
    hint = rng.uniform(0.05, 30.0)
    payload = (
        [np.asarray([hint], np.float32)]
        if status == service.STATUS_OVERLOADED else []
    )
    frame = service._status_response(status, payload)
    status_arr, *rest = service.unpack_arrays(frame)
    assert int(status_arr.reshape(-1)[0]) == status
    if status == service.STATUS_OVERLOADED:
        # the retry-after hint the pool's soft breaker honors
        assert rest and float(rest[0][0]) == pytest.approx(hint)


@pytest.mark.parametrize(
    "status,exc_name",
    [(2, "DeadlineExceededError"), (3, "OverloadedError")],
)
def test_v3_shed_statuses_raise_typed_verdicts(status, exc_name):
    """The client maps each shed word to its typed error — typed so the
    pool soft-breaker and the scheduler's FFD floor can tell backpressure
    (retryable elsewhere) from a doomed solve (never retryable)."""
    import numpy as np

    from karpenter_tpu.resilience.overload import (
        DeadlineExceededError,
        OverloadedError,
    )
    from karpenter_tpu.solver import service

    expected = {"DeadlineExceededError": DeadlineExceededError,
                "OverloadedError": OverloadedError}[exc_name]
    solver = service.RemoteSolver.__new__(service.RemoteSolver)
    solver.address = "fuzz:0"
    frame = service._status_response(
        status, [np.asarray([0.25], np.float32)] if status == 3 else []
    )
    word, payload = service.RemoteSolver._split_status(frame)
    with pytest.raises(expected):
        solver._check_status(word, payload)
    if exc_name == "OverloadedError":
        try:
            solver._check_status(word, payload)
        except OverloadedError as e:
            assert e.retry_after == pytest.approx(0.25)


def test_v3_integrity_status_raises_typed_verdict():
    """STATUS_INTEGRITY (the server saw a corrupt request frame) maps to
    the typed IntegrityError — non-retryable on the same member, so the
    pool quarantines the path instead of replaying corrupt transport."""
    from karpenter_tpu.resilience.integrity import IntegrityError
    from karpenter_tpu.solver import service

    solver = service.RemoteSolver.__new__(service.RemoteSolver)
    solver.address = "fuzz:0"
    frame = service._status_response(service.STATUS_INTEGRITY)
    word, payload = service.RemoteSolver._split_status(frame)
    with pytest.raises(IntegrityError) as ei:
        solver._check_status(word, payload)
    assert ei.value.address == "fuzz:0" and ei.value.kind == "checksum"


@pytest.mark.parametrize("status", [6, 17, -1, 2**20])
def test_v3_unknown_status_word_fails_loudly(status):
    """A status word neither side knows is a protocol error, not a retry
    signal — silent tolerance here would be the status-plane version of a
    silent version-skew mis-parse."""
    from karpenter_tpu.solver import service

    solver = service.RemoteSolver.__new__(service.RemoteSolver)
    solver.address = "fuzz:0"
    frame = service._status_response(status)
    word, payload = service.RemoteSolver._split_status(frame)
    with pytest.raises(RuntimeError, match=f"unknown solver status word {status}"):
        solver._check_status(word, payload)


def test_v3_needs_delta_base_word_round_trips_and_is_distinct():
    """STATUS_NEEDS_DELTA_BASE is flow control the dispatch loop consumes
    (rebuild a full DELTA_ESTABLISH and redispatch), not a terminal
    verdict — but on the wire it is a status word like any other and must
    survive the codec exactly and collide with nothing."""
    from karpenter_tpu.solver import service

    words = [
        service.STATUS_OK,
        service.STATUS_NEEDS_CATALOG,
        service.STATUS_DEADLINE_EXCEEDED,
        service.STATUS_OVERLOADED,
        service.STATUS_INTEGRITY,
        service.STATUS_NEEDS_DELTA_BASE,
    ]
    assert len(set(words)) == len(words)
    frame = service._status_response(service.STATUS_NEEDS_DELTA_BASE)
    word, payload = service.RemoteSolver._split_status(frame)
    assert word == service.STATUS_NEEDS_DELTA_BASE
    assert payload == []


@pytest.mark.parametrize("kind", [0, 1, 2])
def test_v3_delta_header_round_trips_and_spans(kind):
    """The i32[10] delta header (kind, n_idx, base_epoch, new_epoch)
    survives pack/unpack bit-exactly and _delta_span consumes exactly the
    arrays its kind declares — a wrong span would misread the trailing
    trace/deadline arrays as pod rows (the v3 framing bug class)."""
    import numpy as np

    from karpenter_tpu.solver import service

    base, new = bytes(range(16)), bytes(range(16, 32))
    n_idx = 3 if kind == service.DELTA_PATCH else 0
    hdr = service.delta_header(kind, n_idx, base, new)
    assert hdr.dtype == np.int32 and hdr.size == service.DELTA_HEADER_WORDS
    n_body = {0: service.N_POD_ARRAYS, 1: 0, 2: 1 + service.N_POD_ARRAYS}[kind]
    body = [np.zeros((n_idx or 2,), np.int32) for _ in range(n_body)]
    key = np.frombuffer(b"\x01" * 16, np.int32)
    vals = np.asarray([4, 0, service.PACK_FLAG_DELTA], np.int64)
    arrays = [np.asarray(a) for a in service.unpack_arrays(
        service.pack_arrays([key, vals, hdr] + body)
    )]
    got = arrays[2]
    assert got.tobytes() == hdr.tobytes()
    assert int(got[0]) == kind and int(got[1]) == n_idx
    assert got[2:6].tobytes() == base and got[6:10].tobytes() == new
    assert service._delta_span(arrays) == 1 + n_body


def test_v3_malformed_delta_header_yields_no_span():
    """A delta-flagged frame whose third array is NOT a well-formed header
    must resolve to span None (→ sealed STATUS_INTEGRITY), never a guess —
    guessing is how a patch idx array masquerades as a trace context."""
    import numpy as np

    from karpenter_tpu.solver import service

    key = np.frombuffer(b"\x02" * 16, np.int32)
    vals = np.asarray([4, 0, service.PACK_FLAG_DELTA], np.int64)
    bad_headers = [
        np.zeros((6,), np.int32),                      # trace-shaped
        np.zeros((service.DELTA_HEADER_WORDS,), np.float32),  # wrong dtype
        service.delta_header(7, 0, b"\x00" * 16, b"\x00" * 16),  # bad kind
    ]
    for hdr in bad_headers:
        arrays = [np.asarray(a) for a in service.unpack_arrays(
            service.pack_arrays([key, vals, hdr])
        )]
        assert service._delta_span(arrays) is None


@pytest.mark.parametrize("seed", range(5))
def test_v3_old_client_frames_parse_without_deadline(seed):
    """Old client × new server: a Pack frame with NO trailing deadline/trace
    arrays (what a pre-PR-9 client sends) must parse to (no trace, no
    deadline) — the server treats it as an unbounded solve, never an error.
    And the deadline trailer itself round-trips by shape+dtype, whatever
    order the trailers arrive in."""
    import numpy as np

    from karpenter_tpu.solver import service

    rng = random.Random(seed)
    ctx_arr = np.frombuffer(
        bytes(rng.randrange(256) for _ in range(24)), np.int32
    )
    remaining = rng.uniform(0.001, 60.0)
    deadline_arr = np.asarray([remaining], np.float32)

    assert service._parse_trailers([]) == (None, None)
    ctx, dl = service._parse_trailers([deadline_arr, ctx_arr])
    assert dl == pytest.approx(remaining, rel=1e-6)
    assert ctx is not None and ctx.trace_id == ctx_arr.tobytes()[:16].hex()
    # new-server tolerance: an unrecognized future trailer shape is ignored
    ctx2, dl2 = service._parse_trailers(
        [np.zeros(3, np.float64), deadline_arr]
    )
    assert ctx2 is None and dl2 == pytest.approx(remaining, rel=1e-6)


# -- wire integrity: random byte-flip corpus ---------------------------------
#
# The corruption-defense contract (docs/integrity.md): over checksummed v3
# frames, EVERY single-byte mutation must either fail loudly at the codec
# (bad magic, version skew, unparseable framing) or be rejected by the
# checksum layer ("mismatch", or "missing" — a peer that negotiated
# checksums treats an absent trailer as rejection, which is what closes the
# count-word hole). No mutation may ever round-trip to a silently different
# array set.


def _frames_equal(a_list, b_list):
    import numpy as np

    if len(a_list) != len(b_list):
        return False
    return all(
        a.dtype == b.dtype and a.shape == b.shape and np.array_equal(a, b)
        for a, b in zip(a_list, b_list)
    )


@pytest.mark.parametrize("seed", range(12))
def test_byte_flip_corpus_never_silently_differs(seed):
    """400 random single-byte mutations per seeded frame: loud, or
    checksum-rejected — never a quiet different parse."""
    from karpenter_tpu.solver import service

    rng = random.Random(seed)
    arrays = _random_arrays(rng)
    frame = service.append_checksum(service.pack_arrays(arrays))
    original = service.unpack_arrays(frame)
    silent = []
    for _ in range(400):
        out = bytearray(frame)
        pos = rng.randrange(len(out))
        bit = 1 << rng.randrange(8)
        out[pos] ^= bit
        mutated = bytes(out)
        try:
            verdict = service.verify_checksum(mutated)
        except Exception:
            continue  # loud at the codec walk — detected
        if verdict != "ok":
            continue  # checksum layer rejected (mismatch/missing) — detected
        try:
            parsed = service.unpack_arrays(mutated)
        except Exception:
            continue  # loud at the full parse — detected
        if not _frames_equal(parsed, original):
            silent.append((pos, bit))
    assert not silent, (
        f"{len(silent)} mutation(s) passed the checksum yet parsed to "
        f"different arrays: {silent[:5]}"
    )


@pytest.mark.parametrize("seed", range(6))
def test_unchecksummed_frames_admit_silent_flips_motivation(seed):
    """The control: WITHOUT the trailer, some payload byte flips round-trip
    to a silently different array — the vulnerability the checksum closes
    (if this ever stops finding one, the corpus has gone degenerate)."""
    import numpy as np

    from karpenter_tpu.solver import service

    rng = random.Random(seed)
    arrays = [np.arange(64, dtype=np.int32)]
    frame = service.pack_arrays(arrays)
    found_silent = False
    for _ in range(64):
        out = bytearray(frame)
        out[rng.randrange(14, len(out))] ^= 1 << rng.randrange(8)  # payload region
        try:
            parsed = service.unpack_arrays(bytes(out))
        except Exception:
            continue
        if not _frames_equal(parsed, arrays):
            found_silent = True
            break
    assert found_silent


def test_checksum_covers_trailers_and_survives_append():
    """append_checksum only rewrites the count word; the digest covers the
    full pre-trailer body including any trace/deadline trailers."""
    import numpy as np

    from karpenter_tpu.solver import service

    base = service.pack_arrays([
        np.frombuffer(bytes(range(16)), np.int32),  # session key
        np.asarray([4, 1, 1], np.int32),            # n_max/record/flags
        np.ones((3, 2), np.float32),                # a pod array
        np.asarray([0.25], np.float32),             # deadline trailer
    ])
    sealed = service.append_checksum(base)
    assert service.verify_checksum(sealed) == "ok"
    # body bytes identical: old parsers see the same arrays + one trailer
    assert sealed[8:8 + len(base) - 8] == base[8:]
    arrays = service.unpack_arrays(sealed)
    assert service.is_checksum_array(arrays[-1])
    # flipping a trailer byte (the deadline f32) is caught
    broken = bytearray(sealed)
    broken[len(base) - 2] ^= 0x40
    assert service.verify_checksum(bytes(broken)) == "mismatch"


# -- stream messages: the byte-flip corpus over enveloped frames -------------
#
# The streaming transport (solver/stream.py) wraps UNCHANGED v3 frames in a
# 20-byte correlation-id envelope. The corpus contract extends: every
# single-byte mutation of an enveloped, checksummed message must be loud at
# the envelope (bad magic / version skew / truncation / CRC), loud at the
# inner codec, or rejected by the inner checksum — never a silently
# different parse, and NEVER a changed correlation id that still routes (a
# routed flip would complete the WRONG future with a checksum-valid
# result — the one silent-corruption hole multiplexing opens).


@pytest.mark.parametrize("seed", range(8))
def test_stream_message_round_trip(seed):
    from karpenter_tpu.solver import service, stream

    rng = random.Random(seed)
    frame = service.append_checksum(service.pack_arrays(_random_arrays(rng)))
    corr = rng.randrange(2**63)
    msg_type = rng.choice(
        [stream.MSG_SOLVE, stream.MSG_OPEN, stream.MSG_RESULT,
         stream.MSG_SOLVE_SHM]
    )
    mt, cid, payload = stream.unpack_stream_msg(
        stream.pack_stream_msg(msg_type, corr, frame)
    )
    assert (mt, cid) == (msg_type, corr)
    assert payload == frame
    assert _frames_equal(
        service.unpack_arrays(payload), service.unpack_arrays(frame)
    )


@pytest.mark.parametrize("seed", range(12))
def test_stream_byte_flip_corpus_never_silently_differs(seed):
    """400 random single-byte mutations per enveloped message: detected at
    the envelope, the codec, or the checksum — never a quiet different
    parse and never a rerouted correlation id."""
    from karpenter_tpu.solver import service, stream

    rng = random.Random(seed)
    arrays = _random_arrays(rng)
    frame = service.append_checksum(service.pack_arrays(arrays))
    corr = rng.randrange(2**63)
    msg = stream.pack_stream_msg(stream.MSG_SOLVE, corr, frame)
    original = service.unpack_arrays(frame)
    silent = []
    for _ in range(400):
        out = bytearray(msg)
        pos = rng.randrange(len(out))
        bit = 1 << rng.randrange(8)
        out[pos] ^= bit
        try:
            msg_type, cid, payload = stream.unpack_stream_msg(bytes(out))
        except Exception:
            continue  # loud at the envelope (magic/version/CRC/truncation)
        if cid != corr or msg_type != stream.MSG_SOLVE:
            silent.append(("routed header flip", pos, bit))
            continue
        try:
            verdict = service.verify_checksum(payload)
        except Exception:
            continue  # loud at the inner codec walk
        if verdict != "ok":
            continue  # inner checksum rejected
        try:
            parsed = service.unpack_arrays(payload)
        except Exception:
            continue
        if not _frames_equal(parsed, original):
            silent.append(("silent parse", pos, bit))
    assert not silent, (
        f"{len(silent)} mutation(s) slipped the stream defenses: {silent[:5]}"
    )


# -- capability word: the PROTO_* bits over the OpenSession payload ----------
#
# Every trailer/transport feature is gated on a capability bit the sidecar
# advertises in its OpenSession response payload (an i32 word old clients
# never read). The fuzz contract: every subset of the advertised bits must
# survive the status-response codec exactly — a dropped or aliased bit
# would make a client engage a trailer its peer can't parse (the
# rolling-upgrade crash the bits exist to prevent).

PROTO_BITS = ["PROTO_TRACE_TRAILER", "PROTO_DEADLINE", "PROTO_CHECKSUM",
              "PROTO_STREAM", "PROTO_DELTA"]


def test_proto_feature_bits_distinct_and_aggregated():
    from karpenter_tpu.solver import service

    vals = [getattr(service, name) for name in PROTO_BITS]
    assert len(set(vals)) == len(vals)
    for a in vals:
        assert a & (a - 1) == 0, "capability bits must be single bits"
    agg = 0
    for v in vals:
        agg |= v
    assert service.PROTO_FEATURES == agg


@pytest.mark.parametrize("mask", range(32))
def test_proto_capability_word_round_trips_every_subset(mask):
    """Each of the 2^5 subsets of {PROTO_TRACE_TRAILER, PROTO_DEADLINE,
    PROTO_CHECKSUM, PROTO_STREAM, PROTO_DELTA} survives OpenSession payload
    encode → _split_status decode with every bit intact."""
    import numpy as np

    from karpenter_tpu.solver import service

    bits = [getattr(service, name) for name in PROTO_BITS]
    features = 0
    for i, bit in enumerate(bits):
        if mask & (1 << i):
            features |= bit
    frame = service._status_response(
        service.STATUS_OK, [np.array([features], np.int32)]
    )
    word, payload = service.RemoteSolver._split_status(frame)
    assert word == service.STATUS_OK
    decoded = int(payload[0].reshape(-1)[0]) if payload else 0
    for name, bit in zip(PROTO_BITS, bits):
        assert bool(decoded & bit) == bool(features & bit), name


def test_proto_old_server_advertises_nothing():
    """A pre-capability sidecar sends a bare STATUS_OK with no payload —
    the client must decode that as features=0 (no trailers, no stream),
    never crash on the missing word."""
    from karpenter_tpu.solver import service

    frame = service._status_response(service.STATUS_OK)
    word, payload = service.RemoteSolver._split_status(frame)
    assert word == service.STATUS_OK
    features = int(payload[0].reshape(-1)[0]) if payload else 0
    assert features == 0
    for name in PROTO_BITS:
        assert not (features & getattr(service, name))


def test_known_bad_documents_rejected():
    base = serde.to_wire("provisioners", random_provisioner(random.Random(1)))
    bad_op = json.loads(json.dumps(base))
    bad_op.setdefault("spec", {}).setdefault("requirements", []).append(
        {"key": "k", "operator": "Gt", "values": ["1"]}
    )
    assert any("enum" in e for e in validate(bad_op, SCHEMA, "$"))
    bad_ttl = json.loads(json.dumps(base))
    bad_ttl["spec"]["ttlSecondsAfterEmpty"] = -5
    assert any("minimum" in e for e in validate(bad_ttl, SCHEMA, "$"))
