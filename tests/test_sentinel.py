"""The regression sentinel + correlated incident plane (obs/sentinel.py,
obs/incidents.py): online baselines, change-point detection, persistence
across restarts, evidence correlation, and the /debug/incidents surface.
"""

import json
import math
import os
import socket
import urllib.request

import pytest

from karpenter_tpu import obs
from karpenter_tpu.obs.incidents import IncidentLog
from karpenter_tpu.obs.sentinel import (
    BASELINE_FILE,
    SentinelEngine,
    route_of,
    shape_class,
)


@pytest.fixture(autouse=True)
def _fresh_obs():
    obs.reset_for_tests()
    yield
    obs.reset_for_tests()


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class FakeSpan:
    """Sentinel-facing span stand-in: the engine reads name/duration/attrs,
    the incident plane additionally serializes via to_dict()."""

    def __init__(self, name, duration_s, attrs=None, error=None,
                 trace_id="t" * 32):
        self.name = name
        self.duration_s = duration_s
        self.attrs = attrs or {}
        self.error = error
        self.trace_id = trace_id

    def to_dict(self):
        return {
            "name": self.name,
            "trace_id": self.trace_id,
            "duration_s": self.duration_s,
            "attrs": dict(self.attrs),
            "children": [],
        }


def tight_engine(**kw):
    """Bench/test-scale knobs: warm in 8 events, 4-wide windows, trip on
    2 sustained deviating windows, floors low enough for ~1ms stages."""
    defaults = dict(min_events=8, window=4, sustain=2, abs_floor_s=0.0005)
    defaults.update(kw)
    return SentinelEngine(**defaults)


def feed(eng, n, duration, name="solver.solve", attrs=None):
    for _ in range(n):
        eng(FakeSpan(name, duration, attrs=attrs))


# ---------------------------------------------------------------------------
# key derivation


class TestKeying:
    def test_shape_class_power_of_two_buckets(self):
        assert shape_class(4000) == "4096"
        assert shape_class(3900) == "4096"  # same workload shape
        assert shape_class(400) == "512"    # different shape
        assert shape_class(1) == "1"
        assert shape_class(0) == "0"
        assert shape_class(-3) == "0"
        assert shape_class(None) == "-"
        assert shape_class("nope") == "-"

    def test_route_of_prefers_transport_then_backend(self):
        assert route_of(FakeSpan("w", 0.0, {"transport": "stream_shm"})) == "stream_shm"
        assert route_of(FakeSpan("w", 0.0, {"backend": "cpsat"})) == "cpsat"
        assert route_of(FakeSpan("w", 0.0, {"address": "h:50051"})) == "remote"
        assert route_of(FakeSpan("w", 0.0, {})) == "-"

    def test_routes_and_shapes_learn_separate_baselines(self):
        eng = tight_engine()
        feed(eng, 4, 0.001, attrs={"transport": "stream", "pods": 100})
        feed(eng, 4, 0.010, attrs={"transport": "unary", "pods": 100})
        feed(eng, 4, 0.050, attrs={"transport": "stream", "pods": 4000})
        assert eng.baseline_count() == 3

    def test_unwatched_span_is_ignored(self):
        eng = tight_engine()
        feed(eng, 10, 0.001, name="not.watched")
        assert eng.baseline_count() == 0


# ---------------------------------------------------------------------------
# detection


class TestDetection:
    def test_sustained_step_mints_exactly_one_incident(self):
        eng = tight_engine()
        feed(eng, 12, 0.001)
        assert eng.incidents.count() == 0  # steady traffic is quiet
        feed(eng, 12, 0.003)               # a 3x sustained step
        assert eng.incidents.count() == 1  # one regime change, one incident
        rec = eng.incidents.recent()[0]
        assert rec["stage"] == "solver.solve"
        row = rec["stages"][0]
        assert row["observed_s"] == pytest.approx(0.003, rel=0.01)
        assert row["baseline_s"] == pytest.approx(0.001, rel=0.05)
        assert row["observed_s"] > row["threshold_s"]

    def test_single_outlier_never_trips(self):
        eng = tight_engine()
        feed(eng, 12, 0.001)
        feed(eng, 1, 0.050)   # one slow solve is an outlier, not a step
        feed(eng, 12, 0.001)
        assert eng.incidents.count() == 0
        # and the gated update kept the outlier out of the baseline
        snap = eng.snapshot()["baselines"][0]
        assert snap["level_s"] == pytest.approx(0.001, rel=0.05)

    def test_warmup_is_quiet(self):
        # fewer than min_events observations can never produce a verdict,
        # no matter how wild the values look
        eng = tight_engine(min_events=100)
        feed(eng, 20, 0.001)
        feed(eng, 20, 0.100)
        assert eng.incidents.count() == 0

    def test_recovery_after_rebaseline_is_quiet(self):
        eng = tight_engine()
        feed(eng, 12, 0.001)
        feed(eng, 12, 0.003)
        assert eng.incidents.count() == 1
        # the incident re-baselined to the new regime: tracking it and
        # even recovering (a downward step) stays quiet
        feed(eng, 20, 0.003)
        feed(eng, 20, 0.001)
        assert eng.incidents.count() == 1

    def test_persisting_regression_is_one_incident_not_a_siren(self):
        eng = tight_engine()
        feed(eng, 12, 0.001)
        feed(eng, 60, 0.004)  # regression persists for many windows
        assert eng.incidents.count() == 1

    def test_observe_failure_never_raises(self):
        eng = tight_engine()
        # attrs raising inside _observe must be contained by the hook
        class Hostile:
            name = "solver.solve"
            duration_s = 0.001
            trace_id = "t" * 32

            @property
            def attrs(self):
                raise RuntimeError("hostile span")

        eng(Hostile())  # must not raise
        assert eng.baseline_count() == 0


# ---------------------------------------------------------------------------
# incident correlation


def _trip(log, stage="solver.wire", route="stream", shape="4096",
          observed=0.004, baseline=0.001):
    return log.deviation(
        stage=stage, route=route, shape=shape,
        span=FakeSpan(stage, observed, {"transport": route}),
        baseline={
            "observed_s": observed, "baseline_s": baseline,
            "baseline_std_s": 0.0001, "threshold_s": baseline * 2,
            "observations": 50,
        },
    )


class TestIncidentCorrelation:
    def test_deviation_in_window_attaches_as_extra_stage(self):
        t = [1000.0]
        log = IncidentLog(clock=lambda: t[0])
        _trip(log, stage="solver.wire")
        t[0] += 10.0  # inside the 30s correlation window
        _trip(log, stage="sidecar.pack", route="session-1")
        assert log.count() == 1  # wire+device correlate under ONE id
        rec = log.recent()[0]
        assert [s["stage"] for s in rec["stages"]] == [
            "solver.wire", "sidecar.pack",
        ]
        assert rec["last_deviation_at"] == 1010.0

    def test_deviation_past_window_mints_new_incident(self):
        t = [1000.0]
        log = IncidentLog(clock=lambda: t[0])
        _trip(log)
        t[0] += 31.0
        _trip(log)
        assert log.count() == 2
        assert len({r["id"] for r in log.recent()}) == 2

    def test_open_summary_tracks_the_correlation_window(self):
        t = [1000.0]
        log = IncidentLog(clock=lambda: t[0])
        assert log.open_summary() is None
        rec = _trip(log)
        assert log.open_summary() == {"id": rec["id"], "stage": "solver.wire"}
        t[0] += 31.0
        assert log.open_summary() is None  # window closed: quiet again

    def test_stage_attachment_is_bounded(self):
        t = [1000.0]
        log = IncidentLog(clock=lambda: t[0])
        for i in range(20):
            _trip(log, stage=f"stage.{i}")
            t[0] += 1.0
        assert log.count() == 1
        assert len(log.recent()[0]["stages"]) == 8  # MAX_STAGES

    def test_ring_is_bounded_and_get_by_id_works(self):
        t = [1000.0]
        log = IncidentLog(cap=3, clock=lambda: t[0])
        ids = []
        for _ in range(5):
            ids.append(_trip(log)["id"])
            t[0] += 31.0
        assert log.count() == 5             # opened counter is cumulative
        assert len(log.recent(limit=10)) == 3  # ring keeps the newest cap
        assert log.get(ids[0]) is None      # aged out
        assert log.get(ids[-1])["id"] == ids[-1]

    def test_summaries_are_bounded_and_newest_first(self):
        t = [1000.0]
        log = IncidentLog(clock=lambda: t[0])
        for _ in range(3):
            _trip(log)
            t[0] += 31.0
        summ = log.summaries(limit=2)
        assert len(summ) == 2
        assert summ[0]["opened_at"] > summ[1]["opened_at"]
        for s in summ:
            assert set(s) == {
                "id", "opened_at", "stage", "stages", "trace_id",
                "decision_ids", "flight_count",
            }


class TestIncidentEvidence:
    def test_incident_correlates_flight_decisions_and_state(self, tmp_path):
        import time as _time

        obs.configure_flight(str(tmp_path / "flight"), budget_s=10.0)
        prof = obs.configure_profiler(hz=200.0)
        # a provisioning round recorded just before the trip is in-window
        round_rec = obs.decision_log().record_round("default", [], [], context={})
        assert round_rec is not None
        deadline = _time.time() + 5.0
        while (_time.time() < deadline
               and not prof.flight_panel()["window_samples"]):
            _time.sleep(0.01)
        log = IncidentLog()
        rec = _trip(log)
        # the triggering span tree rides the record even though it was
        # under the flight budget (force-recorded + pinned)
        assert rec["trace"]["name"] == "solver.wire"
        assert len(rec["flights"]) >= 1
        assert rec["flights"][0]["incident_id"] == rec["id"]
        assert round_rec["id"] in [d["id"] for d in rec["decisions"]]
        # the profiler's in-window folds ride along (the key is the
        # flight panel's top_folds — pinned: a wrong key reads as "no
        # profiler configured" and silently empties the evidence)
        assert len(rec["profile_top"]) >= 1
        assert rec["profile_top"][0]["samples"] >= 1
        assert isinstance(rec["state"], dict)
        summ = log.summaries()[0]
        assert round_rec["id"] in summ["decision_ids"]
        assert summ["flight_count"] >= 1

    def test_incident_event_carries_decision_id(self):
        events = []

        class RecorderStub:
            def event(self, kind, name, **kw):
                events.append((kind, name, kw))

        obs.decision_log().record_round("default", [], [], context={})
        log = IncidentLog(recorder=RecorderStub())
        _trip(log, route="stream_shm")
        assert len(events) == 1
        kind, name, kw = events[0]
        assert (kind, name) == ("Provisioner", "stream_shm")
        assert kw["reason"] == "IncidentDetected"
        assert kw["type"] == "Warning"
        # the cross-link: the Warning names the in-window decision
        assert kw["decision_id"] == log.recent()[0]["decisions"][0]["id"]

    def test_event_decision_id_empty_when_no_round_in_window(self):
        events = []

        class RecorderStub:
            def event(self, kind, name, **kw):
                events.append(kw)

        log = IncidentLog(recorder=RecorderStub())
        _trip(log)
        assert events[0]["decision_id"] == ""  # honest and allowed

    def test_deviation_never_raises_on_broken_evidence(self):
        log = IncidentLog()

        class NoDict:  # lacks to_dict(): evidence assembly must contain it
            name = "solver.wire"
            duration_s = 0.004
            attrs = {}
            trace_id = "t" * 32

        assert log.deviation(
            stage="solver.wire", route="-", shape="-",
            span=NoDict(), baseline={},
        ) is None

    def test_pinned_flight_evidence_survives_ring_pruning(self, tmp_path):
        from karpenter_tpu.obs.flight import FlightRecorder

        rec = FlightRecorder(str(tmp_path), budget_s=0.0, cap=2)
        for i in range(3):
            rec.record(FakeSpan("solver.solve", 0.5, trace_id=f"{i:032d}"))
        pins = rec.pin_for_incident("i-deadbeef", limit=2)
        assert len(pins) == 2
        for _ in range(6):  # push the ring well past cap
            rec.record(FakeSpan("solver.solve", 0.5))
        on_disk = set(os.listdir(str(tmp_path)))
        for p in pins:
            assert p["file"] in on_disk  # incident evidence outlives age-out


# ---------------------------------------------------------------------------
# persistence (satellite: restart-resume, corrupt, unwritable)


class TestPersistence:
    def test_restart_resumes_from_persisted_baselines(self, tmp_path):
        d = str(tmp_path / "sentinel")
        eng1 = tight_engine(directory=d)
        feed(eng1, 16, 0.001)
        assert eng1.save() is True
        assert os.path.exists(os.path.join(d, BASELINE_FILE))

        eng2 = tight_engine(directory=d)
        assert eng2.baseline_count() == 1
        row = eng2.snapshot()["baselines"][0]
        assert row["restored"] is True
        assert row["level_s"] == pytest.approx(0.001, rel=0.05)
        assert row["observations"] >= eng2.min_events
        # restart mid-stream: steady traffic NEVER mints a warm-up
        # false incident (the restored baseline already knows normal) ...
        feed(eng2, 30, 0.001)
        assert eng2.incidents.count() == 0
        # ... and a real step trips immediately, no re-warm-up needed
        feed(eng2, 12, 0.005)
        assert eng2.incidents.count() == 1

    def test_corrupt_baseline_file_degrades_to_fresh_table(self, tmp_path):
        d = str(tmp_path / "sentinel")
        os.makedirs(d)
        with open(os.path.join(d, BASELINE_FILE), "w") as f:
            f.write("{not json")
        eng = tight_engine(directory=d)
        assert eng.baseline_count() == 0  # fresh table, not half-loaded
        assert eng.directory == d         # next save overwrites forensics
        feed(eng, 16, 0.001)
        assert eng.save() is True         # recovered persistence
        assert tight_engine(directory=d).baseline_count() == 1

    def test_wrong_version_is_corrupt(self, tmp_path):
        d = str(tmp_path / "sentinel")
        os.makedirs(d)
        with open(os.path.join(d, BASELINE_FILE), "w") as f:
            json.dump({"version": 99, "baselines": [
                {"key": ["a", "b", "c"], "level": 1.0},
            ]}, f)
        assert tight_engine(directory=d).baseline_count() == 0

    def test_uncreatable_directory_degrades_to_memory_only(self, tmp_path):
        blocker = tmp_path / "file"
        blocker.write_text("x")
        eng = tight_engine(directory=str(blocker / "sub"))
        assert eng.directory == ""  # memory-only, counted
        feed(eng, 12, 0.001)        # detection keeps running on what it has
        assert eng.baseline_count() == 1
        assert eng.save() is False

    def test_save_failure_degrades_to_memory_only(self, tmp_path):
        eng = tight_engine(directory=str(tmp_path / "ok"))
        feed(eng, 12, 0.001)
        blocker = tmp_path / "f"
        blocker.write_text("x")
        eng.directory = str(blocker / "sub")  # ENOSPC/read-only stand-in
        assert eng.save() is False
        assert eng.directory == ""            # degraded, detection lives on
        feed(eng, 4, 0.001)

    def test_close_persists(self, tmp_path):
        d = str(tmp_path / "sentinel")
        eng = tight_engine(directory=d)
        feed(eng, 12, 0.001)
        eng.close()
        assert tight_engine(directory=d).baseline_count() == 1

    def test_key_cap_evicts_oldest(self):
        eng = tight_engine(key_cap=4)
        for i in range(8):
            feed(eng, 2, 0.001, attrs={"transport": f"r{i}"})
        assert eng.baseline_count() == 4
        routes = {b["route"] for b in eng.snapshot()["baselines"]}
        assert routes == {"r4", "r5", "r6", "r7"}


# ---------------------------------------------------------------------------
# the obs facade + /debug/incidents


class TestObsWiring:
    def test_configure_sentinel_hooks_the_tracer(self):
        eng = obs.configure_sentinel()
        assert obs.sentinel() is eng
        with obs.tracer().span("solver.solve"):
            pass
        with obs.tracer().span("not.watched"):
            pass
        assert eng.baseline_count() == 1
        snap = eng.snapshot()
        assert snap["baselines"][0]["stage"] == "solver.solve"
        assert snap["overhead_ratio"] < 1.0

    def test_sentinel_contributes_a_state_panel(self):
        from karpenter_tpu.obs.flight import state_snapshot

        obs.configure_sentinel()
        panel = state_snapshot()["sentinel"]
        assert set(panel) == {
            "baselines", "incidents", "open_incident", "overhead_ratio",
        }
        obs.shutdown_sentinel()
        assert "sentinel" not in state_snapshot()

    def test_shutdown_is_ownership_checked(self):
        eng1 = obs.configure_sentinel()
        eng2 = obs.configure_sentinel()
        obs.shutdown_sentinel(engine=eng1)  # stale owner: not ours to kill
        assert obs.sentinel() is eng2
        obs.shutdown_sentinel(engine=eng2)
        assert obs.sentinel() is None

    def test_shutdown_final_persists(self, tmp_path):
        d = str(tmp_path / "sentinel")
        eng = obs.configure_sentinel(directory=d, min_events=4)
        feed(eng, 8, 0.001)
        obs.shutdown_sentinel(engine=eng)
        assert os.path.exists(os.path.join(d, BASELINE_FILE))

    def test_reset_for_tests_detaches(self):
        obs.configure_sentinel()
        obs.reset_for_tests()
        assert obs.sentinel() is None

    def test_tuning_kwargs_pass_through(self):
        eng = obs.configure_sentinel(
            min_events=3, window=2, sustain=1, incident_cap=5,
        )
        assert (eng.min_events, eng.window, eng.sustain) == (3, 2, 1)
        assert eng.incidents.cap == 5


class TestDebugIncidentsPayload:
    def test_empty_halves_when_unconfigured(self):
        assert obs.debug_incidents_payload("") == {
            "incidents": [], "sentinel": {},
        }

    def test_listing_and_detail(self):
        eng = obs.configure_sentinel(
            min_events=8, window=4, sustain=2, abs_floor_s=0.0005,
        )
        feed(eng, 12, 0.001)
        feed(eng, 12, 0.003)
        body = obs.debug_incidents_payload("")
        assert len(body["incidents"]) == 1
        assert body["sentinel"]["baseline_count"] == 1
        assert body["sentinel"]["watch"]  # disposition rides every answer
        iid = body["incidents"][0]["id"]
        detail = obs.debug_incidents_payload(f"id={iid}")
        assert detail["incident"]["id"] == iid
        assert detail["incident"]["trace"]["name"] == "solver.solve"
        assert obs.debug_incidents_payload("id=i-nope")["incident"] is None
        assert obs.debug_incidents_payload("limit=0")["incidents"] == []

    def test_sidecar_health_server_serves_incidents(self):
        from karpenter_tpu.solver.service import SolverService, _serve_health

        eng = obs.configure_sentinel(
            min_events=8, window=4, sustain=2, abs_floor_s=0.0005,
            watch=("sidecar.pack",),
        )
        feed(eng, 12, 0.001, name="sidecar.pack")
        feed(eng, 12, 0.004, name="sidecar.pack")
        service = SolverService()
        service.ready.set()
        port = free_port()
        httpd = _serve_health(service, port)
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/incidents", timeout=5
            ) as resp:
                body = json.loads(resp.read())
            assert len(body["incidents"]) == 1
            assert body["incidents"][0]["stages"][0]["stage"] == "sidecar.pack"
            iid = body["incidents"][0]["id"]
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/incidents?id={iid}", timeout=5
            ) as resp:
                detail = json.loads(resp.read())
            assert detail["incident"]["id"] == iid
        finally:
            httpd.shutdown()

    def test_member_payload_ships_incident_summaries(self):
        from karpenter_tpu.obs.collector import member_payload

        eng = obs.configure_sentinel(
            min_events=8, window=4, sustain=2, abs_floor_s=0.0005,
        )
        feed(eng, 12, 0.001)
        feed(eng, 12, 0.003)
        payload = member_payload("ctl-0", "controller")
        assert len(payload["incidents"]) == 1
        assert payload["incidents"][0]["stages"][0]["stage"] == "solver.solve"

    def test_fleet_incidents_merge_and_dedupe(self):
        from karpenter_tpu.obs.collector import TelemetryCollector

        inc_a = {"id": "i-aaa", "opened_at": 100.0, "stage": "solver.wire"}
        inc_b = {"id": "i-bbb", "opened_at": 200.0, "stage": "sidecar.pack"}

        class Backend:
            def poll(self):
                return [
                    {"identity": "ctl-0", "traces": [],
                     "incidents": [inc_a, inc_b]},
                    {"identity": "side-0", "traces": [],
                     "incidents": [inc_b]},  # double-reported: deduped
                ]

        coll = TelemetryCollector([Backend()])
        coll.refresh()
        fleet = coll.fleet_incidents()
        assert [i["id"] for i in fleet] == ["i-bbb", "i-aaa"]  # newest first
        assert fleet[0]["member"] == "ctl-0"
        assert [i["id"] for i in coll.fleet_payload()["incidents"]] == [
            "i-bbb", "i-aaa",
        ]


# ---------------------------------------------------------------------------
# SLO small-sample exactness (the BENCH_r07 device-leg regression:
# 8.03% online/offline delta at 12-iteration sample counts came from
# bucket-midpoint quantization; raw samples answer exactly while complete)


class TestSloSmallSampleExactness:
    @staticmethod
    def _offline_p99(values):
        # bench.py's _p99: exact nearest-rank over the sorted sample
        vs = sorted(values)
        return vs[min(len(vs) - 1, max(math.ceil(0.99 * len(vs)) - 1, 0))]

    def test_online_equals_offline_at_bench_sample_counts(self):
        for n in (6, 12, 24, 64):
            obs.shutdown_slo()
            eng = obs.configure_slo()
            durations = [0.001 + 0.0017 * ((i * 7) % n) for i in range(n)]
            for d in durations:
                eng(FakeSpan("solver.solve", d))
            online = eng.snapshot()["objectives"]["solve_p99"]["value"]
            offline = self._offline_p99(durations)
            # the <5% bench bar, pinned at its strongest: exact agreement
            assert online == pytest.approx(offline, abs=1e-12), (
                f"n={n}: online {online} != offline {offline}"
            )

    def test_sketch_takes_over_past_raw_cap(self):
        from karpenter_tpu.obs.slo import RAW_SAMPLE_CAP

        eng = obs.configure_slo()
        n = RAW_SAMPLE_CAP * 4
        durations = [0.001 * (1 + i % 100) for i in range(n)]
        for d in durations:
            eng(FakeSpan("solver.solve", d))
        online = eng.snapshot()["objectives"]["solve_p99"]["value"]
        offline = self._offline_p99(durations)
        assert abs(online - offline) / offline < 0.05  # the sketch bar
