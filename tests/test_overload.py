"""Overload-control tests (docs/overload.md): the bounded priority-aware
batcher, retry budgets, the sidecar admission gate, the typed shed
verdicts' never-a-failure contract, and the SLO-driven brownout ladder's
engage-and-fully-reverse audit trail."""

import threading
import time

import pytest

from karpenter_tpu.resilience import (
    Budget,
    CircuitBreaker,
    DeadlineExceededError,
    OverloadedError,
    RetryBudget,
    RetryPolicy,
)
from karpenter_tpu.resilience.brownout import (
    LEVEL_NAMES,
    MAX_LEVEL,
    PRESSURE_BY_LEVEL,
    ROUTER_BIAS,
    BrownoutController,
)
from karpenter_tpu.utils.batcher import Batcher
from karpenter_tpu.utils.pod import priority_of


class TestPriorityOf:
    def test_classes_order_correctly(self):
        from karpenter_tpu.testing.factories import make_pod

        system = make_pod(priority_class_name="system-cluster-critical")
        high = make_pod(priority_class_name="high-batch")
        default = make_pod()
        low = make_pod(priority_class_name="low-priority")
        best_effort = make_pod(priority_class_name="best-effort-batch")
        assert (
            priority_of(system)
            > priority_of(high)
            > priority_of(default)
            > priority_of(low)
        )
        assert priority_of(best_effort) < priority_of(default)


class TestBoundedBatcher:
    def test_full_queue_sheds_oldest_lowest_priority(self):
        shed = []
        b = Batcher(
            max_depth=3,
            priority_fn=lambda item: item[0],
            on_shed=lambda item, reason: shed.append((item, reason)),
        )
        b.add((0, "old-low"))
        b.add((5, "mid"))
        b.add((10, "high"))
        b.add((5, "newer-mid"))  # full: the oldest lowest-priority entry goes
        assert shed == [((0, "old-low"), "queue_full")]
        assert b.depth() == 3
        # nothing queued is below the new default tier now: an incoming
        # low-priority item is itself the least important thing in sight
        b.add((0, "new-low"))
        assert shed[-1] == ((0, "new-low"), "queue_full")
        b.stop()

    def test_incoming_item_refused_when_strictly_least_important(self):
        shed = []
        b = Batcher(
            max_depth=2,
            priority_fn=lambda item: item,
            on_shed=lambda item, reason: shed.append(item),
        )
        b.add(5)
        b.add(5)
        b.add(1)  # lower than everything queued: refused outright
        assert shed == [1]
        items, _ = b.wait()
        assert items == [5, 5]
        b.stop()

    def test_queue_depth_never_exceeds_cap(self):
        b = Batcher(max_depth=4)
        for i in range(50):
            b.add(i)
        assert b.depth() == 4
        assert b.max_depth_seen == 4
        assert b.shed_total == 46
        b.stop()

    def test_shed_metric_and_hook_containment(self):
        from karpenter_tpu import metrics as m

        def sample():
            return m.REGISTRY.get_sample_value(
                "karpenter_batcher_shed_total", {"reason": "queue_full"}
            ) or 0.0

        before = sample()

        def raising_hook(item, reason):
            raise RuntimeError("hook bug")

        b = Batcher(max_depth=1, on_shed=raising_hook)
        b.add(1)
        b.add(2)  # shed fires the raising hook — the add must survive
        assert sample() == before + 1
        assert b.depth() == 1
        b.stop()

    def test_pressure_scales_window_and_reverses(self):
        b = Batcher(idle_duration=5.0, max_duration=50.0, max_items=100, max_depth=10)
        b.set_pressure(0.01)
        for i in range(4):
            b.add(i)
        t0 = time.monotonic()
        items, _ = b.wait()  # idle window scaled to ~50ms: returns fast
        assert time.monotonic() - t0 < 2.0
        # cap scaled: max(100*0.01, 1) = 1 item per batch
        assert len(items) == 1
        b.set_pressure(1.0)
        assert b.pressure() == 1.0
        b.stop()

    def test_shed_low_priority_drains_below_floor_only(self):
        shed = []
        b = Batcher(
            max_depth=10,
            priority_fn=lambda item: item,
            on_shed=lambda item, reason: shed.append((item, reason)),
        )
        for pri in (-10, 0, 10, -10, 0):
            b.add(pri)
        dropped = b.shed_low_priority(0)
        assert dropped == 2
        assert [s for s, _ in shed] == [-10, -10]
        assert all(reason == "brownout" for _, reason in shed)
        items, _ = b.wait()
        assert items == [0, 10, 0]
        b.stop()

    def test_add_after_stop_still_returns_preset_gate(self):
        b = Batcher(max_depth=2)
        b.stop()
        gate = b.add(1)
        assert gate.is_set()

    def test_wait_parks_bounded_and_stop_wakes(self):
        b = Batcher(max_depth=2)
        out = []
        t = threading.Thread(target=lambda: out.append(b.wait()))
        t.start()
        time.sleep(0.1)
        b.stop()
        t.join(timeout=5)
        assert not t.is_alive()
        assert out == [([], 0.0)]


class TestRetryBudget:
    def test_spend_drains_and_success_refills(self):
        rb = RetryBudget(capacity=2, refill_per_success=0.5)
        assert rb.try_spend("dep")
        assert rb.try_spend("dep")
        assert not rb.try_spend("dep")  # dry
        rb.record_success("dep")
        rb.record_success("dep")  # +1.0 token
        assert rb.try_spend("dep")
        assert not rb.try_spend("dep")

    def test_refill_caps_at_capacity(self):
        rb = RetryBudget(capacity=3, refill_per_success=10.0)
        rb.record_success("dep")
        assert rb.remaining("dep") == 3.0

    def test_budgets_are_per_dependency(self):
        rb = RetryBudget(capacity=1)
        assert rb.try_spend("a")
        assert not rb.try_spend("a")
        assert rb.try_spend("b")
        assert rb.snapshot() == {"a": 0.0, "b": 0.0}

    def test_policy_stops_retrying_when_budget_dry(self):
        from karpenter_tpu import metrics as m

        def exhausted():
            return m.REGISTRY.get_sample_value(
                "karpenter_resilience_retries_total",
                {"dependency": "flaky", "outcome": "budget_exhausted"},
            ) or 0.0

        rb = RetryBudget(capacity=1, refill_per_success=0.0)
        calls = []

        def fn():
            calls.append(1)
            raise ConnectionError("down")

        policy = RetryPolicy(
            max_attempts=5, base=0.0001, cap=0.0001, dependency="flaky",
            retry_budget=rb, sleep=lambda s: None,
        )
        before = exhausted()
        with pytest.raises(ConnectionError):
            policy.call(fn)
        # one original attempt + one budgeted retry, then the bucket is dry
        assert len(calls) == 2
        assert exhausted() == before + 1

    def test_policy_success_refills_budget(self):
        rb = RetryBudget(capacity=1, refill_per_success=1.0)
        rb.try_spend("dep")  # drain
        policy = RetryPolicy(
            max_attempts=2, dependency="dep", retry_budget=rb,
            sleep=lambda s: None,
        )
        assert policy.call(lambda: "ok") == "ok"
        assert rb.remaining("dep") == 1.0

    def test_unlabeled_policy_skips_budget_accounting(self):
        calls = []

        def fn():
            calls.append(1)
            if len(calls) < 3:
                raise ConnectionError("down")
            return "ok"

        policy = RetryPolicy(
            max_attempts=3, base=0.0001, cap=0.0001, sleep=lambda s: None,
        )
        assert policy.call(fn) == "ok"
        assert len(calls) == 3

    def test_shed_verdicts_are_never_retried(self):
        for exc in (
            OverloadedError("full", retry_after=2.0),
            DeadlineExceededError("expired"),
        ):
            calls = []

            def fn(e=exc):
                calls.append(1)
                raise e

            policy = RetryPolicy(max_attempts=5, sleep=lambda s: None)
            with pytest.raises(type(exc)):
                policy.call(fn)
            assert len(calls) == 1  # non-retryable by classification

    def test_overloaded_error_carries_hint(self):
        e = OverloadedError("full", retry_after=3.5)
        assert e.retry_after == 3.5
        assert OverloadedError("full", retry_after=-1).retry_after == 0.0


class TestAdmissionGate:
    def _gate(self, **kw):
        from karpenter_tpu.solver.service import AdmissionGate

        return AdmissionGate(**kw)

    def test_admits_up_to_inflight_then_refuses_past_queue(self):
        gate = self._gate(max_inflight=2, queue_depth=0)
        assert gate.enter() == "admitted"
        assert gate.enter() == "admitted"
        assert gate.enter() == "overloaded"  # queue_depth 0: refuse at once
        gate.leave()
        assert gate.enter() == "admitted"
        assert gate.depth() == 2

    def test_queued_caller_admitted_when_slot_frees(self):
        gate = self._gate(max_inflight=1, queue_depth=1)
        assert gate.enter() == "admitted"
        results = []
        t = threading.Thread(target=lambda: results.append(gate.enter()))
        t.start()
        time.sleep(0.1)
        assert gate.depth() == 2  # 1 inflight + 1 queued
        gate.leave()
        t.join(timeout=5)
        assert results == ["admitted"]
        assert gate.max_depth_seen == 2

    def test_expired_deadline_while_queued_returns_deadline(self):
        clock = [0.0]
        gate = self._gate(max_inflight=1, queue_depth=2, clock=lambda: clock[0])
        assert gate.enter() == "admitted"
        results = []

        def queued():
            results.append(gate.enter(deadline=0.05))

        t = threading.Thread(target=queued)
        t.start()
        time.sleep(0.1)
        clock[0] = 1.0  # the caller's deadline passed while it sat queued
        with gate._cv:
            gate._cv.notify_all()
        t.join(timeout=5)
        assert results == ["deadline"]

    def test_overflow_past_queue_depth_refused_immediately(self):
        gate = self._gate(max_inflight=1, queue_depth=1)
        assert gate.enter() == "admitted"
        t = threading.Thread(target=gate.enter)  # occupies the queue slot
        t.daemon = True
        t.start()
        time.sleep(0.1)
        t0 = time.monotonic()
        assert gate.enter() == "overloaded"
        assert time.monotonic() - t0 < 1.0  # no park, an immediate refusal
        gate.leave()
        t.join(timeout=5)

    def test_bounded_wait_stays_below_client_rpc_timeout(self):
        """The gate's queue wait must answer STATUS_OVERLOADED BEFORE the
        client's warm gRPC deadline fires — if the RPC deadline won the
        race, the client would see a generic transport error and record a
        real breaker failure on pure backpressure."""
        import inspect

        from karpenter_tpu.solver.service import AdmissionGate, RemoteSolver

        warm_timeout = inspect.signature(
            RemoteSolver.__init__
        ).parameters["timeout"].default
        assert AdmissionGate.MAX_WAIT_S < warm_timeout / 2


class TestSchedulerShedHandling:
    """The never-a-failure contract at the scheduler: typed shed verdicts
    take the FFD floor WITHOUT moving breaker state."""

    def _scheduler_with_failing_remote(self, exc):
        import random as _random

        from karpenter_tpu.kube.client import Cluster
        from karpenter_tpu.solver.backend import TpuScheduler

        sched = TpuScheduler(
            Cluster(), rng=_random.Random(0), service_address="127.0.0.1:1",
        )

        class FakeRemote:
            def pack_begin(self, *a, **kw):
                raise exc

        sched._remote = FakeRemote()
        return sched

    def _solve_inputs(self):
        import random as _random

        from karpenter_tpu.cloudprovider.fake import instance_types
        from karpenter_tpu.cloudprovider.requirements import catalog_requirements
        from karpenter_tpu.testing import make_pod, make_provisioner

        catalog = sorted(
            instance_types(6), key=lambda it: it.effective_price()
        )
        constraints = make_provisioner(solver="tpu").spec.constraints
        constraints.requirements = constraints.requirements.merge(
            catalog_requirements(catalog)
        )
        pods = [make_pod(requests={"cpu": "0.5"}) for _ in range(5)]
        return constraints, catalog, pods

    def test_overloaded_remote_serves_batch_without_breaker_trip(self, monkeypatch):
        monkeypatch.setenv("KARPENTER_PACKER", "device")
        sched = self._scheduler_with_failing_remote(
            OverloadedError("full", retry_after=0.5)
        )
        constraints, catalog, pods = self._solve_inputs()
        vnodes = sched.solve(constraints, catalog, pods)
        assert sum(len(v.pods) for v in vnodes) == len(pods)
        # overload is backpressure: the remote breaker NEVER trips on it
        assert sched._remote_breaker.state == "closed"
        assert not sched._pack_breakers.open_dependencies()

    def test_deadline_exceeded_takes_ffd_floor_without_breaker_trip(self, monkeypatch):
        from karpenter_tpu import metrics as m

        def degraded():
            return m.REGISTRY.get_sample_value(
                "karpenter_solver_degraded_solves_total",
                {"reason": "deadline", "address": ""},
            ) or 0.0

        monkeypatch.setenv("KARPENTER_PACKER", "device")
        sched = self._scheduler_with_failing_remote(
            DeadlineExceededError("budget expired")
        )
        constraints, catalog, pods = self._solve_inputs()
        before = degraded()
        vnodes = sched.solve(constraints, catalog, pods)
        # non-retryable: the batch is still served — by the FFD floor
        assert sum(len(v.pods) for v in vnodes) == len(pods)
        assert degraded() == before + 1
        assert sched._remote_breaker.state == "closed"
        assert not sched._pack_breakers.open_dependencies()
        assert sched.last_profile.get("packer_backend") == "ffd-degraded"

    def test_client_pre_shed_on_expired_budget(self):
        """pack_begin under an already-expired round budget refuses before
        paying serialization."""
        from karpenter_tpu.solver.service import RemoteSolver

        rs = RemoteSolver.__new__(RemoteSolver)  # no channel needed
        budget = Budget(0.0)
        with budget.activate():
            with pytest.raises(DeadlineExceededError):
                rs.pack_begin(*([None] * 10), n_max=4)


class TestRouterBrownoutKnobs:
    def test_probes_pause_and_resume(self):
        from karpenter_tpu.solver.router import CostRouter

        r = CostRouter(probe_every=1)
        key = (1, 2, 3, 0)
        r.record(key, "device", 0.1)
        r.record(key, "native", 0.2)
        r.choose(key, ["device", "native"])
        assert r.should_probe(key)
        r.set_probes_paused(True)
        assert not r.should_probe(key)
        r.set_probes_paused(False)
        assert r.should_probe(key)

    def test_bias_routes_marginal_races_to_native_and_reverses(self):
        from karpenter_tpu.solver.router import CostRouter

        r = CostRouter()
        key = (1, 2, 3, 0)
        r.record(key, "device", 0.010)
        r.record(key, "native", 0.012)  # device wins the honest race
        assert r.choose(key, ["device", "native"]) == "device"
        r.set_brownout_bias(8.0)
        assert r.choose(key, ["device", "native"]) == "native"
        # stored EMAs untouched: recovery is instant
        r.set_brownout_bias(1.0)
        assert r.choose(key, ["device", "native"]) == "device"
        assert r.ema(key, "device") == pytest.approx(0.010)


class TestBrownoutLadder:
    def _harness(self, burning):
        """A controller wired to real actuation surfaces: a provisioning
        double with one batcher, a consolidation double, a fresh router."""
        from karpenter_tpu.kube.client import Cluster
        from karpenter_tpu.solver.router import CostRouter

        batcher = Batcher(max_depth=10, priority_fn=lambda item: item)

        class Worker:
            def __init__(self):
                self.batcher = batcher

        class Provisioning:
            def list_workers(self):
                return [Worker()]

        class Consolidation:
            def __init__(self):
                self._paused = False

            def set_paused(self, paused):
                self._paused = paused

            def paused(self):
                return self._paused

        router = CostRouter()
        consolidation = Consolidation()
        cluster = Cluster()
        ctl = BrownoutController(
            burning_fn=lambda: burning[0],
            provisioning=Provisioning(),
            consolidation=consolidation,
            router=router,
            cluster=cluster,
            escalate_after=1,
            recover_after=1,
        )
        return ctl, batcher, router, consolidation, cluster

    def test_ladder_engages_in_order_and_fully_reverses(self):
        from karpenter_tpu import obs
        from karpenter_tpu import metrics as m

        obs.reset_for_tests()
        burning = [True]
        ctl, batcher, router, consolidation, cluster = self._harness(burning)
        batcher.add(-10)  # queued low-priority work for the shed rung
        batcher.add(0)

        # escalate one rung per burning tick, asserting each rung's actions
        assert ctl.tick() == 1
        assert router.probes_paused()
        assert consolidation.paused()
        assert batcher.pressure() == PRESSURE_BY_LEVEL[1]
        assert ctl.tick() == 2
        assert batcher.pressure() == PRESSURE_BY_LEVEL[2]
        assert ctl.tick() == 3
        assert router.brownout_bias() == ROUTER_BIAS
        assert ctl.tick() == 4
        assert batcher.depth() == 1  # the low-priority entry was shed
        assert ctl.tick() == MAX_LEVEL  # clamped

        gauge = m.REGISTRY.get_sample_value("karpenter_brownout_level")
        assert gauge == MAX_LEVEL

        # recover one rung per clean tick, all the way to normal service
        burning[0] = False
        levels = [ctl.tick() for _ in range(MAX_LEVEL)]
        assert levels == [3, 2, 1, 0]
        assert not router.probes_paused()
        assert router.brownout_bias() == 1.0
        assert not consolidation.paused()
        assert batcher.pressure() == 1.0
        assert m.REGISTRY.get_sample_value("karpenter_brownout_level") == 0

        # audit trail: every step and its reversal is a span...
        spans = [
            s
            for tree in obs.exporter().snapshot(limit=None)
            for s in obs.spans_named(tree, "brownout.transition")
        ]
        directions = [s["attrs"]["direction"] for s in spans]
        assert directions.count("escalate") == MAX_LEVEL
        assert directions.count("recover") == MAX_LEVEL
        steps = {s["attrs"]["step"] for s in spans}
        assert steps == set(LEVEL_NAMES[level] for level in range(1, MAX_LEVEL + 1))
        # ...and a cluster event
        reasons = [e.reason for e in cluster.list("events", None)]
        assert reasons.count("BrownoutEscalated") == MAX_LEVEL
        assert reasons.count("BrownoutRecovered") == MAX_LEVEL
        # the controller's own audit list agrees
        assert len(ctl.transitions) == 2 * MAX_LEVEL
        batcher.stop()
        obs.reset_for_tests()

    def test_escalate_needs_sustained_burn(self):
        burning = [True]
        ctl, batcher, *_ = self._harness(burning)
        ctl.escalate_after = 3
        assert ctl.tick() == 0
        assert ctl.tick() == 0
        assert ctl.tick() == 1  # third consecutive burning tick engages
        burning[0] = False
        ctl.recover_after = 2
        assert ctl.tick() == 1
        assert ctl.tick() == 0
        batcher.stop()

    def test_broken_sensor_counts_as_clean(self):
        ctl = BrownoutController(
            burning_fn=lambda: 1 / 0, escalate_after=1, recover_after=1,
        )
        ctl._level = 2
        assert ctl.tick() == 1  # recovers instead of wedging at rung 2

    def test_stop_reverses_whatever_rung_was_engaged(self):
        burning = [True]
        ctl, batcher, router, consolidation, cluster = self._harness(burning)
        ctl.tick()
        ctl.tick()
        assert ctl.level() == 2
        ctl.stop()
        assert ctl.level() == 0
        assert not router.probes_paused()
        assert batcher.pressure() == 1.0
        assert not consolidation.paused()
        batcher.stop()

    def test_default_sensor_reads_slo_engine(self):
        from karpenter_tpu import obs

        obs.reset_for_tests()
        try:
            ctl = BrownoutController(escalate_after=1)
            assert ctl.tick() == 0  # no engine configured: never burns
            obs.configure_slo(window_s=60)
            assert ctl.tick() == 0  # engine quiet: still clean
        finally:
            obs.reset_for_tests()

    def test_consolidation_reconcile_pauses_under_brownout(self):
        from karpenter_tpu.controllers.consolidation import (
            WAVE_CHECK_INTERVAL,
            ConsolidationController,
        )
        from karpenter_tpu.kube.client import Cluster
        from karpenter_tpu.testing import make_provisioner

        cluster = Cluster()
        cluster.create("provisioners", make_provisioner(name="p1"))

        class NoPlanProvider:
            def get_instance_types(self, provider=None):
                raise AssertionError("a paused consolidation must not plan")

        ctl = ConsolidationController(
            cluster, NoPlanProvider(), enabled=True, migration="bind"
        )
        ctl.set_paused(True)
        assert ctl.reconcile("p1") == WAVE_CHECK_INTERVAL
        ctl.set_paused(False)


class TestRuntimeWiring:
    def test_build_runtime_wires_brownout_and_stop_reverses(self):
        from karpenter_tpu.main import build_runtime
        from karpenter_tpu.options import Options

        rt = build_runtime(Options(), start_workers=False)
        try:
            assert rt.brownout is not None
            assert rt.brownout.provisioning is rt.provisioning
            # actuate a rung, then prove Runtime.stop fully reverses it
            rt.brownout._level = 2
            rt.brownout._apply(2)
        finally:
            rt.stop()
        assert rt.brownout.level() == 0

    def test_no_brownout_option_disables(self):
        from karpenter_tpu.main import build_runtime
        from karpenter_tpu.options import Options, parse_args

        opts = parse_args(["--no-brownout"])
        assert not opts.brownout_enabled
        rt = build_runtime(opts, start_workers=False)
        try:
            assert rt.brownout is None
        finally:
            rt.stop()
