"""Controller-plane soak: sustained random churn through the FULL runtime.

The reference's correctness-under-concurrency story is `-race` + randomized
spec order; the closest Python analog is an actual soak — every controller
running, while pods arrive and vanish, nodes get deleted out from under the
system, the cloud injects stockouts, and consolidation re-packs — with the
system-level invariants asserted at the end:

- every surviving provisionable pod is eventually bound to a live node;
- no node leaks (every cluster node belongs to the provisioner and is
  known to the cloud double's delete ledger or still live);
- controllers never deadlock (the loop completes within the budget);
- provisioner status resources converge to the live node sum.
"""

import random
import time

from karpenter_tpu.api import labels as lbl
from karpenter_tpu.cloudprovider.gke import GkeCloudProvider, SimGkeAPI, ZONES
from karpenter_tpu.kube.client import Cluster
from karpenter_tpu.main import build_runtime
from karpenter_tpu.options import Options
from karpenter_tpu.utils import pod as podutil
from tests.factories import make_pod, make_provisioner


SOAK_SECONDS = 25.0


def test_soak_full_runtime_random_churn():
    rng = random.Random(20260730)
    api = SimGkeAPI()
    provider = GkeCloudProvider(api=api)
    cluster = Cluster()
    rt = build_runtime(
        Options(consolidation_enabled=True), cluster=cluster, cloud_provider=provider
    )
    rt.manager.start()
    try:
        prov = make_provisioner(solver="ffd", ttl_after_empty=1)
        cluster.create("provisioners", prov)
        deadline = time.time() + 10
        while time.time() < deadline and not rt.provisioning.workers:
            time.sleep(0.02)
        for w in rt.provisioning.workers.values():
            w.batcher.idle_duration = 0.1

        created = []
        deleted_pods = set()
        stop = time.time() + SOAK_SECONDS
        i = 0
        while time.time() < stop:
            action = rng.random()
            if action < 0.55:
                # a new pod (sometimes zone-pinned, sometimes spot)
                name = f"soak-{i}"
                i += 1
                kw = {}
                if rng.random() < 0.3:
                    kw["node_selector"] = {lbl.TOPOLOGY_ZONE: rng.choice(list(ZONES))}
                p = make_pod(
                    name=name,
                    requests={"cpu": f"{rng.choice([0.25, 0.5, 1, 2])}"},
                    **kw,
                )
                cluster.create("pods", p)
                created.append(name)
            elif action < 0.7 and created:
                # a pod vanishes (workload scaled down)
                victim = rng.choice(created)
                if victim not in deleted_pods:
                    deleted_pods.add(victim)
                    try:
                        cluster.delete("pods", victim)
                    except Exception:
                        pass
            elif action < 0.8:
                # a node is deleted out from under the system
                nodes = cluster.nodes()
                if nodes:
                    try:
                        cluster.delete(
                            "nodes", rng.choice(nodes).metadata.name, namespace=""
                        )
                    except Exception:
                        pass
            elif action < 0.9:
                # the cloud stocks out an offering (clears itself via the
                # 45s ICE TTL; soak is shorter, so also clear randomly)
                mt = rng.choice(["e2-standard-2", "e2-standard-4", "n2-standard-8"])
                z = rng.choice(list(ZONES))
                if rng.random() < 0.5:
                    api.set_stockout(mt, z)
                else:
                    api.clear_stockout(mt, z)
            time.sleep(rng.uniform(0.005, 0.05))

        # stop injecting; let the system settle
        for z in list(ZONES):
            for mt in ("e2-standard-2", "e2-standard-4", "n2-standard-8"):
                api.clear_stockout(mt, z)
        settle_deadline = time.time() + 60
        while time.time() < settle_deadline:
            pending = [
                p for p in cluster.pods()
                if podutil.is_provisionable(p)
            ]
            if not pending:
                break
            time.sleep(0.25)

        survivors = [p for p in cluster.pods()]
        pending = [p for p in survivors if podutil.is_provisionable(p)]
        assert not pending, (
            f"{len(pending)} pods still pending after settle: "
            f"{[p.metadata.name for p in pending[:5]]}"
        )
        # every surviving pod either got bound or is terminating — nothing
        # is silently dropped into limbo (nodes deleted mid-soak leave
        # bound pods behind: the in-memory double has no kubelet GC, so a
        # stale node_name is expected and fine)
        for p in survivors:
            assert p.spec.node_name or p.metadata.deletion_timestamp is not None, (
                f"pod {p.metadata.name} neither bound nor terminating"
            )
        # no foreign nodes: everything standing belongs to our provisioner
        for n in cluster.nodes():
            assert n.metadata.labels.get(lbl.PROVISIONER_NAME_LABEL) == "default"
    finally:
        rt.stop()


def test_soak_over_apiserver_boundary():
    """The same churn pushed across the real HTTP + wire-format boundary:
    TestApiServer + ApiCluster informers (RV-resumed watches), server-side
    binds (409 on re-bind), merge-patches under load. Shorter than the
    in-memory soak — every operation pays a real round trip."""
    import karpenter_tpu.kube.apiserver as apimod
    from karpenter_tpu.cloudprovider.fake import FakeCloudProvider, instance_types
    from karpenter_tpu.kube.apiserver import ApiCluster
    from karpenter_tpu.kube.testserver import TestApiServer

    rng = random.Random(42)
    server = TestApiServer()
    server.start()
    client = ApiCluster(server.url)
    client.start()
    assert client.wait_for_sync(10)
    provider = FakeCloudProvider(instance_types(20))
    rt = build_runtime(Options(), cluster=client, cloud_provider=provider)
    rt.manager.start()
    try:
        prov = make_provisioner(solver="ffd")
        server.cluster.create("provisioners", prov)
        deadline = time.time() + 10
        while time.time() < deadline and not rt.provisioning.workers:
            time.sleep(0.02)
        for w in rt.provisioning.workers.values():
            w.batcher.idle_duration = 0.1

        created = []
        stop = time.time() + 10.0
        i = 0
        while time.time() < stop:
            action = rng.random()
            if action < 0.7:
                name = f"api-soak-{i}"
                i += 1
                server.cluster.create(
                    "pods",
                    make_pod(name=name, requests={"cpu": f"{rng.choice([0.25, 0.5, 1])}"}),
                )
                created.append(name)
            elif created:
                victim = created[rng.randrange(len(created))]
                try:
                    server.cluster.delete("pods", victim)
                except Exception:
                    pass
            time.sleep(rng.uniform(0.01, 0.05))

        settle_deadline = time.time() + 60
        while time.time() < settle_deadline:
            pending = [
                p for p in server.cluster.pods() if podutil.is_provisionable(p)
            ]
            if not pending:
                break
            time.sleep(0.25)
        pending = [p for p in server.cluster.pods() if podutil.is_provisionable(p)]
        assert not pending, (
            f"{len(pending)} pods pending after settle over apiserver: "
            f"{[p.metadata.name for p in pending[:5]]}"
        )
        # the client's informer cache converged to the server's truth
        server_pods = {p.metadata.name for p in server.cluster.pods()}
        deadline = time.time() + 10
        while time.time() < deadline:
            client_pods = {p.metadata.name for p in client.pods()}
            if client_pods == server_pods:
                break
            time.sleep(0.2)
        assert {p.metadata.name for p in client.pods()} == server_pods
    finally:
        rt.stop()
        server.stop()
