"""Controller-plane soak: sustained random churn through the FULL runtime.

The reference's correctness-under-concurrency story is `-race` + randomized
spec order; the closest Python analog is an actual soak — every controller
running, while pods arrive and vanish, nodes get deleted out from under the
system, the cloud injects stockouts, and consolidation re-packs — with the
system-level invariants asserted at the end:

- every surviving provisionable pod is eventually bound to a live node;
- no node leaks (every cluster node belongs to the provisioner and is
  known to the cloud double's delete ledger or still live);
- controllers never deadlock (the loop completes within the budget);
- provisioner status resources converge to the live node sum.
"""

import random
import time
from contextlib import ExitStack

from karpenter_tpu.api import labels as lbl
from karpenter_tpu.cloudprovider.gke import GkeCloudProvider, SimGkeAPI, ZONES
from karpenter_tpu.kube.client import Cluster
from karpenter_tpu.main import build_runtime
from karpenter_tpu.options import Options
from karpenter_tpu.utils import pod as podutil
from tests.factories import make_pod, make_provisioner


SOAK_SECONDS = 25.0


# -- shared soak scaffolding (three soaks, one settle semantics) -----------

def wait_for_worker(rt, timeout=10.0, idle=0.1):
    deadline = time.time() + timeout
    while time.time() < deadline and not rt.provisioning.workers:
        time.sleep(0.02)
    assert rt.provisioning.workers, f"no provisioner worker after {timeout}s"
    for w in rt.provisioning.workers.values():
        w.batcher.idle_duration = idle


def churn_pods(cluster, rng, seconds, prefix, make_requests, create_frac=0.65):
    """Random pod create/delete churn against ``cluster`` for ``seconds``."""
    created = []
    stop = time.time() + seconds
    i = 0
    while time.time() < stop:
        if rng.random() < create_frac or not created:
            name = f"{prefix}-{i}"
            i += 1
            cluster.create("pods", make_pod(name=name, requests=make_requests(rng)))
            created.append(name)
        else:
            victim = created[rng.randrange(len(created))]
            try:
                cluster.delete("pods", victim)
            except Exception:
                pass
        time.sleep(rng.uniform(0.01, 0.05))
    return created


def settle(cluster, timeout=60.0, context="settle"):
    """Wait until no pod is provisionable; assert none remain."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        if not any(podutil.is_provisionable(p) for p in cluster.pods()):
            break
        time.sleep(0.25)
    pending = [p for p in cluster.pods() if podutil.is_provisionable(p)]
    assert not pending, (
        f"{len(pending)} pods pending after {context}: "
        f"{[p.metadata.name for p in pending[:5]]}"
    )


def test_soak_full_runtime_random_churn():
    rng = random.Random(20260730)
    api = SimGkeAPI()
    provider = GkeCloudProvider(api=api)
    cluster = Cluster()
    rt = build_runtime(
        Options(consolidation_enabled=True), cluster=cluster, cloud_provider=provider
    )
    rt.manager.start()
    try:
        prov = make_provisioner(solver="ffd", ttl_after_empty=1)
        cluster.create("provisioners", prov)
        wait_for_worker(rt)

        created = []
        deleted_pods = set()
        stop = time.time() + SOAK_SECONDS
        i = 0
        while time.time() < stop:
            action = rng.random()
            if action < 0.55:
                # a new pod (sometimes zone-pinned, sometimes spot)
                name = f"soak-{i}"
                i += 1
                kw = {}
                if rng.random() < 0.3:
                    kw["node_selector"] = {lbl.TOPOLOGY_ZONE: rng.choice(list(ZONES))}
                p = make_pod(
                    name=name,
                    requests={"cpu": f"{rng.choice([0.25, 0.5, 1, 2])}"},
                    **kw,
                )
                cluster.create("pods", p)
                created.append(name)
            elif action < 0.7 and created:
                # a pod vanishes (workload scaled down)
                victim = rng.choice(created)
                if victim not in deleted_pods:
                    deleted_pods.add(victim)
                    try:
                        cluster.delete("pods", victim)
                    except Exception:
                        pass
            elif action < 0.8:
                # a node is deleted out from under the system
                nodes = cluster.nodes()
                if nodes:
                    try:
                        cluster.delete(
                            "nodes", rng.choice(nodes).metadata.name, namespace=""
                        )
                    except Exception:
                        pass
            elif action < 0.9:
                # the cloud stocks out an offering (clears itself via the
                # 45s ICE TTL; soak is shorter, so also clear randomly)
                mt = rng.choice(["e2-standard-2", "e2-standard-4", "n2-standard-8"])
                z = rng.choice(list(ZONES))
                if rng.random() < 0.5:
                    api.set_stockout(mt, z)
                else:
                    api.clear_stockout(mt, z)
            time.sleep(rng.uniform(0.005, 0.05))

        # stop injecting; let the system settle
        for z in list(ZONES):
            for mt in ("e2-standard-2", "e2-standard-4", "n2-standard-8"):
                api.clear_stockout(mt, z)
        settle(cluster, context="settle")
        # every surviving pod either got bound or is terminating — nothing
        # is silently dropped into limbo (nodes deleted mid-soak leave
        # bound pods behind: the in-memory double has no kubelet GC, so a
        # stale node_name is expected and fine)
        for p in cluster.pods():
            assert p.spec.node_name or p.metadata.deletion_timestamp is not None, (
                f"pod {p.metadata.name} neither bound nor terminating"
            )
        # no foreign nodes: everything standing belongs to our provisioner
        for n in cluster.nodes():
            assert n.metadata.labels.get(lbl.PROVISIONER_NAME_LABEL) == "default"
    finally:
        rt.stop()


def test_soak_preemption_churn():
    """Interruption leg: the full runtime under pod churn WHILE the cloud
    preempts random nodes mid-workload (short grace periods so deadline
    enforcement also fires). Invariants: every surviving pod is bound or
    pending-and-retryable (nothing silently lost), every preempted node is
    gone by the end, and the controllers never deadlock."""
    import random as _random

    from karpenter_tpu.cloudprovider.fake import FakeCloudProvider, instance_types

    rng = _random.Random(20260803)
    provider = FakeCloudProvider(instance_types(20))
    cluster = Cluster()
    rt = build_runtime(Options(), cluster=cluster, cloud_provider=provider)
    rt.interruption.poll_interval = 0.2  # soak-speed notice latency
    rt.manager.start()
    try:
        cluster.create("provisioners", make_provisioner(solver="ffd"))
        wait_for_worker(rt)
        created = []
        preempted = set()
        stop = time.time() + 12.0
        i = 0
        while time.time() < stop:
            action = rng.random()
            if action < 0.5:
                name = f"preempt-soak-{i}"
                i += 1
                cluster.create(
                    "pods",
                    make_pod(name=name, requests={"cpu": f"{rng.choice([0.25, 0.5, 1])}"}),
                )
                created.append(name)
            elif action < 0.65 and created:
                try:
                    cluster.delete("pods", rng.choice(created))
                except Exception:
                    pass
            elif action < 0.9:
                # the interruption axis: a live node gets a notice with a
                # grace period short enough that some deadlines fire in-soak
                nodes = [
                    n for n in cluster.nodes()
                    if n.metadata.deletion_timestamp is None
                ]
                if nodes:
                    victim = rng.choice(nodes).metadata.name
                    preempted.add(victim)
                    provider.preempt(
                        victim, grace_period_seconds=rng.choice([0.5, 2.0, 30.0])
                    )
            time.sleep(rng.uniform(0.005, 0.05))

        settle(cluster, context="settle after preemption churn")
        assert preempted, "soak never preempted a node"
        # every pod that survived is bound to a LIVE node or terminating
        live = {n.metadata.name for n in cluster.nodes()}
        for p in cluster.pods():
            if p.metadata.deletion_timestamp is not None:
                continue
            assert p.spec.node_name in live, (
                f"pod {p.metadata.name} stranded on {p.spec.node_name!r}"
            )
        # preempted nodes do not outlive their grace periods: give the
        # termination/deadline paths a moment to finish the stragglers
        deadline = time.time() + 30
        while time.time() < deadline and any(
            cluster.try_get("nodes", n, namespace="") is not None for n in preempted
        ):
            time.sleep(0.25)
        for n in preempted:
            assert cluster.try_get("nodes", n, namespace="") is None, (
                f"preempted node {n} never terminated"
            )
        assert rt.interruption.notices_handled >= 1
    finally:
        rt.stop()


import pytest


@pytest.mark.slow
@pytest.mark.chaos
def test_soak_chaos_churn():
    """Chaos leg (slow): pod churn + live preemptions through the FULL
    runtime while the simulated control plane misbehaves statistically —
    10% call failures, 30ms p95 injected latency, a mid-soak blackout
    window. Invariants: the system settles (no pod left provisionable),
    nothing is silently lost, and no circuit breaker is left open once the
    chaos stops."""
    import random as _random

    from karpenter_tpu.cloudprovider.simulated import SimCloudAPI, SimulatedCloudProvider
    from karpenter_tpu.interruption.types import PREEMPTION, DisruptionNotice
    from karpenter_tpu.testing.chaos import ChaosPolicy, ChaosWindow, chaos_wrap

    rng = _random.Random(20260804)
    api = SimCloudAPI()
    chaos = chaos_wrap(api, ChaosPolicy(
        error_rate=0.1,
        latency_p95=0.03,
        blackouts=(ChaosWindow(6.0, 8.0),),
        seed=20260804,
    ))
    provider = SimulatedCloudProvider(api=chaos)
    cluster = Cluster()
    rt = build_runtime(Options(), cluster=cluster, cloud_provider=provider)
    rt.interruption.poll_interval = 0.2
    rt.manager.start()
    try:
        cluster.create("provisioners", make_provisioner(solver="ffd"))
        wait_for_worker(rt)
        created = []
        preempted = set()
        stop = time.time() + 15.0
        i = 0
        while time.time() < stop:
            action = rng.random()
            if action < 0.55:
                name = f"chaos-soak-{i}"
                i += 1
                cluster.create(
                    "pods",
                    make_pod(name=name, requests={"cpu": f"{rng.choice([0.25, 0.5, 1])}"}),
                )
                created.append(name)
            elif action < 0.7 and created:
                try:
                    cluster.delete("pods", rng.choice(created))
                except Exception:
                    pass
            elif action < 0.85:
                nodes = [
                    n for n in cluster.nodes()
                    if n.metadata.deletion_timestamp is None
                ]
                if nodes:
                    victim = rng.choice(nodes).metadata.name
                    preempted.add(victim)
                    api.send_disruption_notice(DisruptionNotice(
                        kind=PREEMPTION, node_name=victim,
                        grace_period_seconds=rng.choice([2.0, 30.0]),
                    ))
            time.sleep(rng.uniform(0.005, 0.05))

        assert chaos.injected_total() > 0, "soak never injected a failure"
        settle(cluster, timeout=120.0, context="settle after chaos churn")
        # nothing silently lost: every surviving pod is bound or terminating
        for p in cluster.pods():
            assert p.spec.node_name or p.metadata.deletion_timestamp is not None, (
                f"pod {p.metadata.name} neither bound nor terminating"
            )
        # preempted nodes do not linger past their grace periods
        deadline = time.time() + 60
        while time.time() < deadline and any(
            cluster.try_get("nodes", n, namespace="") is not None for n in preempted
        ):
            time.sleep(0.25)
        for n in preempted:
            assert cluster.try_get("nodes", n, namespace="") is None, (
                f"preempted node {n} never terminated under chaos"
            )
        # the failure regime is over: no breaker may be left open
        deadline = time.time() + 30
        while time.time() < deadline and rt.cloud_provider.breakers.open_dependencies():
            time.sleep(0.5)
        assert rt.cloud_provider.breakers.open_dependencies() == []
    finally:
        rt.stop()


def test_soak_over_apiserver_boundary():
    """The same churn pushed across the real HTTP + wire-format boundary:
    TestApiServer + ApiCluster informers (RV-resumed watches), server-side
    binds (409 on re-bind), merge-patches under load. Shorter than the
    in-memory soak — every operation pays a real round trip."""
    from karpenter_tpu.cloudprovider.fake import FakeCloudProvider, instance_types
    from karpenter_tpu.kube.apiserver import ApiCluster
    from karpenter_tpu.kube.testserver import TestApiServer

    rng = random.Random(42)
    with ExitStack() as stack:
        server = TestApiServer()
        server.start()
        stack.callback(server.stop)
        client = ApiCluster(server.url)
        client.start()
        stack.callback(client.stop)
        assert client.wait_for_sync(10)
        provider = FakeCloudProvider(instance_types(20))
        rt = build_runtime(Options(), cluster=client, cloud_provider=provider)
        rt.manager.start()
        stack.callback(rt.stop)

        server.cluster.create("provisioners", make_provisioner(solver="ffd"))
        wait_for_worker(rt)
        churn_pods(
            server.cluster, rng, 10.0, "api-soak",
            lambda r: {"cpu": f"{r.choice([0.25, 0.5, 1])}"}, create_frac=0.7,
        )
        settle(server.cluster, context="settle over apiserver")
        # the client's informer cache converged to the server's truth
        server_pods = {p.metadata.name for p in server.cluster.pods()}
        deadline = time.time() + 10
        while time.time() < deadline:
            client_pods = {p.metadata.name for p in client.pods()}
            if client_pods == server_pods:
                break
            time.sleep(0.2)
        assert {p.metadata.name for p in client.pods()} == server_pods


def test_soak_over_both_wires():
    """VERDICT r4 ask #8: the full runtime with BOTH control planes behind
    real HTTP at once — kube (TestApiServer + ApiCluster informers) and
    cloud (the GKE double behind GkeAPIServer/HttpGkeAPI, constructed by
    registry name exactly as ``--cloud-provider=gke-http`` would) — under
    pod churn. selection → batcher → solve → launch → bind crosses two
    wires simultaneously; reference analog: aws/fake/ec2api.go driving the
    real provider in aws/suite_test.go."""
    from karpenter_tpu.cloudprovider.gke import TPU_RESOURCE
    from karpenter_tpu.cloudprovider.httpapi import GkeAPIServer
    from karpenter_tpu.cloudprovider.registry import new_cloud_provider
    from karpenter_tpu.kube.apiserver import ApiCluster
    from karpenter_tpu.kube.testserver import TestApiServer

    rng = random.Random(99)
    with ExitStack() as stack:
        kube = TestApiServer()
        kube.start()
        stack.callback(kube.stop)
        api = SimGkeAPI()
        cloud = GkeAPIServer(api).start()
        stack.callback(cloud.stop)
        client = ApiCluster(kube.url)
        client.start()
        stack.callback(client.stop)
        assert client.wait_for_sync(10)
        provider = new_cloud_provider("gke-http", url=cloud.url)
        rt = build_runtime(Options(), cluster=client, cloud_provider=provider)
        rt.manager.start()
        stack.callback(rt.stop)

        kube.cluster.create("provisioners", make_provisioner(solver="ffd"))
        wait_for_worker(rt)

        def requests(r):
            if r.random() < 0.3:
                return {"cpu": "4", TPU_RESOURCE: "4"}
            return {"cpu": f"{r.choice([0.5, 1, 2])}"}

        churn_pods(kube.cluster, rng, 8.0, "wires", requests)
        settle(kube.cluster, context="settle over both wires")
        # the launches were real GKE-wire calls: node pools exist in the
        # cloud double, created over HTTP, and every cluster node maps to
        # a live pool instance
        assert api.create_calls, "no node pool ever created over the cloud wire"
        nodes = kube.cluster.nodes()
        assert nodes, "churn must have provisioned at least one node"
        pool_instances = {
            inst.name for pool in api.node_pools.values() for inst in pool.instances
        }
        for node in nodes:
            assert node.metadata.name in pool_instances, (
                f"node {node.metadata.name} unknown to the cloud double"
            )
