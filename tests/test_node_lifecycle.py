"""Node lifecycle tests (mirrors node/suite_test.go): expiry TTL, readiness
taint add/remove, init-timeout kill, emptiness TTL. Deterministic time via the
cluster's injectable clock."""

import pytest

from karpenter_tpu.api import labels as lbl
from karpenter_tpu.api.objects import OwnerReference, Taint
from karpenter_tpu.controllers.node import (
    INITIALIZATION_TIMEOUT,
    NodeController,
    result_min,
)
from karpenter_tpu.kube.client import Cluster
from tests.factories import make_node, make_pod, make_provisioner


@pytest.fixture()
def env():
    now = [1000.0]
    cluster = Cluster(clock=lambda: now[0])
    controller = NodeController(cluster)
    return cluster, controller, now


def karpenter_node(cluster, **kw):
    kw.setdefault("provisioner_name", "default")
    kw.setdefault("finalizers", [lbl.TERMINATION_FINALIZER])
    node = make_node(**kw)
    cluster.create("nodes", node)
    return node


class TestInitialization:
    def test_not_ready_taint_removed_when_ready(self, env):
        cluster, controller, _ = env
        cluster.create("provisioners", make_provisioner())
        node = karpenter_node(
            cluster, ready=True, taints=[Taint(key=lbl.NOT_READY_TAINT_KEY, effect="NoSchedule")]
        )
        controller.reconcile(node.metadata.name)
        assert not any(t.key == lbl.NOT_READY_TAINT_KEY for t in node.spec.taints)

    def test_taint_kept_while_not_ready(self, env):
        cluster, controller, _ = env
        cluster.create("provisioners", make_provisioner())
        node = karpenter_node(
            cluster, ready=False, taints=[Taint(key=lbl.NOT_READY_TAINT_KEY, effect="NoSchedule")]
        )
        requeue = controller.reconcile(node.metadata.name)
        assert any(t.key == lbl.NOT_READY_TAINT_KEY for t in node.spec.taints)
        assert requeue is not None and requeue <= INITIALIZATION_TIMEOUT

    def test_unready_node_deleted_after_timeout(self, env):
        cluster, controller, now = env
        cluster.create("provisioners", make_provisioner())
        node = karpenter_node(
            cluster, ready=False, taints=[Taint(key=lbl.NOT_READY_TAINT_KEY, effect="NoSchedule")]
        )
        now[0] += INITIALIZATION_TIMEOUT + 1
        controller.reconcile(node.metadata.name)
        # finalizer-bearing node: deletion timestamp set, awaiting termination
        assert node.metadata.deletion_timestamp is not None

    def test_other_taints_untouched(self, env):
        cluster, controller, _ = env
        cluster.create("provisioners", make_provisioner())
        node = karpenter_node(
            cluster,
            ready=True,
            taints=[
                Taint(key=lbl.NOT_READY_TAINT_KEY, effect="NoSchedule"),
                Taint(key="dedicated", value="team", effect="NoSchedule"),
            ],
        )
        controller.reconcile(node.metadata.name)
        assert [t.key for t in node.spec.taints] == ["dedicated"]


class TestExpiration:
    def test_node_expires_after_ttl(self, env):
        cluster, controller, now = env
        cluster.create("provisioners", make_provisioner(ttl_until_expired=60))
        node = karpenter_node(cluster)
        requeue = controller.reconcile(node.metadata.name)
        assert node.metadata.deletion_timestamp is None
        assert requeue == pytest.approx(60.0, abs=1.0)
        now[0] += 61
        controller.reconcile(node.metadata.name)
        assert node.metadata.deletion_timestamp is not None

    def test_no_ttl_no_expiry(self, env):
        cluster, controller, now = env
        cluster.create("provisioners", make_provisioner())
        node = karpenter_node(cluster)
        now[0] += 10_000_000
        assert controller.reconcile(node.metadata.name) is None
        assert node.metadata.deletion_timestamp is None


class TestEmptiness:
    def test_empty_node_annotated_then_deleted(self, env):
        cluster, controller, now = env
        cluster.create("provisioners", make_provisioner(ttl_after_empty=30))
        node = karpenter_node(cluster, ready=True)
        requeue = controller.reconcile(node.metadata.name)
        assert lbl.EMPTINESS_TIMESTAMP_ANNOTATION in node.metadata.annotations
        assert requeue == pytest.approx(30.0)
        now[0] += 31
        controller.reconcile(node.metadata.name)
        assert node.metadata.deletion_timestamp is not None

    def test_annotation_removed_when_pod_lands(self, env):
        cluster, controller, now = env
        cluster.create("provisioners", make_provisioner(ttl_after_empty=30))
        node = karpenter_node(cluster, ready=True)
        controller.reconcile(node.metadata.name)
        assert lbl.EMPTINESS_TIMESTAMP_ANNOTATION in node.metadata.annotations
        pod = make_pod(node_name=node.metadata.name, unschedulable=False)
        cluster.create("pods", pod)
        controller.reconcile(node.metadata.name)
        assert lbl.EMPTINESS_TIMESTAMP_ANNOTATION not in node.metadata.annotations
        # and the node survives well past the TTL
        now[0] += 1000
        controller.reconcile(node.metadata.name)
        assert node.metadata.deletion_timestamp is None

    def test_daemonset_pods_do_not_count(self, env):
        cluster, controller, _ = env
        cluster.create("provisioners", make_provisioner(ttl_after_empty=30))
        node = karpenter_node(cluster, ready=True)
        ds_pod = make_pod(node_name=node.metadata.name, unschedulable=False)
        ds_pod.metadata.owner_references.append(
            OwnerReference(api_version="apps/v1", kind="DaemonSet", name="ds")
        )
        cluster.create("pods", ds_pod)
        controller.reconcile(node.metadata.name)
        assert lbl.EMPTINESS_TIMESTAMP_ANNOTATION in node.metadata.annotations

    def test_not_ready_node_skipped(self, env):
        cluster, controller, _ = env
        cluster.create("provisioners", make_provisioner(ttl_after_empty=30))
        node = karpenter_node(cluster, ready=False)
        controller.reconcile(node.metadata.name)
        assert lbl.EMPTINESS_TIMESTAMP_ANNOTATION not in node.metadata.annotations


class TestFinalizer:
    def test_finalizer_added_to_self_registered_node(self, env):
        cluster, controller, _ = env
        cluster.create("provisioners", make_provisioner())
        node = karpenter_node(cluster, finalizers=[])
        controller.reconcile(node.metadata.name)
        assert lbl.TERMINATION_FINALIZER in node.metadata.finalizers


class TestController:
    def test_non_karpenter_node_ignored(self, env):
        cluster, controller, _ = env
        node = make_node()
        cluster.create("nodes", node)
        assert controller.reconcile(node.metadata.name) is None

    def test_result_min(self):
        assert result_min(None, 5.0, 2.0, None) == 2.0
        assert result_min(None, None) is None

    def test_double_delete_never_bypasses_finalizer(self, env):
        """Init-timeout + expiry both firing must leave the node terminating
        (finalizer intact), never hard-removed — a hard remove would skip the
        termination controller and leak the cloud instance."""
        cluster, controller, now = env
        cluster.create("provisioners", make_provisioner(ttl_until_expired=60))
        node = karpenter_node(
            cluster, ready=False, taints=[Taint(key=lbl.NOT_READY_TAINT_KEY, effect="NoSchedule")]
        )
        now[0] += INITIALIZATION_TIMEOUT + 1  # past both init timeout and expiry
        controller.reconcile(node.metadata.name)
        still = cluster.try_get("nodes", node.metadata.name, namespace="")
        assert still is not None  # terminating, not gone
        assert still.metadata.deletion_timestamp is not None
        assert lbl.TERMINATION_FINALIZER in still.metadata.finalizers

    def test_requeue_is_soonest_of_subreconcilers(self, env):
        cluster, controller, _ = env
        cluster.create("provisioners", make_provisioner(ttl_after_empty=30, ttl_until_expired=600))
        node = karpenter_node(cluster, ready=True)
        requeue = controller.reconcile(node.metadata.name)
        assert requeue == pytest.approx(30.0)  # emptiness sooner than expiry


class TestMergePatchDiscipline:
    def test_failed_patch_does_not_poison_cache(self):
        """Sub-reconcilers run on a copy: if the merge patch fails, the
        cached node is untouched and the retry still sees the divergence
        (round-2 review finding)."""
        from karpenter_tpu.api.objects import PodCondition, Taint
        from karpenter_tpu.controllers.node import NodeController
        from tests.factories import make_node, make_provisioner

        cluster = Cluster()
        cluster.create("provisioners", make_provisioner())
        node = make_node(name="n", provisioner_name="default", capacity={"cpu": "4"})
        node.spec.taints = [Taint(key=lbl.NOT_READY_TAINT_KEY, effect="NoSchedule")]
        node.status.conditions = [PodCondition(type="Ready", status="True")]
        cluster.create("nodes", node)

        controller = NodeController(cluster)
        boom = {"n": 1}
        real_patch = cluster.merge_patch

        def flaky_patch(kind, name, patch, namespace="default"):
            if boom.pop("n", None):
                raise RuntimeError("transient apiserver error")
            return real_patch(kind, name, patch, namespace=namespace)

        cluster.merge_patch = flaky_patch
        try:
            with pytest.raises(RuntimeError):
                controller.reconcile("n")
            # the cached object kept the taint (no pre-write mutation)
            cached = cluster.get("nodes", "n", namespace="")
            assert any(t.key == lbl.NOT_READY_TAINT_KEY for t in cached.spec.taints)
            # the retry converges
            controller.reconcile("n")
            cached = cluster.get("nodes", "n", namespace="")
            assert all(t.key != lbl.NOT_READY_TAINT_KEY for t in cached.spec.taints)
        finally:
            cluster.merge_patch = real_patch

    def test_annotation_patch_sends_only_changes(self):
        """The annotations patch must not re-assert unchanged keys (stale
        cache values would clobber concurrent writers)."""
        from karpenter_tpu.api.objects import PodCondition
        from karpenter_tpu.controllers.node import NodeController
        from tests.factories import make_node, make_provisioner

        cluster = Cluster()
        cluster.create("provisioners", make_provisioner(ttl_after_empty=600))
        node = make_node(name="n", provisioner_name="default", capacity={"cpu": "4"})
        node.metadata.annotations["unrelated.io/key"] = "theirs"
        node.status.conditions = [PodCondition(type="Ready", status="True")]
        cluster.create("nodes", node)

        controller = NodeController(cluster)
        patches = []
        real_patch = cluster.merge_patch

        def spy(kind, name, patch, namespace="default"):
            patches.append(patch)
            return real_patch(kind, name, patch, namespace=namespace)

        cluster.merge_patch = spy
        try:
            controller.reconcile("n")
        finally:
            cluster.merge_patch = real_patch
        (patch,) = patches
        sent = patch.get("metadata", {}).get("annotations", {})
        assert lbl.EMPTINESS_TIMESTAMP_ANNOTATION in sent
        assert "unrelated.io/key" not in sent  # unchanged keys stay out
