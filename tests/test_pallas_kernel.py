"""Pallas packing kernel parity: identical PackResult to the lax.scan kernel
on real encoded batches. Runs only on a TPU backend — the CI suite (CPU mesh)
exercises the lax.scan path, which pack_best selects there."""

import random

import numpy as np
import pytest

from karpenter_tpu.solver.pallas_kernel import BLOCK, pack_best, pallas_available

pytestmark = pytest.mark.skipif(
    not pallas_available(), reason="pallas pack needs a TPU backend"
)


def encoded_batch(n_pods, seed=42):
    from karpenter_tpu.cloudprovider.fake import instance_types
    from karpenter_tpu.cloudprovider.requirements import catalog_requirements
    from karpenter_tpu.kube.client import Cluster
    from karpenter_tpu.scheduling.ffd import daemon_overhead, sort_pods_ffd
    from karpenter_tpu.scheduling.topology import Topology
    from karpenter_tpu.solver import encode as enc
    from karpenter_tpu.testing import diverse_pods, make_provisioner

    catalog = sorted(instance_types(50), key=lambda it: it.effective_price())
    provisioner = make_provisioner(solver="tpu")
    c = provisioner.spec.constraints
    c.requirements = c.requirements.merge(catalog_requirements(catalog))
    pods = sort_pods_ffd(diverse_pods(n_pods, random.Random(seed)))
    cc = c.clone()
    Topology(Cluster(), rng=random.Random(1)).inject(cc, pods)
    daemon = daemon_overhead(Cluster(), cc)
    batch = enc.encode(cc, catalog, pods, daemon)
    return (
        batch.pod_valid, batch.pod_open_sig, batch.pod_core, batch.pod_host,
        batch.pod_host_in_base, batch.pod_open_host, batch.pod_req,
        batch.join_table, batch.frontiers, batch.daemon,
    )


@pytest.mark.parametrize("n_pods,n_max", [(100, 128), (500, 256), (1500, 512)])
def test_pallas_matches_lax_kernel(n_pods, n_max):
    import jax

    from karpenter_tpu.solver import kernel
    from karpenter_tpu.solver.pallas_kernel import pack_pallas

    args = encoded_batch(n_pods)
    assert args[6].shape[0] % BLOCK == 0
    ref = jax.device_get(tuple(kernel.pack(*args, n_max=n_max)))
    out = jax.device_get(tuple(pack_pallas(*args, n_max=n_max)))
    for name, a, b in zip(kernel.PackResult._fields, ref, out):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b), err_msg=name)


def test_pack_best_selects_a_working_kernel():
    import jax

    args = encoded_batch(200)
    result = pack_best(*args, n_max=128)
    n_nodes = int(np.asarray(jax.device_get(result.n_nodes)).reshape(-1)[0])
    assert n_nodes > 0


def synth_batch(P, S, C, F, R=4, seed=0):
    """Synthetic kernel inputs at controlled signature diversity — real
    encodes top out at the catalog's natural S; the stress cases need S
    well past it (VERDICT r1 weak #5)."""
    rng = np.random.default_rng(seed)
    return (
        np.ones(P, bool),
        rng.integers(0, S, P).astype(np.int32),
        rng.integers(0, C, P).astype(np.int32),
        np.full(P, -1, np.int32),
        np.ones(P, bool),
        np.full(P, -1, np.int32),
        rng.uniform(0.1, 1.0, (P, R)).astype(np.float32),
        rng.integers(-1, S, (S, C)).astype(np.int32),
        rng.uniform(2.0, 16.0, (S, F, R)).astype(np.float32),
        np.zeros(R, np.float32),
    )


def test_pallas_high_signature_diversity_compiles_bounded():
    """S=128, F=8 (S*F = budget): the pallas path must compile within a
    bounded window and match lax.scan exactly."""
    import time

    import jax

    from karpenter_tpu.solver import kernel
    from karpenter_tpu.solver.pallas_kernel import pack_pallas

    args = synth_batch(P=512, S=128, C=16, F=8, seed=3)
    t0 = time.perf_counter()
    out = jax.device_get(tuple(pack_pallas(*args, n_max=128)))
    compile_s = time.perf_counter() - t0
    assert compile_s < 120, f"compile took {compile_s:.0f}s"
    ref = jax.device_get(tuple(kernel.pack(*args, n_max=128)))
    for name, a, b in zip(kernel.PackResult._fields, ref, out):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b), err_msg=name)


def test_unroll_budget_routes_diverse_batches_to_v2():
    """Past the v1 compile budget (S*F > 1024) pack_best must not attempt
    the unrolled kernel — a ~2min Mosaic compile at S=512 would blow the
    solve latency — and must serve the batch with the v2 (matmul-gather)
    kernel, parity-exact with lax.scan."""
    import jax

    from karpenter_tpu.solver import kernel
    from karpenter_tpu.solver import pallas_kernel as pk
    from karpenter_tpu.solver import pallas_kernel_v2 as v2mod

    args = synth_batch(P=256, S=256, C=8, F=8, seed=4)
    assert 256 * 8 > pk.PALLAS_UNROLL_BUDGET
    v1_calls, v2_calls = [], []
    orig_v1, orig_v2 = pk.pack_pallas, v2mod.pack_pallas_v2

    def spy_v1(*a, **kw):
        v1_calls.append(1)
        return orig_v1(*a, **kw)

    def spy_v2(*a, **kw):
        v2_calls.append(1)
        return orig_v2(*a, **kw)

    pk.pack_pallas = spy_v1
    v2mod.pack_pallas_v2 = spy_v2
    try:
        result = pack_best(*args, n_max=128)
    finally:
        pk.pack_pallas = orig_v1
        v2mod.pack_pallas_v2 = orig_v2
    assert v1_calls == []  # the unrolled kernel was never attempted
    assert v2_calls == [1]
    # and v2 SUCCEEDED — a swallowed failure would fall back to lax.scan
    # and make the parity check below compare lax.scan with itself
    assert ("v2", 256, 128) not in pk._pallas_failed_shapes
    ref = jax.device_get(tuple(kernel.pack(*args, n_max=128)))
    out = jax.device_get(tuple(result))
    for name, a, b in zip(kernel.PackResult._fields, ref, out):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b), err_msg=name)


def test_v2_parity_on_real_encoded_batch():
    """The v2 kernel must match lax.scan on a genuine encoded batch (not
    just synthetic tables): hostnames, daemon overhead, topology pins."""
    import jax

    from karpenter_tpu.solver import kernel
    from karpenter_tpu.solver.pallas_kernel_v2 import pack_pallas_v2

    args = encoded_batch(300, seed=9)
    n_max = 256
    ref = jax.device_get(tuple(kernel.pack(*args, n_max=n_max)))
    out = jax.device_get(tuple(pack_pallas_v2(*args, n_max=n_max)))
    for name, a, b in zip(kernel.PackResult._fields, ref, out):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b), err_msg=name)


def test_v2_multi_solve_route_parity():
    """The sharded multi-solve's v2 route (VERDICT r2 #4): a stacked
    constraint-diverse batch solved by the per-shard v2 kernel must match
    the vmapped lax.scan kernel exactly."""
    import jax

    from karpenter_tpu.parallel import sharding as sh

    # identical batches → identical closure shapes across the stack (the
    # production multi-solve stacks same-bucket batches; differing S would
    # not stack). Parity is per-batch, so duplication loses nothing.
    stacks = [encoded_batch(300, seed=3), encoded_batch(300, seed=3)]
    arrays = tuple(np.stack([np.asarray(s[i]) for s in stacks]) for i in range(10))
    mesh = sh.make_solver_mesh()
    n_max = 128
    got = sh._pallas_v2_multi(mesh, arrays, n_max=n_max)
    ref = sh._packed_multi(*[jax.device_put(a) for a in arrays], n_max=n_max)
    for name in ("assignment", "node_sig", "node_host", "node_req", "n_nodes"):
        np.testing.assert_array_equal(
            np.asarray(getattr(got, name)), np.asarray(getattr(ref, name)),
            err_msg=name,
        )
