"""Pallas packing kernel parity: identical PackResult to the lax.scan kernel
on real encoded batches. Runs only on a TPU backend — the CI suite (CPU mesh)
exercises the lax.scan path, which pack_best selects there."""

import random

import numpy as np
import pytest

from karpenter_tpu.solver.pallas_kernel import BLOCK, pack_best, pallas_available

pytestmark = pytest.mark.skipif(
    not pallas_available(), reason="pallas pack needs a TPU backend"
)


def encoded_batch(n_pods, seed=42):
    from karpenter_tpu.cloudprovider.fake import instance_types
    from karpenter_tpu.cloudprovider.requirements import catalog_requirements
    from karpenter_tpu.kube.client import Cluster
    from karpenter_tpu.scheduling.ffd import daemon_overhead, sort_pods_ffd
    from karpenter_tpu.scheduling.topology import Topology
    from karpenter_tpu.solver import encode as enc
    from karpenter_tpu.testing import diverse_pods, make_provisioner

    catalog = sorted(instance_types(50), key=lambda it: it.effective_price())
    provisioner = make_provisioner(solver="tpu")
    c = provisioner.spec.constraints
    c.requirements = c.requirements.merge(catalog_requirements(catalog))
    pods = sort_pods_ffd(diverse_pods(n_pods, random.Random(seed)))
    cc = c.clone()
    Topology(Cluster(), rng=random.Random(1)).inject(cc, pods)
    daemon = daemon_overhead(Cluster(), cc)
    batch = enc.encode(cc, catalog, pods, daemon)
    return (
        batch.pod_valid, batch.pod_open_sig, batch.pod_core, batch.pod_host,
        batch.pod_host_in_base, batch.pod_open_host, batch.pod_req,
        batch.join_table, batch.frontiers, batch.daemon,
    )


@pytest.mark.parametrize("n_pods,n_max", [(100, 128), (500, 256), (1500, 512)])
def test_pallas_matches_lax_kernel(n_pods, n_max):
    import jax

    from karpenter_tpu.solver import kernel
    from karpenter_tpu.solver.pallas_kernel import pack_pallas

    args = encoded_batch(n_pods)
    assert args[6].shape[0] % BLOCK == 0
    ref = jax.device_get(tuple(kernel.pack(*args, n_max=n_max)))
    out = jax.device_get(tuple(pack_pallas(*args, n_max=n_max)))
    for name, a, b in zip(kernel.PackResult._fields, ref, out):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b), err_msg=name)


def test_pack_best_selects_a_working_kernel():
    import jax

    args = encoded_batch(200)
    result = pack_best(*args, n_max=128)
    n_nodes = int(np.asarray(jax.device_get(result.n_nodes)).reshape(-1)[0])
    assert n_nodes > 0
