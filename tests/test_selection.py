"""Selection controller tests (mirrors selection/suite_test.go): pod →
provisioner routing, preference relaxation, volume topology injection, and
unsupported-feature rejection."""

import pytest

from karpenter_tpu.api import labels as lbl
from karpenter_tpu.api.objects import (
    NodeSelectorRequirement,
    NodeSelectorTerm,
    PodAffinityTerm,
    PreferredSchedulingTerm,
    Taint,
    Toleration,
    TopologySpreadConstraint,
    Volume,
)
from karpenter_tpu.cloudprovider.fake import FakeCloudProvider, instance_types
from karpenter_tpu.controllers.provisioning import ProvisioningController
from karpenter_tpu.controllers.selection import (
    Preferences,
    SelectionController,
    validate,
)
from karpenter_tpu.kube.client import Cluster
from tests.factories import (
    make_pod,
    make_provisioner,
    make_pv,
    make_pvc,
    make_storage_class,
)


@pytest.fixture()
def env():
    cluster = Cluster()
    provider = FakeCloudProvider(instance_types(10))
    provisioning = ProvisioningController(cluster, provider, start_workers=False)
    selection = SelectionController(cluster, provisioning, wait=False)
    yield cluster, provisioning, selection
    provisioning.stop()


def drive(cluster, provisioning, selection, pod):
    """Reconcile the pod through selection, then run the chosen worker's
    provision loop synchronously (the ExpectProvisioned analog)."""
    cluster.create("pods", pod)
    result = selection.reconcile(pod.metadata.name, pod.metadata.namespace)
    for worker in provisioning.list_workers():
        worker.batcher.idle_duration = 0.01
        if worker.batcher.depth():
            worker.provision_once()
    return result


class TestRouting:
    def test_routes_to_matching_provisioner(self, env):
        cluster, provisioning, selection = env
        provisioning.apply(make_provisioner(name="default"))
        pod = make_pod(requests={"cpu": "1"})
        assert drive(cluster, provisioning, selection, pod) == 5.0
        assert pod.spec.node_name != ""

    def test_provisioners_tried_in_name_order(self, env):
        cluster, provisioning, selection = env
        # "a" has a taint the pod does not tolerate; "b" matches
        provisioning.apply(
            make_provisioner(name="a", taints=[Taint(key="dedicated", value="x")])
        )
        provisioning.apply(make_provisioner(name="b"))
        pod = make_pod(requests={"cpu": "1"})
        drive(cluster, provisioning, selection, pod)
        assert pod.spec.node_name != ""
        node = cluster.get("nodes", pod.spec.node_name, namespace="")
        assert node.metadata.labels[lbl.PROVISIONER_NAME_LABEL] == "b"

    def test_no_provisioner_matches_raises_for_retry(self, env):
        from karpenter_tpu.controllers.selection import NoProvisionerMatched

        cluster, provisioning, selection = env
        provisioning.apply(
            make_provisioner(name="a", taints=[Taint(key="dedicated", value="x")])
        )
        pod = make_pod(requests={"cpu": "1"})
        cluster.create("pods", pod)
        with pytest.raises(NoProvisionerMatched):
            selection.reconcile(pod.metadata.name)
        assert pod.spec.node_name == ""

    def test_no_workers_is_a_noop(self, env):
        cluster, _, selection = env
        pod = make_pod(requests={"cpu": "1"})
        cluster.create("pods", pod)
        assert selection.reconcile(pod.metadata.name) == 5.0
        assert pod.spec.node_name == ""

    def test_scheduled_pod_ignored(self, env):
        cluster, provisioning, selection = env
        provisioning.apply(make_provisioner())
        pod = make_pod(node_name="n1", unschedulable=False)
        assert drive(cluster, provisioning, selection, pod) is None

    def test_deleted_pod_ignored(self, env):
        _, _, selection = env
        assert selection.reconcile("nope") is None


class TestValidation:
    def test_unsupported_topology_key_rejected(self):
        pod = make_pod(
            topology=[TopologySpreadConstraint(topology_key="custom/key", max_skew=1)]
        )
        assert validate(pod)

    def test_required_pod_affinity_rejected_without_support(self):
        pod = make_pod(
            pod_requirements=[PodAffinityTerm(topology_key=lbl.TOPOLOGY_ZONE)]
        )
        assert validate(pod, allow_pod_affinity=False)
        assert not validate(pod, allow_pod_affinity=True)

    def test_pod_affinity_bad_topology_key_rejected_even_with_support(self):
        pod = make_pod(pod_requirements=[PodAffinityTerm(topology_key="rack")])
        assert validate(pod, allow_pod_affinity=True)

    def test_unsupported_node_selector_operator_rejected(self):
        pod = make_pod(
            node_requirements=[
                NodeSelectorRequirement(key=lbl.TOPOLOGY_ZONE, operator="Gt", values=["1"])
            ]
        )
        assert validate(pod)


class TestPreferences:
    def test_first_sighting_cached_not_relaxed(self):
        prefs = Preferences()
        pod = make_pod(
            node_preferences=[
                PreferredSchedulingTerm(
                    weight=1,
                    preference=NodeSelectorTerm(
                        match_expressions=[
                            NodeSelectorRequirement(
                                key=lbl.TOPOLOGY_ZONE, operator="In", values=["zone-1"]
                            )
                        ]
                    ),
                )
            ]
        )
        prefs.relax(pod)
        assert pod.spec.affinity.node_affinity.preferred  # untouched

    def test_second_round_removes_heaviest_preferred_term(self):
        prefs = Preferences()
        light = PreferredSchedulingTerm(
            weight=1,
            preference=NodeSelectorTerm(
                match_expressions=[
                    NodeSelectorRequirement(key=lbl.TOPOLOGY_ZONE, operator="In", values=["zone-1"])
                ]
            ),
        )
        heavy = PreferredSchedulingTerm(
            weight=10,
            preference=NodeSelectorTerm(
                match_expressions=[
                    NodeSelectorRequirement(key=lbl.TOPOLOGY_ZONE, operator="In", values=["zone-2"])
                ]
            ),
        )
        pod = make_pod(node_preferences=[light, heavy])
        prefs.relax(pod)
        prefs.relax(pod)
        remaining = pod.spec.affinity.node_affinity.preferred
        assert len(remaining) == 1
        assert remaining[0].weight == 1

    def test_required_or_terms_relaxed_one_at_a_time_keeping_last(self):
        prefs = Preferences()
        pod = make_pod()
        from karpenter_tpu.api.objects import Affinity, NodeAffinity

        pod.spec.affinity = Affinity(
            node_affinity=NodeAffinity(
                required=[
                    NodeSelectorTerm(
                        match_expressions=[
                            NodeSelectorRequirement(
                                key=lbl.TOPOLOGY_ZONE, operator="In", values=[z]
                            )
                        ]
                    )
                    for z in ("zone-1", "zone-2")
                ]
            )
        )
        prefs.relax(pod)  # cache
        prefs.relax(pod)  # removes first OR-term
        assert len(pod.spec.affinity.node_affinity.required) == 1
        assert pod.spec.affinity.node_affinity.required[0].match_expressions[0].values == ["zone-2"]
        prefs.relax(pod)  # cannot remove the last required term → tolerates PreferNoSchedule
        assert len(pod.spec.affinity.node_affinity.required) == 1
        assert any(
            t.operator == "Exists" and t.effect == "PreferNoSchedule"
            for t in pod.spec.tolerations
        )

    def test_relaxation_forgotten_after_ttl(self):
        now = [0.0]
        prefs = Preferences(clock=lambda: now[0])
        pod = make_pod(
            node_preferences=[
                PreferredSchedulingTerm(
                    weight=1,
                    preference=NodeSelectorTerm(
                        match_expressions=[
                            NodeSelectorRequirement(
                                key=lbl.TOPOLOGY_ZONE, operator="In", values=["zone-1"]
                            )
                        ]
                    ),
                )
            ]
        )
        prefs.relax(pod)
        now[0] = 301.0
        prefs.relax(pod)  # cache expired → treated as first sighting again
        assert pod.spec.affinity.node_affinity.preferred

    def test_preferences_enable_scheduling_end_to_end(self, env):
        """A pod preferring an unavailable zone schedules after relaxation
        (the reference's preferential-fallback behavior)."""
        cluster, provisioning, selection = env
        provisioning.apply(
            make_provisioner(
                requirements=[
                    NodeSelectorRequirement(
                        key=lbl.TOPOLOGY_ZONE, operator="In", values=["test-zone-1"]
                    )
                ]
            )
        )
        pod = make_pod(
            requests={"cpu": "1"},
            node_preferences=[
                PreferredSchedulingTerm(
                    weight=1,
                    preference=NodeSelectorTerm(
                        match_expressions=[
                            NodeSelectorRequirement(
                                key=lbl.TOPOLOGY_ZONE, operator="In", values=["no-such-zone"]
                            )
                        ]
                    ),
                )
            ],
        )
        from karpenter_tpu.controllers.selection import NoProvisionerMatched

        cluster.create("pods", pod)
        # round 1: preference still present → no provisioner matches; the
        # raise drives the manager's backoff retry
        with pytest.raises(NoProvisionerMatched):
            selection.reconcile(pod.metadata.name)
        assert pod.spec.node_name == ""
        # round 2 (the retry): relaxed → schedules
        selection.reconcile(pod.metadata.name)
        for worker in provisioning.list_workers():
            worker.batcher.idle_duration = 0.01
            worker.provision_once()
        assert pod.spec.node_name != ""


class TestVolumeTopologyCacheIsolation:
    def test_repeated_rounds_do_not_accumulate_injected_requirements(self, env):
        """The preference cache must not alias the pod's affinity: volume
        topology injection would otherwise grow the cached terms each retry."""
        cluster, provisioning, selection = env
        cluster.create("pvs", make_pv(name="pv-x", zones=["test-zone-1"]))
        cluster.create("pvcs", make_pvc(name="claim-x", volume_name="pv-x"))
        pod = make_pod(
            requests={"cpu": "1"},
            node_requirements=[
                NodeSelectorRequirement(key=lbl.TOPOLOGY_ZONE, operator="In", values=["test-zone-1"])
            ],
        )
        pod.spec.volumes = [Volume(name="v", persistent_volume_claim="claim-x")]
        cluster.create("pods", pod)
        for _ in range(4):
            selection.preferences.relax(pod)
            selection.volume_topology.inject(pod)
        n_terms = [
            len(t.match_expressions) for t in pod.spec.affinity.node_affinity.required
        ]
        assert max(n_terms) <= 2  # original + one injected, never compounding


class TestVolumeTopology:
    def test_bound_pv_zone_injected(self, env):
        cluster, provisioning, selection = env
        provisioning.apply(make_provisioner())
        cluster.create("pvs", make_pv(name="pv-a", zones=["test-zone-2"]))
        cluster.create("pvcs", make_pvc(name="claim-a", volume_name="pv-a"))
        pod = make_pod(requests={"cpu": "1"})
        pod.spec.volumes = [Volume(name="data", persistent_volume_claim="claim-a")]
        drive(cluster, provisioning, selection, pod)
        assert pod.spec.node_name != ""
        node = cluster.get("nodes", pod.spec.node_name, namespace="")
        assert node.metadata.labels[lbl.TOPOLOGY_ZONE] == "test-zone-2"

    def test_unbound_pvc_storage_class_topology_injected(self, env):
        cluster, provisioning, selection = env
        provisioning.apply(make_provisioner())
        cluster.create("storageclasses", make_storage_class(name="fast", zones=["test-zone-3"]))
        cluster.create("pvcs", make_pvc(name="claim-b", storage_class="fast"))
        pod = make_pod(requests={"cpu": "1"})
        pod.spec.volumes = [Volume(name="data", persistent_volume_claim="claim-b")]
        drive(cluster, provisioning, selection, pod)
        assert pod.spec.node_name != ""
        node = cluster.get("nodes", pod.spec.node_name, namespace="")
        assert node.metadata.labels[lbl.TOPOLOGY_ZONE] == "test-zone-3"


class TestProvisionerRouting:
    """reference: selection/suite_test.go — alphabetical priority among
    matching provisioners, and a PreferNoSchedule-tainted provisioner loses
    to an untainted match (the pod would need the final relaxation rung to
    tolerate it)."""

    def _controller(self, cluster, provider, *provs):
        from karpenter_tpu.controllers.provisioning import ProvisioningController

        controller = ProvisioningController(cluster, provider, start_workers=False)
        for p in provs:
            cluster.create("provisioners", p)
            controller.reconcile(p.metadata.name)
        return controller

    def test_alphabetical_priority_among_matches(self):
        from karpenter_tpu.cloudprovider.fake import FakeCloudProvider, instance_types
        from karpenter_tpu.controllers.selection import SelectionController

        cluster = Cluster()
        provider = FakeCloudProvider(instance_types(5))
        controller = self._controller(
            cluster, provider,
            make_provisioner(name="zeta"), make_provisioner(name="alpha"),
        )
        selection = SelectionController(cluster, controller, wait=False)
        pod = make_pod(requests={"cpu": "0.5"})
        cluster.create("pods", pod)
        assert selection.select_provisioner(pod) is True
        assert controller.workers["alpha"].is_pending(pod.key)
        assert not controller.workers["zeta"].is_pending(pod.key)

    def test_prefer_no_schedule_taint_loses_to_untainted_match(self):
        from karpenter_tpu.api.objects import Taint
        from karpenter_tpu.cloudprovider.fake import FakeCloudProvider, instance_types
        from karpenter_tpu.controllers.selection import SelectionController

        cluster = Cluster()
        provider = FakeCloudProvider(instance_types(5))
        controller = self._controller(
            cluster, provider,
            make_provisioner(
                name="aaa-tainted",
                taints=[Taint(key="soft", value="x", effect="PreferNoSchedule")],
            ),
            make_provisioner(name="bbb-clean"),
        )
        selection = SelectionController(cluster, controller, wait=False)
        pod = make_pod(requests={"cpu": "0.5"})
        cluster.create("pods", pod)
        assert selection.select_provisioner(pod) is True
        # alphabetically first but tainted -> skipped without relaxation
        assert controller.workers["bbb-clean"].is_pending(pod.key)
        assert not controller.workers["aaa-tainted"].is_pending(pod.key)
