"""Webhook HTTP admission server, leader-election lease, and fleet-path flow
control (reference: cmd/webhook process, leader election main.go:84-85, and
the CreateFleet rate budget instance.go:43-49)."""

import json
import socket
import urllib.request

import pytest

from karpenter_tpu.cloudprovider.simulated import SimCloudAPI, SimulatedCloudProvider
from karpenter_tpu.utils.lease import FileLease, LeaderElector

try:  # the self-managed TLS stack (kube/certs.py) needs cryptography
    import cryptography  # noqa: F401

    _HAS_CRYPTO = True
except ImportError:
    _HAS_CRYPTO = False

# Skip (not fail) the TLS-dependent tests where `cryptography` is absent
# (the hermetic CPU test image) so tier-1 runs green; CI's envtest/image
# jobs install it and run these for real. Tracked in ROADMAP.md ("webhook
# TLS suite needs cryptography").
requires_crypto = pytest.mark.skipif(
    not _HAS_CRYPTO,
    reason="cryptography not installed: webhook TLS tests skipped "
    "(tracked in ROADMAP.md; CI envtest installs it)",
)
from karpenter_tpu.webhook import (
    Webhook,
    deserialize_provisioner,
    serialize_provisioner,
    serve,
)
from tests.factories import make_provisioner


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def post(url, doc):
    req = urllib.request.Request(
        url, data=json.dumps(doc).encode(), headers={"Content-Type": "application/json"}
    )
    with urllib.request.urlopen(req, timeout=5) as resp:
        return json.loads(resp.read())


@pytest.fixture()
def server():
    address = f"127.0.0.1:{free_port()}"
    webhook = Webhook(SimulatedCloudProvider(), default_solver="tpu")
    srv = serve(webhook, address)
    yield f"http://{address}"
    srv.shutdown()


class TestWebhookServer:
    def test_round_trip_serialization(self):
        prov = make_provisioner(
            labels={"team": "a"}, ttl_after_empty=30, limits={"cpu": "100"}, solver="tpu"
        )
        doc = serialize_provisioner(prov)
        back = deserialize_provisioner(doc)
        assert back.spec.constraints.labels == {"team": "a"}
        assert back.spec.ttl_seconds_after_empty == 30
        assert back.spec.limits.resources == {"cpu": 100}
        assert back.spec.solver == "tpu"

    def test_default_resource_endpoint(self, server):
        doc = serialize_provisioner(make_provisioner())
        doc["spec"]["solver"] = ""
        out = post(f"{server}/default-resource", doc)
        assert out["spec"]["solver"] == "tpu"  # process default applied
        keys = {r["key"] for r in out["spec"]["requirements"]}
        assert "karpenter.sh/capacity-type" in keys  # vendor hook applied

    def test_validate_resource_accepts_good_spec(self, server):
        out = post(f"{server}/validate-resource", serialize_provisioner(make_provisioner()))
        assert out["allowed"] is True

    def test_validate_resource_rejects_bad_spec(self, server):
        doc = serialize_provisioner(make_provisioner())
        doc["spec"]["ttlSecondsAfterEmpty"] = -5
        out = post(f"{server}/validate-resource", doc)
        assert out["allowed"] is False
        assert out["errors"]

    def test_healthz(self, server):
        with urllib.request.urlopen(f"{server}/healthz", timeout=5) as resp:
            assert resp.status == 200


class TestLease:
    def test_single_holder(self, tmp_path):
        path = str(tmp_path / "lease")
        a = FileLease(path, identity="a", duration=10)
        b = FileLease(path, identity="b", duration=10)
        assert a.try_acquire()
        assert not b.try_acquire()
        assert a.holder() == "a"

    def test_takeover_after_expiry(self, tmp_path):
        now = [100.0]
        path = str(tmp_path / "lease")
        a = FileLease(path, identity="a", duration=10, clock=lambda: now[0])
        b = FileLease(path, identity="b", duration=10, clock=lambda: now[0])
        assert a.try_acquire()
        now[0] += 11  # a stopped renewing
        assert b.try_acquire()
        assert b.holder() == "b"
        assert not a.renew()  # a lost it

    def test_release(self, tmp_path):
        path = str(tmp_path / "lease")
        a = FileLease(path, identity="a")
        assert a.try_acquire()
        a.release()
        assert a.holder() is None

    def test_elector_acquires_and_releases(self, tmp_path):
        path = str(tmp_path / "lease")
        elector = LeaderElector(FileLease(path, identity="x"), renew_interval=0.05)
        elector.start()
        assert elector.wait_for_leadership(timeout=5)
        assert elector.is_leader
        elector.stop()
        assert FileLease(path, identity="y").try_acquire()


class TestFleetFlowControl:
    def test_describe_retry_survives_transient_inconsistency(self):
        from karpenter_tpu.api.provisioner import Constraints
        from karpenter_tpu.cloudprovider.requirements import catalog_requirements
        from karpenter_tpu.cloudprovider.simulated import CloudAPIError
        from karpenter_tpu.cloudprovider.types import NodeRequest

        api = SimCloudAPI()
        provider = SimulatedCloudProvider(api)
        catalog = provider.get_instance_types()
        c = Constraints()
        provider.default(c)
        c.requirements = c.requirements.merge(catalog_requirements(catalog))
        # first describe fails (eventual consistency); the retry succeeds
        api.inject_error("describe_instances", CloudAPIError("not yet visible"))
        node = provider.create(NodeRequest(template=c, instance_type_options=catalog))
        assert node.metadata.name.startswith("i-")

    def test_fleet_limiter_wired(self):
        provider = SimulatedCloudProvider(SimCloudAPI())
        limiter = provider.instance_provider.fleet_limiter
        assert limiter.qps == 2.0 and limiter.burst == 100


@requires_crypto
class TestWebhookTLS:
    """Admission over HTTPS with the self-managed serving cert — what a
    real apiserver requires (VERDICT r1 missing #2)."""

    @pytest.fixture()
    def tls_server(self, tmp_path):
        import socket

        from karpenter_tpu.cloudprovider.fake import FakeCloudProvider, instance_types
        from karpenter_tpu.kube.certs import ensure_serving_cert
        from karpenter_tpu.webhook import Webhook, serve

        s = socket.socket(); s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]; s.close()
        cert, key, ca = ensure_serving_cert(
            str(tmp_path), ["localhost", "karpenter-tpu-webhook.karpenter.svc"]
        )
        webhook = Webhook(FakeCloudProvider(instance_types(4)), default_solver="tpu")
        server = serve(webhook, f"127.0.0.1:{port}", tls_cert=cert, tls_key=key)
        yield port, ca
        server.shutdown()

    def _post(self, port, ca, path, body):
        import json
        import ssl
        import urllib.request

        ctx = ssl.create_default_context(cafile=ca)
        ctx.check_hostname = False  # IP connect; cert carries DNS SANs
        req = urllib.request.Request(
            f"https://127.0.0.1:{port}{path}",
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, context=ctx) as resp:
            return json.loads(resp.read())

    def _review(self, obj):
        return {
            "apiVersion": "admission.k8s.io/v1",
            "kind": "AdmissionReview",
            "request": {"uid": "test-uid-1", "object": obj},
        }

    def test_cert_reused_and_rotated(self, tmp_path):
        from karpenter_tpu.kube.certs import ensure_serving_cert

        c1 = ensure_serving_cert(str(tmp_path), ["localhost"])
        with open(c1[0], "rb") as f:
            pem1 = f.read()
        c2 = ensure_serving_cert(str(tmp_path), ["localhost"])  # reuse
        with open(c2[0], "rb") as f:
            assert f.read() == pem1
        c3 = ensure_serving_cert(str(tmp_path), ["other-name"])  # SAN change
        with open(c3[0], "rb") as f:
            assert f.read() != pem1

    def test_mutating_review_returns_defaulting_patch(self, tls_server):
        import base64
        import json

        port, ca = tls_server
        obj = {
            "apiVersion": "karpenter.sh/v1alpha5",
            "kind": "Provisioner",
            "metadata": {"name": "default"},
            "spec": {},
        }
        out = self._post(port, ca, "/default-resource", self._review(obj))
        resp = out["response"]
        assert resp["uid"] == "test-uid-1" and resp["allowed"] is True
        patch = json.loads(base64.b64decode(resp["patch"]))
        assert patch[0]["path"] == "/spec"
        assert patch[0]["value"]["solver"] == "tpu"  # process default applied

    def test_validating_review_denies_bad_spec(self, tls_server):
        port, ca = tls_server
        bad = {
            "apiVersion": "karpenter.sh/v1alpha5",
            "kind": "Provisioner",
            "metadata": {"name": "default"},
            "spec": {"solver": "bogus"},
        }
        out = self._post(port, ca, "/validate-resource", self._review(bad))
        assert out["response"]["allowed"] is False
        assert "solver" in out["response"]["status"]["message"]

    def test_validating_review_allows_good_spec(self, tls_server):
        port, ca = tls_server
        good = {
            "apiVersion": "karpenter.sh/v1alpha5",
            "kind": "Provisioner",
            "metadata": {"name": "default"},
            "spec": {"solver": "tpu"},
        }
        out = self._post(port, ca, "/validate-resource", self._review(good))
        assert out["response"]["allowed"] is True

    def test_manifest_cabundle_placeholder_renders(self, tls_server):
        """deploy/webhook.yaml's ${CA_BUNDLE} substitutes to the generated
        CA (the make webhook-cabundle flow)."""
        port, ca = tls_server
        from karpenter_tpu.kube.certs import ca_bundle_b64

        with open("deploy/webhook.yaml") as f:
            manifest = f.read()
        rendered = manifest.replace("${CA_BUNDLE}", ca_bundle_b64(ca))
        assert "${CA_BUNDLE}" not in rendered
        assert "caBundle: LS0t" in rendered  # base64 of '-----BEGIN...'


class TestChartAndPackaging:
    def test_chart_renders_all_components(self):
        """hack/render_chart.py over charts/karpenter-tpu produces valid
        YAML with the controller/solver/webhook wired together."""
        import subprocess
        import sys

        out = subprocess.run(
            [sys.executable, "hack/render_chart.py", "charts/karpenter-tpu"],
            capture_output=True, text=True, check=True,
        ).stdout
        import yaml

        docs = [d for doc in out.split("\n---\n") for d in yaml.safe_load_all(doc) if d]
        kinds = sorted(d["kind"] for d in docs)
        assert kinds.count("Deployment") == 2  # controller, webhook
        # the solver pool is a StatefulSet: ring routing needs stable
        # per-member addresses (docs/fleet.md)
        assert kinds.count("StatefulSet") == 1
        assert "CustomResourceDefinition" in kinds
        assert "ClusterRole" in kinds
        # the controller points at the solver pool members
        controller = next(
            d for d in docs
            if d["kind"] == "Deployment" and "controller" in d["metadata"]["name"]
        )
        args = controller["spec"]["template"]["spec"]["containers"][0]["args"]
        assert any("solver-service-address=karpenter-tpu-solver" in a for a in args)
        assert any("kube-api-server=in-cluster" in a for a in args)
        # fleet mode by default: shard leases on, whole-process election off
        assert any(a.startswith("--shard-lease=kube:") for a in args)
        assert not any(a.startswith("--leader-election-lease") for a in args)
        # pack integrity on by default (docs/integrity.md): wire checksums
        # + the native canary cross-check rate render into the args
        assert "--pack-checksum" in args
        assert any(a.startswith("--canary-rate=0.05") for a in args)

    def test_chart_pack_checksum_gate(self):
        """packChecksum: false must drop the flag (checksum-off wires stay
        byte-identical for the perf-sensitive legs), while the canary rate
        keeps rendering independently."""
        import importlib.util
        from pathlib import Path

        spec = importlib.util.spec_from_file_location("rc", "hack/render_chart.py")
        rc = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(rc)
        values = rc.load_values(Path("charts/karpenter-tpu/values.yaml"))
        tpl = Path(
            "charts/karpenter-tpu/templates/controller-deployment.yaml"
        ).read_text()
        assert "--pack-checksum" in rc.render(tpl, values)
        values["controller"]["packChecksum"] = False
        out = rc.render(tpl, values)
        assert "--pack-checksum" not in out
        assert "--canary-rate=0.05" in out

    def test_chart_gates_render_conditionally(self):
        import subprocess
        import sys

        out = subprocess.run(
            [sys.executable, "hack/render_chart.py", "charts/karpenter-tpu"],
            capture_output=True, text=True, check=True,
        ).stdout
        assert "ServiceMonitor" not in out  # disabled by default

    def test_dockerfile_covers_all_entrypoints(self):
        with open("Dockerfile") as f:
            content = f.read()
        assert "karpenter_tpu.main" in content
        assert "libffd_pack.so" in content  # native packer prebuilt
        with open("deploy/solver.yaml") as f:
            assert "karpenter_tpu.solver.service" in f.read()
        with open("deploy/webhook.yaml") as f:
            assert "karpenter_tpu.webhook" in f.read()

    def test_chart_webhook_registrations_gated_on_cabundle(self):
        """Registrations render only with a caBundle (an empty bundle with
        failurePolicy: Fail would reject every Provisioner write); when set,
        both configurations appear with the bundle injected."""
        import importlib.util
        from pathlib import Path

        spec = importlib.util.spec_from_file_location("rc", "hack/render_chart.py")
        rc = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(rc)
        values = rc.load_values(Path("charts/karpenter-tpu/values.yaml"))
        tpl = Path("charts/karpenter-tpu/templates/webhook.yaml").read_text()
        assert "WebhookConfiguration" not in rc.render(tpl, values)
        values["webhook"]["caBundle"] = "LS0tCg=="
        out = rc.render(tpl, values)
        assert out.count("WebhookConfiguration") == 2
        assert "caBundle: LS0tCg==" in out

    @requires_crypto
    def test_ca_persists_across_leaf_rotation(self, tmp_path):
        """Leaf rotation re-signs under the stored CA so the registered
        caBundle stays valid (a fresh CA per restart would break apiserver
        TLS verification until the bundle is re-injected)."""
        from karpenter_tpu.kube.certs import ensure_serving_cert

        _, _, ca1 = ensure_serving_cert(str(tmp_path), ["localhost"])
        with open(ca1, "rb") as f:
            ca_pem = f.read()
        cert2, _, ca2 = ensure_serving_cert(str(tmp_path), ["rotated-name"])
        with open(ca2, "rb") as f:
            assert f.read() == ca_pem  # same CA
        # and the rotated leaf chains to it
        import ssl
        ctx = ssl.create_default_context(cafile=ca2)
        ctx.load_verify_locations(ca2)  # no exception = CA parses

    @requires_crypto
    def test_readonly_cert_dir_serves_existing_instead_of_crashing(self, tmp_path):
        """A Secret-mounted (read-only) cert dir that hits the rotation
        window must serve the existing cert, not crash-loop the webhook."""
        import os

        from karpenter_tpu.kube.certs import ensure_serving_cert

        d = tmp_path / "certs"
        ensure_serving_cert(str(d), ["localhost"])
        os.chmod(d, 0o555)  # secret volumes are read-only
        try:
            # force the rotation path via a SAN change
            cert, key, ca = ensure_serving_cert(str(d), ["changed-name"])
            assert os.path.exists(cert) and os.path.exists(ca)
        finally:
            os.chmod(d, 0o755)
