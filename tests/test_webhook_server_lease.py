"""Webhook HTTP admission server, leader-election lease, and fleet-path flow
control (reference: cmd/webhook process, leader election main.go:84-85, and
the CreateFleet rate budget instance.go:43-49)."""

import json
import socket
import urllib.request

import pytest

from karpenter_tpu.cloudprovider.simulated import SimCloudAPI, SimulatedCloudProvider
from karpenter_tpu.utils.lease import FileLease, LeaderElector
from karpenter_tpu.webhook import (
    Webhook,
    deserialize_provisioner,
    serialize_provisioner,
    serve,
)
from tests.factories import make_provisioner


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def post(url, doc):
    req = urllib.request.Request(
        url, data=json.dumps(doc).encode(), headers={"Content-Type": "application/json"}
    )
    with urllib.request.urlopen(req, timeout=5) as resp:
        return json.loads(resp.read())


@pytest.fixture()
def server():
    address = f"127.0.0.1:{free_port()}"
    webhook = Webhook(SimulatedCloudProvider(), default_solver="tpu")
    srv = serve(webhook, address)
    yield f"http://{address}"
    srv.shutdown()


class TestWebhookServer:
    def test_round_trip_serialization(self):
        prov = make_provisioner(
            labels={"team": "a"}, ttl_after_empty=30, limits={"cpu": "100"}, solver="tpu"
        )
        doc = serialize_provisioner(prov)
        back = deserialize_provisioner(doc)
        assert back.spec.constraints.labels == {"team": "a"}
        assert back.spec.ttl_seconds_after_empty == 30
        assert back.spec.limits.resources == {"cpu": 100}
        assert back.spec.solver == "tpu"

    def test_default_resource_endpoint(self, server):
        doc = serialize_provisioner(make_provisioner())
        doc["spec"]["solver"] = ""
        out = post(f"{server}/default-resource", doc)
        assert out["spec"]["solver"] == "tpu"  # process default applied
        keys = {r["key"] for r in out["spec"]["requirements"]}
        assert "karpenter.sh/capacity-type" in keys  # vendor hook applied

    def test_validate_resource_accepts_good_spec(self, server):
        out = post(f"{server}/validate-resource", serialize_provisioner(make_provisioner()))
        assert out["allowed"] is True

    def test_validate_resource_rejects_bad_spec(self, server):
        doc = serialize_provisioner(make_provisioner())
        doc["spec"]["ttlSecondsAfterEmpty"] = -5
        out = post(f"{server}/validate-resource", doc)
        assert out["allowed"] is False
        assert out["errors"]

    def test_healthz(self, server):
        with urllib.request.urlopen(f"{server}/healthz", timeout=5) as resp:
            assert resp.status == 200


class TestLease:
    def test_single_holder(self, tmp_path):
        path = str(tmp_path / "lease")
        a = FileLease(path, identity="a", duration=10)
        b = FileLease(path, identity="b", duration=10)
        assert a.try_acquire()
        assert not b.try_acquire()
        assert a.holder() == "a"

    def test_takeover_after_expiry(self, tmp_path):
        now = [100.0]
        path = str(tmp_path / "lease")
        a = FileLease(path, identity="a", duration=10, clock=lambda: now[0])
        b = FileLease(path, identity="b", duration=10, clock=lambda: now[0])
        assert a.try_acquire()
        now[0] += 11  # a stopped renewing
        assert b.try_acquire()
        assert b.holder() == "b"
        assert not a.renew()  # a lost it

    def test_release(self, tmp_path):
        path = str(tmp_path / "lease")
        a = FileLease(path, identity="a")
        assert a.try_acquire()
        a.release()
        assert a.holder() is None

    def test_elector_acquires_and_releases(self, tmp_path):
        path = str(tmp_path / "lease")
        elector = LeaderElector(FileLease(path, identity="x"), renew_interval=0.05)
        elector.start()
        assert elector.wait_for_leadership(timeout=5)
        assert elector.is_leader
        elector.stop()
        assert FileLease(path, identity="y").try_acquire()


class TestFleetFlowControl:
    def test_describe_retry_survives_transient_inconsistency(self):
        from karpenter_tpu.api.provisioner import Constraints
        from karpenter_tpu.cloudprovider.requirements import catalog_requirements
        from karpenter_tpu.cloudprovider.simulated import CloudAPIError
        from karpenter_tpu.cloudprovider.types import NodeRequest

        api = SimCloudAPI()
        provider = SimulatedCloudProvider(api)
        catalog = provider.get_instance_types()
        c = Constraints()
        provider.default(c)
        c.requirements = c.requirements.merge(catalog_requirements(catalog))
        # first describe fails (eventual consistency); the retry succeeds
        api.inject_error("describe_instances", CloudAPIError("not yet visible"))
        node = provider.create(NodeRequest(template=c, instance_type_options=catalog))
        assert node.metadata.name.startswith("i-")

    def test_fleet_limiter_wired(self):
        provider = SimulatedCloudProvider(SimCloudAPI())
        limiter = provider.instance_provider.fleet_limiter
        assert limiter.qps == 2.0 and limiter.burst == 100
