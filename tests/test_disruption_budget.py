"""Disruption-budget arithmetic and enforcement (docs/consolidation.md):
the budget grammar, the PDB-style percent resolution, the cross-wave
ledger, and the consolidation controller honoring all of it — per wave
AND across concurrently-settling waves."""

import pytest

from karpenter_tpu.api import labels as lbl
from karpenter_tpu.api.objects import OwnerReference
from karpenter_tpu.cloudprovider.fake import FakeCloudProvider, instance_types
from karpenter_tpu.cloudprovider.requirements import catalog_requirements
from karpenter_tpu.controllers.consolidation import ConsolidationController
from karpenter_tpu.controllers.disruption import (
    BudgetLedger,
    parse_budget,
    resolve_budget,
)
from karpenter_tpu.controllers.provisioning import REQUEUE_INTERVAL
from karpenter_tpu.kube.client import Cluster
from tests.factories import make_node, make_pod, make_provisioner


class TestParseBudget:
    def test_counts_and_percents_normalize(self):
        assert parse_budget("3") == "3"
        assert parse_budget("20%") == "20%"
        assert parse_budget(" 20% ") == "20%"
        assert parse_budget("007") == "7"

    def test_unset_is_none(self):
        assert parse_budget(None) is None
        assert parse_budget("") is None
        assert parse_budget("   ") is None

    def test_zero_is_preserved_not_none(self):
        # "0" is the explicit off switch — it must survive normalization,
        # not collapse into "unset"
        assert parse_budget("0") == "0"
        assert parse_budget("0%") == "0%"

    @pytest.mark.parametrize("bad", ["abc", "-1", "-5%", "150%", "1.5", "3%%"])
    def test_garbage_fails_admission(self, bad):
        # a typo'd budget must fail validation, not silently disable the
        # safety layer
        with pytest.raises(ValueError):
            parse_budget(bad)


class TestResolveBudget:
    def test_count_is_absolute(self):
        assert resolve_budget("3", 10) == 3
        assert resolve_budget("3", 2) == 3  # count may exceed the cluster

    def test_percent_rounds_up_like_pdb(self):
        # intstr.GetScaledValueFromIntOrPercent with roundUp=true
        assert resolve_budget("20%", 10) == 2
        assert resolve_budget("25%", 10) == 3  # ceil(2.5)
        assert resolve_budget("50%", 3) == 2  # ceil(1.5)

    def test_small_cluster_never_rounds_to_zero(self):
        # a non-zero percent on a non-empty cluster must pace disruption,
        # not quietly become the off switch
        assert resolve_budget("1%", 3) == 1
        assert resolve_budget("10%", 1) == 1

    def test_zero_disables(self):
        assert resolve_budget("0", 10) == 0
        assert resolve_budget("0%", 10) == 0

    def test_empty_cluster_allows_nothing(self):
        assert resolve_budget("20%", 0) == 0

    def test_unset_is_none(self):
        assert resolve_budget(None, 10) is None


class TestBudgetLedger:
    def test_reserve_admits_prefix_up_to_allowed(self):
        ledger = BudgetLedger()
        # prefix, not arbitrary subset: callers pass victims
        # cheapest-disruption-first and the admitted set honors that order
        assert ledger.reserve("p", ["a", "b", "c", "d"], 2) == ["a", "b"]
        assert ledger.in_flight("p") == 2

    def test_concurrent_waves_share_one_account(self):
        ledger = BudgetLedger()
        assert ledger.reserve("p", ["a", "b"], 3) == ["a", "b"]
        # a second wave of the SAME provisioner draws from the same
        # account: only one more slot left
        assert ledger.reserve("p", ["c", "d"], 3) == ["c"]
        # other provisioners have their own account
        assert ledger.reserve("q", ["x", "y"], 3) == ["x", "y"]
        assert ledger.in_flight("p") == 3
        assert ledger.in_flight("q") == 2

    def test_already_held_names_do_not_double_count(self):
        ledger = BudgetLedger()
        ledger.reserve("p", ["a"], 2)
        # re-reserving a held victim is a no-op, not a second slot
        assert ledger.reserve("p", ["a", "b"], 2) == ["b"]
        assert ledger.in_flight("p") == 2

    def test_release_returns_capacity(self):
        ledger = BudgetLedger()
        ledger.reserve("p", ["a", "b"], 2)
        assert ledger.reserve("p", ["c"], 2) == []
        ledger.release("p", ["a"])  # partial settle (out-of-band delete)
        assert ledger.reserve("p", ["c"], 2) == ["c"]
        ledger.release("p", ["b", "c"])
        assert ledger.in_flight("p") == 0

    def test_release_unknown_is_harmless(self):
        ledger = BudgetLedger()
        ledger.release("p", ["never-reserved"])
        assert ledger.in_flight("p") == 0

    def test_zero_allowed_admits_nothing(self):
        ledger = BudgetLedger()
        assert ledger.reserve("p", ["a"], 0) == []
        assert ledger.in_flight("p") == 0


def evict_env(n_nodes, budget=None, default_budget=None, ledger=None):
    """An evict-mode controller over a fragmented cluster whose plan would
    happily retire everything — the budget is the only brake under test."""
    cluster = Cluster()
    provider = FakeCloudProvider(instance_types(20))
    provisioner = make_provisioner(solver="ffd")
    provisioner.spec.disruption_budget = budget
    c = provisioner.spec.constraints
    c.requirements = c.requirements.merge(
        catalog_requirements(provider.get_instance_types())
    )
    cluster.create("provisioners", provisioner)
    controller = ConsolidationController(
        cluster, provider, migration="evict",
        ledger=ledger, default_budget=default_budget,
    )
    owner = OwnerReference(api_version="apps/v1", kind="ReplicaSet", name="rs")
    for i in range(n_nodes):
        node = make_node(
            name=f"big-{i}",
            capacity={"cpu": "20", "memory": "40Gi", "pods": "200"},
            provisioner_name="default",
            labels={lbl.INSTANCE_TYPE: "fake-it-19",
                    lbl.TOPOLOGY_ZONE: "test-zone-1",
                    lbl.CAPACITY_TYPE: "on-demand"},
        )
        cluster.create("nodes", node)
        cluster.create(
            "pods",
            make_pod(name=f"pod-{i}", requests={"cpu": "0.5"},
                     node_name=node.metadata.name, unschedulable=False,
                     owner=owner),
        )
    return cluster, controller, provisioner


class TestControllerEnforcement:
    def test_count_budget_caps_the_wave(self):
        cluster, controller, provisioner = evict_env(20, budget="2")
        before = {n.metadata.name for n in cluster.nodes()}
        controller.reconcile("default")
        after = {n.metadata.name for n in cluster.nodes()}
        # wave size is 5, but the budget admits only 2
        assert len(before - after) == 2
        assert controller.budget_blocked == 3
        reasons = {e.reason for e in cluster.list("events")}
        assert "ConsolidationBudgetBlocked" in reasons

    def test_percent_budget_resolves_against_current_nodes(self):
        cluster, controller, provisioner = evict_env(20, budget="20%")
        before = {n.metadata.name for n in cluster.nodes()}
        controller.reconcile("default")
        after = {n.metadata.name for n in cluster.nodes()}
        # 20% of 20 nodes = 4 < wave size 5
        assert len(before - after) == 4
        assert controller.budget_blocked == 1

    def test_zero_budget_disables_without_planning(self):
        cluster, controller, provisioner = evict_env(8, budget="0")
        assert controller.reconcile("default") == REQUEUE_INTERVAL
        assert len(cluster.nodes()) == 8  # nothing retired
        assert controller.waves_executed == 0

    def test_controller_default_applies_when_spec_unset(self):
        cluster, controller, provisioner = evict_env(20, default_budget="1")
        before = {n.metadata.name for n in cluster.nodes()}
        controller.reconcile("default")
        after = {n.metadata.name for n in cluster.nodes()}
        assert len(before - after) == 1

    def test_provisioner_spec_wins_over_default(self):
        cluster, controller, provisioner = evict_env(
            20, budget="3", default_budget="1"
        )
        before = {n.metadata.name for n in cluster.nodes()}
        controller.reconcile("default")
        after = {n.metadata.name for n in cluster.nodes()}
        assert len(before - after) == 3

    def test_unbudgeted_wave_still_paced_by_wave_size(self):
        from karpenter_tpu.controllers.consolidation import EVICT_WAVE_SIZE

        cluster, controller, provisioner = evict_env(20)
        before = {n.metadata.name for n in cluster.nodes()}
        controller.reconcile("default")
        after = {n.metadata.name for n in cluster.nodes()}
        assert len(before - after) == EVICT_WAVE_SIZE

    def test_concurrent_waves_draw_from_one_budget(self):
        # two replicas (two controller instances) sharing one ledger, as
        # the fleet does during a shard rebalance: their in-flight waves
        # must never exceed the budget COMBINED
        ledger = BudgetLedger()
        cluster, first, provisioner = evict_env(20, budget="3", ledger=ledger)
        second = ConsolidationController(
            cluster, first.cloud_provider, migration="evict", ledger=ledger
        )
        before = {n.metadata.name for n in cluster.nodes()}
        first.reconcile("default")
        after_first = {n.metadata.name for n in cluster.nodes()}
        assert len(before - after_first) == 3  # first wave took the budget
        # the first wave has NOT settled; the second replica reconciles
        second.reconcile("default")
        after_second = {n.metadata.name for n in cluster.nodes()}
        # the shared account is exhausted — zero additional disruption
        assert after_second == after_first

    def test_budget_survives_serde_round_trip(self):
        from karpenter_tpu.kube.serde import (
            _provisioner_from_wire,
            _provisioner_to_wire,
        )

        p = make_provisioner()
        p.spec.disruption_budget = "20%"
        wire = _provisioner_to_wire(p)
        assert wire["spec"]["disruptionBudget"] == "20%"
        back = _provisioner_from_wire(wire)
        assert back.spec.disruption_budget == "20%"
        # unset stays unset (not "" — "" would read as "budget configured")
        p.spec.disruption_budget = None
        assert _provisioner_from_wire(
            _provisioner_to_wire(p)
        ).spec.disruption_budget is None

    def test_admission_rejects_bad_budget(self):
        from karpenter_tpu.api.provisioner import validate_provisioner

        p = make_provisioner()
        p.spec.disruption_budget = "lots"
        assert any("disruptionBudget" in e for e in validate_provisioner(p))
        p.spec.disruption_budget = "20%"
        assert not any(
            "disruptionBudget" in e for e in validate_provisioner(p)
        )

    def test_options_flag_parses_and_validates(self):
        from karpenter_tpu.options import Options, parse_args

        opts = parse_args(["--consolidation-budget", "20%"])
        assert opts.consolidation_budget == "20%"
        bad = Options(consolidation_budget="banana")
        assert any("consolidation budget" in e for e in bad.validate())
        assert not any(
            "consolidation budget" in e
            for e in Options(consolidation_budget="3").validate()
        )

    def test_settled_wave_releases_the_budget(self):
        cluster, controller, provisioner = evict_env(6, budget="2")
        controller.reconcile("default")
        # the wave is in flight: its victims hold the budget
        assert controller.ledger.in_flight("default") == 2
        # the legacy delete path removed the victims outright and no
        # displaced pod is pending beyond the baseline — the wave settles
        # and the budget flows back to the account
        assert controller.wave_settled("default") is True
        assert controller.ledger.in_flight("default") == 0
