"""Solver sidecar pool tests (solver/pool.py): consistent-hash session
affinity, per-member breakers, ring failover, the NEEDS_CATALOG re-upload
on a DIFFERENT member, and the TpuScheduler integration — a dead member
degrades capacity, a dead pool degrades to the in-process kernel, and the
FFD floor still schedules everything."""

import random
import socket

import numpy as np
import pytest

from karpenter_tpu.solver.pool import HashRing, PoolExhausted, SolverPool

pytestmark = pytest.mark.fleet

grpc = pytest.importorskip("grpc")


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _encoded_batch(n_pods=8, n_types=8, seed=0):
    from karpenter_tpu.cloudprovider.fake import instance_types
    from karpenter_tpu.cloudprovider.requirements import catalog_requirements
    from karpenter_tpu.kube.client import Cluster
    from karpenter_tpu.scheduling.ffd import daemon_overhead, sort_pods_ffd
    from karpenter_tpu.scheduling.topology import Topology
    from karpenter_tpu.solver import encode as enc
    from karpenter_tpu.testing import make_pod, make_provisioner

    catalog = sorted(
        instance_types(n_types), key=lambda it: it.effective_price()
    )
    provisioner = make_provisioner(solver="tpu")
    constraints = provisioner.spec.constraints
    constraints.requirements = constraints.requirements.merge(
        catalog_requirements(catalog)
    )
    pods = sort_pods_ffd(
        [make_pod(requests={"cpu": "0.5"}) for _ in range(n_pods)]
    )
    cluster = Cluster()
    Topology(cluster, rng=random.Random(seed)).inject(constraints, pods)
    daemon = daemon_overhead(cluster, constraints)
    batch = enc.encode(constraints, catalog, pods, daemon)
    return batch, constraints, catalog, pods


def _pack_args(batch):
    return tuple(np.asarray(a) for a in batch.pack_args())


class TestHashRing:
    def test_deterministic_and_covers_members(self):
        ring = HashRing(["a:1", "b:1", "c:1"])
        key = b"\x01" * 16
        assert ring.ordered(key) == ring.ordered(key)
        assert set(ring.ordered(key)) == {"a:1", "b:1", "c:1"}

    def test_member_removal_moves_only_its_keys(self):
        members = ["a:1", "b:1", "c:1"]
        ring = HashRing(members)
        smaller = HashRing(["a:1", "c:1"])
        keys = [bytes([i]) * 16 for i in range(64)]
        for key in keys:
            before = ring.route(key)
            if before != "b:1":
                assert smaller.route(key) == before

    def test_distribution_roughly_even(self):
        ring = HashRing(["a:1", "b:1"])
        counts = {"a:1": 0, "b:1": 0}
        for i in range(512):
            counts[ring.route(i.to_bytes(4, "little") * 4)] += 1
        assert min(counts.values()) > 512 * 0.25

    def test_failover_order_starts_after_primary(self):
        ring = HashRing(["a:1", "b:1", "c:1"])
        key = b"\x07" * 16
        order = ring.ordered(key)
        assert order[0] == ring.route(key)
        assert len(order) == len(set(order)) == 3


class TestSolverPoolFailover:
    def _serve(self, address):
        from karpenter_tpu.solver.service import serve

        return serve(address)

    def test_routes_by_session_affinity_and_solves(self):
        from karpenter_tpu.solver import kernel

        addr_a = f"127.0.0.1:{free_port()}"
        addr_b = f"127.0.0.1:{free_port()}"
        server_a, server_b = self._serve(addr_a), self._serve(addr_b)
        try:
            batch, *_ = _encoded_batch()
            args = _pack_args(batch)
            n_max = len(batch.pod_valid)
            pool = SolverPool([addr_a, addr_b], timeout=30)
            result = pool.pack(*args, n_max=n_max)
            import jax

            local = jax.device_get(tuple(kernel.pack(*args, n_max=n_max)))
            for l, r in zip(local, tuple(result)):
                np.testing.assert_array_equal(np.asarray(l), np.asarray(r))
            # affinity: only the ROUTED member's store holds the session
            primary = pool.ring.route(pool._catalog_key(args[7:]))
            primary_srv = server_a if primary == addr_a else server_b
            other_srv = server_b if primary == addr_a else server_a
            assert primary_srv.solver_service.session_count() == 1
            assert other_srv.solver_service.session_count() == 0
            pool.close()
        finally:
            server_a.stop(grace=0)
            server_b.stop(grace=0)

    def test_dead_member_fails_over_through_the_ring(self):
        from karpenter_tpu import metrics as m

        addr_a = f"127.0.0.1:{free_port()}"
        addr_b = f"127.0.0.1:{free_port()}"
        server_a, server_b = self._serve(addr_a), self._serve(addr_b)
        servers = {addr_a: server_a, addr_b: server_b}
        try:
            batch, *_ = _encoded_batch()
            args = _pack_args(batch)
            n_max = len(batch.pod_valid)
            pool = SolverPool([addr_a, addr_b], timeout=5)
            pool.pack(*args, n_max=n_max)  # warm: session on the primary
            primary = pool.ring.route(pool._catalog_key(args[7:]))
            survivor = addr_b if primary == addr_a else addr_a

            def failovers():
                return m.REGISTRY.get_sample_value(
                    "karpenter_solver_pool_failovers_total",
                    {"address": primary},
                ) or 0.0

            before = failovers()
            servers[primary].stop(grace=0)  # SIGKILL the routed member
            result = pool.pack(*args, n_max=n_max)
            assert int(np.asarray(result[4]).reshape(-1)[0]) >= 1
            assert failovers() == before + 1
            # the survivor now holds the re-uploaded session
            assert servers[survivor].solver_service.session_count() == 1
            # and the dead member's breaker is open
            assert not pool._breaker(primary).available()
            assert pool.available_members() == [survivor]
            pool.close()
        finally:
            for s in servers.values():
                s.stop(grace=0)

    def test_needs_catalog_on_failover_member_reuploads_transparently(self):
        """The satellite scenario: the solve fails over to a member whose
        CLIENT remembers the session as open but whose server store is
        empty (restart) — NEEDS_CATALOG must re-upload on the NEW member,
        keep hit-rate accounting solve-true, and the old member's open
        breaker must not poison subsequent solves."""
        from karpenter_tpu.solver import session_stats

        addr_a = f"127.0.0.1:{free_port()}"
        addr_b = f"127.0.0.1:{free_port()}"
        server_a, server_b = self._serve(addr_a), self._serve(addr_b)
        servers = {addr_a: server_a, addr_b: server_b}
        try:
            batch, *_ = _encoded_batch()
            args = _pack_args(batch)
            n_max = len(batch.pod_valid)
            pool = SolverPool([addr_a, addr_b], timeout=5)
            key = pool._catalog_key(args[7:])
            primary = pool.ring.route(key)
            survivor = addr_b if primary == addr_a else addr_a
            pool.pack(*args, n_max=n_max)
            # open the session on the SURVIVOR too, then restart it: its
            # server store empties but the pool's client still remembers
            # the key as open — the classic restart-recovery skew
            pool._client(survivor)._open_session(key, args[7:], timeout=30)
            servers[survivor].stop(grace=0)
            from karpenter_tpu.solver.service import serve

            servers[survivor] = serve(survivor)
            assert servers[survivor].solver_service.session_count() == 0
            from karpenter_tpu import metrics as m

            def uploads():
                return m.REGISTRY.get_sample_value(
                    "karpenter_solver_session_catalog_uploads_total"
                ) or 0.0

            uploads_before = uploads()
            misses_before = session_stats.snapshot()["misses"]
            servers[primary].stop(grace=0)  # kill the routed member
            result = pool.pack(*args, n_max=n_max)
            assert int(np.asarray(result[4]).reshape(-1)[0]) >= 1
            # the NEEDS_CATALOG path re-uploaded on the survivor: exactly
            # one more upload and ONE residency miss for this logical solve
            # (solve-true accounting — the retry doesn't double-count)
            assert servers[survivor].solver_service.session_count() == 1
            assert uploads() == uploads_before + 1
            assert session_stats.snapshot()["misses"] == misses_before + 1
            # the dead primary's breaker stays its own: repeated solves
            # keep routing to the survivor without touching the primary
            for _ in range(3):
                pool.pack(*args, n_max=n_max)
            assert pool._breaker(survivor).available()
            pool.close()
        finally:
            for s in servers.values():
                s.stop(grace=0)

    def test_all_members_dead_raises_pool_exhausted(self):
        addr_a = f"127.0.0.1:{free_port()}"
        addr_b = f"127.0.0.1:{free_port()}"
        server_a, server_b = self._serve(addr_a), self._serve(addr_b)
        batch, *_ = _encoded_batch()
        args = _pack_args(batch)
        n_max = len(batch.pod_valid)
        pool = SolverPool([addr_a, addr_b], timeout=2)
        pool.pack(*args, n_max=n_max)
        server_a.stop(grace=0)
        server_b.stop(grace=0)
        with pytest.raises((PoolExhausted, Exception)):
            pool.pack(*args, n_max=n_max)
        # both breakers open: the next call is refused without an RPC stall
        with pytest.raises(PoolExhausted):
            pool.pack(*args, n_max=n_max)
        pool.close()


class TestPoolSoftBreaker:
    """STATUS_OVERLOADED is backpressure, not failure (docs/overload.md):
    the member sits out its retry-after window, traffic routes around it,
    and its REAL breaker — and the half-open probe traffic a trip would
    bring — is never touched."""

    def _fake_inputs(self):
        from karpenter_tpu.solver.service import N_POD_ARRAYS

        return tuple(
            np.full(4, i, np.float32) for i in range(N_POD_ARRAYS + 3)
        )

    def _pool(self, behaviors, clock):
        """behaviors: {address: callable(address) -> result-or-raise}; the
        callable runs at WAIT time (dispatch always succeeds)."""
        from karpenter_tpu.resilience.overload import OverloadedError  # noqa: F401

        calls = {a: 0 for a in behaviors}

        class FakeClient:
            def __init__(self, address):
                self.address = address

            def pack_begin(self, *inputs, n_max, prof=None, record=True):
                calls[self.address] += 1

                def wait():
                    return behaviors[self.address](self.address)

                return wait

            def close(self):
                pass

        pool = SolverPool(
            list(behaviors),
            client_factory=FakeClient,
            clock=lambda: clock[0],
        )
        return pool, calls

    def test_overloaded_member_sat_out_for_hint_window(self):
        from karpenter_tpu.resilience.overload import OverloadedError

        clock = [0.0]

        def overloaded(addr):
            raise OverloadedError(f"{addr} full", retry_after=5.0)

        inputs = self._fake_inputs()
        key = None
        behaviors = {"a:1": overloaded, "b:1": lambda addr: ("ok", addr)}
        pool, calls = self._pool(behaviors, clock)
        key = pool._catalog_key(inputs[7:])
        order = pool.ring.ordered(key)
        first = order[0]
        if first == "b:1":  # make the OVERLOADED member the primary
            behaviors["b:1"], behaviors["a:1"] = (
                behaviors["a:1"], behaviors["b:1"],
            )
        survivor = order[1]
        out = pool.pack_begin(*inputs, n_max=4)()
        assert out == ("ok", survivor)
        # the overloaded member's REAL breaker never moved
        assert pool._breaker(first).available()
        assert set(pool.available_members()) == {"a:1", "b:1"}
        assert pool.overload_skips == 1
        # within the hint window: routed around WITHOUT an RPC
        calls_before = calls[first]
        out = pool.pack_begin(*inputs, n_max=4)()
        assert out == ("ok", survivor)
        assert calls[first] == calls_before
        assert pool.overload_skips == 2
        # past the window the member earns traffic again
        clock[0] = 6.0
        behaviors[first] = lambda addr: ("recovered", addr)
        out = pool.pack_begin(*inputs, n_max=4)()
        assert out == ("recovered", first)
        pool.close()

    def test_all_members_overloaded_raises_typed_verdict(self):
        from karpenter_tpu.resilience.overload import OverloadedError

        clock = [0.0]

        def overloaded_2(addr):
            raise OverloadedError(f"{addr} full", retry_after=2.0)

        def overloaded_7(addr):
            raise OverloadedError(f"{addr} full", retry_after=7.0)

        inputs = self._fake_inputs()
        pool, _ = self._pool(
            {"a:1": overloaded_2, "b:1": overloaded_7}, clock
        )
        with pytest.raises(OverloadedError) as ei:
            pool.pack_begin(*inputs, n_max=4)()
        # NOT PoolExhausted: the pool is full, not broken — and the hint
        # is the soonest member to free
        assert ei.value.retry_after == 2.0
        # neither breaker moved: a retry after the hint routes normally
        assert set(pool.available_members()) == {"a:1", "b:1"}
        pool.close()

    def test_real_failure_then_overloaded_survivor_is_exhaustion_not_backpressure(self):
        """A hard member failure followed by an overloaded survivor must
        surface as PoolExhausted carrying the REAL error — reporting it as
        OverloadedError would log a broken member as backpressure and skip
        the outer remote-breaker accounting for the failed round."""
        from karpenter_tpu.resilience.overload import OverloadedError

        clock = [0.0]

        def hard_fail(addr):
            raise RuntimeError(f"{addr} segfaulted mid-solve")

        def overloaded(addr):
            raise OverloadedError(f"{addr} full", retry_after=3.0)

        inputs = self._fake_inputs()
        behaviors = {"a:1": hard_fail, "b:1": overloaded}
        pool, _ = self._pool(behaviors, clock)
        key = pool._catalog_key(inputs[7:])
        primary = pool.ring.route(key)
        if primary != "a:1":  # the REAL failure must be the primary's
            behaviors["a:1"], behaviors["b:1"] = (
                behaviors["b:1"], behaviors["a:1"],
            )
        with pytest.raises(PoolExhausted, match="segfaulted"):
            pool.pack_begin(*inputs, n_max=4)()
        pool.close()

    def test_deadline_exceeded_propagates_without_failover(self):
        from karpenter_tpu.resilience.overload import DeadlineExceededError

        clock = [0.0]

        def doomed(addr):
            raise DeadlineExceededError("round budget expired")

        served = []

        def serve_ok(addr):
            served.append(addr)
            return ("ok", addr)

        inputs = self._fake_inputs()
        pool, calls = self._pool({"a:1": doomed, "b:1": doomed}, clock)
        key = pool._catalog_key(inputs[7:])
        primary = pool.ring.route(key)
        with pytest.raises(DeadlineExceededError):
            pool.pack_begin(*inputs, n_max=4)()
        # no failover: the deadline is the WORK's, not the member's — the
        # other member was never asked to solve doomed work
        other = [a for a in ("a:1", "b:1") if a != primary][0]
        assert calls[other] == 0
        assert set(pool.available_members()) == {"a:1", "b:1"}
        pool.close()

    def test_dispatch_time_overload_skips_to_next_member(self):
        from karpenter_tpu.resilience.overload import OverloadedError

        clock = [0.0]
        inputs = self._fake_inputs()

        calls = {"a:1": 0, "b:1": 0}

        class DispatchOverloaded:
            def __init__(self, address):
                self.address = address

            def pack_begin(self, *a, **kw):
                calls[self.address] += 1
                if self.address == primary_box[0]:
                    raise OverloadedError("full at dispatch", retry_after=3.0)
                return lambda: ("ok", self.address)

            def close(self):
                pass

        primary_box = [None]
        pool = SolverPool(
            ["a:1", "b:1"],
            client_factory=DispatchOverloaded,
            clock=lambda: clock[0],
        )
        key = pool._catalog_key(inputs[7:])
        primary_box[0] = pool.ring.route(key)
        survivor = [a for a in ("a:1", "b:1") if a != primary_box[0]][0]
        out = pool.pack_begin(*inputs, n_max=4)()
        assert out == ("ok", survivor)
        assert pool._breaker(primary_box[0]).available()
        assert pool.overload_skips == 1
        assert pool.failovers == 0  # a soft skip is not a failover
        pool.close()


class TestSchedulerWithPool:
    def test_scheduler_solves_through_pool_and_degrades_to_ffd(self):
        """TpuScheduler with a comma-separated pool address solves through
        the pool; with every member dead, the outer breaker + FFD floor
        still schedule every pod (the last-resort degradation)."""
        from karpenter_tpu.kube.client import Cluster
        from karpenter_tpu.solver.backend import TpuScheduler
        from karpenter_tpu.solver.pool import SolverPool
        from karpenter_tpu.solver.service import serve

        batch, constraints, catalog, pods = _encoded_batch()
        addr_a = f"127.0.0.1:{free_port()}"
        addr_b = f"127.0.0.1:{free_port()}"
        server_a, server_b = serve(addr_a), serve(addr_b)
        try:
            sched = TpuScheduler(
                Cluster(), rng=random.Random(0),
                service_address=f"{addr_a},{addr_b}",
            )
            vnodes = sched.solve(constraints, catalog, pods)
            assert sum(len(v.pods) for v in vnodes) == len(pods)
            assert isinstance(sched._remote_or_init(), SolverPool)
        finally:
            server_a.stop(grace=0)
            server_b.stop(grace=0)

        dead = TpuScheduler(
            Cluster(), rng=random.Random(0),
            service_address=f"127.0.0.1:{free_port()},127.0.0.1:{free_port()}",
        )
        dead._remote_or_init()._timeout = 1
        vnodes = dead.solve(constraints, catalog, pods)
        assert sum(len(v.pods) for v in vnodes) == len(pods)
