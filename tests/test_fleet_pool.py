"""Solver sidecar pool tests (solver/pool.py): consistent-hash session
affinity, per-member breakers, ring failover, the NEEDS_CATALOG re-upload
on a DIFFERENT member, and the TpuScheduler integration — a dead member
degrades capacity, a dead pool degrades to the in-process kernel, and the
FFD floor still schedules everything."""

import random
import socket

import numpy as np
import pytest

from karpenter_tpu.solver.pool import HashRing, PoolExhausted, SolverPool

pytestmark = pytest.mark.fleet

grpc = pytest.importorskip("grpc")


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _encoded_batch(n_pods=8, n_types=8, seed=0):
    from karpenter_tpu.cloudprovider.fake import instance_types
    from karpenter_tpu.cloudprovider.requirements import catalog_requirements
    from karpenter_tpu.kube.client import Cluster
    from karpenter_tpu.scheduling.ffd import daemon_overhead, sort_pods_ffd
    from karpenter_tpu.scheduling.topology import Topology
    from karpenter_tpu.solver import encode as enc
    from karpenter_tpu.testing import make_pod, make_provisioner

    catalog = sorted(
        instance_types(n_types), key=lambda it: it.effective_price()
    )
    provisioner = make_provisioner(solver="tpu")
    constraints = provisioner.spec.constraints
    constraints.requirements = constraints.requirements.merge(
        catalog_requirements(catalog)
    )
    pods = sort_pods_ffd(
        [make_pod(requests={"cpu": "0.5"}) for _ in range(n_pods)]
    )
    cluster = Cluster()
    Topology(cluster, rng=random.Random(seed)).inject(constraints, pods)
    daemon = daemon_overhead(cluster, constraints)
    batch = enc.encode(constraints, catalog, pods, daemon)
    return batch, constraints, catalog, pods


def _pack_args(batch):
    return tuple(np.asarray(a) for a in batch.pack_args())


class TestHashRing:
    def test_deterministic_and_covers_members(self):
        ring = HashRing(["a:1", "b:1", "c:1"])
        key = b"\x01" * 16
        assert ring.ordered(key) == ring.ordered(key)
        assert set(ring.ordered(key)) == {"a:1", "b:1", "c:1"}

    def test_member_removal_moves_only_its_keys(self):
        members = ["a:1", "b:1", "c:1"]
        ring = HashRing(members)
        smaller = HashRing(["a:1", "c:1"])
        keys = [bytes([i]) * 16 for i in range(64)]
        for key in keys:
            before = ring.route(key)
            if before != "b:1":
                assert smaller.route(key) == before

    def test_distribution_roughly_even(self):
        ring = HashRing(["a:1", "b:1"])
        counts = {"a:1": 0, "b:1": 0}
        for i in range(512):
            counts[ring.route(i.to_bytes(4, "little") * 4)] += 1
        assert min(counts.values()) > 512 * 0.25

    def test_failover_order_starts_after_primary(self):
        ring = HashRing(["a:1", "b:1", "c:1"])
        key = b"\x07" * 16
        order = ring.ordered(key)
        assert order[0] == ring.route(key)
        assert len(order) == len(set(order)) == 3


class TestSolverPoolFailover:
    def _serve(self, address):
        from karpenter_tpu.solver.service import serve

        return serve(address)

    def test_routes_by_session_affinity_and_solves(self):
        from karpenter_tpu.solver import kernel

        addr_a = f"127.0.0.1:{free_port()}"
        addr_b = f"127.0.0.1:{free_port()}"
        server_a, server_b = self._serve(addr_a), self._serve(addr_b)
        try:
            batch, *_ = _encoded_batch()
            args = _pack_args(batch)
            n_max = len(batch.pod_valid)
            pool = SolverPool([addr_a, addr_b], timeout=30)
            result = pool.pack(*args, n_max=n_max)
            import jax

            local = jax.device_get(tuple(kernel.pack(*args, n_max=n_max)))
            for l, r in zip(local, tuple(result)):
                np.testing.assert_array_equal(np.asarray(l), np.asarray(r))
            # affinity: only the ROUTED member's store holds the session
            primary = pool.ring.route(pool._catalog_key(args[7:]))
            primary_srv = server_a if primary == addr_a else server_b
            other_srv = server_b if primary == addr_a else server_a
            assert primary_srv.solver_service.session_count() == 1
            assert other_srv.solver_service.session_count() == 0
            pool.close()
        finally:
            server_a.stop(grace=0)
            server_b.stop(grace=0)

    def test_dead_member_fails_over_through_the_ring(self):
        from karpenter_tpu import metrics as m

        addr_a = f"127.0.0.1:{free_port()}"
        addr_b = f"127.0.0.1:{free_port()}"
        server_a, server_b = self._serve(addr_a), self._serve(addr_b)
        servers = {addr_a: server_a, addr_b: server_b}
        try:
            batch, *_ = _encoded_batch()
            args = _pack_args(batch)
            n_max = len(batch.pod_valid)
            pool = SolverPool([addr_a, addr_b], timeout=5)
            pool.pack(*args, n_max=n_max)  # warm: session on the primary
            primary = pool.ring.route(pool._catalog_key(args[7:]))
            survivor = addr_b if primary == addr_a else addr_a

            def failovers():
                return m.REGISTRY.get_sample_value(
                    "karpenter_solver_pool_failovers_total",
                    {"address": primary},
                ) or 0.0

            before = failovers()
            servers[primary].stop(grace=0)  # SIGKILL the routed member
            result = pool.pack(*args, n_max=n_max)
            assert int(np.asarray(result[4]).reshape(-1)[0]) >= 1
            assert failovers() == before + 1
            # the survivor now holds the re-uploaded session
            assert servers[survivor].solver_service.session_count() == 1
            # and the dead member's breaker is open
            assert not pool._breaker(primary).available()
            assert pool.available_members() == [survivor]
            pool.close()
        finally:
            for s in servers.values():
                s.stop(grace=0)

    def test_needs_catalog_on_failover_member_reuploads_transparently(self):
        """The satellite scenario: the solve fails over to a member whose
        CLIENT remembers the session as open but whose server store is
        empty (restart) — NEEDS_CATALOG must re-upload on the NEW member,
        keep hit-rate accounting solve-true, and the old member's open
        breaker must not poison subsequent solves."""
        from karpenter_tpu.solver import session_stats

        addr_a = f"127.0.0.1:{free_port()}"
        addr_b = f"127.0.0.1:{free_port()}"
        server_a, server_b = self._serve(addr_a), self._serve(addr_b)
        servers = {addr_a: server_a, addr_b: server_b}
        try:
            batch, *_ = _encoded_batch()
            args = _pack_args(batch)
            n_max = len(batch.pod_valid)
            pool = SolverPool([addr_a, addr_b], timeout=5)
            key = pool._catalog_key(args[7:])
            primary = pool.ring.route(key)
            survivor = addr_b if primary == addr_a else addr_a
            pool.pack(*args, n_max=n_max)
            # open the session on the SURVIVOR too, then restart it: its
            # server store empties but the pool's client still remembers
            # the key as open — the classic restart-recovery skew
            pool._client(survivor)._open_session(key, args[7:], timeout=30)
            servers[survivor].stop(grace=0)
            from karpenter_tpu.solver.service import serve

            servers[survivor] = serve(survivor)
            assert servers[survivor].solver_service.session_count() == 0
            from karpenter_tpu import metrics as m

            def uploads():
                return m.REGISTRY.get_sample_value(
                    "karpenter_solver_session_catalog_uploads_total"
                ) or 0.0

            uploads_before = uploads()
            misses_before = session_stats.snapshot()["misses"]
            servers[primary].stop(grace=0)  # kill the routed member
            result = pool.pack(*args, n_max=n_max)
            assert int(np.asarray(result[4]).reshape(-1)[0]) >= 1
            # the NEEDS_CATALOG path re-uploaded on the survivor: exactly
            # one more upload and ONE residency miss for this logical solve
            # (solve-true accounting — the retry doesn't double-count)
            assert servers[survivor].solver_service.session_count() == 1
            assert uploads() == uploads_before + 1
            assert session_stats.snapshot()["misses"] == misses_before + 1
            # the dead primary's breaker stays its own: repeated solves
            # keep routing to the survivor without touching the primary
            for _ in range(3):
                pool.pack(*args, n_max=n_max)
            assert pool._breaker(survivor).available()
            pool.close()
        finally:
            for s in servers.values():
                s.stop(grace=0)

    def test_all_members_dead_raises_pool_exhausted(self):
        addr_a = f"127.0.0.1:{free_port()}"
        addr_b = f"127.0.0.1:{free_port()}"
        server_a, server_b = self._serve(addr_a), self._serve(addr_b)
        batch, *_ = _encoded_batch()
        args = _pack_args(batch)
        n_max = len(batch.pod_valid)
        pool = SolverPool([addr_a, addr_b], timeout=2)
        pool.pack(*args, n_max=n_max)
        server_a.stop(grace=0)
        server_b.stop(grace=0)
        with pytest.raises((PoolExhausted, Exception)):
            pool.pack(*args, n_max=n_max)
        # both breakers open: the next call is refused without an RPC stall
        with pytest.raises(PoolExhausted):
            pool.pack(*args, n_max=n_max)
        pool.close()


class TestSchedulerWithPool:
    def test_scheduler_solves_through_pool_and_degrades_to_ffd(self):
        """TpuScheduler with a comma-separated pool address solves through
        the pool; with every member dead, the outer breaker + FFD floor
        still schedule every pod (the last-resort degradation)."""
        from karpenter_tpu.kube.client import Cluster
        from karpenter_tpu.solver.backend import TpuScheduler
        from karpenter_tpu.solver.pool import SolverPool
        from karpenter_tpu.solver.service import serve

        batch, constraints, catalog, pods = _encoded_batch()
        addr_a = f"127.0.0.1:{free_port()}"
        addr_b = f"127.0.0.1:{free_port()}"
        server_a, server_b = serve(addr_a), serve(addr_b)
        try:
            sched = TpuScheduler(
                Cluster(), rng=random.Random(0),
                service_address=f"{addr_a},{addr_b}",
            )
            vnodes = sched.solve(constraints, catalog, pods)
            assert sum(len(v.pods) for v in vnodes) == len(pods)
            assert isinstance(sched._remote_or_init(), SolverPool)
        finally:
            server_a.stop(grace=0)
            server_b.stop(grace=0)

        dead = TpuScheduler(
            Cluster(), rng=random.Random(0),
            service_address=f"127.0.0.1:{free_port()},127.0.0.1:{free_port()}",
        )
        dead._remote_or_init()._timeout = 1
        vnodes = dead.solve(constraints, catalog, pods)
        assert sum(len(v.pods) for v in vnodes) == len(pods)
