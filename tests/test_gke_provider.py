"""GKE TPU-podslice provider: google.com/tpu extended resources flowing
through the full solve stack (encode extra axes → kernels → decode →
launch), plus the vendor hook surface (SURVEY §2.6 vendor-layer shape)."""

import random

import pytest

from karpenter_tpu.api import labels as lbl
from karpenter_tpu.cloudprovider.gke import (
    GKE_TPU_ACCELERATOR_LABEL,
    GKE_TPU_TOPOLOGY_LABEL,
    TPU_RESOURCE,
    GkeCloudProvider,
    gke_catalog,
)
from karpenter_tpu.cloudprovider.requirements import catalog_requirements
from karpenter_tpu.cloudprovider.types import NodeRequest
from karpenter_tpu.kube.client import Cluster
from karpenter_tpu.scheduling.scheduler import Scheduler
from tests.factories import make_pod, make_provisioner


def solve(pods, solver):
    catalog = gke_catalog()
    provisioner = make_provisioner(solver=solver)
    c = provisioner.spec.constraints
    GkeCloudProvider().default(c)
    c.requirements = c.requirements.merge(catalog_requirements(catalog))
    return Scheduler(Cluster(), rng=random.Random(0)).solve(provisioner, catalog, pods)


class TestGkeCatalog:
    def test_registry_builds_gke(self):
        from karpenter_tpu.cloudprovider import registry

        provider = registry.new_cloud_provider("gke")
        assert provider.name() == "gke"
        names = {it.name for it in provider.get_instance_types()}
        assert "ct5lp-hightpu-4t" in names and "e2-standard-2" in names

    def test_tpu_types_carry_chips(self):
        by_name = {it.name: it for it in gke_catalog()}
        assert by_name["ct5lp-hightpu-1t"].resources[TPU_RESOURCE] == 1.0
        assert by_name["ct5lp-hightpu-8t"].resources[TPU_RESOURCE] == 8.0
        assert TPU_RESOURCE not in by_name["n2-standard-8"].resources


class TestTpuScheduling:
    @pytest.mark.parametrize("solver", ["ffd", "tpu"])
    def test_tpu_pod_lands_on_cheapest_fitting_slice(self, solver):
        vnodes = solve([make_pod(name="solo", requests={"cpu": "8", TPU_RESOURCE: "4"})], solver)
        assert len(vnodes) == 1
        # 4 chips fit the 4t slice (cheapest TPU type that satisfies)
        assert vnodes[0].instance_type_options[0].name == "ct5lp-hightpu-4t"

    @pytest.mark.parametrize("solver", ["ffd", "tpu"])
    def test_cpu_only_batch_never_buys_tpu_hosts(self, solver):
        vnodes = solve(
            [make_pod(name=f"web-{i}", requests={"cpu": "2"}) for i in range(6)], solver
        )
        assert sum(len(v.pods) for v in vnodes) == 6
        for v in vnodes:
            assert v.instance_type_options[0].name.startswith("e2-")

    @pytest.mark.parametrize("solver", ["ffd", "tpu"])
    def test_first_fit_packs_chip_requests_onto_one_slice(self, solver):
        """Two 4-chip pods pack onto one node whose surviving cheapest
        type is the 8-chip slice (first-fit prefers the open node when any
        type still satisfies the running total)."""
        pods = [
            make_pod(name=f"train-{i}", requests={"cpu": "8", TPU_RESOURCE: "4"})
            for i in range(2)
        ]
        vnodes = solve(pods, solver)
        assert len(vnodes) == 1 and len(vnodes[0].pods) == 2
        assert vnodes[0].instance_type_options[0].name == "ct5lp-hightpu-8t"

    @pytest.mark.parametrize("solver", ["ffd", "tpu"])
    def test_chip_capacity_packs_and_splits(self, solver):
        # 3 pods x 4 chips: one 8t host takes two, the third opens another
        pods = [
            make_pod(name=f"t-{i}", requests={"cpu": "4", TPU_RESOURCE: "4"},
                     node_selector={lbl.INSTANCE_TYPE: "ct5lp-hightpu-8t"})
            for i in range(3)
        ]
        vnodes = solve(pods, solver)
        assert sum(len(v.pods) for v in vnodes) == 3
        sizes = sorted(len(v.pods) for v in vnodes)
        assert sizes == [1, 2]

    @pytest.mark.parametrize("solver", ["ffd", "tpu"])
    def test_oversized_tpu_request_certified_unschedulable(self, solver):
        from karpenter_tpu.scheduling import oracle

        catalog = gke_catalog()
        provisioner = make_provisioner(solver=solver)
        c = provisioner.spec.constraints
        GkeCloudProvider().default(c)
        c.requirements = c.requirements.merge(catalog_requirements(catalog))
        cluster = Cluster()
        pods = [make_pod(name="huge", requests={TPU_RESOURCE: "16"})]
        vnodes = Scheduler(cluster, rng=random.Random(0)).solve(provisioner, catalog, pods)
        assert sum(len(v.pods) for v in vnodes) == 0
        verdict = oracle.classify_drops(
            cluster, c, catalog, pods, [p for v in vnodes for p in v.pods]
        )
        assert verdict["expected"] == {oracle.NO_CAPACITY: 1}
        assert verdict["unexplained"] == []


class TestGkeLaunch:
    def test_launched_tpu_node_carries_gke_labels(self):
        provider = GkeCloudProvider()
        catalog = sorted(provider.get_instance_types(), key=lambda it: it.effective_price())
        tpu_types = [it for it in catalog if it.resources.get(TPU_RESOURCE)]
        prov = make_provisioner()
        provider.default(prov.spec.constraints)
        c = prov.spec.constraints
        c.requirements = c.requirements.merge(catalog_requirements(catalog))
        node = provider.create(NodeRequest(template=c, instance_type_options=tpu_types))
        assert node.metadata.labels[GKE_TPU_ACCELERATOR_LABEL] == "tpu-v5-lite-podslice"
        assert node.metadata.labels[GKE_TPU_TOPOLOGY_LABEL] in ("1x1", "2x2", "2x4")
        assert node.spec.provider_id.startswith("gce://")
        assert node.status.allocatable[TPU_RESOURCE] == node.status.capacity[TPU_RESOURCE]

    def test_defaulting_and_validation_hooks(self):
        provider = GkeCloudProvider()
        prov = make_provisioner()
        provider.default(prov.spec.constraints)
        assert prov.spec.constraints.requirements.get(lbl.CAPACITY_TYPE).has("on-demand")
        prov.spec.constraints.provider = {"project": "p", "bogus": 1}
        errs = provider.validate(prov.spec.constraints)
        assert errs and "bogus" in errs[0]

    def test_end_to_end_tpu_provisioning(self):
        """Pending TPU pods → worker → GKE provider → bound on a podslice."""
        from karpenter_tpu.controllers.provisioning import ProvisioningController

        cluster = Cluster()
        provider = GkeCloudProvider()
        controller = ProvisioningController(cluster, provider, start_workers=False)
        prov = make_provisioner(solver="tpu")
        cluster.create("provisioners", prov)
        controller.apply(cluster.get("provisioners", "default", namespace=""))
        worker = controller.workers["default"]
        pods = [make_pod(requests={"cpu": "4", TPU_RESOURCE: "4"}) for _ in range(2)]
        for p in pods:
            cluster.create("pods", p)
            worker.batcher.add(p)
        worker.batcher.idle_duration = 0.05
        vnodes = worker.provision_once()
        controller.stop()
        assert sum(len(v.pods) for v in vnodes) == 2
        nodes = cluster.nodes()
        assert all(GKE_TPU_ACCELERATOR_LABEL in n.metadata.labels for n in nodes)
        for p in cluster.pods():
            assert p.spec.node_name.startswith("gke-np-")

    def test_unsatisfiable_offering_raises(self):
        from karpenter_tpu.api.objects import NodeSelectorRequirement

        provider = GkeCloudProvider()
        catalog = provider.get_instance_types()
        prov = make_provisioner()
        c = prov.spec.constraints
        c.requirements = c.requirements.add(
            NodeSelectorRequirement(key=lbl.TOPOLOGY_ZONE, operator="In",
                                    values=["us-central2-z"])
        )
        with pytest.raises(ValueError, match="no offering"):
            provider.create(NodeRequest(template=c, instance_type_options=catalog))


class TestGkeStockoutAndMultiHost:
    """SimGkeAPI-backed vendor depth (VERDICT r2 #6): stockout -> ICE cache
    -> offering fallback, atomic multi-host podslice launches, and a
    multi-host slice landing as N bound nodes."""

    def _request(self, it, zones=None, capacity=("on-demand",)):
        from karpenter_tpu.api.objects import NodeSelectorRequirement
        from karpenter_tpu.api.provisioner import Constraints
        from karpenter_tpu.api.requirements import Requirements

        reqs = [NodeSelectorRequirement(key=lbl.CAPACITY_TYPE, operator="In",
                                        values=list(capacity))]
        if zones:
            reqs.append(NodeSelectorRequirement(key=lbl.TOPOLOGY_ZONE, operator="In",
                                                values=list(zones)))
        return NodeRequest(
            template=Constraints(requirements=Requirements.new(*reqs)),
            instance_type_options=[it],
        )

    def test_stockout_falls_through_to_next_zone_and_ice_caches(self):
        from karpenter_tpu.cloudprovider.gke import ZONES, SimGkeAPI
        from karpenter_tpu.utils.ttlcache import TTLCache

        now = [0.0]
        api = SimGkeAPI()
        provider = GkeCloudProvider(api=api, clock=lambda: now[0])
        it = next(t for t in provider.get_instance_types() if t.name == "ct5lp-hightpu-4t")
        api.set_stockout("ct5lp-hightpu-4t", ZONES[0])

        node = provider.create(self._request(it))
        # landed in the NEXT zone after the stocked-out one
        assert node.metadata.labels[lbl.TOPOLOGY_ZONE] == ZONES[1]
        # the stocked-out offering (zone a, on-demand) is ICE-cached OUT of
        # the catalog — per (zone, capacity type), so zone a's SPOT offering
        # legitimately remains purchasable
        def od_zones():
            return {
                o.zone
                for t in provider.get_instance_types() if t.name == "ct5lp-hightpu-4t"
                for o in t.offerings if o.capacity_type == "on-demand"
            }

        assert ZONES[0] not in od_zones()
        # ... and returns after the 45s TTL
        now[0] += 46.0
        assert ZONES[0] in od_zones()

    def test_total_stockout_raises_classified_error(self):
        from karpenter_tpu.cloudprovider.gke import ZONES, GkeStockoutError, SimGkeAPI

        api = SimGkeAPI()
        provider = GkeCloudProvider(api=api)
        it = next(t for t in provider.get_instance_types() if t.name == "ct5lp-hightpu-1t")
        for z in ZONES:
            api.set_stockout("ct5lp-hightpu-1t", z)
        with pytest.raises(GkeStockoutError):
            provider.create(self._request(it))

    def test_multi_host_slice_is_one_atomic_pool(self):
        from karpenter_tpu.cloudprovider.gke import GKE_NODEPOOL_LABEL, SimGkeAPI

        api = SimGkeAPI()
        provider = GkeCloudProvider(api=api)
        it = next(
            t for t in provider.get_instance_types() if t.name == "ct5lp-hightpu-4t-4x4"
        )
        req = self._request(it)
        nodes = [provider.create(req) for _ in range(4)]
        # ONE atomic node-pool create of count=4, not four pools
        assert len(api.create_calls) == 1
        assert api.create_calls[0].count == 4
        assert api.create_calls[0].tpu_topology == "4x4"
        # all four nodes share the topology and the pool
        pools = {n.metadata.labels[GKE_NODEPOOL_LABEL] for n in nodes}
        assert len(pools) == 1
        assert {n.metadata.labels[GKE_TPU_TOPOLOGY_LABEL] for n in nodes} == {"4x4"}
        assert len({n.metadata.name for n in nodes}) == 4
        # a fifth create starts a NEW slice
        provider.create(req)
        assert len(api.create_calls) == 2

    @pytest.mark.parametrize("solver", ["ffd", "tpu"])
    def test_multi_host_workload_lands_as_n_bound_nodes(self, solver):
        """Four pods, one per host of a 4x4 v5e podslice, selected via the
        gke-tpu-topology label + hostname anti-affinity (one worker per
        host): the controller binds them onto 4 nodes of ONE node pool."""
        from karpenter_tpu.api.objects import LabelSelector, PodAffinityTerm
        from karpenter_tpu.cloudprovider.gke import GKE_NODEPOOL_LABEL, SimGkeAPI
        from karpenter_tpu.controllers.provisioning import ProvisioningController
        from karpenter_tpu.kube.client import Cluster

        api = SimGkeAPI()
        provider = GkeCloudProvider(api=api)
        cluster = Cluster()
        provisioner = make_provisioner(solver=solver)
        controller = ProvisioningController(cluster, provider, start_workers=False)
        cluster.create("provisioners", provisioner)
        controller.reconcile(provisioner.metadata.name)
        worker = controller.workers[provisioner.metadata.name]

        sel = {"job": "trainer"}
        pods = []
        for i in range(4):
            p = make_pod(
                name=f"worker-{i}",
                labels=sel,
                requests={"cpu": "8", TPU_RESOURCE: "4"},
                node_selector={GKE_TPU_TOPOLOGY_LABEL: "4x4"},
                pod_anti_requirements=[
                    PodAffinityTerm(
                        label_selector=LabelSelector(match_labels=sel),
                        topology_key=lbl.HOSTNAME,
                    )
                ],
            )
            cluster.create("pods", p)
            pods.append(p)
            worker.add(p)
        worker.batcher.idle_duration = 0.05
        vnodes = worker.provision_once()
        controller.stop()

        assert sum(len(v.pods) for v in vnodes) == 4
        nodes = cluster.nodes()
        assert len(nodes) == 4
        # every node is a host of the SAME atomic podslice
        assert len(api.create_calls) == 1 and api.create_calls[0].count == 4
        assert {n.metadata.labels[GKE_NODEPOOL_LABEL] for n in nodes} == {
            api.create_calls[0].name
        }
        assert {n.metadata.labels[GKE_TPU_TOPOLOGY_LABEL] for n in nodes} == {"4x4"}
        assert {n.metadata.labels[lbl.INSTANCE_TYPE] for n in nodes} == {
            "ct5lp-hightpu-4t-4x4"
        }
        bound = {p.spec.node_name for p in cluster.pods()}
        assert len(bound) == 4 and all(bound)

    def test_topology_selector_routes_to_the_matching_slice_shape(self):
        """A pod selecting gke-tpu-topology=4x4 must ONLY fit the 4x4 slice
        shape — the vendor-declared type labels participate in requirement
        compatibility (types with a different declared topology are out)."""
        pods = [
            make_pod(
                requests={"cpu": "8", TPU_RESOURCE: "4"},
                node_selector={GKE_TPU_TOPOLOGY_LABEL: "4x4"},
            )
        ]
        vnodes = solve(pods, "ffd")
        assert len(vnodes) == 1
        names = {t.name for t in vnodes[0].instance_type_options}
        assert names == {"ct5lp-hightpu-4t-4x4"}

    def test_concurrent_slice_launches_share_one_pool(self):
        """provision_once launches vnodes from a thread pool: concurrent
        creates of the same slice key must claim hosts of ONE atomic pool,
        never race two pools into existence."""
        import threading

        from karpenter_tpu.cloudprovider.gke import GKE_NODEPOOL_LABEL, SimGkeAPI

        api = SimGkeAPI()
        provider = GkeCloudProvider(api=api)
        it = next(
            t for t in provider.get_instance_types() if t.name == "ct5lp-hightpu-4t-4x4"
        )
        req = self._request(it)
        nodes = []
        lock = threading.Lock()
        barrier = threading.Barrier(4)

        def launch():
            barrier.wait()
            n = provider.create(req)
            with lock:
                nodes.append(n)

        threads = [threading.Thread(target=launch) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(api.create_calls) == 1
        assert {n.metadata.labels[GKE_NODEPOOL_LABEL] for n in nodes} == {
            api.create_calls[0].name
        }
        assert len({n.metadata.name for n in nodes}) == 4

    def test_delete_purges_pending_slice_siblings(self):
        from karpenter_tpu.cloudprovider.gke import SimGkeAPI

        api = SimGkeAPI()
        provider = GkeCloudProvider(api=api)
        it = next(
            t for t in provider.get_instance_types() if t.name == "ct5lp-hightpu-4t-4x4"
        )
        req = self._request(it)
        first = provider.create(req)  # pool of 4; 3 pending
        assert len(provider._pending_hosts) == 1
        provider.delete(first)
        # the dying slice's unclaimed siblings die with it
        assert provider._pending_hosts == {}
        assert api.node_pools == {}  # pool fully reaped
        # the next create starts a FRESH atomic slice
        provider.create(req)
        assert len(api.create_calls) == 2
