"""End-to-end pack integrity (docs/integrity.md): wire checksums over a
live gRPC sidecar, the session-generation guard, per-member quarantine and
ring failover in the pool, the host-side NaN/bounds screen, and the native
canary cross-check — including the no-false-positive bar on a clean path.
"""

import random
import socket
import threading
import time

import numpy as np
import pytest

from karpenter_tpu.resilience.integrity import IntegrityError
from karpenter_tpu.solver import integrity
from karpenter_tpu.solver.service import (
    N_POD_ARRAYS,
    PROTO_CHECKSUM,
    STATUS_INTEGRITY,
    STATUS_OK,
    RemoteSolver,
    SolverService,
    append_checksum,
    catalog_session_key,
    is_checksum_array,
    pack_arrays,
    unpack_arrays,
    verify_checksum,
    _key_array,
)


@pytest.fixture(autouse=True)
def _fresh_integrity_counters():
    integrity.reset()
    yield
    integrity.reset()


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def encoded_batch(n_types: int = 8, n_pods: int = 6, seed: int = 3):
    """(constraints, catalog, pods, daemon, batch) for a real encode."""
    from karpenter_tpu.cloudprovider.fake import instance_types
    from karpenter_tpu.cloudprovider.requirements import catalog_requirements
    from karpenter_tpu.kube.client import Cluster
    from karpenter_tpu.scheduling.ffd import daemon_overhead, sort_pods_ffd
    from karpenter_tpu.scheduling.topology import Topology
    from karpenter_tpu.solver import encode as enc
    from karpenter_tpu.testing import diverse_pods, make_provisioner

    catalog = sorted(instance_types(n_types), key=lambda it: it.effective_price())
    constraints = make_provisioner(solver="tpu").spec.constraints
    constraints.requirements = constraints.requirements.merge(
        catalog_requirements(catalog)
    )
    pods = sort_pods_ffd(diverse_pods(n_pods, random.Random(seed)))
    cluster = Cluster()
    Topology(cluster, rng=random.Random(1)).inject(constraints, pods)
    daemon = daemon_overhead(cluster, constraints)
    batch = enc.encode(constraints, catalog, pods, daemon)
    return constraints, catalog, pods, daemon, batch


# ---------------------------------------------------------------------------
# wire checksums over a live sidecar
# ---------------------------------------------------------------------------


class TestWireChecksums:
    def test_checksummed_grpc_round_trip(self):
        """A checksum-enabled client against a live sidecar: the server
        advertises PROTO_CHECKSUM, the exchange verifies both ways, the
        session echo agrees, and the result matches an unchecksummed solve
        bit-for-bit (integrity must never change the answer)."""
        from karpenter_tpu.solver.service import serve

        _, _, _, _, batch = encoded_batch()
        args, n_max = batch.pack_args(), len(batch.pod_valid)
        address = f"127.0.0.1:{free_port()}"
        server = serve(address)
        try:
            plain = RemoteSolver(address, checksum=False)
            sealed = RemoteSolver(address, checksum=True)
            out_plain = plain.pack(*args, n_max=n_max)
            out_sealed = sealed.pack(*args, n_max=n_max)
            assert sealed._server_features & PROTO_CHECKSUM
            for a, b in zip(out_plain, out_sealed):
                np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
            assert integrity.totals().get("checksum_failures", 0) == 0
            plain.close()
            sealed.close()
        finally:
            server.stop(grace=0)

    def test_server_rejects_corrupt_checksummed_request(self):
        """A checksummed Pack frame with one flipped payload bit answers
        STATUS_INTEGRITY — the server never solves against garbage — and
        the sidecar's own failure counter moves."""
        service = SolverService()
        _, _, _, _, batch = encoded_batch()
        args = [np.asarray(a) for a in batch.pack_args()]
        key = catalog_session_key(*args[N_POD_ARRAYS:])
        open_resp = service.open_session_bytes(
            append_checksum(
                pack_arrays([_key_array(key)] + args[N_POD_ARRAYS:])
            )
        )
        assert verify_checksum(open_resp) == "ok"  # sealed in kind
        request = append_checksum(
            pack_arrays(
                [_key_array(key), np.asarray([len(batch.pod_valid), 1, 1], np.int32)]
                + args[:N_POD_ARRAYS]
            )
        )
        corrupt = bytearray(request)
        corrupt[60] ^= 0x10  # payload region
        response = service.solve_bytes(bytes(corrupt))
        status = int(unpack_arrays(response)[0].reshape(-1)[0])
        assert status == STATUS_INTEGRITY
        assert service.checksum_failures.get("pack") == 1
        # the clean frame still solves — the path is not poisoned
        ok = service.solve_bytes(request)
        arrays = unpack_arrays(ok)
        assert int(arrays[0].reshape(-1)[0]) == STATUS_OK
        # and the response carries: checksum (request was sealed) + echo
        assert is_checksum_array(arrays[-1])
        echoed = next(
            np.asarray(a) for a in arrays[1:]
            if np.asarray(a).dtype == np.int32 and np.asarray(a).size == 4
        )
        assert echoed.tobytes() == key

    def test_unchecksummed_exchange_stays_byte_compatible(self):
        """Old-client interop: a plain v3 exchange against the new server
        carries no checksum, no echo — byte-identical framing."""
        service = SolverService()
        _, _, _, _, batch = encoded_batch()
        args = [np.asarray(a) for a in batch.pack_args()]
        key = catalog_session_key(*args[N_POD_ARRAYS:])
        service.open_session_bytes(
            pack_arrays([_key_array(key)] + args[N_POD_ARRAYS:])
        )
        response = service.solve_bytes(
            pack_arrays(
                [_key_array(key), np.asarray([len(batch.pod_valid)], np.int32)]
                + args[:N_POD_ARRAYS]
            )
        )
        arrays = unpack_arrays(response)
        assert int(arrays[0].reshape(-1)[0]) == STATUS_OK
        assert len(arrays) == 2  # status + buf: no echo, no checksum
        assert verify_checksum(response) == "missing"

    def test_corrupt_responses_raise_typed_integrity_error(self):
        """Chaos bit-flips on the wire (either direction): the client's
        verdict is IntegrityError, never a silently wrong array — and a
        healed wire recovers without rebuilding the client."""
        from karpenter_tpu.testing.chaos import ChaosPolicy, chaos_wrap
        from karpenter_tpu.solver.service import serve

        _, _, _, _, batch = encoded_batch()
        args, n_max = batch.pack_args(), len(batch.pod_valid)
        proxy = chaos_wrap(SolverService(), ChaosPolicy())
        address = f"127.0.0.1:{free_port()}"
        server = serve(address, service=proxy)
        try:
            client = RemoteSolver(address, checksum=True)
            client.pack(*args, n_max=n_max)  # clean warm-up (features learned)
            proxy.policy = ChaosPolicy(
                corrupt_rate=1.0, corruption_modes=("bit_flip",), seed=11,
            )
            with pytest.raises(IntegrityError):
                client.pack(*args, n_max=n_max)
            assert proxy.corrupted_total() >= 1
            assert integrity.totals().get("checksum_failures", 0) >= 1
            proxy.policy = ChaosPolicy()
            out = client.pack(*args, n_max=n_max)  # healed wire serves again
            assert len(out) == 5
            client.close()
        finally:
            server.stop(grace=0)


class OldBuildShim:
    """The response surface of a pre-checksum sidecar build over the
    current kernel: no PROTO_CHECKSUM advertisement, never seals, never
    echoes — what a rolled-back member actually answers with."""

    def __init__(self, service):
        self._s = service

    def open_session_bytes(self, request):
        from karpenter_tpu.solver.service import PROTO_CHECKSUM

        arrays = [
            np.asarray(a)
            for a in unpack_arrays(self._s.open_session_bytes(request))
            if not is_checksum_array(a)
        ]
        if len(arrays) > 1:
            arrays[1] = np.array(
                [int(arrays[1].reshape(-1)[0]) & ~PROTO_CHECKSUM], np.int32
            )
        return pack_arrays(arrays)

    def solve_bytes(self, request):
        arrays = [
            np.asarray(a)
            for a in unpack_arrays(self._s.solve_bytes(request))
            if not is_checksum_array(a)
        ]
        arrays = [
            a for i, a in enumerate(arrays)
            if i == 0 or not (a.dtype == np.int32 and a.ndim == 1 and a.size == 4)
        ]
        return pack_arrays(arrays)

    def __getattr__(self, name):
        return getattr(self._s, name)


class TestVersionSkewRecovery:
    def test_rollback_to_old_build_recovers_in_flight(self):
        """Checksum negotiated, then the member restarts on a pre-checksum
        build: the unsealed NEEDS_CATALOG must fall through to the forced
        re-open (the renegotiation channel), which accepts the downgrade —
        the solve completes on the SAME member with zero quarantines."""
        from karpenter_tpu.solver.service import serve

        _, _, _, _, batch = encoded_batch()
        args, n_max = batch.pack_args(), len(batch.pod_valid)
        address = f"127.0.0.1:{free_port()}"
        server = serve(address)
        client = RemoteSolver(address, checksum=True)
        try:
            client.pack(*args, n_max=n_max)  # checksum negotiated
            assert client._server_features & PROTO_CHECKSUM
            server.stop(grace=0)
            server = serve(address, service=OldBuildShim(SolverService()))
            out = client.pack(*args, n_max=n_max)  # rollback restart
            assert len(out) == 5
            totals = integrity.totals()
            assert totals.get("checksum_failures", 0) == 0
            assert totals.get("quarantines", 0) == 0
            assert not (client._server_features & PROTO_CHECKSUM)
            client.close()
        finally:
            server.stop(grace=0)

    def test_upgrade_to_new_build_recovers_in_flight(self):
        """The mirror: negotiated WITHOUT checksums against an old build,
        member restarts upgraded. The re-open learns PROTO_CHECKSUM but
        the retried request carried no checksum, so the expectation must
        not be raised above it — the solve completes, and the NEXT solve
        negotiates checksums."""
        from karpenter_tpu.solver.service import serve

        _, _, _, _, batch = encoded_batch()
        args, n_max = batch.pack_args(), len(batch.pod_valid)
        address = f"127.0.0.1:{free_port()}"
        server = serve(address, service=OldBuildShim(SolverService()))
        client = RemoteSolver(address, checksum=True)
        try:
            client.pack(*args, n_max=n_max)
            assert not (client._server_features & PROTO_CHECKSUM)
            server.stop(grace=0)
            server = serve(address)  # upgraded restart
            out = client.pack(*args, n_max=n_max)
            assert len(out) == 5
            assert client._server_features & PROTO_CHECKSUM
            out = client.pack(*args, n_max=n_max)  # now fully sealed
            assert len(out) == 5
            totals = integrity.totals()
            assert totals.get("checksum_failures", 0) == 0
            assert totals.get("quarantines", 0) == 0
            client.close()
        finally:
            server.stop(grace=0)


class TestOpenSessionIntegrity:
    def test_corrupt_open_request_raises_typed_integrity_error(self):
        """A corrupt OPEN request must surface as IntegrityError (so the
        pool quarantines) — not the generic unknown-status RuntimeError
        that would only record a windowed member failure."""
        from karpenter_tpu.testing.chaos import ChaosPolicy, chaos_wrap
        from karpenter_tpu.solver.service import serve

        _, _, _, _, batch = encoded_batch()
        args, n_max = batch.pack_args(), len(batch.pod_valid)
        proxy = chaos_wrap(SolverService(), ChaosPolicy())
        address = f"127.0.0.1:{free_port()}"
        server = serve(address, service=proxy)
        try:
            client = RemoteSolver(address, checksum=True)
            client.pack(*args, n_max=n_max)  # learn features
            proxy.policy = ChaosPolicy(
                corrupt_rate=1.0, corruption_modes=("bit_flip",),
                methods=frozenset({"open_session_bytes"}), seed=2,
            )
            with pytest.raises(IntegrityError):
                # force the open path (fresh client state, features warm
                # via a clean open first would short-circuit — use force)
                client._open_session(
                    catalog_session_key(
                        *[np.asarray(a) for a in args[N_POD_ARRAYS:]]
                    ),
                    args[N_POD_ARRAYS:], timeout=10.0, force=True,
                )
            client.close()
        finally:
            server.stop(grace=0)

    def test_unparseable_request_answers_integrity_not_crash(self):
        """A corrupt request too mangled to parse (header flip, truncation)
        must answer STATUS_INTEGRITY like any other corruption — a handler
        crash would reach the client as a generic transport error and be
        booked as a windowed availability failure, not a quarantine."""
        service = SolverService()
        _, _, _, _, batch = encoded_batch()
        args = [np.asarray(a) for a in batch.pack_args()]
        key = catalog_session_key(*args[N_POD_ARRAYS:])
        request = append_checksum(
            pack_arrays(
                [_key_array(key), np.asarray([len(batch.pod_valid), 1, 1], np.int32)]
                + args[:N_POD_ARRAYS]
            )
        )
        for corrupt in (
            request[:8] + b"\xff" + request[9:],  # dtype-code byte mangled
            request[: len(request) // 2],          # truncated mid-array
        ):
            response = service.solve_bytes(bytes(corrupt))
            assert int(unpack_arrays(response)[0].reshape(-1)[0]) == STATUS_INTEGRITY
        open_req = append_checksum(
            pack_arrays([_key_array(key)] + args[N_POD_ARRAYS:])
        )
        response = service.open_session_bytes(open_req[: len(open_req) - 9])
        assert int(unpack_arrays(response)[0].reshape(-1)[0]) == STATUS_INTEGRITY
        assert service.session_count() == 0

    def test_wrong_keyed_upload_refused(self):
        """Content-address verification: an upload whose claimed key does
        not hash to the tensors answers STATUS_INTEGRITY — a corrupt
        client memo can never pin tensors the key does not describe."""
        service = SolverService()
        _, _, _, _, batch = encoded_batch()
        args = [np.asarray(a) for a in batch.pack_args()]
        wrong_key = bytes(16)  # all zeros: hashes to nothing real
        response = service.open_session_bytes(
            pack_arrays([_key_array(wrong_key)] + args[N_POD_ARRAYS:])
        )
        assert int(unpack_arrays(response)[0].reshape(-1)[0]) == STATUS_INTEGRITY
        assert service.checksum_failures.get("open_session_key") == 1
        assert service.session_count() == 0

    def test_rollback_to_unchecksummed_member_is_not_quarantined(self):
        """A member rolled back to a pre-checksum build answers opens
        WITHOUT a checksum and without PROTO_CHECKSUM in its features:
        the client must treat that as a legitimate downgrade (disable
        checksums toward it), never as corruption — or a healthy older
        member would re-quarantine on every half-open probe forever."""
        from karpenter_tpu.solver.service import (
            PROTO_DEADLINE,
            PROTO_TRACE_TRAILER,
            _status_response,
        )

        client = RemoteSolver.__new__(RemoteSolver)
        client.address = "fuzz:0"
        client.checksum = True
        # old-build open response: unchecksummed, features without the bit
        old = _status_response(
            STATUS_OK,
            [np.array([PROTO_TRACE_TRAILER | PROTO_DEADLINE], np.int32)],
        )
        status, payload = client._receive_open(old, require_checksum=True)
        assert status == STATUS_OK
        # a server CLAIMING the bit while omitting the trailer stays fatal
        lying = _status_response(
            STATUS_OK, [np.array([PROTO_CHECKSUM], np.int32)]
        )
        with pytest.raises(IntegrityError):
            client._receive_open(lying, require_checksum=True)


# ---------------------------------------------------------------------------
# session-generation guard
# ---------------------------------------------------------------------------


class TestSessionEchoGuard:
    def test_stale_session_replay_rejected_then_recovers(self):
        from karpenter_tpu.testing.chaos import ChaosPolicy, chaos_wrap
        from karpenter_tpu.solver.service import serve

        _, _, _, _, batch = encoded_batch()
        args, n_max = batch.pack_args(), len(batch.pod_valid)
        proxy = chaos_wrap(SolverService(), ChaosPolicy())
        address = f"127.0.0.1:{free_port()}"
        server = serve(address, service=proxy)
        try:
            client = RemoteSolver(address, checksum=True)
            client.pack(*args, n_max=n_max)  # clean warm-up
            # corrupt only the solve responses: every Pack echoes a WRONG
            # session key (checksum recomputed, so only the session guard
            # can catch it); the forced re-open retry hits it again, so the
            # typed verdict escalates
            proxy.policy = ChaosPolicy(
                corrupt_rate=1.0, corruption_modes=("stale_session",),
                methods=frozenset({"solve_bytes"}), seed=5,
            )
            with pytest.raises(IntegrityError) as ei:
                client.pack(*args, n_max=n_max)
            assert ei.value.kind == "session"
            assert integrity.totals().get("session_mismatches", 0) >= 2
            proxy.policy = ChaosPolicy()
            out = client.pack(*args, n_max=n_max)
            assert len(out) == 5
            assert integrity.totals().get("canary_mismatches", 0) == 0
            client.close()
        finally:
            server.stop(grace=0)


# ---------------------------------------------------------------------------
# pool quarantine → ring failover → half-open recovery
# ---------------------------------------------------------------------------


class TestPoolQuarantine:
    def _fake_inputs(self):
        return tuple(
            np.full(4, i, np.float32) for i in range(N_POD_ARRAYS + 3)
        )

    def _pool(self, behaviors, clock, open_seconds=5.0):
        from karpenter_tpu.solver.pool import SolverPool

        calls = {a: 0 for a in behaviors}

        class FakeClient:
            def __init__(self, address):
                self.address = address

            def pack_begin(self, *inputs, n_max, prof=None, record=True):
                calls[self.address] += 1

                def wait():
                    return behaviors[self.address](self.address)

                return wait

            def close(self):
                pass

        pool = SolverPool(
            list(behaviors),
            client_factory=FakeClient,
            clock=lambda: clock[0],
            breaker_open_seconds=open_seconds,
        )
        return pool, calls

    def test_corrupt_member_quarantined_failover_and_recovery(self):
        clock = [0.0]

        def corrupt(addr):
            raise IntegrityError(
                f"{addr} frame checksum mismatch", address=addr, kind="checksum"
            )

        behaviors = {"a:1": corrupt, "b:1": lambda addr: ("ok", addr)}
        inputs = self._fake_inputs()
        pool, calls = self._pool(behaviors, clock)
        key = pool._catalog_key(inputs[N_POD_ARRAYS:])
        order = pool.ring.ordered(key)
        primary, survivor = order[0], order[1]
        if primary == "b:1":  # make the corrupt member the primary
            behaviors["b:1"], behaviors["a:1"] = (
                behaviors["a:1"], behaviors["b:1"],
            )
        quarantines = []
        pool.on_quarantine = lambda reason, addr, detail: quarantines.append(
            (reason, addr)
        )
        # the corrupt pack fails over through the ring: the caller still
        # gets a GOOD result, from the survivor
        out = pool.pack_begin(*inputs, n_max=4)()
        assert out == ("ok", survivor)
        # the corrupt member is QUARANTINED: breaker forced open, counted,
        # evented — and never retried within the cool-off
        assert not pool._breaker(primary).available()
        assert pool._breaker(survivor).available()
        assert quarantines == [("checksum", primary)]
        assert integrity.totals().get("quarantines") == 1
        calls_at_quarantine = calls[primary]
        out = pool.pack_begin(*inputs, n_max=4)()
        assert out == ("ok", survivor)
        assert calls[primary] == calls_at_quarantine  # no same-member retry
        # half-open after the cool-off: a healed member earns its way back
        clock[0] = 6.0
        behaviors[primary] = lambda addr: ("healed", addr)
        out = pool.pack_begin(*inputs, n_max=4)()
        assert out == ("healed", primary)
        assert pool._breaker(primary).state == "closed"
        # a member still corrupting on its probe re-quarantines immediately
        behaviors[primary] = corrupt
        out = pool.pack_begin(*inputs, n_max=4)()
        assert out == ("ok", survivor)
        assert not pool._breaker(primary).available()
        assert integrity.totals().get("quarantines") == 2
        pool.close()


# ---------------------------------------------------------------------------
# host-side NaN/bounds screen
# ---------------------------------------------------------------------------


def _clean_result(p=6, n_max=8, r=3):
    assignment = np.zeros(p, np.int32)
    node_sig = np.zeros(n_max, np.int32)
    node_host = np.full(n_max, -1, np.int32)
    node_req = np.zeros((n_max, r), np.float32)
    node_req[0] = 1.0
    return [assignment, node_sig, node_host, node_req, np.asarray([1], np.int32)]


class TestScreen:
    def test_clean_result_passes(self):
        assert integrity.screen_result(_clean_result(), n_pods=6) is None

    def test_nan_in_node_req_caught(self):
        result = _clean_result()
        result[3][0, 1] = np.nan
        assert "non-finite" in integrity.screen_result(result, n_pods=6)

    def test_assignment_out_of_bounds_caught(self):
        result = _clean_result()
        result[0][2] = 7  # n_nodes is 1
        assert "assignment outside" in integrity.screen_result(result, n_pods=6)
        result = _clean_result()
        result[0][0] = np.float32(np.nan).view(np.int32)  # the SDC bit pattern
        assert "assignment outside" in integrity.screen_result(result, n_pods=6)

    def test_n_nodes_out_of_range_caught(self):
        result = _clean_result()
        result[4] = np.asarray([9], np.int32)  # n_max is 8
        assert "n_nodes" in integrity.screen_result(result, n_pods=6)

    def test_negative_totals_caught(self):
        result = _clean_result()
        result[3][0, 0] = -4.0
        assert "negative" in integrity.screen_result(result, n_pods=6)

    def test_screen_failure_quarantines_and_serves_ffd(self):
        """A corrupt result from the (mocked) accelerated path: the batch
        still schedules (FFD floor), the screen counter moves, the shape
        class is quarantined, and degraded_solves_total attributes it."""
        from karpenter_tpu import metrics as m
        from karpenter_tpu.kube.client import Cluster
        from karpenter_tpu.solver.backend import TpuScheduler

        constraints, catalog, pods, daemon, _ = encoded_batch()
        sched = TpuScheduler(Cluster(), rng=random.Random(0))

        def corrupt_pack(batch):
            def finish():
                result = _clean_result(
                    p=len(batch.pod_valid), n_max=8, r=batch.usable.shape[1]
                )
                result[3][0, 0] = np.nan
                return tuple(result), None

            return finish

        sched._pack = corrupt_pack
        before = m.REGISTRY.get_sample_value(
            "karpenter_solver_degraded_solves_total",
            {"reason": "integrity_screen", "address": "local"},
        ) or 0.0
        nodes = sched.solve(constraints, catalog, list(pods))
        assert nodes and sum(len(n.pods) for n in nodes) == len(pods)
        assert integrity.totals().get("screen_failures") == 1
        assert integrity.totals().get("quarantines") == 1
        after = m.REGISTRY.get_sample_value(
            "karpenter_solver_degraded_solves_total",
            {"reason": "integrity_screen", "address": "local"},
        )
        assert after == before + 1
        # the shape class is quarantined: the next solve goes straight to
        # FFD without touching the (corrupt) accelerated path
        assert sched._pack_breakers.open_dependencies()


# ---------------------------------------------------------------------------
# canary cross-check
# ---------------------------------------------------------------------------


from karpenter_tpu.solver.native import native_available  # noqa: E402

requires_native = pytest.mark.skipif(
    not native_available(wait=120), reason="g++/native packer unavailable"
)


class TestCanary:
    def _served(self, batch, n_max=None):
        """A device-kernel solve of the batch, as host arrays — what the
        canary would be cross-checking in production."""
        import jax

        from karpenter_tpu.solver import kernel

        n_max = n_max or max(256, len(batch.pod_valid) // 4)
        result = kernel.pack(*batch.pack_args(), n_max=n_max)
        return tuple(np.asarray(a) for a in jax.device_get(tuple(result)))

    @requires_native
    def test_mismatch_quarantines_by_provenance(self):
        from karpenter_tpu.kube.client import Cluster
        from karpenter_tpu.solver.backend import TpuScheduler

        _, _, _, _, batch = encoded_batch()
        sched = TpuScheduler(Cluster(), rng=random.Random(0), canary_rate=1.0)
        served = list(self._served(batch))
        served[0] = np.array(served[0])
        served[0][0] = -1  # pod 0 silently dropped: screen-clean, wrong
        quarantined = []

        class FakePool:
            def quarantine(self, address, reason, detail=""):
                quarantined.append((address, reason))

        sched._remote = FakePool()
        sched._canary_check(batch, tuple(served), "10.0.0.1:50051")
        totals = integrity.totals()
        assert totals.get("canary_solves") == 1
        assert totals.get("canary_mismatches") == 1
        assert quarantined == [("10.0.0.1:50051", "canary")]

    @requires_native
    def test_local_mismatch_quarantines_shape_class(self):
        from karpenter_tpu.kube.client import Cluster
        from karpenter_tpu.solver.backend import TpuScheduler

        _, _, _, _, batch = encoded_batch()
        sched = TpuScheduler(Cluster(), rng=random.Random(0), canary_rate=1.0)
        served = list(self._served(batch))
        served[3] = np.array(served[3])
        served[3][0, 0] += 1.0  # wrong totals, screen-clean
        sched._canary_check(batch, tuple(served), "")
        assert integrity.totals().get("canary_mismatches") == 1
        assert sched._pack_breakers.open_dependencies()

    @requires_native
    def test_no_false_positives_across_100_seeded_solves(self):
        """The no-false-positive bar: across 100 seeded device-kernel
        solves of varied batches, the native canary agrees every time —
        a canary that cries wolf would quarantine healthy members."""
        from karpenter_tpu.kube.client import Cluster
        from karpenter_tpu.solver.backend import TpuScheduler

        sched = TpuScheduler(Cluster(), rng=random.Random(0), canary_rate=1.0)
        for seed in range(100):
            _, _, _, _, batch = encoded_batch(n_pods=6, seed=seed)
            served = self._served(batch)
            sched._canary_check(batch, served, "")
        totals = integrity.totals()
        assert totals.get("canary_solves") == 100
        assert totals.get("canary_mismatches", 0) == 0
        assert totals.get("quarantines", 0) == 0

    def test_canary_pauses_under_brownout(self):
        from karpenter_tpu.kube.client import Cluster
        from karpenter_tpu.solver.backend import TpuScheduler

        _, _, _, _, batch = encoded_batch()
        sched = TpuScheduler(Cluster(), rng=random.Random(0), canary_rate=1.0)
        sched.router.set_probes_paused(True)  # brownout rung >= 1
        sched._maybe_canary(batch, None, {"packer_backend": "device"})
        assert sched._canary_thread is None
        assert integrity.totals().get("canary_solves", 0) == 0
        sched.router.set_probes_paused(False)

    def test_canary_samples_by_rate(self):
        from karpenter_tpu.kube.client import Cluster
        from karpenter_tpu.solver.backend import TpuScheduler

        sched = TpuScheduler(Cluster(), rng=random.Random(0), canary_rate=0.0)
        sched._maybe_canary(None, None, {"packer_backend": "device"})
        assert sched._canary_thread is None
        sched.canary_rate = 1.0
        # non-device packs are never canaried (native served = nothing to
        # cross-check against)
        sched._maybe_canary(None, None, {"packer_backend": "native"})
        assert sched._canary_thread is None
