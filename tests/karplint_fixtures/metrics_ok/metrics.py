# karplint-fixture: clean=metric-name
"""Convention-conformant, documented metrics (see ../docs/metrics.md)."""
from prometheus_client import Counter, Gauge, Histogram

THINGS = Counter("ok_things_total", "Things that happened.", namespace="karpenter")
DEPTH = Gauge("ok_queue_depth", "Items queued.", namespace="karpenter")
DURATION = Histogram("ok_op_duration_seconds", "Op latency.", namespace="karpenter")
# labels matching the docs row exactly, and a shared label-set constant
# behind a parenthesized (wildcard) docs cell
LABELED = Counter(
    "ok_labeled_total", "Labeled things.", ["node", "reason"],
    namespace="karpenter",
)
SHARED_LABELS = ["node", "zone"]
SHARED = Gauge(
    "ok_shared_gauge", "Shared-label gauge.", SHARED_LABELS,
    namespace="karpenter",
)
