# karplint-fixture: clean=span-closed
"""Near-misses: the sanctioned context-manager API, and unrelated names
that merely end in `span`."""
from karpenter_tpu import obs


def traced_instrumentation(batch):
    with obs.tracer().span("solve.encode") as sp:  # the one sanctioned way
        sp.set_attribute("pods", len(batch))
    return batch


def unrelated_names(widget):
    widget.restart_spanner()  # not start_span
    lifespan = widget.span  # attribute read, not a call
    return lifespan
