# karplint-fixture: expect=span-closed
"""A bare start_span call: the span never closes, never exports, and
mis-parents every later span in this context."""
from karpenter_tpu import obs


def leaky_instrumentation(batch):
    span = obs.tracer().start_span("solve.encode")  # span-closed: bare open
    span.set_attribute("pods", len(batch))
    return batch
