# karplint-fixture: expect=bounded-wait
"""Unbounded parks: a queue get, an event wait, a condition wait, and a
future result, all timeout-less — each one parks its thread forever when
the far side sheds, crashes, or simply never produces."""

import queue
import threading


class Worker:
    def __init__(self):
        self._queue = queue.Queue()
        self._done = threading.Event()
        self._cv = threading.Condition()

    def run(self, future):
        item = self._queue.get()  # blocks forever on an idle producer
        self._done.wait()  # blocks forever if the setter shed the work
        with self._cv:
            self._cv.wait()  # missed-notify = parked forever
        return item, future.result()  # wedged transport = parked forever
