# karplint-fixture: clean=bounded-wait
"""Bounded parks (the near-miss): every wait carries a timeout and the
loop re-checks its stop condition, so a dead producer costs one slice,
not a thread. A dict's ``.get(key)`` must not trip the queue heuristic."""

import queue
import threading


class Worker:
    def __init__(self):
        self._queue = queue.Queue()
        self._done = threading.Event()
        self._cv = threading.Condition()
        self._stopped = False
        self._config = {}

    def run(self, future):
        try:
            item = self._queue.get(timeout=1.0)
        except queue.Empty:
            item = None
        while not self._done.wait(0.5):
            if self._stopped:
                break
        with self._cv:
            self._cv.wait(0.5)
        # a plain dict .get with a key argument is not a queue park
        mode = self._config.get("mode")
        return item, mode, future.result(timeout=5.0)
