# karplint-fixture: expect=retry-idempotent
"""Retried callables without the marker, and the inverse crime: a
create-path mutator carrying @idempotent."""
from karpenter_tpu.resilience import RetryPolicy, idempotent

_policy = RetryPolicy(max_attempts=3, dependency="fixture")


def launch_mutation(x):
    return x + 1


def run():
    return _policy.call(launch_mutation, 1)  # fires: retried, unmarked


def run_lambda():
    return _policy.call(lambda: 0)  # fires: anonymous retried callable


class FixtureProvider:
    @idempotent
    def create(self, request):  # fires: marked but token-LESS (no replay)
        return request

    def delete(self, node):  # fires: retried by the metered decorator, unmarked
        return None

    def get_instance_types(self, provider=None):  # fires: unmarked
        return []

    def poll_disruptions(self):  # fires: unmarked
        return []


class TokenedButUnmarkedProvider:
    def create(self, request):  # fires: token-carrying create, unmarked
        token = request.launch_token
        return (request, token)

    @idempotent
    def delete(self, node):
        return None

    @idempotent
    def get_instance_types(self, provider=None):
        return []

    @idempotent
    def poll_disruptions(self):
        return []
