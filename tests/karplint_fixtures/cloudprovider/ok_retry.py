# karplint-fixture: clean=retry-idempotent
"""Near-misses: breaker-only policies (max_attempts=1) need no marker,
marked callables pass, abstract interfaces are exempt, and unresolvable
callables are skipped rather than guessed at."""
import abc

from karpenter_tpu.resilience import RetryPolicy, idempotent

_create_policy = RetryPolicy(max_attempts=1, dependency="fixture:create")
_read_policy = RetryPolicy(max_attempts=3, dependency="fixture:read")


def launch_once(request):
    return request


@idempotent
def describe(name):
    return name


def run(fn):
    _create_policy.call(launch_once, 1)  # breaker-only: no marker needed
    _read_policy.call(describe, "n")  # marked: fine
    _read_policy.call(fn)  # a parameter: unresolvable, skipped


class AbstractProvider(abc.ABC):
    @abc.abstractmethod
    def create(self, request): ...

    @abc.abstractmethod
    def delete(self, node): ...

    @abc.abstractmethod
    def get_instance_types(self, provider=None): ...


class GoodProvider:
    def create(self, request):  # unmarked token-less create: correct
        return request

    @idempotent
    def delete(self, node):
        return None

    @idempotent
    def get_instance_types(self, provider=None):
        return []

    @idempotent
    def poll_disruptions(self):
        return []


class GoodTokenProvider:
    @idempotent
    def create(self, request):  # marked token-carrying create: correct
        if request.launch_token in self.launched:
            return self.launched[request.launch_token]
        return request

    @idempotent
    def delete(self, node):
        return None

    @idempotent
    def get_instance_types(self, provider=None):
        return []

    @idempotent
    def poll_disruptions(self):
        return []
