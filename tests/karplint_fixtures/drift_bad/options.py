# karplint-fixture: expect=drift-flag,drift-chart
"""A drifted flag surface: `--cache-dir` and its env twin shipped without
a docs row, the docs table keeps a retired flag's row, the deploy
manifest passes a flag nothing defines AND sets a real flag the chart
cannot render, the chart template reads an undefined values key, and
values.yaml carries a knob no template reads."""
import argparse
import os


def _env(key, default):
    return os.environ.get(key, default)


def parse(argv=None):
    ap = argparse.ArgumentParser(prog="sim")
    ap.add_argument("--listen-port", default=_env("SIM_LISTEN_PORT", "8080"))
    ap.add_argument("--cache-dir", default=_env("SIM_CACHE_DIR", ""))
    return ap.parse_args(argv)
