# Fuzz-corpus stub for the drift-status fixture: it exercises
# STATUS_ACCEPTED only, so the sibling wire.py's other constants fire
# the never-fuzzed check. (All comments on purpose — pytest collects
# nothing here.)
