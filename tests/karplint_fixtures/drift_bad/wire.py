# karplint-fixture: expect=drift-status
"""A drifted wire-constant surface: STATUS_REJECTED is dispatched on but
never fuzzed, and the resume capability bit below is defined on this end
only — nothing anywhere dispatches on it."""

STATUS_ACCEPTED = 0
STATUS_REJECTED = 1
PROTO_RESUME = 2


def encode(status):
    if status == STATUS_REJECTED:
        return b"\x01"
    return bytes([STATUS_ACCEPTED])
