# karplint-fixture: expect=debug-endpoint
"""A health handler growing its own private /debug payload: the exact
controller/sidecar parity drift the shared obs.debug_*_payload helpers
exist to prevent — this body will diverge from the other server's the
first time either is touched."""
import json


class SneakyHandler:
    def do_GET(self):
        if self.path.startswith("/debug/traces"):
            # inline payload build: no shared helper, no parity
            trees = self.exporter.snapshot(limit=50)
            body = json.dumps({"traces": trees}).encode()
            self.send_response(200)
            self.end_headers()
            self.wfile.write(body)
        else:
            self.send_response(404)
            self.end_headers()
