# karplint-fixture: clean=debug-endpoint
"""Near-misses that must stay clean: a /debug branch routing through the
shared obs payload helper, a non-debug branch building whatever it likes,
and a /debug string outside any do_GET handler."""
import json

DOC_LINK = "/debug/traces"  # a bare mention outside do_GET is not a handler


class ParityHandler:
    def do_GET(self):
        if self.path.startswith("/debug/traces"):
            # the sanctioned shape: the ONE shared body builder
            from karpenter_tpu import obs

            body = json.dumps(obs.debug_traces_payload("")).encode()
            self.send_response(200)
            self.end_headers()
            self.wfile.write(body)
        elif self.path == "/healthz":
            # not a /debug path: free to answer inline
            self.send_response(200)
            self.end_headers()
            self.wfile.write(b"ok")
        else:
            self.send_response(404)
            self.end_headers()


def do_get_elsewhere(path):
    # not a do_GET method: handler-shaped strings elsewhere stay clean
    if path.startswith("/debug/flight"):
        return {"records": []}
    return None
