# karplint-fixture: expect=event-decision-id
"""An incident plane (obs/incidents.py shape) emitting its Warning
WITHOUT the decision-id keyword: incident files are decision-path even
under obs/ — an IncidentDetected event that can't be walked back into
/debug/decisions is the same audit dead end as an unannotated
LaunchFailed."""


class IncidentLog:
    def __init__(self, recorder):
        self.recorder = recorder

    def emit(self, record):
        # Warning from an incident file, no decision_id= — must fire
        self.recorder.event(
            "Provisioner", record["route"], "IncidentDetected",
            "latency regression detected", type="Warning",
        )
