# karplint-fixture: clean=event-decision-id
"""The sanctioned incident-plane shape: the IncidentDetected Warning
carries the first correlated decision id (empty when the incident window
held no provisioning round — honest and allowed), and Normal events need
no id."""


class IncidentLog:
    def __init__(self, recorder):
        self.recorder = recorder

    def emit(self, record):
        decisions = record.get("decisions") or []
        self.recorder.event(
            "Provisioner", record["route"], "IncidentDetected",
            "latency regression detected", type="Warning",
            decision_id=decisions[0]["id"] if decisions else "",
        )

    def closed(self, record):
        # Normal events carry no decision obligation
        self.recorder.event(
            "Provisioner", record["route"], "IncidentResolved",
            "stage recovered",
        )
