# karplint-fixture: clean=drift-status
"""A consistent wire-constant surface: both words are dispatched on by
the decoder below and both appear in the sibling fuzz corpus."""

STATUS_READY = 0
STATUS_BUSY = 1


def decode(word):
    if word == STATUS_BUSY:
        return "busy"
    return "ready" if word == STATUS_READY else "?"
