# karplint-fixture: clean=drift-flag,drift-chart
"""A consistent flag surface the drift rules must NOT flag: every flag
and env twin documented, the deploy manifest renders only defined flags
(including the `--no-verbose` boolean twin), and the chart's values keys
and template references line up exactly."""
import argparse
import os


def _env(key, default):
    return os.environ.get(key, default)


def parse(argv=None):
    ap = argparse.ArgumentParser(prog="sim")
    ap.add_argument("--listen-port", default=_env("SIM_OK_LISTEN_PORT", "8080"))
    ap.add_argument(
        "--verbose", action=argparse.BooleanOptionalAction, default=False
    )
    return ap.parse_args(argv)
