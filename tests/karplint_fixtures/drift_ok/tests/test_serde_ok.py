# Fuzz-corpus stub for the drift-status near-miss: it names both of the
# sibling wire.py's words — STATUS_READY and STATUS_BUSY — so neither
# fires the never-fuzzed check. (All comments on purpose — pytest
# collects nothing here.)
