# karplint-fixture: expect=patch-literal-list
"""List-valued merge-patch fields written with literals — the RFC 7386
wholesale-replace clobber, in every literal shape."""


def set_active(cluster, name, cond):
    cluster.patch_status(
        "provisioners", name,
        {"conditions": [cond]},  # fires: literal list erases other writers
    )


def taint(cluster, node_name, wire, extra):
    cluster.merge_patch(
        "nodes", node_name,
        {
            "spec": {
                "unschedulable": True,
                "taints": [wire] + extra,  # fires: concatenation literal
            }
        },
    )


def rebuild(cluster, pod, conds):
    cluster.merge_patch(
        "pods", pod,
        {"status": {"conditions": [c for c in conds if c]}},  # fires: comprehension
    )


def finalize(cluster, name, fin):
    cluster.merge_patch(
        "nodes", name,
        {"metadata": {"finalizers": [fin]}},  # fires: literal finalizers list
    )
