# karplint-fixture: clean=patch-literal-list
"""The sanctioned shapes: RMW helper calls and names built above."""
from karpenter_tpu.kube.patch import upsert_condition, upsert_taint


def set_active(cluster, name, base_wire, cond):
    cluster.patch_status(
        "provisioners", name,
        {"conditions": upsert_condition(base_wire, cond)},
    )


def taint(cluster, node, wire):
    full = upsert_taint([t for t in node.spec.taints], wire)
    cluster.merge_patch("nodes", node.name, {"spec": {"taints": full}})


def other_fields(cluster, name):
    # non-list fields may be literals; scalar-only patches are fine
    cluster.merge_patch("nodes", name, {"spec": {"unschedulable": True}})
