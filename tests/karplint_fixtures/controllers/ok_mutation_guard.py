# karplint-fixture: clean=mutation-guard
"""Near-misses mutation-guard must NOT flag: a lexically prior ownership
check, the explicit exemption marker for a cloud-notified path, and a
mutation helper no reconcile entry can reach."""


class Scaler:
    def __init__(self, cloud_provider, ownership):
        self.cloud_provider = cloud_provider
        self.ownership = ownership

    def reconcile(self):
        for name in ("a", "b"):
            if not self.ownership.owns(name):
                continue
            self.cloud_provider.delete(name)  # proof precedes the mutation

    def reconcile_interruptions(self, node):
        # the provider already reclaimed this capacity; fencing proves
        # nothing on this path, so the exemption is explicit + grep-able
        # mutation-guard: exempt — cloud-notified interruption path
        self.cloud_provider.terminate(node)

    def _maintenance(self, name):
        # never called from a reconcile entry: outside the contract
        self.cloud_provider.create(name)
