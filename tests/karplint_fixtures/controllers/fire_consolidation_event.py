# karplint-fixture: expect=event-decision-id
"""A consolidation wave emitting its Warning event WITHOUT the
decision-id keyword: the operator sees "budget blocked" with no path
back into /debug/decisions to ask WHICH wave's plan was deferred — the
audit dead end rule #13 closes on consolidation event sites too."""


class WaveRunner:
    def __init__(self, cluster, recorder):
        self.cluster = cluster
        self.recorder = recorder
        self.decision_id = "d-1234"

    def budget_blocked(self, provisioner, blocked, allowed):
        # Warning on the consolidation decision path, no decision_id= —
        # must fire
        self.recorder.event(
            "Provisioner", provisioner, "ConsolidationBudgetBlocked",
            f"disruption budget deferred {blocked} victim(s) "
            f"({allowed} allowed)", type="Warning",
        )
