# karplint-fixture: expect=event-decision-id
"""A provisioning decision path emitting a Warning event WITHOUT the
decision-id keyword: the operator's `kubectl describe` shows "launch
failed" with no path back into /debug/decisions — the audit dead end the
event-decision-id rule exists to close."""


class Worker:
    def __init__(self, cluster, recorder):
        self.cluster = cluster
        self.recorder = recorder
        self.last_decision_id = "d-abc"

    def launch_failed(self, name):
        # Warning on the decision path, no decision_id= — must fire
        self.recorder.event(
            "Provisioner", name, "LaunchFailed",
            "node launch failed; see controller logs", type="Warning",
        )
