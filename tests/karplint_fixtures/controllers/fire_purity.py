# karplint-fixture: expect=reconcile-io
"""Raw I/O inside reconcile/poll bodies — every banned shape."""
import time

import requests


class NodeController:
    def reconcile(self, name):
        time.sleep(1.0)  # unmetered stall, no Budget
        requests.get("http://metadata/computeMetadata/v1/")  # bare HTTP
        return None

    def poll_disruptions(self):
        import socket  # raw socket import inside a poll body

        s = socket.socket()
        return s
