# karplint-fixture: clean=event-decision-id
"""Near-misses that must stay clean: a decision-path Warning that DOES
carry decision_id (empty before the first record is honest and allowed),
and a Normal event which needs no id."""


class Worker:
    def __init__(self, cluster, recorder):
        self.cluster = cluster
        self.recorder = recorder
        self.last_decision_id = ""

    def launch_failed(self, name):
        # the sanctioned shape: the decision id rides the event annotation
        self.recorder.event(
            "Provisioner", name, "LaunchFailed",
            "node launch failed; see controller logs", type="Warning",
            decision_id=self.last_decision_id,
        )

    def launched(self, name):
        # Normal events carry no decision obligation
        self.recorder.event(
            "Node", name, "Launched", "launched a node",
        )
