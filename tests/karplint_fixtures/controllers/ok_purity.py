# karplint-fixture: clean=reconcile-io
"""Near-misses: clocks are fine, sleeps outside reconcile bodies are
fine (worker loops own their cadence), metered calls are the sanctioned
route."""
import time


class NodeController:
    def reconcile(self, name):
        start = time.monotonic()  # reading a clock is not sleeping
        self.cloud_provider.poll_disruptions()  # metered provider call
        return max(0.0, 5.0 - (time.monotonic() - start))

    def _worker_loop(self):
        # not a reconcile/poll body: a worker thread may pace itself
        time.sleep(0.1)
