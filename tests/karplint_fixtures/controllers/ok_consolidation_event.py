# karplint-fixture: clean=event-decision-id
"""The sanctioned consolidation event shapes: every Warning carries the
wave's decision id (empty before the first record is honest and
allowed), and the Normal `Consolidated` event may carry one too."""


class WaveRunner:
    def __init__(self, cluster, recorder):
        self.cluster = cluster
        self.recorder = recorder
        self.decision_id = ""

    def budget_blocked(self, provisioner, blocked, allowed):
        self.recorder.event(
            "Provisioner", provisioner, "ConsolidationBudgetBlocked",
            f"disruption budget deferred {blocked} victim(s) "
            f"({allowed} allowed)", type="Warning",
            decision_id=self.decision_id,
        )

    def consolidated(self, provisioner, retired, kept):
        # Normal events carry no decision obligation, but stamping the id
        # anyway keeps the audit trail greppable
        self.recorder.event(
            "Provisioner", provisioner, "Consolidated",
            f"retiring {retired} node(s), {kept} kept in place",
            decision_id=self.decision_id,
        )
