# karplint-fixture: expect=mutation-guard
"""A cloud mutation reachable from a reconcile entry with no
owned()/fenced() proof anywhere on the call-graph path — the stale-leader
split-brain shape PR-6/PR-11 fencing exists to prevent."""


class Expirer:
    def __init__(self, cloud_provider, clock):
        self.cloud_provider = cloud_provider
        self._clock = clock

    def reconcile(self):
        for name in self._expired():
            self._retire(name)

    def _retire(self, name):
        self.cloud_provider.delete(name)  # no guard on any path here

    def _expired(self):
        return []
