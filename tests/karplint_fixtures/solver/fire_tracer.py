# karplint-fixture: expect=tracer-branch, tracer-host-sync
"""Every way the tracer rules must fire: data-dependent Python control
flow and host syncs inside jit-reachable code, both directly in a jitted
def and in a helper reached through the call graph."""
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


@partial(jax.jit, static_argnames=("n_max",))
def bad_pack(pod_req, n_max):
    total = jnp.sum(pod_req)
    if total > 0:  # tracer-branch: Python `if` on a traced value
        pod_req = pod_req + 1.0
    host = float(total)  # tracer-host-sync: float() on a traced value
    arr = np.asarray(pod_req)  # tracer-host-sync: numpy op on a traced value
    count = total.item()  # tracer-host-sync: .item()
    return pod_req, host, arr, count


def _drain(x):
    # reachable only through `entry` below — the cross-function graph
    while x.sum() > 0:  # tracer-branch via reachability
        x = x - 1
    return x


@jax.jit
def entry(x):
    return _drain(x)
