# karplint-fixture: expect=span-closed
"""An obs call inside jit-traced code: host-side span machinery inside
the traced kernel serializes the device pipeline on every solve."""
import jax

from karpenter_tpu import obs


@jax.jit
def traced_kernel(pod_req):
    with obs.tracer().span("kernel.pack"):  # span-closed: obs in jit
        return pod_req + 1.0
