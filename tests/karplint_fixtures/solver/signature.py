# karplint-fixture: clean=tracer-dtype
"""Minimal dtype-contract source: the tracer-dtype rule parses the
``# [shape] dtype`` trailing comments off this file (the corpus stand-in
for karpenter_tpu/solver/signature.py)."""


class Signature:
    sig_id: int
    type_mask: object  # [T] bool — types surviving requirement compat
    frontier: object  # [F, R] f32 — Pareto-max usable capacities


class SignatureTable:
    def __init__(
        self,
        usable_capacity,  # [T, R] capacity - overhead, f32
    ):
        self.usable = usable_capacity
