# karplint-fixture: expect=span-closed, tracer-host-sync
"""An SLO finish-hook leaking into traced solver code: the engine is
host-side span machinery (obs call = span-closed P0), and feeding it a
traced value forces a host sync per solve (tracer-host-sync)."""
import jax
import jax.numpy as jnp

from karpenter_tpu import obs


@jax.jit
def pack_with_inline_slo(pod_req):
    total = jnp.sum(pod_req)
    eng = obs.slo_engine()  # span-closed: obs machinery inside jit
    if eng is not None:
        # tracer-host-sync: float() on a traced value to fill a histogram
        eng.record_ratio("session.catalog_hit_rate", float(total) > 0)
    return pod_req
