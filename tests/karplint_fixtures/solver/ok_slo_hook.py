# karplint-fixture: clean=span-closed, tracer-host-sync
"""Near-miss: the sanctioned SLO hook shape — the engine consumes
COMPLETED spans on the tracer's host side; nothing obs-flavored is
reachable from the jit root."""
import jax
import jax.numpy as jnp

from karpenter_tpu import obs


@jax.jit
def pure_kernel(pod_req):
    # the kernel stays pure device data flow; judgment happens after
    return jnp.cumsum(pod_req, axis=0)


def finish_hook(span):
    # runs host-side when the tracer closes a watched span — never from
    # inside traced code, so the float() below is a host float on a host
    # value, not a device sync
    eng = obs.slo_engine()
    if eng is not None:
        eng(span)
    return float(span.duration_s)
