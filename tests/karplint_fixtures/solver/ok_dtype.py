# karplint-fixture: clean=tracer-dtype
"""Contract-conformant casts, plus names outside the contract."""
import numpy as np


def upload(batch):
    frontiers = np.asarray(batch.frontiers, np.float32)  # matches f32
    join = batch.join_table.astype(np.int32)  # matches i32
    usable = batch.usable.astype(np.float32)  # matches f32
    pod_tab = batch.pod_core.astype(np.int16)  # not a contract name
    other = np.asarray(batch.scratch, np.int64)  # not a contract name
    return frontiers, join, usable, pod_tab, other
