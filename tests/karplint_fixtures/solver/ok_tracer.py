# karplint-fixture: clean=tracer-branch, tracer-host-sync
"""Near-misses the tracer rules must NOT flag: static branches (shapes,
static_argnames, module constants), jnp data flow, and host helpers that
are not reachable from any jit root."""
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

BLOCK = 8


@partial(jax.jit, static_argnames=("n_max", "kernel"))
def good_pack(pod_req, n_max, kernel):
    P, R = pod_req.shape  # shape reads are static under tracing
    if P % BLOCK != 0:  # static: shape arithmetic vs a module constant
        raise ValueError("pad me")
    if kernel == "scan":  # static: named in static_argnames
        out = jnp.cumsum(pod_req, axis=0)
    else:
        out = pod_req
    n = max(BLOCK, n_max)  # static arithmetic
    return jnp.where(out > 0, out, 0.0)[:n]  # data-dependence via where, not `if`


def host_decode(buf, n):
    # NOT reachable from a jit root: host numpy and float() are the point
    arr = np.asarray(buf)
    if arr.sum() > 0:
        return float(arr[0]), int(n)
    return 0.0, int(n)
