# karplint-fixture: expect=tracer-dtype
"""Casts that disagree with the signature.py wire contract."""
import numpy as np


def upload(batch):
    frontiers = np.asarray(batch.frontiers, np.int32)  # contract says f32
    mask = batch.sig_type_mask.astype(np.int8)  # contract says bool
    join = batch.join_table.astype(np.float32)  # contract says i32
    usable = batch.usable.astype(np.float64)  # contract says f32
    return frontiers, mask, join, usable
