# karplint-fixture: clean=kube-transport
"""Near-misses that must stay clean: a module using its OWN private wire
helper (the cloud HTTP wire's shape — its `_request` is its choke point),
and ordinary Cluster-surface calls."""
import urllib.request


class OwnWire:
    """Defines its own ``_request``: calling it is this module's private
    transport discipline, not a kube-transport bypass."""

    base_url = "http://cloud.example"

    def _request(self, method, path, body=None):
        req = urllib.request.Request(self.base_url + path, method=method)
        with urllib.request.urlopen(req, timeout=5) as resp:
            return resp.status

    def describe(self):
        return self._request("GET", "/v1/instances")


def through_the_surface(cluster, name):
    # the sanctioned path: every one of these crosses kube/transport.py
    live = cluster.get_live("nodes", name, namespace="")
    cluster.merge_patch("nodes", name, {"spec": {"unschedulable": True}}, namespace="")
    return live
