# karplint-fixture: expect=kube-transport
"""A controller reaching around the kube transport choke point: raw
``http.client`` AND a direct ``._request`` on someone else's client —
both unmetered, unthrottled, breaker-invisible apiserver traffic."""
import http.client


def sneak_patch(cluster, name):
    # bypasses retries/flow control/metrics: the exact blind single-shot
    # write the transport exists to eliminate
    status, doc = cluster._request(
        "PATCH", f"/api/v1/nodes/{name}", {"spec": {"unschedulable": True}}
    )
    return status, doc


def sneak_raw(host):
    conn = http.client.HTTPConnection(host)
    conn.request("GET", "/api/v1/pods")
    return conn.getresponse().status
