# karplint-fixture: expect=metric-name
"""Every naming-convention violation plus an undocumented metric."""
from prometheus_client import Counter, Gauge, Histogram

LAUNCHES = Counter("launches", "Launches.", namespace="karpenter")  # no _total
NODES = Gauge("nodes_total", "Nodes.", namespace="karpenter")  # gauge ends _total
SOLVE = Histogram("solve_time", "Solve time.", namespace="karpenter")  # no unit
GHOST = Counter("karpenter_ghost_total", "Not in docs/metrics.md.")
WEIRD = Gauge("Karpenter__weird_", "Bad charset.")
# documented, conventionally named — but the docs row promises labels
# (node, zone) while the registration declares (node, reason)
MISLABELED = Counter(
    "karpenter_mislabeled_total", "Docs promise different labels.",
    ["node", "reason"],
)
