# karplint-fixture: expect=lock-guard
"""Guarded state mutated outside its declared lock: the PR-1 lazy-init
race class, both as an instance attribute and a module global."""
import threading

_cache_lock = threading.Lock()
_cache = None  # guarded-by: _cache_lock


def get_cache():
    global _cache
    if _cache is None:
        _cache = {}  # fires: unguarded lazy init of a guarded global
    return _cache


class Tracker:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = set()  # guarded-by: self._lock
        self._count = 0  # guarded-by: self._lock

    def add(self, item):
        self._items.add(item)  # fires: mutating method outside the lock
        self._count += 1  # fires: augmented assign outside the lock

    def drop(self, item):
        with self._lock:
            self._items.discard(item)
        self._count -= 1  # fires: mutation after the with block closed
