# karplint-fixture: clean=lock-guard
"""A real violation silenced by the per-line suppression comment — the
escape hatch for deliberate single-writer phases (documented inline)."""
import threading


class Boot:
    def __init__(self):
        self._lock = threading.Lock()
        self._phase = "cold"  # guarded-by: self._lock

    def single_threaded_warmup(self):
        # only the boot thread exists at this point
        self._phase = "warm"  # karplint: disable=lock-guard
