# karplint-fixture: expect=lock-order
"""Lock-order inversion reachable only through the call graph: `fill`
orders fill_lock -> book_lock lexically, `cancel` orders book_lock ->
fill_lock through a helper — two threads entering from different points
deadlock. Plus the degenerate case: a helper re-acquiring the
non-reentrant Lock its caller already holds."""
import threading


class Exchange:
    def __init__(self):
        self._fill_lock = threading.Lock()
        self._book_lock = threading.Lock()

    def fill(self):
        with self._fill_lock:
            with self._book_lock:  # edge: fill_lock -> book_lock
                pass

    def cancel(self):
        with self._book_lock:
            self._revoke()  # edge: book_lock -> fill_lock, via the callee

    def _revoke(self):
        with self._fill_lock:
            pass

    def restate(self):
        with self._book_lock:
            self._audit()  # re-acquires book_lock: one-thread deadlock

    def _audit(self):
        with self._book_lock:
            pass
