# karplint-fixture: expect=lock-blocking
"""Blocking work reachable while a lock is held — the convoy shape the
PR-4 fetch-off-the-solve-lock invariant forbids: one interprocedural
witness (a helper that sleeps) and one direct future wait."""
import threading
import time


class Poller:
    def __init__(self):
        self._state_lock = threading.Lock()
        self._state = {}

    def refresh(self):
        with self._state_lock:
            self._fetch()  # callee sleeps: every reader stalls behind it

    def _fetch(self):
        time.sleep(0.5)
        return dict(self._state)

    def wait_result(self, fut):
        with self._state_lock:
            return fut.result(timeout=5)  # RPC wait under the lock
