# karplint-fixture: clean=lock-guard
"""Near-misses: mutations under the declared lock, the `_locked`-suffix
caller-holds convention, __init__ construction, and unannotated state
(the rule is opt-in by annotation)."""
import threading

_cache_lock = threading.Lock()
_cache = None  # guarded-by: _cache_lock


def get_cache():
    global _cache
    with _cache_lock:
        if _cache is None:
            _cache = {}
        return _cache


class Tracker:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = set()  # guarded-by: self._lock
        self._stats = {}  # unannotated: the rule has no opinion

    def add(self, item):
        with self._lock:
            self._items.add(item)
            self._grow_locked(item)

    def _grow_locked(self, item):
        # `_locked` suffix: the caller holds self._lock
        self._items.add(("grown", item))

    def note(self, k, v):
        self._stats[k] = v  # unannotated → clean
