# karplint-fixture: clean=lock-order,lock-blocking
"""Near-misses the lock rules must NOT flag: one consistent global lock
order, Condition.wait on the held lock's own condition variable (the
sanctioned sleep-releases-the-lock pattern), and blocking work done
after the lock is released."""
import threading
import time


class Journal:
    def __init__(self):
        self._index_lock = threading.Lock()
        self._file_lock = threading.Lock()
        self._flush_cond = threading.Condition(self._index_lock)

    def append(self):
        with self._index_lock:
            with self._file_lock:  # same order everywhere: no cycle
                pass

    def compact(self):
        with self._index_lock:
            with self._file_lock:
                pass

    def wait_flush(self):
        with self._flush_cond:
            # waits on the HELD lock's own cv: the wait releases it
            self._flush_cond.wait(timeout=0.5)

    def drain(self):
        with self._file_lock:
            snapshot = True
        time.sleep(0.01)  # blocking, but the lock is already released
        return snapshot
