"""Parity harness: the TPU batch solver must produce assignment-identical
results to the FFD reference on randomized scenarios (SURVEY.md §7 Phase 1).

Both backends share sorting, topology injection, and daemon-overhead
computation, so identical seeds give identical pod orderings; the kernel then
must make the same accept decision at every step.
"""

import random

import pytest

from karpenter_tpu.api import labels as lbl
from karpenter_tpu.api.objects import NodeSelectorRequirement as R, Taint
from karpenter_tpu.cloudprovider.fake import (
    default_catalog,
    instance_types,
    instance_types_assorted,
    new_instance_type,
)
from karpenter_tpu.cloudprovider.requirements import catalog_requirements
from karpenter_tpu.kube.client import Cluster
from karpenter_tpu.scheduling.ffd import FFDScheduler
from karpenter_tpu.solver.backend import TpuScheduler
from karpenter_tpu.utils import resources as res
from tests.factories import hostname_spread, make_daemonset, make_pod, make_provisioner, zone_spread


def both_solve(pods, catalog, cluster=None, provisioner=None, seed=42):
    cluster = cluster or Cluster()
    provisioner = provisioner or make_provisioner()
    constraints = provisioner.spec.constraints
    constraints.requirements = constraints.requirements.merge(catalog_requirements(catalog))
    ffd_nodes = FFDScheduler(cluster, rng=random.Random(seed)).solve(constraints, catalog, pods)
    tpu_nodes = TpuScheduler(cluster, rng=random.Random(seed)).solve(constraints, catalog, pods)
    return ffd_nodes, tpu_nodes


def assert_parity(ffd_nodes, tpu_nodes):
    assert len(ffd_nodes) == len(tpu_nodes), (
        f"node count: ffd={len(ffd_nodes)} tpu={len(tpu_nodes)}"
    )
    ffd_sets = sorted(sorted(p.metadata.name for p in n.pods) for n in ffd_nodes)
    tpu_sets = sorted(sorted(p.metadata.name for p in n.pods) for n in tpu_nodes)
    assert ffd_sets == tpu_sets, "pod→node assignments differ"
    # same cheapest launchable type per node ⇒ same launch price
    ffd_prices = sorted(n.instance_type_options[0].effective_price() for n in ffd_nodes)
    tpu_prices = sorted(n.instance_type_options[0].effective_price() for n in tpu_nodes)
    assert ffd_prices == pytest.approx(tpu_prices)


class TestBasicParity:
    def test_generic_pods(self):
        pods = [make_pod(requests={"cpu": "1", "memory": "1Gi"}) for _ in range(20)]
        assert_parity(*both_solve(pods, instance_types(20)))

    def test_single_pod(self):
        assert_parity(*both_solve([make_pod(requests={"cpu": "1"})], default_catalog()))

    def test_unschedulable_dropped_by_both(self):
        pods = [make_pod(requests={"cpu": "10000"}), make_pod(requests={"cpu": "1"})]
        ffd, tpu = both_solve(pods, instance_types(10))
        assert_parity(ffd, tpu)
        assert sum(len(n.pods) for n in tpu) == 1

    def test_empty_batch(self):
        ffd, tpu = both_solve([], instance_types(5))
        assert ffd == [] and tpu == []

    def test_selectors_and_assorted_catalog(self):
        catalog = instance_types_assorted()
        pods = (
            [make_pod(requests={"cpu": "0.5"}) for _ in range(5)]
            + [
                make_pod(
                    requests={"cpu": "1"},
                    node_selector={lbl.TOPOLOGY_ZONE: "test-zone-2"},
                )
                for _ in range(5)
            ]
            + [
                make_pod(
                    requests={"cpu": "1"},
                    node_requirements=[R(key=lbl.ARCH, operator="In", values=["arm64"])],
                )
                for _ in range(3)
            ]
            + [
                make_pod(
                    requests={"cpu": "1"},
                    node_requirements=[
                        R(key=lbl.CAPACITY_TYPE, operator="NotIn", values=["spot"])
                    ],
                )
                for _ in range(3)
            ]
        )
        assert_parity(*both_solve(pods, catalog))


class TestTopologyParity:
    def test_zone_spread(self):
        pods = [
            make_pod(
                requests={"cpu": "0.5"},
                labels={"app": "web"},
                topology=[zone_spread(labels={"app": "web"})],
            )
            for _ in range(9)
        ]
        assert_parity(*both_solve(pods, instance_types(30)))

    def test_hostname_spread(self):
        pods = [
            make_pod(
                requests={"cpu": "0.5"},
                labels={"app": "web"},
                topology=[hostname_spread(labels={"app": "web"})],
            )
            for _ in range(6)
        ]
        assert_parity(*both_solve(pods, instance_types(30)))

    def test_mixed_spread_and_generic(self):
        pods = (
            [make_pod(requests={"cpu": "1"}) for _ in range(10)]
            + [
                make_pod(
                    requests={"cpu": "0.5"},
                    labels={"app": "a"},
                    topology=[zone_spread(labels={"app": "a"})],
                )
                for _ in range(5)
            ]
            + [
                make_pod(
                    requests={"cpu": "0.25"},
                    labels={"app": "b"},
                    topology=[hostname_spread(labels={"app": "b"})],
                )
                for _ in range(5)
            ]
        )
        assert_parity(*both_solve(pods, instance_types(30)))


class TestDaemonParity:
    def test_daemon_overhead(self):
        cluster = Cluster()
        cluster.create("daemonsets", make_daemonset(requests={"cpu": "500m"}))
        pods = [make_pod(requests={"cpu": "2"}) for _ in range(6)]
        assert_parity(*both_solve(pods, instance_types(6), cluster=cluster))


class TestExtendedResourcesParity:
    def test_gpu(self):
        pods = [make_pod(requests={res.NVIDIA_GPU: "1", "cpu": "1"}) for _ in range(3)]
        assert_parity(*both_solve(pods, default_catalog()))


class TestRandomizedParity:
    @pytest.mark.parametrize("seed", range(8))
    def test_fuzz(self, seed):
        rng = random.Random(seed)
        catalog_choice = rng.choice(["linear", "assorted", "default"])
        catalog = {
            "linear": lambda: instance_types(rng.randint(5, 60)),
            "assorted": instance_types_assorted,
            "default": default_catalog,
        }[catalog_choice]()
        pods = []
        n = rng.randint(5, 60)
        for i in range(n):
            kind = rng.random()
            requests = {
                "cpu": f"{rng.choice([100, 250, 500, 1000, 1500])}m",
                "memory": f"{rng.choice([128, 256, 512, 1024, 2048])}Mi",
            }
            if kind < 0.4:
                pods.append(make_pod(requests=requests))
            elif kind < 0.55:
                pods.append(
                    make_pod(
                        requests=requests,
                        node_selector={
                            lbl.TOPOLOGY_ZONE: rng.choice(
                                ["test-zone-1", "test-zone-2", "test-zone-3"]
                            )
                        },
                    )
                )
            elif kind < 0.7:
                pods.append(
                    make_pod(
                        requests=requests,
                        labels={"group": rng.choice(["a", "b"])},
                        topology=[zone_spread(labels={"group": rng.choice(["a", "b"])})],
                    )
                )
            elif kind < 0.85:
                pods.append(
                    make_pod(
                        requests=requests,
                        labels={"group": rng.choice(["a", "b"])},
                        topology=[hostname_spread(labels={"group": rng.choice(["a", "b"])})],
                    )
                )
            else:
                op = rng.choice(["In", "NotIn"])
                pods.append(
                    make_pod(
                        requests=requests,
                        node_requirements=[
                            R(
                                key=lbl.CAPACITY_TYPE,
                                operator=op,
                                values=[rng.choice(["spot", "on-demand"])],
                            )
                        ],
                    )
                )
        assert_parity(*both_solve(pods, catalog, seed=seed))


class TestEncodeCache:
    """Solve-invariant encode state reused across a worker's batches
    (signature table, capacity matrix) — scoped per batch so accumulated
    closure state never leaks into the kernel input."""

    def _setup(self):
        import random

        from karpenter_tpu.cloudprovider.fake import instance_types
        from karpenter_tpu.cloudprovider.requirements import catalog_requirements
        from karpenter_tpu.kube.client import Cluster
        from karpenter_tpu.solver.backend import TpuScheduler
        from tests.factories import make_provisioner

        catalog = instance_types(20)
        c0 = make_provisioner(solver="tpu").spec.constraints
        c0.requirements = c0.requirements.merge(catalog_requirements(catalog))
        return catalog, c0, TpuScheduler(Cluster(), rng=random.Random(0))

    def test_mixed_core_batches_share_one_table(self):
        """Batches with different pod constraint cores must reuse the cached
        table without crashing (round-2 review repro) and still match FFD."""
        import random

        from karpenter_tpu.kube.client import Cluster
        from karpenter_tpu.scheduling.ffd import FFDScheduler
        from tests.factories import make_pod

        catalog, c0, sched = self._setup()
        ffd = FFDScheduler(Cluster(), rng=random.Random(0))
        batches = [
            [make_pod(requests={"cpu": "1"}, node_selector={"team": "a"}) for _ in range(3)],
            [make_pod(requests={"cpu": "1"}) for _ in range(3)],
            [make_pod(requests={"cpu": "1"}, node_selector={"team": "b"}) for _ in range(2)]
            + [make_pod(requests={"cpu": "1"})],
        ]
        for pods in batches:
            v_tpu = sched.solve(c0.clone(), catalog, pods)
            v_ffd = ffd.solve(c0.clone(), catalog, pods)
            a = sorted(
                (sorted(p.key for p in v.pods), v.instance_type_options[0].name)
                for v in v_tpu
            )
            b = sorted(
                (sorted(p.key for p in v.pods), v.instance_type_options[0].name)
                for v in v_ffd
            )
            assert a == b
        assert len(sched._encode_cache.tables) == 1  # one table, reused

    def test_fingerprint_hits_across_fresh_catalog_objects(self):
        """Providers rebuild InstanceType objects per call; the cache must
        key on catalog semantics, not object identity."""
        import copy

        from tests.factories import make_pod

        catalog, c0, sched = self._setup()
        sched.solve(c0.clone(), catalog, [make_pod(requests={"cpu": "1"})])
        fresh = copy.deepcopy(catalog)  # same semantics, all-new objects
        sched.solve(c0.clone(), fresh, [make_pod(requests={"cpu": "1"})])
        assert len(sched._encode_cache.tables) == 1

    def test_lru_bounds_entries(self):
        from karpenter_tpu.solver.encode import EncodeCache

        cache = EncodeCache()
        for i in range(EncodeCache.MAX_ENTRIES + 3):
            cache.put(("k", i), (None, None))
        assert len(cache.tables) == EncodeCache.MAX_ENTRIES

    def test_batch_arrays_scoped_to_batch_cores(self):
        """After a diverse batch grows the table, a simple batch's emitted
        arrays must not inherit the accumulated signature axis."""
        from tests.factories import make_pod

        catalog, c0, sched = self._setup()
        diverse = [
            make_pod(requests={"cpu": "1"}, node_selector={"team": t})
            for t in ("a", "b", "c", "d")
        ]
        sched.solve(c0.clone(), catalog, diverse)
        from karpenter_tpu.scheduling.ffd import daemon_overhead, sort_pods_ffd
        from karpenter_tpu.scheduling.topology import Topology
        from karpenter_tpu.solver import encode as enc

        pods = sort_pods_ffd([make_pod(requests={"cpu": "1"})])
        c = c0.clone()
        Topology(sched.cluster).inject(c, list(pods))
        batch = enc.encode(
            c, sorted(catalog, key=lambda it: it.effective_price()), pods,
            daemon_overhead(sched.cluster, c), cache=sched._encode_cache,
        )
        # base + the plain pod's open signature only
        assert len(batch.signatures) <= 2
        assert batch.join_table.shape[0] == len(batch.signatures)


class TestRandomizedParityWide:
    """Wider feature mix than TestRandomizedParity: pod (anti-)affinity,
    host ports, preferred node affinity, taints/tolerations, extended
    resources, and a live cluster seeded with scheduled pods (topology
    counts) — the interactions the r3 statics/DomainPlan rewrite must keep
    byte-equal between the plan-consuming TPU path and the
    selector-materializing FFD path."""

    @pytest.mark.parametrize("seed", range(16))
    def test_fuzz_wide(self, seed):
        from karpenter_tpu.api.objects import (
            LabelSelector,
            PodAffinityTerm,
            PreferredSchedulingTerm,
            NodeSelectorTerm,
            Toleration,
        )
        from tests.factories import make_node
        from tests.test_scheduling_parity import with_port

        rng = random.Random(1000 + seed)
        catalog = instance_types(rng.randint(10, 50))
        cluster = Cluster()
        # seed the live cluster: scheduled pods feeding topology/affinity
        # counts (reference: topology.go:119-127 counts existing pods)
        for z in ("test-zone-1", "test-zone-2"):
            node = make_node(
                name=f"live-{z}", provisioner_name="default",
                capacity={"cpu": "16", "memory": "32Gi", "pods": "100"},
                labels={lbl.TOPOLOGY_ZONE: z, lbl.INSTANCE_TYPE: "fake-it-5",
                        lbl.CAPACITY_TYPE: "on-demand"},
            )
            cluster.seed("nodes", node)
            for j in range(rng.randint(0, 2)):
                cluster.seed(
                    "pods",
                    make_pod(name=f"seeded-{z}-{j}", labels={"app": "web"},
                             requests={"cpu": "0.5"},
                             node_name=node.metadata.name, unschedulable=False),
                )
        pods = []
        n = rng.randint(10, 70)
        for i in range(n):
            kind = rng.random()
            requests = {
                "cpu": f"{rng.choice([100, 250, 500, 1000])}m",
                "memory": f"{rng.choice([128, 256, 512, 1024])}Mi",
            }
            sel = {"app": rng.choice(["web", "db"])}
            if kind < 0.2:
                pods.append(make_pod(requests=requests))
            elif kind < 0.35:
                # required pod affinity to an app group (zone or hostname)
                pods.append(make_pod(
                    requests=requests, labels=sel,
                    pod_requirements=[PodAffinityTerm(
                        label_selector=LabelSelector(match_labels=sel),
                        topology_key=rng.choice([lbl.TOPOLOGY_ZONE, lbl.HOSTNAME]),
                    )],
                ))
            elif kind < 0.5:
                pods.append(make_pod(
                    requests=requests, labels=sel,
                    pod_anti_requirements=[PodAffinityTerm(
                        label_selector=LabelSelector(match_labels=sel),
                        topology_key=rng.choice([lbl.TOPOLOGY_ZONE, lbl.HOSTNAME]),
                    )],
                ))
            elif kind < 0.62:
                pods.append(with_port(
                    make_pod(requests=requests),
                    host_port=rng.choice([8080, 8443, 9090]),
                    protocol=rng.choice(["TCP", "UDP"]),
                ))
            elif kind < 0.74:
                # preferred node affinity (heaviest term folds into the core)
                pods.append(make_pod(
                    requests=requests,
                    node_preferences=[
                        PreferredSchedulingTerm(
                            weight=rng.randint(1, 100),
                            preference=NodeSelectorTerm(match_expressions=[
                                R(key=lbl.TOPOLOGY_ZONE, operator="In",
                                  values=[rng.choice(["test-zone-1", "test-zone-2"])])
                            ]),
                        )
                    ],
                ))
            elif kind < 0.86:
                pods.append(make_pod(
                    requests=requests,
                    tolerations=[Toleration(key="dedicated", value="team")],
                    node_selector={lbl.TOPOLOGY_ZONE: rng.choice(
                        ["test-zone-1", "test-zone-2", "test-zone-3"])},
                ))
            else:
                r2 = dict(requests)
                r2[res.NVIDIA_GPU] = str(rng.choice([1, 2]))
                pods.append(make_pod(requests=r2))
        assert_parity(*both_solve(pods, catalog, cluster=cluster, seed=seed))


class TestRandomizedParityMultiFrontier:
    """F>1 catalogs (anti-correlated cpu/mem — every type Pareto-optimal,
    frontier width = catalog size): the frontier axis the linear/assorted
    catalogs never exercise (they are Pareto-degenerate, F=1). The r4
    decode mask-dedupe, encode axis-trimming, and the kernels' frontier
    fit loops must stay assignment-identical at every F."""

    @pytest.mark.parametrize("seed", range(8))
    def test_fuzz_tradeoff_catalog(self, seed):
        from karpenter_tpu.cloudprovider.fake import instance_types_tradeoff

        rng = random.Random(3000 + seed)
        catalog = instance_types_tradeoff(rng.randint(4, 24))
        pods = []
        for i in range(rng.randint(10, 60)):
            kind = rng.random()
            requests = {
                "cpu": f"{rng.choice([250, 500, 1000, 2000])}m",
                "memory": f"{rng.choice([256, 1024, 4096, 8192])}Mi",
            }
            sel = {"app": rng.choice(["web", "db", "cache"])}
            if kind < 0.4:
                pods.append(make_pod(requests=requests))
            elif kind < 0.6:
                pods.append(make_pod(
                    requests=requests,
                    node_selector={lbl.TOPOLOGY_ZONE: rng.choice(
                        ["test-zone-1", "test-zone-2", "test-zone-3"])},
                ))
            elif kind < 0.8:
                pods.append(make_pod(labels=sel, requests=requests,
                                     topology=[zone_spread(max_skew=1, labels=sel)]))
            else:
                pods.append(make_pod(labels=sel, requests=requests,
                                     topology=[hostname_spread(max_skew=2, labels=sel)]))
        assert_parity(*both_solve(pods, catalog, seed=seed))

    def test_cpu_vs_memory_heavy_pick_different_frontier_ends(self):
        """Sanity that the tradeoff catalog genuinely exercises F>1: a
        cpu-heavy and a memory-heavy pod must be packable, and the batch
        encodes with frontier width equal to the catalog size."""
        from karpenter_tpu.cloudprovider.fake import instance_types_tradeoff
        from karpenter_tpu.scheduling.ffd import daemon_overhead, sort_pods_ffd
        from karpenter_tpu.scheduling.topology import Topology
        from karpenter_tpu.solver import encode as enc

        catalog = sorted(instance_types_tradeoff(8), key=lambda it: it.effective_price())
        provisioner = make_provisioner()
        c = provisioner.spec.constraints
        c.requirements = c.requirements.merge(catalog_requirements(catalog))
        pods = sort_pods_ffd([
            make_pod(requests={"cpu": "8", "memory": "1Gi"}),
            make_pod(requests={"cpu": "1", "memory": "12Gi"}),
        ])
        cc = c.clone()
        plan = Topology(Cluster(), rng=random.Random(1)).inject_plan(cc, pods)
        batch = enc.encode(cc, catalog, pods, daemon_overhead(Cluster(), cc), plan=plan)
        assert batch.frontiers.shape[1] == 8
        ffd, tpu = both_solve(pods, catalog)
        assert_parity(ffd, tpu)
        assert sum(len(n.pods) for n in tpu) == 2


class TestClosureMemo:
    """The dense closure reindex (visit sweep + SxC join-table fill) is
    memoized per core vocabulary on the SignatureTable; a repeated
    vocabulary must not re-sweep joins, and the memoized arrays are shared
    frozen objects."""

    _setup = TestEncodeCache._setup  # same scheduler/catalog recipe

    def test_repeat_vocabulary_hits_memo(self):
        from karpenter_tpu.solver.signature import SignatureTable

        catalog, c0, sched = self._setup()
        pods = lambda: [
            make_pod(requests={"cpu": "1"}, node_selector={"team": f"t{i % 4}"})
            for i in range(12)
        ]
        sched.solve(c0, catalog, pods())
        table = next(iter(sched._encode_cache.tables.values()))[1]
        assert len(table._closure_memo) == 1
        joins_before = len(table._join_cache)
        calls = []
        orig_join = SignatureTable.join
        SignatureTable.join = lambda self, *a: (calls.append(1), orig_join(self, *a))[1]
        try:
            n2 = sched.solve(c0, catalog, pods())
        finally:
            SignatureTable.join = orig_join
        assert calls == [], f"repeat vocabulary re-swept {len(calls)} joins"
        assert len(table._join_cache) == joins_before
        assert sum(len(n.pods) for n in n2) == 12
        # the memoized arrays are frozen: accidental in-place mutation by a
        # future consumer must fail loudly, not corrupt sibling solves
        entry = next(iter(table._closure_memo.values()))
        assert all(not a.flags.writeable for a in entry[1:4])

    def test_vocabulary_change_misses_then_caches(self):
        catalog, c0, sched = self._setup()
        for k in (2, 5, 2):
            sched.solve(c0, catalog, [
                make_pod(requests={"cpu": "1"}, node_selector={"team": f"t{i % k}"})
                for i in range(10)
            ])
        table = next(iter(sched._encode_cache.tables.values()))[1]
        assert len(table._closure_memo) == 2  # k=2 and k=5 vocabularies


class TestDecodeBitExact:
    """The vectorized ``_decode`` readout (bulk ``.tolist()`` + one
    vectorized division) must reproduce the original per-node scalar loop
    bit for bit: same pod grouping, same surviving-type lists, same
    requirements, and requests dicts whose floats match to the last ULP."""

    def _setup(self):
        catalog = instance_types(20)
        c0 = make_provisioner(solver="tpu").spec.constraints
        c0.requirements = c0.requirements.merge(catalog_requirements(catalog))
        return catalog, c0, TpuScheduler(Cluster(), rng=random.Random(0))

    @staticmethod
    def _scalar_reference(batch, result, typemask, constraints, catalog):
        """The pre-vectorization decode loop, kept verbatim as the oracle:
        per-element ``float(total[i]) / scales[i]`` numpy scalar boxing."""
        import numpy as np

        from karpenter_tpu.solver.backend import _with_hostname

        assignment, node_sig, node_host, node_req, n_nodes_arr = result
        assignment = assignment[: batch.n_pods]
        n_nodes = int(np.asarray(n_nodes_arr).reshape(-1)[0])
        pods_by_node = {}
        for i, a in enumerate(np.asarray(assignment).tolist()):
            if 0 <= a < n_nodes:
                pods_by_node.setdefault(int(a), []).append(batch.pods[i])
        axis_names = batch.axis_names
        scales = np.array(
            [res.AXIS_SCALES.get(nm, res._DEFAULT_SCALE) for nm in axis_names]
        )
        out = []
        for n in sorted(pods_by_node):
            total = np.asarray(node_req)[n]
            if typemask is not None:
                ok = np.asarray(typemask)[n]
            else:
                fit = np.all(batch.usable >= total[None, :], axis=-1)
                ok = fit & batch.type_mask_matrix()[int(np.asarray(node_sig)[n])]
            surviving = [t for t, o in zip(catalog, ok.tolist()) if o]
            node_constraints = constraints.clone()
            reqs = batch.signatures[int(np.asarray(node_sig)[n])].requirements
            h = int(np.asarray(node_host)[n])
            if h >= 0:
                reqs = _with_hostname(reqs, batch.hostnames[h], {})
            node_constraints.requirements = reqs
            requests = {
                name: float(total[i]) / scales[i]
                for i, name in enumerate(axis_names)
                if total[i]
            }
            out.append((pods_by_node[n], surviving, reqs, requests))
        return out

    @staticmethod
    def _assert_bitexact(ref, nodes):
        assert len(ref) == len(nodes), f"node count {len(ref)} != {len(nodes)}"
        for (r_pods, r_types, r_reqs, r_requests), v in zip(ref, nodes):
            assert [p.metadata.name for p in r_pods] == [
                p.metadata.name for p in v.pods
            ], "pod grouping diverged"
            assert [t.name for t in r_types] == [
                t.name for t in v.instance_type_options
            ], "surviving-type list diverged"
            vr = v.constraints.requirements
            assert {k: str(r_reqs.get(k)) for k in sorted(r_reqs.keys())} == {
                k: str(vr.get(k)) for k in sorted(vr.keys())
            }, "node requirements diverged"
            assert set(r_requests) == set(v.requests), "requests keys diverged"
            for k in r_requests:
                assert float(r_requests[k]).hex() == float(v.requests[k]).hex(), (
                    f"requests[{k}] not bit-exact: "
                    f"{float(r_requests[k]).hex()} vs {float(v.requests[k]).hex()}"
                )

    def _solve_and_compare(self, pods, catalog=None, provisioner=None):
        if catalog is None:
            catalog, c0, sched = self._setup()
        else:
            c0 = (provisioner or make_provisioner(solver="tpu")).spec.constraints
            c0.requirements = c0.requirements.merge(catalog_requirements(catalog))
            sched = TpuScheduler(Cluster(), rng=random.Random(0))
        captured = {}
        orig = sched._decode

        def spy(batch, result, typemask, constraints, its):
            out = orig(batch, result, typemask, constraints, its)
            captured["args"] = (batch, result, typemask, constraints, its)
            captured["nodes"] = out
            return out

        sched._decode = spy
        try:
            sched.solve(c0.clone(), catalog, pods)
        finally:
            sched._decode = orig
        if "args" not in captured:
            assert not pods, "decode never ran for a non-empty batch"
            return
        batch, result, typemask, constraints, its = captured["args"]
        # whichever surviving-type branch the live solve took...
        self._assert_bitexact(
            self._scalar_reference(batch, result, typemask, constraints, its),
            captured["nodes"],
        )
        # ...and force the host-side [T, R] fit-scan branch too
        self._assert_bitexact(
            self._scalar_reference(batch, result, None, constraints, its),
            sched._decode(batch, result, None, constraints, its),
        )

    def test_generic_batch(self):
        self._solve_and_compare(
            [
                make_pod(requests={"cpu": str(1 + i % 3), "memory": f"{512 * (1 + i % 4)}Mi"})
                for i in range(24)
            ]
        )

    def test_fractional_requests_exercise_division(self):
        # awkward decimal fractions are where a changed divide order would
        # show up in the last ULP
        self._solve_and_compare(
            [
                make_pod(requests={"cpu": "0.1", "memory": "333Mi"})
                for _ in range(7)
            ]
            + [make_pod(requests={"cpu": "1.3"}) for _ in range(5)]
        )

    def test_zone_selectors_multiple_signatures(self):
        catalog = instance_types_assorted()
        pods = (
            [make_pod(requests={"cpu": "0.5"}) for _ in range(6)]
            + [
                make_pod(
                    requests={"cpu": "1"},
                    node_selector={lbl.TOPOLOGY_ZONE: "test-zone-2"},
                )
                for _ in range(6)
            ]
        )
        self._solve_and_compare(pods, catalog=catalog)

    def test_hostname_spread_pins_hosts(self):
        # hostname topology forces node_host >= 0 → the _with_hostname
        # splice path must match the reference add()-equivalent
        pods = [
            make_pod(
                requests={"cpu": "1"},
                labels={"app": "web"},
                topology=[hostname_spread(labels={"app": "web"})],
            )
            for _ in range(8)
        ]
        self._solve_and_compare(pods)

    def test_randomized_mixed_batches(self):
        rng = random.Random(7)
        catalog = instance_types_assorted()
        for trial in range(5):
            pods = []
            for i in range(rng.randint(5, 30)):
                kwargs = {
                    "requests": {
                        "cpu": str(rng.choice(["0.25", "0.5", "1", "2", "3.7"])),
                        "memory": f"{rng.choice([128, 300, 512, 1000])}Mi",
                    }
                }
                roll = rng.random()
                if roll < 0.25:
                    kwargs["node_selector"] = {
                        lbl.TOPOLOGY_ZONE: f"test-zone-{rng.randint(1, 2)}"
                    }
                elif roll < 0.4:
                    kwargs["labels"] = {"grp": "a"}
                    kwargs["topology"] = [hostname_spread(labels={"grp": "a"})]
                pods.append(make_pod(**kwargs))
            self._solve_and_compare(pods, catalog=catalog)

    def test_empty_batch_decodes_empty(self):
        self._solve_and_compare([])
