"""Parity harness: the TPU batch solver must produce assignment-identical
results to the FFD reference on randomized scenarios (SURVEY.md §7 Phase 1).

Both backends share sorting, topology injection, and daemon-overhead
computation, so identical seeds give identical pod orderings; the kernel then
must make the same accept decision at every step.
"""

import random

import pytest

from karpenter_tpu.api import labels as lbl
from karpenter_tpu.api.objects import NodeSelectorRequirement as R, Taint
from karpenter_tpu.cloudprovider.fake import (
    default_catalog,
    instance_types,
    instance_types_assorted,
    new_instance_type,
)
from karpenter_tpu.cloudprovider.requirements import catalog_requirements
from karpenter_tpu.kube.client import Cluster
from karpenter_tpu.scheduling.ffd import FFDScheduler
from karpenter_tpu.solver.backend import TpuScheduler
from karpenter_tpu.utils import resources as res
from tests.factories import hostname_spread, make_daemonset, make_pod, make_provisioner, zone_spread


def both_solve(pods, catalog, cluster=None, provisioner=None, seed=42):
    cluster = cluster or Cluster()
    provisioner = provisioner or make_provisioner()
    constraints = provisioner.spec.constraints
    constraints.requirements = constraints.requirements.merge(catalog_requirements(catalog))
    ffd_nodes = FFDScheduler(cluster, rng=random.Random(seed)).solve(constraints, catalog, pods)
    tpu_nodes = TpuScheduler(cluster, rng=random.Random(seed)).solve(constraints, catalog, pods)
    return ffd_nodes, tpu_nodes


def assert_parity(ffd_nodes, tpu_nodes):
    assert len(ffd_nodes) == len(tpu_nodes), (
        f"node count: ffd={len(ffd_nodes)} tpu={len(tpu_nodes)}"
    )
    ffd_sets = sorted(sorted(p.metadata.name for p in n.pods) for n in ffd_nodes)
    tpu_sets = sorted(sorted(p.metadata.name for p in n.pods) for n in tpu_nodes)
    assert ffd_sets == tpu_sets, "pod→node assignments differ"
    # same cheapest launchable type per node ⇒ same launch price
    ffd_prices = sorted(n.instance_type_options[0].effective_price() for n in ffd_nodes)
    tpu_prices = sorted(n.instance_type_options[0].effective_price() for n in tpu_nodes)
    assert ffd_prices == pytest.approx(tpu_prices)


class TestBasicParity:
    def test_generic_pods(self):
        pods = [make_pod(requests={"cpu": "1", "memory": "1Gi"}) for _ in range(20)]
        assert_parity(*both_solve(pods, instance_types(20)))

    def test_single_pod(self):
        assert_parity(*both_solve([make_pod(requests={"cpu": "1"})], default_catalog()))

    def test_unschedulable_dropped_by_both(self):
        pods = [make_pod(requests={"cpu": "10000"}), make_pod(requests={"cpu": "1"})]
        ffd, tpu = both_solve(pods, instance_types(10))
        assert_parity(ffd, tpu)
        assert sum(len(n.pods) for n in tpu) == 1

    def test_empty_batch(self):
        ffd, tpu = both_solve([], instance_types(5))
        assert ffd == [] and tpu == []

    def test_selectors_and_assorted_catalog(self):
        catalog = instance_types_assorted()
        pods = (
            [make_pod(requests={"cpu": "0.5"}) for _ in range(5)]
            + [
                make_pod(
                    requests={"cpu": "1"},
                    node_selector={lbl.TOPOLOGY_ZONE: "test-zone-2"},
                )
                for _ in range(5)
            ]
            + [
                make_pod(
                    requests={"cpu": "1"},
                    node_requirements=[R(key=lbl.ARCH, operator="In", values=["arm64"])],
                )
                for _ in range(3)
            ]
            + [
                make_pod(
                    requests={"cpu": "1"},
                    node_requirements=[
                        R(key=lbl.CAPACITY_TYPE, operator="NotIn", values=["spot"])
                    ],
                )
                for _ in range(3)
            ]
        )
        assert_parity(*both_solve(pods, catalog))


class TestTopologyParity:
    def test_zone_spread(self):
        pods = [
            make_pod(
                requests={"cpu": "0.5"},
                labels={"app": "web"},
                topology=[zone_spread(labels={"app": "web"})],
            )
            for _ in range(9)
        ]
        assert_parity(*both_solve(pods, instance_types(30)))

    def test_hostname_spread(self):
        pods = [
            make_pod(
                requests={"cpu": "0.5"},
                labels={"app": "web"},
                topology=[hostname_spread(labels={"app": "web"})],
            )
            for _ in range(6)
        ]
        assert_parity(*both_solve(pods, instance_types(30)))

    def test_mixed_spread_and_generic(self):
        pods = (
            [make_pod(requests={"cpu": "1"}) for _ in range(10)]
            + [
                make_pod(
                    requests={"cpu": "0.5"},
                    labels={"app": "a"},
                    topology=[zone_spread(labels={"app": "a"})],
                )
                for _ in range(5)
            ]
            + [
                make_pod(
                    requests={"cpu": "0.25"},
                    labels={"app": "b"},
                    topology=[hostname_spread(labels={"app": "b"})],
                )
                for _ in range(5)
            ]
        )
        assert_parity(*both_solve(pods, instance_types(30)))


class TestDaemonParity:
    def test_daemon_overhead(self):
        cluster = Cluster()
        cluster.create("daemonsets", make_daemonset(requests={"cpu": "500m"}))
        pods = [make_pod(requests={"cpu": "2"}) for _ in range(6)]
        assert_parity(*both_solve(pods, instance_types(6), cluster=cluster))


class TestExtendedResourcesParity:
    def test_gpu(self):
        pods = [make_pod(requests={res.NVIDIA_GPU: "1", "cpu": "1"}) for _ in range(3)]
        assert_parity(*both_solve(pods, default_catalog()))


class TestRandomizedParity:
    @pytest.mark.parametrize("seed", range(8))
    def test_fuzz(self, seed):
        rng = random.Random(seed)
        catalog_choice = rng.choice(["linear", "assorted", "default"])
        catalog = {
            "linear": lambda: instance_types(rng.randint(5, 60)),
            "assorted": instance_types_assorted,
            "default": default_catalog,
        }[catalog_choice]()
        pods = []
        n = rng.randint(5, 60)
        for i in range(n):
            kind = rng.random()
            requests = {
                "cpu": f"{rng.choice([100, 250, 500, 1000, 1500])}m",
                "memory": f"{rng.choice([128, 256, 512, 1024, 2048])}Mi",
            }
            if kind < 0.4:
                pods.append(make_pod(requests=requests))
            elif kind < 0.55:
                pods.append(
                    make_pod(
                        requests=requests,
                        node_selector={
                            lbl.TOPOLOGY_ZONE: rng.choice(
                                ["test-zone-1", "test-zone-2", "test-zone-3"]
                            )
                        },
                    )
                )
            elif kind < 0.7:
                pods.append(
                    make_pod(
                        requests=requests,
                        labels={"group": rng.choice(["a", "b"])},
                        topology=[zone_spread(labels={"group": rng.choice(["a", "b"])})],
                    )
                )
            elif kind < 0.85:
                pods.append(
                    make_pod(
                        requests=requests,
                        labels={"group": rng.choice(["a", "b"])},
                        topology=[hostname_spread(labels={"group": rng.choice(["a", "b"])})],
                    )
                )
            else:
                op = rng.choice(["In", "NotIn"])
                pods.append(
                    make_pod(
                        requests=requests,
                        node_requirements=[
                            R(
                                key=lbl.CAPACITY_TYPE,
                                operator=op,
                                values=[rng.choice(["spot", "on-demand"])],
                            )
                        ],
                    )
                )
        assert_parity(*both_solve(pods, catalog, seed=seed))
