"""Parity harness: the TPU batch solver must produce assignment-identical
results to the FFD reference on randomized scenarios (SURVEY.md §7 Phase 1).

Both backends share sorting, topology injection, and daemon-overhead
computation, so identical seeds give identical pod orderings; the kernel then
must make the same accept decision at every step.
"""

import random

import pytest

from karpenter_tpu.api import labels as lbl
from karpenter_tpu.api.objects import NodeSelectorRequirement as R, Taint
from karpenter_tpu.cloudprovider.fake import (
    default_catalog,
    instance_types,
    instance_types_assorted,
    new_instance_type,
)
from karpenter_tpu.cloudprovider.requirements import catalog_requirements
from karpenter_tpu.kube.client import Cluster
from karpenter_tpu.scheduling.ffd import FFDScheduler
from karpenter_tpu.solver.backend import TpuScheduler
from karpenter_tpu.utils import resources as res
from tests.factories import hostname_spread, make_daemonset, make_pod, make_provisioner, zone_spread


def both_solve(pods, catalog, cluster=None, provisioner=None, seed=42):
    cluster = cluster or Cluster()
    provisioner = provisioner or make_provisioner()
    constraints = provisioner.spec.constraints
    constraints.requirements = constraints.requirements.merge(catalog_requirements(catalog))
    ffd_nodes = FFDScheduler(cluster, rng=random.Random(seed)).solve(constraints, catalog, pods)
    tpu_nodes = TpuScheduler(cluster, rng=random.Random(seed)).solve(constraints, catalog, pods)
    return ffd_nodes, tpu_nodes


def assert_parity(ffd_nodes, tpu_nodes):
    assert len(ffd_nodes) == len(tpu_nodes), (
        f"node count: ffd={len(ffd_nodes)} tpu={len(tpu_nodes)}"
    )
    ffd_sets = sorted(sorted(p.metadata.name for p in n.pods) for n in ffd_nodes)
    tpu_sets = sorted(sorted(p.metadata.name for p in n.pods) for n in tpu_nodes)
    assert ffd_sets == tpu_sets, "pod→node assignments differ"
    # same cheapest launchable type per node ⇒ same launch price
    ffd_prices = sorted(n.instance_type_options[0].effective_price() for n in ffd_nodes)
    tpu_prices = sorted(n.instance_type_options[0].effective_price() for n in tpu_nodes)
    assert ffd_prices == pytest.approx(tpu_prices)


class TestBasicParity:
    def test_generic_pods(self):
        pods = [make_pod(requests={"cpu": "1", "memory": "1Gi"}) for _ in range(20)]
        assert_parity(*both_solve(pods, instance_types(20)))

    def test_single_pod(self):
        assert_parity(*both_solve([make_pod(requests={"cpu": "1"})], default_catalog()))

    def test_unschedulable_dropped_by_both(self):
        pods = [make_pod(requests={"cpu": "10000"}), make_pod(requests={"cpu": "1"})]
        ffd, tpu = both_solve(pods, instance_types(10))
        assert_parity(ffd, tpu)
        assert sum(len(n.pods) for n in tpu) == 1

    def test_empty_batch(self):
        ffd, tpu = both_solve([], instance_types(5))
        assert ffd == [] and tpu == []

    def test_selectors_and_assorted_catalog(self):
        catalog = instance_types_assorted()
        pods = (
            [make_pod(requests={"cpu": "0.5"}) for _ in range(5)]
            + [
                make_pod(
                    requests={"cpu": "1"},
                    node_selector={lbl.TOPOLOGY_ZONE: "test-zone-2"},
                )
                for _ in range(5)
            ]
            + [
                make_pod(
                    requests={"cpu": "1"},
                    node_requirements=[R(key=lbl.ARCH, operator="In", values=["arm64"])],
                )
                for _ in range(3)
            ]
            + [
                make_pod(
                    requests={"cpu": "1"},
                    node_requirements=[
                        R(key=lbl.CAPACITY_TYPE, operator="NotIn", values=["spot"])
                    ],
                )
                for _ in range(3)
            ]
        )
        assert_parity(*both_solve(pods, catalog))


class TestTopologyParity:
    def test_zone_spread(self):
        pods = [
            make_pod(
                requests={"cpu": "0.5"},
                labels={"app": "web"},
                topology=[zone_spread(labels={"app": "web"})],
            )
            for _ in range(9)
        ]
        assert_parity(*both_solve(pods, instance_types(30)))

    def test_hostname_spread(self):
        pods = [
            make_pod(
                requests={"cpu": "0.5"},
                labels={"app": "web"},
                topology=[hostname_spread(labels={"app": "web"})],
            )
            for _ in range(6)
        ]
        assert_parity(*both_solve(pods, instance_types(30)))

    def test_mixed_spread_and_generic(self):
        pods = (
            [make_pod(requests={"cpu": "1"}) for _ in range(10)]
            + [
                make_pod(
                    requests={"cpu": "0.5"},
                    labels={"app": "a"},
                    topology=[zone_spread(labels={"app": "a"})],
                )
                for _ in range(5)
            ]
            + [
                make_pod(
                    requests={"cpu": "0.25"},
                    labels={"app": "b"},
                    topology=[hostname_spread(labels={"app": "b"})],
                )
                for _ in range(5)
            ]
        )
        assert_parity(*both_solve(pods, instance_types(30)))


class TestDaemonParity:
    def test_daemon_overhead(self):
        cluster = Cluster()
        cluster.create("daemonsets", make_daemonset(requests={"cpu": "500m"}))
        pods = [make_pod(requests={"cpu": "2"}) for _ in range(6)]
        assert_parity(*both_solve(pods, instance_types(6), cluster=cluster))


class TestExtendedResourcesParity:
    def test_gpu(self):
        pods = [make_pod(requests={res.NVIDIA_GPU: "1", "cpu": "1"}) for _ in range(3)]
        assert_parity(*both_solve(pods, default_catalog()))


class TestRandomizedParity:
    @pytest.mark.parametrize("seed", range(8))
    def test_fuzz(self, seed):
        rng = random.Random(seed)
        catalog_choice = rng.choice(["linear", "assorted", "default"])
        catalog = {
            "linear": lambda: instance_types(rng.randint(5, 60)),
            "assorted": instance_types_assorted,
            "default": default_catalog,
        }[catalog_choice]()
        pods = []
        n = rng.randint(5, 60)
        for i in range(n):
            kind = rng.random()
            requests = {
                "cpu": f"{rng.choice([100, 250, 500, 1000, 1500])}m",
                "memory": f"{rng.choice([128, 256, 512, 1024, 2048])}Mi",
            }
            if kind < 0.4:
                pods.append(make_pod(requests=requests))
            elif kind < 0.55:
                pods.append(
                    make_pod(
                        requests=requests,
                        node_selector={
                            lbl.TOPOLOGY_ZONE: rng.choice(
                                ["test-zone-1", "test-zone-2", "test-zone-3"]
                            )
                        },
                    )
                )
            elif kind < 0.7:
                pods.append(
                    make_pod(
                        requests=requests,
                        labels={"group": rng.choice(["a", "b"])},
                        topology=[zone_spread(labels={"group": rng.choice(["a", "b"])})],
                    )
                )
            elif kind < 0.85:
                pods.append(
                    make_pod(
                        requests=requests,
                        labels={"group": rng.choice(["a", "b"])},
                        topology=[hostname_spread(labels={"group": rng.choice(["a", "b"])})],
                    )
                )
            else:
                op = rng.choice(["In", "NotIn"])
                pods.append(
                    make_pod(
                        requests=requests,
                        node_requirements=[
                            R(
                                key=lbl.CAPACITY_TYPE,
                                operator=op,
                                values=[rng.choice(["spot", "on-demand"])],
                            )
                        ],
                    )
                )
        assert_parity(*both_solve(pods, catalog, seed=seed))


class TestEncodeCache:
    """Solve-invariant encode state reused across a worker's batches
    (signature table, capacity matrix) — scoped per batch so accumulated
    closure state never leaks into the kernel input."""

    def _setup(self):
        import random

        from karpenter_tpu.cloudprovider.fake import instance_types
        from karpenter_tpu.cloudprovider.requirements import catalog_requirements
        from karpenter_tpu.kube.client import Cluster
        from karpenter_tpu.solver.backend import TpuScheduler
        from tests.factories import make_provisioner

        catalog = instance_types(20)
        c0 = make_provisioner(solver="tpu").spec.constraints
        c0.requirements = c0.requirements.merge(catalog_requirements(catalog))
        return catalog, c0, TpuScheduler(Cluster(), rng=random.Random(0))

    def test_mixed_core_batches_share_one_table(self):
        """Batches with different pod constraint cores must reuse the cached
        table without crashing (round-2 review repro) and still match FFD."""
        import random

        from karpenter_tpu.kube.client import Cluster
        from karpenter_tpu.scheduling.ffd import FFDScheduler
        from tests.factories import make_pod

        catalog, c0, sched = self._setup()
        ffd = FFDScheduler(Cluster(), rng=random.Random(0))
        batches = [
            [make_pod(requests={"cpu": "1"}, node_selector={"team": "a"}) for _ in range(3)],
            [make_pod(requests={"cpu": "1"}) for _ in range(3)],
            [make_pod(requests={"cpu": "1"}, node_selector={"team": "b"}) for _ in range(2)]
            + [make_pod(requests={"cpu": "1"})],
        ]
        for pods in batches:
            v_tpu = sched.solve(c0.clone(), catalog, pods)
            v_ffd = ffd.solve(c0.clone(), catalog, pods)
            a = sorted(
                (sorted(p.key for p in v.pods), v.instance_type_options[0].name)
                for v in v_tpu
            )
            b = sorted(
                (sorted(p.key for p in v.pods), v.instance_type_options[0].name)
                for v in v_ffd
            )
            assert a == b
        assert len(sched._encode_cache.tables) == 1  # one table, reused

    def test_fingerprint_hits_across_fresh_catalog_objects(self):
        """Providers rebuild InstanceType objects per call; the cache must
        key on catalog semantics, not object identity."""
        import copy

        from tests.factories import make_pod

        catalog, c0, sched = self._setup()
        sched.solve(c0.clone(), catalog, [make_pod(requests={"cpu": "1"})])
        fresh = copy.deepcopy(catalog)  # same semantics, all-new objects
        sched.solve(c0.clone(), fresh, [make_pod(requests={"cpu": "1"})])
        assert len(sched._encode_cache.tables) == 1

    def test_lru_bounds_entries(self):
        from karpenter_tpu.solver.encode import EncodeCache

        cache = EncodeCache()
        for i in range(EncodeCache.MAX_ENTRIES + 3):
            cache.put(("k", i), (None, None))
        assert len(cache.tables) == EncodeCache.MAX_ENTRIES

    def test_batch_arrays_scoped_to_batch_cores(self):
        """After a diverse batch grows the table, a simple batch's emitted
        arrays must not inherit the accumulated signature axis."""
        from tests.factories import make_pod

        catalog, c0, sched = self._setup()
        diverse = [
            make_pod(requests={"cpu": "1"}, node_selector={"team": t})
            for t in ("a", "b", "c", "d")
        ]
        sched.solve(c0.clone(), catalog, diverse)
        from karpenter_tpu.scheduling.ffd import daemon_overhead, sort_pods_ffd
        from karpenter_tpu.scheduling.topology import Topology
        from karpenter_tpu.solver import encode as enc

        pods = sort_pods_ffd([make_pod(requests={"cpu": "1"})])
        c = c0.clone()
        Topology(sched.cluster).inject(c, list(pods))
        batch = enc.encode(
            c, sorted(catalog, key=lambda it: it.effective_price()), pods,
            daemon_overhead(sched.cluster, c), cache=sched._encode_cache,
        )
        # base + the plain pod's open signature only
        assert len(batch.signatures) <= 2
        assert batch.join_table.shape[0] == len(batch.signatures)


class TestRandomizedParityWide:
    """Wider feature mix than TestRandomizedParity: pod (anti-)affinity,
    host ports, preferred node affinity, taints/tolerations, extended
    resources, and a live cluster seeded with scheduled pods (topology
    counts) — the interactions the r3 statics/DomainPlan rewrite must keep
    byte-equal between the plan-consuming TPU path and the
    selector-materializing FFD path."""

    @pytest.mark.parametrize("seed", range(16))
    def test_fuzz_wide(self, seed):
        from karpenter_tpu.api.objects import (
            LabelSelector,
            PodAffinityTerm,
            PreferredSchedulingTerm,
            NodeSelectorTerm,
            Toleration,
        )
        from tests.factories import make_node
        from tests.test_scheduling_parity import with_port

        rng = random.Random(1000 + seed)
        catalog = instance_types(rng.randint(10, 50))
        cluster = Cluster()
        # seed the live cluster: scheduled pods feeding topology/affinity
        # counts (reference: topology.go:119-127 counts existing pods)
        for z in ("test-zone-1", "test-zone-2"):
            node = make_node(
                name=f"live-{z}", provisioner_name="default",
                capacity={"cpu": "16", "memory": "32Gi", "pods": "100"},
                labels={lbl.TOPOLOGY_ZONE: z, lbl.INSTANCE_TYPE: "fake-it-5",
                        lbl.CAPACITY_TYPE: "on-demand"},
            )
            cluster.seed("nodes", node)
            for j in range(rng.randint(0, 2)):
                cluster.seed(
                    "pods",
                    make_pod(name=f"seeded-{z}-{j}", labels={"app": "web"},
                             requests={"cpu": "0.5"},
                             node_name=node.metadata.name, unschedulable=False),
                )
        pods = []
        n = rng.randint(10, 70)
        for i in range(n):
            kind = rng.random()
            requests = {
                "cpu": f"{rng.choice([100, 250, 500, 1000])}m",
                "memory": f"{rng.choice([128, 256, 512, 1024])}Mi",
            }
            sel = {"app": rng.choice(["web", "db"])}
            if kind < 0.2:
                pods.append(make_pod(requests=requests))
            elif kind < 0.35:
                # required pod affinity to an app group (zone or hostname)
                pods.append(make_pod(
                    requests=requests, labels=sel,
                    pod_requirements=[PodAffinityTerm(
                        label_selector=LabelSelector(match_labels=sel),
                        topology_key=rng.choice([lbl.TOPOLOGY_ZONE, lbl.HOSTNAME]),
                    )],
                ))
            elif kind < 0.5:
                pods.append(make_pod(
                    requests=requests, labels=sel,
                    pod_anti_requirements=[PodAffinityTerm(
                        label_selector=LabelSelector(match_labels=sel),
                        topology_key=rng.choice([lbl.TOPOLOGY_ZONE, lbl.HOSTNAME]),
                    )],
                ))
            elif kind < 0.62:
                pods.append(with_port(
                    make_pod(requests=requests),
                    host_port=rng.choice([8080, 8443, 9090]),
                    protocol=rng.choice(["TCP", "UDP"]),
                ))
            elif kind < 0.74:
                # preferred node affinity (heaviest term folds into the core)
                pods.append(make_pod(
                    requests=requests,
                    node_preferences=[
                        PreferredSchedulingTerm(
                            weight=rng.randint(1, 100),
                            preference=NodeSelectorTerm(match_expressions=[
                                R(key=lbl.TOPOLOGY_ZONE, operator="In",
                                  values=[rng.choice(["test-zone-1", "test-zone-2"])])
                            ]),
                        )
                    ],
                ))
            elif kind < 0.86:
                pods.append(make_pod(
                    requests=requests,
                    tolerations=[Toleration(key="dedicated", value="team")],
                    node_selector={lbl.TOPOLOGY_ZONE: rng.choice(
                        ["test-zone-1", "test-zone-2", "test-zone-3"])},
                ))
            else:
                r2 = dict(requests)
                r2[res.NVIDIA_GPU] = str(rng.choice([1, 2]))
                pods.append(make_pod(requests=r2))
        assert_parity(*both_solve(pods, catalog, cluster=cluster, seed=seed))


class TestRandomizedParityMultiFrontier:
    """F>1 catalogs (anti-correlated cpu/mem — every type Pareto-optimal,
    frontier width = catalog size): the frontier axis the linear/assorted
    catalogs never exercise (they are Pareto-degenerate, F=1). The r4
    decode mask-dedupe, encode axis-trimming, and the kernels' frontier
    fit loops must stay assignment-identical at every F."""

    @pytest.mark.parametrize("seed", range(8))
    def test_fuzz_tradeoff_catalog(self, seed):
        from karpenter_tpu.cloudprovider.fake import instance_types_tradeoff

        rng = random.Random(3000 + seed)
        catalog = instance_types_tradeoff(rng.randint(4, 24))
        pods = []
        for i in range(rng.randint(10, 60)):
            kind = rng.random()
            requests = {
                "cpu": f"{rng.choice([250, 500, 1000, 2000])}m",
                "memory": f"{rng.choice([256, 1024, 4096, 8192])}Mi",
            }
            sel = {"app": rng.choice(["web", "db", "cache"])}
            if kind < 0.4:
                pods.append(make_pod(requests=requests))
            elif kind < 0.6:
                pods.append(make_pod(
                    requests=requests,
                    node_selector={lbl.TOPOLOGY_ZONE: rng.choice(
                        ["test-zone-1", "test-zone-2", "test-zone-3"])},
                ))
            elif kind < 0.8:
                pods.append(make_pod(labels=sel, requests=requests,
                                     topology=[zone_spread(max_skew=1, labels=sel)]))
            else:
                pods.append(make_pod(labels=sel, requests=requests,
                                     topology=[hostname_spread(max_skew=2, labels=sel)]))
        assert_parity(*both_solve(pods, catalog, seed=seed))

    def test_cpu_vs_memory_heavy_pick_different_frontier_ends(self):
        """Sanity that the tradeoff catalog genuinely exercises F>1: a
        cpu-heavy and a memory-heavy pod must be packable, and the batch
        encodes with frontier width equal to the catalog size."""
        from karpenter_tpu.cloudprovider.fake import instance_types_tradeoff
        from karpenter_tpu.scheduling.ffd import daemon_overhead, sort_pods_ffd
        from karpenter_tpu.scheduling.topology import Topology
        from karpenter_tpu.solver import encode as enc

        catalog = sorted(instance_types_tradeoff(8), key=lambda it: it.effective_price())
        provisioner = make_provisioner()
        c = provisioner.spec.constraints
        c.requirements = c.requirements.merge(catalog_requirements(catalog))
        pods = sort_pods_ffd([
            make_pod(requests={"cpu": "8", "memory": "1Gi"}),
            make_pod(requests={"cpu": "1", "memory": "12Gi"}),
        ])
        cc = c.clone()
        plan = Topology(Cluster(), rng=random.Random(1)).inject_plan(cc, pods)
        batch = enc.encode(cc, catalog, pods, daemon_overhead(Cluster(), cc), plan=plan)
        assert batch.frontiers.shape[1] == 8
        ffd, tpu = both_solve(pods, catalog)
        assert_parity(ffd, tpu)
        assert sum(len(n.pods) for n in tpu) == 2


class TestClosureMemo:
    """The dense closure reindex (visit sweep + SxC join-table fill) is
    memoized per core vocabulary on the SignatureTable; a repeated
    vocabulary must not re-sweep joins, and the memoized arrays are shared
    frozen objects."""

    _setup = TestEncodeCache._setup  # same scheduler/catalog recipe

    def test_repeat_vocabulary_hits_memo(self):
        from karpenter_tpu.solver.signature import SignatureTable

        catalog, c0, sched = self._setup()
        pods = lambda: [
            make_pod(requests={"cpu": "1"}, node_selector={"team": f"t{i % 4}"})
            for i in range(12)
        ]
        sched.solve(c0, catalog, pods())
        table = next(iter(sched._encode_cache.tables.values()))[1]
        assert len(table._closure_memo) == 1
        joins_before = len(table._join_cache)
        calls = []
        orig_join = SignatureTable.join
        SignatureTable.join = lambda self, *a: (calls.append(1), orig_join(self, *a))[1]
        try:
            n2 = sched.solve(c0, catalog, pods())
        finally:
            SignatureTable.join = orig_join
        assert calls == [], f"repeat vocabulary re-swept {len(calls)} joins"
        assert len(table._join_cache) == joins_before
        assert sum(len(n.pods) for n in n2) == 12
        # the memoized arrays are frozen: accidental in-place mutation by a
        # future consumer must fail loudly, not corrupt sibling solves
        entry = next(iter(table._closure_memo.values()))
        assert all(not a.flags.writeable for a in entry[1:4])

    def test_vocabulary_change_misses_then_caches(self):
        catalog, c0, sched = self._setup()
        for k in (2, 5, 2):
            sched.solve(c0, catalog, [
                make_pod(requests={"cpu": "1"}, node_selector={"team": f"t{i % k}"})
                for i in range(10)
            ])
        table = next(iter(sched._encode_cache.tables.values()))[1]
        assert len(table._closure_memo) == 2  # k=2 and k=5 vocabularies
