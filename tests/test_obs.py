"""End-to-end provisioning traces (karpenter_tpu/obs): span lifecycle,
contextvar propagation, the ring exporter, traceparent propagation across
the HTTP cloud wire and the v3 solver wire (sidecar child spans linked by
trace id + the response stage trailer), the slow-solve flight recorder,
the /debug endpoints, and the satellite wiring (logging filter, event
annotations, breaker short-circuit attribution, stage/profile agreement).
"""

import json
import logging
import random
import socket
import threading
import time
import urllib.request

import numpy as np
import pytest

from karpenter_tpu import metrics, obs


@pytest.fixture(autouse=True)
def _fresh_obs():
    obs.reset_for_tests()
    yield
    obs.reset_for_tests()


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# ---------------------------------------------------------------------------
# span core
# ---------------------------------------------------------------------------


class TestSpanCore:
    def test_nesting_follows_contextvar(self):
        tr = obs.tracer()
        with tr.span("root") as root:
            assert tr.current() is root
            with tr.span("child") as child:
                assert tr.current() is child
                assert child.parent is root
                assert child.trace_id == root.trace_id
            assert tr.current() is root
        assert tr.current() is None
        assert [c.name for c in root.children] == ["child"]

    def test_root_exports_whole_tree(self):
        tr = obs.tracer()
        before = obs.exporter().exported_spans
        with tr.span("root"):
            with tr.span("a"):
                with tr.span("aa"):
                    pass
            with tr.span("b"):
                pass
        trees = obs.exporter().snapshot()
        assert len(trees) == 1
        tree = trees[0]
        assert tree["name"] == "root"
        assert {c["name"] for c in tree["children"]} == {"a", "b"}
        assert tree["children"][0]["children"][0]["name"] == "aa"
        # child spans are NOT separately exported
        assert obs.exporter().exported_spans - before == 4

    def test_error_recorded_and_reraised(self):
        tr = obs.tracer()
        with pytest.raises(ValueError):
            with tr.span("boom"):
                raise ValueError("nope")
        tree = obs.exporter().snapshot()[0]
        assert "ValueError" in tree["error"]

    def test_explicit_parent_across_threads(self):
        tr = obs.tracer()
        with tr.span("round") as round_sp:
            def work():
                # executor threads don't inherit the contextvar: parent
                # must be passed explicitly (the provisioning launch idiom)
                assert tr.current() is None
                with tr.span("launch", parent=round_sp):
                    pass

            t = threading.Thread(target=work)
            t.start()
            t.join()
        tree = obs.exporter().snapshot()[0]
        assert [c["name"] for c in tree["children"]] == ["launch"]

    def test_remote_parent_makes_local_root(self):
        tr = obs.tracer()
        ctx = obs.SpanContext("ab" * 16, "cd" * 8)
        with tr.span("sidecar.pack", parent=ctx) as sp:
            assert sp.trace_id == ctx.trace_id
            assert sp.parent_id == ctx.span_id
            assert sp.parent is None
        # exported as its own tree, joined to the caller's by ids
        assert obs.exporter().snapshot()[0]["trace_id"] == ctx.trace_id

    def test_child_record_attaches_completed_span(self):
        tr = obs.tracer()
        with tr.span("wire") as sp:
            sp.add_child_record("sidecar.solve", 0.004, attrs={"k": 1})
        child = obs.exporter().snapshot()[0]["children"][0]
        assert child["name"] == "sidecar.solve"
        assert child["duration_ms"] == pytest.approx(4.0, abs=0.1)

    def test_disabled_tracer_is_noop(self):
        obs.set_enabled(False)
        tr = obs.tracer()
        with tr.span("root") as sp:
            sp.set_attribute("x", 1)  # absorbed
            sp.add_child_record("y", 0.1)
            assert tr.current() is None
        assert obs.exporter().snapshot() == []

    def test_ring_eviction_counts_drops(self):
        exp = obs.RingExporter(capacity=2)
        tr = obs.Tracer(exporter=exp)
        for i in range(5):
            with tr.span(f"s{i}"):
                pass
        assert len(exp.snapshot()) == 2
        assert exp.dropped_spans == 3
        assert [t["name"] for t in exp.snapshot()] == ["s4", "s3"]

    def test_dump_jsonl(self, tmp_path):
        tr = obs.tracer()
        with tr.span("a"):
            pass
        with tr.span("b"):
            pass
        path = tmp_path / "traces.jsonl"
        assert obs.exporter().dump_jsonl(str(path)) == 2
        lines = path.read_text().strip().splitlines()
        assert [json.loads(ln)["name"] for ln in lines] == ["a", "b"]


class TestTraceparent:
    def test_round_trip(self):
        tr = obs.tracer()
        with tr.span("x") as sp:
            header = obs.to_traceparent(sp)
            ctx = obs.from_traceparent(header)
            assert ctx == sp.context

    @pytest.mark.parametrize("bad", [
        None, "", "00-short-id-01", "zz", "00-" + "g" * 32 + "-" + "1" * 16 + "-01",
        "00-" + "a" * 31 + "-" + "1" * 16 + "-01",
    ])
    def test_malformed_degrades_to_none(self, bad):
        assert obs.from_traceparent(bad) is None


class TestAnalysis:
    def test_critical_path_self_times(self):
        tree = {
            "name": "root", "duration_ms": 10.0,
            "children": [
                {"name": "fast", "duration_ms": 2.0, "children": []},
                {"name": "slow", "duration_ms": 6.0, "children": [
                    {"name": "inner", "duration_ms": 5.0, "children": []},
                ]},
            ],
        }
        path = obs.critical_path(tree)
        assert [p["name"] for p in path] == ["root", "slow", "inner"]
        assert path[0]["self_ms"] == pytest.approx(2.0)
        assert path[1]["self_ms"] == pytest.approx(1.0)

    def test_overlapping_pairs_cross_trace_only(self):
        def tree(tid, name, t0, t1):
            return {"trace_id": tid, "name": name, "t0": t0, "t1": t1,
                    "duration_ms": (t1 - t0) * 1e3, "children": []}

        trees = [
            tree("t1", "solve.encode", 0.0, 1.0),
            tree("t2", "solve.pack_fetch", 0.5, 1.5),  # overlaps t1's encode
            tree("t3", "solve.pack_fetch", 2.0, 3.0),  # does not
        ]
        assert obs.overlapping_pairs(trees) == 1
        # same-trace overlap never counts
        same = [tree("t1", "solve.encode", 0.0, 1.0),
                tree("t1", "solve.pack_fetch", 0.0, 1.0)]
        assert obs.overlapping_pairs(same) == 0


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


class TestFlightRecorder:
    def test_over_budget_watched_span_is_recorded(self, tmp_path):
        rec = obs.configure_flight(str(tmp_path), budget_s=0.0)
        obs.register_state("panel", lambda: {"hello": 1})
        with obs.tracer().span("solver.solve"):
            pass
        with obs.tracer().span("not.watched"):
            pass
        records = rec.recent()
        assert len(records) == 1
        assert records[0]["name"] == "solver.solve"
        assert records[0]["state"]["panel"] == {"hello": 1}
        assert records[0]["trace"]["name"] == "solver.solve"

    def test_under_budget_not_recorded(self, tmp_path):
        rec = obs.configure_flight(str(tmp_path), budget_s=30.0)
        with obs.tracer().span("solver.solve"):
            pass
        assert rec.recent() == []

    def test_capped_on_disk_ring(self, tmp_path):
        rec = obs.configure_flight(str(tmp_path), budget_s=0.0, cap=3)
        for _ in range(7):
            with obs.tracer().span("solver.solve"):
                pass
        files = [p for p in tmp_path.iterdir() if p.name.startswith("flight-")]
        assert len(files) == 3
        assert rec.records_written == 7

    def test_raising_state_provider_contained(self, tmp_path):
        rec = obs.configure_flight(str(tmp_path), budget_s=0.0)
        obs.register_state("broken", lambda: 1 / 0)
        with obs.tracer().span("solver.solve"):
            pass
        state = rec.recent()[0]["state"]
        assert "state provider failed" in state["broken"]


# ---------------------------------------------------------------------------
# satellite: logging filter
# ---------------------------------------------------------------------------


class TestLoggingTraceContext:
    def _record(self):
        return logging.LogRecord(
            name="karpenter.test", level=logging.INFO, pathname="", lineno=0,
            msg="hello %s", args=("there",), exc_info=None,
        )

    def test_stamps_ids_inside_span_and_dash_outside(self):
        from karpenter_tpu.logging_config import TraceContextFilter

        f = TraceContextFilter()
        rec = self._record()
        f.filter(rec)
        assert rec.trace_id == "-" and rec.span_id == "-"
        with obs.tracer().span("x") as sp:
            rec2 = self._record()
            f.filter(rec2)
            assert rec2.trace_id == sp.trace_id
            assert rec2.span_id == sp.span_id

    def test_format_renders_through_filtered_handler(self):
        from karpenter_tpu.logging_config import LOG_FORMAT, TraceContextFilter

        handler = logging.Handler()
        rendered = []
        handler.emit = lambda r: rendered.append(
            logging.Formatter(LOG_FORMAT).format(r)
        )
        handler.addFilter(TraceContextFilter())
        lg = logging.getLogger("karpenter.fmt-test")
        lg.addHandler(handler)
        try:
            with obs.tracer().span("y") as sp:
                lg.warning("traced line")
            assert sp.trace_id in rendered[0]
        finally:
            lg.removeHandler(handler)

    def test_live_level_reload_still_works(self, tmp_path):
        # the regression the satellite demands: the filter must not break
        # the config-logging live reload path
        from karpenter_tpu.logging_config import (
            LogLevelWatcher,
            install_trace_filter,
            setup_logging,
        )

        setup_logging("info")
        install_trace_filter()  # idempotent double-install
        root = logging.getLogger()
        for h in root.handlers:
            from karpenter_tpu.logging_config import TraceContextFilter

            assert sum(isinstance(x, TraceContextFilter) for x in h.filters) <= 1
        level_file = tmp_path / "loglevel"
        level_file.write_text("debug")
        watcher = LogLevelWatcher(str(level_file), interval=60)
        watcher._apply_once()
        assert logging.getLogger("karpenter").level == logging.DEBUG
        level_file.write_text("warning")
        watcher._apply_once()
        assert logging.getLogger("karpenter").level == logging.WARNING
        logging.getLogger("karpenter").setLevel(logging.INFO)


# ---------------------------------------------------------------------------
# satellite: event recorder annotation
# ---------------------------------------------------------------------------


class _StubCluster:
    def __init__(self, fail: bool = False):
        self.fail = fail
        self.created = []

    def clock(self):
        return time.time()

    def create(self, kind, obj):
        if self.fail:
            raise RuntimeError("apiserver down")
        self.created.append(obj)

    def update(self, kind, obj):
        if self.fail:
            raise RuntimeError("apiserver down")


class TestEventTraceAnnotation:
    def test_event_carries_active_trace_id(self):
        from karpenter_tpu.kube.events import TRACE_ID_ANNOTATION, EventRecorder

        rec = EventRecorder(_StubCluster())
        with obs.tracer().span("launch") as sp:
            ev = rec.event("Node", "n1", "Launched", "ok")
        assert ev.metadata.annotations[TRACE_ID_ANNOTATION] == sp.trace_id

    def test_no_span_no_annotation(self):
        from karpenter_tpu.kube.events import TRACE_ID_ANNOTATION, EventRecorder

        ev = EventRecorder(_StubCluster()).event("Node", "n1", "Launched", "ok")
        assert TRACE_ID_ANNOTATION not in ev.metadata.annotations

    def test_write_failure_never_fails_traced_action(self):
        # the satellite's double assertion: annotation path active AND an
        # event write failure still never raises into the caller
        from karpenter_tpu.kube.events import EventRecorder

        rec = EventRecorder(_StubCluster(fail=True))
        with obs.tracer().span("launch") as sp:
            out = rec.event("Node", "n1", "Launched", "ok")
        assert out is None  # swallowed, not raised
        assert sp.error is None


# ---------------------------------------------------------------------------
# satellite: breaker short-circuit attribution
# ---------------------------------------------------------------------------


class TestBreakerShortCircuit:
    def test_shortcircuit_counted_and_tagged(self):
        from karpenter_tpu.cloudprovider.fake import FakeCloudProvider, instance_types
        from karpenter_tpu.cloudprovider.metrics import MeteredCloudProvider
        from karpenter_tpu.resilience import BreakerOpen

        provider = MeteredCloudProvider(FakeCloudProvider(instance_types(4)))
        breaker = provider.breakers.get(f"{provider.name()}:get_instance_types")
        for _ in range(10):
            breaker.record_failure()
        assert not breaker.allow()
        counter = metrics.CLOUDPROVIDER_BREAKER_SHORTCIRCUIT.labels(
            provider=provider.name(), method="get_instance_types"
        )
        before = counter._value.get()
        with obs.tracer().span("provision.launch") as parent:
            with pytest.raises(BreakerOpen):
                provider.get_instance_types(None)
        assert counter._value.get() == before + 1
        # the fast-fail is attributable: the cloud span AND its parent are
        # tagged, so a traced launch with a gap explains itself
        assert parent.attrs.get("short_circuit") is True
        cloud = [c for c in parent.children if c.name == "cloud.get_instance_types"]
        assert cloud and cloud[0].attrs.get("short_circuit") is True


# ---------------------------------------------------------------------------
# scheduler stage spans vs last_stage_profile
# ---------------------------------------------------------------------------


class TestStageSpanAgreement:
    def test_stage_spans_agree_with_profile_within_1ms(self):
        from karpenter_tpu.cloudprovider.fake import instance_types
        from karpenter_tpu.kube.client import Cluster
        from karpenter_tpu.scheduling.scheduler import Scheduler
        from karpenter_tpu.testing import diverse_pods, make_provisioner

        catalog = instance_types(8)
        provisioner = make_provisioner(solver="tpu")
        pods = diverse_pods(16, random.Random(3))
        scheduler = Scheduler(Cluster(), rng=random.Random(1))
        scheduler.solve(provisioner, catalog, pods)  # warmup/compile
        obs.exporter().clear()
        nodes = scheduler.solve(provisioner, catalog, pods)
        assert nodes
        prof = scheduler.last_stage_profile()
        trees = obs.exporter().trees()
        assert len(trees) == 1 and trees[0]["name"] == "solver.solve"
        stages = {c["name"]: c["duration_ms"] for c in trees[0]["children"]}
        for span_name, prof_key in [
            ("solve.sort", "sort_s"), ("solve.inject", "inject_s"),
            ("solve.encode", "encode_s"), ("solve.decode", "decode_s"),
        ]:
            assert abs(stages[span_name] - prof[prof_key] * 1e3) < 1.0, (
                span_name, stages[span_name], prof[prof_key] * 1e3
            )
        # dispatch + fetch spans bracket exactly what pack_fetch_s times
        # (no wire in play in-process, so no wire_ser/deser subtraction)
        packed = stages.get("solve.pack_begin", 0.0) + stages.get(
            "solve.pack_fetch", 0.0
        )
        assert abs(packed - prof["pack_fetch_s"] * 1e3) < 1.0
        # router attributes landed on the dispatch span when routing ran
        tree_attrs = [
            c["attrs"] for c in trees[0]["children"]
            if c["name"] == "solve.pack_begin"
        ]
        assert tree_attrs  # the span exists even when only one candidate


# ---------------------------------------------------------------------------
# the v3 wire: sidecar child spans linked across process boundary
# ---------------------------------------------------------------------------


def encoded_args(n_types: int = 8, n_pods: int = 6, seed: int = 3):
    from karpenter_tpu.cloudprovider.fake import instance_types
    from karpenter_tpu.cloudprovider.requirements import catalog_requirements
    from karpenter_tpu.kube.client import Cluster
    from karpenter_tpu.scheduling.ffd import daemon_overhead, sort_pods_ffd
    from karpenter_tpu.scheduling.topology import Topology
    from karpenter_tpu.solver import encode as enc
    from karpenter_tpu.testing import diverse_pods, make_provisioner

    catalog = sorted(instance_types(n_types), key=lambda it: it.effective_price())
    constraints = make_provisioner(solver="tpu").spec.constraints
    constraints.requirements = constraints.requirements.merge(
        catalog_requirements(catalog)
    )
    pods = sort_pods_ffd(diverse_pods(n_pods, random.Random(seed)))
    cluster = Cluster()
    Topology(cluster, rng=random.Random(1)).inject(constraints, pods)
    batch = enc.encode(
        constraints, catalog, pods, daemon_overhead(cluster, constraints)
    )
    return batch.pack_args(), len(batch.pod_valid)


class TestWirePropagation:
    def test_trace_ctx_array_round_trip(self):
        from karpenter_tpu.solver.service import _ctx_from_array, _trace_ctx_array

        ctx = obs.SpanContext("ab" * 16, "12" * 8)
        arr = _trace_ctx_array(ctx)
        assert arr.dtype == np.int32 and arr.size == 6
        assert _ctx_from_array(arr) == ctx
        assert _ctx_from_array(np.zeros(5, np.int32)) is None
        assert _ctx_from_array(np.zeros(6, np.float32)) is None

    def test_untraced_frame_unchanged_and_no_trailer(self):
        from karpenter_tpu.solver import service as svc

        args, p = encoded_args()
        args = [np.asarray(a) for a in args]
        service = svc.SolverService()
        key = svc.catalog_session_key(*args[svc.N_POD_ARRAYS:])
        service.open_session_bytes(svc.pack_arrays(
            [svc._key_array(key)] + args[svc.N_POD_ARRAYS:]
        ))
        response = service.solve_bytes(svc.pack_arrays(
            [svc._key_array(key), np.asarray([8], np.int32)]
            + args[:svc.N_POD_ARRAYS]
        ))
        arrays = svc.unpack_arrays(response)
        assert int(arrays[0].reshape(-1)[0]) == svc.STATUS_OK
        assert len(arrays) == 2  # status + fused buffer, NO stage trailer

    def test_traced_solve_returns_stage_trailer_and_sidecar_spans(self):
        from karpenter_tpu.solver import service as svc

        args, p = encoded_args()
        args = [np.asarray(a) for a in args]
        service = svc.SolverService()
        key = svc.catalog_session_key(*args[svc.N_POD_ARRAYS:])
        ctx = obs.SpanContext("cd" * 16, "34" * 8)
        service.open_session_bytes(svc.pack_arrays(
            [svc._key_array(key)] + args[svc.N_POD_ARRAYS:]
            + [np.asarray([1], np.int32), svc._trace_ctx_array(ctx)]
        ))
        response = service.solve_bytes(svc.pack_arrays(
            [svc._key_array(key), np.asarray([8], np.int32)]
            + args[:svc.N_POD_ARRAYS] + [svc._trace_ctx_array(ctx)]
        ))
        arrays = svc.unpack_arrays(response)
        assert int(arrays[0].reshape(-1)[0]) == svc.STATUS_OK
        trailer = arrays[-1]
        assert trailer.dtype == np.float32 and trailer.size == 3
        assert all(v >= 0.0 for v in trailer)
        # the sidecar's own ring holds its half of the trace, joined to
        # the caller by the propagated ids
        names = {t["name"]: t for t in obs.exporter().snapshot(limit=None)}
        assert names["sidecar.pack"]["trace_id"] == ctx.trace_id
        assert names["sidecar.pack"]["parent_id"] == ctx.span_id
        assert {c["name"] for c in names["sidecar.pack"]["children"]} >= {
            "sidecar.solve", "sidecar.fetch", "sidecar.serialize",
        }
        assert names["sidecar.device_put"]["trace_id"] == ctx.trace_id

    def test_remote_solver_grafts_sidecar_stages(self):
        grpc = pytest.importorskip("grpc")  # noqa: F841
        from karpenter_tpu.solver.service import RemoteSolver, serve

        address = f"127.0.0.1:{free_port()}"
        server = serve(address)
        try:
            client = RemoteSolver(address, timeout=30)
            args, p = encoded_args()
            with obs.tracer().span("test.root"):
                result = client.pack(*args, n_max=8)
            assert int(result.n_nodes) >= 1
            trees = {t["name"]: t for t in obs.exporter().snapshot(limit=None)}
            root = trees["test.root"]
            wire = [c for c in root["children"] if c["name"] == "solver.wire"]
            assert wire, [c["name"] for c in root["children"]]
            grafted = {c["name"] for c in wire[0]["children"]}
            assert grafted >= {"sidecar.solve", "sidecar.fetch", "sidecar.serialize"}
            # the sidecar's real spans share the trace id (in-process server
            # shares the default tracer here — one ring, same join)
            assert trees["sidecar.pack"]["trace_id"] == root["trace_id"]
        finally:
            server.stop(grace=0)

    def test_old_sidecar_never_sees_pack_trailer(self):
        # rolling-upgrade interop: a pre-trailer sidecar does not advertise
        # PROTO_TRACE_TRAILER, so a traced client must keep its Pack frames
        # trailer-free (an old server's `*pod_arrays` unpack would swallow
        # the trailer as an extra pod array and crash the solve)
        grpc = pytest.importorskip("grpc")  # noqa: F841
        from karpenter_tpu.solver import service as svc

        class OldSidecar(svc.SolverService):
            def open_session_bytes(self, request):
                super().open_session_bytes(request)
                return svc._status_response(svc.STATUS_OK)  # no capabilities

            def solve_bytes(self, request):
                # the old unpack: a trailer would land in pod_arrays here
                arrays = svc.unpack_arrays(request)
                assert len(arrays) == 2 + svc.N_POD_ARRAYS, len(arrays)
                return super().solve_bytes(request)

        address = f"127.0.0.1:{free_port()}"
        server = svc.serve(address, service=OldSidecar())
        try:
            client = svc.RemoteSolver(address, timeout=30)
            args, p = encoded_args()
            with obs.tracer().span("test.root"):
                result = client.pack(*args, n_max=8)
            assert int(result.n_nodes) >= 1
            assert client._server_features == 0
            trees = {t["name"] for t in obs.exporter().snapshot(limit=None)}
            assert "sidecar.pack" not in trees  # nothing traced server-side
        finally:
            server.stop(grace=0)

    def test_http_wire_traceparent_parents_server_span(self):
        from karpenter_tpu.cloudprovider.httpapi import CloudAPIServer, HttpCloudAPI

        with CloudAPIServer() as srv:
            client = HttpCloudAPI(srv.url)
            with obs.tracer().span("cloud.get_instance_types") as sp:
                client.describe_instance_types()
            trees = obs.exporter().snapshot(limit=None)
            server_spans = [t for t in trees if t["name"] == "cloudapi.request"]
            assert server_spans
            assert server_spans[0]["trace_id"] == sp.trace_id
            assert server_spans[0]["parent_id"] == sp.span_id


# ---------------------------------------------------------------------------
# lifecycle traces: provisioning round, node-ready, interruption
# ---------------------------------------------------------------------------


class TestLifecycleTraces:
    def _provision(self):
        from karpenter_tpu.cloudprovider import metrics as cpmetrics
        from karpenter_tpu.cloudprovider.fake import FakeCloudProvider, instance_types
        from karpenter_tpu.controllers.provisioning import ProvisioningController
        from karpenter_tpu.kube.client import Cluster
        from karpenter_tpu.testing import make_pod, make_provisioner

        cluster = Cluster()
        provider = cpmetrics.decorate(FakeCloudProvider(instance_types(6)))
        controller = ProvisioningController(cluster, provider, start_workers=False)
        provisioner = make_provisioner()
        cluster.create("provisioners", provisioner)
        pods = [make_pod(requests={"cpu": "1"}) for _ in range(3)]
        for p in pods:
            cluster.create("pods", p)
        controller.apply(provisioner)
        worker = controller.workers[provisioner.name]
        for p in pods:
            worker.batcher.add(p)
        worker.batcher.idle_duration = 0.01
        nodes = worker.provision_once()
        controller.stop()
        return cluster, nodes

    def test_provision_round_tree_covers_lifecycle(self):
        cluster, nodes = self._provision()
        assert nodes
        trees = obs.exporter().snapshot(limit=None)
        rounds = [t for t in trees if t["name"] == "provision.round"]
        assert rounds
        tree = rounds[0]
        names = {c["name"] for c in tree["children"]}
        assert {"solver.solve", "provision.launch"} <= names
        assert tree["attrs"]["admission_window_s"] >= 0.0
        launch = next(c for c in tree["children"] if c["name"] == "provision.launch")
        launch_children = {c["name"] for c in launch["children"]}
        assert "cloud.create" in launch_children
        assert "provision.bind" in launch_children
        # the launch trace is stamped on the Node for node.ready to join
        node = cluster.nodes()[0]
        header = node.metadata.annotations.get(obs.TRACE_ANNOTATION)
        assert obs.from_traceparent(header) is not None
        assert obs.from_traceparent(header).trace_id == tree["trace_id"]

    def test_node_ready_joins_launch_trace(self):
        from karpenter_tpu.api import labels as lbl
        from karpenter_tpu.api.objects import PodCondition, Taint
        from karpenter_tpu.controllers.node import Initialization
        from karpenter_tpu.kube.client import Cluster
        from karpenter_tpu.testing.factories import make_node, make_provisioner

        cluster = Cluster()
        node = make_node(name="n1", provisioner_name="default")
        node.spec.taints.append(
            Taint(key=lbl.NOT_READY_TAINT_KEY, value="", effect="NoSchedule")
        )
        node.status.conditions.append(PodCondition(type="Ready", status="True"))
        ctx = obs.SpanContext("ef" * 16, "56" * 8)
        node.metadata.annotations[obs.TRACE_ANNOTATION] = obs.to_traceparent(ctx)
        cluster.create("nodes", node)
        Initialization(cluster).reconcile(make_provisioner(), node)
        assert not any(
            t.key == lbl.NOT_READY_TAINT_KEY for t in node.spec.taints
        )
        ready = [
            t for t in obs.exporter().snapshot(limit=None)
            if t["name"] == "node.ready"
        ]
        assert ready and ready[0]["trace_id"] == ctx.trace_id

    def test_interruption_notice_tree(self):
        from karpenter_tpu.interruption.orchestrator import Orchestrator
        from karpenter_tpu.interruption.types import PREEMPTION, DisruptionNotice
        from karpenter_tpu.kube.client import Cluster
        from karpenter_tpu.testing.factories import make_node, make_pod

        cluster = Cluster()
        node = make_node(name="victim", provisioner_name="default")
        cluster.create("nodes", node)
        cluster.create(
            "pods",
            make_pod(name="p1", node_name="victim", unschedulable=False),
        )
        orch = Orchestrator(cluster, None, None, None)
        response = orch.handle(DisruptionNotice(
            kind=PREEMPTION, node_name="victim", grace_period_seconds=30.0,
        ))
        assert response is not None and len(response.migrated) == 1
        trees = [
            t for t in obs.exporter().snapshot(limit=None)
            if t["name"] == "interruption.notice"
        ]
        assert trees
        names = [c["name"] for c in trees[0]["children"]]
        assert names == [
            "interruption.taint_cordon", "interruption.replace",
            "interruption.drain_handoff",
        ]
        assert trees[0]["attrs"]["kind"] == PREEMPTION


# ---------------------------------------------------------------------------
# /debug endpoints
# ---------------------------------------------------------------------------


class TestDebugEndpoints:
    def test_sidecar_health_serves_traces_and_flight(self, tmp_path):
        from karpenter_tpu.solver.service import SolverService, _serve_health

        obs.configure_flight(str(tmp_path), budget_s=0.0)
        with obs.tracer().span("solver.solve", attrs={"pods": 5}):
            pass
        service = SolverService()
        service.ready.set()
        port = free_port()
        httpd = _serve_health(service, port)
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/traces", timeout=5
            ) as resp:
                body = json.loads(resp.read())
            assert body["traces"][0]["name"] == "solver.solve"
            assert body["traces"][0]["attrs"]["pods"] == 5
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/flight", timeout=5
            ) as resp:
                flight = json.loads(resp.read())
            assert flight["records"][0]["name"] == "solver.solve"
            assert "state" in flight["records"][0]
        finally:
            httpd.shutdown()


# ---------------------------------------------------------------------------
# the online SLO engine (obs/slo.py)
# ---------------------------------------------------------------------------


class TestSloGrammar:
    def test_defaults_and_sidecar_sets_parse(self):
        from karpenter_tpu.obs.slo import (
            DEFAULT_OBJECTIVES, SIDECAR_OBJECTIVES, parse_objectives,
        )

        # 5 since the kube transport landed: solve / provision /
        # time_to_bind / session hit rate / kube.p99 (docs/partition.md)
        assert len(parse_objectives(DEFAULT_OBJECTIVES)) == 5
        assert len(parse_objectives(SIDECAR_OBJECTIVES)) == 2

    @pytest.mark.parametrize("expr", [
        "solve.p99 100ms",            # no operator
        "mystery.p99 < 100ms",        # unknown source
        "solve.p101 < 100ms",         # not a percentile
        "solve.median < 100ms",       # unknown stat
        "solve.p99 < 100parsecs",     # unknown unit
    ])
    def test_bad_expression_raises(self, expr):
        from karpenter_tpu.obs.slo import parse_objectives

        with pytest.raises(ValueError):
            parse_objectives([expr])

    def test_units_and_threshold(self):
        from karpenter_tpu.obs.slo import Objective

        assert Objective("solve.p99 < 100ms").threshold == pytest.approx(0.1)
        assert Objective("solve.p50 < 250us").threshold == pytest.approx(250e-6)
        assert Objective("time_to_bind.p99 < 5s").threshold == pytest.approx(5.0)
        assert Objective("provision.success_rate >= 0.999").budget == (
            pytest.approx(0.001)
        )

    def test_name_collision_rejected(self):
        from karpenter_tpu.obs.slo import parse_objectives

        with pytest.raises(ValueError, match="collides"):
            parse_objectives(["solve.p99 < 100ms", "solve.p99 < 50ms"])

    def test_config_file_round_trip_and_eager_validation(self, tmp_path):
        from karpenter_tpu.obs.slo import load_objectives

        good = tmp_path / "slo.conf"
        good.write_text(
            "# the controller's view\n"
            "solve.p99 < 100ms   # BASELINE\n"
            "\n"
            "session.catalog_hit_rate >= 0.9\n"
        )
        assert load_objectives(str(good)) == [
            "solve.p99 < 100ms", "session.catalog_hit_rate >= 0.9",
        ]
        bad = tmp_path / "bad.conf"
        bad.write_text("solve.p99 <\n")
        with pytest.raises(ValueError):
            load_objectives(str(bad))

    def test_typoed_config_fails_options_validation(self, tmp_path):
        from karpenter_tpu.options import Options

        bad = tmp_path / "bad.conf"
        bad.write_text("warp.factor > 9\n")
        errs = Options(slo_config=str(bad)).validate()
        assert any("slo-config" in e for e in errs)


class TestSloEngine:
    def _engine(self, clock, objectives=None, window_s=10.0):
        return obs.configure_slo(
            objectives=objectives, window_s=window_s, clock=clock,
        )

    def test_online_quantile_tracks_offline_within_5pct(self):
        t = [0.0]
        eng = self._engine(lambda: t[0])
        durations = [0.001 * (i + 1) for i in range(200)]  # 1ms..200ms
        for d in durations:
            eng(_FakeSpan("solver.solve", d))
        snap = eng.snapshot()["objectives"]["solve_p99"]
        offline = sorted(durations)[int(0.99 * len(durations)) - 1]
        assert abs(snap["value"] - offline) / offline < 0.05

    def test_window_rotation_burn_rate_transitions(self):
        """The deterministic burn-rate life cycle under a fake clock:
        a burst of budget-breaching solves trips BOTH windows (burning),
        the fast window forgives after window_s of silence (not burning,
        slow still hot), and the slow window forgives after 12x that."""
        t = [0.0]
        eng = self._engine(lambda: t[0], window_s=10.0)  # slow = 120s
        st = eng.snapshot()["objectives"]["solve_p99"]
        assert st["ok"] is None and st["burn_rate"] == {"fast": 0.0, "slow": 0.0}

        for _ in range(50):  # every one breaches the 100ms threshold
            eng(_FakeSpan("solver.solve", 0.5))
        hot = eng.snapshot()["objectives"]["solve_p99"]
        assert hot["ok"] is False
        # 100% bad over a 1% budget: burn rate 100x in both windows
        assert hot["burn_rate"]["fast"] == pytest.approx(100.0)
        assert hot["burn_rate"]["slow"] == pytest.approx(100.0)
        assert hot["burning"] is True

        t[0] += 15.0  # one fast window of silence: slices expire by INDEX
        cooled = eng.snapshot()["objectives"]["solve_p99"]
        assert cooled["events"]["fast"] == 0
        assert cooled["burn_rate"]["fast"] == 0.0
        assert cooled["events"]["slow"] == 50  # still inside the slow window
        assert cooled["burn_rate"]["slow"] == pytest.approx(100.0)
        assert cooled["burning"] is False  # multiwindow: a cooled fast unpages

        t[0] += 130.0  # beyond the slow window too
        cold = eng.snapshot()["objectives"]["solve_p99"]
        assert cold["events"] == {"fast": 0, "slow": 0}
        assert cold["burn_rate"] == {"fast": 0.0, "slow": 0.0}

    def test_good_events_do_not_burn(self):
        t = [0.0]
        eng = self._engine(lambda: t[0])
        for _ in range(100):
            eng(_FakeSpan("solver.solve", 0.001))
        snap = eng.snapshot()["objectives"]["solve_p99"]
        assert snap["ok"] is True
        assert snap["burn_rate"] == {"fast": 0.0, "slow": 0.0}
        assert snap["burning"] is False

    def test_span_ratio_counts_errors(self):
        t = [0.0]
        eng = self._engine(lambda: t[0])
        for i in range(1000):
            eng(_FakeSpan("provision.round", 0.01, error="boom" if i < 5 else None))
        snap = eng.snapshot()["objectives"]["provision_success_rate"]
        assert snap["value"] == pytest.approx(0.995)
        assert snap["ok"] is False  # 0.995 < 0.999
        # 0.5% bad over a 0.1% budget
        assert snap["burn_rate"]["fast"] == pytest.approx(5.0)

    def test_low_volume_windows_never_burn(self):
        """Burn divides by OBSERVED volume: after an idle period a tiny
        all-bad burst is 100% of both windows — the volume guard keeps it
        from paging until the window holds MIN_WINDOW_EVENTS."""
        from karpenter_tpu.obs.slo import MIN_WINDOW_EVENTS

        t = [3600.0 * 10]  # a long-idle process
        eng = self._engine(lambda: t[0])
        for _ in range(MIN_WINDOW_EVENTS - 1):
            eng(_FakeSpan("solver.solve", 0.5))  # every one breaches
        blip = eng.snapshot()["objectives"]["solve_p99"]
        assert blip["burn_rate"] == {"fast": 0.0, "slow": 0.0}
        assert blip["burning"] is False
        assert blip["ok"] is False  # the VERDICT still tells the truth
        eng(_FakeSpan("solver.solve", 0.5))  # ...the guard threshold
        page = eng.snapshot()["objectives"]["solve_p99"]
        assert page["burn_rate"]["fast"] == pytest.approx(100.0)
        assert page["burning"] is True

    def test_ratio_source_via_record_ratio(self):
        t = [0.0]
        eng = self._engine(lambda: t[0])
        for _ in range(8):
            eng.record_ratio("session.catalog_hit_rate", True)
        eng.record_ratio("session.catalog_hit_rate", False)
        snap = eng.snapshot()["objectives"]["session_catalog_hit_rate"]
        assert snap["value"] == pytest.approx(8 / 9)
        assert snap["ok"] is False  # 0.889 < 0.9

    def test_time_to_bind_adds_admission_window(self):
        t = [0.0]
        eng = self._engine(lambda: t[0], objectives=["time_to_bind.p99 < 5s"])
        eng(_FakeSpan(
            "provision.round", 3.0, attrs={"admission_window_s": 4.0},
        ))
        snap = eng.snapshot()["objectives"]["time_to_bind_p99"]
        assert snap["value"] == pytest.approx(7.0, rel=0.05)
        assert snap["ok"] is False

    def test_slo_gauges_published(self):
        from prometheus_client import generate_latest

        t = [0.0]
        eng = self._engine(lambda: t[0])
        bad_before = metrics.SLO_EVENTS.labels(
            objective="solve_p99", verdict="bad"
        )._value.get()
        for _ in range(10):
            eng(_FakeSpan("solver.solve", 0.5))
        eng.snapshot()  # snapshot republishes every gauge
        out = generate_latest(metrics.REGISTRY).decode()
        assert 'karpenter_slo_objective_ok{objective="solve_p99"} 0.0' in out
        assert ('karpenter_slo_burn_rate{objective="solve_p99",'
                'window="fast"} 100.0') in out
        assert 'karpenter_slo_burning{objective="solve_p99"} 1.0' in out
        bad_after = metrics.SLO_EVENTS.labels(
            objective="solve_p99", verdict="bad"
        )._value.get()
        assert bad_after - bad_before == 10

    def test_objective_ok_unset_until_data(self):
        """A data-less objective must not publish ok=0.0 ("failing") —
        the child gauge materializes on the first real verdict."""
        from prometheus_client import generate_latest

        t = [0.0]
        eng = self._engine(lambda: t[0], objectives=["provision.p95 < 1s"])
        eng.snapshot()
        out = generate_latest(metrics.REGISTRY).decode()
        assert 'karpenter_slo_objective_ok{objective="provision_p95"}' not in out
        assert 'karpenter_slo_burning{objective="provision_p95"} 0.0' in out
        eng(_FakeSpan("provision.round", 0.01))
        eng.snapshot()
        out = generate_latest(metrics.REGISTRY).decode()
        assert 'karpenter_slo_objective_ok{objective="provision_p95"} 1.0' in out

    def test_exemplar_agrees_with_flight_record(self, tmp_path):
        """The breach exemplar and the flight record must name the SAME
        trace: /debug/slo's "show me a bad solve" id greps straight into
        the flight dir."""
        rec = obs.configure_flight(str(tmp_path), budget_s=0.0)
        eng = self._engine(
            time.monotonic, objectives=["solve.p99 < 1us"],  # all breach
        )
        with obs.tracer().span("solver.solve"):
            pass
        records = rec.recent()
        assert len(records) == 1
        snap = eng.snapshot()["objectives"]["solve_p99"]
        assert snap["exemplars"]["breach"] == records[0]["trace_id"]
        assert snap["exemplars"]["worst"]["trace_id"] == records[0]["trace_id"]

    def test_flight_record_snapshots_burning_panel(self, tmp_path):
        rec = obs.configure_flight(str(tmp_path), budget_s=0.0)
        self._engine(time.monotonic, objectives=["solve.p99 < 1us"])
        # hooks run in registration order (flight before slo), so each
        # record sees the engine as of the PREVIOUS span — warm with one
        with obs.tracer().span("solver.solve"):
            pass
        with obs.tracer().span("solver.solve"):
            pass
        state = rec.recent()[0]["state"]  # newest record
        assert state["slo"]["solve_p99"]["ok"] is False

    def test_concurrent_hook_vs_snapshot(self):
        """Finish-hooks hammer the windows while /debug/slo snapshots —
        no torn reads, no dict-changed-size, every event accounted for."""
        t = [0.0]
        eng = self._engine(lambda: t[0])
        errors = []
        n_threads, per_thread = 4, 300

        def emit():
            try:
                for _ in range(per_thread):
                    eng(_FakeSpan("solver.solve", 0.001))
            except Exception as e:  # pragma: no cover - the failure mode
                errors.append(e)

        def snapshot():
            try:
                for _ in range(200):
                    eng.snapshot()
            except Exception as e:  # pragma: no cover - the failure mode
                errors.append(e)

        threads = [threading.Thread(target=emit) for _ in range(n_threads)]
        threads.append(threading.Thread(target=snapshot))
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        assert errors == []
        snap = eng.snapshot()["objectives"]["solve_p99"]
        assert snap["events"]["fast"] == n_threads * per_thread

    def test_reset_for_tests_detaches_engine(self):
        self._engine(time.monotonic)
        assert obs.slo_engine() is not None
        obs.reset_for_tests()
        assert obs.slo_engine() is None
        assert obs.slo_snapshot() == {}
        from karpenter_tpu.obs.flight import state_snapshot

        assert "slo" not in state_snapshot()

    def test_shutdown_slo_is_ownership_checked(self):
        """A stopped replica must not tear down the engine a later-started
        replica installed in the same process (Runtime.stop passes the
        engine it owns)."""
        first = self._engine(time.monotonic)
        second = self._engine(time.monotonic)  # replaces first
        obs.shutdown_slo(engine=first)  # stale owner: a no-op
        assert obs.slo_engine() is second
        obs.shutdown_slo(engine=second)  # the current owner detaches
        assert obs.slo_engine() is None


class _FakeSpan:
    """The minimal Span surface the engine's hook reads (a real tracer
    span's duration comes from perf_counter — not fake-clockable)."""

    def __init__(self, name, duration_s, attrs=None, error=None, trace_id="t" * 32):
        self.name = name
        self.duration_s = duration_s
        self.attrs = attrs or {}
        self.error = error
        self.trace_id = trace_id


class TestSloDebugEndpoints:
    def test_sidecar_serves_slo_and_filtered_traces(self):
        from karpenter_tpu.solver.service import SolverService, _serve_health

        eng = obs.configure_slo(objectives=obs.SIDECAR_OBJECTIVES)
        eng(_FakeSpan("sidecar.pack", 0.5))
        with obs.tracer().span("sidecar.pack"):
            pass
        with obs.tracer().span("solver.solve"):
            pass
        service = SolverService()
        service.ready.set()
        port = free_port()
        httpd = _serve_health(service, port)
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/slo", timeout=5
            ) as resp:
                slo = json.loads(resp.read())["slo"]
            assert slo["objectives"]["sidecar_pack_p99"]["ok"] is False
            # ?name= narrows to one trace family; ?limit= bounds it
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/traces?name=sidecar.pack&limit=5",
                timeout=5,
            ) as resp:
                body = json.loads(resp.read())
            assert [t["name"] for t in body["traces"]] == ["sidecar.pack"]
            # exporter residency stats ride the same payload (the drop
            # counter is process-lifetime cumulative, so only its presence
            # is asserted — earlier tests may legitimately have evicted)
            assert body["stats"]["trees"] == 2
            assert body["stats"]["spans"] == 2
            assert body["stats"]["dropped_spans"] >= 0
            assert body["stats"]["capacity"] > 0
        finally:
            httpd.shutdown()

    def test_trace_limit_filter_unit(self):
        for i in range(6):
            with obs.tracer().span("a" if i % 2 else "b"):
                pass
        payload = obs.debug_traces_payload("limit=2")
        assert len(payload["traces"]) == 2
        named = obs.debug_traces_payload("name=a")
        assert {t["name"] for t in named["traces"]} == {"a"}
        assert len(named["traces"]) == 3
        # garbage query degrades to the defaults, never a 500
        assert len(obs.debug_traces_payload("limit=banana")["traces"]) == 6

    def test_ring_gauges_track_residency(self):
        from prometheus_client import generate_latest

        with obs.tracer().span("root"):
            with obs.tracer().span("child"):
                pass
        out = generate_latest(metrics.REGISTRY).decode()
        assert "karpenter_trace_ring_trees 1.0" in out
        assert "karpenter_trace_ring_spans 2.0" in out
        obs.exporter().clear()
        out = generate_latest(metrics.REGISTRY).decode()
        assert "karpenter_trace_ring_trees 0.0" in out
        assert "karpenter_trace_ring_spans 0.0" in out
