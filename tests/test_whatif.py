"""tools/whatif.py: the decision-ring what-if simulator — loaders over a
real on-disk ring, the discrete-event counterfactual, and the CLI."""

import json
import os

import pytest

from karpenter_tpu import obs
from tools import whatif


@pytest.fixture()
def ring(tmp_path):
    """A real on-disk decision ring written through the production log
    (write_interval=0 → every round persists)."""
    d = str(tmp_path / "ring")
    log = obs.configure_decisions(d, write_interval=0.0)
    yield d, log
    obs.configure_decisions("")


def _record_round(log, provisioner, pods_considered, state=None):
    from types import SimpleNamespace

    pods = [
        SimpleNamespace(
            metadata=SimpleNamespace(name=f"p{i}", namespace="default"),
            key=f"default/p{i}",
        )
        for i in range(pods_considered)
    ]
    rec = log.record_round(
        provisioner=provisioner,
        pods=pods,
        nodes=[SimpleNamespace(instance_type_options=[], pods=list(pods))]
        if pods else [],
        trace_id="t" * 32,
        state=state or {},
    )
    # drain the async writer: rapid-fire test writes would overflow its
    # bounded queue (best-effort drops are the production behavior)
    log.flush()
    return rec


class TestLoaders:
    def test_load_records_roundtrip(self, ring):
        d, log = ring
        _record_round(log, "a", 3)
        _record_round(log, "a", 5)
        log.flush()
        records = whatif.load_records(d)
        assert len(records) == 2
        assert [r["pods_considered"] for r in records] == [3, 5]
        assert all("recorded_at" in r for r in records)

    def test_load_records_skips_garbage(self, ring):
        d, log = ring
        _record_round(log, "a", 1)
        log.flush()
        with open(os.path.join(d, "decision-9999999999999-zzzzzz-bad.json"),
                  "w") as f:
            f.write("{not json")
        assert len(whatif.load_records(d)) == 1

    def test_load_records_missing_dir(self):
        assert whatif.load_records("/nonexistent/ring") == []

    def test_load_series_excludes_wave_records(self, ring):
        d, log = ring
        _record_round(log, "a", 4)
        _record_round(log, "a", 0, state={"warm_pool_wave": True,
                                          "deficit": 3})
        _record_round(log, "a", 2, state={"warm_claim": True})
        _record_round(log, "b", 7)
        log.flush()
        series = whatif.load_series(d)
        assert sorted(series) == ["a", "b"]
        # the wave audit entry is not demand; the warm CLAIM is
        assert [p for _, p in series["a"]] == [4.0, 2.0]
        assert [p for _, p in series["b"]] == [7.0]

    def test_load_series_provisioner_filter(self, ring):
        d, log = ring
        _record_round(log, "a", 1)
        _record_round(log, "b", 1)
        log.flush()
        assert sorted(whatif.load_series(d, provisioner="b")) == ["b"]

    def test_measured_pods_per_node(self):
        records = [
            {"pods_considered": 8, "nodes": 2},
            {"pods_considered": 4, "nodes": 1},
            {"pods_considered": 0, "nodes": 0},  # placing rounds only
            {"pods_considered": 99, "nodes": 1,
             "state": {"warm_pool_wave": True}},  # audit entry excluded
        ]
        assert whatif.measured_pods_per_node(records) == pytest.approx(4.0)
        assert whatif.measured_pods_per_node([]) == 1.0


class TestSimulate:
    def _steady(self, n=60, period=5.0, pods=4.0):
        return [(1000.0 + i * period, pods) for i in range(n)]

    def test_empty_series(self):
        out = whatif.simulate([])
        assert out["pods"] == 0
        assert out["warm_hit_rate"] == 0.0
        assert out["speculative_launches"] == 0

    def test_deterministic(self):
        series = self._steady()
        kwargs = dict(warm_pool_ttl=60.0, max_nodes=8, interval_s=5.0,
                      launch_to_ready_s=30.0, bind_latency_s=1.0,
                      pods_per_node=4.0, alpha=0.5, bucket_s=5.0,
                      horizon_s=30.0)
        assert whatif.simulate(series, **kwargs) == whatif.simulate(
            series, **kwargs
        )

    def test_warm_pool_beats_cold_on_steady_demand(self):
        out = whatif.simulate(
            self._steady(), warm_pool_ttl=120.0, max_nodes=10,
            interval_s=5.0, launch_to_ready_s=30.0, bind_latency_s=1.0,
            pods_per_node=4.0, alpha=0.5, bucket_s=5.0, horizon_s=30.0,
        )
        assert out["pods"] == 240
        # the cold ramp (nothing warm until the first wave is ready)
        # bounds the hit rate below 1.0; steady state is all hits
        assert 0.5 < out["warm_hit_rate"] < 1.0
        assert out["p99_without_pool_s"] == 30.0
        assert out["p99_with_pool_s"] <= out["p99_without_pool_s"]
        assert out["speculative_launches"] > 0
        assert out["speculative_cost_usd"] >= 0.0

    def test_long_window_p99_drops_to_bind_latency(self):
        # long enough that the cold ramp is under 1% of arrivals: the
        # with-pool p99 is the warm bind, not the cold launch
        out = whatif.simulate(
            self._steady(n=800, period=5.0, pods=4.0),
            warm_pool_ttl=120.0, max_nodes=10, interval_s=5.0,
            launch_to_ready_s=20.0, bind_latency_s=1.0, pods_per_node=4.0,
            alpha=0.5, bucket_s=5.0, horizon_s=20.0,
        )
        assert out["p99_with_pool_s"] == 1.0
        assert out["p99_without_pool_s"] == 20.0

    def test_zero_max_nodes_is_the_cold_baseline(self):
        out = whatif.simulate(
            self._steady(), max_nodes=0, interval_s=5.0,
            launch_to_ready_s=30.0, pods_per_node=4.0, bucket_s=5.0,
        )
        assert out["warm_hits"] == 0
        assert out["speculative_launches"] == 0
        assert out["p99_with_pool_s"] == 30.0

    def test_unclaimed_speculation_expires_and_is_billed(self):
        # one early burst, then silence: the pool it bought must expire
        series = [(0.0, 10.0), (5.0, 10.0)] + [
            (10.0 + i * 5.0, 0.0) for i in range(30)
        ]
        out = whatif.simulate(
            series, warm_pool_ttl=20.0, max_nodes=6, interval_s=5.0,
            launch_to_ready_s=10.0, pods_per_node=2.0, alpha=0.9,
            bucket_s=5.0, horizon_s=10.0,
        )
        assert out["speculative_launches"] > 0
        # every speculative node was either claimed or expired — the
        # bill covers all of them (node-hours > 0) and none linger
        assert out["speculative_expired"] > 0
        assert out["speculative_node_hours"] > 0.0

    def test_tighter_ttl_costs_less(self):
        series = [(0.0, 8.0), (5.0, 8.0)] + [
            (10.0 + i * 5.0, 0.0) for i in range(60)
        ]
        kwargs = dict(max_nodes=8, interval_s=5.0, launch_to_ready_s=10.0,
                      pods_per_node=2.0, alpha=0.9, bucket_s=5.0,
                      horizon_s=10.0)
        loose = whatif.simulate(series, warm_pool_ttl=300.0, **kwargs)
        tight = whatif.simulate(series, warm_pool_ttl=30.0, **kwargs)
        assert tight["speculative_node_hours"] < loose[
            "speculative_node_hours"
        ]


class TestWhatifEntryPoint:
    def test_per_provisioner_panels_and_combined(self, ring):
        d, log = ring
        for _ in range(10):
            _record_round(log, "a", 4)
            _record_round(log, "b", 2)
        log.flush()
        out = whatif.whatif(d, interval_s=5.0, launch_to_ready_s=30.0,
                            horizon_s=30.0, bucket_s=5.0)
        assert sorted(out["provisioners"]) == ["a", "b"]
        assert out["records"] == 20
        assert out["combined"]["pods"] == 60
        # pods_per_node defaulted to the window-measured ratio
        assert out["pods_per_node"] == pytest.approx(3.0)

    def test_pods_per_node_override(self, ring):
        d, log = ring
        _record_round(log, "a", 4)
        log.flush()
        out = whatif.whatif(d, pods_per_node=7.5)
        assert out["pods_per_node"] == 7.5


class TestCli:
    def test_exit_2_on_empty_ring(self, tmp_path, capsys):
        assert whatif.main(["--decision-dir", str(tmp_path)]) == 2
        doc = json.loads(capsys.readouterr().out)
        assert doc["records"] == 0

    def test_prints_panel(self, ring, capsys):
        d, log = ring
        for _ in range(5):
            _record_round(log, "a", 3)
        log.flush()
        assert whatif.main([
            "--decision-dir", d, "--interval-s", "5",
            "--launch-to-ready-s", "20", "--horizon-s", "20",
        ]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["provisioners"]["a"]["pods"] == 15
        assert "warm_hit_rate" in doc["combined"]

    def test_ttl_sweep(self, ring, capsys):
        d, log = ring
        for _ in range(5):
            _record_round(log, "a", 3)
        log.flush()
        assert whatif.main([
            "--decision-dir", d, "--sweep-ttl", "30,300",
        ]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert [run["warm_pool_ttl"] for run in doc["sweep"]] == [30.0, 300.0]

    def test_seasonal_flag(self, ring, capsys):
        d, log = ring
        for _ in range(5):
            _record_round(log, "a", 3)
        log.flush()
        assert whatif.main([
            "--decision-dir", d, "--seasonal", "--season-len", "12",
        ]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["params"]["model"] == "holt-winters"
