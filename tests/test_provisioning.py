"""End-to-end provisioning slice tests (mirrors provisioning/suite_test.go):
pending pods → batcher → worker → solve → fake cloud provider → node create +
pod bind."""

import time

import pytest

from karpenter_tpu.api import labels as lbl
from karpenter_tpu.cloudprovider.fake import FakeCloudProvider, instance_types
from karpenter_tpu.controllers.provisioning import (
    ProvisionerWorker,
    ProvisioningController,
    is_provisionable,
)
from karpenter_tpu.kube.client import Cluster
from karpenter_tpu.utils import resources as res
from karpenter_tpu.utils.batcher import Batcher
from tests.factories import make_pod, make_provisioner


def provision(pods, provisioner=None, catalog=None, cluster=None, provider=None):
    """Drive one synchronous provision cycle (tests invoke reconciles
    directly, like the reference's ExpectProvisioned)."""
    cluster = cluster or Cluster()
    provider = provider or FakeCloudProvider(catalog or instance_types(20))
    controller = ProvisioningController(cluster, provider, start_workers=False)
    provisioner = provisioner or make_provisioner()
    cluster.create("provisioners", provisioner)
    for p in pods:
        cluster.create("pods", p)
    controller.apply(provisioner)
    worker = controller.workers[provisioner.name]
    for p in pods:
        worker.batcher.add(p)
    worker.batcher.idle_duration = 0.01
    nodes = worker.provision_once()
    controller.stop()
    return cluster, provider, nodes


class TestProvisioning:
    def test_pods_bound_and_nodes_created(self):
        pods = [make_pod(requests={"cpu": "1"}) for _ in range(4)]
        cluster, provider, vnodes = provision(pods)
        assert len(provider.create_calls) == len(vnodes) >= 1
        created = cluster.nodes()
        assert len(created) == len(vnodes)
        for p in cluster.pods():
            assert p.spec.node_name != ""

    def test_node_has_startup_taint_finalizer_and_label(self):
        cluster, provider, _ = provision([make_pod(requests={"cpu": "1"})])
        node = cluster.nodes()[0]
        assert any(t.key == lbl.NOT_READY_TAINT_KEY for t in node.spec.taints)
        assert lbl.TERMINATION_FINALIZER in node.metadata.finalizers
        assert node.metadata.labels[lbl.PROVISIONER_NAME_LABEL] == "default"
        assert lbl.INSTANCE_TYPE in node.metadata.labels

    def test_already_scheduled_pods_skipped(self):
        pod = make_pod(requests={"cpu": "1"}, node_name="existing", unschedulable=False)
        assert not is_provisionable(pod)
        cluster, provider, vnodes = provision([pod])
        assert vnodes == []
        assert provider.create_calls == []

    def test_limits_block_launch(self):
        provisioner = make_provisioner(limits={"cpu": "4"})
        provisioner.status.resources = {res.CPU: 4.0}  # already at the limit
        cluster, provider, vnodes = provision(
            [make_pod(requests={"cpu": "1"})], provisioner=provisioner
        )
        assert provider.create_calls == []  # solve ran but launch was gated
        assert cluster.nodes() == []

    def test_tpu_solver_end_to_end(self):
        provisioner = make_provisioner(solver="tpu")
        pods = [make_pod(requests={"cpu": "1"}) for _ in range(4)]
        cluster, provider, vnodes = provision(pods, provisioner=provisioner)
        assert len(cluster.nodes()) == len(vnodes) >= 1
        for p in cluster.pods():
            assert p.spec.node_name != ""

    def test_worker_hot_swap_on_spec_change(self):
        cluster = Cluster()
        provider = FakeCloudProvider(instance_types(5))
        controller = ProvisioningController(cluster, provider, start_workers=False)
        prov = make_provisioner()
        cluster.create("provisioners", prov)
        controller.apply(prov)
        w1 = controller.workers["default"]
        controller.apply(prov)  # unchanged spec → same worker
        assert controller.workers["default"] is w1
        prov2 = make_provisioner(labels={"team": "a"})
        controller.apply(prov2)
        assert controller.workers["default"] is not w1
        controller.stop()

    def test_reconcile_teardown_on_delete(self):
        cluster = Cluster()
        provider = FakeCloudProvider(instance_types(5))
        controller = ProvisioningController(cluster, provider, start_workers=False)
        prov = make_provisioner()
        cluster.create("provisioners", prov)
        controller.reconcile("default")
        assert "default" in controller.workers
        cluster.delete("provisioners", "default", namespace="")
        controller.reconcile("default")
        assert "default" not in controller.workers
        controller.stop()


class TestThreadedWorkers:
    """The production path: start_workers=True runs the real worker thread,
    batcher window, and (for solver=tpu) the warmup thread — the exact path
    that round 1 shipped broken (NameError on SOLVER_TPU at start())."""

    @pytest.mark.parametrize("solver", ["ffd", "tpu"])
    def test_start_workers_end_to_end(self, solver):
        cluster = Cluster()
        provider = FakeCloudProvider(instance_types(10))
        controller = ProvisioningController(cluster, provider, start_workers=True)
        prov = make_provisioner(solver=solver)
        cluster.create("provisioners", prov)
        try:
            controller.apply(prov)  # crashes here pre-fix when solver == tpu
            worker = controller.workers[prov.name]
            worker.batcher.idle_duration = 0.05
            assert worker._thread is not None and worker._thread.is_alive()
            pods = [make_pod(requests={"cpu": "1"}) for _ in range(3)]
            gates = []
            for p in pods:
                cluster.create("pods", p)
                gates.append(worker.add(p))
            # the selection reconciler blocks on the gate; do the same
            for g in gates:
                assert g.wait(timeout=30), "batch gate never flushed"
            deadline = time.time() + 30
            while time.time() < deadline:
                bound = [p for p in cluster.pods() if p.spec.node_name]
                if len(bound) == len(pods):
                    break
                time.sleep(0.02)
            assert len([p for p in cluster.pods() if p.spec.node_name]) == len(pods)
            assert len(cluster.nodes()) >= 1
        finally:
            controller.stop()
        assert not worker._thread.is_alive()

    def test_tpu_worker_warmup_compiles_solver(self):
        """The warmup thread must complete without raising (it logs on
        failure); verify it actually ran a solve by waiting for it."""
        cluster = Cluster()
        provider = FakeCloudProvider(instance_types(10))
        controller = ProvisioningController(cluster, provider, start_workers=True)
        prov = make_provisioner(solver="tpu")
        cluster.create("provisioners", prov)
        try:
            controller.apply(prov)
            worker = controller.workers[prov.name]
            deadline = time.time() + 60
            while time.time() < deadline and not worker.warmed.is_set():
                time.sleep(0.05)
            assert worker.warmed.is_set(), "warmup never completed"
        finally:
            controller.stop()


class TestBatcher:
    def test_window_closes_on_idle(self):
        b = Batcher(idle_duration=0.05, max_duration=5.0)
        b.add("a")
        b.add("b")
        items, window = b.wait()
        assert items == ["a", "b"]
        assert window < 1.0

    def test_max_items_cap(self):
        b = Batcher(idle_duration=1.0, max_items=3)
        for i in range(5):
            b.add(i)
        items, _ = b.wait()
        assert len(items) == 3

    def test_gate_released_on_flush(self):
        b = Batcher()
        gate = b.add("x")
        assert not gate.is_set()
        b.flush()
        assert gate.is_set()
        # new adds get a fresh gate
        gate2 = b.add("y")
        assert not gate2.is_set()


class TestActiveCondition:
    """Provisioner ``Active`` condition lifecycle (reference:
    provisioner_status.go:28-41 — the knative living condition set): every
    Apply outcome lands in status.conditions with reason +
    lastTransitionTime, and the transition time moves only on flips."""

    def _controller(self, clock=None):
        cluster = Cluster(clock=clock)
        provider = FakeCloudProvider(instance_types(5))
        return cluster, ProvisioningController(cluster, provider, start_workers=False)

    def test_apply_success_marks_active(self):
        cluster, controller = self._controller()
        cluster.create("provisioners", make_provisioner())
        controller.reconcile("default")
        cond = cluster.get("provisioners", "default", namespace="").status.condition()
        assert cond is not None
        assert (cond.type, cond.status) == ("Active", "True")
        assert cond.last_transition_time is not None
        controller.stop()

    def test_apply_failure_marks_not_active_with_reason(self):
        cluster, controller = self._controller()
        bad = make_provisioner(solver="nope")
        cluster.create("provisioners", bad)
        with pytest.raises(ValueError):
            controller.reconcile("default")
        cond = cluster.get("provisioners", "default", namespace="").status.condition()
        assert (cond.status, cond.reason) == ("False", "ValidationFailed")
        assert "solver" in cond.message
        controller.stop()

    def test_transition_bumps_time_steady_state_does_not(self):
        now = [100.0]
        cluster, controller = self._controller(clock=lambda: now[0])
        prov = make_provisioner(solver="nope")
        cluster.create("provisioners", prov)
        with pytest.raises(ValueError):
            controller.reconcile("default")
        t_fail = cluster.get("provisioners", "default", namespace="").status.condition().last_transition_time
        assert t_fail == 100.0
        # fix the spec: False -> True flips the transition time
        now[0] = 200.0
        fixed = cluster.get("provisioners", "default", namespace="")
        fixed.spec.solver = "ffd"
        cluster.update("provisioners", fixed)
        controller.reconcile("default")
        cond = cluster.get("provisioners", "default", namespace="").status.condition()
        assert (cond.status, cond.last_transition_time) == ("True", 200.0)
        assert cond.reason == "" and cond.message == ""
        # steady-state reconcile: no flip, the transition time stays put
        now[0] = 300.0
        controller.reconcile("default")
        cond = cluster.get("provisioners", "default", namespace="").status.condition()
        assert (cond.status, cond.last_transition_time) == ("True", 200.0)
        controller.stop()

    def test_condition_round_trips_over_the_wire(self):
        from karpenter_tpu.kube import serde

        cluster, controller = self._controller()
        cluster.create("provisioners", make_provisioner())
        controller.reconcile("default")
        prov = cluster.get("provisioners", "default", namespace="")
        wire = serde.to_wire("provisioners", prov)
        wc = wire["status"]["conditions"][0]
        assert wc["type"] == "Active" and wc["status"] == "True"
        assert "lastTransitionTime" in wc
        back = serde.from_wire("provisioners", wire)
        cond = back.status.condition()
        assert (cond.type, cond.status) == ("Active", "True")
        assert serde.to_wire("provisioners", back) == wire
        controller.stop()

    def test_failed_condition_write_retried_next_reconcile(self):
        # _set_active never mutates the cached object, so a swallowed write
        # failure leaves the drift detectable and the next reconcile retries
        cluster, controller = self._controller()
        cluster.create("provisioners", make_provisioner())
        real = cluster.patch_status
        calls = {"n": 0}

        def flaky(*a, **kw):
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("transient apiserver error")
            return real(*a, **kw)

        cluster.patch_status = flaky
        controller.reconcile("default")  # write fails, swallowed (debug log)
        assert cluster.get("provisioners", "default", namespace="").status.condition() is None
        controller.reconcile("default")
        cond = cluster.get("provisioners", "default", namespace="").status.condition()
        assert cond is not None and cond.status == "True"
        assert calls["n"] == 2
        controller.stop()

    def test_active_gauge_tracks_condition(self):
        from karpenter_tpu import metrics

        def gauge():
            return metrics.REGISTRY.get_sample_value(
                "karpenter_provisioner_active", {"provisioner": "default"}
            )

        cluster, controller = self._controller()
        bad = make_provisioner(solver="nope")
        cluster.create("provisioners", bad)
        with pytest.raises(ValueError):
            controller.reconcile("default")
        assert gauge() == 0.0
        fixed = cluster.get("provisioners", "default", namespace="")
        fixed.spec.solver = "ffd"
        cluster.update("provisioners", fixed)
        controller.reconcile("default")
        assert gauge() == 1.0
        cluster.delete("provisioners", "default", namespace="")
        controller.reconcile("default")  # teardown clears the series
        assert gauge() is None
        controller.stop()

    def test_condition_write_preserves_foreign_conditions(self):
        # arrays replace wholesale under RFC 7386: a 1-element Active patch
        # would erase conditions owned by other writers — _set_active must
        # read-modify-write the full list
        from karpenter_tpu.api.provisioner import Condition

        cluster, controller = self._controller()
        prov = make_provisioner()
        prov.status.conditions.append(
            Condition(type="CatalogReady", status="True", reason="Discovered")
        )
        cluster.create("provisioners", prov)
        controller.reconcile("default")
        conds = {c.type: c for c in cluster.get("provisioners", "default", namespace="").status.conditions}
        assert conds["Active"].status == "True"
        assert conds["CatalogReady"].status == "True"
        assert conds["CatalogReady"].reason == "Discovered"
        controller.stop()

    def test_reconcile_of_unknown_name_never_raises(self):
        # _teardown guards PROVISIONER_ACTIVE.remove: several
        # prometheus_client releases raise KeyError for a never-gauged
        # label set, and that must not escape reconcile()
        cluster, controller = self._controller()
        assert controller.reconcile("ghost") is None
        controller.stop()

    def test_stop_clears_gauge_for_never_started_provisioner(self):
        from karpenter_tpu import metrics

        cluster, controller = self._controller()
        cluster.create("provisioners", make_provisioner(name="broken", solver="nope"))
        with pytest.raises(ValueError):
            controller.reconcile("broken")
        assert metrics.REGISTRY.get_sample_value(
            "karpenter_provisioner_active", {"provisioner": "broken"}
        ) == 0.0
        controller.stop()  # no worker ever existed for "broken"
        assert metrics.REGISTRY.get_sample_value(
            "karpenter_provisioner_active", {"provisioner": "broken"}
        ) is None
