"""Counter, PVC, and manager-runtime tests (mirrors counter + pvc suites and
the controller-runtime wiring in pkg/controllers/manager.go)."""

import threading
import time

from karpenter_tpu.controllers.counter import CounterController
from karpenter_tpu.controllers.manager import Manager
from karpenter_tpu.controllers.pvc import PVCController, SELECTED_NODE_ANNOTATION
from karpenter_tpu.kube.client import Cluster
from karpenter_tpu.utils import resources as res
from karpenter_tpu.utils.workqueue import ExponentialBackoff, RateLimitingQueue, TokenBucket
from tests.factories import make_node, make_pod, make_provisioner, make_pvc


class TestCounter:
    def test_sums_capacity_of_owned_nodes(self):
        cluster = Cluster()
        cluster.create("provisioners", make_provisioner())
        cluster.create("nodes", make_node(capacity={"cpu": "4", "memory": "8Gi"}, provisioner_name="default"))
        cluster.create("nodes", make_node(capacity={"cpu": "2"}, provisioner_name="default"))
        cluster.create("nodes", make_node(capacity={"cpu": "16"}, provisioner_name="other"))
        CounterController(cluster).reconcile("default")
        prov = cluster.get("provisioners", "default", namespace="")
        assert prov.status.resources[res.CPU] == 6.0
        assert prov.status.resources[res.MEMORY] == 8 * 1024**3

    def test_vanished_resource_key_cleared(self):
        # RFC 7386 merges key-wise: a resource whose last node vanished must
        # be explicitly nulled or it would linger and feed Limits forever
        cluster = Cluster()
        cluster.create("provisioners", make_provisioner())
        cluster.create("nodes", make_node(capacity={"cpu": "4"}, provisioner_name="default"))
        gpu = make_node(
            capacity={"cpu": "2", "nvidia.com/gpu": "1"}, provisioner_name="default"
        )
        cluster.create("nodes", gpu)
        counter = CounterController(cluster)
        counter.reconcile("default")
        prov = cluster.get("provisioners", "default", namespace="")
        assert prov.status.resources.get("nvidia.com/gpu") == 1.0
        cluster.delete("nodes", gpu.metadata.name, namespace="")
        counter.reconcile("default")
        prov = cluster.get("provisioners", "default", namespace="")
        assert "nvidia.com/gpu" not in prov.status.resources
        assert prov.status.resources[res.CPU] == 4.0
        # converged: a further reconcile is a no-op (no patch churn)
        calls = []
        orig = cluster.patch_status
        cluster.patch_status = lambda *a, **k: calls.append(1) or orig(*a, **k)
        counter.reconcile("default")
        assert calls == []

    def test_watch_mapping_enqueues_owner(self):
        cluster = Cluster()
        manager = Manager(cluster)
        counter = CounterController(cluster)
        manager.register("counter", counter.reconcile, concurrency=1)
        counter.register(manager)
        cluster.create("provisioners", make_provisioner())
        manager.start()
        cluster.create("nodes", make_node(capacity={"cpu": "4"}, provisioner_name="default"))
        deadline = time.monotonic() + 5
        prov = cluster.get("provisioners", "default", namespace="")
        while time.monotonic() < deadline and prov.status.resources.get(res.CPU) != 4.0:
            time.sleep(0.01)
        manager.stop()
        assert prov.status.resources[res.CPU] == 4.0


class TestPVC:
    def test_selected_node_annotation_written(self):
        cluster = Cluster()
        pvc = make_pvc(name="claim")
        cluster.create("pvcs", pvc)
        pod = make_pod(node_name="node-1", unschedulable=False)
        from karpenter_tpu.api.objects import Volume

        pod.spec.volumes = [Volume(name="v", persistent_volume_claim="claim")]
        cluster.create("pods", pod)
        PVCController(cluster).reconcile(pod.metadata.name)
        assert pvc.metadata.annotations[SELECTED_NODE_ANNOTATION] == "node-1"

    def test_unscheduled_pod_skipped(self):
        cluster = Cluster()
        pvc = make_pvc(name="claim")
        cluster.create("pvcs", pvc)
        pod = make_pod()
        from karpenter_tpu.api.objects import Volume

        pod.spec.volumes = [Volume(name="v", persistent_volume_claim="claim")]
        cluster.create("pods", pod)
        PVCController(cluster).reconcile(pod.metadata.name)
        assert SELECTED_NODE_ANNOTATION not in pvc.metadata.annotations


class TestWorkqueue:
    def test_dedup_while_queued(self):
        q = RateLimitingQueue()
        q.add("a")
        q.add("a")
        assert len(q) == 1

    def test_re_add_while_processing_requeues_after_done(self):
        q = RateLimitingQueue()
        q.add("a")
        item = q.get()
        q.add("a")  # dirty
        assert len(q) == 0
        q.done(item)
        assert len(q) == 1

    def test_add_after_delays(self):
        q = RateLimitingQueue()
        q.add_after("a", 0.05)
        assert q.get(timeout=0.01) is None
        assert q.get(timeout=1.0) == "a"

    def test_exponential_backoff_grows_and_forgets(self):
        b = ExponentialBackoff(base=0.01, cap=1.0)
        assert b.when("x") == 0.01
        assert b.when("x") == 0.02
        assert b.when("x") == 0.04
        b.forget("x")
        assert b.when("x") == 0.01

    def test_token_bucket_limits(self):
        now = [0.0]
        tb = TokenBucket(qps=10, burst=2, clock=lambda: now[0])
        assert tb.try_take() and tb.try_take()
        assert not tb.try_take()
        now[0] += 0.1  # one token refilled
        assert tb.try_take()
        assert not tb.try_take()


class TestManager:
    def test_reconcile_retry_with_backoff(self):
        cluster = Cluster()
        manager = Manager(cluster)
        calls = []
        done = threading.Event()

        def flaky(key):
            calls.append(key)
            if len(calls) < 3:
                raise RuntimeError("boom")
            done.set()

        manager.register("flaky", flaky, concurrency=1)
        manager.start()
        manager.enqueue("flaky", "k")
        assert done.wait(timeout=5)
        manager.stop()
        assert len(calls) == 3

    def test_requeue_after(self):
        cluster = Cluster()
        manager = Manager(cluster)
        calls = []
        done = threading.Event()

        def periodic(key):
            calls.append(time.monotonic())
            if len(calls) >= 2:
                done.set()
                return None
            return 0.05

        manager.register("periodic", periodic, concurrency=1)
        manager.start()
        manager.enqueue("periodic", "k")
        assert done.wait(timeout=5)
        manager.stop()
        assert calls[1] - calls[0] >= 0.04

    def test_tuple_keys_unpack(self):
        cluster = Cluster()
        manager = Manager(cluster)
        seen = []
        manager.register("t", lambda name, ns: seen.append((name, ns)), concurrency=1)
        assert manager.reconcile_now("t", ("a", "b")) is None
        assert seen == [("a", "b")]

    def test_stop_then_start_reconciles_again(self):
        manager = Manager(Cluster())
        seen = []
        manager.register("echo", lambda k: seen.append(k), concurrency=1)
        manager.start()
        manager.enqueue("echo", "a")
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and not seen:
            time.sleep(0.01)
        manager.stop()
        manager.start()
        manager.enqueue("echo", "b")
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and len(seen) < 2:
            time.sleep(0.01)
        manager.stop()
        assert seen == ["a", "b"]

    def test_healthz(self):
        manager = Manager(Cluster())
        manager.register("noop", lambda k: None)
        assert not manager.healthz()
        manager.start()
        assert manager.healthz()
        manager.stop()
        assert not manager.healthz()


class TestConflictRequeue:
    def test_conflict_requeues_promptly_without_backoff(self):
        """A stale-resourceVersion write is normal optimistic concurrency:
        the manager must retry promptly, not walk the error-backoff ladder
        (the round-2 evict-consolidation stall: a cordon PUT conflicted and
        the retry backoff outlived the test's 60s deadline)."""
        import time

        from karpenter_tpu.controllers.manager import Manager
        from karpenter_tpu.kube.client import Cluster, Conflict

        calls = []

        def reconcile(key):
            calls.append(time.monotonic())
            if len(calls) < 3:
                raise Conflict("resourceVersion stale")
            return None

        manager = Manager(Cluster())
        manager.register("conflicty", reconcile, concurrency=1)
        manager.start()
        try:
            manager.enqueue("conflicty", "obj")
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and len(calls) < 3:
                time.sleep(0.02)
            assert len(calls) == 3, f"only {len(calls)} attempts"
            # prompt: all three attempts inside ~2s, far under backoff scale
            assert calls[-1] - calls[0] < 2.0
        finally:
            manager.stop()

    def test_conflict_storm_backs_off_after_cap(self, caplog):
        """A key that conflicts every time must trip the cap onto the
        backoff ladder with a warning, not hot-loop forever at the
        prompt-requeue cadence."""
        import logging
        import time

        from karpenter_tpu.controllers.manager import Manager
        from karpenter_tpu.kube.client import Cluster, Conflict

        calls = []

        def reconcile(key):
            calls.append(time.monotonic())
            raise Conflict("always stale")

        manager = Manager(Cluster())
        manager.register("stormy", reconcile, concurrency=1)
        manager.start()
        try:
            def backoff_logged():
                return any(
                    "conflicted" in r.message and "backing off" in r.message
                    for r in caplog.records
                )

            with caplog.at_level(logging.WARNING, logger="karpenter.manager"):
                manager.enqueue("stormy", "obj")
                deadline = time.monotonic() + 10
                reg = manager._controllers["stormy"]
                # the worker bumps the counter BEFORE emitting the warning,
                # so wait for the log record itself, not just the count
                while time.monotonic() < deadline and not backoff_logged():
                    time.sleep(0.05)
            assert reg.conflicts["obj"] >= Manager.CONFLICT_RETRY_CAP
            assert backoff_logged()
        finally:
            manager.stop()


class TestInMemoryMergePatch:
    def test_merge_patch_preserves_identity_and_patches_fields(self):
        from karpenter_tpu.kube.client import Cluster
        from tests.factories import make_node

        cluster = Cluster()
        node = make_node(name="n", labels={"keep": "me"})
        cluster.create("nodes", node)
        events = []
        cluster.watch("nodes", lambda e, o: events.append((e, o is node)))
        out = cluster.merge_patch(
            "nodes", "n", {"spec": {"unschedulable": True},
                           "metadata": {"labels": {"extra": "x"}}},
            namespace="",
        )
        assert out is node  # same object: watchers/tests hold references
        assert node.spec.unschedulable is True
        assert node.metadata.labels == {"keep": "me", "extra": "x"}
        assert events == [("MODIFIED", True)]

    def test_merge_patch_null_deletes_key(self):
        from karpenter_tpu.kube.client import Cluster
        from tests.factories import make_node

        cluster = Cluster()
        cluster.create("nodes", make_node(name="n", labels={"a": "1", "b": "2"}))
        out = cluster.merge_patch(
            "nodes", "n", {"metadata": {"labels": {"a": None}}}, namespace=""
        )
        assert out.metadata.labels == {"b": "2"}


class TestPodsOnNodeIndex:
    def test_index_tracks_bind_evict_delete(self):
        from karpenter_tpu.kube.client import Cluster
        from tests.factories import make_pod

        cluster = Cluster()
        a = make_pod(name="a", requests={"cpu": "1"})
        b = make_pod(name="b", requests={"cpu": "1"}, node_name="n1", unschedulable=False)
        cluster.create("pods", a)
        cluster.create("pods", b)
        assert [p.metadata.name for p in cluster.pods_on_node("n1")] == ["b"]
        cluster.bind(a, "n1")
        assert sorted(p.metadata.name for p in cluster.pods_on_node("n1")) == ["a", "b"]
        cluster.evict(b)
        assert [p.metadata.name for p in cluster.pods_on_node("n1")] == ["a"]
        cluster.delete("pods", "a")
        assert cluster.pods_on_node("n1") == []

    def test_index_sees_seeded_shadow_pods(self):
        from karpenter_tpu.kube.client import Cluster
        from tests.factories import make_pod

        live = Cluster()
        pod = make_pod(name="x", requests={"cpu": "1"}, node_name="n", unschedulable=False)
        live.create("pods", pod)
        shadow = Cluster()
        assert shadow.pods_on_node("n") == []  # cold index
        shadow.seed("pods", pod)
        assert [p.metadata.name for p in shadow.pods_on_node("n")] == ["x"]
