"""Native (C++) packer parity: identical PackResult to the lax.scan kernel on
real encoded batches. Runs wherever g++ can build the library — i.e. in the
CPU CI suite, making the native path first-class tested."""

import random

import numpy as np
import pytest

from karpenter_tpu.solver.native import native_available, pack_native

pytestmark = pytest.mark.skipif(
    not native_available(wait=120), reason="g++/native packer unavailable"
)


def encoded_batch(n_pods, seed=42, n_types=50):
    from karpenter_tpu.cloudprovider.fake import instance_types
    from karpenter_tpu.cloudprovider.requirements import catalog_requirements
    from karpenter_tpu.kube.client import Cluster
    from karpenter_tpu.scheduling.ffd import daemon_overhead, sort_pods_ffd
    from karpenter_tpu.scheduling.topology import Topology
    from karpenter_tpu.solver import encode as enc
    from karpenter_tpu.testing import diverse_pods, make_provisioner

    catalog = sorted(instance_types(n_types), key=lambda it: it.effective_price())
    provisioner = make_provisioner(solver="tpu")
    c = provisioner.spec.constraints
    c.requirements = c.requirements.merge(catalog_requirements(catalog))
    pods = sort_pods_ffd(diverse_pods(n_pods, random.Random(seed)))
    cc = c.clone()
    Topology(Cluster(), rng=random.Random(1)).inject(cc, pods)
    daemon = daemon_overhead(Cluster(), cc)
    batch = enc.encode(cc, catalog, pods, daemon)
    return (
        batch.pod_valid, batch.pod_open_sig, batch.pod_core, batch.pod_host,
        batch.pod_host_in_base, batch.pod_open_host, batch.pod_req,
        batch.join_table, batch.frontiers, batch.daemon,
    )


@pytest.mark.parametrize("n_pods,n_max,seed", [(60, 64, 1), (300, 128, 2), (1200, 512, 3)])
def test_native_matches_lax_kernel(n_pods, n_max, seed):
    import jax

    from karpenter_tpu.solver import kernel

    args = encoded_batch(n_pods, seed=seed)
    ref = jax.device_get(tuple(kernel.pack(*args, n_max=n_max)))
    out = pack_native(*args, n_max=n_max)
    for name, a, b in zip(kernel.PackResult._fields, ref, tuple(out)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b), err_msg=name)


def test_saturation_matches_kernel_contract():
    """With a tiny node table both kernels refuse to open past the cap."""
    import jax

    from karpenter_tpu.solver import kernel

    args = encoded_batch(200, seed=4)
    ref = jax.device_get(tuple(kernel.pack(*args, n_max=8)))
    out = pack_native(*args, n_max=8)
    assert int(np.asarray(ref[4]).reshape(-1)[0]) == int(out.n_nodes)
    np.testing.assert_array_equal(np.asarray(ref[0]), out.assignment)


def test_backend_uses_native_on_cpu(monkeypatch):
    """On the CPU test platform, the solve path flows through the native
    packer — asserted by instrumenting it, so a silently-failing native
    path cannot hide behind the lax.scan fallback."""
    from karpenter_tpu.solver import native
    from karpenter_tpu.solver.pallas_kernel import pallas_available

    if pallas_available():
        pytest.skip("TPU platform: pallas path active instead")
    from karpenter_tpu.cloudprovider.fake import instance_types
    from karpenter_tpu.cloudprovider.requirements import catalog_requirements
    from karpenter_tpu.kube.client import Cluster
    from karpenter_tpu.solver.backend import TpuScheduler
    from karpenter_tpu.testing import make_pod, make_provisioner

    calls = []
    original = native.pack_native

    def spy(*args, **kwargs):
        calls.append(1)
        return original(*args, **kwargs)

    monkeypatch.setattr(native, "pack_native", spy)
    catalog = instance_types(8)
    provisioner = make_provisioner(solver="tpu")
    c = provisioner.spec.constraints
    c.requirements = c.requirements.merge(catalog_requirements(catalog))
    pods = [make_pod(requests={"cpu": "1"}) for _ in range(6)]
    vnodes = TpuScheduler(Cluster(), rng=random.Random(0)).solve(c, catalog, pods)
    assert sum(len(v.pods) for v in vnodes) == 6
    assert calls, "solve did not flow through the native packer"
