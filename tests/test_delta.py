"""Resident delta encoding tests (docs/delta-encoding.md).

Covers the three residency layers end to end:

- host: ``ResidentEncoder`` parity fuzz — randomized arrival/bind/delete
  churn over many rounds, the delta-built tensors float-hex-identical to
  a cold full encode on every pack arg, plus the epoch ladder (catalog /
  daemon churn → counted full re-encode, topology pod → forced full);
- wire: the ``PROTO_DELTA`` establish/elide/patch lifecycle on the unary
  AND streamed routes (incl. the coalesced ``solve_stream_group``
  dispatch), with results bit-exact against a non-delta client on the
  same inputs;
- epoch guard unit suite against ``SolverService._resolve_delta``: gap,
  replay, reorder, digest disagreement (the stale-tensor refusal), LRU
  eviction, malformed frames → sealed INTEGRITY;
- recovery: sidecar restart mid-session converges through the
  NEEDS_DELTA_BASE → re-establish ladder on both routes, never a stale
  solve;
- device: ``fused.PodResidency`` identity reuse / column patch / full
  upload, patched table bit-exact vs a fresh ``pack_pod_table``;
- chaos: the ``stale_delta`` corruption mode — a checksum-valid frame
  whose epoch words lie, refused by the digest recompute alone.
"""

import random
import socket
import threading
import time

import numpy as np
import pytest

from karpenter_tpu.solver import encode as enc
from karpenter_tpu.solver.service import (
    DELTA_ESTABLISH,
    DELTA_HEADER_WORDS,
    DELTA_PATCH,
    N_POD_ARRAYS,
    POD_STORE_MAX,
    STATUS_INTEGRITY,
    STATUS_NEEDS_DELTA_BASE,
    RemoteSolver,
    SolverService,
    delta_header,
    pod_epoch_key,
    serve,
)


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def wait_until(predicate, timeout=8.0, interval=0.02) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()


def assert_results_equal(a, b):
    for f in a._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(a, f)), np.asarray(getattr(b, f)), err_msg=f
        )


# ---------------------------------------------------------------------------
# host layer: ResidentEncoder vs cold full encode
# ---------------------------------------------------------------------------


def _host_env(n_types: int = 8):
    from karpenter_tpu.cloudprovider.fake import instance_types
    from karpenter_tpu.cloudprovider.requirements import catalog_requirements
    from karpenter_tpu.kube.client import Cluster
    from karpenter_tpu.scheduling.ffd import daemon_overhead
    from karpenter_tpu.testing import make_provisioner

    catalog = sorted(instance_types(n_types), key=lambda it: it.effective_price())
    constraints = make_provisioner(solver="tpu").spec.constraints
    constraints.requirements = constraints.requirements.merge(
        catalog_requirements(catalog)
    )
    daemon = daemon_overhead(Cluster(), constraints)
    return catalog, constraints, daemon


def _generic_pod(rng: random.Random, i: int):
    """A topology-free pod — the delta-eligible shape."""
    from karpenter_tpu.testing import make_pod

    return make_pod(
        name=f"delta-{i}-{rng.randrange(10**6)}",
        requests={
            "cpu": str(rng.choice([1, 2, 3])),
            "memory": f"{rng.choice([1, 2, 4, 6])}Gi",
        },
    )


def _full_reference(constraints, catalog, pods, daemon):
    """A COLD full encode — fresh cache, the pre-delta pipeline verbatim."""
    from karpenter_tpu.scheduling.ffd import sort_pods_ffd_with_statics
    from karpenter_tpu.scheduling.topology import DomainPlan

    spods, ssts = sort_pods_ffd_with_statics(pods)
    plan = DomainPlan(spods)
    plan.sts = ssts
    return enc.encode(
        constraints, catalog, spods, daemon, cache=enc.EncodeCache(), plan=plan
    )


def _assert_pack_args_bit_exact(batch, ref):
    got, want = batch.pack_args(), ref.pack_args()
    assert len(got) == len(want)
    for i, (a, b) in enumerate(zip(got, want)):
        a, b = np.asarray(a), np.asarray(b)
        assert a.dtype == b.dtype and a.shape == b.shape, f"arg {i}"
        # float-hex equality: identical BYTES, not approx — a delta round
        # must be indistinguishable from a full re-encode downstream
        assert a.tobytes() == b.tobytes(), f"pack arg {i} diverged"


class TestHostDeltaParity:
    @pytest.mark.parametrize("seed", range(4))
    def test_churn_fuzz_bit_exact(self, seed):
        """Randomized arrival/bind/delete churn over 10 rounds: every
        round's resident-path batch is float-hex-identical to a cold full
        encode of the same pods, and the lifecycle visits all three kinds
        (full → delta → reuse)."""
        from karpenter_tpu.solver.delta import ResidentEncoder

        rng = random.Random(seed)
        catalog, constraints, daemon = _host_env()
        res = ResidentEncoder(enc.EncodeCache())
        pods = [_generic_pod(rng, i) for i in range(6)]
        kinds = set()
        for rnd in range(10):
            op = rng.choice(["arrive", "depart", "mixed", "none"])
            if op == "arrive" or (op == "mixed" and len(pods) > 2):
                pods = pods + [
                    _generic_pod(rng, 100 * rnd + j)
                    for j in range(rng.randrange(1, 3))
                ]
            if op in ("depart", "mixed") and len(pods) > 3:
                doomed = rng.sample(range(len(pods)), rng.randrange(1, 3))
                pods = [p for i, p in enumerate(pods) if i not in doomed]
            spods, ssts, _ = res.sort(pods)
            assert res.eligible(ssts)
            plan = res.empty_plan(spods, ssts)
            batch, kind = res.encode(
                constraints, catalog, spods, ssts, daemon, plan
            )
            kinds.add(kind)
            if op == "none" and rnd > 0:
                # identical input objects → the whole round is a reuse
                batch2, kind2 = res.encode(
                    constraints, catalog, spods, ssts, daemon, plan
                )
                assert kind2 == "reuse" and batch2 is batch
                kinds.add(kind2)
            _assert_pack_args_bit_exact(
                batch, _full_reference(constraints, catalog, pods, daemon)
            )
        assert {"full", "delta"} <= kinds

    def test_daemon_churn_mints_new_epoch(self):
        """A node retire changes the daemon overhead → new host epoch →
        counted full re-encode, never a patch of tensors built under the
        old overhead."""
        from karpenter_tpu.solver.delta import ResidentEncoder

        rng = random.Random(7)
        catalog, constraints, daemon = _host_env()
        res = ResidentEncoder(enc.EncodeCache())
        pods = [_generic_pod(rng, i) for i in range(4)]
        spods, ssts, _ = res.sort(pods)
        plan = res.empty_plan(spods, ssts)
        _, kind = res.encode(constraints, catalog, spods, ssts, daemon, plan)
        assert kind == "full"
        retired = dict(daemon)
        retired["cpu"] = retired.get("cpu", 0.0) + 0.25
        batch, kind = res.encode(
            constraints, catalog, spods, ssts, retired, plan
        )
        assert kind == "full"
        _assert_pack_args_bit_exact(
            batch, _full_reference(constraints, catalog, pods, retired)
        )

    def test_sort_fast_path_is_identity_keyed(self):
        """The resident sort serves the cached order only for the SAME pod
        objects — a changed list re-sorts (bit-exact with the ffd sort)."""
        from karpenter_tpu.scheduling.ffd import sort_pods_ffd_with_statics
        from karpenter_tpu.solver.delta import ResidentEncoder

        rng = random.Random(3)
        res = ResidentEncoder(enc.EncodeCache())
        pods = [_generic_pod(rng, i) for i in range(8)]
        s1, _, hit1 = res.sort(pods)
        s2, _, hit2 = res.sort(pods)
        assert not hit1 and hit2 and s2 is s1
        churned = pods[1:] + [_generic_pod(rng, 99)]
        s3, _, hit3 = res.sort(churned)
        assert not hit3
        ref, _ = sort_pods_ffd_with_statics(churned)
        assert [p.metadata.name for p in s3] == [p.metadata.name for p in ref]


# ---------------------------------------------------------------------------
# epoch guard unit suite (SolverService._resolve_delta)
# ---------------------------------------------------------------------------


def _pod_set(seed: int, p: int = 6):
    rng = np.random.RandomState(seed)
    return [
        rng.randint(0, 100, size=(p, 3)).astype(np.int32)
        for _ in range(N_POD_ARRAYS)
    ]


def _establish_frame(pods, epoch=None):
    epoch = epoch if epoch is not None else pod_epoch_key(pods)
    key = np.frombuffer(b"k" * 16, np.int32)
    vals = np.asarray([8, 0], np.int64)
    return [key, vals, delta_header(DELTA_ESTABLISH, 0, b"\x00" * 16, epoch)] + list(pods)


def _patch_frame(base_pods, rows, base_epoch, new_epoch=None):
    """A patch frame replacing ``rows`` with incremented values."""
    patched = [a.copy() for a in base_pods]
    idx = np.asarray(sorted(rows), np.int32)
    for a in patched:
        a[idx] = a[idx] + 1
    new_epoch = new_epoch if new_epoch is not None else pod_epoch_key(patched)
    key = np.frombuffer(b"k" * 16, np.int32)
    vals = np.asarray([8, 0], np.int64)
    hdr = delta_header(DELTA_PATCH, idx.size, base_epoch, new_epoch)
    return [key, vals, hdr, idx] + [a[idx] for a in patched], patched, new_epoch


class TestEpochGuard:
    def setup_method(self):
        self.svc = SolverService()

    def test_establish_then_elide(self):
        pods = _pod_set(1)
        epoch = pod_epoch_key(pods)
        got, refusal = self.svc._resolve_delta(_establish_frame(pods))
        assert refusal is None
        key = np.frombuffer(b"k" * 16, np.int32)
        vals = np.asarray([8, 0], np.int64)
        elide = [key, vals, delta_header(1, 0, epoch, epoch)]
        got, refusal = self.svc._resolve_delta(elide)
        assert refusal is None
        for a, b in zip(got, pods):
            np.testing.assert_array_equal(np.asarray(a), b)
        assert self.svc.delta_stats["elided"] == 1

    def test_gap_refused(self):
        """A patch whose base epoch was never established (a missed delta)
        is a base miss, not a guess."""
        pods = _pod_set(2)
        self.svc._resolve_delta(_establish_frame(pods))
        frame, _, _ = _patch_frame(pods, [0], base_epoch=b"\x55" * 16)
        got, refusal = self.svc._resolve_delta(frame)
        assert got is None and refusal == STATUS_NEEDS_DELTA_BASE
        assert self.svc.delta_stats["base_misses"] == 1

    def test_replay_is_idempotent(self):
        """The same patch applied twice lands on the same epoch both
        times — a replay can never corrupt the store."""
        pods = _pod_set(3)
        e1 = pod_epoch_key(pods)
        self.svc._resolve_delta(_establish_frame(pods))
        frame, patched, e2 = _patch_frame(pods, [1], base_epoch=e1)
        for _ in range(2):
            got, refusal = self.svc._resolve_delta([np.asarray(a) for a in frame])
            assert refusal is None
            for a, b in zip(got, patched):
                np.testing.assert_array_equal(np.asarray(a), b)
        assert self.svc.delta_stats["patched"] == 2
        assert self.svc.delta_stats["epoch_mismatches"] == 0

    def test_reorder_refused_then_converges(self):
        """Patches delivered out of order: the later one misses its base
        and is refused; once the earlier lands, the replayed later patch
        applies cleanly."""
        pods = _pod_set(4)
        e1 = pod_epoch_key(pods)
        self.svc._resolve_delta(_establish_frame(pods))
        f1, mid, e2 = _patch_frame(pods, [0], base_epoch=e1)
        f2, final, e3 = _patch_frame(mid, [2], base_epoch=e2)
        got, refusal = self.svc._resolve_delta(f2)  # out of order
        assert got is None and refusal == STATUS_NEEDS_DELTA_BASE
        assert self.svc._resolve_delta(f1)[1] is None
        got, refusal = self.svc._resolve_delta(f2)  # now in order
        assert refusal is None
        for a, b in zip(got, final):
            np.testing.assert_array_equal(np.asarray(a), b)

    def test_digest_disagreement_refuses_and_keeps_base(self):
        """The stale-tensor guard itself: a patch claiming a new epoch its
        rows cannot hash to is refused (counted mismatch), and the base
        STAYS resident — a later honest patch still applies."""
        pods = _pod_set(5)
        e1 = pod_epoch_key(pods)
        self.svc._resolve_delta(_establish_frame(pods))
        lie, _, _ = _patch_frame(pods, [0], base_epoch=e1, new_epoch=b"\xaa" * 16)
        got, refusal = self.svc._resolve_delta(lie)
        assert got is None and refusal == STATUS_NEEDS_DELTA_BASE
        assert self.svc.delta_stats["epoch_mismatches"] == 1
        honest, patched, _ = _patch_frame(pods, [0], base_epoch=e1)
        got, refusal = self.svc._resolve_delta(honest)
        assert refusal is None
        for a, b in zip(got, patched):
            np.testing.assert_array_equal(np.asarray(a), b)

    def test_establish_digest_lie_is_integrity(self):
        """An establish whose full payload does not hash to its claimed
        epoch is a corrupt/buggy FRAME (non-retryable), not a base miss —
        NEEDS_DELTA_BASE would loop forever."""
        pods = _pod_set(6)
        frame = _establish_frame(pods, epoch=b"\x0f" * 16)
        got, refusal = self.svc._resolve_delta(frame)
        assert got is None and refusal == STATUS_INTEGRITY

    @pytest.mark.parametrize("mangle", ["dtype", "oob", "count"])
    def test_malformed_patch_is_integrity(self, mangle):
        pods = _pod_set(7)
        e1 = pod_epoch_key(pods)
        self.svc._resolve_delta(_establish_frame(pods))
        frame, _, _ = _patch_frame(pods, [1], base_epoch=e1)
        idx = np.asarray(frame[3])
        if mangle == "dtype":
            frame[3] = idx.astype(np.int64)
        elif mangle == "oob":
            frame[3] = np.asarray([len(pods[0]) + 5], np.int32)
        else:  # header n_idx disagrees with the idx array
            frame[2] = delta_header(DELTA_PATCH, 3, e1, b"\x01" * 16)
        got, refusal = self.svc._resolve_delta(frame)
        assert got is None and refusal == STATUS_INTEGRITY

    def test_lru_eviction_is_a_base_miss(self):
        """The store is bounded: POD_STORE_MAX epochs later the oldest
        base is gone and an elide against it fails into re-establish."""
        first = _pod_set(100)
        e_first = pod_epoch_key(first)
        self.svc._resolve_delta(_establish_frame(first))
        for i in range(POD_STORE_MAX):
            self.svc._resolve_delta(_establish_frame(_pod_set(200 + i)))
        assert self.svc.pod_store_count() == POD_STORE_MAX
        key = np.frombuffer(b"k" * 16, np.int32)
        vals = np.asarray([8, 0], np.int64)
        got, refusal = self.svc._resolve_delta(
            [key, vals, delta_header(1, 0, e_first, e_first)]
        )
        assert got is None and refusal == STATUS_NEEDS_DELTA_BASE


# ---------------------------------------------------------------------------
# wire layer: lifecycle + recovery on the live routes
# ---------------------------------------------------------------------------


def encoded_args(n_types: int = 8, n_pods: int = 6, seed: int = 3):
    from karpenter_tpu.cloudprovider.fake import instance_types
    from karpenter_tpu.cloudprovider.requirements import catalog_requirements
    from karpenter_tpu.kube.client import Cluster
    from karpenter_tpu.scheduling.ffd import daemon_overhead, sort_pods_ffd
    from karpenter_tpu.scheduling.topology import Topology
    from karpenter_tpu.testing import diverse_pods, make_provisioner

    catalog = sorted(instance_types(n_types), key=lambda it: it.effective_price())
    constraints = make_provisioner(solver="tpu").spec.constraints
    constraints.requirements = constraints.requirements.merge(
        catalog_requirements(catalog)
    )
    pods = sort_pods_ffd(diverse_pods(n_pods, random.Random(seed)))
    cluster = Cluster()
    Topology(cluster, rng=random.Random(1)).inject(constraints, pods)
    batch = enc.encode(
        constraints, catalog, pods, daemon_overhead(cluster, constraints)
    )
    return [np.asarray(a) for a in batch.pack_args()], len(batch.pod_valid)


def _patch_row(args, row: int = 0, bump: float = 0.0625):
    """The same pod set with one pod's request vector nudged — a ≤1-row
    churn that must plan as DELTA_PATCH."""
    out = [np.array(a, copy=True) for a in args[:N_POD_ARRAYS]] + list(
        args[N_POD_ARRAYS:]
    )
    req = out[6]
    req[row, 0] = req[row, 0] + np.asarray(bump, req.dtype)
    return out


class _Harness:
    def __init__(self, service=None, coalesce_window_s=None):
        self.address = f"127.0.0.1:{free_port()}"
        self.server = serve(
            self.address, service=service, coalesce_window_s=coalesce_window_s
        )
        self.clients = []

    def client(self, delta=False, stream=False) -> RemoteSolver:
        c = RemoteSolver(
            self.address, timeout=10.0, cold_timeout=60.0,
            checksum=True, stream=stream, delta=delta,
        )
        self.clients.append(c)
        return c

    def restart(self, service=None, **kw):
        self.server.stop(grace=0)
        self.server = serve(self.address, service=service, **kw)

    @property
    def stats(self):
        return self.server.solver_service.delta_stats

    def stop(self):
        for c in self.clients:
            try:
                c.close()
            except Exception:
                pass
        self.server.stop(grace=0)


@pytest.fixture
def args16():
    args, p = encoded_args()
    return args, p


class TestWireDeltaLifecycle:
    def test_unary_establish_elide_patch_bit_exact(self, args16):
        """The full lifecycle on the unary route, each phase's result
        bit-exact vs a non-delta client on identical inputs."""
        args, _ = args16
        h = _Harness()
        try:
            ref_c = h.client(delta=False)
            dc = h.client(delta=True)
            prof = {}
            out = dc.pack_begin(*args, n_max=16, prof=prof)()
            assert prof["delta_kind"] == "establish"
            assert_results_equal(out, ref_c.pack(*args, n_max=16))
            prof = {}
            out = dc.pack_begin(*args, n_max=16, prof=prof)()
            assert prof["delta_kind"] == "elide"
            assert_results_equal(out, ref_c.pack(*args, n_max=16))
            churned = _patch_row(args)
            prof = {}
            out = dc.pack_begin(*churned, n_max=16, prof=prof)()
            assert prof["delta_kind"] == "patch"
            assert_results_equal(out, ref_c.pack(*churned, n_max=16))
            assert h.stats["established"] == 1
            assert h.stats["elided"] == 1
            assert h.stats["patched"] == 1
            assert h.stats["epoch_mismatches"] == 0
        finally:
            h.stop()

    def test_unary_wide_churn_re_establishes(self, args16):
        """Churn past the patch fraction (most rows changed) plans a fresh
        establish, not a mega-patch."""
        args, p = args16
        h = _Harness()
        try:
            dc = h.client(delta=True)
            ref_c = h.client(delta=False)
            dc.pack(*args, n_max=16)
            churned = [np.array(a, copy=True) for a in args[:N_POD_ARRAYS]] + list(
                args[N_POD_ARRAYS:]
            )
            churned[6] = churned[6] + np.asarray(0.125, churned[6].dtype)
            prof = {}
            out = dc.pack_begin(*churned, n_max=16, prof=prof)()
            assert prof["delta_kind"] == "establish"
            assert_results_equal(out, ref_c.pack(*churned, n_max=16))
            assert h.stats["established"] == 2
        finally:
            h.stop()

    def test_streamed_lifecycle_bit_exact(self, args16):
        args, _ = args16
        h = _Harness()
        try:
            ref_c = h.client(delta=False)
            dc = h.client(delta=True, stream=True)
            dc.pack(*args, n_max=16)  # warm + establish stream
            assert wait_until(lambda: dc._stream is not None and dc._stream.up)
            prof = {}
            out = dc.pack_begin(*args, n_max=16, prof=prof)()
            assert prof["solver_transport"] == "stream"
            assert prof["delta_kind"] == "elide"
            assert_results_equal(out, ref_c.pack(*args, n_max=16))
            churned = _patch_row(args, row=1)
            prof = {}
            out = dc.pack_begin(*churned, n_max=16, prof=prof)()
            assert prof["delta_kind"] == "patch"
            assert_results_equal(out, ref_c.pack(*churned, n_max=16))
            assert h.stats["patched"] >= 1
        finally:
            h.stop()

    def test_coalesced_stream_group_sees_resolved_pods(self, args16):
        """Deltas resolve at PARSE time, so the cross-stream coalescer and
        ``solve_stream_group`` only ever see full pod sets — two delta
        clients dispatching into one coalesce window both come back
        bit-exact."""
        args, _ = args16
        h = _Harness(coalesce_window_s=0.05)
        try:
            ref_c = h.client(delta=False)
            ref16 = ref_c.pack(*args, n_max=16)
            ref24 = ref_c.pack(*args, n_max=24)
            a = h.client(delta=True, stream=True)
            b = h.client(delta=True, stream=True)
            for c in (a, b):
                c.pack(*args, n_max=16)
                assert wait_until(lambda c=c: c._stream is not None and c._stream.up)
            wait_a = a.pack_begin(*args, n_max=16)
            wait_b = b.pack_begin(*args, n_max=24)
            assert_results_equal(wait_a(), ref16)
            assert_results_equal(wait_b(), ref24)
            assert h.stats["elided"] + h.stats["established"] >= 2
        finally:
            h.stop()


class TestRestartRecovery:
    def test_unary_restart_re_establishes(self, args16):
        """Sidecar restart (empty session AND pod stores): the next delta
        solve converges through refusal → re-establish → re-open, result
        bit-exact — never a stale-tensor bind."""
        args, _ = args16
        h = _Harness()
        try:
            dc = h.client(delta=True)
            ref = h.client(delta=False).pack(*args, n_max=16)
            dc.pack(*args, n_max=16)
            uploads = dc.session_uploads
            h.restart()
            out = dc.pack(*args, n_max=16)
            assert_results_equal(out, ref)
            assert dc.session_uploads > uploads
            assert h.stats["established"] >= 1
        finally:
            h.stop()

    def test_streamed_restart_re_establishes(self, args16):
        args, _ = args16
        h = _Harness()
        try:
            dc = h.client(delta=True, stream=True)
            ref = h.client(delta=False).pack(*args, n_max=16)
            dc.pack(*args, n_max=16)
            assert wait_until(lambda: dc._stream is not None and dc._stream.up)
            established = dc._stream.established_count
            h.restart()
            assert wait_until(
                lambda: dc._stream.established_count > established
                and dc._stream.up,
                timeout=20.0,
            )
            out = dc.pack(*args, n_max=16)
            assert_results_equal(out, ref)
            assert h.stats["established"] >= 1
            # and the steady state resumes: the very next round elides
            prof = {}
            assert_results_equal(dc.pack_begin(*args, n_max=16, prof=prof)(), ref)
            assert prof["delta_kind"] == "elide"
        finally:
            h.stop()

    def test_interop_delta_client_old_server(self, args16):
        """Rolling upgrade: against a sidecar that never advertises
        PROTO_DELTA the delta client sends classic full frames — interop
        in the order the capability gate exists for."""
        from karpenter_tpu.solver import service as svc_mod

        args, _ = args16

        class OldServer(SolverService):
            def open_session_bytes(self, request: bytes) -> bytes:
                out = super().open_session_bytes(request)
                arrays = svc_mod.unpack_arrays(out)
                had = svc_mod.is_checksum_array(np.asarray(arrays[-1]))
                if had:
                    arrays = arrays[:-1]
                status, payload = arrays[0], [np.asarray(a) for a in arrays[1:]]
                if payload:
                    payload[0] = payload[0] & ~np.int32(svc_mod.PROTO_DELTA)
                out = svc_mod.pack_arrays([np.asarray(status)] + payload)
                return svc_mod.append_checksum(out) if had else out

        h = _Harness(service=OldServer())
        try:
            dc = h.client(delta=True)
            ref = h.client(delta=False).pack(*args, n_max=16)
            prof = {}
            out = dc.pack_begin(*args, n_max=16, prof=prof)()
            assert "delta_kind" not in prof  # gate held: classic frame
            assert_results_equal(out, ref)
            assert h.stats["established"] == 0
        finally:
            h.stop()


# ---------------------------------------------------------------------------
# device layer: PodResidency
# ---------------------------------------------------------------------------


class TestDeviceResidency:
    def _batches(self):
        from karpenter_tpu.cloudprovider.fake import instance_types
        from karpenter_tpu.cloudprovider.requirements import catalog_requirements
        from karpenter_tpu.kube.client import Cluster
        from karpenter_tpu.scheduling.ffd import (
            daemon_overhead,
            sort_pods_ffd_with_statics,
        )
        from karpenter_tpu.scheduling.topology import DomainPlan
        from karpenter_tpu.testing import make_provisioner

        catalog = sorted(
            instance_types(6), key=lambda it: it.effective_price()
        )
        constraints = make_provisioner(solver="tpu").spec.constraints
        constraints.requirements = constraints.requirements.merge(
            catalog_requirements(catalog)
        )
        daemon = daemon_overhead(Cluster(), constraints)
        rng = random.Random(11)
        pods = [_generic_pod(rng, i) for i in range(8)]

        def build(pod_list):
            spods, ssts = sort_pods_ffd_with_statics(pod_list)
            plan = DomainPlan(spods)
            plan.sts = ssts
            return enc.encode(constraints, catalog, spods, daemon, plan=plan)

        churned = list(pods)
        churned[3] = _generic_pod(rng, 99)  # one pod swapped, count intact
        return build(pods), build(churned)

    def test_reuse_patch_upload_ladder(self):
        from karpenter_tpu.solver import fused

        b1, b2 = self._batches()
        res = fused.PodResidency()
        devs1 = res.get(b1)
        assert res.stats == {"reused": 0, "patched": 0, "uploaded": 1}
        devs_again = res.get(b1)  # identity hit: no re-pack, no transfer
        assert res.stats["reused"] == 1
        assert devs_again[0] is devs1[0]
        res.get(b2)  # one-pod churn, same shape: column patch
        assert res.stats["patched"] == 1

    def test_patched_table_bit_exact(self):
        from karpenter_tpu.solver import fused

        b1, b2 = self._batches()
        res = fused.PodResidency()
        res.get(b1)
        tab_d, obc_d, bhh_d, uniq_d = res.get(b2)
        want_tab, want_obc, want_bhh = fused.pack_pod_table(b2)
        np.testing.assert_array_equal(np.asarray(tab_d), want_tab)
        np.testing.assert_array_equal(np.asarray(obc_d), want_obc)
        np.testing.assert_array_equal(np.asarray(bhh_d), want_bhh)
        np.testing.assert_array_equal(
            np.asarray(uniq_d), fused.pad_uniq_req(b2.uniq_req)
        )

    def test_shape_change_full_upload(self):
        from karpenter_tpu.solver import fused

        b1, _ = self._batches()
        res = fused.PodResidency()
        res.get(b1)
        rng = random.Random(5)
        # a different pod COUNT: no patch possible, clean re-upload
        from karpenter_tpu.cloudprovider.fake import instance_types
        from karpenter_tpu.cloudprovider.requirements import catalog_requirements
        from karpenter_tpu.kube.client import Cluster
        from karpenter_tpu.scheduling.ffd import (
            daemon_overhead,
            sort_pods_ffd_with_statics,
        )
        from karpenter_tpu.scheduling.topology import DomainPlan
        from karpenter_tpu.testing import make_provisioner

        catalog = sorted(instance_types(6), key=lambda it: it.effective_price())
        constraints = make_provisioner(solver="tpu").spec.constraints
        constraints.requirements = constraints.requirements.merge(
            catalog_requirements(catalog)
        )
        pods = [_generic_pod(rng, i) for i in range(3)]
        spods, ssts = sort_pods_ffd_with_statics(pods)
        plan = DomainPlan(spods)
        plan.sts = ssts
        b3 = enc.encode(
            constraints, catalog, spods,
            daemon_overhead(Cluster(), constraints), plan=plan,
        )
        tab_d, *_ = res.get(b3)
        # padding can keep the table shape equal across pod counts — the
        # route taken (wide patch vs fresh upload) is an implementation
        # detail; the resident table matching a fresh pack is the contract
        assert res.stats["uploaded"] + res.stats["patched"] == 2
        np.testing.assert_array_equal(
            np.asarray(tab_d), fused.pack_pod_table(b3)[0]
        )


# ---------------------------------------------------------------------------
# chaos: the stale_delta corruption mode
# ---------------------------------------------------------------------------


class TestStaleDeltaChaos:
    def test_mode_registered_and_request_side(self):
        from karpenter_tpu.testing import chaos

        assert "stale_delta" in chaos.CORRUPTION_MODES

    def test_corrupt_frame_garbles_epochs_keeps_checksum(self):
        """The injector's contract: the corrupted frame still parses and
        still CHECKSUMS — only the epoch words lie. Byte-level defenses
        must pass it; the digest recompute must refuse it."""
        from karpenter_tpu.solver import service as svc_mod
        from karpenter_tpu.testing import chaos

        pods = _pod_set(9)
        frame = svc_mod.append_checksum(
            svc_mod.pack_arrays(
                [np.asarray(a) for a in _establish_frame(pods)]
            )
        )
        bad = chaos._corrupt_frame(frame, "stale_delta", seed=21)
        assert bad != frame
        arrays = [np.asarray(a) for a in svc_mod.unpack_arrays(bad)]
        assert svc_mod.is_checksum_array(arrays[-1])
        hdr = arrays[2]
        assert hdr.dtype == np.int32 and hdr.size == DELTA_HEADER_WORDS
        assert int(hdr[0]) == DELTA_ESTABLISH  # kind survived
        svc = SolverService()
        got, refusal = svc._resolve_delta(arrays[:-1])
        assert got is None and refusal == STATUS_INTEGRITY

    def test_garbled_patch_refused_never_solves_stale(self):
        pods = _pod_set(10)
        svc = SolverService()
        svc._resolve_delta(_establish_frame(pods))
        frame, _, _ = _patch_frame(pods, [1], base_epoch=pod_epoch_key(pods))
        from karpenter_tpu.solver import service as svc_mod
        from karpenter_tpu.testing import chaos

        packed = svc_mod.append_checksum(
            svc_mod.pack_arrays([np.asarray(a) for a in frame])
        )
        refusals = set()
        for seed in range(6):
            bad = chaos._corrupt_frame(packed, "stale_delta", seed=seed)
            arrays = [
                np.asarray(a)
                for a in svc_mod.unpack_arrays(bad)
                if not svc_mod.is_checksum_array(np.asarray(a))
            ]
            got, refusal = svc._resolve_delta(arrays)
            assert got is None, "a garbled-epoch patch resolved to tensors"
            refusals.add(refusal)
        assert refusals <= {STATUS_NEEDS_DELTA_BASE, STATUS_INTEGRITY}
        assert svc.delta_stats["epoch_mismatches"] + svc.delta_stats["base_misses"] >= 6

    def test_frames_without_delta_header_degrade_to_bit_flip(self):
        from karpenter_tpu.solver import service as svc_mod
        from karpenter_tpu.testing import chaos

        frame = svc_mod.pack_arrays([
            np.frombuffer(b"\x03" * 16, np.int32),
            np.asarray([4, 1], np.int64),
            np.ones((3, 2), np.float32),
        ])
        bad = chaos._corrupt_frame(frame, "stale_delta", seed=4)
        assert bad != frame  # still corrupted, just not epoch-targeted


# ---------------------------------------------------------------------------
# plan reuse + decode residency: topology batches on the resident path
# ---------------------------------------------------------------------------


def _topo_env(n_pods=70, n_types=8, seed=5):
    from karpenter_tpu.cloudprovider.fake import instance_types
    from karpenter_tpu.kube.client import Cluster
    from karpenter_tpu.scheduling.scheduler import Scheduler
    from karpenter_tpu.testing import diverse_pods, make_provisioner

    catalog = instance_types(n_types)
    provisioner = make_provisioner(solver="tpu")
    pods = diverse_pods(n_pods, random.Random(seed))
    cluster = Cluster()
    scheduler = Scheduler(cluster, rng=random.Random(1), solver_delta=True)
    return cluster, scheduler, provisioner, catalog, pods


def _node_shape(nodes):
    return sorted(
        (sorted(p.metadata.name for p in n.pods), sorted(n.requests.items()))
        for n in nodes
    )


class TestPlanReuse:
    def test_topology_steady_state_rides_the_resident_path(self):
        """A topology-bearing batch full-injects once; with the cluster,
        constraints and batch unchanged, every later round reuses the
        cached injected plan, hits the encode reuse rung and the decode
        residency memo — and produces the same plan."""
        _, scheduler, provisioner, catalog, pods = _topo_env()
        first = scheduler.solve(provisioner, catalog, pods)
        prof = scheduler.last_stage_profile()
        assert "inject_s" in prof and "encode_s" in prof
        shapes = {0: _node_shape(first)}
        for rnd in (1, 2):
            nodes = scheduler.solve(provisioner, catalog, pods)
            prof = scheduler.last_stage_profile()
            assert "inject_delta_s" in prof, prof
            assert "encode_delta_s" in prof, prof
            assert "decode_delta_s" in prof, prof
            shapes[rnd] = _node_shape(nodes)
        assert shapes[1] == shapes[0] and shapes[2] == shapes[0]

    def test_cluster_mutation_invalidates_the_plan(self):
        """Any store mutation bumps Cluster.version() and the next solve
        re-injects in full — affinity/spread domains read cluster state the
        epoch digest never covered."""
        from karpenter_tpu.testing import make_pod

        cluster, scheduler, provisioner, catalog, pods = _topo_env()
        scheduler.solve(provisioner, catalog, pods)
        scheduler.solve(provisioner, catalog, pods)
        assert "inject_delta_s" in scheduler.last_stage_profile()
        v0 = cluster.version()
        cluster.create("pods", make_pod(name="late-arrival"))
        assert cluster.version() > v0
        scheduler.solve(provisioner, catalog, pods)
        prof = scheduler.last_stage_profile()
        assert "inject_s" in prof and "inject_delta_s" not in prof

    def test_seed_bumps_the_store_version(self):
        """seed() inserts without events, but version-keyed consumers must
        still see seeded state as a new cluster state."""
        from karpenter_tpu.kube.client import Cluster
        from karpenter_tpu.testing import make_pod

        cluster = Cluster()
        v0 = cluster.version()
        cluster.seed("pods", make_pod(name="seeded"))
        assert cluster.version() > v0

    def test_constraints_change_invalidates_the_plan(self):
        """The plan key holds the PRE-inject requirements content: a
        provisioner constraints edit re-injects even when the cluster and
        the batch stand still."""
        from karpenter_tpu.api.objects import NodeSelectorRequirement

        _, scheduler, provisioner, catalog, pods = _topo_env()
        scheduler.solve(provisioner, catalog, pods)
        scheduler.solve(provisioner, catalog, pods)
        assert "inject_delta_s" in scheduler.last_stage_profile()
        c = provisioner.spec.constraints
        c.requirements = c.requirements.add(
            NodeSelectorRequirement(
                key="example.com/tier", operator="NotIn", values=["spot-x"]
            )
        )
        scheduler.solve(provisioner, catalog, pods)
        prof = scheduler.last_stage_profile()
        assert "inject_s" in prof and "inject_delta_s" not in prof

    def test_topo_resident_rows_never_row_delta(self):
        """Pod churn under a topology-adopted vocabulary falls to a counted
        full("topology") re-encode — the resident rows embed the injected
        plan's decisions, so a row delta would rebuild tensors from inputs
        the epoch guard never checked."""
        from karpenter_tpu.scheduling.topology import Topology
        from karpenter_tpu.solver.delta import ResidentEncoder
        from karpenter_tpu.kube.client import Cluster
        from karpenter_tpu.testing import diverse_pods

        catalog, constraints, daemon = _host_env()
        res = ResidentEncoder(enc.EncodeCache())
        topo_pods = diverse_pods(21, random.Random(9))
        spods, ssts, _ = res.sort(topo_pods)
        assert not res.eligible(ssts)
        injector = Topology(Cluster(), rng=random.Random(2))
        cc = constraints.clone()
        plan = injector.inject_plan(cc, spods, sts=ssts)
        _, kind = res.encode(
            cc, catalog, spods, ssts, daemon, plan, topo=True
        )
        assert kind == "full"
        # same epoch inputs, churned pods: must NOT serve a row delta
        churned = spods[1:]
        s2, st2, _ = res.sort(churned)
        cc2 = constraints.clone()
        plan2 = injector.inject_plan(cc2, s2, sts=st2)
        batch, kind = res.encode(
            cc2, catalog, s2, st2, daemon, plan2, topo=True
        )
        assert kind == "full"

    def test_plan_reuse_hands_out_fresh_clones(self):
        """The cached injected round must survive a consumer mutating what
        it was handed: reuse returns a fresh constraints clone and daemon
        copy every time."""
        from karpenter_tpu.solver.delta import ResidentEncoder

        res = ResidentEncoder(enc.EncodeCache())
        catalog, constraints, daemon = _host_env()
        from karpenter_tpu.scheduling.topology import DomainPlan

        sts = ["sentinel"]
        key = res.plan_key(constraints, 7)
        res.remember_plan(key, sts, constraints, DomainPlan([]), daemon)
        c1, p1, d1 = res.plan_reuse(key, sts)
        c1.labels["poison"] = "yes"
        d1["poison"] = 1.0
        c2, _, d2 = res.plan_reuse(key, sts)
        assert "poison" not in c2.labels and "poison" not in d2
        assert res.plan_reuse(key, ["other"]) is None
        assert res.plan_reuse(res.plan_key(constraints, 8), sts) is None


class TestDecodeResidency:
    def test_result_bit_change_misses_the_memo(self):
        """The decode memo serves only bit-identical results: perturbing
        one assignment entry re-runs the full decode (and re-validates)."""
        _, scheduler, provisioner, catalog, pods = _topo_env()
        scheduler.solve(provisioner, catalog, pods)
        scheduler.solve(provisioner, catalog, pods)
        prof = scheduler.last_stage_profile()
        assert "decode_delta_s" in prof
        sched = scheduler._tpu
        memo = sched._dec_memo
        assert memo is not None
        batch, its = memo[0], memo[1]
        assignment = memo[3].copy()
        n_nodes = memo[7]
        if (assignment >= 0).any() and n_nodes > 1:
            i = int(np.flatnonzero(assignment >= 0)[0])
            assignment[i] = (assignment[i] + 1) % n_nodes
        sig = np.zeros(max(n_nodes, 1), np.int32)
        hit = sched._decode_from_memo(
            batch, assignment, memo[4], memo[5], memo[6], n_nodes,
            memo[8], memo[2], its,
        )
        assert hit is None

    def test_memo_hit_nodes_are_independent_copies(self):
        """A consumer appending to a served node's pods must not leak into
        the next round's nodes."""
        _, scheduler, provisioner, catalog, pods = _topo_env()
        scheduler.solve(provisioner, catalog, pods)
        n1 = scheduler.solve(provisioner, catalog, pods)
        assert "decode_delta_s" in scheduler.last_stage_profile()
        clean_shape = _node_shape(n1)
        placed = [n for n in n1 if n.pods]
        placed[0].pods.append(placed[0].pods[0])
        placed[0].requests["poison"] = 1.0
        n2 = scheduler.solve(provisioner, catalog, pods)
        assert "decode_delta_s" in scheduler.last_stage_profile()
        assert _node_shape(n2) == clean_shape
        assert all("poison" not in n.requests for n in n2)

    def test_failed_validation_never_arms_the_skip_memo(self):
        """A corrupt plan re-validates every round no matter how often the
        device repeats it bit-for-bit: the skip memo arms only on a PASS,
        keyed to the decode memo generation."""
        _, scheduler, provisioner, catalog, pods = _topo_env()
        scheduler.solve(provisioner, catalog, pods)
        sched = scheduler._tpu
        # drop the pass-armed memo: from here on, only a PASS may re-arm it
        sched._validate_memo = None
        calls = []
        real_validate = sched._validate_pack

        def counting_validate(nodes, batch_pods, daemon):
            calls.append(1)
            return "forced violation (test)"

        # keep the pack breaker out of the way: a real violation trips it
        # and routes later rounds straight to FFD, which would hide the
        # property under test (the skip memo, not the breaker)
        quarantines = []
        sched._quarantine_source = (
            lambda address, reason, detail, batch=None: quarantines.append(reason)
        )
        sched._validate_pack = counting_validate
        try:
            scheduler.solve(provisioner, catalog, pods)
            before = len(calls)
            assert before >= 1
            assert quarantines
            assert sched._validate_memo is None
            # bit-identical rounds: the decode memo may hit, but the failed
            # validation must re-run — the skip memo was never armed
            scheduler.solve(provisioner, catalog, pods)
            scheduler.solve(provisioner, catalog, pods)
            assert len(calls) >= before + 2
            assert sched._validate_memo is None
        finally:
            sched._validate_pack = real_validate

    def test_validation_skip_requires_decode_hit(self):
        """With the resident path off, every solve validates."""
        from karpenter_tpu.kube.client import Cluster
        from karpenter_tpu.scheduling.scheduler import Scheduler
        from karpenter_tpu.testing import diverse_pods, make_provisioner
        from karpenter_tpu.cloudprovider.fake import instance_types

        catalog = instance_types(8)
        provisioner = make_provisioner(solver="tpu")
        pods = diverse_pods(35, random.Random(4))
        scheduler = Scheduler(Cluster(), rng=random.Random(1), solver_delta=False)
        scheduler.solve(provisioner, catalog, pods)
        sched = scheduler._tpu
        calls = []
        real_validate = sched._validate_pack

        def counting_validate(nodes, batch_pods, daemon):
            calls.append(1)
            return real_validate(nodes, batch_pods, daemon)

        sched._validate_pack = counting_validate
        try:
            scheduler.solve(provisioner, catalog, pods)
            scheduler.solve(provisioner, catalog, pods)
            assert len(calls) == 2
            assert "decode_delta_s" not in scheduler.last_stage_profile()
        finally:
            sched._validate_pack = real_validate
